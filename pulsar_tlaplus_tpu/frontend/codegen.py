"""TLA+ -> JAX compiler (SURVEY.md §2.2-E1): parsed module + constants ->
vmappable TPU kernels for the device BFS engine.

Pipeline (per spec + cfg binding):

1. **Static splitting** — ``Init``/``Next`` are walked exactly like the
   interpreter's enumerator (frontend/interp.py ``_enum``): conjunction
   threads assignments, disjunction / ``\\E`` / ``x' \\in S`` branch.
   Every branch becomes a static *lane*; nondeterministic binders bind
   their variable to each element of the (statically bounded) domain,
   with a membership guard when the domain is state-dependent.  Lane
   order matches the interpreter's enumeration order (AST order,
   ``_sort_key``-sorted domains) so the two paths are differential
   tests of each other.
2. **Descriptor inference** — an abstract pass over the same compiler
   evaluates descs only (:mod:`.codegen_ir`), with guard-based
   narrowing (``Len(s) < c``, ``x < c`` ...) so bounded-growth patterns
   (Append under a limit guard, counters under a max) reach a fixpoint.
3. **Concrete compilation** — the same traversal with data: every
   expression value is a :class:`CVal` (descriptor + jnp data tree +
   poison bit).  Sub-expressions that do not reference state variables
   are evaluated by the host interpreter and lifted as constants — the
   array compiler only ever sees the state-dependent paths.

**Poison semantics**: TLC evaluates lazily and *errors* on demanded
out-of-domain values; vectorized evaluation is eager, so undefinedness
is tracked as a poison bit with short-circuit algebra (``a /\\ b``
demands ``b`` only when ``a`` holds, masked quantifier elements drop
their body's poison, IF selects branch poison).  A poison demanded by a
valid lane sets the hidden ``__err__`` state bit; the auto-invariant
``__EvalError__`` then halts the check with a trace to the state whose
evaluation TLC would have rejected — never a silently wrong result.

Reference contract being compiled: ``/root/reference/compaction.tla``
Init/Next (lines 188-231) and invariants (236-294) under
``compaction.cfg``; the generic interpreter is the semantic oracle.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pulsar_tlaplus_tpu.frontend import tla_ast as A
from pulsar_tlaplus_tpu.frontend.codegen_ir import (
    CodegenError,
    DBool,
    DEnum,
    DFun,
    DInt,
    DOpt,
    DRec,
    DSeq,
    DSet,
    DescCodec,
    coerce,
    data_eq,
    data_where,
    desc_of_value,
    encode_value,
    encode_value_zero,
    join,
    JV,
    zero_data,
)
from pulsar_tlaplus_tpu.frontend.interp import (
    EvalError,
    FDict,
    MV,
    OpDef,
    Spec,
    _enum_set,
    _refs_any,
    _sort_key,
    _unchanged_names,
    eval_expr,
)

FALSE = False  # poison "constant" (host bool promotes under jnp ops)


@dataclass
class CVal:
    """Compiled value: descriptor + data tree + poison.

    ``data`` is None in the abstract (inference) pass.  ``poison`` is a
    scalar bool array (or host False) meaning "TLC evaluation of this
    value would have errored"."""

    desc: object
    data: object = None
    poison: object = FALSE


def _or(a, b):
    if a is FALSE:
        return b
    if b is FALSE:
        return a
    return a | b


def _and_val(cond_val, p):
    """Poison of an expression demanded only when ``cond_val`` holds."""
    if p is FALSE:
        return FALSE
    return jnp.asarray(cond_val) & p


class CEnv:
    """Chained compile-time scope: name -> ("host", v) | ("cv", CVal) |
    ("op", OpDef-like with a CEnv)."""

    __slots__ = ("table", "parent")

    def __init__(self, table=None, parent=None):
        self.table = table if table is not None else {}
        self.parent = parent

    def get(self, name):
        e = self
        while e is not None:
            if name in e.table:
                return e.table[name]
            e = e.parent
        return None

    def child(self, table):
        return CEnv(table, self)

    def host_overlay(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        e = self
        seen = set()
        while e is not None:
            for k, v in e.table.items():
                if k in seen:
                    continue
                seen.add(k)
                if isinstance(v, tuple) and v and v[0] == "host":
                    out[k] = v[1]
            e = e.parent
        return out

    def dynamic_names(self) -> set:
        out = set()
        e = self
        seen = set()
        while e is not None:
            for k, v in e.table.items():
                if k in seen:
                    continue
                seen.add(k)
                if isinstance(v, tuple) and v and v[0] in ("cv", "op"):
                    if v[0] == "cv":
                        out.add(k)
                    else:  # dynamic only if its body is
                        out.add(k)
            e = e.parent
        return out


@dataclass
class Lane:
    """One static Next/Init branch: label + host binder values + the
    conjunct list to compile under those bindings."""

    label: Optional[str]
    binds: Tuple[Tuple[str, object], ...]  # (name, host value)
    guards_pre: Tuple[Tuple[A.Node, object], ...]  # extra membership guards
    conjuncts: Tuple[A.Node, ...]
    env_tables: Tuple[Dict, ...] = ()  # LET tables captured on the path


class Compiler:
    """Expression/action compiler for one Spec (module + constants)."""

    MAX_LANES = 4096
    MAX_UNIVERSE = 4096

    def __init__(self, spec: Spec):
        self.spec = spec
        self.varset = set(spec.vars)
        self.abstract = False
        self.var_descs: Dict[str, object] = {}

    # ------------------------------------------------------------ util

    def _dyn_names(self, cenv: CEnv) -> set:
        names = set(self.varset)
        names |= {v + "'" for v in self.varset}
        names |= cenv.dynamic_names()
        names |= self.spec._state_defs
        return names

    def is_dynamic(self, node: A.Node, cenv: CEnv) -> bool:
        return _refs_any(node, self._dyn_names(cenv), self.spec.defs)

    def host_eval(self, node: A.Node, cenv: CEnv):
        env = self.spec.genv.child(cenv.host_overlay())
        return eval_expr(node, env)

    def lift(self, v) -> CVal:
        """Host value -> CVal constant."""
        d = desc_of_value(v)
        if self.abstract:
            return CVal(d, None)
        data = jax.tree_util.tree_map(jnp.asarray, encode_value(d, v))
        return CVal(d, data)

    def as_cval(self, x) -> CVal:
        return x if isinstance(x, CVal) else self.lift(x)

    def _coerce(self, cv: CVal, d) -> CVal:
        if cv.desc == d:
            return cv
        if self.abstract:
            return CVal(d, None, cv.poison)
        out = coerce(JV(cv.desc, cv.data), d)
        return CVal(d, out.data, cv.poison)

    def _join2(self, a: CVal, b: CVal):
        d = join(a.desc, b.desc)
        return self._coerce(a, d), self._coerce(b, d), d

    # -------------------------------------------------- narrowing (assign)

    def narrow_to(self, cv: CVal, d) -> CVal:
        """Re-represent ``cv`` under ``d``, poisoning (not erroring) when
        the value falls outside — the runtime descriptor guard that makes
        optimistic inference safe.  Recurses structurally; the returned
        poison may carry structure axes (callers reduce/gate them)."""
        if cv.desc == d:
            return cv
        if self.abstract:
            return CVal(d, None, cv.poison)
        try:
            return self._coerce(cv, d)
        except CodegenError:
            pass
        p = cv.poison
        s = cv.desc
        if isinstance(d, DInt) and isinstance(s, DInt):
            x = cv.data
            p = _or(p, (x < d.lo) | (x > d.hi))
            return CVal(d, jnp.clip(x, d.lo, d.hi), p)
        if isinstance(d, DEnum) and isinstance(s, DEnum):
            codes = []
            ok = jnp.zeros(jnp.shape(cv.data), jnp.bool_)
            for i, m in enumerate(s.members):
                if m in d.members:
                    codes.append(d.members.index(m))
                    ok = ok | (cv.data == i)
                else:
                    codes.append(0)
            remap = jnp.asarray(codes, jnp.int32)
            return CVal(d, remap[cv.data], _or(p, ~ok))
        if isinstance(d, DSet) and isinstance(s, DSet):
            m = cv.data
            cols = []
            for u in d.universe:
                if u in s.universe:
                    cols.append(m[..., s.universe.index(u)])
                else:
                    cols.append(
                        jnp.zeros(jnp.shape(m)[:-1], jnp.bool_)
                    )
            drop = [
                i for i, u in enumerate(s.universe)
                if u not in d.universe
            ]
            if drop:
                p = _or(
                    p,
                    jnp.any(m[..., jnp.asarray(drop)], axis=-1),
                )
            out = (
                jnp.stack(cols, axis=-1)
                if cols
                else jnp.zeros(jnp.shape(m)[:-1] + (0,), jnp.bool_)
            )
            return CVal(d, out, p)
        if isinstance(d, DSeq) and isinstance(s, DSeq):
            ln, ed = cv.data
            p = _or(p, ln > d.cap)
            ln = jnp.minimum(ln, d.cap)
            if s.cap and d.cap and d.elem is not None and s.elem is not None:
                e2 = self.narrow_to(CVal(s.elem, ed), d.elem)
                if e2.poison is not FALSE:
                    live = jnp.arange(s.cap) < ln
                    p = _or(p, jnp.any(_bcast(live, jnp.asarray(e2.poison))
                                       & e2.poison))
                ed = e2.data

                def fit(x):
                    if x.shape[0] >= d.cap:
                        return x[: d.cap]
                    pad = [(0, d.cap - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
                    return jnp.pad(x, pad)

                ed = jax.tree_util.tree_map(fit, ed)
            else:
                ed = zero_data(d.elem, (d.cap,)) if d.cap else (
                    jnp.zeros((0,), jnp.int32)
                )
            return CVal(d, (ln, ed), p)
        if isinstance(d, DRec) and isinstance(s, DRec):
            if tuple(f for f, _ in d.fields) != tuple(
                f for f, _ in s.fields
            ):
                raise CodegenError(f"record mismatch {s} -> {d}")
            datas = {}
            for (fn_, fd), (_, sd) in zip(d.fields, s.fields):
                sub = self.narrow_to(CVal(sd, cv.data[fn_]), fd)
                datas[fn_] = sub.data
                p = _or(p, sub.poison)
            return CVal(d, datas, p)
        if isinstance(d, DOpt) and isinstance(s, DOpt):
            if s.nil != d.nil:
                raise CodegenError(f"nil mismatch {s} -> {d}")
            pres, inner = cv.data
            sub = self.narrow_to(CVal(s.inner, inner), d.inner)
            if sub.poison is not FALSE:
                p = _or(p, jnp.any(_bcast(pres, jnp.asarray(sub.poison))
                                   & sub.poison))
            return CVal(d, (pres, sub.data), p)
        if (
            isinstance(d, DFun)
            and isinstance(s, DFun)
            and d.keys == s.keys
            and d.partial == s.partial
        ):
            pres, vd = cv.data
            sub = self.narrow_to(CVal(s.val, vd), d.val)
            if sub.poison is not FALSE:
                sp = jnp.asarray(sub.poison)
                if d.partial:
                    m = jnp.moveaxis(jnp.asarray(pres), -1, 0)
                    sp = _bcast(m, sp) & sp
                p = _or(p, jnp.any(sp))
            return CVal(d, (pres, sub.data), p)
        if isinstance(d, DOpt) and not isinstance(s, DOpt):
            inner = self.narrow_to(cv, d.inner)
            return CVal(
                d, (jnp.bool_(True), inner.data), inner.poison
            )
        raise CodegenError(f"cannot narrow {s} -> {d}")

    # ---------------------------------------------------------- domains

    def domain_universe(self, node: A.Node, cenv: CEnv):
        """Resolve a binder/quantifier domain to
        ``(sorted host universe, memfn or None)``.  Host domains
        enumerate exactly (memfn None); state-dependent domains get a
        static universe from their descriptor plus a per-element
        membership compiler ``memfn(elem) -> CVal[DBool]``."""
        if not self.is_dynamic(node, cenv):
            dom = self.host_eval(node, cenv)
            elems = sorted(_enum_set(dom), key=_sort_key)
            if len(elems) > self.MAX_UNIVERSE:
                raise CodegenError(f"domain too large: {len(elems)}")
            return elems, None
        if isinstance(node, A.BinOp) and node.op == "..":
            lo = self.as_cval(self.compile(node.lhs, cenv))
            hi = self.as_cval(self.compile(node.rhs, cenv))
            self._want_int(lo, node)
            self._want_int(hi, node)
            if lo.desc is None or hi.desc is None:
                return [], (lambda e: CVal(DBool(), None))
            if hi.desc.hi - lo.desc.lo > self.MAX_UNIVERSE:
                raise CodegenError(f"dynamic range too wide at {node.loc}")
            elems = list(range(lo.desc.lo, hi.desc.hi + 1))
            p = _or(lo.poison, hi.poison)

            def memfn(e):
                if self.abstract:
                    return CVal(DBool(), None, p)
                return CVal(
                    DBool(), (lo.data <= e) & (e <= hi.data), p
                )

            return elems, memfn
        cv = self.as_cval(self.compile(node, cenv))
        d = cv.desc
        if d is None and self.abstract:
            return [], (lambda e: CVal(DBool(), None))
        if isinstance(d, DSet):

            def memfn(e):
                if e not in d.universe:
                    return CVal(
                        DBool(),
                        None if self.abstract else jnp.bool_(False),
                    )
                i = d.universe.index(e)
                if self.abstract:
                    return CVal(DBool(), None, cv.poison)
                return CVal(DBool(), cv.data[..., i], cv.poison)

            return list(d.universe), memfn
        raise CodegenError(f"cannot bound dynamic domain {d}")

    # ------------------------------------------------------- expression

    def compile(self, node: A.Node, cenv: CEnv):
        """-> host value (static) or CVal (dynamic)."""
        if not self.is_dynamic(node, cenv):
            return self.host_eval(node, cenv)
        k = type(node)
        fn = getattr(self, "_c_" + k.__name__, None)
        if fn is None:
            raise CodegenError(
                f"cannot compile {k.__name__} at {node.loc}"
            )
        return fn(node, cenv)

    def cbool(self, node: A.Node, cenv: CEnv) -> CVal:
        v = self.compile(node, cenv)
        if isinstance(v, CVal):
            if not isinstance(v.desc, DBool):
                raise CodegenError(f"expected boolean at {node.loc}")
            return v
        if not isinstance(v, bool):
            raise CodegenError(f"expected boolean at {node.loc}, got {v!r}")
        return self.lift(v)

    # atoms

    def _c_Name(self, node: A.Name, cenv: CEnv):
        ent = cenv.get(node.name)
        if ent is not None:
            kind = ent[0]
            if kind == "host":
                return ent[1]
            if kind == "cv":
                return ent[1]
            if kind == "op":
                raise CodegenError(f"operator {node.name} used as value")
        # zero-arg state-dependent definition: inline its body
        if node.name in self.spec.defs:
            return self.compile(self.spec.defs[node.name].body, cenv)
        raise CodegenError(f"unbound name {node.name} at {node.loc}")

    def _c_Prime(self, node: A.Prime, cenv: CEnv):
        if isinstance(node.expr, A.Name):
            ent = cenv.get(node.expr.name + "'")
            if ent is not None and ent[0] == "cv":
                return ent[1]
            raise CodegenError(
                f"{node.expr.name}' referenced before assignment"
            )
        raise CodegenError(f"cannot prime non-variable at {node.loc}")

    # boolean structure (lazy poison algebra)

    def _c_Junction(self, node: A.Junction, cenv: CEnv):
        if node.op == "/\\":
            return self._conj([*node.items], cenv)
        return self._disj([*node.items], cenv)

    def _conj(self, items, cenv) -> CVal:
        acc_v, acc_p = True, FALSE
        for it in items:
            cv = self.cbool(it, cenv)
            if self.abstract:
                continue
            acc_p = _or(acc_p, _and_val(acc_v, cv.poison))
            acc_v = jnp.asarray(acc_v) & cv.data if acc_v is not True else cv.data
        if self.abstract:
            return CVal(DBool(), None)
        return CVal(DBool(), jnp.asarray(acc_v), acc_p)

    def _disj(self, items, cenv) -> CVal:
        acc_v, acc_p = False, FALSE
        for it in items:
            cv = self.cbool(it, cenv)
            if self.abstract:
                continue
            acc_p = _or(acc_p, _and_val(~jnp.asarray(acc_v), cv.poison))
            acc_v = (
                jnp.asarray(acc_v) | cv.data if acc_v is not False else cv.data
            )
        if self.abstract:
            return CVal(DBool(), None)
        return CVal(DBool(), jnp.asarray(acc_v), acc_p)

    # operators

    def _c_BinOp(self, node: A.BinOp, cenv: CEnv):
        op = node.op
        if op == "/\\":
            return self._conj([node.lhs, node.rhs], cenv)
        if op == "\\/":
            return self._disj([node.lhs, node.rhs], cenv)
        if op == "=>":
            l = self.cbool(node.lhs, cenv)
            r = self.cbool(node.rhs, cenv)
            if self.abstract:
                return CVal(DBool(), None)
            return CVal(
                DBool(),
                ~l.data | r.data,
                _or(l.poison, _and_val(l.data, r.poison)),
            )
        if op == "<=>":
            l = self.cbool(node.lhs, cenv)
            r = self.cbool(node.rhs, cenv)
            if self.abstract:
                return CVal(DBool(), None)
            return CVal(DBool(), l.data == r.data, _or(l.poison, r.poison))
        if op in ("\\in", "\\notin"):
            return self._c_membership(node, cenv)
        l = self.as_cval(self.compile(node.lhs, cenv))
        r = self.as_cval(self.compile(node.rhs, cenv))
        p = _or(l.poison, r.poison)
        if op in ("=", "#"):
            lc, rc, d = self._join2(l, r)
            if self.abstract:
                return CVal(DBool(), None, p)
            eq = data_eq(d, lc.data, rc.data)
            return CVal(DBool(), eq if op == "=" else ~eq, p)
        if op in ("<", ">", "<=", ">=", "\\leq", "\\geq"):
            self._want_int(l, node)
            self._want_int(r, node)
            if self.abstract:
                return CVal(DBool(), None, p)
            f = {
                "<": jnp.less, ">": jnp.greater,
                "<=": jnp.less_equal, ">=": jnp.greater_equal,
                "\\leq": jnp.less_equal, "\\geq": jnp.greater_equal,
            }[op]
            return CVal(DBool(), f(l.data, r.data), p)
        if op in ("+", "-", "*", "\\div", "%"):
            return self._arith(op, l, r, p, node)
        if op in ("\\cup", "\\union", "\\cap", "\\intersect", "\\"):
            return self._setop(op, l, r, p)
        if op == "\\subseteq":
            a, b, d = self._join2(l, r)
            if not isinstance(d, DSet):
                raise CodegenError(f"\\subseteq on non-sets at {node.loc}")
            if self.abstract:
                return CVal(DBool(), None, p)
            return CVal(
                DBool(), jnp.all(~a.data | b.data, axis=-1), p
            )
        if op == "..":
            # dynamic range as a value: DSet over the static envelope
            self._want_int(l, node)
            self._want_int(r, node)
            if l.desc is None or r.desc is None:
                return CVal(None, None)
            if r.desc.hi - l.desc.lo > self.MAX_UNIVERSE:
                raise CodegenError(f"dynamic range too wide at {node.loc}")
            uni = tuple(range(l.desc.lo, r.desc.hi + 1))
            d = DSet(uni)
            if self.abstract:
                return CVal(d, None, p)
            u = jnp.asarray(uni, jnp.int32)
            mask = (l.data <= u) & (u <= r.data)
            return CVal(d, mask, p)
        raise CodegenError(f"cannot compile operator {op} at {node.loc}")

    def _want_int(self, cv: CVal, node):
        if cv.desc is None and self.abstract:
            return
        if not isinstance(cv.desc, DInt):
            raise CodegenError(f"expected integer at {node.loc}: {cv.desc}")

    def _arith(self, op, l: CVal, r: CVal, p, node) -> CVal:
        self._want_int(l, node)
        self._want_int(r, node)
        if l.desc is None or r.desc is None:
            return CVal(None, None)
        a, b = l.desc, r.desc
        if op == "+":
            d = DInt(a.lo + b.lo, a.hi + b.hi)
            fn = lambda x, y: x + y
        elif op == "-":
            d = DInt(a.lo - b.hi, a.hi - b.lo)
            fn = lambda x, y: x - y
        elif op == "*":
            cs = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
            d = DInt(min(cs), max(cs))
            fn = lambda x, y: x * y
        elif op == "\\div":
            if b.lo <= 0:
                raise CodegenError(f"\\div by possibly-nonpositive at {node.loc}")
            d = DInt(min(a.lo // b.lo, a.lo // b.hi, 0),
                     max(a.hi // b.lo, a.hi // max(b.lo, 1), 0))
            fn = lambda x, y: x // y
        elif op == "%":
            if b.lo <= 0:
                raise CodegenError(f"% by possibly-nonpositive at {node.loc}")
            d = DInt(0, b.hi - 1)
            fn = lambda x, y: x % y
        else:  # pragma: no cover
            raise CodegenError(op)
        if self.abstract:
            return CVal(d, None, p)
        return CVal(d, fn(l.data, r.data), p)

    def _setop(self, op, l: CVal, r: CVal, p) -> CVal:
        a, b, d = self._join2(l, r)
        if not isinstance(d, DSet):
            raise CodegenError(f"set operator {op} on {d}")
        if self.abstract:
            return CVal(d, None, p)
        if op in ("\\cup", "\\union"):
            m = a.data | b.data
        elif op in ("\\cap", "\\intersect"):
            m = a.data & b.data
        else:
            m = a.data & ~b.data
        return CVal(d, m, p)

    def _c_membership(self, node: A.BinOp, cenv: CEnv) -> CVal:
        neg = node.op == "\\notin"
        l = self.compile(node.lhs, cenv)
        rhs_dyn = self.is_dynamic(node.rhs, cenv)
        if not rhs_dyn:
            dom = self.host_eval(node.rhs, cenv)
            elems = sorted(_enum_set(dom), key=_sort_key)
            lcv = self.as_cval(l)
            if self.abstract:
                return CVal(DBool(), None, lcv.poison)
            m = jnp.bool_(False)
            for e in elems:
                ec = self.lift(e)
                try:
                    a, b, d = self._join2(lcv, ec)
                except CodegenError:
                    continue  # incomparable kinds never equal
                m = m | data_eq(d, a.data, b.data)
            out = ~m if neg else m
            return CVal(DBool(), out, lcv.poison)
        # dynamic set on the right
        if isinstance(l, CVal):
            # dynamic element in dynamic set: one-hot over the universe
            rcv = self.as_cval(self.compile(node.rhs, cenv))
            if not isinstance(rcv.desc, DSet):
                raise CodegenError(f"\\in non-set at {node.loc}")
            uni = rcv.desc.universe
            if self.abstract:
                return CVal(DBool(), None, _or(l.poison, rcv.poison))
            m = jnp.bool_(False)
            for i, e in enumerate(uni):
                ec = self.lift(e)
                try:
                    a, b, d = self._join2(l, ec)
                except CodegenError:
                    continue
                m = m | (data_eq(d, a.data, b.data) & rcv.data[..., i])
            out = ~m if neg else m
            return CVal(DBool(), out, _or(l.poison, rcv.poison))
        _elems, memfn = self.domain_universe(node.rhs, cenv)
        cv = memfn(l)
        if self.abstract or not neg:
            return cv
        return CVal(DBool(), ~cv.data, cv.poison)

    def _c_UnOp(self, node: A.UnOp, cenv: CEnv):
        op = node.op
        if op == "~":
            cv = self.cbool(node.expr, cenv)
            if self.abstract:
                return cv
            return CVal(DBool(), ~cv.data, cv.poison)
        if op == "-":
            cv = self.as_cval(self.compile(node.expr, cenv))
            self._want_int(cv, node)
            d = DInt(-cv.desc.hi, -cv.desc.lo)
            if self.abstract:
                return CVal(d, None, cv.poison)
            return CVal(d, -cv.data, cv.poison)
        if op == "DOMAIN":
            cv = self.as_cval(self.compile(node.expr, cenv))
            d = cv.desc
            if isinstance(d, DSeq):
                out = DSet(tuple(range(1, d.cap + 1)))
                if self.abstract:
                    return CVal(out, None, cv.poison)
                ln = cv.data[0]
                idx = jnp.arange(1, d.cap + 1)
                return CVal(out, idx <= ln, cv.poison)
            if isinstance(d, DFun):
                out = DSet(d.keys)
                if self.abstract:
                    return CVal(out, None, cv.poison)
                pres = cv.data[0]
                if not d.partial:
                    pres = jnp.ones((len(d.keys),), jnp.bool_)
                return CVal(out, pres, cv.poison)
            raise CodegenError(f"DOMAIN of {d} at {node.loc}")
        raise CodegenError(f"cannot compile unary {op} at {node.loc}")

    def _c_Apply(self, node: A.Apply, cenv: CEnv):
        ent = cenv.get(node.op)
        if ent is not None and ent[0] == "op":
            _k, params, body, defcenv = ent
            return self._inline(params, body, defcenv, node, cenv)
        if node.op in self.spec.defs and self.spec.defs[node.op].params:
            d = self.spec.defs[node.op]
            return self._inline(d.params, d.body, CEnv(), node, cenv)
        if node.op in _BUILTIN_COMPILERS:
            return _BUILTIN_COMPILERS[node.op](self, node, cenv)
        raise CodegenError(f"cannot compile call to {node.op} at {node.loc}")

    def _inline(self, params, body, defcenv: CEnv, node: A.Apply, cenv: CEnv):
        if len(params) != len(node.args):
            raise CodegenError(f"arity mismatch calling {node.op}")
        table = {}
        for p, a in zip(params, node.args):
            v = self.compile(a, cenv)
            table[p] = ("cv", v) if isinstance(v, CVal) else ("host", v)
        return self.compile(body, defcenv.child(table))

    def _c_Index(self, node: A.Index, cenv: CEnv):
        if len(node.args) != 1:
            raise CodegenError("multi-arg application unsupported")
        if self.abstract:
            fk = getattr(self, "_fact_key", None)
            fk = fk(node, cenv) if fk is not None else None
            if fk is not None:
                ent = cenv.get(fk)
                if ent is not None and ent[0] == "cv":
                    return ent[1]
        f = self.as_cval(self.compile(node.fn, cenv))
        i = self.compile(node.args[0], cenv)
        d = f.desc
        if isinstance(d, DOpt):
            # TLC: applying Nil is an error -> poison, index the inner
            inner = CVal(
                d.inner,
                None if self.abstract else f.data[1],
                _or(f.poison, None if self.abstract else ~f.data[0]),
            )
            if self.abstract:
                inner.poison = f.poison
            return self._index_into(inner, i, node)
        return self._index_into(f, i, node)

    def _as_int_index(self, icv: CVal) -> CVal:
        """Unwrap an optional index (applying Nil is a TLC error ->
        poison) and require an integer."""
        if isinstance(icv.desc, DOpt):
            icv = CVal(
                icv.desc.inner,
                None if self.abstract else icv.data[1],
                icv.poison
                if self.abstract
                else _or(icv.poison, ~icv.data[0]),
            )
        return icv

    def _index_into(self, f: CVal, i, node) -> CVal:
        d = f.desc
        if d is None or isinstance(d, DEnum):
            if self.abstract:
                return CVal(None, None)
            raise CodegenError(f"cannot index into {d} at {node.loc}")
        if isinstance(d, DSeq):
            icv = self._as_int_index(self.as_cval(i))
            self._want_int(icv, node)
            if d.elem is None or d.cap == 0:
                return CVal(
                    DInt(0, 0),
                    None if self.abstract else jnp.int32(0),
                    _or(f.poison, icv.poison)
                    if self.abstract
                    else _or(_or(f.poison, icv.poison), jnp.bool_(True)),
                )
            if self.abstract:
                return CVal(d.elem, None, _or(f.poison, icv.poison))
            ln, ed = f.data
            idx = icv.data
            oob = (idx < 1) | (idx > ln)
            sel = jnp.clip(idx - 1, 0, d.cap - 1)
            onehot = jnp.arange(d.cap) == sel
            data = jax.tree_util.tree_map(
                lambda x: _onehot_pick(onehot, x), ed
            )
            return CVal(d.elem, data, _or(_or(f.poison, icv.poison), oob))
        if isinstance(d, DFun):
            if not isinstance(i, CVal):  # static key
                if i not in d.keys:
                    return CVal(
                        d.val,
                        None if self.abstract else _zero(self, d.val),
                        True if self.abstract else jnp.bool_(True),
                    )
                k = d.keys.index(i)
                if self.abstract:
                    return CVal(d.val, None, f.poison)
                pres, vd = f.data
                p = f.poison
                if d.partial:
                    p = _or(p, ~pres[..., k])
                data = jax.tree_util.tree_map(lambda x: x[k], vd)
                return CVal(d.val, data, p)
            # dynamic key over static universe: one-hot select
            icv = i
            if self.abstract:
                return CVal(d.val, None, _or(f.poison, icv.poison))
            pres, vd = f.data
            hits = []
            for key in d.keys:
                kc = self.lift(key)
                try:
                    a, b, dd = self._join2(icv, kc)
                    hits.append(jnp.asarray(data_eq(dd, a.data, b.data)))
                except CodegenError:
                    hits.append(jnp.bool_(False))
            onehot = jnp.stack(jnp.broadcast_arrays(*hits), axis=-1)
            found = jnp.any(onehot, axis=-1)
            inpres = (
                jnp.any(onehot & pres, axis=-1) if d.partial else found
            )
            data = jax.tree_util.tree_map(
                lambda x: _onehot_pick_axis(onehot, x), vd
            )
            p = _or(_or(f.poison, icv.poison), ~inpres)
            return CVal(d.val, data, p)
        raise CodegenError(f"cannot index into {d} at {node.loc}")

    def _c_Field(self, node: A.Field, cenv: CEnv):
        r = self.as_cval(self.compile(node.expr, cenv))
        d = r.desc
        if d is None or isinstance(d, DEnum):
            # bottom / nil-only value: field access is TLC-undefined
            if self.abstract:
                return CVal(None, None)
            raise CodegenError(f".{node.name} on {d} at {node.loc}")
        if isinstance(d, DOpt):
            inner = d.inner
            p = r.poison if self.abstract else _or(r.poison, ~r.data[0])
            r = CVal(inner, None if self.abstract else r.data[1], p)
            d = inner
        if not isinstance(d, DRec):
            raise CodegenError(f".{node.name} on {d} at {node.loc}")
        fd = d.field(node.name)
        if self.abstract:
            return CVal(fd, None, r.poison)
        return CVal(fd, r.data[node.name], r.poison)

    def _c_TupleExpr(self, node: A.TupleExpr, cenv: CEnv):
        items = [self.as_cval(self.compile(e, cenv)) for e in node.items]
        ed = None
        for it in items:
            ed = join(ed, it.desc)
        d = DSeq(ed, len(items))
        p = FALSE
        for it in items:
            p = _or(p, it.poison)
        if self.abstract:
            return CVal(d, None, p)
        if not items:
            return CVal(d, (jnp.int32(0), jnp.zeros((0,), jnp.int32)), p)
        datas = [self._coerce(it, ed).data for it in items]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *datas)
        return CVal(d, (jnp.int32(len(items)), stacked), p)

    def _c_SetEnum(self, node: A.SetEnum, cenv: CEnv):
        items = [self.compile(e, cenv) for e in node.items]
        host_atoms = set()
        for it, e in zip(items, node.items):
            cv = self.as_cval(it)
            host_atoms |= set(_desc_atoms(cv.desc, e))
        uni = tuple(sorted(host_atoms, key=_sort_key))
        d = DSet(uni)
        p = FALSE
        for it in items:
            if isinstance(it, CVal):
                p = _or(p, it.poison)
        if self.abstract:
            return CVal(d, None, p)
        mask = jnp.zeros((len(uni),), jnp.bool_)
        for it in items:
            cv = self.as_cval(it)
            hits = []
            for u in uni:
                uc = self.lift(u)
                try:
                    a, b, dd = self._join2(cv, uc)
                    hits.append(data_eq(dd, a.data, b.data))
                except CodegenError:
                    hits.append(jnp.bool_(False))
            mask = mask | jnp.stack(hits, axis=-1)
        return CVal(d, mask, p)

    def _c_SetFilter(self, node: A.SetFilter, cenv: CEnv):
        elems, memfn = self.domain_universe(node.domain, cenv)
        uni = tuple(elems)
        d = DSet(uni)
        if self.abstract:
            # poison: quantified bodies may poison; ignored per-element
            return CVal(d, None)
        masks, p = [], FALSE
        for e in elems:
            sub = cenv.child({node.var: ("host", e)})
            pv = self.cbool(node.pred, sub)
            m = pv.data
            pe = pv.poison
            if memfn is not None:
                mem = memfn(e)
                m = m & mem.data
                pe = _and_val(mem.data, pe)
            masks.append(m)
            p = _or(p, pe)
        mask = jnp.stack(masks, axis=-1) if masks else jnp.zeros((0,), bool)
        return CVal(d, mask, p)

    def _c_SetMap(self, node: A.SetMap, cenv: CEnv):
        elems, memfn = self.domain_universe(node.domain, cenv)
        # value universe: atoms of the body desc across all bindings
        vals: List[CVal] = []
        for e in elems:
            sub = cenv.child({node.var: ("host", e)})
            vals.append(self.as_cval(self.compile(node.expr, sub)))
        atoms = set()
        for cv in vals:
            atoms |= set(_desc_atoms(cv.desc, node))
        uni = tuple(sorted(atoms, key=_sort_key))
        d = DSet(uni)
        if self.abstract:
            return CVal(d, None)
        mask = jnp.zeros((len(uni),), jnp.bool_)
        p = FALSE
        for e, cv in zip(elems, vals):
            sel = jnp.bool_(True)
            if memfn is not None:
                sel = memfn(e).data
            p = _or(p, _and_val(sel, cv.poison))
            hits = []
            for u in uni:
                uc = self.lift(u)
                try:
                    a, b, dd = self._join2(cv, uc)
                    hits.append(data_eq(dd, a.data, b.data) & sel)
                except CodegenError:
                    hits.append(jnp.bool_(False))
            mask = mask | jnp.stack(hits, axis=-1)
        return CVal(d, mask, p)

    def _c_FnConstruct(self, node: A.FnConstruct, cenv: CEnv):
        # [i \in 1..n |-> e] IS a sequence in the TLA+ value canon
        # (interp make_fn normalization); compile 1..hi domains to DSeq
        dom = node.domain
        if (
            isinstance(dom, A.BinOp)
            and dom.op == ".."
            and self.is_dynamic(dom, cenv)
            and not self.is_dynamic(dom.lhs, cenv)
            and self.host_eval(dom.lhs, cenv) == 1
        ):
            hi = self.as_cval(self.compile(dom.rhs, cenv))
            self._want_int(hi, node)
            if hi.desc is None:
                return CVal(None, None)
            cap = max(hi.desc.hi, 0)
            vals = []
            p = hi.poison
            for j in range(1, cap + 1):
                sub = cenv.child({node.var: ("host", j)})
                cv = self.as_cval(self.compile(node.body, sub))
                vals.append(cv)
            ed = None
            for cv in vals:
                ed = join(ed, cv.desc)
            d = DSeq(ed, cap)
            if self.abstract:
                return CVal(d, None, FALSE)
            ln = jnp.clip(hi.data, 0, cap)
            if cap == 0:
                return CVal(d, (ln, jnp.zeros((0,), jnp.int32)), p)
            live = jnp.arange(cap) < ln
            for j, cv in enumerate(vals):
                p = _or(p, _and_val(live[j], cv.poison))
            datas = [self._coerce(cv, ed).data for cv in vals]
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *datas
            )
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.where(_bcast(live, x), x, jnp.zeros_like(x)),
                stacked,
            )
            return CVal(d, (ln, stacked), p)
        elems, memfn = self.domain_universe(node.domain, cenv)
        if memfn is None and list(elems) == list(range(1, len(elems) + 1)):
            # static contiguous 1..n domain: also a sequence
            vals = []
            p = FALSE
            for j in elems:
                sub = cenv.child({node.var: ("host", j)})
                cv = self.as_cval(self.compile(node.body, sub))
                p = _or(p, cv.poison)
                vals.append(cv)
            ed = None
            for cv in vals:
                ed = join(ed, cv.desc)
            d = DSeq(ed, len(elems))
            if self.abstract:
                return CVal(d, None, FALSE)
            if not vals:
                return CVal(
                    d, (jnp.int32(0), jnp.zeros((0,), jnp.int32)), p
                )
            datas = [self._coerce(cv, ed).data for cv in vals]
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *datas
            )
            return CVal(d, (jnp.int32(len(elems)), stacked), p)
        keys = tuple(sorted(elems, key=_sort_key))
        vals: List[CVal] = []
        pres: List = []
        p = FALSE
        for e in keys:
            sub = cenv.child({node.var: ("host", e)})
            cv = self.as_cval(self.compile(node.body, sub))
            if memfn is not None and not self.abstract:
                sel = memfn(e).data
                pres.append(sel)
                p = _or(p, _and_val(sel, cv.poison))
            else:
                p = _or(p, cv.poison)
            vals.append(cv)
        vd = None
        for cv in vals:
            vd = join(vd, cv.desc)
        d = DFun(keys, vd, partial=memfn is not None)
        if self.abstract:
            return CVal(d, None, FALSE)
        datas = [self._coerce(cv, vd).data for cv in vals]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *datas)
        if memfn is not None:
            pr = jnp.stack(pres, axis=-1)
            stacked = jax.tree_util.tree_map(
                lambda x: _mask_axis(pr, x), stacked
            )
        else:
            pr = ()
        return CVal(d, (pr, stacked), p)

    def _c_FnExcept(self, node: A.FnExcept, cenv: CEnv):
        cur = self.as_cval(self.compile(node.fn, cenv))
        for idx_e, val_e in node.updates:
            cur = self._except_one(cur, idx_e, val_e, cenv, node)
        return cur

    def _except_one(self, cur: CVal, idx_e, val_e, cenv, node) -> CVal:
        d = cur.desc
        if isinstance(d, DSeq):
            icv = self._as_int_index(self.as_cval(self.compile(idx_e, cenv)))
            self._want_int(icv, node)
            old = self._index_into(cur, icv, node)
            sub = cenv.child({"@": ("cv", old)})
            vcv = self.as_cval(self.compile(val_e, sub))
            elem = join(d.elem, vcv.desc)
            nd = DSeq(elem, d.cap)
            p = _or(_or(cur.poison, icv.poison), vcv.poison)
            if self.abstract:
                return CVal(nd, None, p)
            cc = self._coerce(cur, nd)
            ln, ed = cc.data
            oob = (icv.data < 1) | (icv.data > ln)
            if d.cap == 0:
                return CVal(nd, (ln, ed), _or(p, oob))
            sel = jnp.clip(icv.data - 1, 0, nd.cap - 1)
            onehot = jnp.arange(nd.cap) == sel
            vcc = self._coerce(vcv, elem)
            ed = jax.tree_util.tree_map(
                lambda x, v: _onehot_set(onehot, x, v), ed, vcc.data
            )
            # out-of-cap writes must not corrupt slot data
            live = jnp.arange(nd.cap) < ln
            ed = jax.tree_util.tree_map(
                lambda x, o: jnp.where(_bcast(live, x), x, o), ed, cc.data[1]
            )
            return CVal(nd, (ln, ed), _or(p, oob))
        if isinstance(d, DFun):
            i = self.compile(idx_e, cenv)
            if isinstance(i, CVal):
                return self._except_fun_dynamic(cur, i, val_e, cenv, node)
            if i not in d.keys:
                return CVal(
                    d, cur.data,
                    True if self.abstract else _or(cur.poison, jnp.bool_(True)),
                )
            k = d.keys.index(i)
            old = self._index_into(cur, i, node)
            sub = cenv.child({"@": ("cv", old)})
            vcv = self.as_cval(self.compile(val_e, sub))
            val = join(d.val, vcv.desc)
            nd = DFun(d.keys, val, d.partial)
            p = _or(cur.poison, vcv.poison)
            if self.abstract:
                return CVal(nd, None, p)
            cc = self._coerce(cur, nd)
            pres, vd = cc.data
            if d.partial:
                p = _or(p, ~pres[..., k])
            vcc = self._coerce(vcv, val)
            onehot = jnp.arange(len(d.keys)) == k
            vd = jax.tree_util.tree_map(
                lambda x, v: _onehot_set(onehot, x, v), vd, vcc.data
            )
            return CVal(nd, (pres, vd), p)
        raise CodegenError(f"EXCEPT on {d} at {node.loc}")

    def _except_fun_dynamic(
        self, cur: CVal, icv: CVal, val_e, cenv, node
    ) -> CVal:
        """``[f EXCEPT ![i] = e]`` with a dynamic key: one-hot update
        over the static key universe; out-of-domain keys poison (gated
        by the enclosing guards' lazy algebra)."""
        d = cur.desc
        old = self._index_into(cur, icv, node)
        sub = cenv.child({"@": ("cv", old)})
        vcv = self.as_cval(self.compile(val_e, sub))
        val = join(d.val, vcv.desc)
        nd = DFun(d.keys, val, d.partial)
        p = _or(cur.poison, icv.poison)
        if self.abstract:
            return CVal(nd, None, p)
        hits = []
        for key in d.keys:
            kc = self.lift(key)
            try:
                a, b, dd = self._join2(icv, kc)
                hits.append(jnp.asarray(data_eq(dd, a.data, b.data)))
            except CodegenError:
                hits.append(jnp.bool_(False))
        onehot = jnp.stack(jnp.broadcast_arrays(*hits), axis=-1)
        found = jnp.any(onehot, axis=-1)
        cc = self._coerce(cur, nd)
        pres, vd = cc.data
        if d.partial:
            p = _or(p, jnp.any(onehot & ~pres, axis=-1))
        vcc = self._coerce(vcv, val)
        vd = jax.tree_util.tree_map(
            lambda x, v: _onehot_set_dyn(onehot, x, v), vd, vcc.data
        )
        p = _or(p, _or(~found, vcc.poison))
        return CVal(nd, (pres, vd), p)

    def _c_RecordLit(self, node: A.RecordLit, cenv: CEnv):
        fields = []
        datas = {}
        p = FALSE
        for name, e in sorted(node.fields, key=lambda fe: fe[0]):
            cv = self.as_cval(self.compile(e, cenv))
            fields.append((name, cv.desc))
            p = _or(p, cv.poison)
            if not self.abstract:
                datas[name] = cv.data
        d = DRec(tuple(fields))
        if self.abstract:
            return CVal(d, None, p)
        return CVal(d, datas, p)

    def _c_Quant(self, node: A.Quant, cenv: CEnv):
        return self._quant(node, 0, cenv)

    def _quant(self, node: A.Quant, b: int, cenv: CEnv) -> CVal:
        if b == len(node.bindings):
            return self.cbool(node.body, cenv)
        var, dom_e = node.bindings[b]
        elems, memfn = self.domain_universe(dom_e, cenv)
        vals, p = [], FALSE
        for e in sorted(elems, key=_sort_key):
            sub = cenv.child({var: ("host", e)})
            cv = self._quant(node, b + 1, sub)
            if self.abstract:
                continue
            v = cv.data
            pe = cv.poison
            if memfn is not None:
                mem = memfn(e)
                pe = _and_val(mem.data, pe)
                v = (
                    (v | ~mem.data)
                    if node.kind == "A"
                    else (v & mem.data)
                )
            vals.append(v)
            p = _or(p, pe)
        if self.abstract:
            return CVal(DBool(), None)
        if not vals:
            return CVal(DBool(), jnp.bool_(node.kind == "A"))
        stack = jnp.stack(vals, axis=-1)
        out = jnp.all(stack, axis=-1) if node.kind == "A" else jnp.any(
            stack, axis=-1
        )
        return CVal(DBool(), out, p)

    def _c_Choose(self, node: A.Choose, cenv: CEnv):
        elems, memfn = self.domain_universe(node.domain, cenv)
        elems = sorted(elems, key=_sort_key)
        cands: List[Tuple[CVal, object]] = []
        p = FALSE
        for e in elems:
            sub = cenv.child({node.var: ("host", e)})
            pv = self.cbool(node.pred, sub)
            if self.abstract:
                continue
            sel = pv.data
            pe = pv.poison
            if memfn is not None:
                mem = memfn(e)
                sel = sel & mem.data
                pe = _and_val(mem.data, pe)
            cands.append((self.lift(e), sel))
            p = _or(p, pe)
        vd = None
        for e in elems:
            vd = join(vd, desc_of_value(e))
        if vd is None:
            # statically empty domain (possible mid-fixpoint): always a
            # no-witness error if demanded; bottom / poisoned placeholder
            if self.abstract:
                return CVal(None, None)
            return CVal(DInt(0, 0), jnp.int32(0), jnp.bool_(True))
        if self.abstract:
            return CVal(vd, None)
        # first (by _sort_key order) element whose predicate holds
        out = self._coerce(self.lift(elems[0]), vd).data
        found = jnp.bool_(False)
        for cv, sel in cands:
            take = sel & ~found
            dd = self._coerce(cv, vd).data
            out = jax.tree_util.tree_map(
                lambda o, n: jnp.where(_bcast(take, n), n, o), out, dd
            )
            found = found | sel
        return CVal(vd, out, _or(p, ~found))

    def _c_If(self, node: A.If, cenv: CEnv):
        c = self.cbool(node.cond, cenv)
        t = self.as_cval(self.compile(node.then, cenv))
        e = self.as_cval(self.compile(node.orelse, cenv))
        tc, ec, d = self._join2(t, e)
        if self.abstract:
            return CVal(d, None, FALSE)
        data = data_where(d, c.data, tc.data, ec.data)
        p = _or(
            c.poison,
            _or(_and_val(c.data, tc.poison), _and_val(~c.data, ec.poison)),
        )
        return CVal(d, data, p)

    def _c_Let(self, node: A.Let, cenv: CEnv):
        table = {}
        sub = cenv.child(table)
        for name, params, body in node.defs:
            if params:
                table[name] = ("op", params, body, sub)
            else:
                if self.is_dynamic(body, sub):
                    table[name] = ("cv", self.as_cval(self.compile(body, sub)))
                else:
                    table[name] = ("host", self.host_eval(body, sub))
        return self.compile(node.body, sub)

    def _c_Lambda(self, node: A.Lambda, cenv: CEnv):
        raise CodegenError(f"LAMBDA outside SelectSeq at {node.loc}")

    def _c_Num(self, node, cenv):
        return node.value

    def _c_Bool(self, node, cenv):
        return node.value

    def _c_Str(self, node, cenv):
        return node.value


def _bcast(mask, arr):
    extra = arr.ndim - jnp.asarray(mask).ndim
    if extra > 0:
        return jnp.reshape(mask, jnp.shape(mask) + (1,) * extra)
    return mask


def _onehot_pick(onehot, x):
    """x[cap, ...] selected by onehot[cap] -> [...]."""
    oh = onehot
    while oh.ndim < x.ndim:
        oh = oh[..., None]
    return jnp.sum(jnp.where(oh, x, 0), axis=0).astype(x.dtype)


def _onehot_pick_axis(onehot, x):
    """x[..., k, ...]?  vals have leading key axis at position 0 after the
    batch dims collapse — here x is [k, ...] and onehot [..., k]."""
    oh = onehot
    # onehot [..., k]; x [k, ...]: contract over k
    oh2 = jnp.moveaxis(oh, -1, 0)
    while oh2.ndim < x.ndim:
        oh2 = oh2[..., None]
    return jnp.sum(jnp.where(oh2, x, 0), axis=0).astype(x.dtype)


def _onehot_set(onehot, x, v):
    """x[cap, ...] with x[i] = v where onehot[i]."""
    oh = onehot
    while oh.ndim < x.ndim:
        oh = oh[..., None]
    vv = jnp.asarray(v)
    return jnp.where(oh, vv, x)


def _onehot_set_dyn(onehot, x, v):
    """x[k, ...] updated with v where onehot[..., k] (dynamic key)."""
    oh = jnp.moveaxis(jnp.asarray(onehot), -1, 0)
    while oh.ndim < x.ndim:
        oh = oh[..., None]
    return jnp.where(oh, jnp.asarray(v), x)


def _mask_axis(pres, x):
    """Zero val slots whose presence bit is off (canonical form)."""
    m = jnp.moveaxis(pres, -1, 0)
    while m.ndim < x.ndim:
        m = m[..., None]
    return jnp.where(m, x, jnp.zeros_like(x))


def _zero(compiler: Compiler, d):
    return jax.tree_util.tree_map(jnp.asarray, encode_value_zero(d))


def _desc_atoms(d, node) -> List:
    """Enumerable host atoms of a scalar descriptor (for set universes)."""
    if isinstance(d, DInt):
        if d.hi - d.lo > Compiler.MAX_UNIVERSE:
            raise CodegenError(f"int range too wide for a set universe: {d}")
        return list(range(d.lo, d.hi + 1))
    if isinstance(d, DBool):
        return [False, True]
    if isinstance(d, DEnum):
        return list(d.members)
    raise CodegenError(
        f"set universe of non-atomic desc {d} at {getattr(node, 'loc', None)}"
    )


# ---------------------------------------------------------------- builtins


def _unopt(c: Compiler, cv: CVal) -> CVal:
    """Unwrap an option value: using Nil where a sequence/set/record is
    demanded is a TLC evaluation error -> poison."""
    if isinstance(cv.desc, DOpt):
        return CVal(
            cv.desc.inner,
            None if c.abstract else cv.data[1],
            cv.poison if c.abstract else _or(cv.poison, ~cv.data[0]),
        )
    return cv


def _b_len(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    if s.desc is None and c.abstract:
        return CVal(None, None)
    if not isinstance(s.desc, DSeq):
        raise CodegenError(f"Len of {s.desc} at {node.loc}")
    d = DInt(0, s.desc.cap)
    if c.abstract:
        return CVal(d, None, s.poison)
    return CVal(d, s.data[0], s.poison)


def _b_append(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    v = c.as_cval(c.compile(node.args[1], cenv))
    if s.desc is None and c.abstract:
        return CVal(None, None)
    if not isinstance(s.desc, DSeq):
        raise CodegenError(f"Append to {s.desc} at {node.loc}")
    elem = join(s.desc.elem, v.desc)
    cap = s.desc.cap + 1
    d = DSeq(elem, cap)
    p = _or(s.poison, v.poison)
    if c.abstract:
        return CVal(d, None, p)
    sc = c._coerce(s, DSeq(elem, cap))
    ln, ed = sc.data
    vcc = c._coerce(v, elem)
    onehot = jnp.arange(cap) == jnp.clip(ln, 0, cap - 1)
    ed = jax.tree_util.tree_map(
        lambda x, nv: _onehot_set(onehot, x, nv), ed, vcc.data
    )
    return CVal(d, (ln + 1, ed), p)


def _b_head(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    fake = A.Index(fn=node.args[0], args=(A.Num(value=1),), loc=node.loc)
    return c._index_into(s, c.lift(1), fake)


def _b_tail(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    if s.desc is None and c.abstract:
        return CVal(None, None)
    if not isinstance(s.desc, DSeq):
        raise CodegenError(f"Tail of {s.desc} at {node.loc}")
    d = DSeq(s.desc.elem, max(s.desc.cap - 1, 0))
    p = s.poison
    if c.abstract:
        return CVal(d, None, p)
    ln, ed = s.data
    p = _or(p, ln < 1)
    ed2 = jax.tree_util.tree_map(lambda x: x[1:], ed)
    return CVal(d, (jnp.maximum(ln - 1, 0), ed2), p)


def _b_cardinality(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    if s.desc is None and c.abstract:
        return CVal(None, None)
    if not isinstance(s.desc, DSet):
        raise CodegenError(f"Cardinality of {s.desc} at {node.loc}")
    d = DInt(0, len(s.desc.universe))
    if c.abstract:
        return CVal(d, None, s.poison)
    return CVal(
        d, jnp.sum(s.data.astype(jnp.int32), axis=-1), s.poison
    )


def _b_selectseq(c: Compiler, node: A.Apply, cenv: CEnv):
    s = _unopt(c, c.as_cval(c.compile(node.args[0], cenv)))
    if s.desc is None and c.abstract:
        return CVal(None, None)
    if not isinstance(s.desc, DSeq):
        raise CodegenError(f"SelectSeq of {s.desc} at {node.loc}")
    lam = node.args[1]
    if isinstance(lam, A.Lambda):
        params, body, lamenv = lam.params, lam.body, cenv
    else:
        ent = cenv.get(getattr(lam, "name", None)) if isinstance(
            lam, A.Name
        ) else None
        if ent is not None and ent[0] == "op":
            _k, params, body, lamenv = ent
        elif (
            isinstance(lam, A.Name)
            and lam.name in c.spec.defs
            and c.spec.defs[lam.name].params
        ):
            dd = c.spec.defs[lam.name]
            params, body, lamenv = dd.params, dd.body, CEnv()
        else:
            raise CodegenError(f"SelectSeq filter unsupported at {node.loc}")
    cap = s.desc.cap
    d = DSeq(s.desc.elem, cap)
    if c.abstract:
        return CVal(d, None, s.poison)
    ln, ed = s.data
    keeps, p = [], s.poison
    for j in range(cap):
        ej = CVal(
            s.desc.elem, jax.tree_util.tree_map(lambda x: x[j], ed)
        )
        sub = lamenv.child({params[0]: ("cv", ej)})
        kv = c.cbool(body, sub)
        live = jnp.asarray(j < ln)
        keeps.append(kv.data & live)
        p = _or(p, _and_val(live, kv.poison))
    if cap == 0:
        return CVal(d, (jnp.int32(0), ed), p)
    keep = jnp.stack(keeps)  # [cap]
    tgt = jnp.cumsum(keep.astype(jnp.int32)) - 1  # kept j -> output slot
    out_ln = jnp.sum(keep.astype(jnp.int32))
    # out[i] = elem at the (i+1)-th kept position: one-hot matrix [i, j]
    sel = keep[None, :] & (tgt[None, :] == jnp.arange(cap)[:, None])
    ed2 = jax.tree_util.tree_map(
        lambda x: _compress(sel, x), ed
    )
    return CVal(d, (out_ln, ed2), p)


def _compress(sel, x):
    """sel[i, j]: out[i] = x[j] where sel (at most one j per i)."""
    s = sel
    while s.ndim < x.ndim + 1:
        s = s[..., None]
    return jnp.sum(jnp.where(s, x[None, ...], 0), axis=1).astype(x.dtype)


_BUILTIN_COMPILERS = {
    "Len": _b_len,
    "Append": _b_append,
    "Head": _b_head,
    "Tail": _b_tail,
    "Cardinality": _b_cardinality,
    "SelectSeq": _b_selectseq,
}


# ---------------------------------------------------------------- actions


@dataclass
class ActState:
    """One lane in progress: primed assignments + accumulated guard."""

    cenv: CEnv
    assigns: Dict[str, CVal] = field(default_factory=dict)
    valid: object = True  # True | bool array
    poison: object = FALSE
    label: Optional[str] = None

    def fork(self) -> "ActState":
        return ActState(
            self.cenv, dict(self.assigns), self.valid, self.poison,
            self.label,
        )


class ActionCompiler(Compiler):
    """Adds the Init/Next lane walker to the expression compiler.

    The walk mirrors the interpreter's ``_enum`` exactly: conjunction
    threads assignments left to right, disjunction / ``\\E`` /
    ``x' \\in S`` fork lanes, named definitions inline (first name on
    the path labels the lane), IF forks on its (possibly dynamic)
    condition, UNCHANGED copies current values.  In the abstract pass
    recognized guards narrow variable descriptors so bounded-growth
    patterns converge."""

    def __init__(self, spec: Spec, primed: bool):
        super().__init__(spec)
        self.primed = primed
        self.lanes: List[ActState] = []

    # -- guard narrowing (abstract pass only) --------------------------

    _FLIP = {"<": ">", ">": "<", "<=": ">=", ">=": "<=", "=": "="}

    def _narrow(self, node: A.Node, cenv: CEnv) -> CEnv:
        if not self.abstract or not isinstance(node, A.BinOp):
            return cenv
        op, lhs, rhs = node.op, node.lhs, node.rhs
        if op == "\\in":
            return self._narrow_membership(lhs, rhs, cenv)
        if op not in ("<", ">", "<=", ">=", "="):
            return cenv
        if self.is_dynamic(rhs, cenv) and not self.is_dynamic(lhs, cenv):
            lhs, rhs = rhs, lhs
            op = self._FLIP[op]
        if self.is_dynamic(rhs, cenv):
            # dynamic bound (e.g. ``lac < added``): use the rhs
            # DESCRIPTOR's static envelope — lac < added <= added.hi
            try:
                rcv = self.as_cval(self.compile(rhs, cenv))
            except CodegenError:
                return cenv
            if not isinstance(rcv.desc, DInt):
                return cenv
            blo, bhi = rcv.desc.lo, rcv.desc.hi
        else:
            try:
                bound = self.host_eval(rhs, cenv)
            except EvalError:
                return cenv
            if not isinstance(bound, int) or isinstance(bound, bool):
                return cenv
            blo = bhi = bound
        hi = {"<": bhi - 1, "<=": bhi, "=": bhi}.get(op)
        lo = {">": blo + 1, ">=": blo, "=": blo}.get(op)
        # Len(v) bound -> narrow the seq cap
        if (
            isinstance(lhs, A.Apply)
            and lhs.op == "Len"
            and len(lhs.args) == 1
            and isinstance(lhs.args[0], A.Name)
        ):
            nm = lhs.args[0].name
            ent = cenv.get(nm)
            if ent is not None and ent[0] == "cv" and isinstance(
                ent[1].desc, DSeq
            ) and hi is not None:
                d = ent[1].desc
                nd = DSeq(d.elem, min(d.cap, max(hi, 0)))
                return cenv.child({nm: ("cv", CVal(nd, None))})
            return cenv
        if isinstance(lhs, A.Name):
            ent = cenv.get(lhs.name)
            if ent is not None and ent[0] == "cv" and isinstance(
                ent[1].desc, DInt
            ):
                d = ent[1].desc
                nlo = max(d.lo, lo) if lo is not None else d.lo
                nhi = min(d.hi, hi) if hi is not None else d.hi
                if nlo > nhi:
                    nlo, nhi = d.lo, d.hi  # contradictory guard: skip
                return cenv.child(
                    {lhs.name: ("cv", CVal(DInt(nlo, nhi), None))}
                )
        # guard on an indexed element, e.g. ``published[c] < Limit``:
        # record an index-level fact (sound: scoped to this lane's env
        # and to this exact host index) consulted by _c_Index
        fk = self._fact_key(lhs, cenv)
        if fk is not None:
            cur = self.as_cval(self.compile(lhs, cenv))
            if isinstance(cur.desc, DInt):
                d = cur.desc
                nlo = max(d.lo, lo) if lo is not None else d.lo
                nhi = min(d.hi, hi) if hi is not None else d.hi
                if nlo <= nhi:
                    return cenv.child(
                        {fk: ("cv", CVal(DInt(nlo, nhi), None))}
                    )
        return cenv

    def _fact_key(self, node, cenv: CEnv) -> Optional[str]:
        """Stable key for ``Name[host-index]`` / ``Name[i][j]`` chains."""
        idxs = []
        while isinstance(node, A.Index) and len(node.args) == 1:
            if self.is_dynamic(node.args[0], cenv):
                return None
            try:
                idxs.append(self.host_eval(node.args[0], cenv))
            except EvalError:
                return None
            node = node.fn
        if not idxs or not isinstance(node, A.Name):
            return None
        ent = cenv.get(node.name)
        if ent is None or ent[0] != "cv":
            return None
        # key by the resolved binding's identity, not the bare name, so a
        # LET binding shadowing a state variable never inherits its facts
        return f"__fact__:{id(ent)}:{list(reversed(idxs))!r}"

    def _narrow_membership(self, lhs, rhs, cenv: CEnv) -> CEnv:
        """Guard ``v \\in S`` or ``v ± c \\in S``: bound v's int range by
        S's static universe (the membership-guard analog of the CMP
        narrowing; needed for mutual-growth patterns like
        ``(markDelete + 1) \\in acked`` + ``markDelete' = markDelete + 1``)."""
        shift = 0
        if (
            isinstance(lhs, A.BinOp)
            and lhs.op in ("+", "-")
            and isinstance(lhs.lhs, A.Name)
            and not self.is_dynamic(lhs.rhs, cenv)
        ):
            try:
                c = self.host_eval(lhs.rhs, cenv)
            except EvalError:
                return cenv
            if not isinstance(c, int) or isinstance(c, bool):
                return cenv
            shift = c if lhs.op == "+" else -c
            lhs = lhs.lhs
        if not isinstance(lhs, A.Name):
            return cenv
        ent = cenv.get(lhs.name)
        if ent is None or ent[0] != "cv" or not isinstance(
            ent[1].desc, DInt
        ):
            return cenv
        try:
            elems, _m = self.domain_universe(rhs, cenv)
        except CodegenError:
            return cenv
        ints = [e for e in elems if isinstance(e, int)
                and not isinstance(e, bool)]
        if not ints:
            return cenv
        d = ent[1].desc
        nlo = max(d.lo, min(ints) - shift)
        nhi = min(d.hi, max(ints) - shift)
        if nlo > nhi:
            return cenv
        return cenv.child({lhs.name: ("cv", CVal(DInt(nlo, nhi), None))})

    # -- the walk ------------------------------------------------------

    def run(self, node: A.Node, cenv: CEnv) -> List[ActState]:
        self.lanes = []
        st = ActState(cenv)
        self._act(node, st, self._finish)
        return self.lanes

    def _finish(self, st: ActState):
        if len(self.lanes) >= self.MAX_LANES:
            raise CodegenError("action lane explosion (raise MAX_LANES?)")
        self.lanes.append(st)

    def _guard(self, node: A.Node, st: ActState, cont):
        cv = self.cbool(node, st.cenv)
        if not self.abstract:
            st.poison = _or(st.poison, _and_val(st.valid, cv.poison))
            st.valid = (
                cv.data if st.valid is True else st.valid & cv.data
            )
        st.cenv = self._narrow(node, st.cenv)
        cont(st)

    def _assign(self, var: str, cv: CVal, st: ActState, cont):
        key = var + "'"
        if var in st.assigns:
            prev = st.assigns[var]
            if not self.abstract:
                a, b, d = self._join2(prev, cv)
                eq = data_eq(d, a.data, b.data)
                st.poison = _or(
                    st.poison, _and_val(st.valid, _or(prev.poison, cv.poison))
                )
                st.valid = eq if st.valid is True else st.valid & eq
            cont(st)
            return
        st.assigns[var] = cv
        st.cenv = st.cenv.child({key: ("cv", cv)})
        cont(st)

    def _act(self, node: A.Node, st: ActState, cont):
        k = type(node)
        if k is A.Junction and node.op == "/\\":
            self._conj_act(list(node.items), st, cont)
            return
        if k is A.BinOp and node.op == "/\\":
            self._conj_act([node.lhs, node.rhs], st, cont)
            return
        if k is A.Junction and node.op == "\\/":
            for item in node.items:
                self._act(item, st.fork(), cont)
            return
        if k is A.BinOp and node.op == "\\/":
            self._act(node.lhs, st.fork(), cont)
            self._act(node.rhs, st.fork(), cont)
            return
        if k is A.Quant and node.kind == "E":
            self._exists(node, 0, st, cont)
            return
        if k is A.Let:
            table: Dict[str, object] = {}
            sub = st.cenv.child(table)
            for name, params, body in node.defs:
                if params:
                    table[name] = ("op", params, body, sub)
                elif self.is_dynamic(body, sub):
                    table[name] = (
                        "cv", self.as_cval(self.compile(body, sub))
                    )
                else:
                    table[name] = ("host", self.host_eval(body, sub))
            st.cenv = sub
            self._act(node.body, st, cont)
            return
        if k is A.If:
            if not self.is_dynamic(node.cond, st.cenv):
                c = self.host_eval(node.cond, st.cenv)
                self._act(node.then if c else node.orelse, st, cont)
                return
            t = st.fork()
            self._guard(node.cond, t, lambda s: self._act(node.then, s, cont))
            e = st.fork()
            self._guard(
                A.UnOp(op="~", expr=node.cond, loc=node.loc), e,
                lambda s: self._act(node.orelse, s, cont),
            )
            return
        if k is A.Name and node.name in self.spec.defs:
            d = self.spec.defs[node.name]
            if not d.params:
                st.label = st.label or node.name
                self._act(d.body, st, cont)
                return
        if k is A.Apply and node.op in self.spec.defs:
            d = self.spec.defs[node.op]
            if d.params:
                table = {}
                for p, a in zip(d.params, node.args):
                    v = self.compile(a, st.cenv)
                    table[p] = (
                        ("cv", v) if isinstance(v, CVal) else ("host", v)
                    )
                st.label = st.label or node.op
                st.cenv = st.cenv.child(table)
                self._act(d.body, st, cont)
                return
        if k is A.UnOp and node.op == "UNCHANGED":
            if not self.primed:
                raise CodegenError("UNCHANGED in Init")
            for v in _unchanged_names(node.expr, self.varset):
                ent = st.cenv.get(v)
                if ent is None or ent[0] != "cv":
                    raise CodegenError(f"UNCHANGED of unbound {v}")
                # _assign mutates st in place and calls cont synchronously
                self._assign(v, ent[1], st, lambda s: None)
            cont(st)
            return
        tgt = self._assign_target(node)
        if tgt is not None:
            var, kind, rhs = tgt
            if kind == "=":
                cv = self.as_cval(self.compile(rhs, st.cenv))
                self._assign(var, cv, st, cont)
                return
            # x' \in S : fork one lane per universe element
            if not self.is_dynamic(rhs, st.cenv):
                dom = self.host_eval(rhs, st.cenv)
                elems = sorted(_enum_set(dom), key=_sort_key)
                if len(elems) * max(len(self.lanes), 1) > self.MAX_LANES:
                    raise CodegenError(
                        f"x' \\in S fanout too large ({len(elems)})"
                    )
                for e in elems:
                    s2 = st.fork()
                    self._assign(var, self.lift(e), s2, cont)
                return
            elems, memfn = self.domain_universe(rhs, st.cenv)
            for e in sorted(elems, key=_sort_key):
                s2 = st.fork()
                mem = memfn(e)
                if not self.abstract:
                    s2.poison = _or(s2.poison, _and_val(s2.valid, mem.poison))
                    s2.valid = (
                        mem.data
                        if s2.valid is True
                        else s2.valid & mem.data
                    )
                self._assign(var, self.lift(e), s2, cont)
            return
        # plain guard
        self._guard(node, st, cont)

    def _conj_act(self, items, st: ActState, cont):
        if not items:
            cont(st)
            return
        head, rest = items[0], items[1:]
        self._act(head, st, lambda s: self._conj_act(rest, s, cont))

    def _exists(self, node: A.Quant, b: int, st: ActState, cont):
        if b == len(node.bindings):
            self._act(node.body, st, cont)
            return
        var, dom_e = node.bindings[b]
        elems, memfn = self.domain_universe(dom_e, st.cenv)
        elems = sorted(elems, key=_sort_key)
        for e in elems:
            s2 = st.fork()
            if memfn is not None:
                mem = memfn(e)
                if not self.abstract:
                    s2.poison = _or(s2.poison, _and_val(s2.valid, mem.poison))
                    s2.valid = (
                        mem.data
                        if s2.valid is True
                        else s2.valid & mem.data
                    )
            s2.cenv = s2.cenv.child({var: ("host", e)})
            self._exists(node, b + 1, s2, cont)

    def _assign_target(self, node):
        if not isinstance(node, A.BinOp) or node.op not in ("=", "\\in"):
            return None
        lhs = node.lhs
        if self.primed:
            if isinstance(lhs, A.Prime) and isinstance(lhs.expr, A.Name):
                nm = lhs.expr.name
                if nm in self.varset:
                    return nm, node.op, node.rhs
            return None
        if isinstance(lhs, A.Name) and lhs.name in self.varset:
            return lhs.name, node.op, node.rhs
        return None


# ---------------------------------------------------------- inference


ERR_VAR = "__err__"


def infer_var_descs(spec: Spec, max_iters: int = 64) -> Dict[str, object]:
    """Abstract fixpoint: Init seeds the descriptors, Next widens them
    (with guard narrowing) until stable."""
    descs: Dict[str, object] = {}
    # Init seeds: when Init factors into per-variable draws, a
    # representative sample covering every per-slot candidate value
    # joins to the same descriptors as the full cross product — which
    # can be astronomically large (compaction.tla:191-194 at M=64);
    # otherwise enumerate host-side through the interpreter (exact)
    vfactors = _factor_init_values(spec)
    init_sample = (
        _init_value_sample(vfactors)
        if vfactors is not None
        else spec.initial_states()
    )
    for s in init_sample:
        for v, val in zip(spec.vars, s):
            descs[v] = join(descs.get(v), desc_of_value(val))
    for _ in range(max_iters):
        ac = ActionCompiler(spec, primed=True)
        ac.abstract = True
        cenv = CEnv(
            {v: ("cv", CVal(descs[v], None)) for v in spec.vars}
        )
        lanes = ac.run(spec.defs["Next"].body, cenv)
        new = dict(descs)
        for lane in lanes:
            for v in spec.vars:
                if v not in lane.assigns:
                    raise CodegenError(
                        f"lane {lane.label} leaves {v}' unassigned"
                    )
                new[v] = join(new[v], lane.assigns[v].desc)
        if new == descs:
            return descs
        descs = new
    raise CodegenError("descriptor inference did not converge")


# ----------------------------------------------------- engine adapter


class _FactoredInit:
    """Cross-product initial-state set generated by a mixed-radix
    counting kernel instead of host enumeration (VERDICT r2 #5 /
    SURVEY.md §3.2: the reference's ``ModelProducer=FALSE`` Init draws
    ``(|KeySet|*|ValueSet|)^MessageSentLimit`` sequences — host
    enumeration explodes where counting is free).

    ``factors`` is one entry per state variable, in ``spec.vars``
    order:

    - ``("const", encoded)`` — a single value (``var = expr``);
    - ``("choice", tables, n)`` — ``var \\in S``: pytree with a leading
      ``n`` axis of encoded candidate values;
    - ``("funseq", tables, radices)`` — a filtered function/sequence
      space factored per position: pytree with leading ``[P, R]`` axes
      (position, per-position candidate), plus per-position radices.

    ``gen_initial(idx)`` peels mixed-radix digits off ``idx`` (least
    significant factor first) and gathers each variable's encoded
    value — O(state size), fully traced, no tables of the product.
    """

    def __init__(self, factors, n_initial: int):
        self.factors = factors
        self.n = n_initial

    def gen(self, idx):
        out = {}
        rem = idx
        for var, kind, payload in self.factors:
            if kind == "const":
                out[var] = jax.tree_util.tree_map(
                    jnp.asarray, payload
                )
                continue
            if kind == "choice":
                tables, n = payload
                digit = rem % n
                rem = rem // n
                out[var] = jax.tree_util.tree_map(
                    lambda t: jnp.asarray(t)[digit], tables
                )
                continue
            mk, tables, radices = payload
            digits = []
            for r in radices:
                digits.append(rem % r)
                rem = rem // r
            dvec = jnp.stack(digits)
            pvec = jnp.arange(len(radices), dtype=jnp.int32)
            out[var] = mk(
                jax.tree_util.tree_map(
                    lambda t: jnp.asarray(t)[pvec, dvec], tables
                )
            )
        out[ERR_VAR] = jnp.bool_(False)
        return out


def _factor_init_values(spec: Spec):
    """Recognize a purely conjunctive Init over per-variable draws;
    returns per-variable VALUE factors (one per ``spec.vars`` entry) or
    ``None`` when Init falls outside the factored form (callers then
    host-enumerate, exact as before).

    Handled conjunct shapes (after resolving constant-guarded
    disjunction branches, e.g. the reference's ModelProducer split):

    - ``var = closed_expr`` -> ``("const", value)``
    - ``var \\in closed_set_expr`` -> ``("choice", values)``
    - ``var \\in {f \\in [D -> R] : \\A i \\in D : P(i, f[i])}`` ->
      ``("funseq", per_position_values, dom_len)`` — the filter factors
      per position because ``P`` sees ``f`` only at ``i``, so position
      ``d``'s candidates are ``{r \\in R : P(d, r)}``
    """
    from pulsar_tlaplus_tpu.frontend import interp as I
    from pulsar_tlaplus_tpu.frontend import tla_ast as A

    if hasattr(spec, "_init_factor_cache"):
        return spec._init_factor_cache
    spec._init_factor_cache = None
    # eval_expr resolves spec-level definitions through this module
    # slot; spec.initial_states() used to set it as a side effect, and
    # later compile passes (UNCHANGED resolution) still read it
    I._enum._defs = spec.defs
    d = spec.defs.get("Init")
    if d is None or d.params:
        return None
    genv = spec.genv
    varset = set(spec.vars)

    def closed(node) -> bool:
        return not I._refs_any(node, varset, spec.defs)

    def flatten(node, out):
        """Conjunction flattener; constant-guarded disjunctions resolve
        to their single live branch."""
        if isinstance(node, A.Junction) and node.op == "/\\":
            for it in node.items:
                if not flatten(it, out):
                    return False
            return True
        if isinstance(node, A.BinOp) and node.op == "/\\":
            return flatten(node.lhs, out) and flatten(node.rhs, out)
        if (
            isinstance(node, A.Junction) and node.op == "\\/"
        ) or (isinstance(node, A.BinOp) and node.op == "\\/"):
            items = (
                node.items
                if isinstance(node, A.Junction)
                else (node.lhs, node.rhs)
            )
            live = []
            for br in items:
                sub: list = []
                guards_true = True
                if not flatten(br, sub):
                    return False
                kept = []
                for c in sub:
                    if c[0] == "guard":
                        if not c[1]:
                            guards_true = False
                    else:
                        kept.append(c)
                if guards_true:
                    live.append(kept)
            if len(live) != 1:
                return False  # nondeterministic across branches
            out.extend(live[0])
            return True
        if closed(node):
            try:
                val = I.eval_expr(node, genv)
            except I.EvalError:
                return False
            if not isinstance(val, bool):
                return False
            out.append(("guard", val))
            return True
        # var = expr / var \in expr
        if isinstance(node, A.BinOp) and node.op in ("=", "\\in"):
            lhs = node.lhs
            if (
                isinstance(lhs, A.Name)
                and lhs.name in varset
                and closed(node.rhs)
            ):
                out.append((node.op, lhs.name, node.rhs))
                return True
        return False

    conj: list = []
    if not flatten(d.body, conj):
        return None
    assigned = {}
    for c in conj:
        if c[0] == "guard":
            if not c[1]:
                return None  # Init is unsatisfiable; fall back
            continue
        op, var, rhs = c
        if var in assigned:
            return None
        assigned[var] = (op, rhs)
    if set(assigned) != varset:
        return None

    factors = []
    for var in spec.vars:
        op, rhs = assigned[var]
        if op == "=":
            try:
                factors.append(("const", I.eval_expr(rhs, genv)))
            except I.EvalError:
                return None
            continue
        fact = _factor_membership_values(spec, rhs)
        if fact is None:
            return None
        factors.append(fact)
    spec._init_factor_cache = factors
    return factors


def _init_value_sample(factors):
    """Representative initial states covering every per-slot candidate
    value — sufficient to seed descriptor inference (descriptors are
    per-field value joins, so covering each slot's candidates is as
    informative as the full cross product)."""
    width = 1
    for f in factors:
        if f[0] == "choice":
            width = max(width, len(f[1]))
        elif f[0] == "funseq":
            width = max(width, max(len(p) for p in f[1]))
    states = []
    for j in range(width):
        row = []
        for f in factors:
            if f[0] == "const":
                row.append(f[1])
            elif f[0] == "choice":
                row.append(f[1][min(j, len(f[1]) - 1)])
            else:
                from pulsar_tlaplus_tpu.frontend import interp as I

                per_pos, dom_vals = f[1], f[2]
                picks = [
                    p[min(j, len(p) - 1)] for p in per_pos
                ]
                if list(dom_vals) == list(range(1, len(dom_vals) + 1)):
                    row.append(tuple(picks))
                else:
                    row.append(
                        I.make_fn(dict(zip(dom_vals, picks)))
                    )
        states.append(tuple(row))
    return states


def _fvar_only_indexed(node, fvar: str, ivar: str) -> bool:
    """True iff every occurrence of ``fvar`` in ``node`` is exactly the
    application ``fvar[ivar]`` (and ``fvar``/``ivar`` are never
    shadowed-rebound, conservatively rejected)."""
    from pulsar_tlaplus_tpu.frontend import tla_ast as A
    import dataclasses as _dc

    ok = True

    def walk(n):
        nonlocal ok
        if not ok or not isinstance(n, A.Node):
            return
        if isinstance(n, A.Index):
            if (
                isinstance(n.fn, A.Name)
                and n.fn.name == fvar
            ):
                if not (
                    len(n.args) == 1
                    and isinstance(n.args[0], A.Name)
                    and n.args[0].name == ivar
                ):
                    ok = False
                return
        if isinstance(n, A.Name) and n.name == fvar:
            ok = False
            return
        # conservatively reject rebinding of either name
        for binder_attr in ("var",):
            v = getattr(n, binder_attr, None)
            if v in (fvar, ivar):
                ok = False
                return
        if isinstance(n, (A.Quant,)):
            for v, _dom in n.bindings:
                if v in (fvar, ivar):
                    ok = False
                    return
        for f in _dc.fields(n):
            v = getattr(n, f.name)
            if isinstance(v, A.Node):
                walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, A.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Node):
                                walk(y)

    walk(node)
    return ok


def _factor_membership_values(spec: Spec, rhs):
    """Factor one membership conjunct at the VALUE level; returns
    ``("choice", values)`` or ``("funseq", per_position_values,
    dom_len)`` or None."""
    from pulsar_tlaplus_tpu.frontend import interp as I
    from pulsar_tlaplus_tpu.frontend import tla_ast as A

    genv = spec.genv
    # the pointwise-filtered function space
    if (
        isinstance(rhs, A.SetFilter)
        and isinstance(rhs.domain, A.FnSpace)
        and isinstance(rhs.pred, A.Quant)
        and rhs.pred.kind == "A"
        and len(rhs.pred.bindings) == 1
    ):
        fvar = rhs.var
        ivar, idom = rhs.pred.bindings[0]
        if not _fvar_only_indexed(rhs.pred.body, fvar, ivar):
            # the one-entry-function probe below is only faithful when
            # the predicate sees f exclusively as f[ivar]; DOMAIN f,
            # Len(f), f[other] etc. would silently mis-evaluate
            return None
        try:
            dom_vals = sorted(
                I._enum_set(I.eval_expr(rhs.domain.domain, genv)),
                key=I._sort_key,
            )
            rng_vals = sorted(
                I._enum_set(I.eval_expr(rhs.domain.codomain, genv)),
                key=I._sort_key,
            )
            quant_dom = frozenset(
                I._enum_set(I.eval_expr(idom, genv))
            )
        except I.EvalError:
            return None
        per_pos = []
        try:
            for dv in dom_vals:
                if dv not in quant_dom:
                    per_pos.append(list(rng_vals))
                    continue
                keep = []
                for rv in rng_vals:
                    # P sees f only at f[ivar]: a one-entry function
                    # faithfully evaluates it, and any other access
                    # raises (-> fall back to host enumeration)
                    env = genv.child(
                        {
                            fvar: I.make_fn({dv: rv}),
                            ivar: dv,
                        }
                    )
                    v = I.eval_expr(rhs.pred.body, env)
                    if not isinstance(v, bool):
                        return None
                    if v:
                        keep.append(rv)
                per_pos.append(keep)
        except I.EvalError:
            return None
        if any(not p for p in per_pos):
            return None  # empty position => empty set; fall back
        return ("funseq", per_pos, tuple(dom_vals))
    # a flat closed enumerable set
    try:
        vals = sorted(
            I._enum_set(I.eval_expr(rhs, genv)), key=I._sort_key
        )
    except I.EvalError:
        return None
    if not vals or len(vals) > 1 << 20:
        return None
    return ("choice", vals)


def _try_factor_init(spec: Spec, var_descs) -> Optional[_FactoredInit]:
    """Encode the value factors of :func:`_factor_init_values` into the
    counting-kernel generator; ``None`` when Init does not factor or a
    value falls outside its descriptors (callers host-enumerate)."""
    vfactors = _factor_init_values(spec)
    if vfactors is None:
        return None
    factors = []
    n_total = 1
    try:
        for var, f in zip(spec.vars, vfactors):
            desc = var_descs[var]
            if f[0] == "const":
                factors.append((var, "const", encode_value(desc, f[1])))
                continue
            if f[0] == "choice":
                vals = f[1]
                enc = [encode_value(desc, v) for v in vals]
                tables = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *enc
                )
                factors.append((var, "choice", (tables, len(vals))))
                n_total *= len(vals)
                continue
            enc2 = _encode_funseq(desc, f[1], f[2])
            if enc2 is None:
                return None
            payload, count = enc2
            factors.append((var, "funseq", payload))
            n_total *= count
    except CodegenError:
        return None
    return _FactoredInit(factors, n_total)


def _encode_funseq(desc, per_pos, dom_vals):
    """Encode per-position candidate tables for a factored function or
    sequence draw: pytree with leading [position, candidate] axes (pad
    repeats the last candidate; unreachable digits).  Returns
    ``((mk, stacked, radices), count)`` or None."""
    dom_len = len(dom_vals)
    radices = [len(p) for p in per_pos]
    rmax = max(radices)
    if isinstance(desc, DSeq):
        if (
            desc.cap < dom_len
            or desc.elem is None
            or list(dom_vals) != list(range(1, dom_len + 1))
        ):
            return None
        elem_desc = desc.elem
        mk = lambda full: (np.int32(dom_len), full)  # noqa: E731
    elif isinstance(desc, DFun) and not desc.partial:
        if tuple(desc.keys) != tuple(dom_vals):
            return None
        elem_desc = desc.val
        mk = lambda full: ((), full)  # noqa: E731
    else:
        return None
    rows = [
        [
            encode_value(elem_desc, p[min(j, len(p) - 1)])
            for j in range(rmax)
        ]
        for p in per_pos
    ]
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs),
        *[
            jax.tree_util.tree_map(lambda *ys: np.stack(ys), *row)
            for row in rows
        ],
    )
    # sequences shorter than cap: pad positions to desc.cap with zero
    # elements so the stacked tree matches the codec layout
    if isinstance(desc, DSeq) and desc.cap > dom_len:
        zero = encode_value_zero(elem_desc)
        pad = jax.tree_util.tree_map(
            lambda z: np.broadcast_to(
                np.asarray(z)[None, None],
                (desc.cap - dom_len, rmax) + np.asarray(z).shape,
            ),
            zero,
        )
        stacked = jax.tree_util.tree_map(
            lambda t, pz: np.concatenate([t, pz], axis=0),
            stacked, pad,
        )
        radices = radices + [1] * (desc.cap - dom_len)
    n = 1
    for r in radices:
        n *= r
    return ((mk, stacked, radices), n)


class CompiledSpec:
    """Engine-facing compiled model for an arbitrary spec (the device
    BFS protocol: layout/pack/unpack, gen_initial, successors, fused
    invariants, stutter flag, trace replay).

    Evaluation errors TLC would raise become the hidden ``__err__``
    state bit, surfaced by the auto-invariant ``__EvalError__``."""

    def __init__(self, spec: Spec, invariants: Tuple[str, ...] = ()):
        self.spec = spec
        spec.check_assumes()
        self.var_descs = infer_var_descs(spec)
        self.codec_descs = dict(self.var_descs)
        self.codec_descs[ERR_VAR] = DBool()
        self.layout = DescCodec(self.codec_descs)
        # initial states: a mixed-radix counting kernel when Init is a
        # recognizable cross product of per-variable draws (the
        # reference's ModelProducer=FALSE Init is (K*V)^M states —
        # enumeration explodes where counting is free); otherwise
        # host-enumerated by the interpreter (exact parity) and encoded
        # once into a gatherable device table
        self._factored_init = _try_factor_init(spec, self.var_descs)
        if self._factored_init is not None:
            self.n_initial = self._factored_init.n
            self._init_table = None
        else:
            init_states = spec.initial_states()
            self.n_initial = len(init_states)
            rows = []
            for s in init_states:
                d = {
                    v: encode_value(self.var_descs[v], val)
                    for v, val in zip(spec.vars, s)
                }
                d[ERR_VAR] = np.bool_(False)
                rows.append(d)
            self._init_table = jax.tree_util.tree_map(
                lambda *xs: jnp.asarray(np.stack(xs)), *rows
            )
        # concrete lane structure (fixed by descs; probe with abstract
        # pass to learn labels/count)
        probe = ActionCompiler(spec, primed=True)
        probe.abstract = True
        cenv = CEnv(
            {v: ("cv", CVal(self.var_descs[v], None)) for v in spec.vars}
        )
        lanes = probe.run(spec.defs["Next"].body, cenv)
        self.lane_labels = [ln.label or "Next" for ln in lanes]
        self.A = len(lanes)
        names: List[str] = []
        for lb in self.lane_labels:
            if lb not in names:
                names.append(lb)
        self.action_names = tuple(names)
        self.action_ids = np.asarray(
            [names.index(lb) for lb in self.lane_labels], np.int32
        )
        self.requested_invariants = tuple(invariants)
        self.default_invariants = tuple(invariants) + ("__EvalError__",)
        self._check_compiles()

    # -- model protocol ------------------------------------------------

    def gen_initial(self, idx):
        i = jnp.clip(idx, 0, min(self.n_initial, (1 << 31) - 1) - 1)
        if self._factored_init is not None:
            return self._factored_init.gen(i)
        return jax.tree_util.tree_map(lambda x: x[i], self._init_table)

    def successors(self, state):
        """state dict -> (stacked successor dicts [A, ...], valid [A])."""
        ac = ActionCompiler(self.spec, primed=True)
        cenv = CEnv(
            {
                v: ("cv", CVal(self.var_descs[v], state[v]))
                for v in self.spec.vars
            }
        )
        lanes = ac.run(self.spec.defs["Next"].body, cenv)
        assert len(lanes) == self.A, "lane structure drifted"
        succs, valids = [], []
        for lane in lanes:
            out = {}
            poison = lane.poison
            for v in self.spec.vars:
                cv = lane.assigns[v]
                nv = ac.narrow_to(cv, self.var_descs[v])
                poison = _or(poison, _and_val(lane.valid, nv.poison))
                out[v] = nv.data
            err = jnp.asarray(poison) if poison is not FALSE else jnp.bool_(
                False
            )
            out[ERR_VAR] = jnp.asarray(state[ERR_VAR]) | err
            succs.append(out)
            valids.append(
                jnp.bool_(True) if lane.valid is True else lane.valid
            )
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *succs
        )
        return stacked, jnp.stack(valids)

    def stutter_enabled(self, state):
        # stuttering disjuncts are ordinary lanes here; deadlock checking
        # already sees them through the valid mask
        return jnp.bool_(False)

    @property
    def invariants(self):
        out = {}
        for name in self.requested_invariants:
            out[name] = self._invariant_fn(name)
        out["__EvalError__"] = self._eval_error_fn()
        return out

    def _compile_invariant(self, name: str, state):
        if name not in self.spec.defs:
            raise CodegenError(f"spec defines no invariant {name}")
        body = self.spec.defs[name].body
        c = Compiler(self.spec)
        cenv = CEnv(
            {
                v: ("cv", CVal(self.var_descs[v], state[v]))
                for v in self.spec.vars
            }
        )
        return c.cbool(body, cenv)

    def _invariant_fn(self, name: str):
        if name not in self.spec.defs:
            raise CodegenError(f"spec defines no invariant {name}")

        def fn(state):
            cv = self._compile_invariant(name, state)
            ok = cv.data
            if cv.poison is not FALSE:
                # poison while evaluating the invariant is an evaluation
                # error, not a violation of ``name`` — mask it to "ok"
                # here; ``__EvalError__`` (which re-derives the same
                # poison, CSE'd by XLA inside the fused check) reports
                # it with TLC's evaluation-error message instead
                ok = ok | jnp.asarray(cv.poison)
            return ok

        return fn

    @property
    def liveness_goals(self):
        """Named ``<>(predicate)`` temporal properties compiled to state
        predicate kernels (VERDICT r3 #5: the fragment ``Termination``
        uses, /root/reference/compaction.tla:303-307).  A definition
        qualifies when its body is an eventually-applied state
        predicate; the body compiles through the same pipeline as an
        invariant, so it runs vmapped on device in the liveness
        engine's goal sweep."""
        from pulsar_tlaplus_tpu.frontend import tla_ast as A

        out = {}
        for name, d in self.spec.defs.items():
            body = d.body
            if isinstance(body, A.UnOp) and body.op == "<>":
                out[name] = self._goal_fn(name, body.expr)
        return out

    def _goal_fn(self, name: str, body):
        def fn(state):
            c = Compiler(self.spec)
            cenv = CEnv(
                {
                    v: ("cv", CVal(self.var_descs[v], state[v]))
                    for v in self.spec.vars
                }
            )
            cv = c.cbool(body, cenv)
            ok = cv.data
            if cv.poison is not FALSE:
                # an evaluation error inside the goal body counts as
                # not-goal (TLC would raise; the engine surfaces the
                # __EvalError__ invariant separately)
                ok = ok & ~jnp.asarray(cv.poison)
            return ok

        return fn

    def _eval_error_fn(self):
        """Auto-invariant: no lane reached this state through poisoned
        Init/Next evaluation (the ``ERR_VAR`` bit), and no requested
        invariant's own evaluation poisons on it (TLC raises an
        evaluation error in both cases rather than reporting the
        invariant as violated)."""

        def fn(state):
            bad = jnp.asarray(state[ERR_VAR])
            for name in self.requested_invariants:
                cv = self._compile_invariant(name, state)
                if cv.poison is not FALSE:
                    bad = bad | jnp.asarray(cv.poison)
            return ~bad

        return fn

    def _check_compiles(self):
        """Trace every kernel once on a dummy state (host, abstract
        shapes) so unsupported constructs fail at build time, not mid
        check."""
        dummy = jax.tree_util.tree_map(
            jnp.asarray, self.gen_initial(jnp.int32(0))
        )
        jax.eval_shape(self.successors, dummy)
        for name, fn in self.invariants.items():
            jax.eval_shape(fn, dummy)

    # -- trace rendering / replay -------------------------------------

    @property
    def config_sig(self) -> str:
        """Stable identity of (module, constants binding) for
        checkpoint-compatibility checks (engine/bfs.py E8)."""
        return repr(
            (
                self.spec.module.name,
                sorted(
                    (k, repr(v)) for k, v in self.spec.constants.items()
                ),
            )
        )

    def to_pystate(self, state):
        """Generic model protocol for the host-staged engines
        (engine/core.build_trace, engine/simulate): returns the
        rendered variable mapping, which utils.render prints in TLC
        trace format."""
        return self.render_state(state)

    def decode_state(self, state) -> Dict[str, object]:
        host = jax.tree_util.tree_map(np.asarray, state)
        from pulsar_tlaplus_tpu.frontend.codegen_ir import decode_value

        return {
            v: decode_value(self.var_descs[v], host[v])
            for v in self.spec.vars
        }

    def render_state(self, state) -> Dict[str, str]:
        from pulsar_tlaplus_tpu.engine.interp_check import format_value

        return {
            v: format_value(x) for v, x in self.decode_state(state).items()
        }

    def replay_trace(self, init_idx: int, lanes: List[int]):
        """(rendered states, action names) along a lane chain from the
        ``init_idx``-th initial state (device engine E7 protocol)."""
        step = jax.jit(self.successors)
        s = jax.tree_util.tree_map(
            jnp.asarray, self.gen_initial(jnp.int32(init_idx))
        )
        states = [self.render_state(s)]
        actions = []
        for lane in lanes:
            succ, _valid = step(s)
            s = jax.tree_util.tree_map(lambda x: x[lane], succ)
            states.append(self.render_state(s))
            actions.append(self.lane_labels[lane])
        return states, actions

