"""TLA+ spec front end (SURVEY.md §2.2-E1).

Pipeline: lexer -> parser (column-aware, TLA+ junction lists) -> AST ->
  * generic structural interpreter (host; the universal semantic oracle), and
  * finite-domain type inference -> packed layout -> JAX kernel codegen
    (the TPU path), producing models with the same interface as the
    hand-compiled ones in :mod:`pulsar_tlaplus_tpu.models`.
"""
