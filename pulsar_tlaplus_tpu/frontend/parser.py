"""Column-aware Pratt parser for the TLA+ subset.

TLA+'s conjunction/disjunction *junction lists* are alignment-sensitive:

    /\\ a
    /\\ b

parses as an n-ary conjunction whose items are delimited by the bullet
column — any token at column <= the bullet's column terminates the item.
This is implemented by threading a ``min_col`` through the expression
parser: a token starting at column < ``min_col`` acts like EOF.  A ``/\\``
or ``\\/`` in *prefix* position starts a junction list; in *infix*
position it is the ordinary binary operator.

Precedence follows the TLA+ operator table (Lamport, "Specifying
Systems", table 6); only levels needed by the subset are included.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from pulsar_tlaplus_tpu.frontend import tla_ast as A
from pulsar_tlaplus_tpu.frontend.lexer import (
    EOF,
    IDENT,
    NUMBER,
    OP,
    STRING,
    Token,
    tokenize,
)


class ParseError(ValueError):
    pass


# (left_bp, right_bp) — higher binds tighter. right < left => right-assoc.
_INFIX = {
    "<=>": (2, 3),
    "=>": (2, 2),  # right-assoc
    "\\/": (4, 5),
    "/\\": (6, 7),
    "=": (10, 11),
    "#": (10, 11),
    "<": (10, 11),
    ">": (10, 11),
    "<=": (10, 11),
    ">=": (10, 11),
    "\\leq": (10, 11),
    "\\geq": (10, 11),
    "\\in": (10, 11),
    "\\notin": (10, 11),
    "\\subseteq": (10, 11),
    "\\cup": (16, 17),
    "\\union": (16, 17),
    "\\cap": (16, 17),
    "\\intersect": (16, 17),
    "\\": (16, 17),
    "..": (18, 19),
    "+": (20, 21),
    "-": (20, 21),
    "*": (24, 25),
    "\\div": (24, 25),
    "%": (24, 25),
    "\\o": (26, 27),
}

_QUANT_BODY_BP = 1  # quantifier/CHOOSE bodies extend as far as possible


class Parser:
    def __init__(self, toks: List[Token]):
        self.toks = toks
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != EOF:
            self.i += 1
        return t

    def at(self, value: str, kind: str = OP) -> bool:
        t = self.peek()
        return t.kind == kind and t.value == value

    def expect(self, value: str, kind: str = OP) -> Token:
        t = self.peek()
        if t.kind != kind or t.value != value:
            raise ParseError(f"expected {value!r}, got {t}")
        return self.next()

    def _eof_for(self, min_col: int) -> bool:
        t = self.peek()
        return t.kind == EOF or (t.col < min_col) or t.value in ("====", "----")

    # -- expressions ---------------------------------------------------------

    def parse_expr(self, min_col: int, bp: int = 0) -> A.Node:
        lhs = self.parse_prefix(min_col)
        while True:
            if self._eof_for(min_col):
                return lhs
            t = self.peek()
            # postfix: prime, function application, record access
            if t.kind == OP and t.value == "'":
                self.next()
                lhs = A.Prime(loc=(t.line, t.col), expr=lhs)
                continue
            if t.kind == OP and t.value == "[" and bp <= 28:
                # f[e1, ..., en]
                self.next()
                args = self._expr_list(min_col, "]")
                lhs = A.Index(loc=(t.line, t.col), fn=lhs, args=tuple(args))
                continue
            if t.kind == OP and t.value == ".":
                nxt = self.peek(1)
                if nxt.kind == IDENT:
                    self.next()
                    self.next()
                    lhs = A.Field(
                        loc=(t.line, t.col), expr=lhs, name=nxt.value
                    )
                    continue
            if t.kind == OP and t.value in _INFIX:
                lbp, rbp = _INFIX[t.value]
                if lbp < bp:
                    return lhs
                self.next()
                rhs = self.parse_expr(min_col, rbp)
                lhs = A.BinOp(
                    loc=(t.line, t.col), op=t.value, lhs=lhs, rhs=rhs
                )
                continue
            return lhs

    def _expr_list(self, min_col: int, closer: str) -> List[A.Node]:
        args: List[A.Node] = []
        if not self.at(closer):
            args.append(self.parse_expr(min_col))
            while self.at(","):
                self.next()
                args.append(self.parse_expr(min_col))
        self.expect(closer)
        return args

    def _bindings(self, min_col: int) -> List[Tuple[str, A.Node]]:
        """x \\in S, y \\in T, ...  (also `x, y \\in S` sugar)."""
        out: List[Tuple[str, A.Node]] = []
        while True:
            names = [self.expect_ident()]
            while self.at(","):
                # lookahead: another name followed by \in or ','
                save = self.i
                self.next()
                if self.peek().kind == IDENT and self.peek(1).value in (
                    "\\in",
                    ",",
                ):
                    names.append(self.expect_ident())
                else:
                    self.i = save
                    break
            self.expect("\\in")
            dom = self.parse_expr(min_col, 12)  # tighter than \in level
            for nm in names:
                out.append((nm, dom))
            if self.at(","):
                self.next()
                continue
            return out

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind != IDENT:
            raise ParseError(f"expected identifier, got {t}")
        self.next()
        return t.value

    def parse_prefix(self, min_col: int) -> A.Node:
        t = self.peek()
        if self._eof_for(min_col):
            raise ParseError(f"unexpected end of expression at {t}")
        loc = (t.line, t.col)

        if t.kind == NUMBER:
            self.next()
            return A.Num(loc=loc, value=int(t.value))
        if t.kind == STRING:
            self.next()
            return A.Str(loc=loc, value=t.value)
        if t.kind == IDENT:
            self.next()
            if self.at("("):
                self.next()
                args = self._expr_list(min_col, ")")
                return A.Apply(loc=loc, op=t.value, args=tuple(args))
            return A.Name(loc=loc, name=t.value)

        v = t.value
        if v == "TRUE" or v == "FALSE":
            self.next()
            return A.Bool(loc=loc, value=(v == "TRUE"))
        if v in ("Nat", "Int", "BOOLEAN"):
            self.next()
            return A.Name(loc=loc, name=v)
        if v == "@":
            self.next()
            return A.Name(loc=loc, name="@")
        if v in ("/\\", "\\/"):
            # junction list anchored at this column
            return self._junction(v, t.col)
        if v == "~" or v == "\\lnot" or v == "\\neg":
            self.next()
            return A.UnOp(loc=loc, op="~", expr=self.parse_expr(min_col, 9))
        if v == "-":
            self.next()
            return A.UnOp(loc=loc, op="-", expr=self.parse_expr(min_col, 23))
        if v in ("[]", "<>"):
            self.next()
            # [][A]_v or <>(e)
            if v == "[]" and self.at("["):
                inner = self._box_action(min_col, loc)
                return A.UnOp(loc=loc, op="[]", expr=inner)
            return A.UnOp(
                loc=loc, op=v, expr=self.parse_expr(min_col, 5)
            )
        if v in ("DOMAIN", "SUBSET", "UNION", "UNCHANGED", "ENABLED"):
            self.next()
            # operand = atom + postfix only (application binds tighter than
            # these prefix ops: DOMAIN f[x] == DOMAIN (f[x]) per the TLA+
            # precedence table), so parse at bp 28 — application's gate —
            # which still excludes every infix operator (max lbp 27).
            return A.UnOp(
                loc=loc, op=v, expr=self.parse_expr(min_col, 28)
            )
        if v in ("WF_", "SF_"):
            self.next()
            sub = self.parse_prefix(min_col)
            self.expect("(")
            act = self._expr_list(min_col, ")")
            if len(act) != 1:
                raise ParseError(f"{v}(...) takes one action at {loc}")
            return A.Fairness(
                loc=loc, kind=v[:2], sub=sub, action=act[0]
            )
        if v == "\\A" or v == "\\E":
            self.next()
            binds = self._bindings(min_col)
            self.expect(":")
            body = self.parse_expr(min_col, _QUANT_BODY_BP)
            return A.Quant(
                loc=loc,
                kind="A" if v == "\\A" else "E",
                bindings=tuple(binds),
                body=body,
            )
        if v == "CHOOSE":
            self.next()
            var = self.expect_ident()
            self.expect("\\in")
            dom = self.parse_expr(min_col, 12)
            self.expect(":")
            pred = self.parse_expr(min_col, _QUANT_BODY_BP)
            return A.Choose(loc=loc, var=var, domain=dom, pred=pred)
        if v == "IF":
            self.next()
            cond = self.parse_expr(min_col, _QUANT_BODY_BP + 1)
            self.expect("THEN")
            then = self.parse_expr(min_col, _QUANT_BODY_BP + 1)
            self.expect("ELSE")
            orelse = self.parse_expr(min_col, _QUANT_BODY_BP)
            return A.If(loc=loc, cond=cond, then=then, orelse=orelse)
        if v == "LET":
            self.next()
            defs = []
            while True:
                dt = self.peek()
                name = self.expect_ident()
                params: Tuple[str, ...] = ()
                if self.at("("):
                    self.next()
                    ps = [self.expect_ident()]
                    while self.at(","):
                        self.next()
                        ps.append(self.expect_ident())
                    self.expect(")")
                    params = tuple(ps)
                self.expect("==")
                # LET bodies are delimited by alignment: body tokens sit
                # right of the defined name's column
                body = self.parse_expr(dt.col + 1, 0)
                defs.append((name, params, body))
                if self.peek().kind == IDENT and self.peek(1).value in (
                    "==",
                    "(",
                ):
                    # another LET definition (Name == ... or Name(..) == ...)
                    if self.peek(1).value == "(":
                        # distinguish definition from application: scan to
                        # matching ')' and check for '=='
                        save = self.i
                        self.next()
                        depth = 0
                        isdef = False
                        while True:
                            tk = self.peek()
                            if tk.kind == EOF:
                                break
                            if tk.value == "(":
                                depth += 1
                            elif tk.value == ")":
                                depth -= 1
                                if depth == 0:
                                    isdef = self.peek(1).value == "=="
                                    break
                            self.next()
                        self.i = save
                        if not isdef:
                            break
                    continue
                break
            self.expect("IN")
            body = self.parse_expr(min_col, _QUANT_BODY_BP)
            return A.Let(loc=loc, defs=tuple(defs), body=body)
        if v == "LAMBDA":
            self.next()
            ps = [self.expect_ident()]
            while self.at(","):
                self.next()
                ps.append(self.expect_ident())
            self.expect(":")
            body = self.parse_expr(min_col, _QUANT_BODY_BP)
            return A.Lambda(loc=loc, params=tuple(ps), body=body)
        if v == "(":
            self.next()
            e = self.parse_expr(min_col, 0)
            self.expect(")")
            return e
        if v == "<<":
            self.next()
            items = self._expr_list(min_col, ">>")
            return A.TupleExpr(loc=loc, items=tuple(items))
        if v == "{":
            return self._set_expr(min_col, loc)
        if v == "[":
            return self._bracket_expr(min_col, loc)
        raise ParseError(f"unexpected token {t}")

    def _junction(self, op: str, col: int) -> A.Node:
        """Aligned bullet list of `op` at exactly column `col`."""
        items: List[A.Node] = []
        loc = None
        while True:
            t = self.peek()
            if not (t.kind == OP and t.value == op and t.col == col):
                break
            if loc is None:
                loc = (t.line, t.col)
            self.next()
            items.append(self.parse_expr(col + 1, 0))
        if len(items) == 1:
            return items[0]
        return A.Junction(loc=loc, op=op, items=tuple(items))

    def _box_action(self, min_col: int, loc) -> A.Node:
        """[A]_v following a '[]' token (caller consumed '[]')."""
        self.expect("[")
        act = self.parse_expr(min_col, 0)
        self.expect("]")
        self.expect("_")
        sub = self.parse_prefix(min_col)
        return A.BoxAction(loc=loc, action=act, sub=sub)

    def _set_expr(self, min_col: int, loc) -> A.Node:
        self.expect("{")
        if self.at("}"):
            self.next()
            return A.SetEnum(loc=loc, items=())
        # could be: {e, ...} | {x \in S : p} | {e : x \in S}
        save = self.i
        if self.peek().kind == IDENT and self.peek(1).value == "\\in":
            var = self.expect_ident()
            self.next()  # \in
            dom = self.parse_expr(min_col, 12)
            if self.at(":"):
                self.next()
                pred = self.parse_expr(min_col, 0)
                self.expect("}")
                return A.SetFilter(
                    loc=loc, var=var, domain=dom, pred=pred
                )
            self.i = save  # it was `{x \in S}` as an element? fall through
        first = self.parse_expr(min_col, 0)
        if self.at(":"):
            self.next()
            var = self.expect_ident()
            self.expect("\\in")
            dom = self.parse_expr(min_col, 0)
            self.expect("}")
            return A.SetMap(loc=loc, expr=first, var=var, domain=dom)
        items = [first]
        while self.at(","):
            self.next()
            items.append(self.parse_expr(min_col, 0))
        self.expect("}")
        return A.SetEnum(loc=loc, items=tuple(items))

    def _bracket_expr(self, min_col: int, loc) -> A.Node:
        """[x \\in S |-> e] | [f EXCEPT ...] | [f1 |-> e1,...]
        | [f1: S1, ...] | [S -> T] | [A]_v (action subscript)."""
        self.expect("[")
        # [x \in S |-> e]
        if self.peek().kind == IDENT and self.peek(1).value == "\\in":
            save = self.i
            var = self.expect_ident()
            self.next()
            dom = self.parse_expr(min_col, 12)
            if self.at("|->"):
                self.next()
                body = self.parse_expr(min_col, 0)
                self.expect("]")
                return A.FnConstruct(
                    loc=loc, var=var, domain=dom, body=body
                )
            self.i = save
        # [name |-> e, ...] or [name: S, ...]
        if self.peek().kind == IDENT and self.peek(1).value in ("|->", ":"):
            kind = self.peek(1).value
            fields = []
            while True:
                nm = self.expect_ident()
                self.expect(kind)
                e = self.parse_expr(min_col, 0)
                fields.append((nm, e))
                if self.at(","):
                    self.next()
                    continue
                break
            self.expect("]")
            if kind == "|->":
                return A.RecordLit(loc=loc, fields=tuple(fields))
            return A.RecordSpace(loc=loc, fields=tuple(fields))
        first = self.parse_expr(min_col, 0)
        if self.at("->"):
            self.next()
            cod = self.parse_expr(min_col, 0)
            self.expect("]")
            return A.FnSpace(loc=loc, domain=first, codomain=cod)
        if self.peek().value == "EXCEPT":
            self.next()
            updates = []
            while True:
                self.expect("!")
                self.expect("[")
                idx = self.parse_expr(min_col, 0)
                self.expect("]")
                self.expect("=")
                val = self.parse_expr(min_col, 0)
                updates.append((idx, val))
                if self.at(","):
                    self.next()
                    continue
                break
            self.expect("]")
            return A.FnExcept(loc=loc, fn=first, updates=tuple(updates))
        # action subscript [A]_v
        self.expect("]")
        if self.at("_"):
            self.next()
            sub = self.parse_prefix(min_col)
            return A.BoxAction(loc=loc, action=first, sub=sub)
        raise ParseError(f"cannot parse bracket expression at {loc}")


def parse_module(src: str) -> A.Module:
    toks = tokenize(src)
    p = Parser(toks)
    p.expect("----")
    p.expect("MODULE")
    name = p.expect_ident()
    p.expect("----")
    extends: List[str] = []
    constants: List[str] = []
    variables: List[str] = []
    assumes: List[A.Node] = []
    defs: List[A.Definition] = []
    while True:
        t = p.peek()
        if t.kind == EOF:
            raise ParseError(
                f"module {name} is not terminated by '====' (truncated file?)"
            )
        if t.value == "====":
            break
        if t.value == "----":  # separator line
            p.next()
            continue
        if t.value == "EXTENDS":
            p.next()
            extends.append(p.expect_ident())
            while p.at(","):
                p.next()
                extends.append(p.expect_ident())
            continue
        if t.value in ("CONSTANT", "CONSTANTS"):
            p.next()
            constants.append(p.expect_ident())
            while p.at(","):
                p.next()
                constants.append(p.expect_ident())
            continue
        if t.value in ("VARIABLE", "VARIABLES"):
            p.next()
            variables.append(p.expect_ident())
            while p.at(","):
                p.next()
                variables.append(p.expect_ident())
            continue
        if t.value in ("ASSUME", "ASSUMPTION"):
            p.next()
            assumes.append(p.parse_expr(t.col + 1, 0))
            continue
        if t.value == "THEOREM":
            p.next()
            p.parse_expr(t.col + 1, 0)  # parsed, not checked
            continue
        if t.kind == IDENT:
            loc = (t.line, t.col)
            dname = p.expect_ident()
            params: Tuple[str, ...] = ()
            if p.at("("):
                p.next()
                ps = [p.expect_ident()]
                while p.at(","):
                    p.next()
                    ps.append(p.expect_ident())
                p.expect(")")
                params = tuple(ps)
            p.expect("==")
            body = p.parse_expr(t.col + 1, 0)
            defs.append(
                A.Definition(loc=loc, name=dname, params=params, body=body)
            )
            continue
        raise ParseError(f"unexpected module-level token {t}")
    return A.Module(
        loc=(1, 1),
        name=name,
        extends=tuple(extends),
        constants=tuple(constants),
        variables=tuple(variables),
        assumes=tuple(assumes),
        defs=tuple(defs),
    )


def parse_file(path: str) -> A.Module:
    with open(path) as f:
        return parse_module(f.read())
