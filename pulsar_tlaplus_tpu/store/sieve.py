"""Device-side sieve ops for the tiered store (traced sub-functions).

The sieve principle (arXiv:1208.5542): keys already confirmed visited
must never cross the slow link.  Three device-side ops enforce it —
the engine jits them per capacity tier (the same ``_jits`` cache
discipline as every other tier-keyed program):

- :func:`tag_generation` stamps newly-inserted fpset slots with the
  current eviction epoch at level boundaries, so age is a per-slot
  observable without touching the insert hot path (the megakernel's
  probe loop is unchanged — tagging is one masked ``where`` over the
  table per boundary).
- :func:`extract_cold` selects slots at or below a cutoff epoch,
  compacts their keys densely, SORTS them (so the host-side cold run
  is searchable and delta-compressible without a host sort), and
  clears the slots.  The caller must rehash the survivors afterwards
  (open-addressing probe chains break across holes — device_bfs owns
  that step), and the freshly rebuilt table restarts at epoch 1.
- :func:`sieve_new` packs exactly the lanes the hot filter flagged new
  — the only keys that ever cross to the host for cold-tier miss
  resolution — and :func:`unflag_lanes` merges the resolved verdicts
  back by clearing the false-new lanes BEFORE the compaction/append
  that assigns gids, which is what keeps tiered discovery order
  state-for-state identical to the untiered run.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pulsar_tlaplus_tpu.ops import compact as compact_ops
from pulsar_tlaplus_tpu.ops.dedup import SENTINEL
from pulsar_tlaplus_tpu.ops.fpset import all_sentinel

_BIG_LANE = jnp.int32(2**31 - 1)


def _occupied_full(tcols) -> jax.Array:
    """bool[cap + 1] occupancy with the trash row forced empty."""
    cap = tcols[0].shape[0] - 1
    occ = ~all_sentinel(tcols)
    lane = jnp.arange(cap + 1, dtype=jnp.int32)
    return occ & (lane < cap)


def tag_generation(tcols, gen: jax.Array, epoch) -> jax.Array:
    """Stamp occupied-but-untagged slots with ``epoch`` (int32).  The
    generation column is 0 for empty/untagged slots, so calling this
    once per level boundary gives every key the epoch of the first
    boundary after its insertion — the age signal eviction sorts by."""
    occ = _occupied_full(tcols)
    fresh = occ & (gen == 0)
    return jnp.where(fresh, jnp.int32(epoch), gen)


def extract_cold(
    tcols: Tuple[jax.Array, ...],
    gen: jax.Array,
    cutoff,
    compact_impl: str = "logshift",
    sieve_impl: str = "legacy",
):
    """Select slots with ``1 <= gen <= cutoff``, pack their keys
    densely, sort them, and clear the slots.

    Returns ``(tcols_holed, gen_cleared, ev_cols_sorted, n_evicted)``
    — ``ev_cols_sorted`` are full-table-width columns whose first
    ``n_evicted`` lanes hold the evicted keys in unsigned
    lexicographic column order (SENTINEL padding sorts last).  The
    holed table MUST be rehashed before serving lookups again.

    ``sieve_impl`` selects the extract kernel (round 23): ``legacy``
    is the compact+mask+sort below; ``tile`` / ``pallas`` route to
    ``ops/tiles.py``'s mask-in-place formulation (the sort sees the
    same multiset, so outputs are array-identical)."""
    if sieve_impl != "legacy":
        from pulsar_tlaplus_tpu.ops import tiles  # lazy: avoids cycle

        return tiles.extract_cold_tiles(
            tcols, gen, cutoff, sieve_impl=sieve_impl
        )
    cap1 = tcols[0].shape[0]
    occ = _occupied_full(tcols)
    cold = occ & (gen >= 1) & (gen <= jnp.int32(cutoff))
    n_ev = jnp.sum(cold.astype(jnp.int32))
    drop = (~cold).astype(jnp.uint32)
    packed, _ = compact_ops.compact_by_flag(
        drop, tuple(tcols), impl=compact_impl, need_idx=False
    )
    lane = jnp.arange(cap1, dtype=jnp.int32)
    masked = tuple(
        jnp.where(lane < n_ev, c, SENTINEL) for c in packed
    )
    ev_sorted = lax.sort(masked, num_keys=len(masked), is_stable=False)
    tcols_holed = tuple(
        jnp.where(cold, SENTINEL, c) for c in tcols
    )
    gen_cleared = jnp.where(cold, jnp.int32(0), gen)
    return tcols_holed, gen_cleared, ev_sorted, n_ev


def sieve_new(ak_cols, flag_acc, compact_impl: str = "logshift"):
    """Pack the hot-filter survivors: the accumulator lanes flagged
    new, as dense key columns + their ORIGINAL lane ids.  Returns
    ``(kcols..., lane_ids, n_new)`` — only the ``n_new`` prefix is
    meaningful; these are the only keys that cross the link."""
    nq = ak_cols[0].shape[0]
    lane = jnp.arange(nq, dtype=jnp.uint32)
    drop = flag_acc ^ jnp.uint32(1)
    packed, _ = compact_ops.compact_by_flag(
        drop, tuple(ak_cols) + (lane,), impl=compact_impl,
        need_idx=False,
    )
    n_new = jnp.sum(flag_acc.astype(jnp.int32))
    return (*packed[:-1], packed[-1].astype(jnp.int32), n_new)


def unflag_lanes(flag_acc, lanes, n):
    """Clear ``lanes[:n]`` in the new-state flag vector — the miss
    verdict merge: lanes the cold tiers resolved as already-visited
    stop being new BEFORE the compaction that assigns gids, so tiered
    gid assignment is identical to the untiered run's."""
    p = lanes.shape[0]
    idx = jnp.where(
        jnp.arange(p, dtype=jnp.int32) < n, lanes, _BIG_LANE
    )
    return flag_acc.at[idx].set(jnp.uint32(0), mode="drop")
