"""Host-side tiers: cold key runs, row/log segments, spill manifest.

The :class:`TieredStore` is the engine's "slower memory": evicted
fpset key runs and aged row/log ranges live here — in host RAM always,
and (for checkpointed runs) as compressed files under the run's spill
directory so crash/preempt/daemon-suspend resume restores the WHOLE
tiered store, not just the device-resident window.

Design rules:

- **Synchronous availability, asynchronous durability.**  An evicted
  run is queryable the moment :meth:`evict_keys` returns (the very
  next flush may probe a just-evicted key); the encode + disk write
  runs on a background worker, overlapped with the next level's
  compute, and :meth:`flush` joins it at the next boundary.  The
  overlap is measured: ``blocked_s`` (time boundaries actually waited)
  over ``transfer_s`` (total encode/write work) is the
  ``spill_overlap_ratio`` the bench artifact carries.
- **Batched miss resolution.**  :meth:`lookup_keys` resolves a whole
  sieved batch against every cold run with range-pruned binary
  searches — O(batch * log(run)) per run, no per-key host loops.
- **Crash hygiene** (the round-16 bugfix satellite): spill files are
  written to a per-writer-unique ``<name>.tmp.<pid>.<tid>`` and
  ``os.replace``d into place (the utils/ckpt.py frame discipline), so
  a killed run can never publish a torn file; stale temps are swept at
  startup (:func:`cleanup_stale_spill`), and a FRESH (non-resume) run
  wipes its spill dir outright so dead runs cannot leak unbounded
  host/disk bytes across restarts.
- **Manifest-anchored resume.**  :meth:`manifest` describes every run
  and segment (counts, byte sizes, file names, content digests);
  checkpoint frames embed it, and :meth:`restore` refuses digest
  mismatches — a torn or swapped spill file can never feed a resumed
  run silently-wrong cold verdicts.
- **ENOSPC degrades, never crashes** (r17).  A disk-full on the
  background durable write — real, or the ``enospc@spill:N`` drill —
  latches :attr:`degraded`: the in-RAM tiers stay fully queryable (so
  everything already evicted keeps deduplicating exactly), further
  durable writes stop, and the ENGINE finishes or truncates honestly
  with ``stop_reason="spill_enospc"`` instead of surfacing a raw
  worker crash.  A degraded store refuses :meth:`manifest` — a frame
  must never anchor a resume on spill files that were not written.
"""

from __future__ import annotations

import errno
import hashlib
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from pulsar_tlaplus_tpu.store import compress as codec
from pulsar_tlaplus_tpu.utils import faults

_TMP_MARK = ".tmp."


def _digest(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:16]


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = f"{path}{_TMP_MARK}{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def cleanup_stale_spill(spill_dir: Optional[str]) -> int:
    """Remove stale ``*.tmp.<pid>.<tid>`` spill temps left by a crash
    mid-write (same contract as ``ckpt.cleanup_stale_tmp``).  Returns
    the number of files removed; a missing dir is a no-op."""
    if not spill_dir:
        return 0
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return 0
    removed = 0
    for name in names:
        if _TMP_MARK not in name:
            continue
        try:
            os.remove(os.path.join(spill_dir, name))
            removed += 1
        except OSError:
            pass
    return removed


class SpillStats:
    """Cumulative spill counters (the ``spill`` telemetry payload)."""

    FIELDS = (
        "evictions", "keys_evicted", "rows_evicted", "logs_evicted",
        "bytes_raw", "bytes_comp", "transfer_s", "blocked_s",
        "misses_resolved", "miss_hits", "miss_batches", "lookup_s",
    )

    def __init__(self):
        for f in self.FIELDS:
            setattr(self, f, 0.0 if f.endswith("_s") else 0)

    def as_dict(self) -> Dict[str, object]:
        return {
            f: (
                round(getattr(self, f), 4)
                if f.endswith("_s")
                else int(getattr(self, f))
            )
            for f in self.FIELDS
        }

    @property
    def overlap_ratio(self) -> Optional[float]:
        """Fraction of spill transfer work that overlapped compute
        (1.0 = boundaries never waited on a transfer)."""
        if self.transfer_s <= 0:
            return None
        return round(
            max(0.0, 1.0 - self.blocked_s / self.transfer_s), 4
        )


class TieredStore:
    """Cold tiers for one run: key runs + row/log segments.

    ``durable`` runs (anything with a checkpoint path) persist every
    run/segment to ``spill_dir`` as it is created, so a checkpoint
    frame only needs to embed the manifest.  Non-durable runs keep
    the cold tiers in host RAM only.
    """

    def __init__(
        self,
        ncols: int,
        spill_dir: Optional[str] = None,
        compress: bool = True,
        durable: bool = False,
        miss_batch: int = 1 << 15,
    ):
        if durable and not spill_dir:
            raise ValueError("durable spill needs a spill_dir")
        if miss_batch < 1:
            raise ValueError(f"miss_batch must be >= 1: {miss_batch}")
        self.ncols = int(ncols)
        self.spill_dir = spill_dir
        self.compress = bool(compress)
        self.durable = bool(durable)
        self.miss_batch = int(miss_batch)
        self.stats = SpillStats()
        # cold key runs: [{n, hi, lo, file, digest, raw, comp}]
        self._runs: List[Dict] = []
        # row/log segments: [{lo, hi, arr(s), file(s), digest(s)}]
        self._rows: List[Dict] = []
        self._logs: List[Dict] = []
        self._seq = 0
        self._spill_write_n = 0  # enospc@spill fault-site counter
        # ENOSPC degradation latch (r17): once set, durable writes
        # stop (the in-RAM tiers stay queryable) and manifest() — the
        # resume anchor — refuses to describe the incomplete dir
        self.degraded = False
        self.degraded_error: Optional[str] = None
        self._pending: List[Future] = []
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ptt-spill"
        )
        self._lock = threading.Lock()
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            cleanup_stale_spill(spill_dir)

    # ------------------------------------------------------------ keys

    @property
    def has_cold_keys(self) -> bool:
        return bool(self._runs)

    @property
    def cold_keys(self) -> int:
        return sum(r["n"] for r in self._runs)

    def evict_keys(self, kcols_np) -> int:
        """Ingest one SORTED evicted key run (dense numpy columns from
        the device's ``extract_cold``).  Queryable immediately; encode
        + durable write happen on the background worker."""
        hi, lo = codec.pack_keys(kcols_np)
        n = len(hi)
        if n == 0:
            return 0
        rec: Dict = {
            "kind": "keys", "n": n, "hi": hi, "lo": lo,
            "file": None, "digest": None,
            "raw": hi.nbytes + lo.nbytes, "comp": None,
        }
        self._runs.append(rec)
        self.stats.evictions += 1
        self.stats.keys_evicted += n
        self._submit_encode(
            rec, lambda: codec.encode_key_run(hi, lo, self.compress),
            f"keys_{self._next_seq()}.ptsk",
        )
        return n

    def lookup_keys(self, kcols_np) -> np.ndarray:
        """bool mask over the query batch: True = the key is in SOME
        cold run (a false-new verdict the engine must merge back)."""
        t0 = time.perf_counter()
        qhi, qlo = codec.pack_keys(kcols_np)
        member = np.zeros(qhi.shape, bool)
        for rec in self._runs:
            hi, lo = rec["hi"], rec["lo"]
            if not len(hi):
                continue
            # range pruning: most runs cover disjoint key ranges only
            # probabilistically, but the bounds check is nearly free
            sel = (qhi >= hi[0]) & (qhi <= hi[-1]) & ~member
            if not sel.any():
                continue
            qh = qhi[sel]
            left = np.searchsorted(hi, qh, "left")
            right = np.searchsorted(hi, qh, "right")
            hit = np.zeros(qh.shape, bool)
            simple = right - left == 1
            idx = np.clip(left, 0, len(hi) - 1)
            hit[simple] = lo[idx[simple]] == qlo[sel][simple]
            wide = np.nonzero(right - left > 1)[0]
            for t in wide:  # equal-hi blocks (3-col keys, ~never)
                seg = lo[left[t]: right[t]]
                p = np.searchsorted(seg, qlo[sel][t])
                hit[t] = p < len(seg) and seg[p] == qlo[sel][t]
            member[np.nonzero(sel)[0][hit]] = True
        self.stats.misses_resolved += int(len(qhi))
        self.stats.miss_hits += int(member.sum())
        self.stats.miss_batches += 1
        self.stats.lookup_s += time.perf_counter() - t0
        return member

    # ------------------------------------------------- rows / logs

    def spill_rows(self, gid_lo: int, gid_hi: int, flat_u32) -> None:
        """Store the packed rows of gid range [gid_lo, gid_hi) (flat
        uint32, ``(gid_hi - gid_lo) * W`` words)."""
        if gid_hi <= gid_lo:
            return
        arr = np.ascontiguousarray(flat_u32, np.uint32)
        rec: Dict = {
            "kind": "rows", "lo": int(gid_lo), "hi": int(gid_hi),
            "arr": arr, "file": None, "digest": None,
            "raw": arr.nbytes, "comp": None,
        }
        self._rows.append(rec)
        self.stats.rows_evicted += int(gid_hi - gid_lo)
        self._submit_encode(
            rec, lambda: codec.encode_plane(arr, self.compress),
            f"rows_{gid_lo}_{gid_hi}.ptsr",
        )

    def spill_logs(
        self, gid_lo: int, gid_hi: int, parent, lane
    ) -> None:
        """Store the parent/lane trace-log range [gid_lo, gid_hi)."""
        if gid_hi <= gid_lo:
            return
        par = np.ascontiguousarray(parent, np.int32)
        lan = np.ascontiguousarray(lane, np.int32)
        rec: Dict = {
            "kind": "logs", "lo": int(gid_lo), "hi": int(gid_hi),
            "arrs": (par, lan), "files": None, "digests": None,
            "raw": par.nbytes + lan.nbytes, "comp": None,
        }
        self._logs.append(rec)
        self.stats.logs_evicted += int(gid_hi - gid_lo)
        seq = self._next_seq()

        def encode():
            bp, rp, cp = codec.encode_plane(par, self.compress)
            bl, rl, cl = codec.encode_plane(lan, self.compress)
            return (bp, bl), rp + rl, cp + cl

        self._submit_encode(
            rec, encode,
            (f"parent_{gid_lo}_{gid_hi}.{seq}.ptsr",
             f"lane_{gid_lo}_{gid_hi}.{seq}.ptsr"),
        )

    def _gather(self, segs: List[Dict], lo: int, hi: int, width: int,
                pick) -> np.ndarray:
        """Concatenate segment slices covering [lo, hi) — tier by
        tier, in gid order; raises on gaps (a spilled range the store
        never saw would silently corrupt a sweep/trace)."""
        out = []
        cur = lo
        for rec in sorted(segs, key=lambda r: r["lo"]):
            if rec["hi"] <= cur or rec["lo"] >= hi:
                continue
            if rec["lo"] > cur:
                raise ValueError(
                    f"cold tier gap: [{cur}, {rec['lo']}) missing"
                )
            a, b = cur, min(rec["hi"], hi)
            arr = pick(rec)
            out.append(
                arr[(a - rec["lo"]) * width: (b - rec["lo"]) * width]
            )
            cur = b
            if cur >= hi:
                break
        if cur < hi:
            raise ValueError(f"cold tier gap: [{cur}, {hi}) missing")
        if not out:
            return np.zeros((0,), np.int32)
        return np.concatenate(out)

    def fetch_rows(self, gid_lo: int, gid_hi: int, W: int) -> np.ndarray:
        """Flat uint32 rows for gid range [gid_lo, gid_hi) streamed
        back from the cold segments."""
        if gid_hi <= gid_lo:
            return np.zeros((0,), np.uint32)
        return self._gather(
            self._rows, gid_lo, gid_hi, W, lambda r: r["arr"]
        )

    def fetch_logs(
        self, gid_lo: int, gid_hi: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        if gid_hi <= gid_lo:
            z = np.zeros((0,), np.int32)
            return z, z
        par = self._gather(
            self._logs, gid_lo, gid_hi, 1, lambda r: r["arrs"][0]
        )
        lan = self._gather(
            self._logs, gid_lo, gid_hi, 1, lambda r: r["arrs"][1]
        )
        return par, lan

    @property
    def rows_spilled_hi(self) -> int:
        """One past the highest spilled row gid (0 = nothing spilled);
        spilled row ranges are contiguous from 0 by construction."""
        return max((r["hi"] for r in self._rows), default=0)

    # ------------------------------------------------------ async tier

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def note_transfer(self, seconds: float) -> None:
        """Account engine-side D2H gather time for the spilled data
        (the other half of the transfer beside encode/write).  Under
        the lock: the background encode worker increments the same
        counter, and an unlocked read-modify-write would lose one of
        the two updates."""
        with self._lock:
            self.stats.transfer_s += float(seconds)

    def _submit_encode(self, rec: Dict, encode, names) -> None:
        # the enospc@spill:N drill arms on the SUBMITTING (engine)
        # thread so the firing write is deterministic; the synthetic
        # OSError is raised at the worker's write, where a real
        # disk-full lands
        self._spill_write_n += 1
        inject = "enospc" in faults.poll("spill", self._spill_write_n)
        inject_n = self._spill_write_n

        def job():
            t0 = time.perf_counter()
            blob, raw, comp = encode()
            files = digests = None
            try:
                if self.durable and not self.degraded:
                    if inject:
                        raise faults.enospc_error("spill", inject_n)
                    blobs = (
                        blob if isinstance(blob, tuple) else (blob,)
                    )
                    fnames = (
                        names if isinstance(names, tuple) else (names,)
                    )
                    files, digests = [], []
                    for b, nm in zip(blobs, fnames):
                        _atomic_write(
                            os.path.join(self.spill_dir, nm), b
                        )
                        files.append(nm)
                        digests.append(_digest(b))
            except OSError as e:
                if e.errno != errno.ENOSPC:
                    raise  # only disk-full degrades; the rest is real
                # ENOSPC: keep the run alive — the in-RAM copy stays
                # queryable, durability is gone, the engine finishes
                # honestly (stop_reason="spill_enospc")
                files = digests = None
                with self._lock:
                    self.degraded = True
                    self.degraded_error = f"{e}"
            with self._lock:
                rec["comp"] = comp
                if rec["kind"] == "logs":
                    rec["files"] = files
                    rec["digests"] = digests
                else:
                    rec["file"] = files[0] if files else None
                    rec["digest"] = digests[0] if digests else None
                self.stats.bytes_raw += raw
                self.stats.bytes_comp += comp
                self.stats.transfer_s += time.perf_counter() - t0

        self._pending.append(self._pool.submit(job))

    def flush(self) -> None:
        """Join pending encode/write work (boundary barrier).  Time
        actually spent waiting here is the NON-overlapped share of the
        transfer work — the ``spill_overlap_ratio`` denominator's
        counterpart."""
        if not self._pending:
            return
        t0 = time.perf_counter()
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()  # re-raises a worker failure loudly
        self.stats.blocked_s += time.perf_counter() - t0

    def quiesce(self) -> None:
        """Join + shut down the spill worker while keeping the in-RAM
        tiers fully readable (trace walks and the liveness sweep read
        cold data after the run ends).  Engines call this at run end
        so finished checkers never hold an idle worker thread; a later
        run rebuilds the store."""
        self.flush()
        self._pool.shutdown(wait=True)

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._pool.shutdown(wait=True)

    # ------------------------------------------------ manifest / resume

    def manifest(self) -> Dict[str, object]:
        """JSON-able description of every cold run/segment — embedded
        in checkpoint frames (requires :meth:`flush` first so every
        durable file + digest is final)."""
        self.flush()
        if self.degraded:
            raise ValueError(
                "spill tier degraded (ENOSPC): the spill dir is "
                "incomplete, so no frame may anchor a resume on it "
                f"({self.degraded_error})"
            )
        with self._lock:
            return {
                "spill_v": 1,
                "ncols": self.ncols,
                "compress": self.compress,
                "durable": self.durable,
                "stats": self.stats.as_dict(),
                "key_runs": [
                    {
                        "n": r["n"], "file": r["file"],
                        "digest": r["digest"], "raw": r["raw"],
                        "comp": r["comp"],
                    }
                    for r in self._runs
                ],
                "rows": [
                    {
                        "lo": r["lo"], "hi": r["hi"], "file": r["file"],
                        "digest": r["digest"], "raw": r["raw"],
                        "comp": r["comp"],
                    }
                    for r in self._rows
                ],
                "logs": [
                    {
                        "lo": r["lo"], "hi": r["hi"],
                        "files": r["files"], "digests": r["digests"],
                        "raw": r["raw"], "comp": r["comp"],
                    }
                    for r in self._logs
                ],
            }

    def _read_verified(self, name: str, want_digest: str) -> bytes:
        path = os.path.join(self.spill_dir, name)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise ValueError(
                f"spill file missing/unreadable on resume: {path} ({e})"
            ) from e
        if _digest(blob) != want_digest:
            raise ValueError(
                f"spill file digest mismatch on resume: {path} — "
                "torn or foreign file; the run cannot resume from it"
            )
        return blob

    def restore(self, manifest: Dict) -> None:
        """Rebuild the cold tiers from a frame-embedded manifest (the
        durable files must be under ``spill_dir``).  Digest mismatches
        and gaps raise — never a silently partial cold tier."""
        if not self.spill_dir:
            raise ValueError("restore needs a spill_dir")
        if int(manifest.get("spill_v", 0)) > 1:
            raise ValueError("spill manifest newer than supported")
        self._runs, self._rows, self._logs = [], [], []
        for e in manifest.get("key_runs", []):
            blob = self._read_verified(e["file"], e["digest"])
            hi, lo = codec.decode_key_run(blob)
            self._runs.append(
                {
                    "kind": "keys", "n": int(e["n"]), "hi": hi,
                    "lo": lo, "file": e["file"], "digest": e["digest"],
                    "raw": int(e["raw"]), "comp": int(e["comp"]),
                }
            )
            if len(hi) != int(e["n"]):
                raise ValueError(
                    f"spill run {e['file']}: decoded {len(hi)} keys, "
                    f"manifest says {e['n']}"
                )
        for e in manifest.get("rows", []):
            blob = self._read_verified(e["file"], e["digest"])
            self._rows.append(
                {
                    "kind": "rows", "lo": int(e["lo"]),
                    "hi": int(e["hi"]), "arr": codec.decode_plane(blob),
                    "file": e["file"], "digest": e["digest"],
                    "raw": int(e["raw"]), "comp": int(e["comp"]),
                }
            )
        for e in manifest.get("logs", []):
            bp = self._read_verified(e["files"][0], e["digests"][0])
            bl = self._read_verified(e["files"][1], e["digests"][1])
            self._logs.append(
                {
                    "kind": "logs", "lo": int(e["lo"]),
                    "hi": int(e["hi"]),
                    "arrs": (codec.decode_plane(bp), codec.decode_plane(bl)),
                    "files": e["files"], "digests": e["digests"],
                    "raw": int(e["raw"]), "comp": int(e["comp"]),
                }
            )
        # cumulative stats continue from the frame (the monotone-
        # cumulative telemetry contract survives resume)
        st = manifest.get("stats") or {}
        for f in SpillStats.FIELDS:
            if f in st:
                setattr(
                    self.stats, f,
                    float(st[f]) if f.endswith("_s") else int(st[f]),
                )
        self._seq = len(self._runs) + len(self._rows) + len(self._logs)

    def wipe(self) -> None:
        """Fresh-run hygiene: drop every spill file in the dir (this
        run owns it — a dead prior run must not leak disk bytes) and
        reset the in-memory tiers."""
        self._runs, self._rows, self._logs = [], [], []
        self.stats = SpillStats()
        if not self.spill_dir:
            return
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return
        for name in names:
            if name.endswith((".ptsk", ".ptsr")) or _TMP_MARK in name:
                try:
                    os.remove(os.path.join(self.spill_dir, name))
                except OSError:
                    pass
