"""Cold-tier codecs: delta-encoded sorted key planes, packed payloads.

"Compression and Sieve" (arXiv:1208.5542) splits the slow-link traffic
problem in two: the *sieve* (store/sieve.py) keeps already-confirmed
keys from crossing at all, and the *compressor* here shrinks what must
cross and what must sit in the cold tiers.  Evicted key runs arrive
SORTED (the eviction op sorts them on device — one ``lax.sort``, cheap
where sorts are bandwidth-bound), so the natural encoding is
first-value + deltas: deltas of a sorted 64-bit key plane are small,
heavily repetitive integers that zlib (stdlib — nothing to install)
packs at a fraction of raw width, and the cumulative-sum decode is one
vectorized numpy pass.  Packed row/log payloads compress as raw planes
(their entropy is the state encoding's problem, but zero runs and
field repetition still fold well).

Keys are carried as ``(hi, lo)`` numpy planes: ``hi`` is the first two
uint32 key columns packed into one uint64 and ``lo`` the third column
(all-zero for 2-column exact keys).  Sorting by ``(hi, lo)`` is
exactly the device sort's unsigned lexicographic column order, so a
run decoded on the host binary-searches with ``np.searchsorted``
directly — no re-sort, no host-side canonicalization.

Every blob is self-describing (magic + version + flags) and carries
its element count; ``raw`` vs ``comp`` byte counts feed the ``spill``
telemetry so compression ratios are first-class observables.
"""

from __future__ import annotations

import struct
import zlib
from typing import Tuple

import numpy as np

_KEY_MAGIC = b"PTSK"
_PLANE_MAGIC = b"PTSR"
_VERSION = 1
_F_COMP = 1  # payloads are zlib-compressed

# zlib level 6 is the measured sweet spot for delta planes (level 9
# buys <2% over it at ~3x the CPU); fixed so spill byte counts are
# DETERMINISTIC — the ledger gates spill_bytes_per_state on them
_ZLEVEL = 6


def pack_keys(kcols) -> Tuple[np.ndarray, np.ndarray]:
    """K uint32 key columns -> ``(hi u64, lo u32)`` planes whose
    ``(hi, lo)`` lexicographic order equals the columns' unsigned
    column-major sort order.  K is 2 or 3 (ops/dedup.KeySpec)."""
    cs = [np.asarray(c, np.uint32) for c in kcols]
    if len(cs) not in (2, 3):
        raise ValueError(f"key planes need 2 or 3 columns: {len(cs)}")
    hi = (cs[0].astype(np.uint64) << np.uint64(32)) | cs[1].astype(
        np.uint64
    )
    lo = (
        cs[2].copy()
        if len(cs) == 3
        else np.zeros(hi.shape, np.uint32)
    )
    return hi, lo


def unpack_keys(hi: np.ndarray, lo: np.ndarray, ncols: int):
    """Inverse of :func:`pack_keys` (for tests and re-insertion)."""
    c0 = (hi >> np.uint64(32)).astype(np.uint32)
    c1 = (hi & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    if ncols == 2:
        return (c0, c1)
    return (c0, c1, np.asarray(lo, np.uint32))


def _emit(payload: bytes, compress: bool) -> Tuple[bytes, int]:
    if compress:
        return zlib.compress(payload, _ZLEVEL), _F_COMP
    return payload, 0


def _take(blob: bytes, flags: int) -> bytes:
    return zlib.decompress(blob) if flags & _F_COMP else blob


def encode_key_run(
    hi: np.ndarray, lo: np.ndarray, compress: bool = True
) -> Tuple[bytes, int, int]:
    """Encode one SORTED key run; returns ``(blob, raw_bytes,
    comp_bytes)``.  ``raw_bytes`` is the in-memory plane width (the
    bytes that would cross the link uncompressed), ``comp_bytes`` the
    encoded blob size."""
    hi = np.ascontiguousarray(hi, np.uint64)
    lo = np.ascontiguousarray(lo, np.uint32)
    if hi.shape != lo.shape:
        raise ValueError("hi/lo plane shapes differ")
    n = len(hi)
    if n:
        # first value + deltas: sorted, so deltas are non-negative and
        # small — this is where the compression ratio comes from
        deltas = np.empty((n,), np.uint64)
        deltas[0] = hi[0]
        np.subtract(hi[1:], hi[:-1], out=deltas[1:])
        hp = deltas.tobytes()
    else:
        hp = b""
    lp = lo.tobytes()
    raw = hi.nbytes + lo.nbytes
    h_enc, flags = _emit(hp, compress)
    l_enc, _ = _emit(lp, compress)
    blob = (
        _KEY_MAGIC
        + struct.pack("<BBQQQ", _VERSION, flags, n, len(h_enc), len(l_enc))
        + h_enc
        + l_enc
    )
    return blob, raw, len(blob)


def decode_key_run(blob: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Decode a key-run blob back to the sorted ``(hi, lo)`` planes."""
    if blob[:4] != _KEY_MAGIC:
        raise ValueError("not a key-run blob (bad magic)")
    ver, flags, n, lh, ll = struct.unpack_from("<BBQQQ", blob, 4)
    if ver > _VERSION:
        raise ValueError(f"key-run blob v{ver} newer than supported")
    off = 4 + struct.calcsize("<BBQQQ")
    hp = _take(blob[off: off + lh], flags)
    lp = _take(blob[off + lh: off + lh + ll], flags)
    deltas = np.frombuffer(hp, np.uint64, count=n)
    # wraparound-safe cumulative sum restores the absolute keys
    with np.errstate(over="ignore"):
        hi = np.cumsum(deltas, dtype=np.uint64)
    lo = np.frombuffer(lp, np.uint32, count=n).copy()
    return hi, lo


def encode_plane(
    arr: np.ndarray, compress: bool = True
) -> Tuple[bytes, int, int]:
    """Encode one packed payload plane (rows as flat uint32 words,
    parent/lane logs as int32); returns ``(blob, raw, comp)``."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in (np.dtype(np.uint32), np.dtype(np.int32)):
        raise ValueError(f"plane dtype must be 32-bit: {arr.dtype}")
    kind = b"u" if arr.dtype == np.dtype(np.uint32) else b"i"
    payload = arr.tobytes()
    enc, flags = _emit(payload, compress)
    blob = (
        _PLANE_MAGIC
        + struct.pack("<BBcQQ", _VERSION, flags, kind, arr.size, len(enc))
        + enc
    )
    return blob, arr.nbytes, len(blob)


def decode_plane(blob: bytes) -> np.ndarray:
    if blob[:4] != _PLANE_MAGIC:
        raise ValueError("not a payload-plane blob (bad magic)")
    ver, flags, kind, n, le = struct.unpack_from("<BBcQQ", blob, 4)
    if ver > _VERSION:
        raise ValueError(f"plane blob v{ver} newer than supported")
    off = 4 + struct.calcsize("<BBcQQ")
    payload = _take(blob[off: off + le], flags)
    dt = np.uint32 if kind == b"u" else np.int32
    return np.frombuffer(payload, dt, count=n).copy()
