"""The HBM budget knob: parsing and resolution.

A tiered run is configured with a byte budget for everything the
engine keeps resident on the device — the fpset table (+ its
generation column), the row-store window, the trace-log window, and
the fixed accumulator buffers.  The engine's growth sites consult the
budget instead of growing unboundedly toward ``max_states``: a growth
step that would overflow it triggers an eviction/spill boundary
instead (engine/device_bfs.py), which is what breaks the "visited set
must fit HBM" ceiling.

The knob is testable on the CPU mesh by setting it artificially small
— the spill machinery is backend-independent (host RAM is just
"slower memory than the device buffers" there), so every tier-1 spill
test runs the same code path the real chip does.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Union

ENV_VAR = "PTT_HBM_BUDGET"

_UNITS = {
    "": 1,
    "b": 1,
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_budget(spec: Union[str, int, float]) -> int:
    """``"512M"`` / ``"7.5G"`` / ``"65536"`` -> bytes (int).

    Raises ValueError with the offending token on malformed input; a
    non-positive budget is rejected too (0 would mean "nothing fits",
    which is never what the caller meant — pass ``None`` upstream to
    disable tiering)."""
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        n = int(spec)
        if n <= 0:
            raise ValueError(f"hbm budget must be positive: {spec!r}")
        return n
    m = re.fullmatch(
        r"\s*([0-9]+(?:\.[0-9]+)?)\s*([a-zA-Z]*)\s*", str(spec)
    )
    if not m:
        raise ValueError(
            f"bad hbm budget {spec!r} (want e.g. 512M, 7.5G, 65536)"
        )
    unit = _UNITS.get(m.group(2).lower())
    if unit is None:
        raise ValueError(
            f"bad hbm budget unit {m.group(2)!r} in {spec!r} "
            "(want K/M/G/T)"
        )
    n = int(float(m.group(1)) * unit)
    if n <= 0:
        raise ValueError(f"hbm budget must be positive: {spec!r}")
    return n


def resolve_budget(
    arg: Union[None, str, int, float] = None,
) -> Optional[int]:
    """The effective budget in bytes: an explicit ctor/CLI value wins,
    then the ``PTT_HBM_BUDGET`` env override, else ``None`` (tiering
    off — the pre-r16 all-resident memory contract)."""
    if arg is not None:
        return parse_budget(arg)
    env = os.environ.get(ENV_VAR)
    if env:
        return parse_budget(env)
    return None


def fmt_bytes(n: int) -> str:
    """Human rendering for logs/errors (binary units)."""
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{n} B"
