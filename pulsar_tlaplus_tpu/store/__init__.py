"""Tiered state store (round 16): break the HBM ceiling.

The device engine's visited keys, packed rows, and parent/lane trace
logs historically had to live entirely in HBM, which is what made every
capacity-bounded run die at ``stop_reason: "max_states"``.  This
package is the TLC ``states/`` disk tier reborn for the TPU split
(PAPERS.md, "Compression and Sieve", arXiv:1208.5542):

- :mod:`~pulsar_tlaplus_tpu.store.budget` — the ``--hbm-budget`` /
  ``PTT_HBM_BUDGET`` knob and the byte arithmetic behind it;
- :mod:`~pulsar_tlaplus_tpu.store.sieve` — the device-side ops
  (generation tagging, evict-cold-runs extraction, miss-verdict
  unflagging) that keep confirmed-visited keys from ever crossing the
  slow link;
- :mod:`~pulsar_tlaplus_tpu.store.compress` — delta-encoded sorted key
  planes and packed row payloads for what must cross;
- :mod:`~pulsar_tlaplus_tpu.store.tiers` — the host-side
  :class:`TieredStore`: cold key runs + row/log segments in host RAM
  (and on disk under the run's state dir), async eviction transfers,
  batched miss resolution, and the spill manifest checkpoint frames
  embed.

See docs/memory.md for the full architecture.
"""

from pulsar_tlaplus_tpu.store.budget import (  # noqa: F401
    parse_budget,
    resolve_budget,
)
from pulsar_tlaplus_tpu.store.tiers import (  # noqa: F401
    SpillStats,
    TieredStore,
    cleanup_stale_spill,
)
