"""Device-resident growable FPSet — the hash-table visited set that
retires the flush's visited-width sort-merge (round 6 tentpole).

Why a table, and why now.  The round-5 per-stage split (BASELINE.md)
showed the flush — three full-width sorts of up to 203M keys per
26.7M-candidate accumulator — at ~50% of stage time: a per-candidate
cost that GROWS with the visited set.  An HBM-resident open-addressing
table makes dedup O(batch * E[probes]) independent of how many states
have been visited — the frontier-expansion shape tensor-core BFS work
(BLEST, arxiv 2512.21967; Graph Traversal on Tensor Cores, arxiv
2606.05081) gets its throughput from.  BASELINE.md's own crossover
estimate ("wins once the visited set is >= 2x the 78M-key tier") is the
sizing argument; this module is the `ops/hashtable.py` triangular-
probing design generalised to the device hot path:

- **K key columns** (2 or 3 uint32 words, straight from
  :class:`~pulsar_tlaplus_tpu.ops.dedup.KeySpec`) instead of the fixed
  3+occupancy layout — the all-SENTINEL tuple is the empty marker
  (KeySpec reserves it), so no occupancy column and one fewer scatter
  per insert round.
- **Staged pending compaction.**  The probe loop's dense per-round cost
  is O(nq) random accesses whether one lane is pending or all are; the
  expected MAX probe count over millions of lanes is ~log2(nq) /
  log2(1/load) rounds, so a single monolithic loop pays ~20+ dense
  rounds for a tail that involves a few thousand lanes (this is what
  kept the table off the hot path in rounds 3-5).  ``lookup_or_insert``
  runs a few dense rounds, then compacts the surviving pending lanes
  (one single-key sort, the `compact_by_flag` idiom) into a 4x-smaller
  buffer, probes on, compacts again into a 16x-smaller buffer — the
  tail rounds cost 1/4 and 1/16 of a dense round.  At load <= 1/2 the
  expected pending fraction after r rounds is ~2^-r, so the static
  stage capacities carry 2-8x safety margins; a lane that overflows a
  stage is counted in ``n_failed`` and the engine fails LOUDLY (the
  same fail-stop contract as `ops/hashtable.py`), never a silent drop.
- **Deterministic discovery order.**  Equal-key lanes resolve to the
  minimum lane id (scatter-min bidding; compaction is order-preserving
  and stages bid with original lane ids), which is exactly the
  sort-merge flush's "lowest accumulator slot wins" — the fpset-backed
  engine assigns the SAME gids as the legacy flush, state for state.
- **On-device growth**: :func:`rehash_cols` re-inserts every occupied
  slot of the old table into a double-size table with a `fori_loop` of
  chunked probe rounds — one dispatch, no host staging, and the
  transient is old+new table (far below the retired flush sort's
  3x-visited-width transients).

Load factor is the caller's contract: engines grow before the table
exceeds 1/2 (`ops/hashtable.py`'s regime), which bounds expected probes
per lane at ~2 and makes stage overflow astronomically unlikely.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pulsar_tlaplus_tpu.ops import compact as compact_ops
from pulsar_tlaplus_tpu.ops.dedup import SENTINEL, _fmix

# Width of the zero-sync device metrics vector engines accumulate next
# to the table and ride on their ONE hot-path stats fetch: [flushes,
# probe_rounds, failures, valid_lanes_lo, max_probe_rounds,
# valid_lanes_hi].  valid_lanes is the candidate count after validity
# masking (the duplicate-rate denominator the host cannot know without
# a sync); it is the one counter that genuinely outgrows int32 — a
# 1B-state run examines far more than 2.1G candidate lanes — so it is
# carried as hi/lo uint32 WORDS (r12; lo at the historical index 3,
# the hi carry word appended at index 5 so every older index keeps its
# meaning and pre-widening checkpoint frames restore zero-padded, the
# same pattern as the r8/r9 widenings).  :func:`fpm_update` owns the
# device-side carry arithmetic and :func:`fpm_logical` the host-side
# 64-bit reassembly.  max_probe_rounds is the worst flush's probe
# depth (a running max, not a sum) — with avg probes the
# probe-schedule tuning signal for DENSE_ROUNDS/STAGES below.  Shared
# by device_bfs and sharded_device.
FPM_N = 6

# length of the host-side LOGICAL view: [flushes, probe_rounds,
# failures, valid_lanes (64-bit), max_probe_rounds]
FPM_LOGICAL_N = 5


def fpm_update(fpm, rounds, n_failed, n_valid):
    """One flush's device-side metrics update (jit-traceable).

    ``fpm`` is the int32[FPM_N] vector; ``n_valid`` (int32, < 2^31 per
    flush) accumulates into the valid-lane LO word with uint32 wraparound
    and the carry lands in the HI word — int32 storage holds the uint32
    bit patterns (bitcast, never a value conversion), so 1B-state runs
    report honest duplicate ratios instead of a wrapped counter."""
    lo = lax.bitcast_convert_type(fpm[3], jnp.uint32)
    new_lo = lo + n_valid.astype(jnp.uint32)
    carry = (new_lo < lo).astype(jnp.int32)
    return jnp.stack(
        [
            fpm[0] + 1,
            fpm[1] + rounds,
            fpm[2] + n_failed,
            lax.bitcast_convert_type(new_lo, jnp.int32),
            jnp.maximum(fpm[4], rounds),
            fpm[5] + carry,
        ]
    )


def fpm_logical(vec):
    """int64[FPM_LOGICAL_N] logical view of a fetched fpm vector:
    [flushes, probe_rounds, failures, valid_lanes, max_probe_rounds]
    with the hi/lo valid-lane words reassembled into one 64-bit count.
    Accepts the historical widths too (3-wide pre-r8, 5-wide r9-r11
    frames restore zero-padded): a missing hi word reads as 0 and a
    5-wide vector's index-3 int32 reinterprets as the lo uint32 word —
    identical for every pre-wrap value."""
    import numpy as np

    a = np.asarray(vec, np.int64).reshape(-1)
    v = np.zeros((FPM_N,), np.int64)
    v[: min(len(a), FPM_N)] = a[:FPM_N]
    lo = np.int64(np.uint32(v[3] & 0xFFFFFFFF))
    return np.array(
        [v[0], v[1], v[2], (v[5] << 32) | lo, v[4]], np.int64
    )

# Width of the zero-sync WORK-UNIT vector (r14, fused-era cost
# attribution): the level megakernel accumulates per-stage work units
# inside its ``lax.while_loop`` body and returns them in the packed
# stats vector, so a single fused run carries enough information to
# attribute per-stage cost WITHOUT the ``-fuse stage`` differential
# rerun the r13 fusion destroyed.  Layout: [expand_rows,
# probe_lanes_lo, compact_elems_lo, append_rows, groups,
# probe_lanes_hi, compact_elems_hi].
#
# - ``expand_rows``: live frontier rows fed through expand windows
#   (masked past-frontier window tails are constant-factor overhead the
#   calibration absorbs); per level this sums to the frontier size, so
#   the run total is bounded by max_states + slack and fits int32.
# - ``probe_lanes``: lanes PRESENTED to the fpset flush — the full
#   accumulator width per flush dispatch, because the dense probe cost
#   is O(nq) per round whether a lane is valid or parked (valid-lane
#   counts live in the fpm vector).  Outgrows int32 on 1B-state runs,
#   so it carries hi/lo uint32 words (the r12 ``fpm_update`` pattern).
# - ``compact_elems``: lanes presented to ``compact.compact_rows``
#   (one row-matrix compaction per flush); hi/lo like probe_lanes.
# - ``append_rows``: deduped new states landed by the append body
#   (bounded by max_states — int32 safe).
# - ``groups``: flush-group while-iterations the megakernel ran (the
#   per-batch iteration count; the stage chain's equivalent is its
#   flush dispatch count).
#
# The counters are defined so the fused totals EQUAL the ``-fuse
# stage`` host dispatch-chain counts exactly (the differential parity
# tests pin it): rows = sum of live window rows, lanes/elems =
# accumulator width x flush count, appends = deduped states.
WKM_N = 7

# host-side LOGICAL view: [expand_rows, probe_lanes (64-bit),
# compact_elems (64-bit), append_rows, groups]
WKM_LOGICAL_N = 5


def wkm_update(wkm, rows, lanes, elems, appended, groups):
    """One flush group's device-side work-unit update (jit-traceable,
    called inside the fused megakernel's while body).  ``lanes`` and
    ``elems`` accumulate into uint32 lo words with the carry landing in
    the hi words (bitcast storage, the :func:`fpm_update` pattern) so
    1B-state runs report honest work totals instead of wrapped ones."""
    lo_l = lax.bitcast_convert_type(wkm[1], jnp.uint32)
    new_l = lo_l + lanes.astype(jnp.uint32)
    carry_l = (new_l < lo_l).astype(jnp.int32)
    lo_e = lax.bitcast_convert_type(wkm[2], jnp.uint32)
    new_e = lo_e + elems.astype(jnp.uint32)
    carry_e = (new_e < lo_e).astype(jnp.int32)
    return jnp.stack(
        [
            wkm[0] + rows,
            lax.bitcast_convert_type(new_l, jnp.int32),
            lax.bitcast_convert_type(new_e, jnp.int32),
            wkm[3] + appended,
            wkm[4] + groups,
            wkm[5] + carry_l,
            wkm[6] + carry_e,
        ]
    )


def wkm_logical(vec):
    """int64[WKM_LOGICAL_N] logical view of a fetched work vector:
    [expand_rows, probe_lanes, compact_elems, append_rows, groups]
    with the hi/lo words reassembled into 64-bit counts."""
    import numpy as np

    a = np.asarray(vec, np.int64).reshape(-1)
    v = np.zeros((WKM_N,), np.int64)
    v[: min(len(a), WKM_N)] = a[:WKM_N]
    lanes = (v[5] << 32) | np.int64(np.uint32(v[1] & 0xFFFFFFFF))
    elems = (v[6] << 32) | np.int64(np.uint32(v[2] & 0xFFFFFFFF))
    return np.array([v[0], lanes, elems, v[3], v[4]], np.int64)


MAX_PROBES = 64
# staged-compaction schedule for the engine hot path: a few dense
# rounds, then (shrink divisor, probe-round limit) per stage.  At load
# <= 1/2 the expected pending fraction entering stage i is ~2^-rounds,
# well under 1/divisor (see module docstring).  These are first-guess
# constants — the real-chip tuning signal is the zero-sync
# ``fpset_max_probe_rounds``/``fpset_avg_probe_rounds`` counters
# (docs/observability.md), and the schedule is sweepable without code
# edits: engine/FPSet ctor params, or the ``PTT_FPSET_SCHEDULE`` env
# override parsed by :func:`resolve_schedule` (round 10).
DENSE_ROUNDS = 4
STAGES = ((4, 16), (16, MAX_PROBES))


def parse_schedule(spec: str) -> Tuple[int, Tuple[Tuple[int, int], ...]]:
    """Parse a probe-schedule spec ``"DENSE[,DIV:LIMIT]*"`` — e.g. the
    default is ``"4,4:16,16:64"`` (4 dense rounds, then a 1/4-width
    stage probing to round 16 and a 1/16-width stage to round 64).
    Raises ValueError with the offending token on malformed input."""
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    if not parts:
        raise ValueError(f"empty fpset schedule: {spec!r}")
    try:
        dense = int(parts[0])
    except ValueError:
        raise ValueError(
            f"fpset schedule must start with the dense round count "
            f"(got {parts[0]!r} in {spec!r})"
        ) from None
    stages = []
    for tok in parts[1:]:
        try:
            div_s, limit_s = tok.split(":", 1)
            div, limit = int(div_s), int(limit_s)
        except ValueError:
            raise ValueError(
                f"bad fpset schedule stage {tok!r} (want DIV:LIMIT) "
                f"in {spec!r}"
            ) from None
        if div < 2 or limit < 1:
            raise ValueError(
                f"bad fpset schedule stage {tok!r} (DIV >= 2, "
                f"LIMIT >= 1) in {spec!r}"
            )
        stages.append((div, limit))
    if dense < 1:
        raise ValueError(f"fpset dense rounds must be >= 1: {spec!r}")
    return dense, tuple(stages)


def schedule_hint(dense_rounds, stages) -> str:
    """Remediation hint for a probe-overflow abort.  Under the default
    schedule an overflow means the table broke its load-factor contract
    (the capacity is the lever); under a custom schedule — notably a
    dense-only or LIMIT-truncated sweep via ``PTT_FPSET_SCHEDULE`` —
    the truncated probe budget is the likelier culprit, so name it
    instead of blaming visited_cap."""
    if (int(dense_rounds), tuple(stages)) == (DENSE_ROUNDS, STAGES):
        return (
            "raise visited_cap (the table broke its load-factor "
            "contract)"
        )
    sched = ",".join(
        [str(int(dense_rounds))]
        + [f"{d}:{limit}" for d, limit in stages]
    )
    return (
        f"the active probe schedule '{sched}' (ctor / "
        "PTT_FPSET_SCHEDULE) truncates probing — raise its round "
        "LIMITs, add a stage, or raise visited_cap"
    )


def resolve_schedule(
    dense_rounds: Optional[int] = None, stages=None
) -> Tuple[int, Tuple[Tuple[int, int], ...]]:
    """The effective probe schedule: explicit ctor values win, then the
    ``PTT_FPSET_SCHEDULE`` env override (so a real-chip tuning pass can
    sweep the schedule without code edits), then the module defaults."""
    env = os.environ.get("PTT_FPSET_SCHEDULE")
    env_dense, env_stages = (
        parse_schedule(env) if env else (None, None)
    )
    if dense_rounds is None:
        dense_rounds = env_dense if env_dense is not None else DENSE_ROUNDS
    if stages is None:
        stages = env_stages if env_stages is not None else STAGES
    return int(dense_rounds), tuple(tuple(s) for s in stages)
# stage-capacity floor: the 1/div shrink is a concentration argument
# that only holds for large batches (binomial tail at nq/16 expected
# pending vs nq/4 capacity).  Small batches get the full width — for
# nq below the floor the stages run in place, where overflow is
# impossible and compaction would save nothing anyway.
MIN_STAGE = 1 << 10

_NO_LANE = jnp.int32(2**31 - 1)  # claims fill: above every real lane id


def slot_hash(kcols: Tuple[jax.Array, ...]) -> jax.Array:
    """Mix K key columns into a table-index basis (u32).  Exact keys
    are raw state words with heavily skewed low bits; the fmix chain
    spreads them (identical to ``hashtable._slot_hash`` for K=3, so the
    shim below stays layout-compatible)."""
    h = _fmix(kcols[0] ^ jnp.uint32(0x9E3779B9))
    for c in kcols[1:]:
        h = _fmix(h ^ c)
    return h


def empty_cols(cap: int, ncols: int) -> Tuple[jax.Array, ...]:
    """K SENTINEL-filled uint32 columns of ``cap + 1`` slots for a
    power-of-two ``cap``.  Slot ``cap`` is the write-only trash row
    that parked lanes scatter into (keeps every scatter dense)."""
    if cap & (cap - 1):
        raise ValueError(f"table capacity must be a power of two: {cap}")
    return tuple(
        jnp.full((cap + 1,), SENTINEL, jnp.uint32) for _ in range(ncols)
    )


def occupied_mask(tcols: Tuple[jax.Array, ...]) -> jax.Array:
    """bool[cap] — occupied (non-all-SENTINEL) slots, trash row
    excluded.  Used by rehash and checkpoint extraction."""
    cap = tcols[0].shape[0] - 1
    e = tcols[0][:cap] == SENTINEL
    for c in tcols[1:]:
        e = e & (c[:cap] == SENTINEL)
    return ~e


def all_sentinel(cols) -> jax.Array:
    e = cols[0] == SENTINEL
    for c in cols[1:]:
        e = e & (c == SENTINEL)
    return e


def probe_insert(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    valid: jax.Array,
    occ: Optional[jax.Array] = None,
    max_probes: int = MAX_PROBES,
    start_round: int | jax.Array = 0,
    lane_ids: Optional[jax.Array] = None,
):
    """One batched triangular-probing lookup-or-insert loop.

    Probe round r inspects slot ``(h + r(r+1)/2) & (cap-1)`` (covers
    every slot when cap is a power of two); lanes seeing their key
    resolve as duplicates; lanes seeing an empty slot bid for it with a
    scatter-min of their lane id (the unique winner writes its key, and
    same-key losers resolve against the freshly written slot).

    ``occ`` selects the empty-slot encoding: ``None`` = all-SENTINEL
    key (the engines' layout), else an explicit occupancy column (the
    ``ops.hashtable`` compatibility layout).  ``start_round`` /
    ``lane_ids`` let the staged wrapper resume the probe sequence on a
    compacted buffer while bidding with ORIGINAL lane ids (preserving
    min-lane-wins — the sort-merge flush's discovery order).

    Returns ``(is_new, tcols', occ', pending, rounds)``; ``pending``
    lanes are unresolved after ``max_probes`` rounds (callers count
    them as hard failures, never silent drops).
    """
    cap = tcols[0].shape[0] - 1
    nq = kcols[0].shape[0]
    if lane_ids is None:
        lane_ids = jnp.arange(nq, dtype=jnp.int32)
    h = slot_hash(kcols)
    capm = jnp.uint32(cap - 1)
    has_occ = occ is not None
    occ0 = occ if has_occ else jnp.zeros((0,), jnp.int32)

    def occupied_at(tc, oc, s, sv):
        if has_occ:
            return oc[s] == 1
        return ~all_sentinel(sv)

    def cond(st):
        r, pending = st[0], st[1]
        return (r < max_probes) & jnp.any(pending)

    def body(st):
        r, pending, is_new, tc, oc = st
        ru = r.astype(jnp.uint32)
        off = (ru * (ru + jnp.uint32(1))) >> 1
        slot = ((h + off) & capm).astype(jnp.int32)
        s = jnp.where(pending, slot, cap)  # parked lanes hit the trash row
        sv = tuple(c[s] for c in tc)
        occ_s = occupied_at(tc, oc, s, sv)
        eq = sv[0] == kcols[0]
        for cv, ck in zip(sv[1:], kcols[1:]):
            eq = eq & (cv == ck)
        found = pending & occ_s & eq
        pending = pending & ~found
        # bid for empty slots with the lane id; min wins
        bid = pending & ~occ_s
        bid_slot = jnp.where(bid, s, cap)
        claims = jnp.full((cap + 1,), _NO_LANE, jnp.int32).at[
            bid_slot
        ].min(lane_ids)
        win = bid & (claims[s] == lane_ids)
        ws = jnp.where(win, s, cap)
        tc = tuple(c.at[ws].set(k) for c, k in zip(tc, kcols))
        if has_occ:
            oc = oc.at[ws].set(1)
        is_new = is_new | win
        pending = pending & ~win
        # same-key losers resolve against the newly written slot
        sv2 = tuple(c[s] for c in tc)
        eq2 = sv2[0] == kcols[0]
        for cv, ck in zip(sv2[1:], kcols[1:]):
            eq2 = eq2 & (cv == ck)
        occ2 = occupied_at(tc, oc, s, sv2)
        pending = pending & ~(occ2 & eq2)
        return (r + 1, pending, is_new, tc, oc)

    st = (
        jnp.asarray(start_round, jnp.int32),
        valid,
        jnp.zeros((nq,), jnp.bool_),
        tuple(tcols),
        occ0,
    )
    r, pending, is_new, tcols, occ_out = lax.while_loop(cond, body, st)
    return is_new, tcols, (occ_out if has_occ else None), pending, r


def lookup_or_insert(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    valid: jax.Array,
    max_probes: int = MAX_PROBES,
    dense_rounds: Optional[int] = None,
    stages=None,
    compact_impl: str = "logshift",
):
    """Engine hot path: staged batched lookup-or-insert (see module
    docstring for the why of the stages).

    Returns ``(is_new, tcols', n_failed, rounds)`` where ``is_new`` is
    in ORIGINAL lane order (exactly one True per distinct new key — the
    minimum valid lane), ``n_failed`` counts lanes dropped at a stage
    overflow or still pending at ``max_probes`` (callers treat nonzero
    as a hard error), and ``rounds`` is the probe rounds consumed (the
    per-flush probe metric).
    """
    nq = kcols[0].shape[0]
    K = len(kcols)
    dense_rounds, stages = resolve_schedule(dense_rounds, stages)
    is_new, tcols, _, pending, r = probe_insert(
        tcols, kcols, valid, max_probes=min(dense_rounds, max_probes)
    )
    n_failed = jnp.int32(0)
    cur_keys, cur_ids, cur_pending = kcols, None, pending
    for div, limit in stages:
        limit = min(limit, max_probes)
        capi = max(nq // div, min(nq, MIN_STAGE))
        if capi >= nq or limit <= dense_rounds:
            # no shrink to be had (tiny batches): just probe on in place
            is_new2, tcols, _, cur_pending, r = probe_insert(
                tcols, cur_keys, cur_pending, max_probes=limit,
                start_round=r, lane_ids=cur_ids,
            )
            is_new = _merge_new(is_new, is_new2, cur_ids, nq)
            continue
        # order-preserving compaction of the pending lanes (+ their
        # original lane ids) into the 1/div-size stage buffer —
        # log-shift by default (round 10), sort behind compact_impl
        ids = (
            cur_ids
            if cur_ids is not None
            else jnp.arange(nq, dtype=jnp.int32)
        )
        drop = (~cur_pending).astype(jnp.uint32)
        ccols, _ = compact_ops.compact_by_flag(
            drop, tuple(cur_keys) + (ids.astype(jnp.uint32),),
            impl=compact_impl, need_idx=False,
        )
        npend = jnp.sum(cur_pending.astype(jnp.int32))
        n_failed = n_failed + jnp.maximum(npend - capi, 0)
        cur_keys = tuple(c[:capi] for c in ccols[:K])
        cur_ids = ccols[K][:capi].astype(jnp.int32)
        cur_pending = jnp.arange(capi, dtype=jnp.int32) < npend
        is_new2, tcols, _, cur_pending, r = probe_insert(
            tcols, cur_keys, cur_pending, max_probes=limit,
            start_round=r, lane_ids=cur_ids,
        )
        is_new = _merge_new(is_new, is_new2, cur_ids, nq)
    n_failed = n_failed + jnp.sum(cur_pending.astype(jnp.int32))
    return is_new, tcols, n_failed, r


def _merge_new(is_new, stage_new, stage_ids, nq):
    """Scatter a stage's winner flags back to original lane order
    (only True flags are written — resolved lanes keep their bits)."""
    if stage_ids is None:
        return is_new | stage_new
    tgt = jnp.where(stage_new, stage_ids, nq)
    return is_new.at[tgt].set(True, mode="drop")


def flush_acc(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    n_acc,
    fpm: jax.Array,
    dense_rounds: Optional[int] = None,
    stages=None,
    compact_impl: str = "logshift",
    probe_impl: str = "legacy",
):
    """One accumulator flush as a traced sub-function (round 13): mask
    the live prefix, probe-or-insert, count the new states, and ride
    the metrics vector — ``(tcols', n_new, flag_acc, fpm')`` with
    ``flag_acc`` the uint32 new-state flags in ORIGINAL lane order.

    This is the body the device engine's ``_fpflush_jit`` always ran;
    factoring it here lets the fused level megakernel chain it inside
    one dispatch while the per-stage jit keeps calling the identical
    trace — bit-for-bit the same flush either way.  Lanes past
    ``n_acc`` (a stale tail from a previous fill) and all-SENTINEL
    lanes (masked expand output) are invalid; min-lane-wins keeps the
    sort-merge flush's discovery order.

    ``probe_impl`` selects the probe kernel (round 23): ``legacy`` is
    the staged loop below; ``tile`` / ``pallas`` route to the blocked
    membership-prefilter formulations in ``ops/tiles.py``, which are
    pinned bit-identical on ``is_new`` (discovery order depends only
    on pre-flush membership + min-lane-wins, never slot placement).
    """
    if probe_impl != "legacy":
        from pulsar_tlaplus_tpu.ops import tiles  # lazy: tiles imports us

        return tiles.flush_acc_tiles(
            tcols, kcols, n_acc, fpm,
            dense_rounds=dense_rounds, stages=stages,
            compact_impl=compact_impl, probe_impl=probe_impl,
        )
    nq = kcols[0].shape[0]
    lanei = jnp.arange(nq, dtype=jnp.int32)
    amask = lanei < n_acc
    valid = amask & ~all_sentinel(kcols)
    is_new, tcols2, n_failed, rounds = lookup_or_insert(
        tcols, kcols, valid,
        dense_rounds=dense_rounds, stages=stages,
        compact_impl=compact_impl,
    )
    n_new = jnp.sum(is_new.astype(jnp.int32))
    fpm2 = fpm_update(
        fpm, rounds, n_failed, jnp.sum(valid.astype(jnp.int32))
    )
    return tcols2, n_new, is_new.astype(jnp.uint32), fpm2


def lookup(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    valid: jax.Array,
    max_probes: int = MAX_PROBES,
):
    """Read-only membership probe: bool[nq] (True = key present).
    Lanes resolve on their key (member) or the first empty slot in
    their probe sequence (non-member)."""
    cap = tcols[0].shape[0] - 1
    nq = kcols[0].shape[0]
    h = slot_hash(kcols)
    capm = jnp.uint32(cap - 1)

    def cond(st):
        r, pending = st[0], st[1]
        return (r < max_probes) & jnp.any(pending)

    def body(st):
        r, pending, member = st
        ru = r.astype(jnp.uint32)
        off = (ru * (ru + jnp.uint32(1))) >> 1
        s = jnp.where(
            pending, ((h + off) & capm).astype(jnp.int32), cap
        )
        sv = tuple(c[s] for c in tcols)
        empty = all_sentinel(sv)
        eq = sv[0] == kcols[0]
        for cv, ck in zip(sv[1:], kcols[1:]):
            eq = eq & (cv == ck)
        member = member | (pending & ~empty & eq)
        pending = pending & ~empty & ~eq
        return (r + 1, pending, member)

    _, _, member = lax.while_loop(
        cond, body, (jnp.int32(0), valid, jnp.zeros((nq,), jnp.bool_))
    )
    return member


def rehash_cols(
    old_cols: Tuple[jax.Array, ...],
    new_cols: Tuple[jax.Array, ...],
    chunk: int = 1 << 16,
    max_probes: int = MAX_PROBES,
):
    """Re-insert every occupied slot of ``old_cols`` into the (larger)
    ``new_cols`` — fully on device (one `fori_loop` of chunked probe
    rounds), so it is usable inside jit and shard_map bodies alike.

    Returns ``(new_cols, n_failed)``; the keys are distinct by
    construction and the post-growth load is <= 1/4, so a nonzero
    failure count means the caller's capacity contract was broken
    (fail-stop upstream, like every other capacity violation here).
    """
    ocap = old_cols[0].shape[0] - 1
    chunk = min(chunk, ocap)
    if ocap % chunk:
        raise ValueError("rehash chunk must divide the old capacity")

    def body(i, carry):
        new, failed = carry
        ks = tuple(
            lax.dynamic_slice(c, (i * chunk,), (chunk,))
            for c in old_cols
        )
        occm = ~all_sentinel(ks)
        _new_flags, new, _, pending, _r = probe_insert(
            new, ks, occm, max_probes=max_probes
        )
        return new, failed + jnp.sum(pending.astype(jnp.int32))

    new_cols, n_failed = lax.fori_loop(
        0, ocap // chunk, body, (tuple(new_cols), jnp.int32(0))
    )
    return new_cols, n_failed


class FPSet:
    """Host-side convenience wrapper (tests, probes, host-loop engines):
    owns the column tuple, the entry count, growth, and cumulative
    probe/occupancy/failure metrics.  The device engines inline the
    functional core above in their own jitted programs instead."""

    def __init__(
        self,
        ncols: int,
        cap: int = 1 << 10,
        telemetry=None,
        dense_rounds: Optional[int] = None,
        stages=None,
        compact_impl: str = "logshift",
    ):
        from pulsar_tlaplus_tpu.obs import telemetry as obs

        self.cols = empty_cols(cap, ncols)
        self.ncols = ncols
        self.n = 0
        # probe schedule: ctor params > PTT_FPSET_SCHEDULE > defaults
        # (the real-chip tuning pass sweeps these; the feedback signal
        # is fpset_max_probe_rounds/avg — docs/observability.md)
        self.dense_rounds, self.stages = resolve_schedule(
            dense_rounds, stages
        )
        self.compact_impl = compact_ops.validate_impl(compact_impl)
        self.stats = {"inserts": 0, "probe_rounds": 0, "failures": 0}
        # optional JSONL stream (obs.telemetry): one ``fpset_insert``
        # record per batched insert — host-loop users get the same
        # per-flush visibility the device engines emit
        self.tel = obs.as_telemetry(telemetry)
        self._tel_owned = obs.owns_stream(telemetry)

    def close(self) -> None:
        """Close a telemetry stream this FPSet opened (a caller-passed
        Telemetry instance stays the caller's to close)."""
        if self._tel_owned:
            self.tel.close()

    @property
    def cap(self) -> int:
        return self.cols[0].shape[0] - 1

    @property
    def occupancy(self) -> float:
        return self.n / self.cap

    def reserve(self, n_entries: int):
        """Grow (double + on-device rehash) until ``n_entries`` fit at
        load factor <= 1/2."""
        while 2 * n_entries > self.cap:
            new = empty_cols(self.cap * 2, self.ncols)
            self.cols, failed = rehash_cols(self.cols, new)
            if int(failed):
                raise RuntimeError("fpset rehash overflow")
        return self

    def insert(self, kcols, valid=None):
        """Batched insert; returns the is_new bool vector (lane order).
        Grows first so the load-factor contract always holds."""
        kcols = tuple(jnp.asarray(c, jnp.uint32) for c in kcols)
        nq = kcols[0].shape[0]
        if valid is None:
            valid = jnp.ones((nq,), jnp.bool_)
        self.reserve(self.n + nq)
        is_new, self.cols, n_failed, rounds = lookup_or_insert(
            self.cols, kcols, valid,
            dense_rounds=self.dense_rounds, stages=self.stages,
            compact_impl=self.compact_impl,
        )
        nf = int(n_failed)
        from pulsar_tlaplus_tpu.utils import faults

        if "fpset_fail" in faults.poll(
            "flush", self.stats["inserts"] + 1
        ):
            # injected stage overflow (PTT_FAULT=fpset_fail@flush:N):
            # exercises the fail-stop contract below without needing a
            # genuinely overloaded table
            nf += 1
        self.n += int(jnp.sum(is_new.astype(jnp.int32)))
        self.stats["inserts"] += 1
        self.stats["probe_rounds"] += int(rounds)
        self.stats["failures"] += nf
        self.tel.emit(
            "fpset_insert",
            inserts=self.stats["inserts"],
            probe_rounds=int(rounds),
            failures=nf,
            n=self.n,
            occupancy=round(self.occupancy, 4),
        )
        if nf:
            raise RuntimeError(
                f"fpset probe overflow ({nf} lanes unresolved) — "
                "grow the table before exceeding load factor 1/2"
            )
        return is_new

    def contains(self, kcols, valid=None):
        kcols = tuple(jnp.asarray(c, jnp.uint32) for c in kcols)
        if valid is None:
            valid = jnp.ones((kcols[0].shape[0],), jnp.bool_)
        return lookup(self.cols, kcols, valid)
