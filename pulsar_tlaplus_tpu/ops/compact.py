"""Sort-free stream compaction — the log-shift pass that retires the
chunked single-key sorts on every hot path (round 10 tentpole).

Every engine hot path ends with the same primitive: "move the value
columns whose ``drop`` flag is 0 to the front, preserving original
order" — the append (device + sharded), the fpset's staged
pending-compaction, and the liveness sweep's edge compaction.  Since
round 4 that primitive was ``ops/dedup.compact_by_flag``: chunked
single-key unstable sorts with the row iota embedded in the key.  The
sort was chosen for its COMPILE behavior (a monolithic multi-operand
stable sort compiled 4-5x slower), but its RUN cost is still a sort —
width-linear data movement across O(log^2 n) comparator stages, and at
round-9 bench shapes the append stage it dominates is the largest
stage (17.6 s of ~45 s, BASELINE.md r5 split) now that the flush sort
is gone.

The replacement is prefix-sum stream compaction: one exclusive prefix
sum of the drop flags gives every kept element its destination, and a
sort-free materialization moves the columns.  The materialization is
picked for the backend's memory system at trace time:

- **Accelerators (the TPU hot path): masked doubling shifts** — the
  scan-then-shift frontier compaction of tensor-core BFS frameworks
  (BLEST, arXiv:2512.21967).  ``log2(n)`` passes; bit b of an
  element's remaining shift distance decides whether it rides the
  ``2^b`` shift.  Every pass is a contiguous copy + elementwise select
  per column — the cheapest ops on the TPU memory system (9-30 ns/elem
  contiguous vs 17-50 ns/elem latency-bound random access, BASELINE.md
  environment facts) — and there is no comparator network, so the
  compile is trivial (the round-4 sort-compile blowup is gone too).
- **The CPU backend (the virtual-mesh test/differential tier):
  prefix-sum + branchless-binary-search gather** — XLA:CPU lowers
  sorts AND scatters to serial per-element loops (measured here:
  ~140 ns/elem scatter, ~480 ns/elem 3-operand sort) while its gathers
  vectorize at ~2 ns/elem, so the shift passes' 10-19 full-array
  sweeps lose to one ``log2(n)``-round branchless binary search over
  the inclusive kept-count (the ``dedup.bsearch_member`` idiom) + one
  gather per column.  Same outputs element-for-element; measured
  2-4x faster than the sort path at the 253k-oracle shapes where the
  shifts only break even (the CPU profile is flat — there is no
  contiguous-vs-random asymmetry to exploit).

``PTT_COMPACT_MATERIALIZE=shift|gather`` overrides the choice for
differential measurement of the materializations themselves.

Correctness sketch for the shift passes (the property test hammers
both materializations with random masks): ``delta`` (dropped elements
before position i) is monotone non-decreasing and increases by at most
1 per position, so among KEPT elements the partial positions
``i - (delta_i mod 2^b)`` are strictly increasing before every pass —
two kept elements can never collide, and the element destined for slot
j lands there on its final moving pass and never moves again.  Dropped
elements never move (their remaining distance starts at 0) and slots
vacated without replacement have their distance zeroed, so stale
copies never travel; both are eventually overwritten inside the kept
prefix and are DON'T-CARE beyond it — the same tail contract as the
sort path (callers consume only the ``n_kept`` prefix; the
differential tests pin the prefix element-for-element against the
sort).

``compact_by_flag`` below is the dispatcher: ``impl="logshift"`` (the
default everywhere since round 10) or ``impl="sort"`` — the round-4
chunked sort kept bit-for-bit for differential timing, mirroring the
round-6 ``-visited sort`` pattern.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.ops import dedup

IMPLS = ("logshift", "sort")


def validate_impl(impl: str) -> str:
    """The one ``compact_impl`` membership check — every ctor and the
    dispatcher route through here so a new impl is a one-line change."""
    if impl not in IMPLS:
        raise ValueError(
            f"compact_impl must be {'|'.join(IMPLS)}: {impl}"
        )
    return impl


def _materialization() -> str:
    """Trace-time materialization choice (see module docstring)."""
    env = os.environ.get("PTT_COMPACT_MATERIALIZE")
    if env in ("shift", "gather"):
        return env
    if env:
        raise ValueError(
            f"PTT_COMPACT_MATERIALIZE must be shift|gather: {env!r}"
        )
    return "gather" if jax.default_backend() == "cpu" else "shift"


def _shifted(x: jax.Array, d: int) -> jax.Array:
    """``x`` shifted left by ``d``: out[i] = x[i + d], zero-padded."""
    return jnp.concatenate([x[d:], jnp.zeros((d,), x.dtype)])


def _shift_compact(drop, vals):
    """Masked doubling-shift materialization (the TPU path): move every
    kept element left by its drop-prefix-sum distance, one bit of the
    distance per pass — contiguous copies and selects only."""
    n = drop.shape[0]
    keep = drop == 0
    # delta[i] = dropped elements strictly before i = how far a kept
    # element at i must move left; exclusive prefix sum of the flags
    drop_u = drop.astype(jnp.uint32)
    delta = jnp.cumsum(drop_u) - drop_u
    # remaining shift distance, travelling WITH each element.  Dropped
    # elements get 0 so they never ride a shift (a dropped element
    # pulled over a kept one was the classic corruption mode).
    rem = jnp.where(keep, delta, jnp.uint32(0))
    d = 1
    while d < n:
        du = jnp.uint32(d)
        rem_s = _shifted(rem, d)
        # pull from i+d when THAT element's remaining distance has this
        # bit set; stale/dropped slots have rem 0 and are never pulled
        take = (rem_s & du) != 0
        vals = [jnp.where(take, _shifted(v, d), v) for v in vals]
        # a slot whose occupant left with nothing arriving holds a
        # stale copy: zero its distance so it can never move again
        rem_keep = jnp.where((rem & du) != 0, jnp.uint32(0), rem)
        rem = jnp.where(take, rem_s - du, rem_keep)
        d <<= 1
    return vals


def _gather_compact(drop, vals):
    """Prefix-sum + branchless-binary-search gather materialization
    (the CPU path): ``src[j]`` = the j-th kept original index, found by
    an unrolled binary search over the inclusive kept-count vector
    (``dedup.bsearch_member``'s idiom), then one vectorized gather per
    column.  Positions past the kept count gather garbage — the shared
    tail contract."""
    n = drop.shape[0]
    kc = jnp.cumsum((drop == 0).astype(jnp.int32))
    tgt = jnp.arange(1, n + 1, dtype=jnp.int32)
    lo = jnp.zeros((n,), jnp.int32)
    hi = jnp.full((n,), n, jnp.int32)
    for _ in range(max(1, n.bit_length())):
        mid = (lo + hi) >> 1
        less = kc[mid] < tgt
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
    src = jnp.clip(lo, 0, n - 1)
    return [v[src] for v in vals], src


def logshift_compact(
    drop: jax.Array, cols, need_idx: bool = True
) -> Tuple[tuple, Optional[jax.Array]]:
    """Sort-free stable compaction of ``cols`` to the front where
    ``drop == 0`` (module docstring; materialization is
    backend-adaptive at trace time).

    Same contract as :func:`ops.dedup.compact_by_flag`: the kept prefix
    is in original order; positions past the kept count are don't-care.
    ``idx[j]`` is the original row of compacted position ``j`` (valid
    in the kept prefix); pass ``need_idx=False`` to skip carrying the
    index column when the caller discards it.
    """
    n = drop.shape[0]
    vals = list(cols)
    if _materialization() == "gather":
        # the search's src vector IS the original-index map — idx
        # rides for free, no extra column travels
        out, src = _gather_compact(drop, vals)
        return tuple(out), (src if need_idx else None)
    if need_idx:
        vals.append(jnp.arange(n, dtype=jnp.uint32))
    out = _shift_compact(drop, vals)
    idx = None
    if need_idx:
        idx = out[-1].astype(jnp.int32)
        out = out[:-1]
    return tuple(out), idx


def compact_rows(
    arows: jax.Array, flag_keep: jax.Array, impl: str = "logshift"
) -> Tuple[jax.Array, jax.Array]:
    """Compact a word-major ``[W, N]`` packed-row matrix to the front
    where ``flag_keep`` (uint32 0/1) is set, preserving original order
    — the device append's stream-compaction step, shared as a traced
    sub-function by the per-stage ``_compact_jit`` and the fused level
    megakernel (round 13).  Returns ``(compacted [W, N], idx)`` where
    ``idx[j]`` is the original lane of compacted position ``j``."""
    drop = flag_keep ^ jnp.uint32(1)
    cols = tuple(arows[j] for j in range(arows.shape[0]))
    ccols, idx = compact_by_flag(drop, cols, impl=impl)
    return jnp.stack(ccols), idx


def compact_by_flag(
    drop: jax.Array,
    cols,
    impl: str = "logshift",
    chunk: int = 5,
    need_idx: bool = True,
):
    """Dispatch stream compaction: ``"logshift"`` (default — the
    sort-free kernel above) or ``"sort"`` (the round-4 chunked
    single-key sorts, kept verbatim in ``ops/dedup.py`` for
    differential timing).  Returns ``(compacted cols, idx)`` with
    identical kept-prefix semantics either way."""
    if validate_impl(impl) == "sort":
        return dedup.compact_by_flag(drop, cols, chunk=chunk)
    return logshift_compact(drop, cols, need_idx=need_idx)
