"""Dense-tile kernel layer (round 23 tentpole): blocked / Pallas
formulations of the three hottest device kernels, selected per shape by
the autotuner — never hardcoded.

The r14 work counters say probe lanes and expand rows dominate cost at
every calibrated shape, and the r13 megakernel fused the *dispatches*
without touching the *kernel shapes*: the fpset probe is a per-round
triangular-probe gather chain, the expand sweep a `lax.scan` of chunked
vmaps, the sieve's extract an order-preserving compaction feeding a
sort.  BLEST (arXiv:2512.21967) and Graph Traversal on Tensor Cores
(arXiv:2606.05081) recast exactly these shapes as dense tile ops picked
by a cost model; this module is that layer for our three kernels.  Each
kernel ships two variants behind one constructor knob:

- ``tile`` — a pure-XLA blocked formulation (reshaped ``(TILE_R,
  TILE_L)`` planes that vectorize on the CPU mesh and lower to
  MXU/VPU tiles on TPU);
- ``pallas`` — the same blocking as an explicit
  ``jax.experimental.pallas`` kernel (``interpret=True`` on the CPU
  backend, real lowering on the chip; chip-only native lowering is
  skip-gated by ``tests/helpers.needs_pallas_tpu``).

**(1) Tiled probe** (``probe_impl``).  The legacy flush interleaves
membership resolution and insertion: every dense round gathers K slot
columns, scatter-min-bids for empty slots, scatter-writes winners, and
re-gathers — O(nq + cap) scatter traffic per round whether a lane is a
duplicate or not.  The tile probe splits the two concerns:

- a **blocked membership prefilter**: ``TILE_R`` probe rounds of the
  triangular sequence evaluated at once as a ``(TILE_R, TILE_L)`` key
  plane x slot tile comparison — gather-only, no claims buffer, no
  scatter.  Membership is EXACT for every resolved lane: a key present
  in a triangular-probed table is always found before the first empty
  slot of its probe sequence (inserts claim the then-first empty slot
  and the flush path never holes the table mid-run), so "saw my key
  before an empty slot" = member, "saw an empty slot first" =
  definitely new.
- a **width-proportional insert tail**: the surviving lanes (new keys
  + the rare unresolved tail) compact order-preservingly — original
  lane ids ride along — into ``ceil(npend / CW)`` chunks of width
  ``CW = max(nq/4, MIN_STAGE)`` that run the UNCHANGED legacy
  ``probe_insert`` loop sequentially.  Chunk order is lane order and
  the bidding uses original lane ids, so equal-key resolution is
  min-lane-wins exactly as the legacy flush: a later chunk's equal key
  finds the earlier chunk's insert as a member.  ``is_new`` is
  therefore bit-identical to the legacy path — discovery order is a
  function of (pre-flush table membership, batch keys, min-lane-wins),
  never of slot placement or probe scheduling.

The dynamic chunk count makes the insert cost proportional to the
actual new-key count (duplicate-heavy steady-state flushes run ONE
narrow chunk) while an all-new ramp flush degrades gracefully to
legacy-equivalent width.  Probe-round metrics (``fpm``) count the
prefilter block plus the chunk rounds — the schedule differs from the
legacy path by design and is NOT part of the pinned parity surface
(the work counters are: lanes presented per flush are identical).

**(2) Tiled expand** (``expand_impl``).  The engine's legacy expand is
a ``lax.scan`` over ``G/Fi`` chunks of vmapped successor evaluation.
The tile variant evaluates the whole ``(G, A)`` successor matrix as
one batched tile op and forms the key plane on the full ``(G*A, W)``
matrix in one shot (:func:`key_plane`) — per-lane math is identical
elementwise, so gids, rows, and logs are bit-identical; what changes
is the compiled structure (no scan carry, one fused key-plane
materialization).  The ``pallas`` variant moves the key-plane kernel
(fmix/murmur mixing + validity masking) into an explicit Pallas tile
kernel; the successor functions themselves are arbitrary traced JAX
from the model and stay in XLA — that boundary is the honest one, and
it is the key plane the r14 counters bill per lane anyway.  (The
successor-sweep blocking itself lives in
``engine/device_bfs._expand_body`` where the model closure is; this
module owns the engine-independent tile kernels.)

**(3) Tiled sieve** (``sieve_impl``).  The legacy
``store/sieve.extract_cold`` compacts the cold keys densely, masks the
tail, and sorts.  The tile variant observes the compaction is
redundant work before a sort: masking non-cold lanes to SENTINEL *in
place* (one elementwise tile pass over the table planes) feeds the
same ``lax.sort`` the identical multiset — cold table keys are
distinct and SENTINEL padding sorts last, so the sorted output is
ARRAY-identical while the gather-heavy compact disappears.  The
``pallas`` variant runs the masking plane (cold select, table holing,
generation clear) as one elementwise Pallas kernel over slot tiles.

Every impl preserves discovery order state-for-state (pinned by
``tests/test_tiles.py``: randomized-shape parity properties, the
producer_on rows/parent/lane differentials, and both published bug
oracles under every ``*_impl``).  The winner per shape is arbitrated
by ``cli.py tune`` — the knobs register in ``tune/space.py`` and are
priced by ``tune/predict.py`` at calibrated per-impl lane costs.
Measured CPU-mesh verdicts per kernel: BASELINE.md Round 23.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pulsar_tlaplus_tpu.ops import compact as compact_ops
from pulsar_tlaplus_tpu.ops import fpset
from pulsar_tlaplus_tpu.ops.dedup import SENTINEL

# SENTINEL as a numpy scalar for use INSIDE Pallas kernel bodies —
# the jnp scalar would be captured by the kernel trace (which
# pallas_call rejects) and a bare Python int overflows the weak-int32
# promotion; a numpy scalar embeds as a plain jaxpr literal
_SENT = np.uint32(0xFFFFFFFF)

# probe rounds resolved per blocked membership pass (the prefilter's
# key-plane height; >= the default dense schedule so steady-state
# flushes resolve in one block)
TILE_R = 8
# lane-tile width for the blocked membership pass — bounds the
# (TILE_R, TILE_L) intermediate planes so a bench-width accumulator
# never materializes an (R, 26M) gather (the r5 relayout lesson)
TILE_L = 1 << 16
# lane-tile width for the Pallas kernels (one grid program per tile;
# sized for VPU-friendly blocks without interpret-mode overhead
# dominating at test shapes)
PALLAS_TILE = 4096

IMPLS = ("legacy", "tile", "pallas")


def validate_impl(knob: str, impl: Optional[str]) -> str:
    """Normalize/validate one ``*_impl`` knob value (``None`` = the
    engine default ``legacy``)."""
    impl = impl or "legacy"
    if impl not in IMPLS:
        raise ValueError(
            f"{knob} must be one of {'|'.join(IMPLS)}: {impl}"
        )
    return impl


@lru_cache(maxsize=1)
def pallas_available() -> bool:
    """Whether ``jax.experimental.pallas`` imports at all (it does on
    the container's jax 0.4.37; guarded so a stripped-down jax build
    degrades to the pure-XLA tile path instead of an ImportError)."""
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — any import failure = absent
        return False


@lru_cache(maxsize=1)
def pallas_lowers_natively() -> bool:
    """Whether Pallas lowers for the CURRENT backend without the
    interpreter (True on real TPU/GPU lowering paths, False on the CPU
    mesh of jax 0.4.37).  The kernels below pass
    ``interpret=not pallas_lowers_natively()`` so the same code runs
    everywhere; chip-only native tests skip-gate on this probe
    (``tests/helpers.needs_pallas_tpu``)."""
    if not pallas_available():
        return False
    try:
        from jax.experimental import pallas as pl

        def _k(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1

        x = jnp.zeros((8,), jnp.int32)
        jax.jit(
            lambda v: pl.pallas_call(
                _k,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(v)
        )(x).block_until_ready()
        return True
    except Exception:  # noqa: BLE001 — no native lowering here
        return False


def _interpret() -> bool:
    return not pallas_lowers_natively()


# ------------------------------------------------------------- probe


def _triangular_offsets(rounds: int) -> jax.Array:
    # weak Python literals only: this also traces inside Pallas
    # kernels, where jnp scalar constants would be captured
    r = jnp.arange(rounds, dtype=jnp.uint32)
    return (r * (r + 1)) >> 1


def _member_plane(tcols, kcols, h, rounds: int):
    """The (rounds, n) blocked membership plane for one lane tile:
    gather the triangular probe sequence of every lane AT ONCE and
    reduce first-match vs first-empty.  Returns ``(member,
    resolved)`` bool[n] — both exact where ``resolved``."""
    cap = tcols[0].shape[0] - 1
    capm = jnp.uint32(cap - 1)
    off = _triangular_offsets(rounds)  # (R,)
    slots = ((h[None, :] + off[:, None]) & capm).astype(jnp.int32)
    sv = tuple(c[slots] for c in tcols)  # K gathers of (R, n)
    empty = sv[0] == SENTINEL
    for c in sv[1:]:
        empty = empty & (c == SENTINEL)
    eq = sv[0] == kcols[0][None, :]
    for cv, ck in zip(sv[1:], kcols[1:]):
        eq = eq & (cv == ck[None, :])
    match = eq & ~empty
    ri = jnp.arange(rounds, dtype=jnp.int32)[:, None]
    big = jnp.int32(rounds)
    first_match = jnp.min(jnp.where(match, ri, big), axis=0)
    first_empty = jnp.min(jnp.where(empty, ri, big), axis=0)
    member = first_match < first_empty
    resolved = member | (first_empty < big)
    return member, resolved


def member_block(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    valid: jax.Array,
    rounds: int = TILE_R,
):
    """Pure-XLA blocked membership prefilter over the whole batch,
    lane-tiled at :data:`TILE_L` so the (rounds, tile) intermediates
    stay small.  Returns ``(member, resolved)`` bool[nq], both masked
    by ``valid`` (invalid lanes read as resolved non-members)."""
    nq = kcols[0].shape[0]
    h = fpset.slot_hash(kcols)
    if nq <= TILE_L:
        member, resolved = _member_plane(tcols, kcols, h, rounds)
        return member & valid, resolved | ~valid
    lt = TILE_L
    ntiles = -(-nq // lt)
    pad = ntiles * lt - nq
    if pad:
        h = jnp.pad(h, (0, pad))
        kcols = tuple(
            jnp.pad(c, (0, pad), constant_values=SENTINEL)
            for c in kcols
        )

    def body(i, st):
        member, resolved = st
        base = i * lt
        kk = tuple(
            lax.dynamic_slice(c, (base,), (lt,)) for c in kcols
        )
        hh = lax.dynamic_slice(h, (base,), (lt,))
        m, r = _member_plane(tcols, kk, hh, rounds)
        member = lax.dynamic_update_slice(member, m, (base,))
        resolved = lax.dynamic_update_slice(resolved, r, (base,))
        return member, resolved

    member, resolved = lax.fori_loop(
        0, ntiles,
        body,
        (
            jnp.zeros((ntiles * lt,), jnp.bool_),
            jnp.zeros((ntiles * lt,), jnp.bool_),
        ),
    )
    member, resolved = member[:nq], resolved[:nq]
    return member & valid, resolved | ~valid


def member_block_pallas(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    valid: jax.Array,
    rounds: int = TILE_R,
):
    """The membership prefilter as an explicit Pallas kernel: one grid
    program per :data:`PALLAS_TILE` lane tile, the table planes passed
    whole (the kernel gathers its (rounds, tile) slot tile from them —
    interpret-mode on the CPU mesh; on-chip lowering keeps the table
    in HBM and the key tiles in VMEM).  Same contract as
    :func:`member_block`."""
    from jax.experimental import pallas as pl

    nq = kcols[0].shape[0]
    K = len(kcols)
    h = fpset.slot_hash(kcols)
    lt = min(PALLAS_TILE, nq)
    ntiles = -(-nq // lt)
    pad = ntiles * lt - nq
    if pad:
        h = jnp.pad(h, (0, pad))
        kcols = tuple(
            jnp.pad(c, (0, pad), constant_values=SENTINEL)
            for c in kcols
        )
    cap = tcols[0].shape[0] - 1

    def kernel(*refs):
        trefs = refs[:K]
        krefs = refs[K: 2 * K]
        h_ref = refs[2 * K]
        m_ref, r_ref = refs[2 * K + 1], refs[2 * K + 2]
        off = _triangular_offsets(rounds)
        hh = h_ref[...]
        # weak Python literals only — jnp scalar constants would be
        # captured by the kernel trace, which pallas_call rejects
        slots = ((hh[None, :] + off[:, None]) & (cap - 1)).astype(
            jnp.int32
        )
        sv = tuple(t[slots] for t in trefs)
        empty = sv[0] == _SENT
        for c in sv[1:]:
            empty = empty & (c == _SENT)
        eq = sv[0] == krefs[0][...][None, :]
        for cv, kr in zip(sv[1:], krefs[1:]):
            eq = eq & (cv == kr[...][None, :])
        match = eq & ~empty
        ri = jnp.arange(rounds, dtype=jnp.int32)[:, None]
        fm = jnp.min(jnp.where(match, ri, rounds), axis=0)
        fe = jnp.min(jnp.where(empty, ri, rounds), axis=0)
        m_ref[...] = fm < fe
        r_ref[...] = (fm < fe) | (fe < rounds)

    whole = lambda i: (0,)  # noqa: E731 — table planes unblocked
    member, resolved = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((ntiles * lt,), jnp.bool_),
            jax.ShapeDtypeStruct((ntiles * lt,), jnp.bool_),
        ),
        grid=(ntiles,),
        in_specs=(
            [pl.BlockSpec(tcols[0].shape, whole) for _ in range(K)]
            + [pl.BlockSpec((lt,), lambda i: (i,)) for _ in range(K)]
            + [pl.BlockSpec((lt,), lambda i: (i,))]
        ),
        out_specs=(
            pl.BlockSpec((lt,), lambda i: (i,)),
            pl.BlockSpec((lt,), lambda i: (i,)),
        ),
        interpret=_interpret(),
    )(*tcols, *kcols, h)
    member, resolved = member[:nq], resolved[:nq]
    return member & valid, resolved | ~valid


def flush_acc_tiles(
    tcols: Tuple[jax.Array, ...],
    kcols: Tuple[jax.Array, ...],
    n_acc,
    fpm: jax.Array,
    dense_rounds: Optional[int] = None,
    stages=None,
    compact_impl: str = "logshift",
    probe_impl: str = "tile",
):
    """The tiled accumulator flush — drop-in for
    :func:`ops.fpset.flush_acc` with IDENTICAL ``(tcols', n_new,
    flag_acc, fpm')`` semantics and bit-identical ``is_new`` (see the
    module docstring's exactness argument).  ``probe_impl`` selects
    the membership kernel (``tile`` pure-XLA blocked / ``pallas``)."""
    nq = kcols[0].shape[0]
    K = len(kcols)
    dense_rounds, stages = fpset.resolve_schedule(dense_rounds, stages)
    rounds_blk = max(TILE_R, int(dense_rounds))
    # the insert tail inherits the legacy schedule's total budget
    max_probes = max(
        [int(dense_rounds)] + [int(lim) for _, lim in stages]
    )
    lanei = jnp.arange(nq, dtype=jnp.int32)
    amask = lanei < n_acc
    valid = amask & ~fpset.all_sentinel(kcols)
    member_fn = (
        member_block_pallas if probe_impl == "pallas" else member_block
    )
    member, _resolved = member_fn(tcols, kcols, valid, rounds_blk)
    survivors = valid & ~member
    # order-preserving compaction of survivors + ORIGINAL lane ids —
    # chunk order is lane order, so cross-chunk equal-key resolution
    # stays min-lane-wins
    drop = (~survivors).astype(jnp.uint32)
    ccols, _ = compact_ops.compact_by_flag(
        drop, tuple(kcols) + (lanei.astype(jnp.uint32),),
        impl=compact_impl, need_idx=False,
    )
    npend = jnp.sum(survivors.astype(jnp.int32))
    cw = max(nq // 4, min(nq, fpset.MIN_STAGE))
    nchunks_cap = -(-nq // cw)
    padn = nchunks_cap * cw - nq
    ckeys = tuple(c for c in ccols[:K])
    cids = ccols[K].astype(jnp.int32)
    if padn:
        ckeys = tuple(
            jnp.pad(c, (0, padn), constant_values=SENTINEL)
            for c in ckeys
        )
        cids = jnp.pad(cids, (0, padn), constant_values=nq)
    nchunks = jnp.minimum(
        (npend + cw - 1) // cw, jnp.int32(nchunks_cap)
    )

    def chunk(i, carry):
        tc, is_new, nf, rounds = carry
        base = i * cw
        kk = tuple(
            lax.dynamic_slice(c, (base,), (cw,)) for c in ckeys
        )
        lid = lax.dynamic_slice(cids, (base,), (cw,))
        pend = base + jnp.arange(cw, dtype=jnp.int32) < npend
        new2, tc, _, pending, r = fpset.probe_insert(
            tc, kk, pend, max_probes=max_probes, lane_ids=lid
        )
        tgt = jnp.where(new2, lid, jnp.int32(nq))
        is_new = is_new.at[tgt].set(True, mode="drop")
        nf = nf + jnp.sum(pending.astype(jnp.int32))
        return (tc, is_new, nf, rounds + r)

    tcols2, is_new, n_failed, rounds = lax.fori_loop(
        0, nchunks, chunk,
        (
            tuple(tcols),
            jnp.zeros((nq,), jnp.bool_),
            jnp.int32(0),
            jnp.int32(rounds_blk),
        ),
    )
    n_new = jnp.sum(is_new.astype(jnp.int32))
    fpm2 = fpset.fpm_update(
        fpm, rounds, n_failed, jnp.sum(valid.astype(jnp.int32))
    )
    return tcols2, n_new, is_new.astype(jnp.uint32), fpm2


# ------------------------------------------------------------ expand


def _rotl_k(x, r: int):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _fmix_k(h):
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _murmur3_words_k(words, seed: int):
    w = words.shape[-1]
    h = jnp.full(words.shape[:-1], np.uint32(seed), jnp.uint32)
    for i in range(w):
        k = words[..., i] * np.uint32(0xCC9E2D51)
        k = _rotl_k(k, 15) * np.uint32(0x1B873593)
        h = h ^ k
        h = _rotl_k(h, 13) * np.uint32(5) + np.uint32(0xE6546B64)
    return _fmix_k(h ^ np.uint32(4 * w))


def _key_cols_kernel(keyspec, packed):
    """``KeySpec.make`` re-expressed with kernel-safe numpy-literal
    constants (the dedup originals are jnp scalars, which a Pallas
    kernel trace would capture).  Bit-identical to ``keyspec.make`` —
    pinned by the ``key_plane`` parity properties in
    ``tests/test_tiles.py``."""
    n, w = packed.shape
    if keyspec.exact:
        cols = [packed[:, i] for i in range(w)]
        while len(cols) < keyspec.ncols:
            cols.append(jnp.zeros((n,), jnp.uint32))
        return tuple(cols)
    h = [
        _murmur3_words_k(packed, seed)
        for seed in (0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35)[
            : keyspec.ncols
        ]
    ]
    all_sent = h[0] == _SENT
    for c in h[1:]:
        all_sent = all_sent & (c == _SENT)
    h[-1] = jnp.where(all_sent, h[-1] ^ np.uint32(1), h[-1])
    return tuple(h)


def key_plane(keyspec, packedf: jax.Array, vflat: jax.Array,
              impl: str = "tile"):
    """Key-column formation for one expand window's flattened
    successor matrix: ``packed u32[nc, W] -> K masked u32[nc]``
    columns (invalid lanes SENTINEL).  ``tile`` runs the mixing chain
    as one full-matrix XLA op; ``pallas`` blocks it into
    :data:`PALLAS_TILE` row tiles through an explicit kernel.  Both
    are elementwise per lane — bit-identical to the legacy per-chunk
    path."""
    if impl != "pallas":
        kcols = keyspec.make(packedf)
        return tuple(
            jnp.where(vflat, c, SENTINEL) for c in kcols
        )
    from jax.experimental import pallas as pl

    nc, w = packedf.shape
    K = keyspec.ncols
    lt = min(PALLAS_TILE, nc)
    ntiles = -(-nc // lt)
    pad = ntiles * lt - nc
    if pad:
        packedf = jnp.pad(packedf, ((0, pad), (0, 0)))
        vflat = jnp.pad(vflat, (0, pad))

    def kernel(p_ref, v_ref, *orefs):
        cols = _key_cols_kernel(keyspec, p_ref[...])
        v = v_ref[...]
        for o, c in zip(orefs, cols):
            o[...] = jnp.where(v, c, _SENT)

    cols = pl.pallas_call(
        kernel,
        out_shape=tuple(
            jax.ShapeDtypeStruct((ntiles * lt,), jnp.uint32)
            for _ in range(K)
        ),
        grid=(ntiles,),
        in_specs=(
            pl.BlockSpec((lt, w), lambda i: (i, 0)),
            pl.BlockSpec((lt,), lambda i: (i,)),
        ),
        out_specs=tuple(
            pl.BlockSpec((lt,), lambda i: (i,)) for _ in range(K)
        ),
        interpret=_interpret(),
    )(packedf, vflat)
    if isinstance(cols, jax.Array):  # K == 1 unwraps
        cols = (cols,)
    return tuple(c[:nc] for c in cols)


# ------------------------------------------------------------- sieve


def sieve_mask_planes(
    tcols: Tuple[jax.Array, ...],
    gen: jax.Array,
    cold: jax.Array,
    impl: str = "tile",
):
    """The sieve's masking plane as a tile op: ``(masked_ev cols,
    holed cols, gen_cleared)`` from the cold mask — elementwise over
    the table planes (``tile`` = one fused XLA pass; ``pallas`` = an
    explicit elementwise kernel over slot tiles)."""
    if impl != "pallas":
        masked = tuple(
            jnp.where(cold, c, SENTINEL) for c in tcols
        )
        holed = tuple(
            jnp.where(cold, SENTINEL, c) for c in tcols
        )
        gen2 = jnp.where(cold, jnp.int32(0), gen)
        return masked, holed, gen2
    from jax.experimental import pallas as pl

    K = len(tcols)
    cap1 = tcols[0].shape[0]
    lt = min(PALLAS_TILE, cap1)
    ntiles = -(-cap1 // lt)
    pad = ntiles * lt - cap1
    cols = tcols
    if pad:
        cols = tuple(
            jnp.pad(c, (0, pad), constant_values=SENTINEL)
            for c in tcols
        )
        gen = jnp.pad(gen, (0, pad))
        cold = jnp.pad(cold, (0, pad))

    def kernel(*refs):
        trefs = refs[:K]
        cold_ref, gen_ref = refs[K], refs[K + 1]
        m_refs = refs[K + 2: 2 * K + 2]
        h_refs = refs[2 * K + 2: 3 * K + 2]
        g_ref = refs[3 * K + 2]
        cm = cold_ref[...]
        for m, hr, t in zip(m_refs, h_refs, trefs):
            v = t[...]
            m[...] = jnp.where(cm, v, _SENT)
            hr[...] = jnp.where(cm, _SENT, v)
        g_ref[...] = jnp.where(cm, 0, gen_ref[...])

    spec = pl.BlockSpec((lt,), lambda i: (i,))
    out = pl.pallas_call(
        kernel,
        out_shape=(
            tuple(
                jax.ShapeDtypeStruct((ntiles * lt,), jnp.uint32)
                for _ in range(2 * K)
            )
            + (jax.ShapeDtypeStruct((ntiles * lt,), jnp.int32),)
        ),
        grid=(ntiles,),
        in_specs=[spec] * (K + 2),
        out_specs=tuple([spec] * (2 * K + 1)),
        interpret=_interpret(),
    )(*cols, cold, gen)
    masked = tuple(c[:cap1] for c in out[:K])
    holed = tuple(c[:cap1] for c in out[K: 2 * K])
    gen2 = out[2 * K][:cap1]
    return masked, holed, gen2


def extract_cold_tiles(
    tcols: Tuple[jax.Array, ...],
    gen: jax.Array,
    cutoff,
    sieve_impl: str = "tile",
):
    """The tiled ``extract_cold``: identical contract and ARRAY-
    identical outputs to :func:`store.sieve.extract_cold`, with the
    pre-sort compaction dropped — the sort receives the same multiset
    (cold keys are distinct table entries; SENTINEL padding sorts
    last), so sorting the masked planes directly yields the same
    sorted columns while skipping the gather-heavy compact pass."""
    cap = tcols[0].shape[0] - 1
    lane = jnp.arange(cap + 1, dtype=jnp.int32)
    occ = ~fpset.all_sentinel(tcols) & (lane < cap)
    cold = occ & (gen >= 1) & (gen <= jnp.int32(cutoff))
    n_ev = jnp.sum(cold.astype(jnp.int32))
    masked, holed, gen2 = sieve_mask_planes(
        tcols, gen, cold, impl=sieve_impl
    )
    ev_sorted = lax.sort(
        masked, num_keys=len(masked), is_stable=False
    )
    return holed, gen2, ev_sorted, n_ev
