"""Packed-state codec: fixed-width bit layouts over uint32 word vectors.

SURVEY.md §7-L0.  Every TLA+ state of a compiled spec is encoded into ``W``
uint32 words with a layout derived statically from the model constants.
The encoding is *canonical* (equal TLA+ states <-> equal words) and
*compact* — see the compaction notes on :class:`Layout` below.

Implementation note: pack/unpack are **field-vectorized**.  A field of
``n`` elements of ``width`` bits occupies a contiguous bit range with
stride ``width``; its word indices and shifts are static numpy arrays, so
packing is two scatter-adds per field (disjoint bit ranges make OR == ADD)
and unpacking is two static gathers plus shifts — a few vector ops per
FIELD rather than several scalar ops per ELEMENT.  At the |Msgs|=64 stress
config this keeps the traced graph ~50x smaller than an element-unrolled
codec, which is the difference between seconds and minutes of XLA compile
time for the fused BFS step.

Canonical-form obligations on writers (kernels must maintain these so that
packing is injective):
- ``keys[i] = vals[i] = 0`` for positions ``i >= length``;
- ``led_mask[c] = 0`` whenever ``led_present[c] = 0``;
- ``p1_readpos = 0`` whenever ``p1_present = 0``;
- ``cursor_h = cursor_c = 0`` whenever ``cursor_present = 0``.

No 64-bit integer types are used anywhere (TPU-friendly; jax x64 off).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from pulsar_tlaplus_tpu.ref.pyeval import Constants


def bitlen(n: int) -> int:
    """Bits needed to represent values 0..n (0 -> 0 bits)."""
    return n.bit_length()


class _FieldCodec:
    """Bit-level codec over an ordered list of (name, n_elems, width)."""

    def __init__(self, fields):
        self.fields = []
        base = 0
        for name, n, width in fields:
            if not 0 <= width <= 32:
                raise ValueError(f"{name}: width {width} not in 0..32")
            offs = base + np.arange(n, dtype=np.int64) * width
            widx = (offs // 32).astype(np.int32)
            shift = (offs % 32).astype(np.uint32)
            spill = (offs % 32) + width > 32
            # shift amounts for the spill word; 0 where unused (w <= 32
            # guarantees shift >= 1 whenever spill, so 32-shift is in 1..31)
            shr = np.where(spill, 32 - (offs % 32), 0).astype(np.uint32)
            self.fields.append(
                (name, n, width, widx, shift, spill, shr)
            )
            base += n * width
        self.total_bits = base
        self.W = max(1, math.ceil(base / 32))

    def pack(self, values_by_field) -> jax.Array:
        """List of u32-castable [n] arrays (field order) -> u32[W]."""
        words = jnp.zeros((self.W + 1,), jnp.uint32)  # +1 spill scratch
        for (name, n, width, widx, shift, spill, shr), v in zip(
            self.fields, values_by_field
        ):
            if width == 0 or n == 0:
                continue
            mask = (
                jnp.uint32((1 << width) - 1)
                if width < 32
                else jnp.uint32(0xFFFFFFFF)
            )
            v = jnp.asarray(v).reshape(n).astype(jnp.uint32) & mask
            words = words.at[widx].add(v << shift)
            if spill.any():
                hi = jnp.where(spill, v >> shr, jnp.uint32(0))
                words = words.at[widx + 1].add(hi)
        return words[: self.W]

    def unpack(self, words: jax.Array):
        """u32[W] -> dict name -> i32[n] (flat)."""
        ext = jnp.concatenate([words, jnp.zeros((1,), jnp.uint32)])
        out = {}
        for name, n, width, widx, shift, spill, shr in self.fields:
            if width == 0 or n == 0:
                out[name] = jnp.zeros((n,), jnp.int32)
                continue
            mask = (
                jnp.uint32((1 << width) - 1)
                if width < 32
                else jnp.uint32(0xFFFFFFFF)
            )
            lo = ext[widx] >> shift
            if spill.any():
                # low (32-shift) bits came from word widx; the rest were
                # spilled to word widx+1 starting at bit 0, so they slot
                # back in at bit position shr = 32-shift
                hi = jnp.where(spill, ext[widx + 1] << shr, jnp.uint32(0))
                lo = lo | hi
            out[name] = (lo & mask).astype(jnp.int32)
        return out


class SState(NamedTuple):
    """Struct-of-scalars state (one TLA+ state; batch via vmap).

    Mirrors the 10 VARIABLES of compaction.tla:56-70 under the compressed
    encoding documented in :class:`Layout`.
    """

    length: jax.Array  # i32 scalar: Len(messages), 0..M
    keys: jax.Array  # i32[M]: message keys, 0 = NullKey / padding
    vals: jax.Array  # i32[M]: message values, 0 = NullValue / padding
    led_present: jax.Array  # i32[C]: 1 if compactedLedgers[c+1] # Nil
    led_mask: jax.Array  # u32[C, MW]: kept-position bitmask per ledger slot
    cursor_present: jax.Array  # i32 scalar
    cursor_h: jax.Array  # i32 scalar: cursor.compactionHorizon
    cursor_c: jax.Array  # i32 scalar: cursor.compactedTopicContext
    cstate: jax.Array  # i32 scalar: 0..5 (compaction.tla:38-44 order)
    p1_present: jax.Array  # i32 scalar
    p1_readpos: jax.Array  # i32 scalar: phaseOneResult.readPosition
    horizon: jax.Array  # i32 scalar: compactionHorizon
    context: jax.Array  # i32 scalar: compactedTopicContext
    crash: jax.Array  # i32 scalar: crashTimes
    consume: jax.Array  # i32 scalar: consumeTimes


class Layout:
    """Static bit layout for the compaction spec; pack/unpack kernels.

    Encoding (bit-identical to the original element-stream layout):

    - ``messages`` (compaction.tla:57): ids are positional, so only
      ``(key, value)`` per position plus a length are stored.
    - ``compactedLedgers`` (compaction.tla:58-59): a compacted ledger — a
      subsequence of a past message prefix — is a presence bit plus a
      *bitmask over message positions* (bit j-1 = position j kept).
    - ``phaseOneResult`` (compaction.tla:64): ``latestForKey`` is
      derivable, so only ``(present, readPosition)`` is stored.
    - ``cursor`` (compaction.tla:60): presence bit + two small ints.
    """

    def __init__(self, c: Constants):
        self.c = c
        m = c.message_sent_limit
        self.M = m
        self.C = c.compaction_times_limit
        self.MW = max(1, math.ceil(m / 32))  # mask words per ledger slot
        self.kb = bitlen(c.num_keys)
        self.vb = bitlen(c.num_values)
        self.mb = bitlen(m)
        self.cb = bitlen(self.C)
        self.crb = bitlen(c.max_crash_times)
        self.cob = bitlen(c.consume_times_limit) if c.model_consumer else 0
        fields = [
            ("length", 1, self.mb),
            ("keys", m, self.kb),
            ("vals", m, self.vb),
        ]
        for cc in range(self.C):
            fields.append((f"led_present{cc}", 1, 1))
            fields.append((f"led_mask{cc}", m, 1))  # == the old word stream
        fields += [
            ("cursor_present", 1, 1),
            ("cursor_h", 1, self.mb),
            ("cursor_c", 1, self.cb),
            ("cstate", 1, 3),
            ("p1_present", 1, 1),
            ("p1_readpos", 1, self.mb),
            ("horizon", 1, self.mb),
            ("context", 1, self.cb),
            ("crash", 1, self.crb),
            ("consume", 1, self.cob),
        ]
        self._codec = _FieldCodec(fields)
        self.total_bits = self._codec.total_bits
        self.W = self._codec.W
        # static index arrays for mask words <-> bit lanes
        j = np.arange(m, dtype=np.int32)
        self._bit_word = j // 32
        self._bit_shift = jnp.asarray(j % 32, jnp.uint32)

    def _mask_to_bits(self, mask_words: jax.Array) -> jax.Array:
        """u32[MW] -> u32[M] of 0/1 (bit j-1 = position j kept)."""
        return (mask_words[self._bit_word] >> self._bit_shift) & jnp.uint32(1)

    def _bits_to_mask(self, bits: jax.Array) -> jax.Array:
        """u32-castable [M] of 0/1 -> u32[MW]."""
        words = jnp.zeros((self.MW,), jnp.uint32)
        return words.at[self._bit_word].add(
            bits.astype(jnp.uint32) << self._bit_shift
        )

    def pack(self, s: SState) -> jax.Array:
        """One state -> u32[W].  vmap for batches."""
        values = [s.length, s.keys, s.vals]
        for cc in range(self.C):
            values.append(s.led_present[cc])
            values.append(self._mask_to_bits(s.led_mask[cc]))
        values += [
            s.cursor_present,
            s.cursor_h,
            s.cursor_c,
            s.cstate,
            s.p1_present,
            s.p1_readpos,
            s.horizon,
            s.context,
            s.crash,
            s.consume,
        ]
        return self._codec.pack(values)

    def unpack(self, words: jax.Array) -> SState:
        """u32[W] -> one state.  vmap for batches."""
        d = self._codec.unpack(words)
        sc = lambda name: d[name][0]
        if self.C:
            led_present = jnp.stack(
                [sc(f"led_present{cc}") for cc in range(self.C)]
            )
            led_mask = jnp.stack(
                [
                    self._bits_to_mask(d[f"led_mask{cc}"])
                    for cc in range(self.C)
                ]
            )
        else:
            led_present = jnp.zeros((0,), jnp.int32)
            led_mask = jnp.zeros((0, self.MW), jnp.uint32)
        return SState(
            length=sc("length"),
            keys=d["keys"],
            vals=d["vals"],
            led_present=led_present,
            led_mask=led_mask,
            cursor_present=sc("cursor_present"),
            cursor_h=sc("cursor_h"),
            cursor_c=sc("cursor_c"),
            cstate=sc("cstate"),
            p1_present=sc("p1_present"),
            p1_readpos=sc("p1_readpos"),
            horizon=sc("horizon"),
            context=sc("context"),
            crash=sc("crash"),
            consume=sc("consume"),
        )


class StructLayout:
    """Generic fixed-width bit layout over a user NamedTuple state class.

    The model-agnostic counterpart of the compaction :class:`Layout`
    (SURVEY.md §7-L0): a compiled spec model declares its state as a
    NamedTuple of int32 scalars / vectors / matrices plus a ``specs`` map
    ``field -> (shape, width_bits)`` and gets canonical ``pack``/``unpack``
    kernels for free.  Fields are packed in NamedTuple field order,
    row-major within a field.  Widths must be <= 32; every element must be
    a non-negative integer < 2**width (canonical-form obligation on the
    model's kernels, as for ``Layout``).
    """

    def __init__(self, state_cls, specs: dict):
        self.state_cls = state_cls
        missing = [f for f in state_cls._fields if f not in specs]
        if missing:
            raise ValueError(f"specs missing fields: {missing}")
        self.shapes = {}
        fields = []
        for name in state_cls._fields:
            shape, width = specs[name]
            shape = tuple(shape)
            n_elems = 1
            for d in shape:
                n_elems *= d
            self.shapes[name] = (shape, n_elems)
            fields.append((name, n_elems, width))
        self._codec = _FieldCodec(fields)
        self.total_bits = self._codec.total_bits
        self.W = self._codec.W

    def pack(self, s) -> jax.Array:
        """One state -> u32[W].  vmap for batches."""
        values = [
            jnp.reshape(getattr(s, name), (self.shapes[name][1],))
            for name in self.state_cls._fields
        ]
        return self._codec.pack(values)

    def unpack(self, words: jax.Array):
        """u32[W] -> one state.  vmap for batches."""
        d = self._codec.unpack(words)
        out = {}
        for name in self.state_cls._fields:
            shape, n_elems = self.shapes[name]
            v = d[name]
            out[name] = (
                v.reshape(shape) if shape != () else v[0]
            )
        return self.state_cls(**out)
