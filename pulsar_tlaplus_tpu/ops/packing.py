"""Packed-state codec: fixed-width bit layouts over uint32 word vectors.

SURVEY.md §7-L0.  Every TLA+ state of the ``compaction`` spec is encoded into
``W`` uint32 words with a layout derived statically from the model constants.
The encoding is *canonical* (equal TLA+ states <-> equal words) and *compact*:

- ``messages`` (compaction.tla:57): ids are positional (``Producer`` appends
  ``id = Len+1`` at compaction.tla:86; pre-generated Init forces ``id = i`` at
  compaction.tla:194), so only ``(key, value)`` per position plus a length are
  stored.
- ``compactedLedgers`` (compaction.tla:58-59): messages are append-only, so a
  compacted ledger — a subsequence of a past message prefix — is stored as a
  per-slot *bitmask over message positions* plus a presence bit.  Distinct
  masks give distinct sequences (entries carry distinct positional ids), and
  the mask plus the current ``messages`` array reconstructs the sequence
  exactly, so the encoding is bijective on reachable states.
- ``phaseOneResult`` (compaction.tla:64): ``latestForKey`` is a deterministic
  function of ``messages[1..readPosition]`` (compaction.tla:97-98) and
  ``messages`` is append-only, so only ``(present, readPosition)`` is stored.
- ``cursor`` (compaction.tla:60): presence bit + two small ints.

Canonical-form obligations on writers (kernels must maintain these so that
packing is injective):
- ``keys[i] = vals[i] = 0`` for positions ``i >= length``;
- ``led_mask[c] = 0`` whenever ``led_present[c] = 0``;
- ``p1_readpos = 0`` whenever ``p1_present = 0``;
- ``cursor_h = cursor_c = 0`` whenever ``cursor_present = 0``.

No 64-bit integer types are used anywhere (TPU-friendly; jax x64 stays off).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.ref.pyeval import Constants


def bitlen(n: int) -> int:
    """Bits needed to represent values 0..n (0 -> 0 bits)."""
    return n.bit_length()


class StructLayout:
    """Generic fixed-width bit layout over a user NamedTuple state class.

    The model-agnostic counterpart of the hand-tuned compaction ``Layout``
    (SURVEY.md §7-L0): a compiled spec model declares its state as a
    NamedTuple of int32 scalars / vectors / matrices plus a ``specs`` map
    ``field -> (shape, width_bits)`` and gets canonical ``pack``/``unpack``
    kernels for free.  Fields are packed in NamedTuple field order,
    row-major within a field.  Widths must be <= 32; every element must be
    a non-negative integer < 2**width (canonical-form obligation on the
    model's kernels, as for ``Layout``).
    """

    def __init__(self, state_cls, specs: dict):
        self.state_cls = state_cls
        missing = [f for f in state_cls._fields if f not in specs]
        if missing:
            raise ValueError(f"specs missing fields: {missing}")
        self.fields = []
        total = 0
        for name in state_cls._fields:
            shape, width = specs[name]
            shape = tuple(shape)
            if not 0 <= width <= 32:
                raise ValueError(f"{name}: width {width} not in 0..32")
            n_elems = 1
            for d in shape:
                n_elems *= d
            self.fields.append((name, shape, width, n_elems))
            total += n_elems * width
        self.total_bits = total
        self.W = max(1, math.ceil(total / 32))

    def _flat(self, s):
        """Ordered (scalar u32-castable value, width) stream."""
        items = []
        for name, shape, width, n_elems in self.fields:
            v = getattr(s, name)
            if shape == ():
                items.append((v, width))
            else:
                flat = jnp.reshape(v, (n_elems,))
                for i in range(n_elems):
                    items.append((flat[i], width))
        return items

    def pack(self, s) -> jax.Array:
        """One state -> u32[W].  vmap for batches."""
        words = [jnp.uint32(0)] * self.W
        pos = 0
        for val, width in self._flat(s):
            if width == 0:
                continue
            mask = (
                jnp.uint32((1 << width) - 1)
                if width < 32
                else jnp.uint32(0xFFFFFFFF)
            )
            v = val.astype(jnp.uint32) & mask
            w, off = divmod(pos, 32)
            words[w] = words[w] | (v << jnp.uint32(off))
            if off + width > 32:
                words[w + 1] = words[w + 1] | (v >> jnp.uint32(32 - off))
            pos += width
        return jnp.stack(words)

    def unpack(self, words: jax.Array):
        """u32[W] -> one state.  vmap for batches."""
        pos = 0

        def read(width: int) -> jax.Array:
            nonlocal pos
            if width == 0:
                return jnp.int32(0)
            w, off = divmod(pos, 32)
            lo = words[w] >> jnp.uint32(off)
            if off + width > 32:
                lo = lo | (words[w + 1] << jnp.uint32(32 - off))
            mask = (
                jnp.uint32((1 << width) - 1)
                if width < 32
                else jnp.uint32(0xFFFFFFFF)
            )
            pos += width
            return lo & mask

        out = {}
        for name, shape, width, n_elems in self.fields:
            if shape == ():
                out[name] = read(width).astype(jnp.int32)
            else:
                elems = [read(width).astype(jnp.int32) for _ in range(n_elems)]
                arr = (
                    jnp.stack(elems).reshape(shape)
                    if n_elems
                    else jnp.zeros(shape, jnp.int32)
                )
                out[name] = arr
        return self.state_cls(**out)


class SState(NamedTuple):
    """Struct-of-scalars state (one TLA+ state; batch via vmap).

    Mirrors the 10 VARIABLES of compaction.tla:56-70 under the compressed
    encoding documented in the module docstring.
    """

    length: jax.Array  # i32 scalar: Len(messages), 0..M
    keys: jax.Array  # i32[M]: message keys, 0 = NullKey / padding
    vals: jax.Array  # i32[M]: message values, 0 = NullValue / padding
    led_present: jax.Array  # i32[C]: 1 if compactedLedgers[c+1] # Nil
    led_mask: jax.Array  # u32[C, MW]: kept-position bitmask per ledger slot
    cursor_present: jax.Array  # i32 scalar
    cursor_h: jax.Array  # i32 scalar: cursor.compactionHorizon
    cursor_c: jax.Array  # i32 scalar: cursor.compactedTopicContext
    cstate: jax.Array  # i32 scalar: 0..5 (compaction.tla:38-44 order)
    p1_present: jax.Array  # i32 scalar
    p1_readpos: jax.Array  # i32 scalar: phaseOneResult.readPosition
    horizon: jax.Array  # i32 scalar: compactionHorizon
    context: jax.Array  # i32 scalar: compactedTopicContext
    crash: jax.Array  # i32 scalar: crashTimes
    consume: jax.Array  # i32 scalar: consumeTimes


class Layout:
    """Static bit layout for a given ``Constants``; pack/unpack kernels."""

    def __init__(self, c: Constants):
        self.c = c
        m = c.message_sent_limit
        self.M = m
        self.C = c.compaction_times_limit
        self.MW = max(1, math.ceil(m / 32))  # mask words per ledger slot
        self.kb = bitlen(c.num_keys)
        self.vb = bitlen(c.num_values)
        self.mb = bitlen(m)
        self.cb = bitlen(self.C)
        self.crb = bitlen(c.max_crash_times)
        self.cob = bitlen(c.consume_times_limit) if c.model_consumer else 0
        self.total_bits = (
            self.mb
            + m * (self.kb + self.vb)
            + self.C * (1 + m)
            + (1 + self.mb + self.cb)  # cursor
            + 3  # cstate
            + (1 + self.mb)  # phaseOneResult
            + self.mb  # horizon
            + self.cb  # context
            + self.crb
            + self.cob
        )
        self.W = max(1, math.ceil(self.total_bits / 32))

    # -- stream construction -------------------------------------------------

    def _items(self, s: SState):
        """Ordered (scalar, width) stream defining the bit layout."""
        items = [(s.length, self.mb)]
        for i in range(self.M):
            items.append((s.keys[i], self.kb))
        for i in range(self.M):
            items.append((s.vals[i], self.vb))
        for cc in range(self.C):
            items.append((s.led_present[cc], 1))
            rem = self.M
            for w in range(self.MW):
                width = min(32, rem)
                if width > 0:
                    items.append((s.led_mask[cc, w], width))
                rem -= width
        items.append((s.cursor_present, 1))
        items.append((s.cursor_h, self.mb))
        items.append((s.cursor_c, self.cb))
        items.append((s.cstate, 3))
        items.append((s.p1_present, 1))
        items.append((s.p1_readpos, self.mb))
        items.append((s.horizon, self.mb))
        items.append((s.context, self.cb))
        items.append((s.crash, self.crb))
        items.append((s.consume, self.cob))
        return items

    def pack(self, s: SState) -> jax.Array:
        """One state -> u32[W].  vmap for batches."""
        words = [jnp.uint32(0)] * self.W
        pos = 0
        for val, width in self._items(s):
            if width == 0:
                continue
            mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
            v = val.astype(jnp.uint32) & mask
            w, off = divmod(pos, 32)
            words[w] = words[w] | (v << jnp.uint32(off))
            if off + width > 32:
                words[w + 1] = words[w + 1] | (v >> jnp.uint32(32 - off))
            pos += width
        return jnp.stack(words)

    def unpack(self, words: jax.Array) -> SState:
        """u32[W] -> one state.  vmap for batches."""
        pos = 0

        def read(width: int) -> jax.Array:
            nonlocal pos
            if width == 0:
                return jnp.int32(0)
            w, off = divmod(pos, 32)
            lo = words[w] >> jnp.uint32(off)
            if off + width > 32:
                lo = lo | (words[w + 1] << jnp.uint32(32 - off))
            mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
            pos += width
            return lo & mask

        length = read(self.mb).astype(jnp.int32)
        keys = jnp.stack([read(self.kb).astype(jnp.int32) for _ in range(self.M)]) if self.M else jnp.zeros((0,), jnp.int32)
        vals = jnp.stack([read(self.vb).astype(jnp.int32) for _ in range(self.M)]) if self.M else jnp.zeros((0,), jnp.int32)
        led_present = []
        led_mask = []
        for _cc in range(self.C):
            led_present.append(read(1).astype(jnp.int32))
            rem = self.M
            mws = []
            for _w in range(self.MW):
                width = min(32, rem)
                mws.append(read(width).astype(jnp.uint32) if width > 0 else jnp.uint32(0))
                rem -= width
            led_mask.append(jnp.stack(mws))
        led_present = (
            jnp.stack(led_present) if self.C else jnp.zeros((0,), jnp.int32)
        )
        led_mask = (
            jnp.stack(led_mask)
            if self.C
            else jnp.zeros((0, self.MW), jnp.uint32)
        )
        cursor_present = read(1).astype(jnp.int32)
        cursor_h = read(self.mb).astype(jnp.int32)
        cursor_c = read(self.cb).astype(jnp.int32)
        cstate = read(3).astype(jnp.int32)
        p1_present = read(1).astype(jnp.int32)
        p1_readpos = read(self.mb).astype(jnp.int32)
        horizon = read(self.mb).astype(jnp.int32)
        context = read(self.cb).astype(jnp.int32)
        crash = read(self.crb).astype(jnp.int32)
        consume = read(self.cob).astype(jnp.int32)
        return SState(
            length,
            keys,
            vals,
            led_present,
            led_mask,
            cursor_present,
            cursor_h,
            cursor_c,
            cstate,
            p1_present,
            p1_readpos,
            horizon,
            context,
            crash,
            consume,
        )
