"""Fingerprint keys, sorting, and visited-set membership — the TPU-native
equivalent of TLC's ``FPSet`` (SURVEY.md §2.2-E3).

Design: a state's dedup key is 3 x uint32 (96 bits).

- When the packed state fits in <= 3 words, the key *is* the packed state —
  dedup is exact (strictly stronger than TLC, whose 64-bit Rabin
  fingerprints accept a small collision probability).  This covers the
  shipped ``compaction.cfg`` (42 bits) and all differential-test configs.
- Wider states use three independent murmur3-style 32-bit hashes (96-bit
  effective fingerprint; collision expectation n^2/2^97 — e.g. ~1e-11 at a
  billion states, far below TLC's 64-bit regime).

The visited set is a sorted 3-column uint32 array padded with the all-ones
sentinel; membership is an unrolled branchless binary search (vectorized
over queries), insertion is concat + ``lax.sort`` (v0 of the mesh-sharded
FPSet; SURVEY.md §7-L3 replaces this with ownership-sharded tables routed
over ICI).

No 64-bit integers anywhere: TPU-friendly, jax x64 stays off.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

SENTINEL = jnp.uint32(0xFFFFFFFF)

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _fmix(h: jax.Array) -> jax.Array:
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def murmur3_words(words: jax.Array, seed: int) -> jax.Array:
    """murmur3_32 over the trailing word axis.  words: u32[..., W] -> u32[...]."""
    w = words.shape[-1]
    h = jnp.full(words.shape[:-1], seed, jnp.uint32)
    for i in range(w):
        k = words[..., i] * _C1
        k = _rotl(k, 15) * _C2
        h = h ^ k
        h = _rotl(h, 13) * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    return _fmix(h ^ jnp.uint32(4 * w))


def make_keys(packed: jax.Array, total_bits: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """packed u32[N, W] -> 3 x u32[N] dedup key columns.

    Exact (identity) when the state fits in < 96 bits, hashed otherwise.
    The all-SENTINEL triple is reserved as the empty/invalid marker: it is
    unreachable in exact mode (padding bits above ``total_bits`` are
    always zero, and at exactly 96 bits we fall through to hashing), and
    remapped with negligible collision cost in hashed mode.
    """
    n, w = packed.shape
    if w <= 3 and total_bits < 96:
        cols = [packed[:, i] for i in range(w)]
        while len(cols) < 3:
            cols.append(jnp.zeros((n,), jnp.uint32))
        return cols[0], cols[1], cols[2]
    h1 = murmur3_words(packed, 0x9E3779B9)
    h2 = murmur3_words(packed, 0x85EBCA6B)
    h3 = murmur3_words(packed, 0xC2B2AE35)
    all_sent = (h1 == SENTINEL) & (h2 == SENTINEL) & (h3 == SENTINEL)
    return h1, h2, jnp.where(all_sent, h3 ^ jnp.uint32(1), h3)


class KeySpec:
    """Dedup-key layout for one state layout (SURVEY.md §2.2-E3).

    Chooses the number of uint32 key columns and exact-vs-hashed mode:

    - ``total_bits < 64`` (W <= 2): the packed state IS the key — 2 exact
      columns (strictly stronger than TLC's 64-bit Rabin fingerprints);
    - ``total_bits < 96`` (W <= 3): 3 exact columns, as before;
    - wider states: murmur3 fingerprints — ``fp_bits=64`` (2 columns,
      TLC's fingerprint-width regime, collision probability reported
      like TLC's) or ``fp_bits=96`` (3 columns).  Default 64: one fewer
      operand in every dedup sort = ~25% less sort traffic, and XLA
      lowers the smaller comparator measurably faster.

    The all-SENTINEL tuple is reserved as the empty marker (unreachable
    in exact mode because at least one pad bit above ``total_bits`` is
    zero; remapped with negligible collision cost in hashed mode).
    """

    def __init__(self, total_bits: int, W: int, fp_bits: int | None = None):
        if W <= 2 and total_bits < 64:
            self.ncols, self.exact = 2, True
        elif W <= 3 and total_bits < 96:
            self.ncols, self.exact = 3, True
        else:
            if fp_bits is None:
                fp_bits = 64
            if fp_bits not in (64, 96):
                raise ValueError("fp_bits must be 64 or 96")
            self.ncols, self.exact = fp_bits // 32, False
        self.total_bits = total_bits
        self.W = W

    def make(self, packed: jax.Array) -> Tuple[jax.Array, ...]:
        """packed u32[N, W] -> ``ncols`` x u32[N] key columns."""
        n, w = packed.shape
        if self.exact:
            cols = [packed[:, i] for i in range(w)]
            while len(cols) < self.ncols:
                cols.append(jnp.zeros((n,), jnp.uint32))
            return tuple(cols)
        h = [
            murmur3_words(packed, seed)
            for seed in (0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35)[: self.ncols]
        ]
        all_sent = h[0] == SENTINEL
        for c in h[1:]:
            all_sent = all_sent & (c == SENTINEL)
        h[-1] = jnp.where(all_sent, h[-1] ^ jnp.uint32(1), h[-1])
        return tuple(h)

    _warned: set = set()

    def warn_if_hashed(self, max_states: int):
        """One stderr note when hashed-fingerprint mode engages by
        default (ADVICE r3): dedup turned probabilistic silently for
        wide states — surface it up front, not only in the final
        report.  Engines call this when the caller did not pick
        ``fp_bits`` explicitly.  Deduplicated per key configuration
        (ADVICE r4: a bench/test run builds several checkers and the
        note used to repeat for each)."""
        if self.exact:
            return
        cfg = (self.total_bits, self.ncols, max_states)
        if cfg in KeySpec._warned:
            return
        KeySpec._warned.add(cfg)
        import sys

        print(
            f"note: state is {self.total_bits} bits wide -> "
            f"{32 * self.ncols}-bit hashed fingerprints (TLC's regime); "
            f"expected fp collisions at {max_states} states: "
            f"{self.collision_prob(max_states):.3g} "
            "(fp_bits=96 available)",
            file=sys.stderr,
        )

    def collision_prob(self, n_states: int) -> float:
        """Expected number of fingerprint collisions at ``n_states``
        distinct states (birthday bound) — 0.0 in exact mode.  TLC
        prints the analogous estimate after every run."""
        if self.exact:
            return 0.0
        return float(n_states) * float(n_states) / 2.0 ** (
            32 * self.ncols + 1
        )


def merge_new_keys(vcols, ccols, cpay):
    """Sort-merge candidate key columns into the sorted visited columns
    (both SENTINEL-padded) — the shared dedup core of the device
    engine's flush and seed-merge paths.

    ``cpay`` is the candidates' payload word with the tag bit (1 << 31)
    set; visited entries ride payload 0, so one unstable sort orders
    visited before same-key candidates and resolves in-batch duplicates
    and visited membership in a single pass.  Returns ``(vcols',
    n_new, sorted_payload, new_flag)`` where ``vcols'`` has the same
    width as ``vcols`` (callers guarantee the merged set fits).
    """
    V = vcols[0].shape[0]
    cols = tuple(
        jnp.concatenate([v, c]) for v, c in zip(vcols, ccols)
    )
    pay = jnp.concatenate([jnp.zeros((V,), jnp.uint32), cpay])
    out = jax.lax.sort((*cols, pay), num_keys=len(cols) + 1,
                       is_stable=False)
    scols, sp = out[:-1], out[-1]
    tag = sp >> 31  # 1 = candidate, 0 = visited
    sent = scols[0] == SENTINEL
    for c in scols[1:]:
        sent = sent & (c == SENTINEL)
    eq = scols[0][1:] == scols[0][:-1]
    for c in scols[1:]:
        eq = eq & (c[1:] == c[:-1])
    prev_same = jnp.zeros(sp.shape, jnp.bool_).at[1:].set(eq)
    new_flag = (tag == 1) & ~sent & ~prev_same
    keep = ~sent & ((tag == 0) | new_flag)
    n_new = jnp.sum(new_flag.astype(jnp.int32))
    # blank dropped entries to SENTINEL *before* compacting: their key
    # values must not survive into the visited columns, or the table
    # silently fills with phantom duplicates
    kk = (~keep).astype(jnp.uint32)
    masked = tuple(jnp.where(keep, c, SENTINEL) for c in scols)
    vout = jax.lax.sort((kk, *masked), num_keys=1, is_stable=True)
    return tuple(c[:V] for c in vout[1:]), n_new, sp, new_flag


def compact_by_flag(drop, cols, chunk: int = 5):
    """Stable-compact value columns to the front where ``drop == 0``
    (original order preserved), without a wide multi-operand sort.

    XLA sort COMPILE time explodes superlinearly in operand count on
    the TPU tunnel backend (measured, scripts/profile.py prims: 2 ops
    12 s, 6 ops 33 s, 21 ops 245 s, 21 stable 435 s — the round-3
    append's 22-operand stable sort was 84% of the 886 s bench warmup)
    while RUN time grows sublinearly.  So: ONE u32 key ``drop << 31 |
    iota`` (all keys distinct, so an unstable single-key sort IS the
    stable (drop, original-order) sort), applied in ``chunk``-column
    value-carrying sorts.  ~4x faster compile at bench shapes for
    ~25% more sort traffic.

    Returns (compacted cols, idx) where ``idx[j]`` is the original row
    of compacted position ``j`` (valid in the kept prefix).
    """
    n = drop.shape[0]
    key = (drop.astype(jnp.uint32) << jnp.uint32(31)) | jnp.arange(
        n, dtype=jnp.uint32
    )
    outs = []
    idx = None
    for i in range(0, len(cols), chunk):
        res = jax.lax.sort(
            (key, *cols[i: i + chunk]), num_keys=1, is_stable=False
        )
        if idx is None:
            idx = (res[0] & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)
        outs.extend(res[1:])
    if idx is None:
        srt = jax.lax.sort((key,), num_keys=1, is_stable=False)
        idx = (srt[0] & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)
    return tuple(outs), idx


def _lex_less(
    a1: jax.Array, a2: jax.Array, a3: jax.Array,
    b1: jax.Array, b2: jax.Array, b3: jax.Array,
) -> jax.Array:
    """(a1,a2,a3) < (b1,b2,b3) lexicographically, unsigned."""
    return (a1 < b1) | (
        (a1 == b1) & ((a2 < b2) | ((a2 == b2) & (a3 < b3)))
    )


def sort_perm(
    invalid: jax.Array, k1: jax.Array, k2: jax.Array, k3: jax.Array
) -> jax.Array:
    """Stable permutation ordering valid lanes by key; invalid lanes last."""
    n = k1.shape[0]
    iota = jnp.arange(n, dtype=jnp.uint32)
    _, _, _, _, perm = jax.lax.sort(
        (invalid.astype(jnp.uint32), k1, k2, k3, iota),
        num_keys=4,
        is_stable=True,
    )
    return perm.astype(jnp.int32)


def bsearch_member(
    vk1: jax.Array, vk2: jax.Array, vk3: jax.Array, n_visited: jax.Array,
    q1: jax.Array, q2: jax.Array, q3: jax.Array,
) -> jax.Array:
    """Membership of queries in the sorted visited columns.  bool[N]."""
    cap = vk1.shape[0]
    nq = q1.shape[0]
    lo = jnp.zeros((nq,), jnp.int32)
    hi = jnp.full((nq,), n_visited, jnp.int32)
    for _ in range(max(1, cap.bit_length())):
        mid = (lo + hi) >> 1
        m1, m2, m3 = vk1[mid], vk2[mid], vk3[mid]
        less = _lex_less(m1, m2, m3, q1, q2, q3)
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
    at = jnp.clip(lo, 0, cap - 1)
    eq = (vk1[at] == q1) & (vk2[at] == q2) & (vk3[at] == q3)
    return (lo < n_visited) & eq


def merge_sorted(
    vk1: jax.Array, vk2: jax.Array, vk3: jax.Array,
    nk1: jax.Array, nk2: jax.Array, nk3: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Merge new key columns (sentinel-padded) into the sorted visited set.

    Returns sorted columns of size ``cap`` (callers guarantee the real keys
    fit; sentinels sort to the tail and are sliced off).
    """
    cap = vk1.shape[0]
    c1 = jnp.concatenate([vk1, nk1])
    c2 = jnp.concatenate([vk2, nk2])
    c3 = jnp.concatenate([vk3, nk3])
    s1, s2, s3 = jax.lax.sort((c1, c2, c3), num_keys=3, is_stable=False)
    return s1[:cap], s2[:cap], s3[:cap]
