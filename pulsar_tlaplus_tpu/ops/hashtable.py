"""Open-addressing visited-set hash table in HBM — the TPU-native FPSet
(SURVEY.md §2.2-E3, §7-L3).

Replaces the v0 sorted-columns + binary-search + full-merge design: a
merge re-sorts the ENTIRE visited set every chunk (O(cap log cap)), while
table probes cost O(batch * E[probes]) independent of how many states have
been visited — the difference between a per-step cost that grows with the
run and one that stays flat.

Layout: four uint32[cap + 1] columns — three key words (the 96-bit exact
or hashed dedup key from :mod:`.dedup`) plus an occupancy column.  ``cap``
is a power of two; slot ``cap`` is a write-only trash row that lanes
without work scatter into (keeps every scatter dense and branch-free).

Batched lookup-or-insert resolves races entirely on device:

1. probe round r inspects slot ``(h + r(r+1)/2) & (cap-1)`` (triangular
   probing — covers every slot when cap is a power of two);
2. lanes whose key already sits in the slot resolve as duplicates;
3. lanes seeing an empty slot bid for it with a scatter-min of their lane
   id; the unique winner writes its key (scatter-set, winner slots are
   distinct by construction);
4. losers re-read the slot: if the winner had the SAME key they resolve
   as duplicates, otherwise they continue to the next round.

The loop is a ``lax.while_loop`` — typical batches resolve in 2-4 rounds
at load factor <= 1/2 (the engine grows the table before exceeding it).
Lanes still pending after ``max_probes`` rounds are reported in the
returned failure count; the caller treats that as a hard error rather
than silently dropping states (probability ~ load^max_probes per lane).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.ops.dedup import _fmix

MAX_PROBES = 64


def empty_table(cap: int) -> Tuple[jax.Array, ...]:
    """(t1, t2, t3, occ) columns for a power-of-two ``cap``."""
    if cap & (cap - 1):
        raise ValueError(f"table capacity must be a power of two: {cap}")
    z = jnp.zeros((cap + 1,), jnp.uint32)
    return z, z, z, jnp.zeros((cap + 1,), jnp.int32)


def _slot_hash(k1: jax.Array, k2: jax.Array, k3: jax.Array) -> jax.Array:
    """Mix the three key words into a table index basis (u32)."""
    h = _fmix(k1 ^ jnp.uint32(0x9E3779B9))
    h = _fmix(h ^ k2)
    return _fmix(h ^ k3)


def lookup_insert(
    t1: jax.Array,
    t2: jax.Array,
    t3: jax.Array,
    occ: jax.Array,
    k1: jax.Array,
    k2: jax.Array,
    k3: jax.Array,
    valid: jax.Array,
    max_probes: int = MAX_PROBES,
):
    """Batched lookup-or-insert of keys into the table.

    Returns ``(is_new, t1', t2', t3', occ', n_failed)`` where ``is_new[i]``
    is True iff lane i's key was absent and this call inserted it (exactly
    one lane wins per distinct new key), and ``n_failed`` counts lanes
    still unresolved after ``max_probes`` rounds (callers must treat
    nonzero as an error — see module docstring).
    """
    cap = t1.shape[0] - 1
    nq = k1.shape[0]
    lane = jnp.arange(nq, dtype=jnp.int32)
    h = _slot_hash(k1, k2, k3)
    capm = jnp.uint32(cap - 1)

    def cond(st):
        r, pending, _is_new, _t1, _t2, _t3, _occ = st
        return (r < max_probes) & jnp.any(pending)

    def body(st):
        r, pending, is_new, t1, t2, t3, occ = st
        # triangular probe: slot_r = h + r(r+1)/2 (mod cap)
        off = (r.astype(jnp.uint32) * (r.astype(jnp.uint32) + 1)) >> 1
        slot = ((h + off) & capm).astype(jnp.int32)
        s = jnp.where(pending, slot, cap)  # parked lanes hit the trash row
        o = occ[s]
        eq = (t1[s] == k1) & (t2[s] == k2) & (t3[s] == k3)
        found = pending & (o == 1) & eq
        pending = pending & ~found
        # bid for empty slots with lane id; min wins
        bid_slot = jnp.where(pending & (o == 0), s, cap)
        claims = jnp.full((cap + 1,), nq, jnp.int32).at[bid_slot].min(lane)
        win = pending & (o == 0) & (claims[s] == lane)
        ws = jnp.where(win, s, cap)
        t1 = t1.at[ws].set(k1)
        t2 = t2.at[ws].set(k2)
        t3 = t3.at[ws].set(k3)
        occ = occ.at[ws].set(1)
        is_new = is_new | win
        pending = pending & ~win
        # same-key losers resolve against the newly written slot
        eq2 = (t1[s] == k1) & (t2[s] == k2) & (t3[s] == k3)
        pending = pending & ~((occ[s] == 1) & eq2)
        return r + 1, pending, is_new, t1, t2, t3, occ

    st = (
        jnp.int32(0),
        valid,
        jnp.zeros((nq,), jnp.bool_),
        t1,
        t2,
        t3,
        occ,
    )
    _r, pending, is_new, t1, t2, t3, occ = jax.lax.while_loop(cond, body, st)
    return is_new, t1, t2, t3, occ, jnp.sum(pending.astype(jnp.int32))


_REHASH_STEP = jax.jit(lookup_insert)


def rehash_into(
    old: Tuple[jax.Array, ...],
    new: Tuple[jax.Array, ...],
    chunk: int = 1 << 16,
):
    """Move every occupied entry of ``old`` into the (larger) ``new``
    table.  Host-driven chunked loop; returns the new columns.

    Used when the engine grows the table past load factor 1/2 — the
    hash-table analog of the sorted path's pad-and-carry growth.
    """
    t1, t2, t3, occ = old
    n1, n2, n3, nocc = new
    cap = t1.shape[0] - 1
    step = _REHASH_STEP
    for start in range(0, cap, chunk):
        sl = slice(start, min(start + chunk, cap))
        is_new, n1, n2, n3, nocc, failed = step(
            n1, n2, n3, nocc,
            t1[sl], t2[sl], t3[sl],
            occ[sl] == 1,
        )
        if int(failed):
            raise RuntimeError(
                "hash table rehash overflow — raise visited capacity"
            )
        del is_new
    return n1, n2, n3, nocc
