"""Open-addressing visited-set hash table in HBM (SURVEY.md §2.2-E3,
§7-L3) — now a thin compatibility layer over :mod:`.fpset`.

Round 6 promoted this design to the device hot path as the growable,
K-column, staged-compaction FPSet in ``ops/fpset.py`` (see its module
docstring for the probing/bidding algorithm and the discovery-order
guarantee).  Round 23 added alternative dense-tile formulations of the
flush-stage probe behind ``fpset.flush_acc(..., probe_impl=...)``
(``legacy`` | ``tile`` | ``pallas``, kernels in ``ops/tiles.py``,
arbitrated by ``cli.py tune``); all of them preserve the same
min-lane-wins discovery order.  The host-loop engines
(``engine/core.py``, ``engine/bfs.py``, ``engine/sharded.py``) keep
this module's original fixed 3-column + occupancy-column API; that
path always uses ``fpset.probe_insert``'s triangular probing and
scatter-min bidding — the impl knobs apply only to the device
engines' accumulate-then-flush path.

Layout: four uint32[cap + 1] columns — three key words plus an
occupancy column.  ``cap`` is a power of two; slot ``cap`` is the
write-only trash row that parked lanes scatter into.  Batched
lookup-or-insert resolves races entirely on device; lanes still pending
after ``max_probes`` rounds are reported in the returned failure count
(callers treat nonzero as a hard error, never a silent drop).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.ops import fpset

MAX_PROBES = fpset.MAX_PROBES


def empty_table(cap: int) -> Tuple[jax.Array, ...]:
    """(t1, t2, t3, occ) columns for a power-of-two ``cap``."""
    if cap & (cap - 1):
        raise ValueError(f"table capacity must be a power of two: {cap}")
    z = jnp.zeros((cap + 1,), jnp.uint32)
    return z, z, z, jnp.zeros((cap + 1,), jnp.int32)


def _slot_hash(k1: jax.Array, k2: jax.Array, k3: jax.Array) -> jax.Array:
    """Mix the three key words into a table index basis (u32)."""
    return fpset.slot_hash((k1, k2, k3))


def lookup_insert(
    t1: jax.Array,
    t2: jax.Array,
    t3: jax.Array,
    occ: jax.Array,
    k1: jax.Array,
    k2: jax.Array,
    k3: jax.Array,
    valid: jax.Array,
    max_probes: int = MAX_PROBES,
):
    """Batched lookup-or-insert of keys into the table.

    Returns ``(is_new, t1', t2', t3', occ', n_failed)`` where ``is_new[i]``
    is True iff lane i's key was absent and this call inserted it (exactly
    one lane wins per distinct new key — the minimum lane id), and
    ``n_failed`` counts lanes still unresolved after ``max_probes`` rounds
    (callers must treat nonzero as an error — see module docstring).
    """
    is_new, (t1, t2, t3), occ, pending, _rounds = fpset.probe_insert(
        (t1, t2, t3), (k1, k2, k3), valid, occ=occ,
        max_probes=max_probes,
    )
    return is_new, t1, t2, t3, occ, jnp.sum(pending.astype(jnp.int32))


_REHASH_STEP = jax.jit(lookup_insert)


def rehash_into(
    old: Tuple[jax.Array, ...],
    new: Tuple[jax.Array, ...],
    chunk: int = 1 << 16,
):
    """Move every occupied entry of ``old`` into the (larger) ``new``
    table.  Host-driven chunked loop; returns the new columns.

    Used when the engine grows the table past load factor 1/2 — the
    hash-table analog of the sorted path's pad-and-carry growth.
    """
    t1, t2, t3, occ = old
    n1, n2, n3, nocc = new
    cap = t1.shape[0] - 1
    step = _REHASH_STEP
    for start in range(0, cap, chunk):
        sl = slice(start, min(start + chunk, cap))
        is_new, n1, n2, n3, nocc, failed = step(
            n1, n2, n3, nocc,
            t1[sl], t2[sl], t3[sl],
            occ[sl] == 1,
        )
        if int(failed):
            raise RuntimeError(
                "hash table rehash overflow — raise visited capacity"
            )
        del is_new
    return n1, n2, n3, nocc
