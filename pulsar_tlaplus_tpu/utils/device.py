"""Small device-interaction helpers shared by the engines."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def drain(out) -> None:
    """True completion barrier for a dispatched computation.

    ``block_until_ready`` is unreliable on the tunnel backend (it can
    return at enqueue time), so the only dependable barrier is a host
    fetch of one element of one output leaf (~130 ms tunnel RTT).
    Engines use this for warmup sequencing and stage timing — never on
    the hot path.
    """
    leaf = jax.tree.leaves(out)[0]
    np.asarray(jnp.ravel(leaf)[0])
