"""Shared checkpoint-frame layer — the run-survivability substrate.

TLC's killer production feature is that a week-long run survives
crashes via its ``states/`` checkpoint directory.  This module is the
engine-agnostic half of that story for the JAX engines: an atomic
``tmp + os.replace`` npz frame with a config signature, a format
version, a compacted-occupancy codec for hash-table (fpset) visited
sets, and a preemption watcher that turns SIGTERM/SIGINT into a
"checkpoint at the next level boundary" request (the TPU-VM
preemption contract).

Design rules every engine follows:

- **Atomicity**: a frame is written to a per-writer-unique
  ``<path>.tmp.<pid>.<tid>.npz`` and ``os.replace``d over the target,
  so a crash mid-write can never leave a half-frame where a resumable
  one used to be — and two writers racing on one path (a job handed
  between daemon scheduling slices) each publish a complete frame,
  never each other's half-filled tmp.
- **Signature**: every frame embeds a config signature (model hash,
  invariant set, key geometry, visited impl, engine format revision).
  ``load_frame`` refuses a frame written under a different
  configuration with a clean error — two specs can never silently
  resume each other's state.
- **Format version**: frames carry ``__format__``; readers accept
  every version up to :data:`FORMAT_VERSION` (v1 frames predate the
  field and the compacted fpset codec; they still load).
- **Compacted fpset occupancy** (:func:`pack_fpset` /
  :func:`unpack_fpset`): hash-table occupancy is scattered across the
  table, so full-column snapshots carry mostly SENTINEL runs.  The
  compacted codec stores only the occupied slots (keys + slot index)
  — frame size scales with the *state count*, not the table tier.
- **Hardened writer**: a transient ``OSError`` (disk full, EIO, an
  NFS hiccup) retries with bounded exponential backoff instead of
  killing an hours-long run over one bad write; the retry count comes
  back to the caller (the ``ckpt_retries`` telemetry breadcrumb).
  Stale ``<path>.tmp.*.npz`` left by a crash mid-write is removed at
  run start (:func:`cleanup_stale_tmp`, scoped to the one frame path
  so sibling jobs sharing a checkpoint dir are never touched) — the
  atomic ``os.replace`` already guarantees it never shadows a valid
  frame, but a dead multi-GB temp file must not squat the checkpoint
  volume either.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from pulsar_tlaplus_tpu.utils import faults

# v1: full-column fpset snapshots, no version field (round-4/6 sharded
# frames).  v2: ``__format__`` field + compacted-occupancy fpset codec
# + the device_bfs frame layout.  Readers accept <= FORMAT_VERSION.
FORMAT_VERSION = 2

_SENTINEL = np.uint32(0xFFFFFFFF)


def config_sig(**fields) -> str:
    """Canonical signature string from keyword fields (sorted, so two
    call sites building the same logical config always agree)."""
    return repr(tuple(sorted((k, repr(v)) for k, v in fields.items())))


# bounded retry-with-backoff for transient frame-write failures: a
# week-long run must not die because one write hit a full/flaky disk.
# MAX_WRITE_RETRIES retries (so MAX+1 attempts) with exponential
# backoff starting at WRITE_BACKOFF_S; a persistent error still raises.
MAX_WRITE_RETRIES = 3
WRITE_BACKOFF_S = 0.05


def save_frame(
    path: str, sig: str, arrays: Dict[str, np.ndarray],
    wall_s: float = 0.0,
    meta: Optional[Dict[str, object]] = None,
) -> Tuple[int, float, int]:
    """Write one checkpoint frame atomically; returns ``(nbytes,
    write_s, retries)`` — size, the frame-write stall time the caller
    was blocked here (the ``ckpt_write_s`` telemetry counter:
    compression + fsync-adjacent filesystem time, NOT the D2H gather,
    which engines time on their side), and how many transient-failure
    retries the write needed (0 on the happy path; the ``ckpt_retries``
    breadcrumb).  ``sig`` is the writer's config signature (verified by
    :func:`load_frame`); ``wall_s`` the cumulative run wall time so a
    resumed run's states/sec stays meaningful end to end.  ``meta`` is
    an optional small JSON-able dict (writer run_id, frame_seq, level)
    stored under ``__meta__`` — read back with :func:`frame_meta`; v2
    frames without it still load.

    Transient ``OSError`` (disk full, EIO) retries with bounded
    exponential backoff; only a persistent failure propagates.  The
    ``PTT_FAULT=ckpt_fail@frame:N`` injection raises a synthetic
    ENOSPC on frame N's first attempt, exercising exactly this path.

    The tmp name is unique per writer (pid + thread id): two writers
    racing on one path — a job handed between daemon slices, a
    split-brain daemon pair — each publish a COMPLETE frame through
    their own tmp, so ``os.replace`` can never install a half-written
    file another writer was still filling (last complete write wins)."""
    t0 = time.perf_counter()
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}.npz"
    extra = {}
    if meta:
        extra["__meta__"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        )
    inject = meta is not None and meta.get(
        "frame_seq"
    ) is not None and "ckpt_fail" in faults.poll(
        "frame", int(meta["frame_seq"])
    )
    retries = 0
    while True:
        try:
            if inject:
                inject = False  # transient: only the first attempt
                raise OSError(
                    28,
                    "No space left on device "
                    "(injected fault ckpt_fail, PTT_FAULT)",
                )
            np.savez_compressed(
                tmp,
                __format__=np.int64(FORMAT_VERSION),
                sig=np.frombuffer(sig.encode(), dtype=np.uint8),
                wall_s=np.float64(wall_s),
                **extra,
                **arrays,
            )
            nbytes = os.path.getsize(tmp)
            os.replace(tmp, path)  # atomic vs crashes + readers
            return nbytes, time.perf_counter() - t0, retries
        except OSError:
            # a half-written tmp from the failed attempt must not
            # linger (and on ENOSPC, freeing it is what lets the
            # retry succeed)
            try:
                os.remove(tmp)
            except OSError:
                pass
            if retries >= MAX_WRITE_RETRIES:
                raise
            time.sleep(WRITE_BACKOFF_S * (1 << retries))
            retries += 1


def cleanup_stale_tmp(path: Optional[str]) -> bool:
    """Remove stale ``<path>.tmp.*.npz`` temps (and the pre-r11 fixed
    ``<path>.tmp.npz`` name) left by a crash mid-write — engines call
    this at run start.  The atomic ``os.replace`` already guarantees a
    tmp never shadows a valid frame; this is disk hygiene — a dead
    multi-GB temp must not squat the checkpoint volume.  Scoped to
    THIS frame path only: sibling frames sharing the directory (other
    jobs' run_ids in a service checkpoint dir) are never touched.
    Returns True when something was removed."""
    if not path:
        return False
    d, base = os.path.split(path)
    prefix = base + ".tmp."
    removed = False
    try:
        names = os.listdir(d or ".")
    except OSError:
        return False
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".npz")):
            continue
        try:
            os.remove(os.path.join(d, name))
            removed = True
        except OSError:
            pass
    return removed


def frame_meta(d) -> Dict[str, object]:
    """Writer metadata of a loaded frame (``{}`` for frames that
    predate the field or carry none)."""
    if "__meta__" not in d:
        return {}
    try:
        return json.loads(d["__meta__"].tobytes().decode())
    except (ValueError, AttributeError):
        return {}


def load_frame(path: str, sig: str, what: str = "configuration"):
    """Open a frame, verify format + signature, return the npz dict.

    A file that isn't a frame (arbitrary npz, truncated write,
    pre-frame formats) fails with one clean "unrecognized checkpoint
    format" error rather than a raw KeyError/zipfile error; a missing
    file raises FileNotFoundError untouched (callers distinguish
    "nothing to resume" from "corrupt").
    """
    try:
        d = np.load(path)
        frame_sig = d["sig"].tobytes().decode()
        version = int(d["__format__"]) if "__format__" in d else 1
    except FileNotFoundError:
        raise  # a missing file is not a format problem
    except Exception as e:  # noqa: BLE001
        raise ValueError(
            f"unrecognized checkpoint format at {path!r} — not written "
            f"by this engine ({type(e).__name__}: {e})"
        ) from e
    if version > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint frame format v{version} is newer than this "
            f"build supports (v{FORMAT_VERSION}); upgrade to resume it"
        )
    if frame_sig != sig:
        raise ValueError(f"checkpoint was written by a different {what}")
    return d


# ------------------------------------------------- fpset frame codec


def pack_fpset(
    cols: Sequence[np.ndarray], prefix: str = "fp"
) -> Dict[str, np.ndarray]:
    """Compacted-occupancy snapshot of fpset key columns.

    ``cols`` are K uint32 columns of ``cap + 1`` slots (the trailing
    trash row is dropped), either 1-D (single device) or 2-D
    ``[N, cap + 1]`` (one row per shard).  Only occupied (non-all-
    SENTINEL) slots are stored: their keys per column plus the slot
    index, with per-shard counts so ragged occupancy round-trips.
    """
    cs = [np.asarray(c, np.uint32) for c in cols]
    ndim = cs[0].ndim
    if ndim == 1:
        cs = [c[None, :] for c in cs]
    cap = cs[0].shape[1] - 1
    body = [c[:, :cap] for c in cs]
    empty = body[0] == _SENTINEL
    for b in body[1:]:
        empty &= b == _SENTINEL
    occ = ~empty
    out: Dict[str, np.ndarray] = {
        f"{prefix}_tcap": np.int64(cap),
        f"{prefix}_ndim": np.int64(ndim),
    }
    keys = [[] for _ in cs]
    slots = []
    cnts = []
    for s in range(cs[0].shape[0]):
        idx = np.flatnonzero(occ[s])
        cnts.append(len(idx))
        slots.append(idx.astype(np.int64))
        for i, b in enumerate(body):
            keys[i].append(b[s][idx])
    out[f"{prefix}_cnt"] = np.asarray(cnts, np.int64)
    out[f"{prefix}_slot"] = (
        np.concatenate(slots) if slots else np.zeros((0,), np.int64)
    )
    for i, k in enumerate(keys):
        out[f"{prefix}k{i}"] = (
            np.concatenate(k) if k else np.zeros((0,), np.uint32)
        )
    return out


def unpack_fpset(
    d, ncols: int, prefix: str = "fp"
) -> Tuple[np.ndarray, ...]:
    """Rebuild full fpset columns (SENTINEL-filled, occupied slots
    scattered back, trash row restored) from a :func:`pack_fpset`
    frame.  Returns numpy arrays shaped exactly as saved (1-D or
    ``[N, cap + 1]``); callers device_put them."""
    cap = int(d[f"{prefix}_tcap"])
    ndim = int(d[f"{prefix}_ndim"])
    cnts = np.asarray(d[f"{prefix}_cnt"], np.int64)
    slots = np.asarray(d[f"{prefix}_slot"], np.int64)
    n_shards = len(cnts)
    cols = tuple(
        np.full((n_shards, cap + 1), _SENTINEL, np.uint32)
        for _ in range(ncols)
    )
    off = 0
    for s in range(n_shards):
        n = int(cnts[s])
        sl = slots[off: off + n]
        for i in range(ncols):
            cols[i][s, sl] = np.asarray(
                d[f"{prefix}k{i}"][off: off + n], np.uint32
            )
        off += n
    if ndim == 1:
        cols = tuple(c[0] for c in cols)
    return cols


# --------------------------------------------- preemption-safe stops


class PreemptionWatcher:
    """SIGTERM/SIGINT -> "checkpoint at the next level boundary".

    The TPU-VM preemption contract delivers SIGTERM with a short grace
    window; an operator Ctrl-C deserves the same survivable exit.  The
    first signal only sets :attr:`requested` — the engine finishes the
    level it is on, writes a resumable frame, and returns a truncated
    result with ``stop_reason="preempted"``.  A second SIGINT raises
    KeyboardInterrupt immediately (the operator insists).

    Usable as a context manager; installs handlers only when
    ``enabled`` and on the main thread (signal handlers cannot be set
    elsewhere — a checker driven from a worker thread simply runs
    without preemption capture).
    """

    def __init__(self, enabled: bool = True, log=None):
        self.enabled = enabled
        self.requested = False
        self._log = log
        self._prev: Dict[int, object] = {}
        self._installed = False

    def _handle(self, signum, frame):
        if self.requested and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self.requested = True
        name = signal.Signals(signum).name
        msg = (
            f"{name} received: checkpointing at the next level "
            "boundary, then exiting resumably"
        )
        if self._log is not None:
            self._log(msg)
        else:
            import sys

            print(f"  {msg}", file=sys.stderr, flush=True)

    def __enter__(self):
        if (
            self.enabled
            and threading.current_thread() is threading.main_thread()
        ):
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._prev[sig] = signal.signal(sig, self._handle)
                except (ValueError, OSError):  # non-main thread/races
                    break
            else:
                self._installed = True
        return self

    def __exit__(self, *exc):
        if self._installed:
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass
            self._installed = False
        return False
