"""Disk cache of compiled TPU executables — cross-process AOT warmup.

The JAX persistent compilation cache does not capture executables for
the tunnel TPU backend (verified round 4: only CPU-suite entries ever
appear in ``.jax_cache``), so every process historically paid the full
compile warmup — 440-820 s at bench shapes, for a ~30 s run
(VERDICT r4 weak #2).  What DOES work on this backend (verified round
5, see BASELINE.md) is `jax.experimental.serialize_executable`:
a ``Compiled`` serialized in one process deserializes and executes
correctly in a fresh process, donation semantics included.

``ajit(fn, **jit_kwargs)`` is a drop-in replacement for
``jax.jit(fn, **jit_kwargs)``:

- on CPU (the virtual-mesh test suite) or with ``PTT_AOT=0`` it is
  exactly ``jax.jit`` — the persistent cache already covers CPU;
- on an accelerator backend, each distinct argument-shape signature is
  lowered once, keyed by a hash of the lowered StableHLO (+ jax
  version + device kind), and the compiled executable is pickled to
  ``PTT_AOT_DIR`` (default ``~/.ptt_aot_cache``).  A later process
  whose lowering hashes identically loads the executable instead of
  compiling — measured: the bench warmup drops from ~440-820 s to the
  trace+lower+load time.

Robustness: serialize/deserialize failures fall back to the normal
jit path (the cache is an optimization, never a correctness
dependency), and a deserialized executable is verified by its first
call — a runtime rejection recompiles in-process.

Hardening (ADVICE r5): the cache entries are pickles, and unpickling
attacker-controlled bytes executes arbitrary code.  So (a) the cache
directory is created 0o700 and the cache refuses to load OR store when
the directory is owned by another uid or writable by group/other,
(b) every entry embeds a SHA-256 digest of the pickled payload that is
verified BEFORE unpickling (rejects truncation/corruption and casual
tampering), and (c) the cache key folds in compile-affecting
environment (``XLA_FLAGS``, ``LIBTPU_INIT_ARGS``, ``JAX_ENABLE_X64``)
so changing those between runs can never load a stale executable
compiled under different options.

Bounded size (r11): the cache is capped (``PTT_AOT_MAX_BYTES``,
default 8 GiB) with mtime-LRU eviction after every store — loads
touch their entry, so a resident checker daemon's warmed registry
stays hot while stale experiments age out.  ``cli.py cache`` is the
operator inspector (``--stats`` / ``--clear``).
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import jax

# entry layout: magic + sha256(payload) + payload (a pickled
# (serialized_executable, in_tree, out_tree) tuple).  Bump the magic on
# any format change — old entries then fail verification and recompile.
_MAGIC = b"PTTAOTX2"

# compile-affecting environment folded into the cache key (ADVICE r5:
# XLA_FLAGS changes must never load a stale executable)
_COMPILE_ENV = ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "JAX_ENABLE_X64")

# size cap with LRU eviction (r11): a resident daemon warming four
# specs across capacity tiers writes hundreds of entries; the cache
# must not grow unboundedly.  mtime is the recency signal — loads
# touch their entry (os.utime) so a warm daemon's working set stays
# resident while one-off experiments age out.  Override with
# PTT_AOT_MAX_BYTES (0 disables eviction).
DEFAULT_MAX_BYTES = 8 << 30


def max_bytes() -> int:
    try:
        return int(
            os.environ.get("PTT_AOT_MAX_BYTES", DEFAULT_MAX_BYTES)
        )
    except ValueError:
        return DEFAULT_MAX_BYTES


def _cache_dir() -> str:
    return os.environ.get(
        "PTT_AOT_DIR", os.path.expanduser("~/.ptt_aot_cache")
    )


_DIR_TRUSTED: Optional[bool] = None


def _dir_trusted() -> bool:
    """Create the cache dir 0o700 and verify it is exclusively ours
    (owned by this uid, not group/other-writable) before any pickle
    crosses it.  Resolved once per process; an untrusted directory
    disables the cache (one stderr note), it never raises."""
    global _DIR_TRUSTED
    if _DIR_TRUSTED is not None:
        return _DIR_TRUSTED
    d = _cache_dir()
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        stat = os.stat(d)
        uid_ok = not hasattr(os, "getuid") or stat.st_uid == os.getuid()
        _DIR_TRUSTED = bool(uid_ok and not (stat.st_mode & 0o022))
    except OSError:
        _DIR_TRUSTED = False
    if not _DIR_TRUSTED:
        import sys

        print(
            f"note: AOT executable cache disabled: {d!r} is not an "
            "exclusively-owned 0o700 directory (loading pickled "
            "executables from a shared directory would be unsafe)",
            file=sys.stderr,
        )
    return _DIR_TRUSTED


_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """Resolved once per process (the answer cannot change mid-run and
    this sits on every hot-path dispatch)."""
    global _ENABLED
    if _ENABLED is None:
        flag = os.environ.get("PTT_AOT", "")
        if flag == "0":
            _ENABLED = False
        elif flag == "1":
            _ENABLED = True
        else:
            # default: on for accelerator backends only (CPU uses the
            # normal JAX persistent cache, and the test suite's tiny
            # programs would pay lower+hash overhead for nothing)
            try:
                _ENABLED = jax.default_backend() not in ("cpu",)
            except Exception:  # noqa: BLE001
                _ENABLED = False
    return _ENABLED


def _key_of(lowered) -> str:
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    h.update(jax.__version__.encode())
    for name in _COMPILE_ENV:
        # compile-affecting env must shape the key: two processes with
        # different XLA_FLAGS would otherwise share entries and the
        # second would silently run under the first one's options
        h.update(f"{name}={os.environ.get(name, '')}\x00".encode())
    try:
        import jaxlib

        # jax and jaxlib/runtime version independently; a runtime
        # upgrade must invalidate serialized executables
        h.update(getattr(jaxlib, "__version__", "?").encode())
    except Exception:  # noqa: BLE001
        pass
    try:
        dev = jax.devices()[0]
        h.update(str(dev.device_kind).encode())
        h.update(str(dev.client.platform_version).encode())
        h.update(str(jax.device_count()).encode())
    except Exception:  # noqa: BLE001
        pass
    return h.hexdigest()


def _load(path: str):
    from jax.experimental import serialize_executable as se

    with open(path, "rb") as fh:
        raw = fh.read()
    hlen = len(_MAGIC) + 32
    if len(raw) < hlen or not raw.startswith(_MAGIC):
        raise ValueError("unrecognized AOT cache entry format")
    digest, blob = raw[len(_MAGIC): hlen], raw[hlen:]
    # verify BEFORE unpickling: a truncated/corrupted/tampered entry
    # must never reach pickle.loads (see module docstring)
    if hashlib.sha256(blob).digest() != digest:
        raise ValueError("AOT cache entry failed digest verification")
    payload, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


def _entries():
    """(path, size, mtime) for every ``*.aotx`` entry, oldest first.
    Unreadable entries (racing eviction/writers) are skipped."""
    d = _cache_dir()
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.endswith(".aotx"):
            continue
        p = os.path.join(d, name)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out.append((p, st.st_size, st.st_mtime))
    out.sort(key=lambda e: e[2])
    return out


def stats() -> Dict[str, object]:
    """Cache inspector view: entry count, byte total, age span, cap.
    Never raises — a missing directory is an empty cache."""
    es = _entries()
    return {
        "dir": _cache_dir(),
        "entries": len(es),
        "bytes": sum(s for _p, s, _m in es),
        "max_bytes": max_bytes(),
        "oldest_mtime": es[0][2] if es else None,
        "newest_mtime": es[-1][2] if es else None,
    }


def clear() -> Tuple[int, int]:
    """Delete every cache entry; returns (entries_removed, bytes)."""
    n = b = 0
    for p, size, _m in _entries():
        try:
            os.unlink(p)
            n += 1
            b += size
        except OSError:
            pass
    return n, b


def enforce_cap(cap: Optional[int] = None) -> Tuple[int, int]:
    """Evict least-recently-used entries (mtime order — loads touch
    their entry) until the cache fits ``cap`` bytes (default
    :func:`max_bytes`); returns (entries_evicted, bytes_evicted).
    A cap of 0 (or negative) disables eviction.  Called after every
    store, so a resident daemon warming the whole registry converges
    to the cap instead of growing forever."""
    cap = max_bytes() if cap is None else cap
    if cap <= 0:
        return 0, 0
    es = _entries()
    total = sum(s for _p, s, _m in es)
    n = b = 0
    for p, size, _m in es:
        if total <= cap:
            break
        try:
            os.unlink(p)
        except OSError:
            continue
        total -= size
        n += 1
        b += size
    return n, b


def _store(path: str, compiled) -> None:
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    blob = pickle.dumps((payload, in_tree, out_tree))
    tmp = f"{path}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(hashlib.sha256(blob).digest())
        fh.write(blob)
    os.replace(tmp, path)  # atomic vs concurrent writers


class _AJit:
    """jit wrapper that routes stable-shape calls through disk-cached
    compiled executables.  One ``Compiled`` per argument signature;
    signatures are expected to be stable per capacity tier (the
    engines re-create wrappers per tier)."""

    def __init__(self, fn, **jit_kwargs):
        self._jit = jax.jit(fn, **jit_kwargs)
        self._compiled: Dict[tuple, Any] = {}
        # per-signature: one bad cached entry must not bypass verified
        # executables already loaded for other signatures of this jit
        self._fallback_sigs: set = set()
        self._donates = bool(
            jit_kwargs.get("donate_argnums")
            or jit_kwargs.get("donate_argnames")
        )
        self._paths: Dict[tuple, str] = {}
        # surfaced for telemetry: "hit" | "compile" per signature
        self.events: Dict[tuple, str] = {}

    def _sig(self, args) -> Optional[tuple]:
        sig = []
        for leaf in jax.tree_util.tree_leaves(args):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                return None  # python scalar etc. — don't risk it
            wt = bool(getattr(leaf, "weak_type", False))
            sig.append((tuple(shape), str(dtype), wt))
        return tuple(sig)

    def _build(self, sig, args):
        lowered = self._jit.lower(*args)
        trusted = _dir_trusted()
        key = _key_of(lowered)
        path = os.path.join(_cache_dir(), f"{key}.aotx")
        if trusted and os.path.exists(path):
            try:
                comp = _load(path)
                self.events[sig] = "hit"
                self._paths[sig] = path
                try:
                    # refresh recency: a loaded entry is in use, so
                    # LRU eviction must not see it as cold
                    os.utime(path)
                except OSError:
                    pass
                return comp
            except Exception as e:  # noqa: BLE001
                # digest-mismatch / truncated / unpicklable /
                # incompatible entry: a cache miss, never a crash — a
                # corrupt cache must not kill a run.  Delete the bad
                # entry so no later process trips over it either.
                import sys

                print(
                    f"note: AOT cache entry {os.path.basename(path)!r} "
                    f"is unusable ({type(e).__name__}: {e}); deleting "
                    "and recompiling",
                    file=sys.stderr,
                )
                try:
                    os.unlink(path)
                except OSError:
                    pass
        comp = lowered.compile()
        self.events[sig] = "compile"
        comp._ptt_verified = True  # freshly compiled, nothing to verify
        if trusted:
            try:
                _store(path, comp)
                enforce_cap()
            except Exception:  # noqa: BLE001
                pass  # serialization unsupported: still usable in-process
        return comp

    def __call__(self, *args):
        if not enabled():
            return self._jit(*args)
        sig = self._sig(args)
        if sig is None or sig in self._fallback_sigs:
            return self._jit(*args)
        comp = self._compiled.get(sig)
        if comp is None:
            try:
                comp = self._build(sig, args)
            except Exception:  # noqa: BLE001
                # lowering/compile through the AOT path failed — never
                # let the cache break the engine
                self._fallback_sigs.add(sig)
                return self._jit(*args)
            self._compiled[sig] = comp
        if getattr(comp, "_ptt_verified", False):
            return comp(*args)
        try:
            out = comp(*args)
        except Exception:  # noqa: BLE001
            self._compiled.pop(sig, None)
            self._fallback_sigs.add(sig)
            # a deserialized entry the runtime rejects would crash every
            # future process too — remove it so the next run recompiles
            # (the cache must never become a correctness dependency)
            path = self._paths.pop(sig, None)
            if path is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if self._donates:
                # the failed dispatch may already have consumed the
                # donated inputs; a retry would raise a misleading
                # "Array has been deleted" and mask the real error
                raise
            return self._jit(*args)
        comp._ptt_verified = True
        return out


def ajit(fn, **jit_kwargs) -> _AJit:
    """Drop-in ``jax.jit`` replacement with cross-process executable
    caching (see module docstring)."""
    return _AJit(fn, **jit_kwargs)
