"""Disk cache of compiled TPU executables — cross-process AOT warmup.

The JAX persistent compilation cache does not capture executables for
the tunnel TPU backend (verified round 4: only CPU-suite entries ever
appear in ``.jax_cache``), so every process historically paid the full
compile warmup — 440-820 s at bench shapes, for a ~30 s run
(VERDICT r4 weak #2).  What DOES work on this backend (verified round
5, see BASELINE.md) is `jax.experimental.serialize_executable`:
a ``Compiled`` serialized in one process deserializes and executes
correctly in a fresh process, donation semantics included.

``ajit(fn, **jit_kwargs)`` is a drop-in replacement for
``jax.jit(fn, **jit_kwargs)``:

- on CPU (the virtual-mesh test suite) or with ``PTT_AOT=0`` it is
  exactly ``jax.jit`` — the persistent cache already covers CPU;
- on an accelerator backend, each distinct argument-shape signature is
  lowered once, keyed by a hash of the lowered StableHLO (+ jax
  version + device kind), and the compiled executable is pickled to
  ``PTT_AOT_DIR`` (default ``~/.ptt_aot_cache``).  A later process
  whose lowering hashes identically loads the executable instead of
  compiling — measured: the bench warmup drops from ~440-820 s to the
  trace+lower+load time.

Robustness: serialize/deserialize failures fall back to the normal
jit path (the cache is an optimization, never a correctness
dependency), and a deserialized executable is verified by its first
call — a runtime rejection recompiles in-process.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, Optional

import jax


def _cache_dir() -> str:
    return os.environ.get(
        "PTT_AOT_DIR", os.path.expanduser("~/.ptt_aot_cache")
    )


_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """Resolved once per process (the answer cannot change mid-run and
    this sits on every hot-path dispatch)."""
    global _ENABLED
    if _ENABLED is None:
        flag = os.environ.get("PTT_AOT", "")
        if flag == "0":
            _ENABLED = False
        elif flag == "1":
            _ENABLED = True
        else:
            # default: on for accelerator backends only (CPU uses the
            # normal JAX persistent cache, and the test suite's tiny
            # programs would pay lower+hash overhead for nothing)
            try:
                _ENABLED = jax.default_backend() not in ("cpu",)
            except Exception:  # noqa: BLE001
                _ENABLED = False
    return _ENABLED


def _key_of(lowered) -> str:
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    h.update(jax.__version__.encode())
    try:
        import jaxlib

        # jax and jaxlib/runtime version independently; a runtime
        # upgrade must invalidate serialized executables
        h.update(getattr(jaxlib, "__version__", "?").encode())
    except Exception:  # noqa: BLE001
        pass
    try:
        dev = jax.devices()[0]
        h.update(str(dev.device_kind).encode())
        h.update(str(dev.client.platform_version).encode())
        h.update(str(jax.device_count()).encode())
    except Exception:  # noqa: BLE001
        pass
    return h.hexdigest()


def _load(path: str):
    from jax.experimental import serialize_executable as se

    with open(path, "rb") as fh:
        payload, in_tree, out_tree = pickle.load(fh)
    return se.deserialize_and_load(payload, in_tree, out_tree)


def _store(path: str, compiled) -> None:
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        pickle.dump((payload, in_tree, out_tree), fh)
    os.replace(tmp, path)  # atomic vs concurrent writers


class _AJit:
    """jit wrapper that routes stable-shape calls through disk-cached
    compiled executables.  One ``Compiled`` per argument signature;
    signatures are expected to be stable per capacity tier (the
    engines re-create wrappers per tier)."""

    def __init__(self, fn, **jit_kwargs):
        self._jit = jax.jit(fn, **jit_kwargs)
        self._compiled: Dict[tuple, Any] = {}
        # per-signature: one bad cached entry must not bypass verified
        # executables already loaded for other signatures of this jit
        self._fallback_sigs: set = set()
        self._donates = bool(
            jit_kwargs.get("donate_argnums")
            or jit_kwargs.get("donate_argnames")
        )
        self._paths: Dict[tuple, str] = {}
        # surfaced for telemetry: "hit" | "compile" per signature
        self.events: Dict[tuple, str] = {}

    def _sig(self, args) -> Optional[tuple]:
        sig = []
        for leaf in jax.tree_util.tree_leaves(args):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                return None  # python scalar etc. — don't risk it
            wt = bool(getattr(leaf, "weak_type", False))
            sig.append((tuple(shape), str(dtype), wt))
        return tuple(sig)

    def _build(self, sig, args):
        lowered = self._jit.lower(*args)
        key = _key_of(lowered)
        path = os.path.join(_cache_dir(), f"{key}.aotx")
        if os.path.exists(path):
            try:
                comp = _load(path)
                self.events[sig] = "hit"
                self._paths[sig] = path
                return comp
            except Exception:  # noqa: BLE001
                pass  # stale/incompatible entry: recompile below
        comp = lowered.compile()
        self.events[sig] = "compile"
        comp._ptt_verified = True  # freshly compiled, nothing to verify
        try:
            _store(path, comp)
        except Exception:  # noqa: BLE001
            pass  # serialization unsupported: still usable in-process
        return comp

    def __call__(self, *args):
        if not enabled():
            return self._jit(*args)
        sig = self._sig(args)
        if sig is None or sig in self._fallback_sigs:
            return self._jit(*args)
        comp = self._compiled.get(sig)
        if comp is None:
            try:
                comp = self._build(sig, args)
            except Exception:  # noqa: BLE001
                # lowering/compile through the AOT path failed — never
                # let the cache break the engine
                self._fallback_sigs.add(sig)
                return self._jit(*args)
            self._compiled[sig] = comp
        if getattr(comp, "_ptt_verified", False):
            return comp(*args)
        try:
            out = comp(*args)
        except Exception:  # noqa: BLE001
            self._compiled.pop(sig, None)
            self._fallback_sigs.add(sig)
            # a deserialized entry the runtime rejects would crash every
            # future process too — remove it so the next run recompiles
            # (the cache must never become a correctness dependency)
            path = self._paths.pop(sig, None)
            if path is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if self._donates:
                # the failed dispatch may already have consumed the
                # donated inputs; a retry would raise a misleading
                # "Array has been deleted" and mask the real error
                raise
            return self._jit(*args)
        comp._ptt_verified = True
        return out


def ajit(fn, **jit_kwargs) -> _AJit:
    """Drop-in ``jax.jit`` replacement with cross-process executable
    caching (see module docstring)."""
    return _AJit(fn, **jit_kwargs)
