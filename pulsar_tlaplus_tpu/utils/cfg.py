"""TLC model-configuration (.cfg) front end (SURVEY.md §1-L4).

Parses the TLC config grammar subset the reference exercises
(``compaction.cfg``): two ``CONSTANTS`` blocks (value bindings and
model-value self-bindings), ``SPECIFICATION``, and ``INVARIANTS``
(``compaction.cfg:2-31``), with ``\\*`` comments.

Constant canonicalization: the spec reserves 0 for NullKey/NullValue and
``ASSUME``s ``KeySpace \\in SUBSET Nat`` (compaction.tla:29-32), but the
shipped cfg binds strings (``{"key1", "key2"}`` at compaction.cfg:7) — a
strict evaluator rejects that (SURVEY.md §1-L4 discrepancy).  Like the
intent of the spec's own encoding, non-integer space elements are interned
to ``1..n`` with a warning; integer spaces are required to be exactly
``1..n`` (the packed encoding relabels any gap-free positive set).
"""

from __future__ import annotations

import re
import warnings
from dataclasses import dataclass, field
from typing import Dict, List

from pulsar_tlaplus_tpu.ref.pyeval import Constants


@dataclass
class TLCConfig:
    constants: Dict[str, object] = field(default_factory=dict)
    model_values: List[str] = field(default_factory=list)
    specification: str = "Spec"
    invariants: List[str] = field(default_factory=list)
    properties: List[str] = field(default_factory=list)


def _strip_comments(text: str) -> str:
    # \* line comments and (* ... *) block comments (not nested in cfgs)
    text = re.sub(r"\\\*.*", "", text)
    text = re.sub(r"\(\*.*?\*\)", "", text, flags=re.S)
    return text


def _parse_value(tok: str):
    tok = tok.strip()
    if tok == "TRUE":
        return True
    if tok == "FALSE":
        return False
    if re.fullmatch(r"-?\d+", tok):
        return int(tok)
    if tok.startswith("{"):
        inner = tok.strip("{}").strip()
        if not inner:
            return frozenset()
        return frozenset(_parse_value(p) for p in inner.split(","))
    if tok.startswith('"') and tok.endswith('"'):
        return tok[1:-1]
    return tok  # identifier / model value


def parse_cfg(text: str) -> TLCConfig:
    cfg = TLCConfig()
    text = _strip_comments(text)
    # tokenize into sections
    section = None
    # assignments may span lines; normalize whitespace, then split on
    # keywords
    tokens = re.split(
        r"\b(CONSTANTS?|SPECIFICATION|INVARIANTS?|PROPERTIES|INIT|NEXT)\b", text
    )
    i = 1
    while i < len(tokens):
        kw, body = tokens[i], tokens[i + 1] if i + 1 < len(tokens) else ""
        i += 2
        if kw.startswith("CONSTANT"):
            for m in re.finditer(
                r"([A-Za-z_]\w*)\s*=\s*(\{[^}]*\}|\"[^\"]*\"|[^,\s]+)", body
            ):
                name, val = m.group(1), _parse_value(m.group(2))
                if val == name:
                    cfg.model_values.append(name)
                else:
                    cfg.constants[name] = val
        elif kw == "SPECIFICATION":
            cfg.specification = body.strip().split()[0]
        elif kw.startswith("INVARIANT"):
            cfg.invariants += [
                p for p in re.split(r"[\s,]+", body.strip()) if p
            ]
        elif kw == "PROPERTIES":
            cfg.properties += [
                p for p in re.split(r"[\s,]+", body.strip()) if p
            ]
    return cfg


def _intern_space(name: str, val) -> int:
    """Canonicalize a key/value space to its size (elements -> 1..n)."""
    if isinstance(val, frozenset):
        if all(isinstance(x, int) for x in val):
            n = len(val)
            if val and (0 in val):
                raise ValueError(
                    f"{name}: 0 is reserved for the null element "
                    "(compaction.tla:30,32)"
                )
            if val != frozenset(range(1, n + 1)):
                warnings.warn(
                    f"{name}: relabeling {sorted(val)} to 1..{n} "
                    "(packed encoding uses dense positive ints)"
                )
            return n
        warnings.warn(
            f"{name}: non-integer elements {sorted(map(str, val))} violate "
            f"ASSUME {name} \\in SUBSET Nat (compaction.tla:29-32); "
            f"interning to 1..{len(val)}"
        )
        return len(val)
    raise ValueError(f"{name} must be a finite set, got {val!r}")


def to_constants(cfg: TLCConfig) -> Constants:
    """Bind a parsed cfg to the compaction spec's nine parameters."""
    c = cfg.constants
    required = [
        "MessageSentLimit",
        "CompactionTimesLimit",
        "ModelConsumer",
        "ConsumeTimesLimit",
        "KeySpace",
        "ValueSpace",
        "RetainNullKey",
        "MaxCrashTimes",
        "ModelProducer",
    ]
    missing = [r for r in required if r not in c]
    if missing:
        raise ValueError(f"cfg missing CONSTANTS: {missing}")
    out = Constants(
        message_sent_limit=int(c["MessageSentLimit"]),
        compaction_times_limit=int(c["CompactionTimesLimit"]),
        model_consumer=bool(c["ModelConsumer"]),
        consume_times_limit=int(c["ConsumeTimesLimit"]),
        num_keys=_intern_space("KeySpace", c["KeySpace"]),
        num_values=_intern_space("ValueSpace", c["ValueSpace"]),
        retain_null_key=bool(c["RetainNullKey"]),
        max_crash_times=int(c["MaxCrashTimes"]),
        model_producer=bool(c["ModelProducer"]),
    )
    out.validate()
    return out


def load(path: str) -> TLCConfig:
    with open(path) as f:
        return parse_cfg(f.read())
