"""Deterministic fault injection — prove survivability, don't hope.

Differential robustness tests need interrupted runs whose
interruption point is exact and repeatable: "the process died at
level 5", "HBM ran out at level 7", "the fpset overflowed a probe
stage on flush 3".  This module turns the ``PTT_FAULT`` environment
variable into synthetic faults fired at named host-side sites:

    PTT_FAULT=oom@level:7              synthetic RESOURCE_EXHAUSTED
    PTT_FAULT=oom@flush:3              same, at the flush site (hits the
                                       sharded fpset flush too)
    PTT_FAULT=fpset_fail@flush:3       fpset stage-overflow (fail-stop)
    PTT_FAULT=kill@level:5             hard process death (os._exit 137)
    PTT_FAULT=sigterm@level:4          SIGTERM to self (preemption drill)
    PTT_FAULT=ckpt_fail@frame:1        transient OSError on checkpoint
                                       frame 1's write (retry drill)
    PTT_FAULT=drop@conn:3              daemon closes connection 3
                                       mid-reply (client-retry drill)
    PTT_FAULT=torn@line:5              daemon writes half of protocol
                                       line 5, then closes
    PTT_FAULT=enospc@persist:2         queue.json persist 2 fails with
                                       a synthetic ENOSPC
    PTT_FAULT=enospc@spill:1           spill write 1 fails with ENOSPC
                                       (tiered-store degradation drill)
    PTT_FAULT=corrupt@warm:1           warm-artifact verification 1
                                       computes a corrupted digest
                                       (cold-fallback drill, r19)
    PTT_FAULT=torn@warmwrite:2         warm-artifact write 2 publishes
                                       half a manifest (quarantine drill)
    PTT_FAULT=partition@backend:3      fleet backend poll 3's backend
                                       turns unreachable from the
                                       dispatcher (alive, partitioned)
                                       for a drain-length window (r21)
    PTT_FAULT=slow@conn:2              the dispatcher's outbound
                                       connection 2 stalls past the
                                       poll timeout (hung-backend
                                       drill, r21)
    PTT_FAULT=flap@backend:5           backend poll 5's backend starts
                                       a die/return cycle (drain, one
                                       clean poll, drain again —
                                       the readmission-hysteresis
                                       drill, r21)
    PTT_FAULT=oom@level:7,kill@level:9 comma-separated specs compose

Syntax: ``kind@site:count`` — ``site`` is a counter the engines
advance (``level`` = the BFS level about to be expanded, ``flush`` =
the flush sequence number, ``frame`` = the checkpoint frame sequence
number, ``sweep`` = the liveness engine's edge-sweep chunk,
``segment`` = the simulation engine's segment epoch (r18); since
round 17 the SERVICE layer counts too: ``conn`` = the daemon's
accepted-connection sequence, ``line`` = the daemon's sent-protocol-
line sequence, ``persist`` = the scheduler's queue.json snapshot
sequence, ``spill`` = the tiered store's spill-write sequence,
``warm`` = the warm store's artifact-verification sequence and
``warmwrite`` its artifact-write sequence — r19; since round 21 the
FLEET layer counts too: ``backend`` = the registry's per-backend
health-poll sequence (every individual backend poll advances it) and
``conn`` doubles as the dispatcher's outbound-connection sequence
for ``slow``),
``count`` the value at which the spec fires.  Each spec fires AT MOST ONCE per process: a run that recovers
from an injected OOM and re-expands the same level must not be
re-injected forever (mirroring the real world, where the recovery's
degraded capacity is what prevents the repeat).

Engines call :func:`poll` at their sites.  ``kill`` and ``sigterm``
are performed inside :func:`poll` (the process dies / signals
itself); every other kind is returned for the caller to realize in
engine-appropriate form (``oom`` is raised by the engine as a
:class:`FaultError` whose text contains ``RESOURCE_EXHAUSTED`` so it
exercises the *same* handler as a real XLA allocation failure).

Everything is inert unless ``PTT_FAULT`` is set — one short env read
per poll, no parsing on the common path.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Set, Tuple

# telemetry observer: called as (kind, site, count) for every spec
# that fires, BEFORE the fault is realized — a ``kill`` leaves no
# other trace, so the breadcrumb must hit the (line-buffered) stream
# first.  Engines install it for the duration of a run.
_observer: Optional[Callable[[str, str, int], None]] = None


def set_observer(fn: Optional[Callable[[str, str, int], None]]) -> None:
    global _observer
    _observer = fn


class FaultError(RuntimeError):
    """An injected fault, raised by the engine at the injection site.
    ``oom`` faults embed ``RESOURCE_EXHAUSTED`` in the message so the
    engines' real out-of-memory handlers fire."""


KINDS = (
    "oom", "fpset_fail", "kill", "sigterm", "ckpt_fail",
    # service-layer kinds (r17): the caller realizes them — the
    # daemon closes the connection (`drop`), tears a protocol line
    # (`torn`), or raises :func:`enospc_error` (`enospc`)
    "drop", "torn", "enospc",
    # warm-artifact kinds (r19, warm/store.py): `corrupt@warm:N`
    # makes the N-th artifact digest VERIFICATION compute a corrupted
    # digest (the bit-flip-on-disk path); `torn@warmwrite:N` /
    # `kill@warmwrite:N` fire inside the N-th artifact WRITE (torn
    # publishes half a manifest; kill dies between frame and
    # manifest — the startup-sweep quarantine drill)
    "corrupt",
    # network-level kinds (r21, fleet/registry.py): `partition@
    # backend:N` makes the N-th polled backend unreachable from the
    # dispatcher for a drain-length window (the backend itself stays
    # alive and keeps running its jobs — the reconciliation drill);
    # `slow@conn:N` stalls the dispatcher's N-th outbound poll past
    # its timeout (a hung backend, not a dead one); `flap@backend:N`
    # starts a die/return cycle on the N-th polled backend (the
    # readmission-hysteresis drill).  All three are realized by the
    # registry's health loop, not here.
    "partition", "slow", "flap",
)

# parse cache keyed on the raw env value + set of fired spec indexes
# (per process; a changed PTT_FAULT re-arms everything)
_cache_raw: str = ""
_cache_specs: List[Tuple[str, str, int]] = []
_fired: Set[int] = set()


def reset() -> None:
    """Re-arm every spec (tests that reuse one process)."""
    global _cache_raw
    _cache_raw = ""
    _fired.clear()


def _specs() -> List[Tuple[str, str, int]]:
    global _cache_raw, _cache_specs
    raw = os.environ.get("PTT_FAULT", "")
    if raw == _cache_raw:
        return _cache_specs
    specs: List[Tuple[str, str, int]] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            kind, rest = part.split("@", 1)
            site, count = rest.split(":", 1)
            kind, site, n = kind.strip(), site.strip(), int(count)
        except ValueError:
            raise ValueError(
                f"bad PTT_FAULT spec {part!r} (want kind@site:count, "
                f"e.g. oom@level:7)"
            ) from None
        if kind not in KINDS:
            raise ValueError(
                f"unknown PTT_FAULT kind {kind!r} (known: {KINDS})"
            )
        specs.append((kind, site, n))
    _cache_raw = raw
    _cache_specs = specs
    _fired.clear()
    return specs


def active() -> bool:
    return bool(os.environ.get("PTT_FAULT"))


def poll(site: str, count: int) -> Tuple[str, ...]:
    """Fire every armed spec matching ``(site, count)``.

    ``kill`` exits the process here with status 137 (SIGKILL's shell
    convention — a death no handler can soften, which is the point);
    ``sigterm`` delivers SIGTERM to this process (the preemption
    watcher then sees exactly what a TPU-VM preemption sends).  All
    other kinds are returned for the engine to realize.
    """
    if not os.environ.get("PTT_FAULT"):
        return ()
    hits = []
    for i, (kind, s, n) in enumerate(_specs()):
        if i in _fired or s != site or n != count:
            continue
        _fired.add(i)
        if _observer is not None:
            try:
                _observer(kind, site, count)
            except Exception:  # noqa: BLE001 — observers never mask faults
                pass
        if kind == "kill":
            import sys

            print(
                f"PTT_FAULT: kill@{site}:{count} — hard exit",
                file=sys.stderr, flush=True,
            )
            os._exit(137)
        if kind == "sigterm":
            import signal

            os.kill(os.getpid(), signal.SIGTERM)
            continue
        hits.append(kind)
    return tuple(hits)


def oom_error(site: str, count: int) -> FaultError:
    """The canonical injected-OOM exception (text matches the real
    XLA allocator's RESOURCE_EXHAUSTED status prefix)."""
    return FaultError(
        f"RESOURCE_EXHAUSTED: injected fault oom@{site}:{count} "
        "(PTT_FAULT)"
    )


def enospc_error(site: str, count: int) -> OSError:
    """The canonical injected disk-full exception — a real
    ``OSError`` with ``errno.ENOSPC`` so it exercises the *same*
    handlers as a genuinely full disk (the queue.json persist retry,
    the spill-tier degradation path)."""
    import errno

    return OSError(
        errno.ENOSPC,
        f"injected fault enospc@{site}:{count} (PTT_FAULT)",
    )
