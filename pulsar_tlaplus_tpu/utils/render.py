"""TLA+-style pretty-printing of states and counterexample traces, mirroring
TLC's error-trace output format so existing eyes/tooling can read it."""

from __future__ import annotations

from typing import List, Optional

from pulsar_tlaplus_tpu.ref import pyeval


def _msg(m) -> str:
    return f"[id |-> {m[0]}, key |-> {_key(m[1])}, value |-> {_val(m[2])}]"


def _key(k: int) -> str:
    return str(k)


def _val(v: int) -> str:
    return str(v)


def _seq(entries) -> str:
    return "<<" + ", ".join(_msg(m) for m in entries) + ">>"


def render_state(s, c) -> str:
    if isinstance(s, dict):
        # generic model protocol: to_pystate returns an ordered mapping
        # TLA+ variable name -> rendered value (str or plain value)
        return "\n".join(f"/\\ {k} = {v}" for k, v in s.items())
    lines = []
    lines.append(f"/\\ messages = {_seq(s.messages)}")
    led = ", ".join(
        f"{i+1} :> " + ("Nil" if v is None else _seq(v))
        for i, v in enumerate(s.ledgers)
    )
    lines.append(f"/\\ compactedLedgers = ({led})")
    if s.cursor is None:
        lines.append("/\\ cursor = Nil")
    else:
        lines.append(
            f"/\\ cursor = [compactionHorizon |-> {s.cursor[0]}, "
            f"compactedTopicContext |-> {s.cursor[1]}]"
        )
    lines.append(f"/\\ compactorState = {pyeval.PHASE_NAMES[s.cstate]}")
    if s.p1 is None:
        lines.append("/\\ phaseOneResult = Nil")
    else:
        latest = ", ".join(f"{k} :> {p}" for k, p in s.p1[1])
        lines.append(
            f"/\\ phaseOneResult = [readPosition |-> {s.p1[0]}, "
            f"latestForKey |-> ({latest})]"
        )
    lines.append(f"/\\ compactionHorizon = {s.horizon}")
    lines.append(f"/\\ compactedTopicContext = {s.context}")
    lines.append(f"/\\ crashTimes = {s.crash}")
    lines.append(f"/\\ consumeTimes = {s.consume}")
    return "\n".join(lines)


def render_trace(
    trace: List[pyeval.State],
    actions: Optional[List[str]],
    c,
) -> str:
    out = []
    for i, s in enumerate(trace):
        if i == 0:
            hdr = f"State {i+1}: <Initial predicate>"
        else:
            hdr = f"State {i+1}: <{actions[i-1]}>"
        out.append(hdr)
        out.append(render_state(s, c))
        out.append("")
    return "\n".join(out)
