"""Shared HBM-exhaustion recovery layer (round 9).

Round 7 gave the single-chip engine the free-buffers → rebuild-from-
the-last-frame → continue-at-degraded-capacity state machine; this
module is that machinery factored out so the mesh-sharded engine (and
anything that checkpoints through ``utils/ckpt.py``) runs the SAME
contract instead of fail-stopping on the first ``RESOURCE_EXHAUSTED``:

::

               RESOURCE_EXHAUSTED
      RUNNING ────────────────────────► frame on disk, armed?
         ▲                                   │yes           │no
         │  rebuild from frame at            ▼              ▼
         │  DEGRADED capacity:          RECOVERING     truncate honestly
         │  - group-ahead halved             │          (stop_reason="hbm")
         │  - growth headroom frozen         │
         └───────────────────────────────────┘

The pieces:

- :func:`is_resource_exhausted` — the ONE place that decides whether
  an exception is an allocator failure (real XLA OOM or the injected
  ``PTT_FAULT=oom@...`` drill, which embeds the same status text so it
  exercises the same handler).
- :class:`HbmExhausted` — internal control flow raised by a level loop
  when exhaustion hits while a valid frame exists.  The rebuild happens
  OUTSIDE the ``except`` block that catches it: the traceback pins the
  loop's frame locals (accumulators, expand windows) plus the chained
  XLA error, and restoring under it would re-OOM exactly when memory
  is tightest.
- :class:`RecoveryState` — the armed/recovered/degraded bookkeeping
  both engines share.  "Armed" means the on-disk frame is valid AND no
  recovery has consumed it since; a second exhaustion without a fresh
  frame in between means recovery is not making progress — truncate
  honestly rather than loop.  Degradation halves the dispatch
  group-ahead (fewer in-flight flushes = smaller worst-case
  transients) and freezes growth headroom to one accumulator, so the
  retry fits where the full-headroom run did not.
"""

from __future__ import annotations

import os
from typing import List, Optional


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA allocator failures (and the ``PTT_FAULT`` oom
    drill, whose message embeds the same status prefix on purpose)."""
    return "RESOURCE_EXHAUSTED" in str(e)


class HbmExhausted(Exception):
    """Internal control flow: a RESOURCE_EXHAUSTED surfaced while a
    valid checkpoint frame exists — the run loop rebuilds device state
    from that frame at degraded capacity instead of truncating.

    ``nv`` and ``level_sizes`` snapshot what the interrupted attempt
    had verified (reported honestly if the rebuild itself fails)."""

    def __init__(self, nv: int, level_sizes: List[int], msg: str):
        super().__init__(msg)
        self.nv = nv
        self.level_sizes = level_sizes
        self.msg = msg


class RecoveryState:
    """Armed/recovered/degraded bookkeeping for one checker instance.

    ``group0`` is the pre-degradation dispatch group-ahead; ``group``
    the current (possibly halved) one.  ``headroom_frozen`` tells the
    engine's growth logic to reserve one accumulator of headroom
    instead of a full group's worth.
    """

    def __init__(self, checkpoint_path: Optional[str], group: int):
        self.checkpoint_path = checkpoint_path
        self.group0 = group
        self.group = group
        self.hbm_recovered = 0
        self.armed = False
        self.headroom_frozen = False

    def reset(self) -> None:
        """Per-run reset: a fresh run() must not inherit a previous
        run's degraded capacity or recovery counts."""
        self.group = self.group0
        self.hbm_recovered = 0
        self.armed = False
        self.headroom_frozen = False

    def arm(self) -> None:
        """A fresh resumable frame reached disk (or a resume started
        from one): the next exhaustion may rebuild from it."""
        self.armed = True

    def can_recover(self) -> bool:
        return (
            self.armed
            and self.checkpoint_path is not None
            and os.path.exists(self.checkpoint_path)
        )

    def degrade(self) -> int:
        """Consume the armed frame and degrade capacity for the retry:
        count the recovery, halve the group-ahead, freeze growth
        headroom.  Returns the new group-ahead."""
        self.hbm_recovered += 1
        self.armed = False
        self.group = max(1, self.group // 2)
        self.headroom_frozen = True
        return self.group
