// TLC-class native baseline: explicit-state BFS model checker for the
// `compaction` spec (/root/reference/compaction.tla), written the way a
// tuned CPU checker would be — packed POD states, 64-bit fingerprints
// in an open-addressing table (TLC's FPSet regime), level-synchronous
// BFS with optional worker threads sharding the fingerprint space.
//
// Purpose (BASELINE.md round-3): the image has no JVM, so 8-worker TLC
// cannot be measured directly; this is the honest in-image stand-in for
// "a fast native CPU checker of the same spec" against which the TPU
// engine's states/sec is compared.  Semantics mirror the repo's Python
// oracle (pulsar_tlaplus_tpu/ref/pyeval.py) exactly; the shipped-config
// run is validated against the published 45,198-state / diameter-20
// oracle (compaction.tla:23) in tests/test_native_baseline.py.
//
// State encoding (M <= 64): messages as (key,value) codes (ids are
// positions, compaction.tla:84-86); compacted ledgers as 64-bit
// position bitmaps over the immutable message sequence (entries of a
// compacted ledger are original messages, compaction.tla:107-119);
// phaseOneResult's latestForKey map is derived from (messages,
// readPosition) on demand (compaction.tla:97-98).
//
// Build: g++ -O2 -std=c++17 -pthread compaction_bfs.cpp -o compaction_bfs
// Run:   ./compaction_bfs M K V C crash producer retain budget_s threads

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

struct Cfg {
  int M, K, V, C, max_crash;
  bool producer, retain, consumer = false;
};

static Cfg cfg;

enum Phase {
  PHASE_ONE = 0,
  P2_WRITE,
  P2_UPDATE_CONTEXT,
  P2_UPDATE_HORIZON,
  P2_PERSIST_CURSOR,
  P2_DELETE_LEDGER
};

struct State {
  uint8_t msg[64];  // code = key * (V+1) + val; zero beyond mlen
  uint64_t led[3];  // position bitmaps (1-based position p -> bit p-1)
  uint8_t led_live; // presence bits (a live ledger may be empty)
  uint8_t mlen, cstate, crash, consume, horizon, context;
  uint8_t has_p1, p1_read, has_cur, cur_h, cur_ctx;

  bool operator==(const State &o) const {
    return std::memcmp(this, &o, sizeof(State)) == 0;
  }
};

static inline int msg_key(const State &s, int pos1) { // 1-based
  return s.msg[pos1 - 1] / (cfg.V + 1);
}
static inline int msg_val(const State &s, int pos1) {
  return s.msg[pos1 - 1] % (cfg.V + 1);
}

// MaxCompactedLedgerId (compaction.tla:103-106): highest live slot, 1-based.
static inline int max_ledger_id(const State &s) {
  int mx = 0;
  for (int i = 0; i < cfg.C; i++)
    if (s.led_live >> i & 1) mx = i + 1;
  return mx;
}

// 64-bit fingerprint over the canonical bytes (splitmix64 mixing).
static inline uint64_t fingerprint(const State &s) {
  const uint64_t *p = reinterpret_cast<const uint64_t *>(&s);
  size_t words = sizeof(State) / 8;
  uint64_t h = 0x9e3779b97f4a7c15ULL;
  for (size_t i = 0; i < words; i++) {
    uint64_t x = p[i] + h + 0xbf58476d1ce4e5b9ULL * (i + 1);
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    h = (h ^ x ^ (x >> 31)) * 0x2545f4914f6cdd1dULL;
  }
  return h ? h : 1; // 0 is the empty marker
}

// --- invariants (compaction.tla:236-274; defaults of the shipped cfg) ---

static bool type_safe(const State &s) {
  for (int i = 1; i <= s.mlen; i++) {
    int k = msg_key(s, i), v = msg_val(s, i);
    if (k < 0 || k > cfg.K || v < 0 || v > cfg.V) return false;
  }
  for (int j = 0; j < cfg.C; j++) {
    if (!(s.led_live >> j & 1)) continue;
    uint64_t bm = s.led[j];
    while (bm) {
      int pos = __builtin_ctzll(bm) + 1;
      bm &= bm - 1;
      if (pos < 1 || pos > s.mlen) return false;
      int k = msg_key(s, pos), v = msg_val(s, pos);
      if (k < 0 || k > cfg.K || v < 0 || v > cfg.V) return false;
    }
  }
  if (s.has_p1 && !(1 <= s.p1_read && s.p1_read <= s.mlen)) return false;
  if (s.cstate > 5) return false;
  if (s.horizon > cfg.M || s.context > cfg.C) return false;
  if (s.crash > cfg.max_crash) return false;
  if (s.has_cur &&
      !(1 <= s.cur_h && s.cur_h <= cfg.M && 1 <= s.cur_ctx &&
        s.cur_ctx <= cfg.C))
    return false;
  return true;
}

static bool horizon_correct(const State &s) {
  if (s.horizon == 0) return true; // lazy guard (compaction.tla:259-274)
  uint64_t bm = 0;
  if (s.context >= 1 && (s.led_live >> (s.context - 1) & 1))
    bm = s.led[s.context - 1];
  // per-key max position present in the ledger (ids are positions)
  int maxpos[16] = {0};
  uint64_t b = bm;
  while (b) {
    int pos = __builtin_ctzll(b) + 1;
    b &= b - 1;
    int k = msg_key(s, pos);
    if (pos > maxpos[k]) maxpos[k] = pos;
  }
  for (int i = 1; i <= s.horizon; i++) {
    int k = msg_key(s, i);
    if (k == 0 && !cfg.retain) continue;
    if (maxpos[k] < i) return false;
  }
  return true;
}

// --- successor generation (compaction.tla:216-231) ---

template <typename Fn> static void successors(const State &s, Fn emit) {
  int n = s.mlen;
  if (cfg.producer && n < cfg.M) { // Producer (compaction.tla:83-87)
    for (int k = 0; k <= cfg.K; k++)
      for (int v = 0; v <= cfg.V; v++) {
        State t = s;
        t.msg[n] = (uint8_t)(k * (cfg.V + 1) + v);
        t.mlen = (uint8_t)(n + 1);
        emit(t);
      }
  }
  if (s.cstate == PHASE_ONE && !s.has_p1 && n > 0) { // PhaseOne (:93-100)
    State t = s;
    t.has_p1 = 1;
    t.p1_read = (uint8_t)n;
    t.cstate = P2_WRITE;
    emit(t);
  }
  if (s.has_p1 && s.cstate == P2_WRITE) { // PhaseTwoWrite (:121-132)
    int new_id = max_ledger_id(s) + 1;
    if (new_id <= cfg.C) {
      // CompactMessages (:107-119): latest-per-key over the snapshot
      // prefix, null keys kept per RetainNullKey
      int latest[16] = {0};
      for (int i = 1; i <= s.p1_read; i++) {
        int k = msg_key(s, i);
        if (k != 0) latest[k] = i;
      }
      uint64_t bm = 0;
      for (int i = 1; i <= s.p1_read; i++) {
        int k = msg_key(s, i);
        if (k == 0 ? cfg.retain : latest[k] == i) bm |= 1ULL << (i - 1);
      }
      State t = s;
      t.led[new_id - 1] = bm;
      t.led_live |= (uint8_t)(1 << (new_id - 1));
      t.cstate = P2_UPDATE_CONTEXT;
      emit(t);
    }
  }
  if (s.cstate == P2_UPDATE_CONTEXT) { // (:135-139)
    State t = s;
    t.context = (uint8_t)max_ledger_id(s);
    t.cstate = P2_UPDATE_HORIZON;
    emit(t);
  }
  if (s.cstate == P2_UPDATE_HORIZON) { // (:141-145)
    State t = s;
    t.horizon = s.p1_read;
    t.cstate = P2_PERSIST_CURSOR;
    emit(t);
  }
  if (s.cstate == P2_PERSIST_CURSOR) { // (:147-151)
    State t = s;
    t.has_cur = 1;
    t.cur_h = s.horizon;
    t.cur_ctx = s.context;
    t.cstate = P2_DELETE_LEDGER;
    emit(t);
  }
  if (s.cstate == P2_DELETE_LEDGER) { // (:153-165)
    int max_id = max_ledger_id(s);
    State t = s;
    if (max_id >= 2 && (s.led_live >> (max_id - 2) & 1)) {
      t.led[max_id - 2] = 0;
      t.led_live &= (uint8_t)~(1 << (max_id - 2));
    }
    t.cstate = PHASE_ONE;
    t.has_p1 = 0;
    t.p1_read = 0;
    emit(t);
  }
  if (s.crash < cfg.max_crash) { // BrokerCrash (:169-182)
    State t = s;
    t.crash = (uint8_t)(s.crash + 1);
    t.cstate = PHASE_ONE;
    t.has_p1 = 0;
    t.p1_read = 0;
    t.horizon = s.has_cur ? s.cur_h : 0;
    t.context = s.has_cur ? s.cur_ctx : 0;
    emit(t);
  }
  // Consumer / Terminating are stutters (dedup drops them).
}

// --- fingerprint set: open addressing, linear probing, CAS inserts ---
// Lock-free: a probe chain may cross any slot, so per-slot CAS is the
// only sound sharing discipline (striped locks cannot cover a chain).

struct FpSet {
  std::vector<std::atomic<uint64_t>> tab;
  uint64_t mask;
  std::atomic<size_t> count{0};
  size_t high_water; // stop before load factor ~0.85: probe chains
                     // degrade and a full table would probe forever

  explicit FpSet(size_t cap_log2)
      : tab(1ULL << cap_log2), mask((1ULL << cap_log2) - 1),
        high_water(((1ULL << cap_log2) / 20) * 17) {
    for (auto &slot : tab) slot.store(0, std::memory_order_relaxed);
  }
  bool nearly_full() const {
    return count.load(std::memory_order_relaxed) >= high_water;
  }
  // returns true if newly inserted
  bool insert(uint64_t fp) {
    for (size_t i = fp & mask;; i = (i + 1) & mask) {
      uint64_t cur = tab[i].load(std::memory_order_relaxed);
      if (cur == fp) return false;
      if (cur == 0) {
        uint64_t expect = 0;
        if (tab[i].compare_exchange_strong(expect, fp,
                                           std::memory_order_relaxed)) {
          count.fetch_add(1, std::memory_order_relaxed);
          return true;
        }
        if (expect == fp) return false; // raced with same fingerprint
        // raced with a different fp: fall through, keep probing at i
        if (tab[i].load(std::memory_order_relaxed) == fp) return false;
      }
    }
  }
};

int main(int argc, char **argv) {
  if (argc < 9) {
    std::fprintf(
        stderr,
        "usage: %s M K V C crash producer retain budget_s [threads] "
        "[table_log2]\n",
        argv[0]);
    return 2;
  }
  cfg.M = std::atoi(argv[1]);
  cfg.K = std::atoi(argv[2]);
  cfg.V = std::atoi(argv[3]);
  cfg.C = std::atoi(argv[4]);
  cfg.max_crash = std::atoi(argv[5]);
  cfg.producer = std::atoi(argv[6]) != 0;
  cfg.retain = std::atoi(argv[7]) != 0;
  double budget_s = std::atof(argv[8]);
  int nthreads = argc > 9 ? std::atoi(argv[9]) : 1;
  if (cfg.M > 64 || cfg.K > 15 || cfg.C > 3) {
    std::fprintf(stderr, "config out of encoding range\n");
    return 2;
  }

  auto t0 = std::chrono::steady_clock::now();
  auto elapsed = [&] {
    return std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                         t0)
        .count();
  };

  int table_log2 = argc > 10 ? std::atoi(argv[10])
                            : (cfg.producer ? 27 : 22);
  FpSet seen((size_t)table_log2);
  std::atomic<bool> violated{false};
  std::vector<State> frontier, next;
  State z;
  std::memset(&z, 0, sizeof z);

  // Init (compaction.tla:188-202)
  if (cfg.producer) {
    seen.insert(fingerprint(z));
    frontier.push_back(z);
  } else {
    int codes = (cfg.K + 1) * (cfg.V + 1);
    std::vector<int> digit(cfg.M, 0);
    for (;;) {
      State s = z;
      s.mlen = (uint8_t)cfg.M;
      for (int i = 0; i < cfg.M; i++) s.msg[i] = (uint8_t)digit[i];
      if (seen.insert(fingerprint(s))) frontier.push_back(s);
      int d = 0;
      while (d < cfg.M && ++digit[d] == codes) digit[d++] = 0;
      if (d == cfg.M) break;
    }
  }
  for (auto &s : frontier)
    if (!type_safe(s) || !horizon_correct(s)) violated = true;

  size_t levels = 1;
  std::atomic<bool> truncated{false};

  while (!frontier.empty() && !truncated && !violated.load()) {
    next.clear();
    if (nthreads <= 1) {
      for (size_t fi = 0; fi < frontier.size(); fi++) {
        successors(frontier[fi], [&](const State &t) {
          if (seen.insert(fingerprint(t))) {
            if (!type_safe(t) || !horizon_correct(t)) violated = true;
            next.push_back(t);
          }
        });
        if ((fi & 1023) == 0 &&
            (elapsed() > budget_s || seen.nearly_full())) {
          truncated = true;
          break;
        }
      }
    } else {
      std::vector<std::vector<State>> outs(nthreads);
      std::atomic<size_t> cursor{0};
      std::vector<std::thread> ws;
      for (int w = 0; w < nthreads; w++)
        ws.emplace_back([&, w] {
          for (;;) {
            size_t i = cursor.fetch_add(256);
            if (i >= frontier.size() || truncated) break;
            size_t end = std::min(i + 256, frontier.size());
            for (; i < end; i++)
              successors(frontier[i], [&](const State &t) {
                if (seen.insert(fingerprint(t))) {
                  if (!type_safe(t) || !horizon_correct(t)) violated = true;
                  outs[w].push_back(t);
                }
              });
            if (elapsed() > budget_s || seen.nearly_full())
              truncated = true;
          }
        });
      for (auto &th : ws) th.join();
      for (auto &o : outs)
        next.insert(next.end(), o.begin(), o.end());
    }
    if (!next.empty()) {
      levels++;
      // per-level profile on stderr: ground truth for the TPU engine's
      // level accounting (round 5: the HBM-capped TPU bench truncates
      // mid-level, so its per-level "+N" lines cannot be read as full
      // level sizes — this is the authoritative source).  The empty-
      // frontier iteration is skipped so each level prints exactly once.
      std::fprintf(stderr,
                   "{\"level\": %zu, \"new\": %zu, \"cum\": %zu, "
                   "\"wall_s\": %.3f, \"complete\": %s}\n",
                   levels, next.size(), seen.count.load(), elapsed(),
                   truncated ? "false" : "true");
    }
    frontier.swap(next);
  }

  double dt = elapsed();
  size_t n = seen.count.load();
  std::printf("{\"distinct_states\": %zu, \"levels\": %zu, \"wall_s\": %.3f, "
              "\"states_per_sec\": %.1f, \"truncated\": %s, "
              "\"violated\": %s, \"threads\": %d}\n",
              n, levels, dt, n / (dt > 0 ? dt : 1e-9),
              truncated.load() ? "true" : "false",
              violated ? "true" : "false",
              nthreads);
  return violated ? 1 : 0;
}
