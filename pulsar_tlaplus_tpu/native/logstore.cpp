// Native disk-backed state/trace log for the BFS engine.
//
// TLC keeps discovered states and their parent-fingerprint chains on disk
// (the gitignored `states/` dir, reference .gitignore:2) so traces can be
// reconstructed without holding every state in RAM.  This is the TPU
// framework's native equivalent (SURVEY.md §2.2-E7/E8): an append-only
// fixed-record file
//
//     record := packed_state(u32 x row_words) | parent_gid(i64) | action(i32)
//
// written with pwrite/pread so appends (BFS flush) and random reads (trace
// walk, checkpoint resume) can interleave without seek bookkeeping.  At
// 10^9 states this is ~100 GB — far beyond host RAM — while the BFS hot
// path only ever touches the (device-resident) fingerprint set.
//
// Built as a CPython extension (no pybind11 in the image); a pure-python
// fallback with the same API lives in pulsar_tlaplus_tpu/engine/statelog.py.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct LogStoreObject {
    PyObject_HEAD
    int fd;
    Py_ssize_t row_words;
    Py_ssize_t rec_size;
    Py_ssize_t n_rows;
};

int logstore_init(LogStoreObject* self, PyObject* args, PyObject* kwds) {
    const char* path = nullptr;
    Py_ssize_t row_words = 0;
    static const char* kwlist[] = {"path", "row_words", nullptr};
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "sn",
                                     const_cast<char**>(kwlist), &path,
                                     &row_words)) {
        return -1;
    }
    if (row_words <= 0 || row_words > (1 << 16)) {
        PyErr_SetString(PyExc_ValueError, "row_words out of range");
        return -1;
    }
    self->fd = ::open(path, O_RDWR | O_CREAT, 0644);
    if (self->fd < 0) {
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
        return -1;
    }
    self->row_words = row_words;
    self->rec_size = row_words * 4 + 8 + 4;
    off_t end = ::lseek(self->fd, 0, SEEK_END);
    if (end < 0 || end % self->rec_size != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "existing file size is not a whole number of records");
        ::close(self->fd);
        self->fd = -1;
        return -1;
    }
    self->n_rows = end / self->rec_size;
    return 0;
}

void logstore_dealloc(LogStoreObject* self) {
    if (self->fd >= 0) {
        ::close(self->fd);
    }
    Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

// append(packed_bytes, parents_bytes, actions_bytes, n) -> first_gid
PyObject* logstore_append(LogStoreObject* self, PyObject* args) {
    Py_buffer packed, parents, actions;
    Py_ssize_t n = 0;
    if (!PyArg_ParseTuple(args, "y*y*y*n", &packed, &parents, &actions, &n)) {
        return nullptr;
    }
    PyObject* result = nullptr;
    if (packed.len != n * self->row_words * 4 || parents.len != n * 8 ||
        actions.len != n * 4) {
        PyErr_SetString(PyExc_ValueError, "buffer sizes do not match n");
        goto done;
    }
    {
        // interleave into one write buffer per batch
        Py_ssize_t total = n * self->rec_size;
        char* buf = static_cast<char*>(PyMem_Malloc(total));
        if (!buf) {
            PyErr_NoMemory();
            goto done;
        }
        const char* p = static_cast<const char*>(packed.buf);
        const char* q = static_cast<const char*>(parents.buf);
        const char* a = static_cast<const char*>(actions.buf);
        const Py_ssize_t rw4 = self->row_words * 4;
        for (Py_ssize_t i = 0; i < n; i++) {
            char* dst = buf + i * self->rec_size;
            std::memcpy(dst, p + i * rw4, rw4);
            std::memcpy(dst + rw4, q + i * 8, 8);
            std::memcpy(dst + rw4 + 8, a + i * 4, 4);
        }
        off_t off = static_cast<off_t>(self->n_rows) * self->rec_size;
        Py_ssize_t written = 0;
        while (written < total) {
            ssize_t w = ::pwrite(self->fd, buf + written, total - written,
                                 off + written);
            if (w < 0) {
                if (errno == EINTR) continue;
                PyMem_Free(buf);
                PyErr_SetFromErrno(PyExc_OSError);
                goto done;
            }
            written += w;
        }
        PyMem_Free(buf);
        Py_ssize_t first = self->n_rows;
        self->n_rows += n;
        result = PyLong_FromSsize_t(first);
    }
done:
    PyBuffer_Release(&packed);
    PyBuffer_Release(&parents);
    PyBuffer_Release(&actions);
    return result;
}

// get(gid) -> (packed_bytes, parent, action)
PyObject* logstore_get(LogStoreObject* self, PyObject* args) {
    Py_ssize_t gid = 0;
    if (!PyArg_ParseTuple(args, "n", &gid)) {
        return nullptr;
    }
    if (gid < 0 || gid >= self->n_rows) {
        PyErr_SetString(PyExc_IndexError, "gid out of range");
        return nullptr;
    }
    char rec[1 << 12];
    char* buf = rec;
    PyObject* result = nullptr;
    if (self->rec_size > static_cast<Py_ssize_t>(sizeof(rec))) {
        buf = static_cast<char*>(PyMem_Malloc(self->rec_size));
        if (!buf) return PyErr_NoMemory();
    }
    off_t off = static_cast<off_t>(gid) * self->rec_size;
    Py_ssize_t done_n = 0;
    while (done_n < self->rec_size) {
        ssize_t r =
            ::pread(self->fd, buf + done_n, self->rec_size - done_n, off + done_n);
        if (r < 0) {
            if (errno == EINTR) continue;
            PyErr_SetFromErrno(PyExc_OSError);
            goto done;
        }
        if (r == 0) {
            PyErr_SetString(PyExc_EOFError, "short read");
            goto done;
        }
        done_n += r;
    }
    {
        const Py_ssize_t rw4 = self->row_words * 4;
        int64_t parent;
        int32_t action;
        std::memcpy(&parent, buf + rw4, 8);
        std::memcpy(&action, buf + rw4 + 8, 4);
        result = Py_BuildValue("y#Li", buf, rw4, (long long)parent,
                               (int)action);
    }
done:
    if (buf != rec) PyMem_Free(buf);
    return result;
}

PyObject* logstore_sync(LogStoreObject* self, PyObject*) {
    if (::fsync(self->fd) < 0) {
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

Py_ssize_t logstore_len(PyObject* self) {
    return reinterpret_cast<LogStoreObject*>(self)->n_rows;
}

PyMethodDef logstore_methods[] = {
    {"append", reinterpret_cast<PyCFunction>(logstore_append), METH_VARARGS,
     "append(packed_bytes, parents_bytes, actions_bytes, n) -> first gid"},
    {"get", reinterpret_cast<PyCFunction>(logstore_get), METH_VARARGS,
     "get(gid) -> (packed_bytes, parent, action)"},
    {"sync", reinterpret_cast<PyCFunction>(logstore_sync), METH_NOARGS,
     "fsync the backing file"},
    {nullptr, nullptr, 0, nullptr},
};

PySequenceMethods logstore_as_sequence = {
    logstore_len, /* sq_length */
};

PyTypeObject LogStoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

PyModuleDef logstore_module = {
    PyModuleDef_HEAD_INIT, "_logstore",
    "Disk-backed fixed-record state/trace log (native)", -1, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__logstore(void) {
    LogStoreType.tp_name = "_logstore.LogStore";
    LogStoreType.tp_basicsize = sizeof(LogStoreObject);
    LogStoreType.tp_flags = Py_TPFLAGS_DEFAULT;
    LogStoreType.tp_new = PyType_GenericNew;
    LogStoreType.tp_init = reinterpret_cast<initproc>(logstore_init);
    LogStoreType.tp_dealloc = reinterpret_cast<destructor>(logstore_dealloc);
    LogStoreType.tp_methods = logstore_methods;
    LogStoreType.tp_as_sequence = &logstore_as_sequence;
    if (PyType_Ready(&LogStoreType) < 0) return nullptr;
    PyObject* mod = PyModule_Create(&logstore_module);
    if (!mod) return nullptr;
    Py_INCREF(&LogStoreType);
    if (PyModule_AddObject(mod, "LogStore",
                           reinterpret_cast<PyObject*>(&LogStoreType)) < 0) {
        Py_DECREF(&LogStoreType);
        Py_DECREF(mod);
        return nullptr;
    }
    return mod;
}
