"""Native runtime components (C++ CPython extensions).

``build()`` compiles ``logstore.cpp`` with the system toolchain directly
(g++; no pybind11 in the image) into this package directory.  Import of
``_logstore`` triggers a build on first use; failures fall back to the
pure-python implementation in ``engine/statelog.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(__file__)


def _ext_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, "_logstore" + suffix)


def build(force: bool = False) -> str:
    """Compile the extension if needed; returns the .so path."""
    out = _ext_path()
    src = os.path.join(_DIR, "logstore.cpp")
    if not force and os.path.exists(out) and os.path.getmtime(
        out
    ) >= os.path.getmtime(src):
        return out
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        f"-I{include}",
        src,
        "-o",
        out,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def build_baseline(force: bool = False) -> str:
    """Compile the TLC-class native baseline checker
    (``compaction_bfs.cpp``) into a standalone binary; returns its path.
    See BASELINE.md: this is the in-image stand-in for 8-worker CPU TLC
    (no JVM in the image)."""
    src = os.path.join(_DIR, "compaction_bfs.cpp")
    out = os.path.join(_DIR, "compaction_bfs")
    if not force and os.path.exists(out) and os.path.getmtime(
        out
    ) >= os.path.getmtime(src):
        return out
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-pthread", src, "-o", out],
        check=True, capture_output=True,
    )
    return out


def run_baseline(
    m: int, k: int, v: int, c: int, crash: int, producer: bool,
    retain: bool, budget_s: float, threads: int = 1,
    table_log2: int | None = None,
) -> dict:
    """Run the native baseline checker; returns its JSON result dict.

    ``table_log2`` sizes the fingerprint table (slots = 2^n); small
    differential-test configs should pass ~22 so each run does not
    zero-fill the 1 GB bench-sized default table."""
    import json

    binary = build_baseline()
    if table_log2 is None:
        table_log2 = 27 if producer else 22
    p = subprocess.run(
        [
            binary, str(m), str(k), str(v), str(c), str(crash),
            "1" if producer else "0", "1" if retain else "0",
            str(budget_s), str(threads), str(table_log2),
        ],
        capture_output=True, text=True,
    )
    if p.returncode not in (0, 1):
        raise RuntimeError(f"baseline checker failed: {p.stderr[:500]}")
    res = json.loads(p.stdout.strip().splitlines()[-1])
    if res.get("violated"):
        # a violated run stops BFS early — its states/sec is measured
        # against a partial exploration and must never be used as a
        # throughput baseline (ADVICE r3)
        raise RuntimeError(
            "native baseline run hit an invariant violation; its "
            f"partial-run throughput is not a valid baseline: {res}"
        )
    return res


def load_logstore():
    """Returns the native _logstore module, building it if necessary.

    Raises on toolchain/build failure — callers fall back to the python
    implementation.
    """
    try:
        from pulsar_tlaplus_tpu.native import _logstore  # type: ignore

        return _logstore
    except ImportError:
        build()
        import importlib

        return importlib.import_module("pulsar_tlaplus_tpu.native._logstore")
