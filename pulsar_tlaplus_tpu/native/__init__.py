"""Native runtime components (C++ CPython extensions).

``build()`` compiles ``logstore.cpp`` with the system toolchain directly
(g++; no pybind11 in the image) into this package directory.  Import of
``_logstore`` triggers a build on first use; failures fall back to the
pure-python implementation in ``engine/statelog.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(__file__)


def _ext_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, "_logstore" + suffix)


def build(force: bool = False) -> str:
    """Compile the extension if needed; returns the .so path."""
    out = _ext_path()
    src = os.path.join(_DIR, "logstore.cpp")
    if not force and os.path.exists(out) and os.path.getmtime(
        out
    ) >= os.path.getmtime(src):
        return out
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        f"-I{include}",
        src,
        "-o",
        out,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def load_logstore():
    """Returns the native _logstore module, building it if necessary.

    Raises on toolchain/build failure — callers fall back to the python
    implementation.
    """
    try:
        from pulsar_tlaplus_tpu.native import _logstore  # type: ignore

        return _logstore
    except ImportError:
        build()
        import importlib

        return importlib.import_module("pulsar_tlaplus_tpu.native._logstore")
