"""Dense-tile kernel layer tests (round 23, ``ops/tiles.py``).

The acceptance bar (ISSUE 20):

- kernel-level parity properties: the tiled/Pallas probe, key-plane,
  and sieve formulations bit-identical to the legacy ops on
  randomized shapes — ragged (non-tile-multiple) lane counts, dup-
  heavy batches, SENTINEL lanes, partial ``n_acc``, growth-boundary
  load factors;
- engine state-for-state differentials: identical level sizes, rows,
  parent/lane logs on producer_on under EVERY ``*_impl`` setting,
  with the r14 work-counter totals key-for-key equal and the r13
  fused dispatch economy unchanged;
- both published bug oracles replay identically (violation gid +
  full trace) through the tile kernels;
- knob plumbing: ctor validation, tuned-profile resolution with
  explicit-wins, profile validator enum, search-space membership,
  predict pricing, v16 headers, bench_schema-12 artifacts;
- the tiles ledger gate: a tile-impl run gates CLEAN against the
  committed legacy-comparable mini baseline on the deterministic
  economy keys (the impls share one comparability class by design),
  and a tampered baseline fails loudly.
"""

import importlib.util
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest
from jax import random

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import ledger
from pulsar_tlaplus_tpu.ops import fpset, tiles
from pulsar_tlaplus_tpu.ops.dedup import SENTINEL, KeySpec
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.store import sieve as store_sieve
from pulsar_tlaplus_tpu.tune import predict, profiles
from pulsar_tlaplus_tpu.tune import space as tune_space
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TILES_PINNED = os.path.join(
    ROOT, "tests", "data", "mini_bench_tiles_producer_on.jsonl"
)


def _checker_mod():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(ROOT, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk(c, sub_batch=256, **kw):
    kw.setdefault("visited_cap", 1 << 12)
    kw.setdefault("frontier_cap", 1 << 12)
    return DeviceChecker(
        CompactionModel(c), invariants=kw.pop("invariants", ()),
        sub_batch=sub_batch, **kw,
    )


def _rand_cols(key, n, K):
    cols = []
    for _ in range(K):
        key, sub = random.split(key)
        cols.append(random.bits(sub, (n,), jnp.uint32))
    return key, tuple(cols)


# ---- kernel parity: probe ------------------------------------------


# (cap_log2, nq, dup_frac, n_acc_frac, fill_frac) — ragged lane
# counts that are NOT chunk multiples, dup-heavy batches, stale tails,
# and a growth-boundary load factor; fill_frac keeps the post-flush
# load under the engine's growth threshold (the engine rehashes BEFORE
# a flush could overload the table, so an overloaded flush is outside
# the parity contract — probe-failure resolution under impossible
# load is schedule-dependent in every impl)
PROBE_SHAPES = [
    (12, 1000, 0.0, 1.0, 0.375),
    (12, 1024, 0.6, 1.0, 0.5),
    (11, 777, 0.5, 0.61, 0.375),
    (11, 2048, 0.9, 0.83, 0.25),
    (13, 3000, 0.3, 1.0, 0.375),
]


@pytest.mark.parametrize("impl", ["tile", "pallas"])
@pytest.mark.parametrize(
    "cap_log2,nq,dup_frac,n_acc_frac,fill_frac", PROBE_SHAPES
)
def test_flush_probe_parity(
    impl, cap_log2, nq, dup_frac, n_acc_frac, fill_frac
):
    """flush_acc under tile/pallas: bit-identical ``is_new``/``n_new``
    and the same resulting table KEY SET as legacy (slot placement may
    differ — the tiled insert probes in chunks — but membership, the
    only observable the engine reads, may not)."""
    cap = 1 << cap_log2
    K = 2
    key = random.PRNGKey(cap_log2 * 1000 + nq)
    key, fill_cols = _rand_cols(key, int(cap * fill_frac), K)
    tcols = fpset.empty_cols(cap, K)
    fpm = jnp.zeros((fpset.FPM_N,), jnp.int32)
    tcols, _, _, _ = fpset.flush_acc(
        tcols, fill_cols, jnp.int32(fill_cols[0].shape[0]), fpm
    )
    ndup = int(nq * dup_frac)
    key, fresh = _rand_cols(key, nq - ndup, K)
    dup_ix = jnp.arange(ndup) % fill_cols[0].shape[0]
    kcols = tuple(
        jnp.concatenate([f[dup_ix], g])
        for f, g in zip(fill_cols, fresh)
    )
    # a few SENTINEL (masked-expand) lanes sprinkled in
    sent = jnp.arange(nq) % 97 == 3
    kcols = tuple(jnp.where(sent, SENTINEL, c) for c in kcols)
    n_acc = jnp.int32(int(nq * n_acc_frac))
    t_l, n_l, f_l, m_l = fpset.flush_acc(tcols, kcols, n_acc, fpm)
    t_i, n_i, f_i, m_i = fpset.flush_acc(
        tcols, kcols, n_acc, fpm, probe_impl=impl
    )
    assert int(n_l) == int(n_i)
    assert np.array_equal(np.asarray(f_l), np.asarray(f_i))
    # same key multiset in both tables (sorted column compare);
    # slot `cap` is the write-only trash row — parked/duplicate lanes
    # scatter into it, so its residue is last-writer scheduling noise
    # in EVERY impl and is never read back
    def keyset(tc):
        cols = tuple(np.asarray(c)[:cap] for c in tc)
        order = np.lexsort(cols)
        return tuple(c[order] for c in cols)

    for a, b in zip(keyset(t_l), keyset(t_i)):
        assert np.array_equal(a, b)
    # the duplicate/valid accounting rides the same metrics vector
    # (probe-round totals legitimately differ per impl — the schedule
    # is reformulated — but failure count and presented lanes may not)
    assert int(m_l[2]) == int(m_i[2])  # n_failed accumulator


@pytest.mark.parametrize("impl", ["tile", "pallas"])
def test_flush_probe_within_batch_duplicates(impl):
    """Lanes presenting the SAME new key in one batch: exactly one
    winner, and it is the minimum lane id (the discovery-order
    invariant every engine path leans on)."""
    cap, K, nq = 1 << 10, 2, 512
    tcols = fpset.empty_cols(cap, K)
    fpm = jnp.zeros((fpset.FPM_N,), jnp.int32)
    key, cols = _rand_cols(random.PRNGKey(7), nq, K)
    # force groups of 4 consecutive lanes to share a key
    kcols = tuple(c[::4].repeat(4)[:nq] for c in cols)
    _, n_l, f_l, _ = fpset.flush_acc(tcols, kcols, jnp.int32(nq), fpm)
    _, n_i, f_i, _ = fpset.flush_acc(
        tcols, kcols, jnp.int32(nq), fpm, probe_impl=impl
    )
    assert int(n_l) == int(n_i)
    assert np.array_equal(np.asarray(f_l), np.asarray(f_i))
    w = np.flatnonzero(np.asarray(f_i))
    assert (w % 4 == 0).all()  # min-lane wins every group


# ---- kernel parity: expand key plane --------------------------------


@pytest.mark.parametrize("impl", ["tile", "pallas"])
@pytest.mark.parametrize(
    "total_bits,W,fp_bits",
    [(60, 2, None), (90, 3, None), (160, 5, 64), (160, 5, 96)],
)
def test_key_plane_parity(impl, total_bits, W, fp_bits):
    """key_plane vs KeySpec.make + SENTINEL masking: bit-identical on
    exact and hashed layouts, ragged row counts included."""
    ks = KeySpec(total_bits, W, fp_bits)
    for nc in (257, 4096, 5000):
        key = random.PRNGKey(nc)
        packedf = random.bits(key, (nc, W), jnp.uint32)
        vflat = (jnp.arange(nc) % 11) != 5
        want = tuple(
            jnp.where(vflat, c, SENTINEL) for c in ks.make(packedf)
        )
        got = tiles.key_plane(ks, packedf, vflat, impl=impl)
        assert len(want) == len(got) == ks.ncols
        for a, b in zip(want, got):
            assert np.array_equal(np.asarray(a), np.asarray(b))


# ---- kernel parity: sieve ------------------------------------------


@pytest.mark.parametrize("impl", ["tile", "pallas"])
def test_extract_cold_parity(impl):
    """extract_cold under tile/pallas: array-identical holed table,
    cleared generations, sorted eviction run, and count."""
    cap, K = 1 << 11, 3
    key, cols = _rand_cols(random.PRNGKey(3), (cap * 3) // 4, K)
    tcols = fpset.empty_cols(cap, K)
    fpm = jnp.zeros((fpset.FPM_N,), jnp.int32)
    tcols, _, _, _ = fpset.flush_acc(
        tcols, cols, jnp.int32(cols[0].shape[0]), fpm
    )
    occ = fpset.occupied_mask(tcols)
    gen = jnp.where(occ, (jnp.arange(cap, dtype=jnp.int32) % 5) + 1, 0)
    gen = jnp.concatenate([gen, jnp.zeros((1,), jnp.int32)])
    for cutoff in (1, 3):
        legacy = store_sieve.extract_cold(tcols, gen, cutoff)
        tiled = store_sieve.extract_cold(
            tcols, gen, cutoff, sieve_impl=impl
        )
        for a, b in zip(legacy[0], tiled[0]):  # holed planes
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(
            np.asarray(legacy[1]), np.asarray(tiled[1])
        )
        for a, b in zip(legacy[2], tiled[2]):  # sorted eviction run
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert int(legacy[3]) == int(tiled[3])


# ---- engine state-for-state differentials ---------------------------


# work-counter keys the impls may NOT move (ACAP-presented lanes per
# flush are schedule-invariant); fpset_probe_rounds is deliberately
# NOT here — the tiled schedule legitimately reports different round
# totals (docs/kernels.md)
def _work(ck):
    return {
        k: v for k, v in ck.last_stats.items() if k.startswith("work_")
    }


IMPL_COMBOS = [
    dict(probe_impl="tile"),
    dict(expand_impl="tile"),
    dict(probe_impl="tile", expand_impl="tile"),
    dict(probe_impl="pallas", expand_impl="pallas"),
]


def test_engine_state_for_state_under_every_impl():
    """producer_on under every impl combo: identical level sizes,
    packed rows, parent/lane logs, work-counter totals, and the r13
    fused dispatch economy."""
    c = SMALL_CONFIGS["producer_on"]
    ck0 = _mk(c)
    r0 = ck0.run()
    nv, W = r0.distinct_states, ck0.W
    rows0 = np.asarray(ck0.last_bufs["rows"][: nv * W])
    p0 = np.asarray(ck0.last_bufs["parent"][:nv])
    l0 = np.asarray(ck0.last_bufs["lane"][:nv])
    wk0 = _work(ck0)
    disp0 = ck0.last_stats["dispatches_per_level"]
    for kw in IMPL_COMBOS:
        ck = _mk(c, **kw)
        r = ck.run()
        assert r.distinct_states == nv, kw
        assert r.level_sizes == r0.level_sizes, kw
        assert np.array_equal(
            np.asarray(ck.last_bufs["rows"][: nv * W]), rows0
        ), kw
        assert np.array_equal(
            np.asarray(ck.last_bufs["parent"][:nv]), p0
        ), kw
        assert np.array_equal(
            np.asarray(ck.last_bufs["lane"][:nv]), l0
        ), kw
        assert _work(ck) == wk0, kw
        assert ck.last_stats["dispatches_per_level"] == disp0, kw


def test_tiered_sieve_impl_state_for_state():
    """A budgeted producer_on run with the tiled cold-extract: same
    discovery and the same spill economy as the legacy sieve."""
    from tests.helpers import tight_hbm_budget

    c = SMALL_CONFIGS["producer_on"]
    # test_store's spill shape: caps well under the 1654-state
    # reachable set so the pinned-tier budget MUST evict
    kw = dict(
        sub_batch=64, visited_cap=1 << 9, frontier_cap=1 << 9,
        check_deadlock=False,
    )
    budget = tight_hbm_budget(lambda b: _mk(c, hbm_budget=b, **kw))
    ck_l = _mk(c, hbm_budget=budget, **kw)
    r_l = ck_l.run()
    assert ck_l.last_stats["spill_evictions"] >= 1
    for impl in ("tile", "pallas"):
        ck_t = _mk(c, hbm_budget=budget, sieve_impl=impl, **kw)
        r_t = ck_t.run()
        assert r_t.distinct_states == r_l.distinct_states, impl
        assert r_t.level_sizes == r_l.level_sizes, impl
        for k in (
            "spill_evictions", "spill_keys_evicted",
            "spill_rows_evicted", "spill_misses_resolved",
        ):
            assert ck_t.last_stats[k] == ck_l.last_stats[k], (impl, k)


# the untiered device engine's deterministic verdicts at these exact
# shapes (sub_batch 512, visited_cap 2^11) — the same pins
# tests/test_store.py replays the tiered store against
BUG_ORACLE_PINS = {
    "CompactedLedgerLeak": (23329, 12),
    "DuplicateNullKeyMessage": (3645, 4),
}


@pytest.mark.parametrize("invariant", sorted(BUG_ORACLE_PINS))
def test_bug_oracles_identical_under_tile_impls(invariant):
    """Both published counterexamples through the tile kernels: the
    pinned violation gid + diameter, and a replayed trace the oracle
    validates step by step."""
    gid, depth = BUG_ORACLE_PINS[invariant]
    ck = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), invariants=(invariant,),
        sub_batch=512, visited_cap=1 << 11, frontier_cap=1 << 11,
        probe_impl="tile", expand_impl="tile",
    )
    r = ck.run()
    assert r.violation == invariant
    assert r.violation_gid == gid
    assert r.diameter == depth
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, invariant
    )


def test_bug_oracle_identical_under_pallas_probe():
    """The shallow published counterexample through the Pallas probe
    (interpret mode off-TPU): identical pinned verdict."""
    gid, depth = BUG_ORACLE_PINS["DuplicateNullKeyMessage"]
    r = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG),
        invariants=("DuplicateNullKeyMessage",),
        sub_batch=512, visited_cap=1 << 11, frontier_cap=1 << 11,
        probe_impl="pallas",
    ).run()
    assert r.violation_gid == gid and r.diameter == depth


# ---- knob plumbing --------------------------------------------------


def test_ctor_validates_impls():
    c = SMALL_CONFIGS["producer_on"]
    for knob in ("probe_impl", "expand_impl", "sieve_impl"):
        with pytest.raises(ValueError, match=knob):
            _mk(c, **{knob: "warp"})


def test_impls_resolve_from_profile_with_explicit_wins(tmp_path):
    """A tuned profile's impl knobs land on the engine; an explicit
    ctor value still wins; prewarm compiles the TUNED programs (zero
    post-run compiles)."""
    os.environ["PTT_TUNE_DIR"] = str(tmp_path)
    try:
        c = SMALL_CONFIGS["producer_on"]
        m = CompactionModel(c)
        sig = profiles.profile_key(
            model=m, invariants=(), engine="device_bfs"
        )
        profiles.save(
            profiles.build(
                sig=sig, engine="device_bfs", backend="cpu",
                knobs={"probe_impl": "tile", "expand_impl": "tile"},
                spec="compaction",
            )
        )
        ck = _mk(c, profile="auto")
        assert ck.profile_sig == sig
        assert ck.probe_impl == "tile"
        assert ck.expand_impl == "tile"
        assert ck.sieve_impl == "legacy"
        # explicit ctor value beats the profile
        ck2 = _mk(c, profile="auto", probe_impl="legacy")
        assert ck2.probe_impl == "legacy"
        assert ck2.expand_impl == "tile"
        # prewarm covers the tuned impl programs: zero new jit keys
        # after a real run (tiers=True = every reachable capacity
        # tier, the r10 contract)
        ck.warmup(tiers=True)
        keys = set(ck._jits)
        ck.run()
        assert set(ck._jits) == keys
    finally:
        del os.environ["PTT_TUNE_DIR"]


def test_profile_validator_rejects_bad_impl(tmp_path):
    p = tmp_path / "prof.json"
    prof = profiles.build(
        sig="cafecafecafecafe", engine="device_bfs", backend="cpu",
        knobs={"probe_impl": "warp"}, spec="compaction",
    )
    p.write_text(json.dumps(prof))
    errs = profiles.validate(prof, str(p))
    assert any("probe_impl" in e for e in errs)
    ok = dict(prof, knobs={"probe_impl": "pallas"})
    assert not [
        e for e in profiles.validate(ok, str(p)) if "probe_impl" in e
    ]


def test_impls_in_search_space():
    """probe/expand are searched in the device space; sieve rides the
    budgeted (spill) product only; all three are PROFILE_KNOBS."""
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    cands = tune_space.candidates(m, limit=None)
    assert any(c.get("probe_impl") == "tile" for c in cands)
    assert any(c.get("expand_impl") == "pallas" for c in cands)
    assert not any("sieve_impl" in c for c in cands)
    spill = tune_space.candidates(m, spill=True, limit=None)
    assert any(c.get("sieve_impl") == "tile" for c in spill)
    for k in ("probe_impl", "expand_impl", "sieve_impl"):
        assert k in tune_space.PROFILE_KNOBS["device_bfs"]


def test_predict_prices_impls():
    """The cost model separates the impls: on the CPU ratio table the
    tile probe is priced above legacy (the measured r23 prefilter
    overhead) and the tile expand below; a calibrated per-impl unit
    overrides the table."""
    ref = {
        "backend": "cpu",
        "work": {"probe_lanes": 10_000_000, "expand_rows": 1_000_000},
        "level_sizes": [10, 100, 1000],
        "avg_probe_rounds": 2.0,
        "probe_impl": "legacy", "expand_impl": "legacy",
    }
    base = predict.predict_candidate({}, ref)["est_s"]
    tile_p = predict.predict_candidate({"probe_impl": "tile"}, ref)
    tile_e = predict.predict_candidate({"expand_impl": "tile"}, ref)
    assert tile_p["est_s"] > base
    assert tile_e["est_s"] < base
    cal = {
        "units": {
            "probe_lane_ns": 100.0, "expand_row_ns": 10.0,
            "probe_lane_tile_ns": 50.0,
        },
        "rtt_s": 2e-4,
    }
    fast = predict.predict_candidate({"probe_impl": "tile"}, ref, cal)
    slow = predict.predict_candidate({}, ref, cal)
    assert fast["est_s"] < slow["est_s"]


def test_reference_of_carries_impls():
    c = SMALL_CONFIGS["producer_on"]
    ck = _mk(c, probe_impl="tile")
    r = ck.run()
    ref = predict.reference_of(ck, r)
    assert ref["probe_impl"] == "tile"
    assert ref["expand_impl"] == "legacy"
    assert ref["sieve_impl"] == "legacy"


# ---- telemetry v16 + bench_schema 12 --------------------------------


def test_run_header_carries_impls(tmp_path):
    stream = str(tmp_path / "s.jsonl")
    _mk(
        SMALL_CONFIGS["producer_on"], telemetry=stream,
        probe_impl="tile", sieve_impl="tile",
    ).run()
    ckr = _checker_mod()
    assert ckr.validate_stream(stream) == []
    with open(stream) as f:
        hd = next(
            json.loads(ln) for ln in f
            if json.loads(ln).get("event") == "run_header"
        )
    assert hd["v"] == 16
    assert hd["probe_impl"] == "tile"
    assert hd["expand_impl"] == "legacy"
    assert hd["sieve_impl"] == "tile"


def test_bench_schema_v12_keys():
    """bench_schema 12 artifacts must carry the impl keys +
    probe_lanes_per_sec; a v12 artifact missing them fails; a v11
    artifact without them stays clean (additive versioning)."""
    ckr = _checker_mod()
    base = {k: 1 for k in ckr.BENCH_KEYS_V12}
    base.update(bench_schema=12, value=1.0)
    assert ckr.validate_bench_artifact(dict(base), "good") == []
    bad = dict(base)
    del bad["probe_impl"], bad["probe_lanes_per_sec"]
    errs = ckr.validate_bench_artifact(bad, "bad")
    assert any("probe_impl" in e for e in errs)
    assert any("probe_lanes_per_sec" in e for e in errs)
    v11 = {k: 1 for k in ckr.BENCH_KEYS_V11}
    v11.update(bench_schema=11, value=1.0)
    assert ckr.validate_bench_artifact(v11, "v11") == []


# ---- the tiles ledger gate ------------------------------------------


def test_tiles_ledger_gate_against_committed_baseline(tmp_path):
    """THE r23 gate: a fresh tile-impl producer_on run shares the
    legacy runs' comparability class (impls are NOT in the config
    key) and gates clean against the committed tile mini baseline on
    the deterministic economy keys; a tampered (better-than-
    reality) baseline fails loudly — wall-clock never enters."""
    baseline = ledger.load(TILES_PINNED)[-1]
    assert ledger.validate_ledger(TILES_PINNED) == []
    assert "visited=fpset|compact=logshift|fuse=level" in baseline["key"]
    stream = str(tmp_path / "run.jsonl")
    _mk(
        SMALL_CONFIGS["producer_on"], telemetry=stream,
        probe_impl="tile", expand_impl="tile",
    ).run()
    cur = ledger.record_from_file(stream)
    assert cur["key"] == baseline["key"]  # same comparability class
    assert (
        ledger.gate(
            baseline, cur, threshold=0.1, keys=ledger.TILES_GATE_KEYS
        )
        == []
    )
    # negative: shrink the baseline's economy so the identical fresh
    # run reads as a regression — deterministic, no timing flake
    tampered = dict(baseline, values=dict(baseline["values"]))
    for k in ledger.TILES_GATE_KEYS:
        tampered["values"][k] = tampered["values"][k] / 2
    tampered["digest"] = ledger._digest(tampered["values"])
    violations = ledger.gate(
        tampered, cur, threshold=0.1, keys=ledger.TILES_GATE_KEYS
    )
    assert {v["key"] for v in violations} == set(ledger.TILES_GATE_KEYS)


def test_tiles_record_derives_probe_lanes_per_sec(tmp_path):
    """Stream-ingested records derive the r23 throughput signal from
    the work counters + wall clock."""
    stream = str(tmp_path / "run.jsonl")
    _mk(
        SMALL_CONFIGS["producer_on"], telemetry=stream,
        probe_impl="tile",
    ).run()
    rec = ledger.record_from_file(stream)
    v = rec["values"]
    assert v["probe_lanes_per_sec"] == round(
        v["work_probe_lanes"] / v["wall_s"], 1
    )
    assert v["probe_impl"] == "tile"
