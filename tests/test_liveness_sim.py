"""Liveness (SURVEY.md §2.2-E10) and simulation-mode (E9) tests."""

import dataclasses

import pytest

from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker
from pulsar_tlaplus_tpu.engine.simulate import Simulator
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import needs_shard_map, SMALL_CONFIGS, assert_valid_counterexample

LIVENESS_CASES = {
    "producer_on": SMALL_CONFIGS["producer_on"],
    "two_crashes": SMALL_CONFIGS["two_crashes"],
    # Consumer modeled: consumeTimes never advances (the spec's stub
    # consumer, compaction.tla:185-186 and the TODO at :299), so the goal is
    # unreachable and the Consumer self-loop is a fair not-goal cycle.
    "consumer_on": dataclasses.replace(
        SMALL_CONFIGS["producer_on"], model_consumer=True
    ),
}


@pytest.mark.parametrize("name", sorted(LIVENESS_CASES))
@pytest.mark.parametrize("fairness", ["none", "wf_next"])
def test_liveness_matches_oracle(name, fairness):
    c = LIVENESS_CASES[name]
    want_holds, _ = pe.check_eventually(c, fairness)
    got = LivenessChecker(
        CompactionModel(c),
        fairness=fairness,
        frontier_chunk=512,
        visited_cap=1 << 13,
    ).run()
    assert got.holds == want_holds


def test_liveness_scales_past_round2_cap():
    """VERDICT r2 #8: liveness exploration now runs on the device
    engine, so a state space far beyond the old host-staged explorer's
    comfort zone (253,361 states, the published full-cfg oracle) gets a
    Termination verdict in one run."""
    c = dataclasses.replace(
        pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
    )
    got = LivenessChecker(
        CompactionModel(c),
        fairness="none",
        frontier_chunk=4096,
        visited_cap=1 << 18,
    ).run()
    assert got.distinct_states == 253361
    want_holds, _ = pe.check_eventually(c, "none")
    assert got.holds == want_holds


def test_liveness_wf_holds_on_plain_configs():
    # the substantive verdict: Termination genuinely holds under
    # WF_vars(Next) (ledger ids grow monotonically to the limit), and is
    # trivially violated without fairness (TLC's stuttering semantics)
    c = SMALL_CONFIGS["producer_on"]
    assert LivenessChecker(CompactionModel(c), fairness="wf_next",
                           visited_cap=1 << 13).run().holds
    assert not LivenessChecker(CompactionModel(c), fairness="none",
                               visited_cap=1 << 13).run().holds


def test_simulation_finds_leak_violation():
    m = CompactionModel(pe.SHIPPED_CFG)
    sim = Simulator(
        m,
        invariants=("TypeSafe", "CompactedLedgerLeak"),
        n_walkers=512,
        depth=48,
        seed=1,
    )
    r = sim.run()
    assert r.violation == "CompactedLedgerLeak"
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_simulation_clean_on_active_invariants():
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    r = Simulator(m, n_walkers=256, depth=32, seed=0).run()
    assert r.violation is None
    assert r.states_visited == 256 * 33


def test_liveness_wf_next_at_full_cfg_scale():
    """VERDICT r3 #5: wf_next must materialize the full edge list at
    the 253,361-state published-oracle scale — the round-3 scale test
    used fairness="none", which never builds edges.  The device
    merge-join sweep (key->gid table + one sort per chunk) makes this
    tractable; the verdict must match the Python oracle's wf_next
    semantics on the same config."""
    c = dataclasses.replace(
        pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
    )
    got = LivenessChecker(
        CompactionModel(c),
        fairness="wf_next",
        frontier_chunk=8192,
        visited_cap=1 << 18,
    ).run()
    assert got.distinct_states == 253361
    # the oracle's graph analysis at 253k states is slow but feasible
    want_holds, _ = pe.check_eventually(c, "wf_next")
    assert got.holds == want_holds


@pytest.mark.parametrize("fairness", ["none", "wf_next"])
@needs_shard_map
def test_liveness_sharded_exploration_matches_oracle(fairness):
    """Round 5 (VERDICT r4 #7): LivenessChecker can explore on the
    mesh-sharded engine; the per-shard row stores are remapped to a
    dense gid space before the (single-device) edge sweep, and the
    verdict matches the oracle exactly."""
    c = LIVENESS_CASES["producer_on"]
    want_holds, _ = pe.check_eventually(c, fairness)
    got = LivenessChecker(
        CompactionModel(c),
        fairness=fairness,
        frontier_chunk=512,
        visited_cap=1 << 13,
        n_devices=4,
    ).run()
    assert got.holds == want_holds
    want = pe.check(c, invariants=())
    assert got.distinct_states == want.distinct_states
