"""Differential tests for the subscription spec (specs/subscription.tla):
compiled TPU model vs the generic interpreter on the same .tla source —
state sets, counts, diameters, invariant verdicts, counterexample traces,
sharded parity, liveness, and simulation mode."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker
from pulsar_tlaplus_tpu.frontend.interp import Spec, install_defs
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.models.subscription import (
    SubscriptionConstants,
    SubscriptionModel,
)
from tests.helpers import needs_shard_map, tight_hbm_budget

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "specs",
    "subscription.tla",
)

CONFIGS = {
    "tiny": SubscriptionConstants(message_limit=2, max_crash_times=1),
    "shipped": SubscriptionConstants(message_limit=3, max_crash_times=2),
    "no_crash": SubscriptionConstants(message_limit=3, max_crash_times=0),
}


@pytest.fixture(scope="module")
def module():
    return parse_file(SPEC_PATH)


def spec_for(module, c: SubscriptionConstants) -> Spec:
    return Spec(
        module,
        {"MessageLimit": c.message_limit, "MaxCrashTimes": c.max_crash_times},
    )


def run_model(c, **kw):
    m = SubscriptionModel(c)
    return m, Checker(m, frontier_chunk=256, keep_log=True, **kw).run()


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_counts_and_verdicts_match_interpreter(module, name):
    c = CONFIGS[name]
    spec = spec_for(module, c)
    ri = InterpChecker(
        spec, invariants=("TypeOK", "NoLostMessage", "AckedWasProcessed")
    ).run()
    m, rm = run_model(c)
    assert ri.violation is None and rm.violation is None
    assert not ri.deadlock and not rm.deadlock
    assert rm.distinct_states == ri.distinct_states
    assert rm.diameter == ri.diameter
    assert rm.level_sizes == ri.level_sizes


def test_exact_state_set_matches_interpreter(module):
    c = CONFIGS["tiny"]
    spec = spec_for(module, c)
    install_defs(spec)
    expected = {spec.initial_states()[0]}
    frontier = list(expected)
    while frontier:
        new = []
        for s in frontier:
            for _lab, t in spec.successors(s):
                if t not in expected:
                    expected.add(t)
                    new.append(t)
        frontier = new
    m = SubscriptionModel(c)
    ck = Checker(m, frontier_chunk=256, keep_log=True)
    ck.run()
    packed = ck.last_run_state.log.packed_matrix()
    unpack = jax.jit(m.layout.unpack)
    got = {
        m.to_interp_state(unpack(jnp.asarray(row))) for row in packed
    }
    assert got == expected


def test_golden_bug_duplicate_processing(module):
    """ExactlyOnceProcessing is violated (at-least-once delivery); both
    paths find the same shortest depth and the trace replays on the
    interpreter semantics."""
    c = CONFIGS["shipped"]
    spec = spec_for(module, c)
    install_defs(spec)
    ri = InterpChecker(spec, invariants=("ExactlyOnceProcessing",)).run()
    m, rm = run_model(c, invariants=("ExactlyOnceProcessing",))
    assert ri.violation == rm.violation == "ExactlyOnceProcessing"
    assert len(ri.trace) == len(rm.trace) == 7
    assert rm.trace_actions == [
        "Publish", "Deliver", "Process", "ConsumerCrash", "Deliver", "Process",
    ]
    # only the final state violates; duplicate visible only at the end
    assert rm.trace[0]["produced"] == 0
    assert rm.trace[-1]["duplicated"] != "{}"
    for st in rm.trace[:-1]:
        assert st["duplicated"] == "{}"
    # the compiled trace replays step by step on the interpreter semantics:
    # every consecutive rendered state must be a real labeled transition
    rendered = lambda t: m.to_pystate(m.from_interp_state(t))
    cur = spec.initial_states()[0]
    assert rendered(cur) == rm.trace[0]
    for act, want in zip(rm.trace_actions, rm.trace[1:]):
        nxt = [
            t
            for lab, t in spec.successors(cur)
            if lab == act and rendered(t) == want
        ]
        assert nxt, (act, want)
        cur = nxt[0]


def test_no_crash_config_is_exactly_once(module):
    """With MaxCrashTimes = 0 no duplicate is reachable: the bug invariant
    HOLDS, pinning that redelivery-after-crash is the only dup source."""
    c = CONFIGS["no_crash"]
    m, rm = run_model(c, invariants=("ExactlyOnceProcessing",))
    assert rm.violation is None
    spec = spec_for(module, c)
    ri = InterpChecker(spec, invariants=("ExactlyOnceProcessing",)).run()
    assert ri.violation is None
    assert ri.distinct_states == rm.distinct_states


@needs_shard_map
def test_sharded_counts_match():
    from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker

    c = CONFIGS["tiny"]
    m = SubscriptionModel(c)
    base = Checker(m, frontier_chunk=256).run()
    for nd in (2, 4, 8):
        r = ShardedChecker(
            m, n_devices=nd, frontier_chunk=64, visited_cap=1 << 10
        ).run()
        assert r.distinct_states == base.distinct_states, nd
        assert r.diameter == base.diameter


def test_liveness_termination():
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    c = CONFIGS["tiny"]
    m = SubscriptionModel(c)
    r = LivenessChecker(m, goal="Termination", fairness="wf_next").run()
    assert r.holds, r.reason
    r2 = LivenessChecker(m, goal="Termination", fairness="none").run()
    assert not r2.holds  # raw Spec admits infinite stuttering at Init


# Subscription becomes the FOURTH exact-parity pinned workload beside
# compaction (45,198 / 253,361), bookkeeper (297 / 2,257), and
# georeplication (6,400): the shipped binding (specs/subscription.cfg —
# MessageLimit 3, MaxCrashTimes 2) pins 2,272 states / diameter 24 on
# the interpreter, the host engine, AND the device engine.  Derived
# from the interpreter BFS on specs/subscription.tla; the tiny binding
# (122 / 16) re-derives inline as the cheap cross-check.  It is also
# the round-16 SPILL-PARITY differential workload: the same device run
# under a budget that forces key eviction + row/log spill must be
# state-for-state identical (tests below; docs/memory.md).

SHIPPED_STATES, SHIPPED_DIAMETER = 2272, 24  # specs/subscription.cfg
TINY_STATES, TINY_DIAMETER = 122, 16


def test_shipped_cfg_pinned_oracle_count(module):
    """Interpreter, host engine, and device engine all reproduce the
    pinned shipped-binding count — the exact-parity contract the
    other three registry workloads already carry."""
    c = CONFIGS["shipped"]
    ri = InterpChecker(
        spec_for(module, c),
        invariants=("TypeOK", "NoLostMessage", "AckedWasProcessed"),
    ).run()
    assert (ri.distinct_states, ri.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    rh = Checker(SubscriptionModel(c), frontier_chunk=256).run()
    assert (rh.distinct_states, rh.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    rd = DeviceChecker(
        SubscriptionModel(c), sub_batch=256, visited_cap=1 << 12,
        frontier_cap=1 << 10,
    ).run()
    assert (rd.distinct_states, rd.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    assert rd.violation is None and not rd.deadlock
    ti = InterpChecker(
        spec_for(module, CONFIGS["tiny"]),
        invariants=("TypeOK", "NoLostMessage", "AckedWasProcessed"),
    ).run()
    assert (ti.distinct_states, ti.diameter) == (
        TINY_STATES, TINY_DIAMETER,
    )


def test_shipped_cfg_spill_parity_differential():
    """The round-16 spill-parity workload: the shipped subscription
    run under a budget that forces eviction + row/log spill is
    state-for-state identical to the untiered run — level sizes,
    packed rows, and parent/lane logs (merged cold+device view)."""
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    c = CONFIGS["shipped"]
    kw = dict(
        invariants=(), check_deadlock=False, sub_batch=128,
        visited_cap=1 << 9, frontier_cap=1 << 9,
    )
    ck_u = DeviceChecker(SubscriptionModel(c), **kw)
    r_u = ck_u.run()
    assert (r_u.distinct_states, r_u.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    budget = tight_hbm_budget(
        lambda b: DeviceChecker(SubscriptionModel(c), hbm_budget=b, **kw)
    )
    ck_t = DeviceChecker(SubscriptionModel(c), hbm_budget=budget, **kw)
    r_t = ck_t.run()
    assert r_t.distinct_states == r_u.distinct_states
    assert r_t.level_sizes == r_u.level_sizes
    assert ck_t.last_stats["spill_evictions"] >= 1
    assert ck_t.last_stats["spill_rows_evicted"] > 0
    nv, W = r_u.distinct_states, ck_u.W
    base = ck_t._last_rb["row_base"]
    cp, cl = ck_t.tstore.fetch_logs(0, base)
    pt = np.concatenate(
        [cp, np.asarray(ck_t.last_bufs["parent"][: nv - base])]
    )
    lt = np.concatenate(
        [cl, np.asarray(ck_t.last_bufs["lane"][: nv - base])]
    )
    assert (np.asarray(ck_u.last_bufs["parent"][:nv]) == pt).all()
    assert (np.asarray(ck_u.last_bufs["lane"][:nv]) == lt).all()
    cold = ck_t.tstore.fetch_rows(0, base, W)
    rt = np.concatenate(
        [cold, np.asarray(ck_t.last_bufs["rows"][: (nv - base) * W])]
    )
    assert (np.asarray(ck_u.last_bufs["rows"][: nv * W]) == rt).all()


def test_simulation_finds_duplicate():
    from pulsar_tlaplus_tpu.engine.simulate import Simulator

    c = CONFIGS["shipped"]
    m = SubscriptionModel(c)
    sres = Simulator(
        m,
        invariants=("ExactlyOnceProcessing",),
        n_walkers=512,
        depth=32,
        seed=3,
    ).run()
    assert sres.violation == "ExactlyOnceProcessing"
    assert sres.trace[-1]["duplicated"] != "{}"
    for st in sres.trace[:-1]:
        assert st["duplicated"] == "{}"
