"""Round-10 tentpole tests: the sort-free log-shift stream compaction
(`ops/compact.py`) — kernel properties against the sort path, engine
discovery-order differentials pinned state-for-state on the published
oracles, the fused+grouped liveness sweep parity, the capacity-tier
prewarm (zero post-run() compiles), and the fpset probe-schedule
exposure."""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ops import compact, dedup, fpset
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, needs_shard_map

CONSUMER_CFG = dataclasses.replace(
    SMALL_CONFIGS["producer_on"], model_consumer=True
)
FULL_CFG = dataclasses.replace(
    pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
)


# ---- kernel properties ----------------------------------------------


def _ref_compact(drop, cols):
    kept = np.nonzero(drop == 0)[0]
    return [c[kept] for c in cols], kept


@pytest.mark.parametrize("mat", ["shift", "gather"])
def test_logshift_matches_sort_random_masks_and_widths(
    mat, monkeypatch
):
    """Random masks, drop rates, lengths (incl. non-powers-of-two) and
    column counts, under BOTH materializations (the TPU doubling-shift
    passes and the CPU prefix+gather): the kept prefix must equal the
    numpy reference AND the sort path element-for-element, idx
    included."""
    monkeypatch.setenv("PTT_COMPACT_MATERIALIZE", mat)
    rng = np.random.default_rng(0)
    for trial in range(10):
        n = int(rng.integers(1, 200))
        p = rng.uniform(0, 1)
        drop = (rng.random(n) < p).astype(np.uint32)
        ncols = int(rng.integers(1, 4))
        cols = [
            rng.integers(0, 2**32, size=n, dtype=np.uint32)
            for _ in range(ncols)
        ]
        jcols = tuple(jnp.asarray(c) for c in cols)
        out, idx = compact.logshift_compact(jnp.asarray(drop), jcols)
        sout, sidx = dedup.compact_by_flag(jnp.asarray(drop), jcols)
        ref_cols, kept = _ref_compact(drop, cols)
        k = len(kept)
        for got, srt, want in zip(out, sout, ref_cols):
            assert np.array_equal(np.asarray(got)[:k], want), trial
            assert np.array_equal(np.asarray(srt)[:k], want), trial
        assert np.array_equal(np.asarray(idx)[:k], kept), trial
        assert np.array_equal(np.asarray(sidx)[:k], kept), trial


@pytest.mark.parametrize("mat", ["shift", "gather"])
@pytest.mark.parametrize("n", [1, 2, 129])
@pytest.mark.parametrize("all_drop", [False, True])
def test_logshift_all_keep_all_drop_edges(n, all_drop, mat, monkeypatch):
    monkeypatch.setenv("PTT_COMPACT_MATERIALIZE", mat)
    drop = np.full(n, 1 if all_drop else 0, np.uint32)
    c = np.arange(n, dtype=np.uint32) * 3
    out, idx = compact.logshift_compact(
        jnp.asarray(drop), (jnp.asarray(c),)
    )
    k = 0 if all_drop else n
    assert np.array_equal(np.asarray(out[0])[:k], c[:k])
    assert np.array_equal(np.asarray(idx)[:k], np.arange(k))


def test_device_engine_shift_materialization_state_for_state(
    monkeypatch,
):
    """The TPU materialization (doubling shifts) forced end-to-end
    through the device engine on the CPU backend: identical logs to
    the sort path."""
    monkeypatch.setenv("PTT_COMPACT_MATERIALIZE", "shift")
    c = SMALL_CONFIGS["producer_on"]
    logs = {}
    for impl in ("logshift", "sort"):
        ck = DeviceChecker(
            CompactionModel(c), invariants=(), sub_batch=64,
            visited_cap=1 << 8, frontier_cap=1 << 8, group=2,
            compact_impl=impl,
        )
        r = ck.run()
        n = r.distinct_states
        logs[impl] = (
            n,
            np.asarray(ck.last_bufs["parent"][:n]).copy(),
            np.asarray(ck.last_bufs["lane"][:n]).copy(),
        )
    assert logs["logshift"][0] == logs["sort"][0]
    assert np.array_equal(logs["logshift"][1], logs["sort"][1])
    assert np.array_equal(logs["logshift"][2], logs["sort"][2])


def test_materialization_env_validation(monkeypatch):
    monkeypatch.setenv("PTT_COMPACT_MATERIALIZE", "bogus")
    with pytest.raises(ValueError, match="shift|gather"):
        compact.logshift_compact(
            jnp.zeros((4,), jnp.uint32),
            (jnp.arange(4, dtype=jnp.uint32),),
        )


def test_compact_dispatcher_validates_impl():
    drop = jnp.zeros((4,), jnp.uint32)
    cols = (jnp.arange(4, dtype=jnp.uint32),)
    with pytest.raises(ValueError, match="logshift|sort"):
        compact.compact_by_flag(drop, cols, impl="bogus")
    # need_idx=False skips the iota column
    out, idx = compact.compact_by_flag(drop, cols, need_idx=False)
    assert idx is None and np.array_equal(np.asarray(out[0]),
                                          np.arange(4))


# ---- engine differential: logshift vs sort, state for state ----------


def test_device_engine_compact_differential_state_for_state():
    """Same model, both compaction impls, growth + mid-level syncs
    forced by tiny caps: identical counts, levels, AND identical row
    stores / parent / lane logs — the log-shift append must assign
    every gid exactly like the sort append."""
    c = SMALL_CONFIGS["producer_on"]
    m = CompactionModel(c)
    results = {}
    for impl in ("logshift", "sort"):
        ck = DeviceChecker(
            CompactionModel(c), invariants=(), sub_batch=64,
            visited_cap=1 << 6, frontier_cap=1 << 6, group=2,
            compact_impl=impl,
        )
        r = ck.run()
        n = r.distinct_states
        results[impl] = (
            r,
            np.asarray(ck.last_bufs["rows"][: n * m.layout.W]).copy(),
            np.asarray(ck.last_bufs["parent"][:n]).copy(),
            np.asarray(ck.last_bufs["lane"][:n]).copy(),
        )
    rl, rows_l, par_l, lane_l = results["logshift"]
    rs, rows_s, par_s, lane_s = results["sort"]
    want = pe.check(c, invariants=())
    assert rl.distinct_states == rs.distinct_states == want.distinct_states
    assert rl.level_sizes == rs.level_sizes
    assert np.array_equal(rows_l, rows_s)
    assert np.array_equal(par_l, par_s)
    assert np.array_equal(lane_l, lane_s)


def test_device_engine_shipped_oracle_sort_compact_impl():
    """First published oracle (45,198 / diameter 20, compaction.tla:23)
    pinned on the SORT compaction path explicitly (the rest of the
    suite pins it on the logshift default — this stays meaningful if
    the default ever flips back)."""
    r = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15, compact_impl="sort",
    ).run()
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock


@pytest.mark.slow
def test_device_engine_full_cfg_compact_differential():
    """Second published oracle (253,361 / diameter 23): logshift vs
    sort pinned state-for-state (parent/lane logs equal) at the
    round-6 differential shape — the acceptance oracle for the
    CPU-mesh append differential.  Slow-marked (two full-cfg runs) so
    tier-1 stays inside its budget; the real host runs it, and the
    45k state-for-state + the small-config differentials cover the
    same property in-tier."""
    m = CompactionModel(FULL_CFG)
    logs = {}
    for impl in ("logshift", "sort"):
        ck = DeviceChecker(
            CompactionModel(FULL_CFG), invariants=(), sub_batch=4096,
            visited_cap=1 << 18, frontier_cap=1 << 17, flush_factor=2,
            compact_impl=impl,
        )
        r = ck.run()
        assert r.distinct_states == 253361
        assert r.diameter == 23
        n = r.distinct_states
        logs[impl] = (
            np.asarray(ck.last_bufs["parent"][:n]).copy(),
            np.asarray(ck.last_bufs["lane"][:n]).copy(),
        )
        del ck
    assert np.array_equal(logs["logshift"][0], logs["sort"][0])
    assert np.array_equal(logs["logshift"][1], logs["sort"][1])


@needs_shard_map
def test_sharded_engine_compact_differential_state_for_state():
    """The sharded append's compaction carries rows + routed parent +
    lane: both impls must produce identical per-shard stores on the
    virtual mesh."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    stores = {}
    for impl in ("logshift", "sort"):
        ck = ShardedDeviceChecker(
            CompactionModel(c), n_devices=4, invariants=(),
            sub_batch=64, visited_cap=1 << 6, group=2,
            compact_impl=impl,
        )
        r = ck.run()
        assert r.distinct_states == want.distinct_states
        assert r.diameter == want.diameter
        counts = np.asarray(ck.last_stats_matrix[:, 0])
        stores[impl] = [
            (
                np.asarray(
                    ck.last_bufs["rows"][s, : int(counts[s]) * ck.W]
                ).copy(),
                np.asarray(
                    ck.last_bufs["parent"][s, : int(counts[s])]
                ).copy(),
                np.asarray(
                    ck.last_bufs["lane"][s, : int(counts[s])]
                ).copy(),
            )
            for s in range(ck.N)
        ]
    for (ra, pa, la), (rb, pb, lb) in zip(
        stores["logshift"], stores["sort"]
    ):
        assert np.array_equal(ra, rb)
        assert np.array_equal(pa, pb)
        assert np.array_equal(la, lb)


@needs_shard_map
@pytest.mark.slow
@pytest.mark.parametrize("impl", ["logshift", "sort"])
def test_sharded_engine_full_cfg_both_compact_impls(impl):
    """253,361 pinned on the sharded engine under both compaction
    impls (slow: two full-cfg runs on the virtual mesh — tier-1 skips
    via -m 'not slow'; the real host runs it)."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    r = ShardedDeviceChecker(
        CompactionModel(FULL_CFG), n_devices=4, invariants=(),
        sub_batch=2048, visited_cap=1 << 16, compact_impl=impl,
    ).run()
    assert r.distinct_states == 253361
    assert r.diameter == 23


# ---- fused + grouped liveness sweep ---------------------------------


def test_liveness_fused_sweep_parity_consumer_oracle():
    """The grouped sweep (G chunks per dispatch) must produce the same
    wf_next verdict, edge count, and out-degrees as the per-chunk
    pipeline on the consumer_on lasso oracle, for sort and logshift
    compaction alike."""
    want_holds, _ = pe.check_eventually(CONSUMER_CFG, "wf_next")
    base = None
    for kw in (
        dict(sweep_group=1),
        dict(sweep_group=3),
        dict(sweep_group=2, compact_impl="sort"),
    ):
        lck = LivenessChecker(
            CompactionModel(CONSUMER_CFG), fairness="wf_next",
            frontier_chunk=256, sweep_chunk=256, visited_cap=1 << 13,
            **kw,
        )
        r = lck.run()
        assert r.holds == want_holds is False
        assert r.lasso_cycle
        src, dst, out_deg = lck._edge_cache
        sig = (
            len(src),
            int(out_deg.sum()),
            hash(tuple(np.sort(src * 10_000_000 + dst).tolist())),
        )
        if base is None:
            base = sig
        else:
            assert sig == base, kw


def test_liveness_group_exceeding_chunks_is_safe():
    """A sweep_group larger than the chunk count: overrun windows are
    masked dead and the verdict is unchanged."""
    want_holds, _ = pe.check_eventually(CONSUMER_CFG, "wf_next")
    r = LivenessChecker(
        CompactionModel(CONSUMER_CFG), fairness="wf_next",
        frontier_chunk=256, sweep_chunk=256, visited_cap=1 << 13,
        sweep_group=64,
    ).run()
    assert r.holds == want_holds


# ---- capacity-tier prewarm (VERDICT r5 #8) --------------------------


def test_prewarm_compiles_every_tier_before_run():
    """warmup(tiers=True) walks the growth schedule: a run that
    crosses capacity tiers must add ZERO new jitted programs after
    run() starts (the 317 s mid-window lazy compile, retired)."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ck = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=64,
        visited_cap=1 << 6, frontier_cap=1 << 6, group=2,
        max_states=1 << 12,
    )
    v0 = ck.VCAP
    ck.warmup(seed=False, tiers=True)
    keys_before = set(ck._jits)
    r = ck.run()
    assert set(ck._jits) == keys_before  # zero post-run() compiles
    assert ck.VCAP > v0  # the run genuinely crossed visited tiers
    assert r.distinct_states == want.distinct_states
    assert r.diameter == want.diameter
    # control: a tiers=False warmup compiles strictly fewer programs —
    # the crossing run above genuinely needed the prewarmed tier keys
    ck2 = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=64,
        visited_cap=1 << 6, frontier_cap=1 << 6, group=2,
        max_states=1 << 12,
    )
    ck2.warmup(seed=False, tiers=False)
    assert set(ck2._jits) < keys_before


@needs_shard_map
def test_sharded_prewarm_compiles_every_tier_before_run():
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ck = ShardedDeviceChecker(
        CompactionModel(c), n_devices=2, invariants=(), sub_batch=64,
        visited_cap=1 << 6, group=2, max_states=1 << 12,
    )
    ck.warmup(tiers=True)
    keys_before = set(ck._jits)
    r = ck.run()
    assert set(ck._jits) == keys_before
    assert r.distinct_states == want.distinct_states


# ---- fpset probe-schedule exposure ----------------------------------


def test_fpset_schedule_parse_and_env_override(monkeypatch):
    assert fpset.parse_schedule("4,4:16,16:64") == (
        4, ((4, 16), (16, 64))
    )
    with pytest.raises(ValueError, match="DIV:LIMIT"):
        fpset.parse_schedule("4,banana")
    with pytest.raises(ValueError, match="dense round count"):
        fpset.parse_schedule("x,4:16")
    monkeypatch.setenv("PTT_FPSET_SCHEDULE", "2,8:32")
    assert fpset.resolve_schedule() == (2, ((8, 32),))
    # explicit ctor values always win over the env
    assert fpset.resolve_schedule(5, ((4, 16),)) == (5, ((4, 16),))
    monkeypatch.delenv("PTT_FPSET_SCHEDULE")
    assert fpset.resolve_schedule() == (
        fpset.DENSE_ROUNDS, fpset.STAGES
    )


def test_fpset_custom_schedule_is_exact():
    """A non-default probe schedule changes cost, never semantics:
    same winners as the defaults on an adversarial duplicate batch."""
    rng = np.random.default_rng(11)
    pool = rng.integers(0, 2**31, size=(37, 2), dtype=np.uint32)
    keys = pool[rng.integers(0, len(pool), size=512)]
    kcols = (keys[:, 0], keys[:, 1])
    s_default = fpset.FPSet(2, cap=1 << 10)
    s_tuned = fpset.FPSet(
        2, cap=1 << 10, dense_rounds=2, stages=((2, 12), (8, 64)),
    )
    got_d = np.asarray(s_default.insert(kcols))
    got_t = np.asarray(s_tuned.insert(kcols))
    assert np.array_equal(got_d, got_t)
    assert s_default.n == s_tuned.n == len(pool)


def test_engine_schedule_env_round_trips(monkeypatch):
    """An engine built under PTT_FPSET_SCHEDULE runs the same search
    (exact counts) with the swept schedule."""
    monkeypatch.setenv("PTT_FPSET_SCHEDULE", "2,4:32")
    c = SMALL_CONFIGS["producer_on"]
    ck = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=64,
        visited_cap=1 << 10, frontier_cap=1 << 10,
    )
    assert ck.fps_dense == 2 and ck.fps_stages == ((4, 32),)
    r = ck.run()
    want = pe.check(c, invariants=())
    assert r.distinct_states == want.distinct_states


# ---- compact telemetry fields ---------------------------------------


def test_compact_telemetry_events_and_validator(tmp_path):
    """The device engine emits per-fetch ``compact`` records tagged
    with the impl, the run header carries ``compact_impl``, and the
    stream passes the schema validator (v3)."""
    import json
    import sys

    stream = str(tmp_path / "c.jsonl")
    c = SMALL_CONFIGS["producer_on"]
    ck = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=64,
        visited_cap=1 << 10, frontier_cap=1 << 10,
        telemetry=stream,
    )
    r = ck.run()
    assert r.distinct_states > 0
    evs = [json.loads(l) for l in open(stream)]
    hdr = [e for e in evs if e["event"] == "run_header"][0]
    assert hdr["compact_impl"] == "logshift"
    comps = [e for e in evs if e["event"] == "compact"]
    assert comps, "no compact records in the stream"
    assert all(e["impl"] == "logshift" for e in comps)
    assert sum(e["dispatches"] for e in comps) > 0
    res = [e for e in evs if e["event"] == "result"][-1]
    assert res["stats"]["compact_impl"] == "logshift"
    assert res["stats"]["stage_compact_n"] == sum(
        e["dispatches"] for e in comps
    )
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from check_telemetry_schema import validate_stream

    assert validate_stream(stream) == []
    # a SECOND run() on the same checker must report only ITS OWN
    # dispatches (the stage counters are lifetime-cumulative; the
    # event deltas baseline per run)
    ck.run()
    evs2 = [json.loads(l) for l in open(stream)]
    runs = {e["run_id"] for e in evs2 if e["event"] == "run_header"}
    assert len(runs) == 2
    per_run = {}
    for e in evs2:
        if e["event"] == "compact":
            per_run[e["run_id"]] = per_run.get(e["run_id"], 0) + (
                e["dispatches"]
            )
    first = sum(e["dispatches"] for e in comps)
    assert set(per_run.values()) == {first}  # identical runs, no bleed
