"""Front-end tests: parser structure, interpreter semantics, and full
state-set differential vs the hand-written pyeval oracle on the
compaction spec (SURVEY.md §4a/§4d; reference /root/reference/compaction.tla).
"""

import pytest

from pulsar_tlaplus_tpu.frontend import interp as I
from pulsar_tlaplus_tpu.frontend import tla_ast as A
from pulsar_tlaplus_tpu.frontend.loader import (
    compaction_constants,
    compaction_pystate,
)
from pulsar_tlaplus_tpu.frontend.parser import parse_file, parse_module
from pulsar_tlaplus_tpu.ref import pyeval as pe

from tests.helpers import REFERENCE_TLA  # specs/ first, /root/reference fallback


@pytest.fixture(scope="module")
def module():
    return parse_file(REFERENCE_TLA)


def spec_for(module, c: pe.Constants) -> I.Spec:
    return I.Spec(module, compaction_constants(c))


def pyeval_bfs(c: pe.Constants):
    seen = set()
    frontier = list(pe.initial_states(c))
    seen.update(frontier)
    diam = 0
    while frontier:
        new = []
        for s in frontier:
            for _a, t in pe.successors(c, s):
                if t not in seen:
                    seen.add(t)
                    new.append(t)
        frontier = new
        if frontier:
            diam += 1
    return seen, diam


# --------------------------------------------------------------------------
# parser
# --------------------------------------------------------------------------


class TestParser:
    def test_reference_module_structure(self, module):
        assert module.name == "compaction"
        assert "Sequences" in module.extends
        assert len(module.constants) == 16  # 9 params + 7 model values
        assert module.variables == (
            "messages",
            "compactedLedgers",
            "cursor",
            "compactorState",
            "phaseOneResult",
            "compactionHorizon",
            "compactedTopicContext",
            "crashTimes",
            "consumeTimes",
        )
        names = [d.name for d in module.defs]
        for required in (
            "Init",
            "Next",
            "Spec",
            "TypeSafe",
            "CompactionHorizonCorrectness",
            "CompactedLedgerLeak",
            "DuplicateNullKeyMessage",
            "Termination",
        ):
            assert required in names

    def test_junction_alignment(self):
        m = parse_module(
            """---- MODULE t ----
X ==
    /\\ 1 = 1
    /\\ \\/ 2 = 2
       \\/ 3 = 3
    /\\ 4 = 4
====
"""
        )
        x = m.defs_by_name()["X"].body
        assert isinstance(x, A.Junction) and x.op == "/\\"
        assert len(x.items) == 3
        assert isinstance(x.items[1], A.Junction) and x.items[1].op == "\\/"

    def test_misaligned_bullets_become_infix(self):
        # the reference's BrokerCrash THEN-branch layout (compaction.tla:177-178)
        m = parse_module(
            """---- MODULE t ----
X == IF TRUE
     THEN /\\ 1 = 1
           /\\ 2 = 2
     ELSE FALSE
====
"""
        )
        x = m.defs_by_name()["X"].body
        assert isinstance(x, A.If)

    def test_precedence(self):
        m = parse_module(
            """---- MODULE t ----
X == 1 + 2 * 3 = 7 /\\ 2 >= 1
Y == {i \\in 1..4 : i % 2 = 0}
Z == [k \\in {1, 2} |-> k + 1]
====
"""
        )
        s = I.Spec(m, {})
        assert s.genv.lookup("X") is True
        assert s.genv.lookup("Y") == frozenset({2, 4})
        assert s.genv.lookup("Z") == (2, 3)

    def test_temporal_forms_parse(self, module):
        spec_def = module.defs_by_name()["Spec"]
        term = module.defs_by_name()["Termination"]
        assert isinstance(term.body, A.UnOp) and term.body.op == "<>"
        # Spec == Init /\ [][Next]_vars
        assert isinstance(spec_def.body, A.BinOp)


# --------------------------------------------------------------------------
# interpreter semantics
# --------------------------------------------------------------------------


class TestInterp:
    def test_value_canonicalization(self):
        # functions over 1..n normalize to tuples (sequence equality)
        assert I.make_fn({1: "a", 2: "b"}) == ("a", "b")
        assert I.make_fn({}) == ()
        f = I.make_fn({2: 10, 5: 20})
        assert isinstance(f, I.FDict)
        assert f[5] == 20

    def test_assume_checks(self, module):
        c = pe.Constants(
            message_sent_limit=1,
            compaction_times_limit=1,
            num_keys=1,
            num_values=1,
        )
        spec_for(module, c).check_assumes()  # must not raise

    def test_lazy_let_out_of_domain(self, module):
        """CompactionHorizonCorrectness at horizon=0 must not force
        compactedLedgers[0] (TLC lazy-LET parity, SURVEY.md C23)."""
        c = pe.Constants(
            message_sent_limit=1,
            compaction_times_limit=1,
            num_keys=1,
            num_values=1,
            model_producer=True,
        )
        spec = spec_for(module, c)
        s0 = spec.initial_states()[0]
        assert spec.eval_predicate("CompactionHorizonCorrectness", s0)

    @pytest.mark.parametrize(
        "kw",
        [
            dict(
                message_sent_limit=2,
                compaction_times_limit=2,
                num_keys=1,
                num_values=1,
                max_crash_times=1,
                model_producer=True,
            ),
            dict(
                message_sent_limit=2,
                compaction_times_limit=2,
                num_keys=2,
                num_values=1,
                max_crash_times=1,
                model_producer=False,
            ),
            dict(
                message_sent_limit=2,
                compaction_times_limit=2,
                num_keys=1,
                num_values=2,
                max_crash_times=1,
                model_producer=True,
                retain_null_key=False,
                model_consumer=True,
            ),
        ],
    )
    def test_state_set_matches_pyeval(self, module, kw):
        """The full reachable state SET (not just the count) matches the
        hand-written oracle."""
        c = pe.Constants(**kw)
        spec = spec_for(module, c)
        r = I.bfs_check(spec, check_deadlock=False)
        ref_seen, ref_diam = pyeval_bfs(c)

        seen = set()
        frontier = spec.initial_states()
        seen.update(frontier)
        while frontier:
            new = []
            for s in frontier:
                for _lab, t in spec.successors(s):
                    if t not in seen:
                        seen.add(t)
                        new.append(t)
            frontier = new
        got = {compaction_pystate(s) for s in seen}
        assert got == ref_seen
        assert r.distinct_states == len(ref_seen)
        assert r.diameter == ref_diam

    def test_action_labels(self, module):
        c = pe.Constants(
            message_sent_limit=2,
            compaction_times_limit=1,
            num_keys=1,
            num_values=1,
            model_producer=True,
        )
        spec = spec_for(module, c)
        s0 = spec.initial_states()[0]
        labels = {lab for lab, _t in spec.successors(s0)}
        assert "Producer" in labels
        assert "BrokerCrash" in labels


# --------------------------------------------------------------------------
# oracle parity on the shipped configuration
# --------------------------------------------------------------------------


class TestOracles:
    def test_shipped_cfg_state_count(self, module):
        """45,198 distinct states — the spec's own ground truth
        (compaction.tla:23)."""
        spec = spec_for(module, pe.SHIPPED_CFG)
        r = I.bfs_check(
            spec,
            invariants=("TypeSafe", "CompactionHorizonCorrectness"),
            check_deadlock=False,
        )
        assert r.violation is None
        assert r.distinct_states == 45198

    @pytest.mark.parametrize(
        "inv,kw,max_depth",
        [
            (
                "CompactedLedgerLeak",
                dict(
                    message_sent_limit=2,
                    compaction_times_limit=3,
                    num_keys=1,
                    num_values=1,
                    max_crash_times=1,
                    model_producer=True,
                ),
                12,
            ),
            (
                "DuplicateNullKeyMessage",
                dict(
                    message_sent_limit=2,
                    compaction_times_limit=2,
                    num_keys=1,
                    num_values=1,
                    max_crash_times=1,
                    model_producer=False,
                ),
                3,
            ),
        ],
    )
    def test_bug_invariants_violate(self, module, inv, kw, max_depth):
        """The two known, unfixed Pulsar bugs reproduce as counterexamples
        (compaction.tla:252,279), with a valid shortest trace."""
        c = pe.Constants(**kw)
        spec = spec_for(module, c)
        r = I.bfs_check(spec, invariants=(inv,), check_deadlock=False)
        assert r.violation == inv
        assert len(r.trace) - 1 <= max_depth
        # trace validity: starts initial, consecutive, ends in violation
        assert r.trace[0] in spec.initial_states()
        for a, b in zip(r.trace, r.trace[1:]):
            assert any(t == b for _lab, t in spec.successors(a))
        assert not spec.eval_predicate(inv, r.trace[-1])
        for s in r.trace[:-1]:
            assert spec.eval_predicate(inv, s)
