"""Oracle evaluator vs the reference's published ground truth (SURVEY.md §4.3).

The two distinct-state counts in the spec comment (compaction.tla:23) are the
only quantitative oracles the reference publishes; the two commented-out
invariants (compaction.cfg:27-31) are its known-bug regression fixtures.
"""

import dataclasses

import pytest

from pulsar_tlaplus_tpu.ref import pyeval as pe


def test_shipped_cfg_state_count():
    r = pe.check(pe.SHIPPED_CFG)
    assert r.distinct_states == 45198  # compaction.tla:23
    assert r.diameter == 20
    assert r.violation is None


def test_modeled_producer_consumer_state_count():
    # The 253,361 figure (compaction.tla:23) corresponds to
    # RetainNullKey=FALSE; see BASELINE.md and the round-1 survey note.
    c = dataclasses.replace(
        pe.SHIPPED_CFG,
        model_producer=True,
        model_consumer=True,
        retain_null_key=False,
    )
    r = pe.check(c, invariants=())
    assert r.distinct_states == 253361
    assert r.diameter == 23


def test_compacted_ledger_leak_counterexample():
    from tests.helpers import assert_valid_counterexample

    r = pe.check(pe.SHIPPED_CFG, invariants=("CompactedLedgerLeak",))
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_duplicate_null_key_counterexample():
    from tests.helpers import assert_valid_counterexample

    r = pe.check(pe.SHIPPED_CFG, invariants=("DuplicateNullKeyMessage",))
    assert r.violation == "DuplicateNullKeyMessage"
    assert r.diameter == 4
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "DuplicateNullKeyMessage"
    )


def test_assume_validation():
    with pytest.raises(ValueError):
        pe.check(dataclasses.replace(pe.SHIPPED_CFG, message_sent_limit=-1))


def test_state_explosion_guard():
    with pytest.raises(RuntimeError):
        pe.check(pe.SHIPPED_CFG, invariants=(), max_states=100)
