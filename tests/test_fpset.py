"""Property and differential tests for the fpset visited-set subsystem
(round 6 tentpole): the table must behave as an exact set (insert/
lookup round-trips, adversarial same-key batches, growth-preserving
rehash, loud failure on overload), and the fpset-backed device engine
must match the legacy sort-merge flush STATE FOR STATE — same counts,
same levels, same gid assignment, same trace logs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ops import fpset
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS


# ---- table properties ------------------------------------------------


@pytest.mark.parametrize("ncols", [2, 3])
def test_insert_lookup_roundtrip(ncols):
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 2**32 - 2, size=(4000, ncols), dtype=np.uint32)
    n_unique = len(np.unique(keys, axis=0))
    s = fpset.FPSet(ncols, cap=1 << 10)
    kcols = tuple(keys[:, i] for i in range(ncols))
    is_new = np.asarray(s.insert(kcols))
    assert int(is_new.sum()) == n_unique == s.n
    # every inserted key is a member; a re-insert finds only duplicates
    assert np.asarray(s.contains(kcols)).all()
    assert int(np.asarray(s.insert(kcols)).sum()) == 0
    # disjoint fresh keys are not members
    other = rng.integers(2**32 - 2, 2**32 - 1, size=(500, ncols),
                         dtype=np.uint32)
    assert not np.asarray(s.contains(tuple(other[:, i]
                                           for i in range(ncols)))).any()


def test_adversarial_same_key_batches():
    """Batches dominated by equal-key groups: exactly one winner per
    distinct key, and it is the FIRST (minimum-lane) occurrence — the
    sort-merge flush's discovery order, which the engine's gid
    assignment depends on."""
    rng = np.random.default_rng(11)
    # draw from a tiny pool so most lanes are in-batch duplicates
    pool = rng.integers(0, 2**31, size=(37, 3), dtype=np.uint32)
    idx = rng.integers(0, len(pool), size=2048)
    keys = pool[idx]
    expected = np.zeros(len(keys), bool)
    seen = set()
    for i, j in enumerate(idx):
        if int(j) not in seen:
            seen.add(int(j))
            expected[i] = True
    s = fpset.FPSet(3, cap=1 << 12)
    got = np.asarray(s.insert(tuple(keys[:, i] for i in range(3))))
    assert np.array_equal(got, expected)
    assert s.n == len(pool)


def test_growth_preserves_membership():
    """Inserting far past the initial capacity forces repeated
    double-and-rehash; membership and uniqueness counts must be exact
    across every growth step."""
    rng = np.random.default_rng(3)
    s = fpset.FPSet(2, cap=1 << 6)
    all_keys = []
    total_new = 0
    for _ in range(6):
        batch = rng.integers(0, 2**31, size=(700, 2), dtype=np.uint32)
        all_keys.append(batch)
        total_new += int(np.asarray(
            s.insert((batch[:, 0], batch[:, 1]))
        ).sum())
    stacked = np.concatenate(all_keys)
    assert s.n == total_new == len(np.unique(stacked, axis=0))
    assert s.cap >= 2 * s.n  # load-factor contract held through growth
    assert np.asarray(s.contains((stacked[:, 0], stacked[:, 1]))).all()


def test_failure_count_on_overload():
    """More distinct keys than the table can hold: the unresolved lanes
    MUST surface in n_failed (and the wrapper must raise) — never a
    silent drop."""
    cap = 1 << 6
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 2**31, size=(4 * cap, 2), dtype=np.uint32)
    cols = fpset.empty_cols(cap, 2)
    is_new, cols, n_failed, _rounds = fpset.lookup_or_insert(
        cols, (keys[:, 0], keys[:, 1]),
        jnp.ones((len(keys),), jnp.bool_),
    )
    assert int(n_failed) > 0
    assert int(np.asarray(is_new).sum()) + int(n_failed) >= len(keys) - cap

    class NoGrow(fpset.FPSet):
        def reserve(self, n):  # defeat auto-growth to hit the overload
            return self

    s = NoGrow(2, cap=cap)
    with pytest.raises(RuntimeError, match="probe overflow"):
        s.insert((keys[:, 0], keys[:, 1]))


def test_staged_compaction_matches_single_loop():
    """The staged (dense -> compacted) probe schedule must make exactly
    the decisions of the plain single-loop probe: same winners, same
    final table — the stages are a cost optimization, not a semantics
    change."""
    rng = np.random.default_rng(13)
    cap = 1 << 12
    pool = rng.integers(0, 2**31, size=(1500, 2), dtype=np.uint32)
    keys = pool[rng.integers(0, len(pool), size=4096)]
    kcols = (jnp.asarray(keys[:, 0]), jnp.asarray(keys[:, 1]))
    valid = jnp.ones((len(keys),), jnp.bool_)
    staged_new, staged_cols, nf, _ = fpset.lookup_or_insert(
        fpset.empty_cols(cap, 2), kcols, valid
    )
    simple_new, simple_cols, _, pending, _ = fpset.probe_insert(
        fpset.empty_cols(cap, 2), kcols, valid
    )
    assert int(nf) == 0 and not bool(np.asarray(pending).any())
    assert np.array_equal(np.asarray(staged_new), np.asarray(simple_new))
    for a, b in zip(staged_cols, simple_cols):
        assert np.array_equal(np.asarray(a)[:cap], np.asarray(b)[:cap])


# ---- engine differential: fpset vs the legacy sort-merge flush -------


def test_fpset_engine_matches_sort_engine_state_for_state():
    """Same model, both visited implementations: identical counts,
    levels, AND identical row stores / parent / lane logs — the fpset
    flush must assign every gid exactly like the sort-merge flush."""
    c = SMALL_CONFIGS["producer_on"]
    m = CompactionModel(c)
    results = {}
    for impl in ("fpset", "sort"):
        ck = DeviceChecker(
            CompactionModel(c), invariants=(), sub_batch=64,
            visited_cap=1 << 10, frontier_cap=1 << 10, group=2,
            visited_impl=impl,
        )
        r = ck.run()
        n = r.distinct_states
        results[impl] = (
            r,
            np.asarray(ck.last_bufs["rows"][: n * m.layout.W]).copy(),
            np.asarray(ck.last_bufs["parent"][:n]).copy(),
            np.asarray(ck.last_bufs["lane"][:n]).copy(),
        )
    rf, rows_f, par_f, lane_f = results["fpset"]
    rs, rows_s, par_s, lane_s = results["sort"]
    assert rf.distinct_states == rs.distinct_states
    assert rf.diameter == rs.diameter
    assert rf.level_sizes == rs.level_sizes
    assert np.array_equal(rows_f, rows_s)
    assert np.array_equal(par_f, par_s)
    assert np.array_equal(lane_f, lane_s)


@pytest.mark.parametrize("impl", ["fpset", "sort"])
def test_engine_shipped_oracle_both_impls(impl):
    """45,198 / diameter 20 (compaction.tla:23) pinned on BOTH visited
    implementations explicitly (the rest of the suite exercises the
    default; this stays meaningful if the default ever flips back)."""
    r = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15, visited_impl=impl,
    ).run()
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock


def test_fpset_full_cfg_published_count():
    """The second published oracle (253,361 / diameter 23) on the
    fpset-backed engine explicitly, with growth forced from a small
    initial table (ISSUE r6 acceptance)."""
    import dataclasses

    c = dataclasses.replace(
        pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
    )
    r = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=4096,
        visited_cap=1 << 12, frontier_cap=1 << 17, flush_factor=2,
        visited_impl="fpset",
    ).run()
    assert r.distinct_states == 253361
    assert r.diameter == 23
    assert r.violation is None and not r.deadlock


# ---- _load_seed frontier-window guard (ADVICE r5 medium) -------------


def test_load_seed_frontier_window_guard():
    """A seed whose LAST LEVEL leaves no room for one blind APAD append
    window must be rejected up front (it used to flip rows_ok on the
    first flush and overwrite live frontier rows with scratch writes —
    silent corruption)."""
    m = CompactionModel(pe.SHIPPED_CFG)
    ck = DeviceChecker(
        m, sub_batch=8192, visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 10,
    )
    # the guard fires before any seed-content validation, so the seed
    # can be fabricated to land exactly on the edge: a last level too
    # big for window + append scratch, under a total the OLD guard
    # (n + SEED_CHUNK <= LCAP) accepts
    last = ck.LCAP - ck.APAD + 1
    n = min(ck.LCAP - ck.SEED_CHUNK, last + 1024)
    assert n >= last, "edge needs SEED_CHUNK < APAD at this tier"
    W = m.layout.W
    seed = (
        np.zeros((n, W), np.uint32),
        np.zeros((n,), np.int32),
        np.zeros((n,), np.int32),
        [n - last, last],
    )
    assert n + ck.SEED_CHUNK <= ck.LCAP, "edge precondition (old guard)"
    assert last + ck.APAD > ck.LCAP, "edge precondition (new guard)"
    with pytest.raises(ValueError, match="frontier rows window"):
        ck.run(seed=seed)


# ---- sharded engine differential (virtual CPU mesh) ------------------


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="sharded engine needs jax.shard_map (newer jax)",
)
@pytest.mark.parametrize("impl", ["fpset", "sort"])
def test_sharded_fpset_counts_match_oracle(impl):
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 6, group=2, visited_impl=impl,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
