"""Round-9 survivability: mesh-wide OOM recovery on the sharded
engine, liveness-engine checkpoint frames, and the hardened frame
writer (retry/backoff + ``ckpt_retries`` breadcrumb + stale-tmp
cleanup) — every new recovery path proven by deterministic
crash/recover differential drills.

The PTT_FAULT smoke matrix at the bottom is the tier-1 gate that keeps
fault paths from silently rotting: one fast kill/oom/ckpt_fail drill
per engine (kill drills ride the existing subprocess parity tests in
test_survivability.py; the in-process rows here use the shallow
DuplicateNullKeyMessage oracle so each run stops at depth 4)."""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.utils import ckpt, faults
from tests.helpers import SMALL_CONFIGS, needs_shard_map

KW = dict(sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15)
SKW = dict(n_devices=4, sub_batch=512, visited_cap=1 << 13)
# the lasso liveness oracle: the stub consumer never advances, so
# Termination is violated under wf_next (a fair not-goal cycle)
CONSUMER_CFG = dataclasses.replace(
    SMALL_CONFIGS["producer_on"], model_consumer=True
)


def _shipped():
    return CompactionModel(pe.SHIPPED_CFG)


def _run_sub(*args, fault=None, expect_kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PTT_FAULT", None)
    if fault:
        env["PTT_FAULT"] = fault
    proc = subprocess.run(
        [sys.executable, "-m", "tests._survivable_run", *args],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if expect_kill:
        assert proc.returncode == 137, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        return None
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---- hardened frame writer -------------------------------------------


def test_save_frame_retries_transient_oserror(tmp_path, monkeypatch):
    """One transient OSError is absorbed by the retry/backoff path;
    the frame lands intact and the retry count comes back."""
    calls = {"n": 0}
    real = np.savez_compressed

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(28, "No space left on device")
        return real(*a, **k)

    monkeypatch.setattr(ckpt.np, "savez_compressed", flaky)
    monkeypatch.setattr(ckpt, "WRITE_BACKOFF_S", 0.001)
    p = str(tmp_path / "f.npz")
    nbytes, write_s, retries = ckpt.save_frame(
        p, "sig", {"x": np.arange(4)}
    )
    assert retries == 1 and nbytes > 0
    assert list(ckpt.load_frame(p, "sig")["x"]) == [0, 1, 2, 3]


def test_save_frame_persistent_failure_raises(tmp_path, monkeypatch):
    """A persistent failure still raises (bounded retries, never an
    infinite loop) and leaves no half-written tmp behind."""
    def dead(*a, **k):
        raise OSError(5, "Input/output error")

    monkeypatch.setattr(ckpt.np, "savez_compressed", dead)
    monkeypatch.setattr(ckpt, "WRITE_BACKOFF_S", 0.001)
    p = str(tmp_path / "f.npz")
    with pytest.raises(OSError, match="Input/output"):
        ckpt.save_frame(p, "sig", {"x": np.arange(2)})
    assert not os.path.exists(p)
    assert not os.path.exists(p + ".tmp.npz")


def test_cleanup_stale_tmp(tmp_path):
    p = str(tmp_path / "c.npz")
    with open(p + ".tmp.npz", "wb") as f:
        f.write(b"dead half-frame")
    assert ckpt.cleanup_stale_tmp(p)
    assert not os.path.exists(p + ".tmp.npz")
    assert not ckpt.cleanup_stale_tmp(p)  # nothing left
    assert not ckpt.cleanup_stale_tmp(None)  # no checkpoint configured


def test_ckpt_fail_injection_retries_and_completes(monkeypatch, tmp_path):
    """Acceptance: ``ckpt_fail@frame:1`` — the first frame write fails
    transiently, the retry absorbs it, the run completes, and
    ``ckpt_retries >= 1`` lands in last_stats AND the stream (whose
    ckpt_frame record carries ``retries``); the schema validator
    passes on the stream."""
    monkeypatch.setenv("PTT_FAULT", "ckpt_fail@frame:1")
    faults.reset()
    stream = str(tmp_path / "s.jsonl")
    path = str(tmp_path / "ck.npz")
    ck = DeviceChecker(
        _shipped(), invariants=("DuplicateNullKeyMessage",),
        checkpoint_path=path, checkpoint_every=1, telemetry=stream,
        **KW,
    )
    r = ck.run()
    assert r.violation == "DuplicateNullKeyMessage"  # run completed
    assert ck.last_stats["ckpt_retries"] >= 1
    evs = [json.loads(l) for l in open(stream)]
    frames = [e for e in evs if e["event"] == "ckpt_frame"]
    assert frames and frames[0]["retries"] >= 1
    assert sum(e["retries"] for e in frames) == ck.last_stats[
        "ckpt_retries"
    ]
    # the breadcrumb flushed BEFORE the failed write's retry succeeded
    faults_seen = [e for e in evs if e["event"] == "fault"]
    assert any(e["kind"] == "ckpt_fail" for e in faults_seen)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from check_telemetry_schema import validate_stream

    assert validate_stream(stream) == []


def test_level1_fault_site_has_breadcrumb(monkeypatch, tmp_path):
    """The observer is installed before the level-1 poll (the r9 fix):
    a sigterm@level:1 drill leaves its fault breadcrumb in the stream
    and the run exits preempted at the very first boundary."""
    monkeypatch.setenv("PTT_FAULT", "sigterm@level:1")
    faults.reset()
    stream = str(tmp_path / "l1.jsonl")
    path = str(tmp_path / "l1.npz")
    r = DeviceChecker(
        _shipped(), checkpoint_path=path, telemetry=stream, **KW
    ).run()
    assert r.truncated and r.stop_reason == "preempted"
    evs = [json.loads(l) for l in open(stream)]
    assert any(
        e["event"] == "fault" and e["kind"] == "sigterm"
        and e["site"] == "level" and e["count"] == 1
        for e in evs
    )


# ---- mesh-wide OOM recovery on the sharded engine --------------------


@needs_shard_map
@pytest.mark.parametrize(
    "invariant,oom_level,depth",
    [
        ("CompactedLedgerLeak", 8, 12),
        ("DuplicateNullKeyMessage", 3, 4),
    ],
)
def test_sharded_oom_recovery_parity(
    monkeypatch, tmp_path, invariant, oom_level, depth
):
    """Acceptance: ``oom@level:N`` on the sharded engine completes with
    ``hbm_recovered >= 1`` and a state-for-state identical reachable
    set, violation trace, and violation_gid versus an unfaulted run —
    on both published bug oracles."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    monkeypatch.setenv("PTT_FAULT", f"oom@level:{oom_level}")
    faults.reset()
    path = str(tmp_path / "soom.npz")
    ck = ShardedDeviceChecker(
        _shipped(), invariants=(invariant,), checkpoint_path=path,
        checkpoint_every=1, **SKW,
    )
    r = ck.run()
    assert r.hbm_recovered >= 1
    assert not r.truncated and r.stop_reason is None
    assert ck._headroom_frozen  # degraded capacity actually applied
    monkeypatch.delenv("PTT_FAULT")
    faults.reset()
    full = ShardedDeviceChecker(
        _shipped(), invariants=(invariant,), **SKW
    ).run()
    assert r.violation == full.violation == invariant
    assert r.diameter == full.diameter == depth
    assert r.distinct_states == full.distinct_states
    assert r.level_sizes == full.level_sizes
    assert r.violation_gid == full.violation_gid
    assert r.trace == full.trace


@needs_shard_map
def test_sharded_oom_at_flush_recovers(monkeypatch, tmp_path):
    """The new flush-site drill hits the sharded fpset flush: recovery
    rebuilds mesh-wide and the full published count is reached."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    monkeypatch.setenv("PTT_FAULT", "oom@flush:8")
    faults.reset()
    path = str(tmp_path / "sfl.npz")
    r = ShardedDeviceChecker(
        _shipped(), checkpoint_path=path, checkpoint_every=1, **SKW
    ).run()
    assert r.hbm_recovered >= 1
    assert not r.truncated
    assert r.distinct_states == 45198 and r.diameter == 20


@needs_shard_map
def test_sharded_oom_without_frame_truncates(monkeypatch):
    """No checkpoint configured: exhaustion keeps the honest
    truncate contract (stop_reason "hbm") instead of crashing."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    monkeypatch.setenv("PTT_FAULT", "oom@level:3")
    faults.reset()
    r = ShardedDeviceChecker(_shipped(), **SKW).run()
    assert r.truncated and r.stop_reason == "hbm"
    assert r.hbm_recovered == 0
    assert 0 < r.distinct_states < 45198


@needs_shard_map
def test_sharded_oom_then_kill_resume_parity(tmp_path):
    """Subprocess drill: the run recovers from an injected OOM, is
    then hard-killed, and ``-recover`` still reproduces the unfaulted
    verdict exactly (trace + gid) — recovery state survives frames."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    path = str(tmp_path / "sok.npz")
    _run_sub(
        "--engine", "sharded", "--checkpoint", path,
        "--invariant", "CompactedLedgerLeak", "--every", "1",
        fault="oom@level:5,kill@level:8", expect_kill=True,
    )
    assert os.path.exists(path)
    resumed = _run_sub(
        "--engine", "sharded", "--checkpoint", path,
        "--invariant", "CompactedLedgerLeak", "--resume",
    )
    full = ShardedDeviceChecker(
        _shipped(), invariants=("CompactedLedgerLeak",), **SKW
    ).run()
    assert resumed["violation"] == "CompactedLedgerLeak"
    assert resumed["distinct_states"] == full.distinct_states
    assert resumed["level_sizes"] == full.level_sizes
    assert resumed["violation_gid"] == full.violation_gid
    assert resumed["trace"] == [repr(s) for s in full.trace]


# ---- liveness-engine checkpoint frames -------------------------------


def test_liveness_kill_sweep_resume_lasso_verdict(tmp_path):
    """Acceptance: kill mid-sweep (subprocess) -> ``run(resume=True)``
    reproduces the unfaulted verdict from the last sweep frame — on the
    lasso oracle (consumer modeled: Termination violated under
    wf_next), without re-exploration."""
    path = str(tmp_path / "lk.npz")
    stream = str(tmp_path / "lk.jsonl")
    common = (
        "--engine", "liveness", "--config", "consumer_on",
        "--frontier-chunk", "256", "--sweep-chunk", "256",
        "--checkpoint", path, "--every", "1",
    )
    _run_sub(
        *common, "--telemetry", stream,
        fault="kill@sweep:3", expect_kill=True,
    )
    assert os.path.exists(path)
    # the killed run's stream ends with the breadcrumb
    evs = [json.loads(l) for l in open(stream)]
    assert any(
        e["event"] == "fault" and e["kind"] == "kill"
        and e["site"] == "sweep" for e in evs
    )
    assert any(e["event"] == "sweep" for e in evs)
    resumed = _run_sub(*common, "--resume")
    want_holds, _ = pe.check_eventually(CONSUMER_CFG, "wf_next")
    assert resumed["holds"] == want_holds is False
    assert not resumed["truncated"]
    assert resumed["distinct_states"] == 1654
    assert resumed["lasso_cycle"]  # the lasso skeleton survived resume


def test_liveness_preempt_and_resume_inprocess(monkeypatch, tmp_path):
    """Acceptance: ``stop_reason="preempted"`` on SIGTERM mid-sweep;
    resume completes with the unfaulted (no-lasso) verdict."""
    monkeypatch.setenv("PTT_FAULT", "sigterm@sweep:2")
    faults.reset()
    path = str(tmp_path / "lp.npz")
    lkw = dict(
        goal="Termination", fairness="wf_next", frontier_chunk=256,
        sweep_chunk=256, visited_cap=1 << 13, checkpoint_path=path,
        checkpoint_every=1,
    )
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    r = LivenessChecker(m, **lkw).run()
    assert r.truncated and r.stop_reason == "preempted"
    assert os.path.exists(path)
    monkeypatch.delenv("PTT_FAULT")
    faults.reset()
    r2 = LivenessChecker(
        CompactionModel(SMALL_CONFIGS["producer_on"]), **lkw
    ).run(resume=True)
    assert not r2.truncated
    assert r2.holds  # producer_on: Termination holds under wf_next
    assert r2.distinct_states == 1654


def test_liveness_resume_from_exploration_frame(tmp_path):
    """A kill during the EXPLORATION phase leaves the inner engine's
    frame; liveness resume re-enters exploration from it and still
    reaches the verdict."""
    path = str(tmp_path / "le.npz")
    common = (
        "--engine", "liveness", "--config", "shipped",
        "--checkpoint", path, "--every", "2",
    )
    _run_sub(*common, fault="kill@level:8", expect_kill=True)
    assert os.path.exists(path)
    resumed = _run_sub(*common, "--resume")
    assert resumed["holds"] is True  # shipped: Termination holds (wf)
    assert resumed["distinct_states"] == 45198


def test_liveness_telemetry_zero_extra_fetches(tmp_path):
    """Satellite 2: heartbeat + telemetry on the sweep add ZERO device
    fetches — asserted fetch-count-identical like the BFS engines."""
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    lkw = dict(
        goal="Termination", fairness="wf_next", frontier_chunk=256,
        sweep_chunk=256, visited_cap=1 << 13,
    )
    plain = LivenessChecker(CompactionModel(
        SMALL_CONFIGS["producer_on"]), **lkw)
    r1 = plain.run()
    stream = str(tmp_path / "lt.jsonl")
    loud = LivenessChecker(
        m, telemetry=stream, heartbeat_s=0.05, **lkw
    )
    r2 = loud.run()
    assert r1.holds == r2.holds
    assert plain._fetch_n == loud._fetch_n  # zero extra syncs
    evs = [json.loads(l) for l in open(stream)]
    kinds = {e["event"] for e in evs}
    assert {"run_header", "sweep", "result"} <= kinds
    headers = [e for e in evs if e["event"] == "run_header"]
    assert any(h["engine"] == "liveness" for h in headers)
    sweeps = [e for e in evs if e["event"] == "sweep"]
    assert sweeps[-1]["swept"] == 1654
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from check_telemetry_schema import validate_stream

    assert validate_stream(stream) == []


def test_validator_accepts_pre_r9_v1_streams(tmp_path):
    """Schema versioning: a v1 (pre-r9) ckpt_frame record has no
    ``retries`` field and must stay valid — records are held only to
    their OWN version's required fields (FIELD_SINCE); a v2 record
    without it fails."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from check_telemetry_schema import validate_stream

    base = dict(
        event="ckpt_frame", t=0.1, seq=0, run_id="r", frame_seq=1,
        bytes=10, write_s=0.0, distinct_states=5,
    )
    p1 = str(tmp_path / "v1.jsonl")
    with open(p1, "w") as f:
        f.write(json.dumps(dict(base, v=1)) + "\n")
    assert validate_stream(p1) == []
    p2 = str(tmp_path / "v2.jsonl")
    with open(p2, "w") as f:
        f.write(json.dumps(dict(base, v=2)) + "\n")
    errs = validate_stream(p2)
    assert errs and "retries" in errs[0]


def test_validator_bench_schema4_requires_ckpt_retries():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from check_telemetry_schema import (
        BENCH_KEYS_V4,
        validate_bench_artifact,
    )

    good = {k: 1 for k in BENCH_KEYS_V4}
    good.update(bench_schema=4, value=1.0)
    assert validate_bench_artifact(dict(good), "g") == []
    bad = dict(good)
    del bad["ckpt_retries"]
    errs = validate_bench_artifact(bad, "b")
    assert errs and "ckpt_retries" in errs[0]
    # a schema-3 artifact is NOT held to the r9 key
    v3 = dict(bad)
    v3["bench_schema"] = 3
    assert validate_bench_artifact(v3, "v3") == []


# ---- PTT_FAULT smoke matrix (tier-1 gate; satellite 6) ---------------
# One fast drill per engine x fault kind.  kill drills are covered by
# the subprocess parity tests (test_survivability.py and above); the
# rows here are in-process and use the shallow depth-4 oracle.


def test_smoke_device_oom(monkeypatch, tmp_path):
    monkeypatch.setenv("PTT_FAULT", "oom@level:3")
    faults.reset()
    ck = DeviceChecker(
        _shipped(), invariants=("DuplicateNullKeyMessage",),
        checkpoint_path=str(tmp_path / "d.npz"), checkpoint_every=1,
        **KW,
    )
    r = ck.run()
    assert r.hbm_recovered == 1
    assert r.violation == "DuplicateNullKeyMessage" and r.diameter == 4


def test_smoke_device_oom_at_flush(monkeypatch, tmp_path):
    monkeypatch.setenv("PTT_FAULT", "oom@flush:4")
    faults.reset()
    r = DeviceChecker(
        _shipped(), invariants=("DuplicateNullKeyMessage",),
        checkpoint_path=str(tmp_path / "df.npz"), checkpoint_every=1,
        **KW,
    ).run()
    assert r.hbm_recovered == 1
    assert r.violation == "DuplicateNullKeyMessage"


@needs_shard_map
def test_smoke_sharded_fpset_fail(monkeypatch):
    """The sharded fpset_fail drill must fail-stop like a real probe
    overflow — one synthetic dropped lane, on one shard."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    monkeypatch.setenv("PTT_FAULT", "fpset_fail@flush:2")
    faults.reset()
    with pytest.raises(RuntimeError, match="probe overflow on 1 shard"):
        ShardedDeviceChecker(_shipped(), **SKW).run()


@needs_shard_map
def test_smoke_sharded_ckpt_fail(monkeypatch, tmp_path):
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    monkeypatch.setenv("PTT_FAULT", "ckpt_fail@frame:1")
    faults.reset()
    ck = ShardedDeviceChecker(
        _shipped(), invariants=("DuplicateNullKeyMessage",),
        checkpoint_path=str(tmp_path / "s.npz"), checkpoint_every=1,
        **SKW,
    )
    r = ck.run()
    assert r.violation == "DuplicateNullKeyMessage"
    assert ck.last_stats["ckpt_retries"] >= 1


def test_smoke_liveness_ckpt_fail(monkeypatch, tmp_path):
    monkeypatch.setenv("PTT_FAULT", "ckpt_fail@frame:1")
    faults.reset()
    lck = LivenessChecker(
        CompactionModel(SMALL_CONFIGS["producer_on"]),
        goal="Termination", fairness="wf_next", frontier_chunk=256,
        sweep_chunk=256, visited_cap=1 << 13,
        checkpoint_path=str(tmp_path / "l.npz"), checkpoint_every=1,
    )
    r = lck.run()
    assert r.holds and not r.truncated  # the retry absorbed the fault
    # frame 1 is the inner explorer's first exploration frame (the
    # sweep's frames come later in the same sequence-per-writer);
    # whichever writer hit the injection, the retry count surfaced
    assert lck._ckpt_retries + lck._checker._ckpt_retries >= 1


def test_smoke_liveness_oom_fails_loudly(monkeypatch, tmp_path):
    """The sweep has no degraded-capacity rebuild: an injected OOM
    must abort loudly, never produce a verdict over partial edges."""
    monkeypatch.setenv("PTT_FAULT", "oom@sweep:1")
    faults.reset()
    lck = LivenessChecker(
        CompactionModel(SMALL_CONFIGS["producer_on"]),
        goal="Termination", fairness="wf_next", frontier_chunk=256,
        sweep_chunk=256, visited_cap=1 << 13,
    )
    with pytest.raises(faults.FaultError, match="RESOURCE_EXHAUSTED"):
        lck.run()
