"""State-log backends (SURVEY.md §2.2-E7/E8): native C++ disk store vs
memory log, and a full engine run + trace over the disk-backed log."""

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.statelog import FileLog, MemoryLog
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample


def _roundtrip(log, packed, parents, actions):
    assert log.append(packed[:600], parents[:600], actions[:600]) == 0
    assert log.append(packed[600:], parents[600:], actions[600:]) == 600
    assert len(log) == 1000
    for g in (0, 1, 599, 600, 999, 500):
        row, p, a = log.get(g)
        assert (np.asarray(row) == packed[g]).all()
        assert p == parents[g] and a == actions[g]


@pytest.fixture
def sample():
    rng = np.random.default_rng(0)
    return (
        rng.integers(0, 2**32, size=(1000, 3), dtype=np.uint32),
        rng.integers(-1, 10**12, size=1000).astype(np.int64),
        rng.integers(0, 9, size=1000).astype(np.int32),
    )


def test_memory_log(sample):
    log = MemoryLog(3)
    _roundtrip(log, *sample)
    assert (log.packed_matrix() == sample[0]).all()


def test_file_log_native_and_reopen(tmp_path, sample):
    path = str(tmp_path / "log.bin")
    log = FileLog(path, 3)
    assert log.native, "C++ extension must build in this image"
    _roundtrip(log, *sample)
    log.sync()
    log2 = FileLog(path, 3)
    assert len(log2) == 1000
    row, p, a = log2.get(777)
    assert (row == sample[0][777]).all()
    assert p == sample[1][777] and a == sample[2][777]


def test_file_log_truncate(tmp_path, sample):
    path = str(tmp_path / "log.bin")
    log = FileLog(path, 3)
    _roundtrip(log, *sample)
    log.truncate(500)
    assert len(log) == 500
    assert (log.get(499)[0] == sample[0][499]).all()
    with pytest.raises(ValueError):
        log.truncate(600)


def test_engine_with_disk_log(tmp_path):
    """Full check over the native disk log, including trace reconstruction."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    r = Checker(
        CompactionModel(c),
        invariants=(),
        frontier_chunk=1024,
        visited_cap=1 << 14,
        state_log_path=str(tmp_path / "states.bin"),
    ).run()
    assert r.distinct_states == want.distinct_states
    assert r.diameter == want.diameter

    r2 = Checker(
        CompactionModel(pe.SHIPPED_CFG),
        invariants=("CompactedLedgerLeak",),
        visited_cap=1 << 16,
        state_log_path=str(tmp_path / "states2.bin"),
    ).run()
    assert r2.violation == "CompactedLedgerLeak"
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r2.trace, r2.trace_actions, "CompactedLedgerLeak"
    )
