"""Run-survivability tests (ISSUE r7): device_bfs checkpoint/resume,
HBM-exhaustion recovery, preemption-safe shutdown, and the
deterministic fault-injection harness — interrupted+resumed runs must
match uninterrupted runs state-for-state on the published oracles."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.utils import ckpt, faults
from tests.helpers import assert_valid_counterexample, needs_shard_map

KW = dict(sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15)


def _shipped():
    return CompactionModel(pe.SHIPPED_CFG)


# ---- checkpoint/resume on the device engine --------------------------


def test_device_checkpoint_resume_exact_count(tmp_path):
    """A budget-truncated device run leaves a frame; resume reaches the
    published 45,198-state count with level sizes identical to an
    uninterrupted run's."""
    m = _shipped()
    path = str(tmp_path / "dev.npz")
    r1 = DeviceChecker(
        m, checkpoint_path=path, checkpoint_every=3,
        max_states=10_000, **KW,
    ).run()
    assert r1.truncated and r1.stop_reason == "max_states"
    assert r1.distinct_states < 45198
    assert os.path.exists(path)
    r2 = DeviceChecker(m, checkpoint_path=path, **KW).run(resume=True)
    assert r2.distinct_states == 45198
    assert r2.diameter == 20
    assert not r2.truncated
    full = DeviceChecker(m, **KW).run()
    assert r2.level_sizes == full.level_sizes


def test_device_checkpoint_rejects_other_config(tmp_path):
    import dataclasses

    path = str(tmp_path / "dev.npz")
    DeviceChecker(
        _shipped(), checkpoint_path=path, checkpoint_every=2,
        max_states=5_000, **KW,
    ).run()
    other = CompactionModel(
        dataclasses.replace(pe.SHIPPED_CFG, max_crash_times=2)
    )
    with pytest.raises(ValueError, match="different configuration"):
        DeviceChecker(other, checkpoint_path=path, **KW).run(resume=True)
    # a non-frame file fails with one clean message, not a zip error
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as f:
        f.write(b"not a frame")
    with pytest.raises(ValueError, match="unrecognized checkpoint"):
        DeviceChecker(
            _shipped(), checkpoint_path=bad, **KW
        ).run(resume=True)


def test_device_resume_trace_spans_checkpoint(tmp_path):
    """A violation found after resume replays a valid counterexample
    THROUGH the checkpointed prefix, with the same violating gid as an
    uninterrupted run (dedup order is deterministic)."""
    m = _shipped()
    inv = ("CompactedLedgerLeak",)
    path = str(tmp_path / "dev.npz")
    full = DeviceChecker(m, invariants=inv, **KW).run()
    r1 = DeviceChecker(
        m, invariants=inv, checkpoint_path=path, checkpoint_every=2,
        max_states=6_000, **KW,
    ).run()
    assert r1.truncated and r1.violation is None
    r2 = DeviceChecker(
        m, invariants=inv, checkpoint_path=path, **KW
    ).run(resume=True)
    assert r2.violation == "CompactedLedgerLeak"
    assert r2.diameter == 12
    assert r2.violation_gid == full.violation_gid
    assert r2.trace == full.trace
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r2.trace, r2.trace_actions, "CompactedLedgerLeak"
    )


def test_device_sort_mode_resume(tmp_path):
    """-visited sort keeps its own frame layout (sorted key prefix);
    resume is exact there too."""
    m = _shipped()
    path = str(tmp_path / "sort.npz")
    DeviceChecker(
        m, visited_impl="sort", checkpoint_path=path,
        checkpoint_every=3, max_states=10_000, **KW,
    ).run()
    r = DeviceChecker(
        m, visited_impl="sort", checkpoint_path=path, **KW
    ).run(resume=True)
    assert r.distinct_states == 45198 and r.diameter == 20
    # sort-mode frames must not resume under fpset (different layout)
    with pytest.raises(ValueError, match="different configuration"):
        DeviceChecker(
            m, visited_impl="fpset", checkpoint_path=path, **KW
        ).run(resume=True)


def test_device_frontier_window_resume(tmp_path):
    """Frontier-window mode checkpoints only the live rows window;
    resume restores it at window offset 0 and stays exact."""
    m = _shipped()
    path = str(tmp_path / "fw.npz")
    fkw = dict(
        sub_batch=256, visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 13,
    )
    DeviceChecker(
        m, checkpoint_path=path, checkpoint_every=4, max_states=9_000,
        **fkw,
    ).run()
    r = DeviceChecker(m, checkpoint_path=path, **fkw).run(resume=True)
    assert r.distinct_states == 45198 and r.diameter == 20


# ---- HBM-exhaustion recovery -----------------------------------------


def test_device_oom_recovery_completes(monkeypatch, tmp_path):
    """An injected RESOURCE_EXHAUSTED mid-run rebuilds from the last
    frame at degraded capacity and COMPLETES — hbm_recovered >= 1, no
    truncation, exact published count (the acceptance criterion)."""
    monkeypatch.setenv("PTT_FAULT", "oom@level:7")
    faults.reset()
    path = str(tmp_path / "oom.npz")
    ck = DeviceChecker(
        m := _shipped(), checkpoint_path=path, checkpoint_every=1, **KW
    )
    r = ck.run()
    assert r.hbm_recovered == 1
    assert not r.truncated and r.stop_reason is None
    assert r.distinct_states == 45198 and r.diameter == 20
    # degraded capacity was actually applied
    assert ck._headroom_frozen
    full = DeviceChecker(m, **KW).run()
    assert r.level_sizes == full.level_sizes


def test_device_oom_without_frame_truncates(monkeypatch):
    """No checkpoint configured: exhaustion keeps the honest
    poison-and-truncate contract (stop_reason "hbm")."""
    monkeypatch.setenv("PTT_FAULT", "oom@level:3")
    faults.reset()
    r = DeviceChecker(_shipped(), **KW).run()
    assert r.truncated and r.stop_reason == "hbm"
    assert r.hbm_recovered == 0
    assert 0 < r.distinct_states < 45198


def test_fpset_fail_injection_fail_stops(monkeypatch):
    """An injected fpset stage overflow must abort loudly (states were
    dropped; the counts cannot be trusted) — never a silent drop."""
    monkeypatch.setenv("PTT_FAULT", "fpset_fail@flush:2")
    faults.reset()
    with pytest.raises(RuntimeError, match="probe overflow"):
        DeviceChecker(_shipped(), **KW).run()


# ---- preemption-safe shutdown ----------------------------------------


def test_device_preemption_checkpoints_and_resumes(monkeypatch, tmp_path):
    """SIGTERM mid-run (delivered by the sigterm fault — exactly what a
    TPU-VM preemption sends) checkpoints at the next level boundary and
    exits with stop_reason "preempted"; resume is exact."""
    monkeypatch.setenv("PTT_FAULT", "sigterm@level:6")
    faults.reset()
    m = _shipped()
    path = str(tmp_path / "pre.npz")
    r1 = DeviceChecker(
        m, checkpoint_path=path, checkpoint_every=100, **KW
    ).run()
    assert r1.truncated and r1.stop_reason == "preempted"
    assert os.path.exists(path)  # the preemption wrote the frame
    assert 0 < r1.distinct_states < 45198
    monkeypatch.delenv("PTT_FAULT")
    faults.reset()
    r2 = DeviceChecker(m, checkpoint_path=path, **KW).run(resume=True)
    assert r2.distinct_states == 45198 and r2.diameter == 20


# ---- crash (kill -9 class) + resume parity: the subprocess drill -----


def _run_sub(tmp_path, *args, fault=None, expect_kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PTT_FAULT", None)
    if fault:
        env["PTT_FAULT"] = fault
    proc = subprocess.run(
        [sys.executable, "-m", "tests._survivable_run", *args],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if expect_kill:
        assert proc.returncode == 137, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        return None
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.parametrize(
    "invariant,kill_level,every,depth",
    [
        ("CompactedLedgerLeak", 8, 2, 12),
        ("DuplicateNullKeyMessage", 3, 2, 4),
    ],
)
def test_kill_resume_parity_device(
    tmp_path, invariant, kill_level, every, depth
):
    """kill@level:k (hard os._exit mid-run, subprocess) + -recover
    reproduces the uninterrupted run's level sizes, first-violation
    gid, and trace exactly — on both published bug oracles."""
    path = str(tmp_path / "kill.npz")
    _run_sub(
        tmp_path, "--checkpoint", path, "--invariant", invariant,
        "--every", str(every),
        fault=f"kill@level:{kill_level}", expect_kill=True,
    )
    assert os.path.exists(path)  # died after frames were written
    resumed = _run_sub(
        tmp_path, "--checkpoint", path, "--invariant", invariant,
        "--resume",
    )
    full = DeviceChecker(
        _shipped(), invariants=(invariant,), **KW
    ).run()
    assert resumed["violation"] == invariant == full.violation
    assert resumed["diameter"] == depth == full.diameter
    assert resumed["distinct_states"] == full.distinct_states
    assert resumed["level_sizes"] == full.level_sizes
    assert resumed["violation_gid"] == full.violation_gid
    assert resumed["trace"] == [repr(s) for s in full.trace]
    assert resumed["trace_actions"] == list(full.trace_actions)


@needs_shard_map
@pytest.mark.parametrize(
    "invariant,kill_level,depth",
    [
        ("CompactedLedgerLeak", 8, 12),
        ("DuplicateNullKeyMessage", 3, 4),
    ],
)
def test_kill_resume_parity_sharded(tmp_path, invariant, kill_level, depth):
    """The same crash-resume drill on the sharded engine (CPU mesh)."""
    from pulsar_tlaplus_tpu.engine.sharded_device import (
        ShardedDeviceChecker,
    )

    path = str(tmp_path / "skill.npz")
    _run_sub(
        tmp_path, "--engine", "sharded", "--checkpoint", path,
        "--invariant", invariant, "--every", "2",
        fault=f"kill@level:{kill_level}", expect_kill=True,
    )
    assert os.path.exists(path)
    resumed = _run_sub(
        tmp_path, "--engine", "sharded", "--checkpoint", path,
        "--invariant", invariant, "--resume",
    )
    full = ShardedDeviceChecker(
        _shipped(), n_devices=4, invariants=(invariant,),
        sub_batch=512, visited_cap=1 << 13,
    ).run()
    assert resumed["violation"] == invariant == full.violation
    assert resumed["diameter"] == depth == full.diameter
    assert resumed["distinct_states"] == full.distinct_states
    assert resumed["level_sizes"] == full.level_sizes
    assert resumed["violation_gid"] == full.violation_gid
    assert resumed["trace"] == [repr(s) for s in full.trace]


# ---- fault-harness + frame-codec units -------------------------------


def test_fault_spec_parsing(monkeypatch):
    monkeypatch.setenv("PTT_FAULT", "oom@level:7, fpset_fail@flush:3")
    faults.reset()
    assert faults.poll("level", 6) == ()
    assert faults.poll("level", 7) == ("oom",)
    assert faults.poll("level", 7) == ()  # single-shot per process
    assert faults.poll("flush", 3) == ("fpset_fail",)
    monkeypatch.setenv("PTT_FAULT", "bogus@level:1")
    faults.reset()
    with pytest.raises(ValueError, match="unknown PTT_FAULT kind"):
        faults.poll("level", 1)
    monkeypatch.setenv("PTT_FAULT", "oom@level")
    faults.reset()
    with pytest.raises(ValueError, match="bad PTT_FAULT spec"):
        faults.poll("level", 1)
    monkeypatch.delenv("PTT_FAULT")
    faults.reset()
    assert faults.poll("level", 1) == ()


def test_fpset_frame_codec_roundtrip():
    """pack_fpset/unpack_fpset: occupied slots round-trip exactly, for
    single-device (1-D) and per-shard (2-D) column layouts."""
    S = 0xFFFFFFFF
    rng = np.random.RandomState(7)
    for shape in [(65,), (4, 33)]:
        cols = [
            np.full(shape, S, np.uint32) for _ in range(2)
        ]
        cap = shape[-1] - 1
        flat_occ = rng.rand(*cols[0][..., :cap].shape) < 0.3
        vals0 = rng.randint(0, S, size=flat_occ.shape).astype(np.uint32)
        vals1 = rng.randint(0, S, size=flat_occ.shape).astype(np.uint32)
        cols[0][..., :cap][flat_occ] = vals0[flat_occ]
        cols[1][..., :cap][flat_occ] = vals1[flat_occ]
        packed = ckpt.pack_fpset(cols)
        # npz round-trip (the codec feeds save_frame)
        out = ckpt.unpack_fpset(
            {k: np.asarray(v) for k, v in packed.items()}, 2
        )
        for a, b in zip(cols, out):
            assert np.array_equal(a, b), shape


def test_frame_format_version_gate(tmp_path):
    path = str(tmp_path / "f.npz")
    ckpt.save_frame(path, "sig1", {"x": np.arange(3)})
    d = ckpt.load_frame(path, "sig1")
    assert list(d["x"]) == [0, 1, 2]
    with pytest.raises(ValueError, match="different configuration"):
        ckpt.load_frame(path, "sig2")
    # a frame from a NEWER format must be refused, not misread
    np.savez_compressed(
        path,
        __format__=np.int64(ckpt.FORMAT_VERSION + 1),
        sig=np.frombuffer(b"sig1", dtype=np.uint8),
    )
    with pytest.raises(ValueError, match="newer than this build"):
        ckpt.load_frame(path, "sig1")
    with pytest.raises(FileNotFoundError):
        ckpt.load_frame(str(tmp_path / "missing.npz"), "sig1")


def test_preemption_watcher_signal_sets_flag():
    import signal

    with ckpt.PreemptionWatcher(enabled=True) as w:
        assert not w.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert w.requested
    # handlers restored on exit
    assert signal.getsignal(signal.SIGTERM) != w._handle


def test_aot_cache_corrupt_entry_is_a_miss(tmp_path, monkeypatch, capsys):
    """A truncated/tampered AOT cache entry is deleted and recompiled
    with a one-line note — a corrupt cache must never kill a run."""
    import jax.numpy as jnp

    from pulsar_tlaplus_tpu.utils import aot_cache

    monkeypatch.setenv("PTT_AOT_DIR", str(tmp_path / "cache"))
    monkeypatch.setattr(aot_cache, "_DIR_TRUSTED", None)
    aj = aot_cache.ajit(lambda x: x + 1)
    args = (jnp.arange(4),)
    sig = aj._sig(args)
    comp = aj._build(sig, args)
    assert aj.events[sig] == "compile"
    entries = list((tmp_path / "cache").glob("*.aotx"))
    if not entries:
        pytest.skip("backend does not support executable serialization")
    # corrupt the entry: digest check must treat it as a miss
    with open(entries[0], "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.truncate(size // 2)
    aj2 = aot_cache.ajit(lambda x: x + 1)
    comp2 = aj2._build(sig, args)
    assert aj2.events[sig] == "compile"  # miss, not a crash
    assert "unusable" in capsys.readouterr().err
