"""Checkpoint/resume tests (SURVEY.md §2.2-E8): a truncated run must resume
to the exact published state count, and traces must span checkpoints."""

import dataclasses

import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import assert_valid_counterexample


def test_checkpoint_resume_exact_count(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    r1 = Checker(
        m, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=3, max_states=10_000,
    ).run()
    assert r1.truncated and r1.distinct_states < 45198
    r2 = Checker(m, visited_cap=1 << 16, checkpoint_path=path).run(resume=True)
    assert r2.distinct_states == 45198
    assert r2.diameter == 20
    assert not r2.truncated


def test_checkpoint_config_mismatch_rejected(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    Checker(
        m, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=2, max_states=5_000,
    ).run()
    other = CompactionModel(
        dataclasses.replace(pe.SHIPPED_CFG, max_crash_times=2)
    )
    with pytest.raises(ValueError, match="different model configuration"):
        Checker(other, checkpoint_path=path).run(resume=True)


def test_trace_spans_checkpoint(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    inv = ("CompactedLedgerLeak",)
    r1 = Checker(
        m, invariants=inv, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=2, max_states=8_000,
    ).run()
    assert r1.truncated and r1.violation is None
    r2 = Checker(m, invariants=inv, visited_cap=1 << 16, checkpoint_path=path).run(
        resume=True
    )
    assert r2.violation == "CompactedLedgerLeak"
    assert r2.diameter == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r2.trace, r2.trace_actions, "CompactedLedgerLeak"
    )
