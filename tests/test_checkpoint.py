"""Checkpoint/resume tests (SURVEY.md §2.2-E8): a truncated run must resume
to the exact published state count, and traces must span checkpoints."""

import dataclasses
import os

import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import assert_valid_counterexample


def test_checkpoint_resume_exact_count(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    r1 = Checker(
        m, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=3, max_states=10_000,
    ).run()
    assert r1.truncated and r1.distinct_states < 45198
    r2 = Checker(m, visited_cap=1 << 16, checkpoint_path=path).run(resume=True)
    assert r2.distinct_states == 45198
    assert r2.diameter == 20
    assert not r2.truncated


def test_checkpoint_config_mismatch_rejected(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    Checker(
        m, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=2, max_states=5_000,
    ).run()
    other = CompactionModel(
        dataclasses.replace(pe.SHIPPED_CFG, max_crash_times=2)
    )
    with pytest.raises(ValueError, match="different model configuration"):
        Checker(other, checkpoint_path=path).run(resume=True)


def test_trace_spans_checkpoint(tmp_path):
    m = CompactionModel(pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    inv = ("CompactedLedgerLeak",)
    r1 = Checker(
        m, invariants=inv, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=2, max_states=8_000,
    ).run()
    assert r1.truncated and r1.violation is None
    r2 = Checker(m, invariants=inv, visited_cap=1 << 16, checkpoint_path=path).run(
        resume=True
    )
    assert r2.violation == "CompactedLedgerLeak"
    assert r2.diameter == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r2.trace, r2.trace_actions, "CompactedLedgerLeak"
    )


# ---- concurrent frame writers (r11, checking-as-a-service) ----------
# Two run_ids sharing a checkpoint dir (the daemon's jobs/<id>/ layout
# collapses to this when paths collide) must never clobber each other's
# frames, tmp files, or stale-tmp cleanup.


def _hammer_frames(path, sig, run_id, payload, n, errors):
    from pulsar_tlaplus_tpu.utils import ckpt
    import numpy as np

    try:
        for seq in range(n):
            ckpt.save_frame(
                path, sig,
                {"payload": np.full(256, payload, np.int64)},
                meta={"run_id": run_id, "frame_seq": seq},
            )
    except Exception as e:  # noqa: BLE001 — surfaced by the test body
        errors.append(e)


def test_concurrent_writers_same_path_never_torn(tmp_path):
    """Two writers racing on ONE path: every load observes a COMPLETE
    frame from one of them (per-writer-unique tmp names make the
    os.replace publish atomic even under contention; the pre-r11 fixed
    tmp name let writer A install writer B's half-filled tmp)."""
    import threading

    import numpy as np

    from pulsar_tlaplus_tpu.utils import ckpt

    path = str(tmp_path / "frame.npz")
    sig = ckpt.config_sig(test="race")
    errors: list = []
    writers = [
        threading.Thread(
            target=_hammer_frames,
            args=(path, sig, rid, val, 30, errors),
        )
        for rid, val in (("run-a", 1), ("run-b", 2))
    ]
    for t in writers:
        t.start()
    torn = []
    while any(t.is_alive() for t in writers):
        try:
            d = ckpt.load_frame(path, sig)
        except FileNotFoundError:
            continue  # before the first publish
        except ValueError as e:
            torn.append(repr(e))
            break
        p = np.asarray(d["payload"])
        if not (p == p[0]).all() or int(p[0]) not in (1, 2):
            torn.append(f"mixed payload {set(p.tolist())}")
            break
    for t in writers:
        t.join()
    assert not errors, errors
    assert not torn, torn
    # final frame: complete, signed, from one of the two writers
    d = ckpt.load_frame(path, sig)
    assert int(np.asarray(d["payload"])[0]) in (1, 2)
    assert ckpt.frame_meta(d)["run_id"] in ("run-a", "run-b")
    # no tmp survives the writers
    assert not [
        n for n in os.listdir(tmp_path) if ".tmp." in n
    ]


def test_shared_dir_frames_and_cleanup_are_isolated(tmp_path):
    """Two run_ids with sibling frame paths in ONE dir: concurrent
    writes land in their own frames, and one path's stale-tmp cleanup
    never touches the sibling's tmp or frame."""
    import threading

    import numpy as np

    from pulsar_tlaplus_tpu.utils import ckpt

    pa = str(tmp_path / "frame.a.npz")
    pb = str(tmp_path / "frame.b.npz")
    sig = ckpt.config_sig(test="shared-dir")
    errors: list = []
    ts = [
        threading.Thread(
            target=_hammer_frames, args=(p, sig, rid, v, 20, errors)
        )
        for p, rid, v in ((pa, "run-a", 1), (pb, "run-b", 2))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    da, db = ckpt.load_frame(pa, sig), ckpt.load_frame(pb, sig)
    assert int(np.asarray(da["payload"])[0]) == 1
    assert int(np.asarray(db["payload"])[0]) == 2
    assert ckpt.frame_meta(da)["run_id"] == "run-a"
    assert ckpt.frame_meta(db)["run_id"] == "run-b"
    # stale tmps: cleanup is scoped to ITS frame path — a crashed
    # writer's debris for A never takes B's live tmp (or frame) along
    for stale in (
        pa + ".tmp.npz",              # pre-r11 fixed name
        pa + ".tmp.999.888.npz",      # per-writer name, dead writer
    ):
        with open(stale, "wb") as f:
            f.write(b"half-written")
    live_b = pb + ".tmp.777.666.npz"
    with open(live_b, "wb") as f:
        f.write(b"in flight")
    assert ckpt.cleanup_stale_tmp(pa)
    assert not [
        n for n in os.listdir(tmp_path)
        if n.startswith("frame.a.npz.tmp.")
    ]
    assert os.path.exists(live_b)  # B's tmp untouched
    assert os.path.exists(pb)      # B's frame untouched
    assert not ckpt.cleanup_stale_tmp(pa)  # idempotent: nothing left
    os.remove(live_b)
