"""Fused level megakernel tests (round 13, ``-fuse level``).

The acceptance bar (ISSUE 9):

- the dispatch-count REGRESSION GATE: on the pinned producer_on oracle
  the fused engine executes an exact, pinned number of megakernel
  dispatches and stats fetches — steady-state levels cost exactly
  1 dispatch + 1 fetch, the ramp batches >= 4 levels per dispatch, and
  no per-level stage dispatches survive (a future PR reintroducing a
  per-level host round trip fails here);
- fused-vs-stage state-for-state differentials: identical level sizes,
  rows, parent/lane logs on clean runs, identical violation gid +
  replayed trace on both published bug oracles;
- ramp-megakernel survivability: a mid-ramp ``kill@level:N`` drill
  crash-resumes to the exact uninterrupted result;
- the daemon time-slices ``-fuse level`` jobs with solo parity;
- telemetry: the v6 stream validates, and the validator's fused-run
  cross-check catches a corrupted per-level record.
"""

import dataclasses
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker_mod():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(ROOT, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk(c, fuse="level", sub_batch=256, **kw):
    kw.setdefault("visited_cap", 1 << 12)
    kw.setdefault("frontier_cap", 1 << 12)
    return DeviceChecker(
        CompactionModel(c), invariants=kw.pop("invariants", ()),
        sub_batch=sub_batch, fuse=fuse, **kw,
    )


# ---- the dispatch-count regression gate (tier-1 acceptance) ---------


def test_fused_dispatch_count_regression_gate(tmp_path):
    """Pinned dispatch economy on the producer_on oracle (1,654 states
    / 16 levels).  With sub_batch=256 every frontier fits one expand
    window, so the WHOLE run is two ramp batches of 8 levels: exactly
    2 megakernel dispatches + 3 stats fetches (init + one per batch),
    and zero per-level stage dispatches (the stage counters show only
    the init path's single flush/compact/append chain).  Any future
    change that reintroduces a per-level host round trip moves these
    exact numbers and fails here."""
    stream = str(tmp_path / "fuse_gate.jsonl")
    ck = _mk(SMALL_CONFIGS["producer_on"], telemetry=stream)
    r = ck.run()
    assert r.distinct_states == 1654 and r.diameter == 16
    assert ck.fuse == "level"
    assert ck.last_stats["stage_fused_n"] == 2
    assert ck._fetch_n == 3  # init fetch + 1 per megakernel dispatch
    assert ck.last_stats["fuse_levels"] == 16
    assert ck.last_stats["dispatches_per_level"] < 0.5
    # the init path is the ONLY user of the stage chain
    assert ck.last_stats["stage_flush_n"] == 1
    assert ck.last_stats["stage_compact_n"] == 1
    assert ck.last_stats["stage_append_n"] == 1
    assert "stage_expand_n" not in ck.last_stats
    evs = [json.loads(x) for x in open(stream)]
    fuse_evs = [e for e in evs if e["event"] == "fuse"]
    assert [e["levels"] for e in fuse_evs] == [8, 8]
    # ramp acceptance: >= 4 levels batched into one dispatch
    assert max(e["levels"] for e in fuse_evs) >= 4


def test_fused_steady_state_one_dispatch_one_fetch_per_level(tmp_path):
    """With sub_batch=64 the deep producer_on levels (sizes 76..212)
    exceed one expand window, so the ramp hands off after its 4-level
    opening batch and every steady-state level costs EXACTLY one
    megakernel dispatch + one stats fetch."""
    stream = str(tmp_path / "fuse_steady.jsonl")
    ck = _mk(SMALL_CONFIGS["producer_on"], sub_batch=64,
             telemetry=stream)
    r = ck.run()
    assert r.distinct_states == 1654 and r.diameter == 16
    assert ck.last_stats["stage_fused_n"] == 13  # 1 ramp + 12 steady
    assert ck._fetch_n == 14
    assert ck.last_stats["dispatches_per_level"] == 1.0
    evs = [json.loads(x) for x in open(stream)]
    fuse_evs = [e for e in evs if e["event"] == "fuse"]
    assert fuse_evs[0]["levels"] == 4  # the ramp batch
    # every steady-state dispatch closed exactly one level
    assert all(e["levels"] == 1 for e in fuse_evs[1:])


# ---- fused-vs-stage state-for-state differentials -------------------


@pytest.mark.parametrize("name", ["producer_on", "two_crashes"])
def test_fused_vs_stage_state_for_state(name):
    """Same states in the same order: level sizes, packed rows, and
    parent/lane trace logs must be bit-identical between the fused
    megakernel and the r10 stage chain."""
    c = SMALL_CONFIGS[name]
    ck_f = _mk(c)
    r_f = ck_f.run()
    ck_s = _mk(c, fuse="stage")
    r_s = ck_s.run()
    assert r_f.distinct_states == r_s.distinct_states
    assert r_f.level_sizes == r_s.level_sizes
    nv, W = r_f.distinct_states, ck_f.W
    for key in ("parent", "lane"):
        a = np.asarray(ck_f.last_bufs[key][:nv])
        b = np.asarray(ck_s.last_bufs[key][:nv])
        assert (a == b).all(), key
    a = np.asarray(ck_f.last_bufs["rows"][: nv * W])
    b = np.asarray(ck_s.last_bufs["rows"][: nv * W])
    assert (a == b).all()


@pytest.mark.parametrize(
    "invariant,depth",
    [("CompactedLedgerLeak", 12), ("DuplicateNullKeyMessage", 4)],
)
def test_fused_vs_stage_bug_oracles(invariant, depth):
    """Both published counterexamples: identical violation gid and an
    identical replayed trace through the fused path."""
    m1 = CompactionModel(pe.SHIPPED_CFG)
    r_f = DeviceChecker(
        m1, invariants=(invariant,), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    ).run()
    m2 = CompactionModel(pe.SHIPPED_CFG)
    r_s = DeviceChecker(
        m2, invariants=(invariant,), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15, fuse="stage",
    ).run()
    assert r_f.violation == r_s.violation == invariant
    assert r_f.violation_gid == r_s.violation_gid
    assert r_f.diameter == r_s.diameter == depth
    assert r_f.trace == r_s.trace
    assert r_f.trace_actions == r_s.trace_actions
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r_f.trace, r_f.trace_actions, invariant
    )


def test_fused_growth_and_flush_factor_matches_oracle():
    """Tiny capacities force mid-level segmentation (the megakernel
    exits on its in-kernel capacity guard, the host grows, re-enters
    via w_off) and flush_factor>1 exercises multi-window groups with
    masked partial tails; counts must stay exact."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = _mk(c, sub_batch=64, visited_cap=1 << 6,
              frontier_cap=1 << 6, group=2).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    got = _mk(c, sub_batch=128, visited_cap=1 << 10,
              frontier_cap=1 << 10, flush_factor=4).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_fused_sort_visited_falls_back_to_stage():
    """The fused kernel chains the fpset probe; the legacy sort-merge
    visited set keeps the stage chain (the r6 differential path stays
    bit-for-bit) — silently, so existing -visited sort flows work."""
    ck = _mk(SMALL_CONFIGS["producer_on"], visited_impl="sort")
    assert ck.fuse == "stage"
    r = ck.run()
    assert r.distinct_states == 1654


def test_fuse_ctor_validation():
    with pytest.raises(ValueError, match="fuse must be"):
        _mk(SMALL_CONFIGS["producer_on"], fuse="banana")
    with pytest.raises(ValueError, match="fuse_group"):
        _mk(SMALL_CONFIGS["producer_on"], fuse_group=0)


def test_fuse_group_one_disables_ramp_batching(tmp_path):
    stream = str(tmp_path / "fuse_g1.jsonl")
    ck = _mk(SMALL_CONFIGS["producer_on"], fuse_group=1,
             telemetry=stream)
    r = ck.run()
    assert r.distinct_states == 1654
    evs = [json.loads(x) for x in open(stream)]
    assert all(
        e["levels"] <= 1 for e in evs if e["event"] == "fuse"
    )
    assert ck.last_stats["stage_fused_n"] == 16


# ---- ramp survivability: mid-ramp kill drill ------------------------


def _run_drill(tmp_path, fault, resume=False):
    env = dict(os.environ)
    env["PTT_FAULT"] = "" if resume else fault
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, "-m", "tests._survivable_run",
        "--checkpoint", str(tmp_path / "frame.npz"),
        "--every", "4",
        "--telemetry", str(tmp_path / "drill.jsonl"),
    ]
    if resume:
        cmd.append("--resume")
    return subprocess.run(
        cmd, cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=900,
    )


def test_mid_ramp_kill_drill_crash_resume_parity(tmp_path):
    """kill@level:7 with checkpoint_every=4: level 7 sits mid-batch
    (batches end on checkpoint boundaries — levels 5..8 share one
    dispatch on the shipped ramp), so the kill fires during the
    host-side replay of a multi-level megakernel batch.  The resumed
    run must land the exact 45,198/diam-20 published result."""
    p = _run_drill(tmp_path, "kill@level:7")
    assert p.returncode == 137, p.stderr[-500:]
    # the crashed run's stream proves the drill hit a RAMP batch: a
    # fuse record closing >1 level precedes the kill breadcrumb
    evs = [json.loads(x) for x in open(tmp_path / "drill.jsonl")]
    assert any(
        e["event"] == "fuse" and e["levels"] > 1 for e in evs
    )
    assert any(e["event"] == "fault" for e in evs)
    p2 = _run_drill(tmp_path, "", resume=True)
    assert p2.returncode == 0, p2.stderr[-500:]
    out = json.loads(p2.stdout.strip().splitlines()[-1])
    assert out["distinct_states"] == 45198
    assert out["diameter"] == 20
    assert not out["truncated"]


# ---- the daemon time-slices fused jobs with solo parity -------------


def test_daemon_timeslices_fused_jobs_with_solo_parity(tmp_path):
    """Two queued jobs share one device through suspend/resume at
    level boundaries while BOTH run the fused megakernel (the r13
    default): results match solo runs state-for-state and the pool's
    checkers genuinely dispatched fused."""
    from pulsar_tlaplus_tpu.service import jobs as jobmod
    from pulsar_tlaplus_tpu.service.scheduler import (
        CheckerPool,
        Scheduler,
        ServiceConfig,
    )

    cfgs = tmp_path / "cfgs"
    cfgs.mkdir()
    (cfgs / "a.cfg").write_text(
        "CONSTANTS\n    MessageSentLimit = 2\n"
        "    CompactionTimesLimit = 2\n    ModelConsumer = FALSE\n"
        "    ConsumeTimesLimit = 2\n    KeySpace = {1}\n"
        "    ValueSpace = {1}\n    RetainNullKey = TRUE\n"
        "    MaxCrashTimes = 1\n    ModelProducer = TRUE\n"
        "SPECIFICATION Spec\nINVARIANTS\n"
    )
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"),
        slice_s=0.2,
        sub_batch=64,
        visited_cap=1 << 10,
        frontier_cap=1 << 8,
        max_states=1 << 14,
        checkpoint_every=1,
        prewarm_tiers=False,
    )
    pool = CheckerPool(config)
    sched = Scheduler(config, pool=pool)
    j1 = sched.submit("compaction", str(cfgs / "a.cfg"), invariants=[])
    j2 = sched.submit("compaction", str(cfgs / "a.cfg"), invariants=[])
    sched.run_until_idle()
    assert j1.state == j2.state == jobmod.DONE
    assert j1.suspends >= 1  # time-slicing genuinely happened
    solo = _mk(
        SMALL_CONFIGS["producer_on"], sub_batch=64,
        visited_cap=1 << 10, frontier_cap=1 << 8,
        max_states=1 << 14,
    ).run()
    for j in (j1, j2):
        assert j.result["distinct_states"] == solo.distinct_states
        assert j.result["diameter"] == solo.diameter
        assert j.result["level_sizes"] == list(solo.level_sizes)
    # the pooled checker ran the fused path, not a silent fallback
    (_key, ck), = pool._checkers.items()
    assert ck.fuse == "level"
    assert ck.last_stats.get("stage_fused_n", 0) > 0


# ---- telemetry schema v6 + the fused-run validator cross-check ------


def test_fused_stream_validates_and_crosschecks(tmp_path):
    ckr = _checker_mod()
    stream = tmp_path / "v6.jsonl"
    ck = _mk(SMALL_CONFIGS["producer_on"], telemetry=str(stream))
    r = ck.run()
    assert ckr.validate_stream(str(stream)) == []
    evs = [json.loads(x) for x in open(stream)]
    # boundary level records reproduce the result's level sizes
    bound = [
        e for e in evs
        if e["event"] == "level" and not e.get("partial")
    ]
    assert [e["new_states"] for e in bound] == list(r.level_sizes)[1:]
    # negative: corrupt one boundary record's count — the v6
    # cross-check must flag it (sizes no longer match the result)
    bad = []
    done = False
    for e in evs:
        if (
            not done and e["event"] == "level"
            and not e.get("partial")
        ):
            e = dict(e, new_states=e["new_states"] + 1)
            done = True
        bad.append(e)
    p = tmp_path / "v6_bad.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in bad))
    errs = ckr.validate_stream(str(p))
    assert errs and any("level" in e for e in errs)
    # negative: a dropped boundary record breaks nothing (levels may
    # legally be absent) but a DUPLICATED one breaks monotonicity
    dup = evs + [e for e in evs if e["event"] == "level"][:1]
    for i, e in enumerate(dup):
        dup[i] = dict(e, seq=i)
    p2 = tmp_path / "v6_dup.jsonl"
    p2.write_text("".join(json.dumps(e) + "\n" for e in dup))
    errs2 = ckr.validate_stream(str(p2))
    assert errs2 and any("strictly increasing" in e for e in errs2)


def test_bench_schema_v6_keys(tmp_path):
    """bench_schema 6 artifacts must carry the fuse keys; a v6
    artifact missing them fails the validator."""
    ckr = _checker_mod()
    base = {k: 1 for k in ckr.BENCH_KEYS_V6}
    base.update(bench_schema=6, value=1.0)
    assert ckr.validate_bench_artifact(dict(base), "good") == []
    bad = dict(base)
    del bad["fuse"], bad["dispatches_per_level"]
    errs = ckr.validate_bench_artifact(bad, "bad")
    assert any("fuse" in e for e in errs)
    assert any("dispatches_per_level" in e for e in errs)


def test_shipped_oracle_through_fused_path():
    """The 45,198-state / diameter-20 vendored reference binding,
    state-count-pinned through the fused megakernel (the ISSUE 9
    acceptance restated on the engine default)."""
    ck = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    )
    assert ck.fuse == "level"
    r = ck.run()
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock
    assert ck.last_stats["dispatches_per_level"] <= 2.0


def test_fused_prewarm_zero_compiles_across_tier_crossing():
    """warmup(tiers=True) walks the unified fused staircase: a run
    that crosses capacity tiers adds ZERO jitted programs after run()
    starts (the r10 prewarm contract, now covering the megakernel's
    (TCAP, LCAP, PCAP) triples)."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ck = _mk(c, sub_batch=64, visited_cap=1 << 6, frontier_cap=1 << 6,
             group=2, max_states=1 << 12)
    v0 = ck.VCAP
    ck.warmup(seed=False, tiers=True)
    keys_before = set(ck._jits)
    r = ck.run()
    assert set(ck._jits) == keys_before  # zero post-run() compiles
    assert ck.VCAP > v0  # the run genuinely crossed tiers
    assert r.distinct_states == want.distinct_states


def test_fused_frontier_window_matches_oracle():
    """rows_window="frontier" under the fused path: ramp batching is
    host-disabled (the boundary shift is host-side) but levels still
    run as single fused dispatches; counts stay exact."""
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, sub_batch=256, visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 13,
    ).run()
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert not r.truncated
