"""Self-tuning checker tests (ISSUE r15, tune/).

- the knob SPACE enumerates validity-pruned candidates, defaults
  first;
- the PREDICT stage ranks by the calibrated cost model (dispatch
  overhead and probe-schedule scaling move ranks the right way);
- PROFILES round-trip, resolve by config signature, and are
  warned-and-ignored when corrupt / version-mismatched / renamed /
  cross-config — the engine always falls back to defaults, never
  crashes, and a profile written for one config-sig is NEVER applied
  to another;
- the ENGINE resolves profiles at construction (explicit knobs win),
  records ``profile_sig`` on the v8 run header, and discovery order
  is state-for-state identical under tuned profiles AND online
  adaptation — pinned on both published compaction bug oracles;
- the ONLINE controller nudges only within its declared bounds, at
  dispatch boundaries, with every change a telemetry ``tune`` event;
- the DAEMON prewarms tuned knobs: a warm submit against a profiled
  key pays zero jit compiles (the r10/r13 ``set(ck._jits)`` harness)
  and its slice headers carry the profile sig;
- the LEDGER splits tuned vs default trajectories (``profile_sig``
  on records, gate ``--profile none`` = the "tuning never regresses"
  check against the pinned machine-independent keys);
- ``cli.py tune`` runs the whole predict -> measure -> persist loop
  end-to-end and the written profile resolves back into the engine.
"""

import importlib.util
import json
import os
import shutil

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.bookkeeper import (
    BookkeeperConstants,
    BookkeeperModel,
)
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import ledger
from pulsar_tlaplus_tpu.obs import telemetry
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.tune import online, predict, profiles, space
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PINNED = os.path.join(
    ROOT, "tests", "data", "mini_bench_producer_on.jsonl"
)
BK_KW = dict(sub_batch=256, visited_cap=1 << 12, frontier_cap=1 << 10)


@pytest.fixture(autouse=True)
def _isolated_profiles(tmp_path, monkeypatch):
    """Every test gets its own empty profile store — a stray
    ~/.ptt_profiles must never shape test runs."""
    monkeypatch.setenv(
        profiles.TUNE_DIR_ENV, str(tmp_path / "profiles")
    )
    monkeypatch.delenv(online.ADAPT_ENV, raising=False)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bk_model():
    return BookkeeperModel(BookkeeperConstants())


def _bk_profile(knobs, model=None, invariants=None, **over):
    """Write a profile keyed for the shipped-bookkeeper test config
    and return (sig, profile)."""
    m = model or _bk_model()
    invs = (
        invariants
        if invariants is not None
        else tuple(m.default_invariants)
    )
    sig = profiles.profile_key(
        model=m, invariants=invs, engine="device_bfs"
    )
    prof = profiles.build(
        sig=sig, engine="device_bfs", backend="cpu", knobs=knobs,
        spec="bookkeeper", **over,
    )
    profiles.save(prof)
    return sig, prof


# ---- knob space ------------------------------------------------------


def test_space_defaults_first_and_validity_pruned():
    m = _bk_model()
    cands = space.candidates(m, base_sub_batch=8192)
    assert cands[0] == {}  # the baseline the winner must beat
    assert space.describe(cands[0]) == "defaults"
    assert len(cands) > 100
    for c in cands:
        g = c.get("sub_batch", 8192)
        ff = c.get("flush_factor", 1)
        # the engine's int32 flat-addressing constraint holds for
        # every enumerated candidate
        assert g * m.A * ff * m.layout.W < 1 << 31
    # sub_batch multipliers resolve to powers of two
    subs = {c["sub_batch"] for c in cands if "sub_batch" in c}
    assert subs and all(s & (s - 1) == 0 for s in subs)
    # limit caps enumeration
    assert len(space.candidates(m, limit=7)) == 7


# ---- prediction ------------------------------------------------------


def _ref(levels, sub_batch=2048):
    return {
        "backend": "cpu",
        "work": {
            "expand_rows": 50_000, "probe_lanes": 400_000,
            "compact_elems": 120_000, "append_rows": 45_000,
        },
        "level_sizes": levels,
        "distinct_states": sum(levels),
        "sub_batch": sub_batch,
        "fuse_group": 8,
        "flush_factor": 1,
        "group": 4,
        "A": 22,
        "dense_rounds": 4,
        "stages": ((4, 16), (16, 64)),
        "avg_probe_rounds": 3.0,
        "wall_s": 1.0,
    }


def test_predict_dispatch_overhead_ranks_fuse_group():
    """A long ramp makes fuse_group=1 strictly more expensive than
    fuse_group=16 — the overhead term the megakernel exists for."""
    ref = _ref([10, 20, 40, 80, 160, 300, 700, 1500], sub_batch=2048)
    p1 = predict.predict_candidate({"fuse_group": 1}, ref)
    p16 = predict.predict_candidate({"fuse_group": 16}, ref)
    assert p1["dispatches"] > p16["dispatches"]
    assert p1["est_s"] > p16["est_s"]


def test_predict_probe_schedule_scales_lanes():
    """Fewer dense rounds present fewer full-width probe lanes (the
    work the adaptation loop watches); more dense rounds present
    more."""
    ref = _ref([100, 400, 1000])
    # observed probe depth must exceed the dense rounds under test:
    # raising dense ABOVE the depth lanes actually reach changes
    # nothing (and the model is right to say so)
    ref["avg_probe_rounds"] = 6.0
    base = predict.predict_candidate({}, ref)
    lo = predict.predict_candidate({"fpset_dense_rounds": 2}, ref)
    hi = predict.predict_candidate({"fpset_dense_rounds": 8}, ref)
    assert lo["est_work"]["probe_lanes"] < base["est_work"]["probe_lanes"]
    assert hi["est_work"]["probe_lanes"] > base["est_work"]["probe_lanes"]
    # state-determined work never moves
    for k in ("expand_rows", "append_rows", "compact_elems"):
        assert lo["est_work"][k] == ref["work"][k]


def test_predict_rank_orders_by_cost():
    ref = _ref([10, 20, 40, 80])
    ranked = predict.rank(
        [{}, {"fuse_group": 1}, {"fuse_group": 16}], ref
    )
    costs = [p["est_s"] for _c, p in ranked]
    assert costs == sorted(costs)


# ---- profile lifecycle ----------------------------------------------


def test_profile_roundtrip_and_key_identity():
    sig, prof = _bk_profile({"fuse_group": 2, "sub_batch": 512})
    assert profiles.load(sig)["knobs"]["fuse_group"] == 2
    # key is stable across model instances with equal constants...
    assert sig == profiles.profile_key(
        model=_bk_model(),
        invariants=tuple(_bk_model().default_invariants),
        engine="device_bfs",
    )
    # ...and differs across constants, invariant sets, and engines
    other = BookkeeperModel(BookkeeperConstants(entry_limit=3))
    assert sig != profiles.profile_key(
        model=other, invariants=tuple(other.default_invariants),
        engine="device_bfs",
    )
    assert sig != profiles.profile_key(
        model=_bk_model(), invariants=("TypeOK",),
        engine="device_bfs",
    )
    assert sig != profiles.profile_key(
        model=_bk_model(),
        invariants=tuple(_bk_model().default_invariants),
        engine="liveness",
    )


def test_corrupt_stale_and_mismatched_profiles_warned_and_ignored(
    capsys,
):
    """Every bad-profile mode degrades to defaults with a stderr
    note — never a crash, never a silently-applied wrong profile."""
    sig, prof = _bk_profile({"fuse_group": 2})
    path = profiles.path_for(sig)

    # corrupt JSON
    with open(path, "w") as f:
        f.write("{not json")
    assert profiles.load(sig) is None
    assert "ignored" in capsys.readouterr().err

    # version mismatch
    stale = dict(prof, profile_v=profiles.PROFILE_VERSION + 1)
    with open(path, "w") as f:
        json.dump(stale, f)
    assert profiles.load(sig) is None
    assert "profile_v" in capsys.readouterr().err

    # wrong engine
    profiles.save(prof)
    assert profiles.load(sig, engine="liveness") is None
    assert "engine" in capsys.readouterr().err

    # a renamed/copied file never crosses config signatures
    other_sig = "0" * 16
    shutil.copy(path, profiles.path_for(other_sig))
    assert profiles.load(other_sig) is None
    assert "sig" in capsys.readouterr().err

    # the engine shrugs all of this off: corrupt file -> defaults
    with open(path, "w") as f:
        f.write("\x00garbage")
    ck = DeviceChecker(_bk_model(), profile="auto", **BK_KW)
    assert ck.profile_sig is None
    assert ck.G == 256 and ck.RMAX == 8  # untouched defaults


def test_profile_never_applied_to_another_config():
    sig, prof = _bk_profile({"fuse_group": 2})
    # same profile dict handed to a DIFFERENT config: refused
    m2 = CompactionModel(pe.SHIPPED_CFG)
    assert (
        profiles.resolve(
            prof, model=m2,
            invariants=tuple(pe.DEFAULT_INVARIANTS),
            engine="device_bfs",
        )
        is None
    )
    ck = DeviceChecker(
        m2, profile=prof, sub_batch=2048, visited_cap=1 << 16,
        frontier_cap=1 << 15,
    )
    assert ck.profile_sig is None and ck.RMAX == 8


def test_profile_validator_catches_unknown_knobs():
    sig, prof = _bk_profile({"fuse_group": 2})
    bad = dict(prof, knobs={"warp_drive": 11})
    errs = profiles.validate(bad)
    assert errs and "warp_drive" in errs[0]
    with pytest.raises(ValueError, match="warp_drive"):
        profiles.save(bad)


# ---- engine resolution ----------------------------------------------


def test_engine_resolves_profile_and_explicit_knobs_win(tmp_path):
    sig, _prof = _bk_profile(
        {"fuse_group": 2, "sub_batch": 512, "fpset_dense_rounds": 2}
    )
    stream = str(tmp_path / "run.jsonl")
    ck = DeviceChecker(
        _bk_model(), profile="auto", telemetry=stream,
        visited_cap=1 << 12, frontier_cap=1 << 10,
    )
    assert ck.profile_sig == sig
    assert ck.G == 512 and ck.RMAX == 2 and ck.fps_dense == 2
    assert set(ck.profile_applied) == {
        "fuse_group", "sub_batch", "fpset_dense_rounds",
    }
    r = ck.run()
    assert (r.distinct_states, r.diameter) == (297, 14)  # pinned
    hd = [json.loads(x) for x in open(stream)][0]
    assert hd["event"] == "run_header"
    assert hd["profile_sig"] == sig
    assert hd["v"] == telemetry.SCHEMA_VERSION

    # explicit ctor knobs beat the profile, sig still attributes
    ck2 = DeviceChecker(
        _bk_model(), profile="auto", fuse_group=8, **BK_KW
    )
    assert ck2.profile_sig == sig
    assert ck2.RMAX == 8 and ck2.G == 256
    assert "fuse_group" not in ck2.profile_applied
    assert "sub_batch" not in ck2.profile_applied  # explicit BK_KW


def test_liveness_engine_resolves_its_own_profile(tmp_path):
    m = _bk_model()
    sig = profiles.profile_key(
        model=m, invariants=(), engine="liveness"
    )
    profiles.save(
        profiles.build(
            sig=sig, engine="liveness", backend="cpu",
            knobs={"sweep_group": 2}, spec="bookkeeper",
        )
    )
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    stream = str(tmp_path / "live.jsonl")
    lck = LivenessChecker(
        m, goal="Termination", fairness="wf_next", profile="auto",
        telemetry=stream,
    )
    assert lck.profile_sig == sig
    assert lck.sweep_group == 2
    r = lck.run()
    assert r.holds, r.reason
    headers = [
        json.loads(x)
        for x in open(stream)
        if '"run_header"' in x
    ]
    live_hd = [h for h in headers if h["engine"] == "liveness"]
    assert live_hd and live_hd[0]["profile_sig"] == sig


# ---- discovery-order differentials (the acceptance pins) ------------


TUNED_KNOBS = {
    "fuse_group": 2,
    "fpset_dense_rounds": 2,
    "flush_factor": 2,
    "group": 2,
}


@pytest.mark.parametrize(
    "invariant,depth",
    [("CompactedLedgerLeak", 12), ("DuplicateNullKeyMessage", 4)],
)
def test_tuned_and_adapted_bug_oracles_state_for_state(
    invariant, depth
):
    """Both published counterexamples: identical violation gid and
    identical replayed trace under (a) hand defaults, (b) a tuned
    profile moving every schedule knob, (c) online adaptation —
    tuning changes schedules and batching, never semantics."""
    kw = dict(
        invariants=(invariant,), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    )
    r_def = DeviceChecker(CompactionModel(pe.SHIPPED_CFG), **kw).run()
    m = CompactionModel(pe.SHIPPED_CFG)
    sig = profiles.profile_key(
        model=m, invariants=(invariant,), engine="device_bfs"
    )
    profiles.save(
        profiles.build(
            sig=sig, engine="device_bfs", backend="cpu",
            knobs=dict(TUNED_KNOBS, sub_batch=1024),
            spec="compaction",
        )
    )
    ck_t = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), profile="auto",
        invariants=(invariant,), visited_cap=1 << 16,
        frontier_cap=1 << 15,
    )
    assert ck_t.profile_sig == sig and ck_t.G == 1024
    r_tun = ck_t.run()
    r_ada = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), adapt=True, **kw
    ).run()
    for r in (r_tun, r_ada):
        assert r.violation == r_def.violation == invariant
        assert r.violation_gid == r_def.violation_gid
        assert r.diameter == r_def.diameter == depth
        assert r.trace == r_def.trace
        assert r.trace_actions == r_def.trace_actions
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r_def.trace, r_def.trace_actions, invariant
    )


def test_online_adaptation_state_for_state_with_tune_events(tmp_path):
    """Adaptation on the producer_on oracle: identical states in the
    identical order (level sizes, packed rows, trace logs), every
    adjustment a bounded v8 ``tune`` event at a dispatch boundary."""
    c = SMALL_CONFIGS["producer_on"]
    kw = dict(sub_batch=512, visited_cap=1 << 13, frontier_cap=1 << 12)
    ck_a = DeviceChecker(CompactionModel(c), **kw)
    r_a = ck_a.run()
    stream = str(tmp_path / "adapt.jsonl")
    ck_b = DeviceChecker(
        CompactionModel(c), adapt=True, telemetry=stream, **kw
    )
    r_b = ck_b.run()
    assert r_b.distinct_states == r_a.distinct_states
    assert r_b.level_sizes == r_a.level_sizes
    nv, W = r_a.distinct_states, ck_a.W
    for key in ("parent", "lane"):
        assert (
            np.asarray(ck_b.last_bufs[key][:nv])
            == np.asarray(ck_a.last_bufs[key][:nv])
        ).all(), key
    assert (
        np.asarray(ck_b.last_bufs["rows"][: nv * W])
        == np.asarray(ck_a.last_bufs["rows"][: nv * W])
    ).all()
    evs = [json.loads(x) for x in open(stream)]
    assert evs[0]["adapt"] is True
    tunes = [e for e in evs if e["event"] == "tune"]
    # the controller moved at least one knob on this workload (the
    # shipped schedule's 4 dense rounds are oversized for a table
    # that never probes deep), and every move respected its bounds
    assert tunes
    for e in tunes:
        assert e["v"] == telemetry.SCHEMA_VERSION
        assert e["knob"] in (
            "fuse_cap", "fpset_dense_rounds",
        )
        if e["knob"] == "fuse_cap":
            assert 1 <= e["value"] <= ck_b.RMAX
        else:
            assert online.MIN_DENSE <= e["value"] <= online.MAX_DENSE
    assert ck_b.last_stats["tune_adjustments"] == len(tunes)
    # kill switch: PTT_TUNE_ADAPT=0 beats the explicit ctor flag
    os.environ[online.ADAPT_ENV] = "0"
    try:
        ck_c = DeviceChecker(CompactionModel(c), adapt=True, **kw)
        assert ck_c.adapt is False
    finally:
        del os.environ[online.ADAPT_ENV]


def test_online_controller_policy_bounds():
    ctl = online.OnlineController(8, 4, ((4, 16), (16, 64)))
    # two consecutive ramp early-exits shrink the cap to what ran
    assert not ctl.observe(
        levels_closed=3, cap_asked=8, max_probe_rounds=3
    )
    adjs = ctl.observe(levels_closed=3, cap_asked=8, max_probe_rounds=3)
    assert [a["knob"] for a in adjs] == ["fuse_cap"]
    assert ctl.fuse_cap == 3
    # two consecutive full batches double it back (bounded by rmax)
    ctl.observe(levels_closed=3, cap_asked=3, max_probe_rounds=3)
    adjs = ctl.observe(levels_closed=3, cap_asked=3, max_probe_rounds=3)
    assert ctl.fuse_cap == 6 and adjs
    # probe pressure doubles dense rounds ONCE per observed max: the
    # engine feeds a run-lifetime maximum, so repeating the same max
    # must not ratchet (each raise would re-jit the megakernel), and
    # calm can never lower a pressured controller (hysteresis)
    adjs = ctl.observe(
        levels_closed=1, cap_asked=1, max_probe_rounds=40
    )
    assert [a["knob"] for a in adjs] == ["fpset_dense_rounds"]
    assert ctl.dense == 8
    for _ in range(6):
        ctl.observe(levels_closed=1, cap_asked=1, max_probe_rounds=40)
    assert ctl.dense == 8
    # only a NEW high (genuinely deeper probing) escalates again
    adjs = ctl.observe(
        levels_closed=1, cap_asked=1, max_probe_rounds=55
    )
    assert ctl.dense == 16 and adjs
    # calm controller (fresh) lowers toward the floor, never below
    ctl2 = online.OnlineController(8, 4, ((4, 16), (16, 64)))
    for _ in range(8):
        ctl2.observe(levels_closed=1, cap_asked=1, max_probe_rounds=1)
    assert ctl2.dense == online.MIN_DENSE


# ---- schema v8 + validators -----------------------------------------


@pytest.fixture(scope="module")
def checker_mod():
    return _load_script("check_telemetry_schema")


def test_v8_stream_validates_and_profile_sig_required(
    tmp_path, checker_mod
):
    stream = str(tmp_path / "v8.jsonl")
    DeviceChecker(_bk_model(), telemetry=stream, **BK_KW).run()
    assert checker_mod.validate_stream(stream) == []
    evs = [json.loads(x) for x in open(stream)]
    assert evs[0]["profile_sig"] is None  # untuned: null, not absent
    # a v8 header WITHOUT the field fails; the same header at v7
    # stays clean (FIELD_SINCE gating — committed streams unaffected)
    del evs[0]["profile_sig"]
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    errs = checker_mod.validate_stream(bad)
    assert errs and "profile_sig" in errs[0]
    evs[0]["v"] = 7
    ok = str(tmp_path / "v7.jsonl")
    with open(ok, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    assert checker_mod.validate_stream(ok) == []


def test_tune_event_required_fields(tmp_path, checker_mod):
    stream = str(tmp_path / "adapt.jsonl")
    ck = DeviceChecker(
        _bk_model(), adapt=True, telemetry=stream, **BK_KW
    )
    ck.run()
    assert checker_mod.validate_stream(stream) == []
    evs = [json.loads(x) for x in open(stream)]
    tunes = [e for e in evs if e["event"] == "tune"]
    assert tunes  # bookkeeper's shallow table triggers the calm rule
    bad = dict(tunes[0])
    del bad["knob"]
    p = str(tmp_path / "bad.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(evs[0]) + "\n")
        bad["seq"] = evs[0]["seq"] + 1
        f.write(json.dumps(bad) + "\n")
    errs = checker_mod.validate_stream(p)
    assert errs and "knob" in errs[0]


def test_profile_validator_front_end(tmp_path, checker_mod):
    sig, _prof = _bk_profile({"fuse_group": 4})
    path = profiles.path_for(sig)
    assert checker_mod.main([path, "--profile"]) == 0
    assert profiles.validate_file(path) == []
    # renamed copy: filename/sig disagreement is a violation
    rogue = str(tmp_path / ("f" * 16 + ".json"))
    shutil.copy(path, rogue)
    assert checker_mod.main([rogue, "--profile"]) == 1
    # unknown knob is a violation
    d = json.load(open(path))
    d["knobs"]["warp_drive"] = 1
    with open(path, "w") as f:
        json.dump(d, f)
    assert checker_mod.main([path, "--profile"]) == 1


# ---- ledger: tuned vs default context -------------------------------


def test_ledger_gate_with_tuning_enabled(tmp_path):
    """The acceptance pin: a tuned run gates CLEAN against the
    committed machine-independent baseline (tuning never regresses
    dispatches/level or work-units/state), records carry
    profile_sig, and ``gate --profile none`` is the tuned-vs-hand-
    defaults check."""
    from pulsar_tlaplus_tpu import cli

    # EXACTLY the pinned mini-bench shape (test_attribution._mk:
    # invariants=(), sub_batch=256) so the ledger config keys match
    c = SMALL_CONFIGS["producer_on"]
    m = CompactionModel(c)
    kw = dict(
        invariants=(), sub_batch=256, visited_cap=1 << 12,
        frontier_cap=1 << 12,
    )
    sig = profiles.profile_key(
        model=m, invariants=(), engine="device_bfs"
    )
    # schedule-only knobs: the dispatch economy must not regress
    profiles.save(
        profiles.build(
            sig=sig, engine="device_bfs", backend="cpu",
            knobs={"fpset_dense_rounds": 2, "group": 8},
            spec="compaction",
        )
    )
    stream = str(tmp_path / "tuned.jsonl")
    ck = DeviceChecker(m, profile="auto", telemetry=stream, **kw)
    assert ck.profile_sig == sig
    ck.run()
    rec = ledger.record_from_file(stream)
    assert rec["values"]["profile_sig"] == sig
    assert ledger.profile_of(rec) == sig

    path = str(tmp_path / "ledger.jsonl")
    shutil.copy(PINNED, path)
    rc = cli.main(["ledger", "--ledger", path, "add", stream])
    assert rc == 0
    # tuned current vs the UNTUNED pinned baseline on the
    # machine-independent keys: --profile none finds it and passes
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate",
            "--profile", "none", "--threshold", "0.1",
            "--keys", "dispatches_per_level", "work_units_per_state",
        ]
    )
    assert rc == 0
    # default context "same" has no tuned baseline yet: exit 2, not
    # a vacuous pass
    rc = cli.main(["ledger", "--ledger", path, "gate"])
    assert rc == 2
    # ...and once a tuned baseline exists, "same" gates against it
    stream2 = str(tmp_path / "tuned2.jsonl")
    DeviceChecker(
        CompactionModel(c), profile="auto", telemetry=stream2, **kw
    ).run()
    assert cli.main(["ledger", "--ledger", path, "add", stream2]) == 0
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate",
            "--keys", "dispatches_per_level", "work_units_per_state",
        ]
    )
    assert rc == 0
    # the trajectory table shows the profile column
    out = ledger.render_list(ledger.load(path))
    assert "profile_sig" in out and sig in out


# ---- daemon: warm tuned submits -------------------------------------


def test_daemon_warm_tuned_submit_zero_compiles(tmp_path):
    """The serving acceptance pin: the pool resolves the tuned
    profile at construction, prewarm compiles the TUNED programs,
    a warm submit adds zero jits, and the slice's run header carries
    profile_sig."""
    from pulsar_tlaplus_tpu.service import jobs as jobmod
    from pulsar_tlaplus_tpu.service.scheduler import (
        CheckerPool,
        Scheduler,
        ServiceConfig,
    )
    from pulsar_tlaplus_tpu.utils import cfg as cfgmod

    bk_cfg = os.path.join(ROOT, "specs", "bookkeeper.cfg")
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"),
        sub_batch=256, visited_cap=1 << 8, frontier_cap=1 << 7,
        max_states=1 << 12, slice_s=30.0,
    )
    pool = CheckerPool(config)
    model = pool.build_model("bookkeeper", cfgmod.load(bk_cfg))
    invs = pool.resolve_invariants(
        "bookkeeper", cfgmod.load(bk_cfg), None
    )
    sig = profiles.profile_key(
        model=model, invariants=tuple(invs), engine="device_bfs"
    )
    profiles.save(
        profiles.build(
            sig=sig, engine="device_bfs", backend="cpu",
            knobs={"fuse_group": 4, "fpset_dense_rounds": 2},
            spec="bookkeeper",
        )
    )
    key, _compile_s = pool.warm("bookkeeper", bk_cfg)
    ck = pool._checkers[key]
    assert ck.profile_sig == sig  # tuned knobs were prewarmed
    assert ck.RMAX == 4 and ck.fps_dense == 2
    assert ck.adapt is False  # the pool pins adaptation off
    assert ck._jits
    keys_before = set(ck._jits)

    sched = Scheduler(config, pool=pool)
    job = sched.submit("bookkeeper", bk_cfg)
    sched.run_until_idle()
    assert job.state == jobmod.DONE
    assert job.result["status"] == "ok"
    assert job.result["distinct_states"] == 297  # pinned oracle
    assert set(ck._jits) == keys_before  # ZERO post-warm compiles
    evs = [
        json.loads(x)
        for x in open(os.path.join(job.dir, "events.jsonl"))
    ]
    hd = [e for e in evs if e["event"] == "run_header"][0]
    assert hd["profile_sig"] == sig


# ---- cli tune end-to-end --------------------------------------------


def test_cli_tune_end_to_end(tmp_path, capsys, checker_mod):
    """The whole loop: predict (full space, pruned), measure top-K
    interleaved min-of-2, persist — then the written profile
    resolves back into a fresh engine with the pinned count, and
    validates under the --profile schema mode."""
    from pulsar_tlaplus_tpu import cli

    rc = cli.main(
        [
            "tune", "bookkeeper",
            "--maxstates", "4096",
            "--visited-cap", "4096",
            "--frontier-cap", "2048",
            "--top-k", "1",
            "--repeat", "2",
            "--stream-dir", str(tmp_path / "streams"),
            "--ledger", str(tmp_path / "tune_ledger.jsonl"),
            "-cpu",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    # the report shows the predict-stage pruning: candidates
    # predicted vs measured, and a measured column
    assert "predicted 648 candidate(s)" in out.replace("\n", " ") or (
        "candidate" in out and "measured" in out
    )
    prof_files = os.listdir(profiles.profiles_dir())
    assert len(prof_files) == 1
    path = os.path.join(profiles.profiles_dir(), prof_files[0])
    assert checker_mod.main([path, "--profile"]) == 0
    prof = json.load(open(path))
    t = prof["tuner"]
    assert t["candidates_predicted"] > 100
    assert t["candidates_measured"] >= 2  # baseline + top-k
    # min-of-2 interleaved: the winner never loses to the baseline
    assert t["winner_s"] <= t["baseline_s"] + 1e-9
    # measured runs were ingested into the ledger
    recs = ledger.load(str(tmp_path / "tune_ledger.jsonl"))
    assert recs
    # the profile resolves back into a fresh engine
    from pulsar_tlaplus_tpu.models import registry
    from pulsar_tlaplus_tpu.utils import cfg as cfgmod

    tlc_cfg = cfgmod.load(os.path.join(ROOT, "specs", "bookkeeper.cfg"))
    model, _ = registry.COMPILED["bookkeeper"](tlc_cfg)
    ck = DeviceChecker(
        model, invariants=tuple(tlc_cfg.invariants), profile="auto",
        visited_cap=4096, frontier_cap=2048, max_states=4096,
    )
    assert ck.profile_sig == prof["sig"]
    r = ck.run()
    assert (r.distinct_states, r.diameter) == (297, 14)
