"""Per-action and per-invariant differential tests vs the Python oracle
(SURVEY.md §4d): every successor lane and every invariant verdict must agree
on a depth-spread sample of reachable states."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, oracle_sample


def _batch(m, sample):
    return jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[m.from_pystate(s) for s in sample],
    )


@pytest.mark.parametrize("name", sorted(SMALL_CONFIGS))
def test_successors_match_oracle(name):
    c = SMALL_CONFIGS[name]
    m = CompactionModel(c)
    sample = oracle_sample(c, n_states=100, seed=2)
    batch = _batch(m, sample)
    succs, valid = jax.jit(jax.vmap(m.successors))(batch)
    valid = np.asarray(valid)
    for i, s in enumerate(sample):
        want = {}
        for a, t in pe.successors(c, s):
            if a <= 7:  # non-stuttering lanes
                want.setdefault(a, []).append(t)
        got = {}
        for lane in range(m.A):
            if valid[i, lane]:
                st = jax.tree.map(lambda x: np.asarray(x)[i, lane], succs)
                got.setdefault(int(m.action_ids[lane]), []).append(
                    m.to_pystate(st)
                )
        assert {k: sorted(v) for k, v in want.items()} == {
            k: sorted(v) for k, v in got.items()
        }, f"state {s}"


@pytest.mark.parametrize("name", sorted(SMALL_CONFIGS))
def test_invariants_match_oracle(name):
    c = SMALL_CONFIGS[name]
    m = CompactionModel(c)
    sample = oracle_sample(c, n_states=100, seed=3)
    batch = _batch(m, sample)
    pairs = [
        ("TypeSafe", pe.type_safe),
        ("CompactedLedgerLeak", pe.compacted_ledger_leak),
        ("CompactionHorizonCorrectness", pe.compaction_horizon_correctness),
        ("DuplicateNullKeyMessage", pe.duplicate_null_key_message),
    ]
    for inv_name, pfn in pairs:
        got = np.asarray(jax.jit(jax.vmap(m.invariants[inv_name]))(batch))
        want = np.array([pfn(c, s) for s in sample])
        assert (got == want).all(), inv_name


@pytest.mark.parametrize("name", sorted(SMALL_CONFIGS))
def test_stutter_enabledness_match_oracle(name):
    c = SMALL_CONFIGS[name]
    m = CompactionModel(c)
    sample = oracle_sample(c, n_states=100, seed=4)
    batch = _batch(m, sample)
    got = np.asarray(jax.jit(jax.vmap(m.stutter_enabled))(batch))
    for i, s in enumerate(sample):
        want = any(a in (8, 9) for a, _ in pe.successors(c, s))
        assert bool(got[i]) == want, s
