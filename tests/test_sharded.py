"""Multi-chip determinism tests (SURVEY.md §4e) on a virtual CPU mesh:
n_devices in {1, 2, 4, 8} must produce identical distinct-state counts,
diameters, and verdicts."""

import pytest

from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import needs_shard_map, SMALL_CONFIGS

pytestmark = needs_shard_map


@pytest.mark.parametrize("nd", [1, 2, 4, 8])
def test_sharded_matches_oracle(nd):
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedChecker(
        CompactionModel(c),
        n_devices=nd,
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 12,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_hash_dedup_matches_oracle():
    """Hash-table visited sets per shard (with growth/rehash) produce
    the exact oracle counts."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedChecker(
        CompactionModel(c),
        n_devices=4,
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 8,  # force rehash growth
        dedup_mode="hash",
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_2d_mesh_matches_oracle():
    """2-D (dcn, ici) mesh with hierarchical fingerprint routing:
    identical counts on a 2x4 virtual mesh (SURVEY.md §2.2-E11)."""
    from pulsar_tlaplus_tpu.parallel.mesh import make_mesh2d

    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedChecker(
        CompactionModel(c),
        mesh=make_mesh2d(2, 4),
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 12,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_checkpoint_resume(tmp_path):
    """Interrupt a sharded run at a level-boundary checkpoint and resume;
    the final counts must match an uninterrupted run."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ckpt = str(tmp_path / "sharded.npz")
    metrics = str(tmp_path / "metrics.jsonl")
    first = ShardedChecker(
        CompactionModel(c),
        n_devices=2,
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 12,
        checkpoint_path=ckpt,
        checkpoint_every=2,
        metrics_path=metrics,
        time_budget_s=0.0,  # truncate ASAP after the first checkpoint
    )
    r1 = first.run()
    assert r1.truncated
    import os

    assert os.path.exists(ckpt)
    second = ShardedChecker(
        CompactionModel(c),
        n_devices=2,
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 12,
        checkpoint_path=ckpt,
    )
    r2 = second.run(resume=True)
    assert r2.distinct_states == want.distinct_states
    assert r2.diameter == want.diameter
    assert os.path.getsize(metrics) > 0


def test_sharded_violation_trace_valid():
    c = SMALL_CONFIGS["shipped"]
    got = ShardedChecker(
        CompactionModel(c),
        n_devices=4,
        invariants=("CompactedLedgerLeak",),
        frontier_chunk=512,
        visited_cap=1 << 13,
    ).run()
    assert got.violation == "CompactedLedgerLeak"
    assert got.diameter == 12  # shortest-counterexample depth is device-count
    # independent (BFS level = depth), even if the reported state differs
    from tests.helpers import assert_valid_counterexample

    assert_valid_counterexample(
        c, got.trace, got.trace_actions, "CompactedLedgerLeak"
    )
