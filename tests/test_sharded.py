"""Multi-chip determinism tests (SURVEY.md §4e) on a virtual CPU mesh:
n_devices in {1, 2, 4, 8} must produce identical distinct-state counts,
diameters, and verdicts."""

import pytest

from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS


@pytest.mark.parametrize("nd", [1, 2, 4, 8])
def test_sharded_matches_oracle(nd):
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedChecker(
        CompactionModel(c),
        n_devices=nd,
        invariants=(),
        frontier_chunk=256,
        visited_cap=1 << 12,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_violation_trace_valid():
    c = SMALL_CONFIGS["shipped"]
    got = ShardedChecker(
        CompactionModel(c),
        n_devices=4,
        invariants=("CompactedLedgerLeak",),
        frontier_chunk=512,
        visited_cap=1 << 13,
    ).run()
    assert got.violation == "CompactedLedgerLeak"
    assert got.diameter == 12  # shortest-counterexample depth is device-count
    # independent (BFS level = depth), even if the reported state differs
    from tests.helpers import assert_valid_counterexample

    assert_valid_counterexample(
        c, got.trace, got.trace_actions, "CompactedLedgerLeak"
    )
