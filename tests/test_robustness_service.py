"""Open-network daemon hardening (r17, ISSUE 13).

The acceptance bar:

- the CHAOS DRILL: a daemon under injected connection drops, torn
  protocol lines, and a persist ENOSPC, with concurrent clients
  retrying through it, completes every ADMITTED job with
  state-for-state solo parity while over-quota and bad-token submits
  are rejected with their distinct exit codes and appear in
  ``ptt_admission_*`` (``scripts/chaos.py``, seeded + reproducible);
- a retried submit with the same ``submit_id`` never creates a second
  job — pinned through a real ``drop@conn`` (reply lost, request
  processed);
- telemetry v10 streams from the drills are validator-clean, and the
  v10 gates (``run_header.tenant``, ``admission``/``auth``/
  ``deadline`` required fields) hold records to their own version;
- one fast drill per new service fault site (drop/torn/enospc x2),
  auth accept+reject, quota reject, priority preemption order,
  deadline cancel — all tier-1; the randomized chaos run slow-marked.
"""

import importlib.util
import json
import os
import time

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.bookkeeper import (
    BookkeeperConstants,
    BookkeeperModel,
)
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.service import admission as admmod
from pulsar_tlaplus_tpu.service import auth as authmod
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.client import (
    AdmissionRejected,
    AuthError,
    ServiceClient,
    TransportError,
    backoff_delays,
    poll_delays,
)
from pulsar_tlaplus_tpu.service.scheduler import (
    CheckerPool,
    Scheduler,
    ServiceConfig,
)
from pulsar_tlaplus_tpu.service.server import ServiceDaemon
from pulsar_tlaplus_tpu.utils import faults
from tests.helpers import SMALL_CONFIGS, tight_hbm_budget

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the test_service engine geometry: small caps, growth exercised,
# cheap on the CPU mesh — and identical across solo/pool so parity is
# state-for-state
GEOM = dict(
    sub_batch=64,
    visited_cap=1 << 10,
    frontier_cap=1 << 8,
    max_states=1 << 20,
    checkpoint_every=1,
)

SMALL_COMPACTION_CFG = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 2
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = TRUE
    MaxCrashTimes = 1
    ModelProducer = TRUE
SPECIFICATION Spec
INVARIANTS
"""

BK_CRASH2_CFG = """
CONSTANTS
    NumBookies = 3
    WriteQuorum = 2
    AckQuorum = 2
    EntryLimit = 2
    MaxBookieCrashes = 2
SPECIFICATION Spec
INVARIANTS
    ConfirmedEntryReadable
"""

TOKENS = {
    "tokens_v": 1,
    "tenants": [
        {"tenant": "alpha", "token": "test-alpha-token-1"},
        {"tenant": "beta", "token": "test-beta-token-22"},
    ],
}


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def checker_mod():
    return _load_script("check_telemetry_schema")


@pytest.fixture(scope="module")
def chaos_mod():
    return _load_script("chaos")


@pytest.fixture(scope="module")
def cfg_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cfgs")
    (d / "small_compaction.cfg").write_text(SMALL_COMPACTION_CFG)
    (d / "bk_crash2.cfg").write_text(BK_CRASH2_CFG)
    (d / "tokens.json").write_text(json.dumps(TOKENS))
    return d


def _config(state_dir, **kw) -> ServiceConfig:
    base = dict(GEOM)
    base.update(kw)
    return ServiceConfig(state_dir=str(state_dir), **base)


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    return CheckerPool(
        _config(tmp_path_factory.mktemp("pool-anchor"))
    )


def _solo(model, invariants):
    return DeviceChecker(
        model,
        invariants=invariants,
        sub_batch=GEOM["sub_batch"],
        visited_cap=GEOM["visited_cap"],
        frontier_cap=GEOM["frontier_cap"],
        max_states=GEOM["max_states"],
    ).run()


@pytest.fixture(scope="module")
def solo_compaction():
    want = pe.check(SMALL_CONFIGS["producer_on"], invariants=())
    solo = _solo(CompactionModel(SMALL_CONFIGS["producer_on"]), ())
    assert solo.distinct_states == want.distinct_states == 1654
    return solo


@pytest.fixture(scope="module")
def solo_bk_crash2():
    solo = _solo(
        BookkeeperModel(BookkeeperConstants(max_bookie_crashes=2)),
        ("ConfirmedEntryReadable",),
    )
    assert solo.violation == "ConfirmedEntryReadable"
    assert len(solo.trace) == 9
    return solo


@pytest.fixture()
def fault_env():
    """Set PTT_FAULT for one test, re-arm the spec cache, and always
    restore afterwards (the faults module is process-global)."""
    def arm(spec: str):
        os.environ["PTT_FAULT"] = spec
        faults.reset()

    prev = os.environ.get("PTT_FAULT")
    yield arm
    if prev is None:
        os.environ.pop("PTT_FAULT", None)
    else:
        os.environ["PTT_FAULT"] = prev
    faults.reset()


# ---- auth: tokens.json + constant-time handshake --------------------


class TestAuth:
    def test_tokens_validation(self, tmp_path):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(TOKENS))
        assert authmod.validate_tokens_file(str(good)) == []
        assert authmod.load_tokens(str(good)) == {
            "test-alpha-token-1": "alpha",
            "test-beta-token-22": "beta",
        }
        for label, obj in {
            "not-object": [1],
            "no-version": {"tenants": TOKENS["tenants"]},
            "newer": {"tokens_v": 99, "tenants": TOKENS["tenants"]},
            "empty": {"tokens_v": 1, "tenants": []},
            "short-token": {
                "tokens_v": 1,
                "tenants": [{"tenant": "a", "token": "short"}],
            },
            "dup-token": {
                "tokens_v": 1,
                "tenants": [
                    {"tenant": "a", "token": "same-token-12345"},
                    {"tenant": "b", "token": "same-token-12345"},
                ],
            },
            "reserved": {
                "tokens_v": 1,
                "tenants": [
                    {
                        "tenant": authmod.LOCAL_TENANT,
                        "token": "whatever-token-1",
                    }
                ],
            },
        }.items():
            errs = authmod.validate_tokens_obj(obj, label=label)
            assert errs, label
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"tokens_v": 1, "tenants": []}))
        with pytest.raises(ValueError):
            authmod.load_tokens(str(bad))

    def test_tokens_cli_front_end(self, tmp_path, checker_mod):
        good = tmp_path / "tokens.json"
        good.write_text(json.dumps(TOKENS))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"tokens_v": 1, "tenants": []}))
        assert checker_mod.main(["--tokens", str(good)]) == 0
        assert checker_mod.main(["--tokens", str(bad)]) == 1

    def test_authenticate_never_trusts_claims(self):
        tokens = {"test-alpha-token-1": "alpha"}
        assert authmod.authenticate(tokens, "test-alpha-token-1") == (
            "alpha"
        )
        assert authmod.authenticate(tokens, "wrong") is None
        assert authmod.authenticate(tokens, None) is None
        assert authmod.authenticate({}, "test-alpha-token-1") is None

    def test_tcp_requires_tokens(self, tmp_path, pool):
        with pytest.raises(ValueError, match="requires --tokens"):
            ServiceDaemon(
                _config(tmp_path / "state", tcp="127.0.0.1:0"),
                pool=pool,
            )


# ---- the TCP transport: accept, reject, tenant attribution ----------


def test_tcp_auth_roundtrip_and_reject(
    tmp_path, pool, cfg_dir, checker_mod
):
    """A good token submits over TCP and the derived tenant lands on
    the job, the job_submit event, and the engine run header (v10);
    a bad token is rejected with the typed ``auth`` code; the streams
    validate."""
    config = _config(
        tmp_path / "state", slice_s=0.3,
        tcp="127.0.0.1:0", tokens_path=str(cfg_dir / "tokens.json"),
    )
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        addr = f"tcp://127.0.0.1:{daemon.tcp_port}"
        with pytest.raises(AuthError):
            ServiceClient(addr, token="wrong-token", retries=1).submit(
                "compaction", str(cfg_dir / "small_compaction.cfg"),
            )
        cl = ServiceClient(
            addr, token="test-alpha-token-1", timeout=240.0
        )
        jid = cl.submit(
            "compaction", str(cfg_dir / "small_compaction.cfg"),
            invariants=[],
        )
        r = cl.wait(jid, timeout=240.0)
        assert r["result"]["distinct_states"] == 1654
        job = daemon.sched.get(jid)
        assert job.tenant == "alpha"
    finally:
        daemon.shutdown()
    # tenant end to end: job_submit + auth events in the daemon
    # stream, tenant on every engine run header, all v10-clean
    evs = [json.loads(x) for x in open(config.telemetry_path)]
    assert {
        e["action"] for e in evs if e["event"] == "auth"
    } == {"accept", "reject"}
    sub = [e for e in evs if e["event"] == "job_submit"][0]
    assert sub["tenant"] == "alpha"
    assert checker_mod.validate_stream(config.telemetry_path) == []
    heads = [
        json.loads(x)
        for x in open(job.events_path)
        if '"run_header"' in x
    ]
    assert heads and all(h["tenant"] == "alpha" for h in heads)
    assert checker_mod.validate_stream(job.events_path) == []


def test_cli_exit_codes_auth_and_quota(tmp_path, pool, cfg_dir):
    """The distinct client exit codes: 4 = bad token, 5 = over quota
    — never 1 (violation) or 2 (transport)."""
    from pulsar_tlaplus_tpu import cli

    config = _config(
        tmp_path / "state", slice_s=30.0,
        tcp="127.0.0.1:0", tokens_path=str(cfg_dir / "tokens.json"),
        tenant_max_queued=1,
    )
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    # freeze claiming so the queued quota-filler stays QUEUED — the
    # overflow decision must not race the scheduler thread
    daemon.sched._stop.set()
    try:
        addr = f"tcp://127.0.0.1:{daemon.tcp_port}"
        cfg = str(cfg_dir / "small_compaction.cfg")
        with pytest.raises(SystemExit) as ei:
            cli.main([
                "submit", "compaction", cfg,
                "--socket", addr, "--token", "wrong-token",
            ])
        assert ei.value.code == 4
        # fill the quota, then overflow it
        cl = ServiceClient(addr, token="test-beta-token-22")
        cl.submit("compaction", cfg, invariants=[])
        with pytest.raises(SystemExit) as ei:
            cli.main([
                "submit", "compaction", cfg,
                "--socket", addr, "--token", "test-beta-token-22",
            ])
        assert ei.value.code == 5
        # the contract holds on EVERY subcommand, not just submit: a
        # bad token on `status` is "fix my token" (4), never "the
        # daemon is down" (2)
        with pytest.raises(SystemExit) as ei:
            cli.main([
                "status", "--socket", addr, "--token", "wrong-token",
            ])
        assert ei.value.code == 4
    finally:
        daemon.shutdown()


# ---- admission control ----------------------------------------------


def test_quota_rejections_never_queue(tmp_path, pool, cfg_dir):
    """Over-quota and over-capacity submits are rejected AT THE DOOR:
    typed errors, nothing enqueued, counters + admission events."""
    config = _config(
        tmp_path / "state",
        queue_cap=3, tenant_max_queued=2, tenant_max_states=1 << 21,
    )
    sched = Scheduler(config, pool=pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    j1 = sched.submit("compaction", cfg, tenant="alpha")
    sched.submit("compaction", cfg, tenant="alpha")
    with pytest.raises(admmod.AdmissionError) as ei:
        sched.submit("compaction", cfg, tenant="alpha")
    assert ei.value.code == "quota"
    assert ei.value.reason == "tenant_queued"
    # another tenant still fits — then the GLOBAL cap sheds
    sched.submit("compaction", cfg, tenant="beta")
    with pytest.raises(admmod.AdmissionError) as ei:
        sched.submit("compaction", cfg, tenant="beta")
    assert ei.value.code == "capacity"
    assert ei.value.reason == "queue_full"
    # aggregate state budget: each job prices at the service default
    cfg2 = _config(
        tmp_path / "state2", tenant_max_states=GEOM["max_states"],
    )
    sched2 = Scheduler(cfg2, pool=pool)
    sched2.submit("compaction", cfg, tenant="alpha")
    with pytest.raises(admmod.AdmissionError) as ei:
        sched2.submit("compaction", cfg, tenant="alpha")
    assert ei.value.reason == "tenant_states"
    # the unix-socket operator ("local") is exempt from per-tenant
    # quotas — a pre-r17 local batch sweep must keep queueing freely
    # (the global queue_cap shed still applies to everyone)
    sched2.submit("compaction", cfg)
    sched2.submit("compaction", cfg)
    # nothing over quota ever entered the table
    assert len(sched.jobs) == 3
    snap = sched.admission.snapshot()
    assert snap["admitted"] == {"alpha": 2, "beta": 1}
    assert snap["rejected"] == {
        "alpha/tenant_queued": 1, "beta/queue_full": 1,
    }
    # the decisions are telemetry too (the ptt_admission_* source in
    # file-scrape mode is these events)
    from pulsar_tlaplus_tpu.obs import metrics as metrics_mod

    text = metrics_mod.render_exposition(
        metrics_mod.scheduler_metrics(sched)
    )
    assert 'ptt_admission_admitted_total{tenant="alpha"} 2' in text
    assert (
        'ptt_admission_rejected_total{reason="tenant_queued",'
        'tenant="alpha"} 1' in text
    )
    assert 'ptt_admission_shed_total{tenant="beta"} 1' in text
    assert j1.tenant == "alpha"


# ---- priorities + deadlines -----------------------------------------


def test_priority_claim_order_and_preemption(tmp_path, pool, cfg_dir):
    """(priority, FIFO) claim order, and a waiting higher-priority
    job preempts the running lower-priority one at its next level
    boundary (through the existing suspend/resume primitive)."""
    cfg = str(cfg_dir / "small_compaction.cfg")
    config = _config(tmp_path / "state", slice_s=30.0)
    sched = Scheduler(config, pool=pool)
    jlow = sched.submit("compaction", cfg, invariants=[], priority=0)
    sched.start()
    deadline = time.monotonic() + 120.0
    while jlow.state == jobmod.QUEUED:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    jhigh = sched.submit("compaction", cfg, invariants=[], priority=5)
    sched.wait(jhigh.job_id, timeout=240.0)
    sched.wait(jlow.job_id, timeout=240.0)
    sched.stop(timeout=120.0)
    assert jlow.state == jhigh.state == jobmod.DONE
    # the running low-prio job was preempted (not just sliced out:
    # slice_s is 30 s, far beyond either job's wall)
    assert jlow.suspends >= 1
    assert jhigh.suspends == 0
    assert jhigh.finished_unix < jlow.finished_unix
    # both still exact
    assert jlow.result["distinct_states"] == 1654
    assert jhigh.result["distinct_states"] == 1654

    # claim order within the synchronous drain: high before low, FIFO
    # within a class
    config2 = _config(tmp_path / "state2")
    sched2 = Scheduler(config2, pool=pool)
    ja = sched2.submit("compaction", cfg, invariants=[], priority=0)
    jb = sched2.submit("compaction", cfg, invariants=[], priority=2)
    jc = sched2.submit("compaction", cfg, invariants=[], priority=2)
    sched2.run_until_idle()
    order = sorted(
        (j.started_unix, j.job_id)
        for j in (ja, jb, jc)
    )
    assert [jid for _t, jid in order] == [
        jb.job_id, jc.job_id, ja.job_id
    ]


def test_deadline_cancels_queued_and_running(
    tmp_path, pool, cfg_dir, checker_mod
):
    """The deadline sweep cancels an expired queued job; a running
    job's hook cancels it mid-run — both with the honest
    ``stop_reason="deadline"`` record, a v10 ``deadline`` event, and
    exit code 3 (truncated, no verdict)."""
    from pulsar_tlaplus_tpu import cli as climod
    from pulsar_tlaplus_tpu.obs.telemetry import Telemetry

    cfg = str(cfg_dir / "small_compaction.cfg")
    config = _config(tmp_path / "state", slice_s=30.0)
    stream = str(tmp_path / "svc.jsonl")
    tel = Telemetry(stream)
    sched = Scheduler(config, pool=pool, telemetry=tel)
    with pytest.raises(ValueError, match="deadline_s"):
        sched.submit("compaction", cfg, deadline_s=0.0)
    # a queued job whose deadline passes before it is claimed
    jq = sched.submit(
        "compaction", cfg, invariants=[], deadline_s=1e-3,
    )
    time.sleep(0.01)
    sched.run_until_idle()  # the sweep expires it before any claim
    assert jq.slices == 0
    # a running job whose deadline passes mid-run: claim the slice,
    # then expire the deadline under it — the hook's next level-
    # boundary poll discards the run (deterministic: no wall racing)
    jr = sched.submit("compaction", cfg, invariants=[], deadline_s=60.0)
    job = sched._claim()
    assert job is jr
    with sched.cv:
        jr.deadline_unix = time.time() - 0.01
    sched._run_slice(jr)
    tel.close()
    for j in (jq, jr):
        assert j.state == jobmod.DONE
        assert j.result["status"] == "deadline"
        assert j.result["stop_reason"] == "deadline"
        assert j.result["truncated"] is True
        assert json.load(open(j.result_path)) == j.result
        # exit-code contract: truncated/no-verdict = 3
        assert climod._service_exit(j.state, j.result, None) == 3
    evs = [json.loads(x) for x in open(stream)]
    dl = [e for e in evs if e["event"] == "deadline"]
    assert {e["job_id"] for e in dl} == {jq.job_id, jr.job_id}
    assert checker_mod.validate_stream(stream) == []


# ---- client resilience ----------------------------------------------


def test_backoff_helpers():
    import random as _random

    rng = _random.Random(7)
    ds = list(backoff_delays(6, base=0.05, cap=1.0, rng=rng))
    assert len(ds) == 6
    assert all(0 <= d <= 1.0 for d in ds)
    # the envelope doubles until the cap
    assert ds[5] <= 1.0
    gen = poll_delays(base=0.05, cap=0.5, rng=_random.Random(3))
    seq = [next(gen) for _ in range(10)]
    assert all(0 < d <= 0.5 for d in seq)
    assert seq[9] >= 0.25  # ramped to the cap's neighborhood

    # two clients with different rngs never sleep in lockstep
    a = list(backoff_delays(5, rng=_random.Random(1)))
    b = list(backoff_delays(5, rng=_random.Random(2)))
    assert a != b


def test_drop_conn_retry_dedups_submit(
    tmp_path, pool, cfg_dir, fault_env
):
    """THE dedup pin: the daemon processes a submit but drops the
    reply (``drop@conn``); the client's retry with the same
    ``submit_id`` must return the SAME job — one job in the table,
    one ``dedup`` admission decision."""
    config = _config(tmp_path / "state", slice_s=0.3)
    fault_env("drop@conn:1")
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        cl = ServiceClient(
            config.socket_path, timeout=240.0, retries=5,
        )
        jid = cl.submit(
            "compaction", str(cfg_dir / "small_compaction.cfg"),
            invariants=[], submit_id="pinned-submit",
        )
        # a second explicit retry is the same job too
        assert cl.submit(
            "compaction", str(cfg_dir / "small_compaction.cfg"),
            invariants=[], submit_id="pinned-submit",
        ) == jid
        assert len(cl.status()) == 1
        r = cl.wait(jid, timeout=240.0)
        assert r["result"]["distinct_states"] == 1654
        snap = daemon.sched.admission.snapshot()
        assert snap["admitted"] == {"local": 1}
        assert snap["deduped"]["local"] >= 2
    finally:
        daemon.shutdown()


def test_torn_line_retries_clean(tmp_path, pool, cfg_dir, fault_env):
    """``torn@line``: the daemon tears a reply line mid-write; the
    client sees a protocol error and retries to success."""
    config = _config(tmp_path / "state")
    fault_env("torn@line:1")
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        cl = ServiceClient(config.socket_path, retries=5)
        pong = cl.ping()  # first reply line is torn; retry succeeds
        assert pong["pid"] == os.getpid()
        # retries exhausted surfaces as TransportError (exit 2), not
        # a violation: arm more tears than the budget
        fault_env(",".join(f"torn@line:{i}" for i in range(2, 9)))
        with pytest.raises(TransportError):
            ServiceClient(config.socket_path, retries=2).ping()
    finally:
        daemon.shutdown()


def test_enospc_persist_retries_and_daemon_survives(
    tmp_path, pool, cfg_dir, fault_env
):
    """``enospc@persist``: a queue.json snapshot hits disk-full; the
    retry (after freeing the half-written tmp) succeeds, the daemon
    keeps serving, and the final queue.json parses."""
    config = _config(tmp_path / "state")
    fault_env("enospc@persist:1")
    sched = Scheduler(config, pool=pool)
    job = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[],
    )
    sched.run_until_idle()
    assert job.state == jobmod.DONE
    assert job.result["distinct_states"] == 1654
    assert sched.persist_failures == 0  # the retry absorbed it
    snap = json.load(open(config.queue_path))
    assert {d["state"] for d in snap["jobs"]} == {jobmod.DONE}
    assert not [
        f for f in os.listdir(config.state_dir)
        if ".tmp." in f
    ]


# ---- torn-queue recovery (satellite) --------------------------------


def test_torn_queue_recovery_rebuilds_from_job_dirs(
    tmp_path, pool, cfg_dir, solo_compaction
):
    """``serve --recover`` with a forged half-written queue.json
    quarantines it and rebuilds the queue from the per-job dirs —
    jobs complete with solo parity, submit_id dedup survives."""
    cfg = str(cfg_dir / "small_compaction.cfg")
    config = _config(tmp_path / "state")
    sched = Scheduler(config, pool=pool)
    j1 = sched.submit(
        "compaction", cfg, invariants=[], submit_id="recover-me",
    )
    j2 = sched.submit("compaction", cfg, invariants=[])
    # forge the torn write: a half-written queue.json
    raw = open(config.queue_path).read()
    with open(config.queue_path, "w") as f:
        f.write(raw[: len(raw) // 2])

    sched2 = Scheduler(config, pool=pool)
    assert sched2.recover() == 2
    corrupt = [
        f for f in os.listdir(config.state_dir)
        if f.startswith("queue.json.corrupt.")
    ]
    assert len(corrupt) == 1
    # the quarantined bytes are the torn original (forensics intact)
    assert open(
        os.path.join(config.state_dir, corrupt[0])
    ).read() == raw[: len(raw) // 2]
    # dedup index rebuilt from the job dirs
    assert sched2.submit(
        "compaction", cfg, submit_id="recover-me",
    ).job_id == j1.job_id
    sched2.run_until_idle()
    r1, r2 = sched2.get(j1.job_id), sched2.get(j2.job_id)
    assert r1.state == r2.state == jobmod.DONE
    assert r1.result["distinct_states"] == solo_compaction.distinct_states
    assert r1.result["level_sizes"] == [
        int(x) for x in solo_compaction.level_sizes
    ]
    # a fresh queue.json took the torn one's place
    assert json.load(open(config.queue_path))["jobs"]
    # missing queue.json is still a clean no-op
    assert Scheduler(
        _config(tmp_path / "other"), pool=pool
    ).recover() == 0


# ---- spill-tier ENOSPC degradation (satellite) ----------------------


def test_spill_enospc_degrades_honestly(tmp_path, fault_env, checker_mod):
    """``enospc@spill``: the async spill worker hits disk-full; the
    run STOPS EVICTING and truncates with ``stop_reason=
    "spill_enospc"`` (counts exact up to the stop — the in-RAM tiers
    kept dedup sound), the ``spill`` record carries ``degraded``, no
    poisoned frame is left, and the stream validates."""
    c = SMALL_CONFIGS["producer_on"]

    def mk(**kw):
        kw.setdefault("invariants", ())
        kw.setdefault("check_deadlock", False)
        kw.setdefault("sub_batch", 64)
        kw.setdefault("visited_cap", 1 << 9)
        kw.setdefault("frontier_cap", 1 << 9)
        return DeviceChecker(CompactionModel(c), **kw)

    budget = tight_hbm_budget(lambda b: mk(hbm_budget=b))
    frame = str(tmp_path / "ck.npz")
    stream = str(tmp_path / "run.jsonl")
    fault_env("enospc@spill:1")
    ck = mk(
        hbm_budget=budget, checkpoint_path=frame,
        checkpoint_every=2, telemetry=stream,
    )
    r = ck.run()
    assert r.truncated and r.stop_reason == "spill_enospc"
    assert 0 < r.distinct_states < 1654
    assert ck.last_stats["spill_degraded"] is True
    assert ck.tstore.degraded
    evs = [json.loads(x) for x in open(stream)]
    degraded = [
        e for e in evs if e["event"] == "spill" and e.get("degraded")
    ]
    assert degraded
    assert checker_mod.validate_stream(stream) == []
    # a degraded store never anchors a manifest
    with pytest.raises(ValueError, match="degraded"):
        ck.tstore.manifest()


# ---- v10 schema gates -----------------------------------------------


def test_v10_validator_gates(tmp_path, checker_mod):
    """v10 requires run_header.tenant and the admission/auth/deadline
    fields — but holds older records only to their own version."""
    def rec(seq, t, event, v=10, **kw):
        base = {
            "v": v, "event": event, "t": t, "run_id": "r", "seq": seq,
        }
        base.update(kw)
        return base

    header = dict(
        engine="device_bfs", visited_impl="fpset", config_sig="sig",
        profile_sig=None, hbm_budget=None,
    )
    good = tmp_path / "good.jsonl"
    good.write_text(
        "\n".join(
            json.dumps(r)
            for r in [
                rec(0, 0.1, "run_header", tenant=None, **header),
                rec(1, 0.2, "admission", action="admit",
                    tenant="alpha"),
                rec(2, 0.3, "auth", action="reject"),
                rec(3, 0.4, "deadline", job_id="j1"),
                # a v9 header without tenant stays clean
                rec(4, 0.5, "run_header", v=9, **header),
            ]
        )
        + "\n"
    )
    assert checker_mod.validate_stream(str(good)) == []

    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        "\n".join(
            json.dumps(r)
            for r in [
                rec(0, 0.1, "run_header", **header),  # no tenant @v10
                rec(1, 0.2, "admission", action="reject"),  # no tenant
                rec(2, 0.3, "deadline"),  # no job_id
            ]
        )
        + "\n"
    )
    errs = checker_mod.validate_stream(str(bad))
    assert len(errs) == 3
    assert any("tenant" in e and "run_header" in e for e in errs)
    assert any("admission" in e for e in errs)
    assert any("deadline" in e for e in errs)


# ---- THE chaos drill (tier-1 fast; randomized slow) -----------------


@pytest.fixture(scope="module")
def chaos_solos(solo_compaction, solo_bk_crash2):
    return {
        "compaction": solo_compaction,
        "bookkeeper": solo_bk_crash2,
    }


def test_chaos_drill_fast(
    tmp_path, pool, chaos_mod, chaos_solos, fault_env
):
    """The acceptance drill, pinned schedule: a daemon under a
    connection drop, a torn protocol line, and a persist ENOSPC, with
    two concurrent retrying clients — every admitted job completes
    with state-for-state solo parity, over-quota and bad-token
    submits are rejected at the door and appear in ptt_admission_*,
    and every stream is v10-validator-clean."""
    report = chaos_mod.run_chaos(
        str(tmp_path / "chaos"),
        seed=1,
        schedule="drop@conn:2,torn@line:5,enospc@persist:2",
        pool=pool,
        geom=GEOM,
        clients=2,
        jobs_per_client=1,
        solos=chaos_solos,
        timeout_s=300.0,
    )
    assert report["completed"] == len(report["admitted"]) >= 3
    assert report["rejected"]["auth"] == 1
    assert report["rejected"]["quota"] >= 1
    assert report["streams_validated"] >= 3


@pytest.mark.slow
def test_chaos_drill_randomized(
    tmp_path, pool, chaos_mod, chaos_solos, fault_env
):
    """The full randomized drill: seeded fault schedules, more
    clients/jobs — reproduce any failure with the printed seed."""
    for seed in (3, 11):
        report = chaos_mod.run_chaos(
            str(tmp_path / f"chaos{seed}"),
            seed=seed,
            pool=pool,
            geom=GEOM,
            clients=3,
            jobs_per_client=2,
            solos=chaos_solos,
            timeout_s=540.0,
        )
        assert report["completed"] == len(report["admitted"])
