"""Packed-state codec property tests (SURVEY.md §4c): pack-unpack identity
and injectivity over oracle-reachable states."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from tests.helpers import SMALL_CONFIGS, oracle_sample


@pytest.mark.parametrize("name", sorted(SMALL_CONFIGS))
def test_roundtrip_and_injectivity(name):
    c = SMALL_CONFIGS[name]
    m = CompactionModel(c)
    sample = oracle_sample(c, n_states=120, seed=1)
    pack = jax.jit(jax.vmap(m.layout.pack))
    unpack = jax.jit(jax.vmap(m.layout.unpack))
    batch = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[m.from_pystate(s) for s in sample],
    )
    words = np.asarray(pack(batch))
    assert words.shape[1] == m.layout.W
    back = unpack(jnp.asarray(words))
    for i, s in enumerate(sample):
        s2 = m.to_pystate(jax.tree.map(lambda x: np.asarray(x)[i], back))
        assert s2 == s
    # injectivity: distinct TLA+ states -> distinct packed rows
    assert len({tuple(row) for row in words.tolist()}) == len(sample)


def test_layout_width_shipped():
    m = CompactionModel(SMALL_CONFIGS["shipped"])
    assert m.layout.total_bits <= 64  # fits 2 words -> exact (identity) keys
    assert m.layout.W == 2
