"""Packed-state codec property tests (SURVEY.md §4c): pack-unpack identity
and injectivity over oracle-reachable states."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from tests.helpers import SMALL_CONFIGS, oracle_sample


@pytest.mark.parametrize("name", sorted(SMALL_CONFIGS))
def test_roundtrip_and_injectivity(name):
    c = SMALL_CONFIGS[name]
    m = CompactionModel(c)
    sample = oracle_sample(c, n_states=120, seed=1)
    pack = jax.jit(jax.vmap(m.layout.pack))
    unpack = jax.jit(jax.vmap(m.layout.unpack))
    batch = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[m.from_pystate(s) for s in sample],
    )
    words = np.asarray(pack(batch))
    assert words.shape[1] == m.layout.W
    back = unpack(jnp.asarray(words))
    for i, s in enumerate(sample):
        s2 = m.to_pystate(jax.tree.map(lambda x: np.asarray(x)[i], back))
        assert s2 == s
    # injectivity: distinct TLA+ states -> distinct packed rows
    assert len({tuple(row) for row in words.tolist()}) == len(sample)


def test_layout_width_shipped():
    m = CompactionModel(SMALL_CONFIGS["shipped"])
    assert m.layout.total_bits <= 64  # fits 2 words -> exact (identity) keys
    assert m.layout.W == 2


class _ToyState(NamedTuple):
    a: jax.Array  # scalar
    b: jax.Array  # vector[3]
    m: jax.Array  # matrix[2, 2]


_TOY_SPECS = {"a": ((), 5), "b": ((3,), 7), "m": ((2, 2), 3)}


def test_struct_layout_roundtrip():
    from pulsar_tlaplus_tpu.ops.packing import StructLayout

    lay = StructLayout(_ToyState, _TOY_SPECS)
    assert lay.total_bits == 5 + 3 * 7 + 4 * 3
    rng = np.random.default_rng(7)
    seen = set()
    for _ in range(200):
        s = _ToyState(
            a=jnp.int32(rng.integers(0, 32)),
            b=jnp.asarray(rng.integers(0, 128, 3), jnp.int32),
            m=jnp.asarray(rng.integers(0, 8, (2, 2)), jnp.int32),
        )
        w = lay.pack(s)
        assert w.shape == (lay.W,)
        back = lay.unpack(w)
        assert int(back.a) == int(s.a)
        assert np.array_equal(np.asarray(back.b), np.asarray(s.b))
        assert np.array_equal(np.asarray(back.m), np.asarray(s.m))
        seen.add(tuple(np.asarray(w).tolist()))
    # word-spanning fields: b's 7-bit elements cross the 32-bit boundary
    assert lay.W == 2


def test_struct_layout_vmap_jit():
    from pulsar_tlaplus_tpu.ops.packing import StructLayout

    lay = StructLayout(_ToyState, _TOY_SPECS)
    batch = _ToyState(
        a=jnp.arange(4, dtype=jnp.int32),
        b=jnp.arange(12, dtype=jnp.int32).reshape(4, 3) % 128,
        m=jnp.arange(16, dtype=jnp.int32).reshape(4, 2, 2) % 8,
    )
    words = jax.jit(jax.vmap(lay.pack))(batch)
    back = jax.jit(jax.vmap(lay.unpack))(words)
    assert np.array_equal(np.asarray(back.a), np.asarray(batch.a))
    assert np.array_equal(np.asarray(back.b), np.asarray(batch.b))
    assert np.array_equal(np.asarray(back.m), np.asarray(batch.m))
