"""Checking-as-a-service tests (r11, ``pulsar_tlaplus_tpu/service/``).

The acceptance bar (ISSUE 6 / docs/service.md):

- >= 2 concurrent queued jobs time-slice ONE device, each job's result
  state-for-state equal (states, verdict, violation trace/gid) to a
  solo run of the same spec + .cfg;
- SIGTERM mid-job + ``serve --recover`` completes the queue with the
  same results (the in-process tests drive the exact code path the
  signal handler arms; the subprocess drill with a real SIGTERM is the
  ``slow``-marked load test);
- a warm-start submit against an already-warmed spec pays ZERO jit
  compiles (the capacity-tier prewarm harness from test_compact.py);
- the daemon's telemetry stream (schema v4 ``job_*`` events) and every
  per-job engine stream pass the schema validator.

One module-scoped CheckerPool is shared across tests — exactly the
resident-daemon shape: compiled programs persist while queues, state
dirs, and jobs come and go.
"""

import importlib.util
import json
import os
import time

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.bookkeeper import (
    BookkeeperConstants,
    BookkeeperModel,
)
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import report
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.client import ServiceClient, ServiceError
from pulsar_tlaplus_tpu.service.scheduler import (
    CheckerPool,
    Scheduler,
    ServiceConfig,
)
from pulsar_tlaplus_tpu.service.server import ServiceDaemon
from tests.helpers import SMALL_CONFIGS

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BK_CFG = os.path.join(ROOT, "specs", "bookkeeper.cfg")

# one engine geometry for the whole module (the daemon's "one geometry
# for the whole registry" rule): small caps so growth paths exercise,
# cheap enough for the CPU mesh
GEOM = dict(
    sub_batch=64,
    visited_cap=1 << 10,
    frontier_cap=1 << 8,
    max_states=1 << 20,
    checkpoint_every=1,
)

# small compaction binding == SMALL_CONFIGS["producer_on"] (1,654
# states, diameter 16 — asserted against the Python oracle below)
SMALL_COMPACTION_CFG = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 2
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = TRUE
    MaxCrashTimes = 1
    ModelProducer = TRUE
SPECIFICATION Spec
INVARIANTS
"""

# bookkeeper crash2 violates ConfirmedEntryReadable with a pinned
# 9-state counterexample (test_bookkeeper.py) — the violation/trace
# parity workload
BK_CRASH2_CFG = """
CONSTANTS
    NumBookies = 3
    WriteQuorum = 2
    AckQuorum = 2
    EntryLimit = 2
    MaxBookieCrashes = 2
SPECIFICATION Spec
INVARIANTS
    ConfirmedEntryReadable
"""


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def checker_mod():
    return _load_script("check_telemetry_schema")


@pytest.fixture(scope="module")
def cfg_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cfgs")
    (d / "small_compaction.cfg").write_text(SMALL_COMPACTION_CFG)
    (d / "bk_crash2.cfg").write_text(BK_CRASH2_CFG)
    return d


def _config(state_dir, **kw) -> ServiceConfig:
    base = dict(GEOM)
    base.update(kw)
    return ServiceConfig(state_dir=str(state_dir), **base)


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    """The resident pool: warmed checkers shared by every scheduler
    instance in this module (exactly what the daemon holds)."""
    return CheckerPool(
        _config(tmp_path_factory.mktemp("pool-anchor"))
    )


def _solo(model, invariants) -> object:
    """A solo run with the pool's exact engine geometry — the parity
    baseline the acceptance criteria name."""
    return DeviceChecker(
        model,
        invariants=invariants,
        sub_batch=GEOM["sub_batch"],
        visited_cap=GEOM["visited_cap"],
        frontier_cap=GEOM["frontier_cap"],
        max_states=GEOM["max_states"],
    ).run()


@pytest.fixture(scope="module")
def solo_compaction():
    """Solo baseline for the small compaction binding (computed once;
    oracle-pinned here so every parity consumer inherits the pin)."""
    want = pe.check(SMALL_CONFIGS["producer_on"], invariants=())
    solo = _solo(CompactionModel(SMALL_CONFIGS["producer_on"]), ())
    assert solo.distinct_states == want.distinct_states == 1654
    assert solo.diameter == want.diameter == 16
    return solo


@pytest.fixture(scope="module")
def solo_bk_crash2():
    """Solo baseline for the bookkeeper violation binding (pinned
    9-state ConfirmedEntryReadable counterexample)."""
    solo = _solo(
        BookkeeperModel(BookkeeperConstants(max_bookie_crashes=2)),
        ("ConfirmedEntryReadable",),
    )
    assert solo.violation == "ConfirmedEntryReadable"
    assert len(solo.trace) == 9
    return solo


def assert_result_matches_solo(job, solo):
    """State-for-state job-vs-solo equality: counts, per-level sizes,
    verdict, violation gid, and the full rendered trace."""
    r = job.result
    assert r is not None, (job.state, job.error)
    assert r["distinct_states"] == solo.distinct_states
    assert r["diameter"] == solo.diameter
    assert r["level_sizes"] == [int(x) for x in solo.level_sizes]
    assert r["violation"] == solo.violation
    assert r["violation_gid"] == solo.violation_gid
    assert r["deadlock"] == bool(solo.deadlock)
    if solo.trace is None:
        assert r["trace"] is None
    else:
        assert r["trace"] == [repr(s) for s in solo.trace]
        assert r["trace_actions"] == list(solo.trace_actions)


# ---- the 2-job time-slicing smoke (tier-1 acceptance) ---------------


@pytest.fixture(scope="module")
def two_job_run(tmp_path_factory, pool, cfg_dir):
    """ONE shared 2-job time-sliced run (both queued before the loop
    starts, so every slice expiry sees another waiter and the run
    genuinely interleaves) — the parity test and the telemetry test
    both read it."""
    from pulsar_tlaplus_tpu.obs.telemetry import Telemetry

    state = tmp_path_factory.mktemp("two-job")
    config = _config(state / "state", slice_s=0.3)
    svc_stream = str(state / "service.jsonl")
    tel = Telemetry(svc_stream)
    sched = Scheduler(config, pool=pool, telemetry=tel)
    j1 = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[],
    )
    j2 = sched.submit("bookkeeper", str(cfg_dir / "bk_crash2.cfg"))
    sched.run_until_idle()
    tel.close()
    return config, j1, j2, svc_stream


def test_two_jobs_time_slice_one_device_with_solo_parity(
    two_job_run, solo_compaction, solo_bk_crash2
):
    """Two concurrent queued jobs share one device via suspend/resume
    at checkpoint-frame boundaries; both finish with results equal to
    their solo runs — one clean pass, one invariant violation with a
    replayed counterexample trace."""
    config, j1, j2, _stream = two_job_run
    assert j1.state == j2.state == jobmod.DONE
    # time-slicing actually happened: each job was suspended at a
    # frame boundary at least once and resumed in a later slice
    assert j1.suspends >= 1 and j2.suspends >= 1
    assert j1.slices == j1.suspends + 1
    assert len(j1.run_ids) == j1.slices  # one engine run_id per slice
    assert len(set(j1.run_ids) & set(j2.run_ids)) == 0

    assert_result_matches_solo(j1, solo_compaction)
    assert j1.result["status"] == "ok"
    assert_result_matches_solo(j2, solo_bk_crash2)
    assert j2.result["status"] == "violation"

    # durable artifacts: per-job result.json matches, the terminal
    # frame is gone, the queue snapshot marks both done
    for j in (j1, j2):
        assert json.load(open(j.result_path)) == j.result
        assert not os.path.exists(j.frame_path)
    snap = json.load(open(config.queue_path))
    assert {d["state"] for d in snap["jobs"]} == {jobmod.DONE}


def test_per_job_streams_and_daemon_events_validate(
    two_job_run, checker_mod
):
    """Per-job telemetry isolation: each job's events.jsonl carries
    only that job's slice run_ids, chains resume frames, and passes
    the v4 validator; the scheduler's own stream carries the job_*
    lifecycle in order."""
    _config_, j1, j2, svc_stream = two_job_run
    for j in (j1, j2):
        assert checker_mod.validate_stream(j.events_path) == []
        evs = [json.loads(x) for x in open(j.events_path)]
        rids = [e["run_id"] for e in evs if e["event"] == "run_header"]
        assert rids == j.run_ids  # one header per slice, this job only
        resumed = [
            e for e in evs
            if e["event"] == "run_header" and e.get("resume")
        ]
        assert len(resumed) == j.suspends
    assert checker_mod.validate_stream(svc_stream) == []
    rows = report.job_table(
        [json.loads(x) for x in open(svc_stream)]
    )
    by_id = {r["job_id"]: r for r in rows}
    assert by_id[j1.job_id]["status"] == "ok"
    assert by_id[j1.job_id]["slices"] == j1.slices
    assert by_id[j1.job_id]["suspends"] == j1.suspends
    assert by_id[j2.job_id]["status"] == "violation"
    assert "| job | spec |" in report.render_job_table(
        [json.loads(x) for x in open(svc_stream)]
    )


# ---- shutdown mid-job -> recover (the SIGTERM contract) -------------


def test_shutdown_mid_job_then_recover_same_results(
    tmp_path, pool, cfg_dir, solo_compaction, solo_bk_crash2
):
    """Stop the scheduler while a job is mid-run (the code path the
    SIGTERM handler arms): the running job suspends at its next frame
    boundary, the queue persists, and a recovered scheduler completes
    BOTH jobs with solo-run results."""
    config = _config(tmp_path / "state", slice_s=30.0)
    sched = Scheduler(config, pool=pool)
    j1 = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[],
    )
    j2 = sched.submit("bookkeeper", str(cfg_dir / "bk_crash2.cfg"))
    sched.start()
    deadline = time.monotonic() + 120.0
    while j1.state == jobmod.QUEUED:
        assert time.monotonic() < deadline, "job never started"
        time.sleep(0.02)
    sched.stop(timeout=120.0)  # what the daemon's SIGTERM path calls
    assert j1.state in (jobmod.SUSPENDED, jobmod.QUEUED, jobmod.DONE)
    assert j2.state == jobmod.QUEUED
    if j1.state == jobmod.SUSPENDED:
        assert os.path.exists(j1.frame_path)  # resumable frame on disk

    # "serve --recover": a fresh scheduler over the same state dir
    sched2 = Scheduler(config, pool=pool)
    n = sched2.recover()
    assert n >= 1
    r1, r2 = sched2.get(j1.job_id), sched2.get(j2.job_id)
    sched2.run_until_idle()
    assert r1.state == r2.state == jobmod.DONE
    assert_result_matches_solo(r1, solo_compaction)
    assert_result_matches_solo(r2, solo_bk_crash2)


def test_recover_edge_cases(tmp_path, pool):
    config = _config(tmp_path / "state")
    sched = Scheduler(config, pool=pool)
    assert sched.recover() == 0  # no queue.json: fresh daemon
    os.makedirs(config.state_dir, exist_ok=True)
    with open(config.queue_path, "w") as f:
        f.write("{not json")
    # r17 torn-queue recovery: a corrupt queue.json is QUARANTINED
    # and the queue rebuilt from the job dirs (none here) — never a
    # crash (tests/test_robustness_service.py drills the full path)
    assert Scheduler(config, pool=pool).recover() == 0
    assert not os.path.exists(config.queue_path) or json.load(
        open(config.queue_path)
    )["jobs"] == []
    assert [
        f for f in os.listdir(config.state_dir)
        if f.startswith("queue.json.corrupt.")
    ]


def test_recover_resumes_first_slice_frame(
    tmp_path, pool, cfg_dir, solo_compaction
):
    """A daemon killed mid-FIRST-slice last persisted the job as it
    was claimed (slices=0, running) while its engine had already
    written a frame; recovery must RESUME that frame — a slice-count
    guard must never throw the progress away."""
    config = _config(tmp_path / "state", slice_s=0.0)
    sched = Scheduler(config, pool=pool)
    j1 = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[],
    )
    sched.submit("bookkeeper", BK_CFG)  # waiter -> j1's slice expires
    job = sched._claim()
    assert job is j1
    sched._run_slice(job)
    assert j1.state == jobmod.SUSPENDED
    assert os.path.exists(j1.frame_path)
    assert j1.progress["distinct_states"] > 0
    # forge the crash shape: the last snapshot to reach disk was
    # _claim()'s (slices=0, running), THEN the frame landed
    with sched.cv:
        j1.state = jobmod.RUNNING
        j1.slices = 0
        sched.fifo.remove(j1.job_id)
        sched._running_id = j1.job_id
    sched.persist()

    sched2 = Scheduler(config, pool=pool)
    assert sched2.recover() == 2
    r1 = sched2.get(j1.job_id)
    assert r1.state == jobmod.SUSPENDED  # frame on disk -> resumable
    sched2.run_until_idle()
    assert r1.state == jobmod.DONE
    assert_result_matches_solo(r1, solo_compaction)
    # the frame was USED: a later slice's engine run resumed it
    evs = [json.loads(x) for x in open(r1.events_path)]
    assert any(
        e.get("event") == "run_header" and e.get("resume")
        for e in evs
    )


def test_terminal_retention_prune(tmp_path, pool):
    """``keep_terminal`` bounds the resident job table: the oldest
    terminal records — and their jobs/<id>/ dirs — are pruned on every
    persist, so a long-lived daemon does not grow per-submit forever."""
    config = _config(tmp_path / "state", keep_terminal=2)
    sched = Scheduler(config, pool=pool)
    jids = []
    for _ in range(5):
        j = sched.submit("bookkeeper", BK_CFG)
        sched.cancel(j.job_id)  # cheap terminal transition
        jids.append(j.job_id)
    assert [jid for jid in jids if jid in sched.jobs] == jids[-2:]
    for jid in jids[:3]:
        assert not os.path.exists(os.path.join(config.jobs_dir, jid))
    with open(config.queue_path) as f:
        snap = json.load(f)
    assert {d["job_id"] for d in snap["jobs"]} == set(jids[-2:])


def test_state_dir_single_instance_lock(tmp_path, pool):
    """A second daemon on the same state dir must fail fast — not
    unlink the live daemon's socket and split-brain queue.json."""
    config = _config(tmp_path / "state")
    d1 = ServiceDaemon(config, pool=pool)
    try:
        with pytest.raises(RuntimeError, match="already serves"):
            ServiceDaemon(config, pool=pool)
    finally:
        d1.shutdown()
    d2 = ServiceDaemon(config, pool=pool)  # flock died with the fd
    d2.shutdown()


def test_client_transport_failure_exits_2(tmp_path):
    """Daemon-down is exit 2 (no verdict) — NEVER 1, which the exit
    contract reserves for a confirmed violation/deadlock (a CI lane
    must not report a spec bug because the daemon was down)."""
    from pulsar_tlaplus_tpu import cli

    with pytest.raises(SystemExit) as ei:
        cli.main([
            "submit", "bookkeeper", BK_CFG,
            "--socket", str(tmp_path / "no_daemon.sock"),
        ])
    assert ei.value.code == 2


# ---- cancel + budget ------------------------------------------------


def test_cancel_queued_running_and_time_budget(
    tmp_path, pool, cfg_dir
):
    config = _config(tmp_path / "state", slice_s=30.0)
    sched = Scheduler(config, pool=pool)
    # a queued job cancels immediately (never touches the device)
    jq = sched.submit("bookkeeper", BK_CFG)
    assert sched.cancel(jq.job_id).state == jobmod.CANCELLED
    assert sched.cancel(jq.job_id).state == jobmod.CANCELLED  # idempotent
    # an exhausted time budget truncates honestly (no verdict claimed)
    jb = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], time_budget_s=1e-6,
    )
    # a running job exits via the suspend hook's "cancelled" answer
    jr = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[],
    )
    sched.start()
    deadline = time.monotonic() + 120.0
    while jr.state in (jobmod.QUEUED,) or jb.state == jobmod.QUEUED:
        assert time.monotonic() < deadline
        time.sleep(0.02)
    sched.cancel(jr.job_id)
    sched.wait(jr.job_id, timeout=120.0)
    sched.stop(timeout=120.0)
    assert jr.state == jobmod.CANCELLED
    assert not os.path.exists(jr.frame_path)  # no dead-weight frame
    assert jb.result["status"] == "truncated"
    assert jb.result["stop_reason"] == "time_budget"
    # a bad submit fails eagerly, not in the queue
    with pytest.raises(ValueError, match="not in the compiled registry"):
        sched.submit("no_such_spec", BK_CFG)
    with pytest.raises(ValueError, match="unknown invariant"):
        sched.submit("bookkeeper", BK_CFG, invariants=["Nope"])
    with pytest.raises(ValueError, match="service ceiling"):
        sched.submit("bookkeeper", BK_CFG, max_states=1 << 40)


# ---- warm-start: zero jit compiles ----------------------------------


def test_warm_submit_pays_zero_jit_compiles(tmp_path):
    """The resident-daemon payoff: after ``prewarm`` (capacity-tier
    warmup, r10), a submit against the warmed key adds ZERO jitted
    programs — the same ``set(ck._jits)`` harness as
    test_compact.py's prewarm proofs."""
    config = _config(
        tmp_path / "state",
        visited_cap=1 << 8, frontier_cap=1 << 7, max_states=1 << 12,
    )
    own_pool = CheckerPool(config)
    key, _compile_s = own_pool.warm("bookkeeper", BK_CFG)
    ck = own_pool._checkers[key]
    assert ck._jits  # genuinely warmed
    key2, compile_s2 = own_pool.warm("bookkeeper", BK_CFG)
    assert key2 == key and compile_s2 == 0.0  # idempotent
    keys_before = set(ck._jits)

    sched = Scheduler(config, pool=own_pool)
    job = sched.submit("bookkeeper", BK_CFG)
    sched.run_until_idle()
    assert job.state == jobmod.DONE
    assert job.result["status"] == "ok"
    assert job.result["distinct_states"] == 297  # pinned oracle
    assert set(ck._jits) == keys_before  # ZERO post-warm compiles


# ---- the wire protocol + daemon -------------------------------------


def test_daemon_protocol_roundtrip(tmp_path, pool, cfg_dir):
    """Socket-level lifecycle: ping, submit, status, watch (streamed
    per-slice engine telemetry relayed under the job's run_ids),
    result, error paths, shutdown op, socket cleanup."""
    config = _config(tmp_path / "state", slice_s=0.2)
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        cl = ServiceClient(config.socket_path, timeout=120.0)
        pong = cl.ping()
        assert pong["pid"] == os.getpid() and pong["jobs"] == {}

        with pytest.raises(ServiceError, match="not in the compiled"):
            cl.submit("no_such_spec", BK_CFG)
        with pytest.raises(ServiceError, match="unknown job"):
            cl.status("nope")

        jid1 = cl.submit(
            "compaction", str(cfg_dir / "small_compaction.cfg"),
            invariants=[],
        )
        jid2 = cl.submit("bookkeeper", str(cfg_dir / "bk_crash2.cfg"))
        seen_events = []
        done = None
        for msg in cl.watch(jid2, timeout_s=240.0):
            if "event" in msg:
                seen_events.append(msg["event"])
            elif "done" in msg:
                done = msg["done"]
        assert done is not None and done["state"] == jobmod.DONE
        assert done["result"]["status"] == "violation"
        kinds = {e["event"] for e in seen_events}
        assert "run_header" in kinds  # engine telemetry relayed
        assert {e["run_id"] for e in seen_events} == set(
            done["run_ids"]
        )
        r1 = cl.wait(jid1, timeout=240.0)
        assert r1["state"] == jobmod.DONE
        assert r1["result"]["status"] == "ok"
        assert r1["result"]["distinct_states"] == 1654

        jobs = cl.status()
        assert {j["job_id"] for j in jobs} == {jid1, jid2}
        assert {j["state"] for j in jobs} == {jobmod.DONE}
        one = cl.status(jid1)
        assert one["distinct_states"] == 1654

        # cancel on a terminal job is a no-op answer, not an error
        assert cl.cancel(jid1) == jobmod.DONE

        assert cl.shutdown()["stopping"] is True
    finally:
        daemon.shutdown()
    assert not os.path.exists(config.socket_path)  # socket removed
    # daemon stream: serve start/stop + full job lifecycle, v4-clean
    evs = [json.loads(x) for x in open(config.telemetry_path)]
    assert [
        e["action"] for e in evs if e["event"] == "serve"
    ] == ["start", "stop"]
    assert {
        e["event"] for e in evs if e["event"].startswith("job_")
    } >= {"job_submit", "job_start", "job_result"}


def test_protocol_rejects_garbage(tmp_path, pool):
    import socket as socketmod

    from pulsar_tlaplus_tpu.service import protocol

    config = _config(tmp_path / "state")
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        resp = protocol.request(config.socket_path, "frobnicate")
        assert not resp["ok"] and "unknown op" in resp["error"]

        s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
        s.connect(config.socket_path)
        s.sendall(b"this is not json\n")
        r = s.makefile("r")
        assert not json.loads(r.readline())["ok"]
        s.close()
    finally:
        daemon.shutdown()


# ---- v4 schema: interleaved run_ids + per-run seq monotonicity ------


def test_validator_accepts_interleaved_runs_rejects_torn_seq(
    tmp_path, checker_mod
):
    def rec(run_id, seq, t, event="progress", **kw):
        base = {
            "v": 4, "event": event, "t": t, "run_id": run_id,
            "seq": seq, "distinct_states": 1, "level": 1,
            "states_per_sec": 1.0,
        }
        base.update(kw)
        return base

    good = tmp_path / "interleaved.jsonl"
    good.write_text(
        "\n".join(
            json.dumps(r)
            for r in [
                rec("run-a", 0, 0.1),
                rec("run-b", 0, 0.2),  # interleaved run_ids: legal
                rec("run-a", 1, 0.3),
                rec("run-b", 1, 0.4),
                rec("run-a", 2, 0.5),
            ]
        )
        + "\n"
    )
    assert checker_mod.validate_stream(str(good)) == []

    torn = tmp_path / "torn.jsonl"
    torn.write_text(
        "\n".join(
            json.dumps(r)
            for r in [
                rec("run-a", 0, 0.1),
                rec("run-b", 7, 0.2),
                rec("run-a", 1, 0.3),
                rec("run-a", 1, 0.4),  # duplicated seq within run-a
                rec("run-b", 8, 0.5),
            ]
        )
        + "\n"
    )
    errs = checker_mod.validate_stream(str(torn))
    assert len(errs) == 1 and "seq not increasing" in errs[0]

    noseq = tmp_path / "noseq.jsonl"
    rec_noseq = rec("run-a", 0, 0.1)
    del rec_noseq["seq"]  # seq is a BASE envelope field
    rec_badseq = rec("run-a", "7", 0.2)  # present but not an int
    noseq.write_text(
        json.dumps(rec_noseq) + "\n" + json.dumps(rec_badseq) + "\n"
    )
    errs = checker_mod.validate_stream(str(noseq))
    assert any("missing base fields" in e for e in errs)
    assert any("non-integer seq" in e for e in errs)

    # v4 job events: required fields enforced at v4, not before
    misstream = tmp_path / "jobs.jsonl"
    ok_job = {
        "v": 4, "event": "job_submit", "t": 0.1, "run_id": "d", "seq": 0,
        "job_id": "j1", "spec": "compaction",
    }
    bad_job = {
        "v": 4, "event": "job_result", "t": 0.2, "run_id": "d", "seq": 1,
        "job_id": "j1",  # missing "status"
    }
    old_style = {
        "v": 3, "event": "job_result", "t": 0.3, "run_id": "e", "seq": 0,
        "job_id": "j1",  # pre-v4 record: job fields not yet required
    }
    misstream.write_text(
        "\n".join(json.dumps(r) for r in (ok_job, bad_job, old_style))
        + "\n"
    )
    errs = checker_mod.validate_stream(str(misstream))
    assert len(errs) == 1 and "status" in errs[0]


# ---- the AOT cache cap (satellite) ----------------------------------


class TestAotCacheCap:
    @pytest.fixture(autouse=True)
    def _isolated_cache(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTT_AOT_DIR", str(tmp_path / "aot"))
        monkeypatch.delenv("PTT_AOT_MAX_BYTES", raising=False)
        self.dir = str(tmp_path / "aot")
        os.makedirs(self.dir)

    def _seed(self, n=4, size=1000):
        from pulsar_tlaplus_tpu.utils import aot_cache

        for i in range(n):
            p = os.path.join(self.dir, f"e{i}.aotx")
            with open(p, "wb") as f:
                f.write(b"x" * size)
            os.utime(p, (1000.0 + i, 1000.0 + i))  # e0 oldest
        return aot_cache

    def test_stats_and_clear(self):
        aot_cache = self._seed(3)
        st = aot_cache.stats()
        assert st["entries"] == 3 and st["bytes"] == 3000
        assert st["dir"] == self.dir
        n, b = aot_cache.clear()
        assert (n, b) == (3, 3000)
        assert aot_cache.stats()["entries"] == 0

    def test_lru_evicts_oldest_mtime_first(self):
        aot_cache = self._seed(4)
        n, b = aot_cache.enforce_cap(2500)
        assert (n, b) == (2, 2000)  # two oldest gone
        left = sorted(os.listdir(self.dir))
        assert left == ["e2.aotx", "e3.aotx"]
        assert aot_cache.enforce_cap(2500) == (0, 0)  # already fits

    def test_cap_zero_disables_and_env_overrides(self, monkeypatch):
        aot_cache = self._seed(4)
        assert aot_cache.enforce_cap(0) == (0, 0)
        monkeypatch.setenv("PTT_AOT_MAX_BYTES", "1500")
        assert aot_cache.max_bytes() == 1500
        n, _b = aot_cache.enforce_cap()  # default = env cap
        assert n == 3 and os.listdir(self.dir) == ["e3.aotx"]
        monkeypatch.setenv("PTT_AOT_MAX_BYTES", "not-a-number")
        assert aot_cache.max_bytes() == aot_cache.DEFAULT_MAX_BYTES

    def test_cli_cache_inspector(self, capsys):
        from pulsar_tlaplus_tpu import cli

        self._seed(2)
        assert cli.main(["cache", "--stats"]) == 0
        out = capsys.readouterr().out
        assert "2 entrie(s)" in out
        assert cli.main(["cache", "--evict-to", "1500"]) == 0
        out = capsys.readouterr().out
        assert "evicted 1 entrie(s)" in out
        assert cli.main(["cache", "--clear"]) == 0
        out = capsys.readouterr().out
        assert "cleared 1 entrie(s)" in out
        from pulsar_tlaplus_tpu.utils import aot_cache

        assert aot_cache.stats()["entries"] == 0


# ---- bench stale-stream hygiene (satellite) -------------------------


def test_bench_cleans_stale_telemetry_streams(tmp_path):
    import subprocess
    import sys

    sys.path.insert(0, ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    # a genuinely dead pid (reaped child), our own pid, and noise
    child = subprocess.Popen(["true"])
    child.wait()
    dead = tmp_path / f"bench_telemetry_{child.pid}.jsonl"
    live = tmp_path / f"bench_telemetry_{os.getpid()}.jsonl"
    other = tmp_path / "not_a_bench_stream.jsonl"
    for p in (dead, live, other):
        p.write_text("{}\n")
    assert bench.cleanup_stale_streams(str(tmp_path)) == 1
    assert not dead.exists()
    assert live.exists() and other.exists()
    assert bench.cleanup_stale_streams(str(tmp_path / "missing")) == 0

    args = bench.parse_args(["--telemetry-path", str(tmp_path)])
    assert args.telemetry_path == str(tmp_path)
    assert args.telemetry == bench._DEFAULT_TELEMETRY  # resolved in main


# ---- load test: many jobs, mixed specs, real SIGTERM (slow) ---------


@pytest.mark.slow
def test_load_many_jobs_mixed_specs(tmp_path, pool, cfg_dir):
    """>= 2-job load: six queued jobs across three bindings time-slice
    one device; every result equals its solo baseline."""
    config = _config(tmp_path / "state", slice_s=0.2)
    sched = Scheduler(config, pool=pool)
    jobs = []
    for i in range(2):
        jobs.append(
            (
                sched.submit(
                    "compaction",
                    str(cfg_dir / "small_compaction.cfg"),
                    invariants=[],
                ),
                "compaction",
            )
        )
        jobs.append((sched.submit("bookkeeper", BK_CFG), "bk"))
        jobs.append(
            (
                sched.submit(
                    "bookkeeper", str(cfg_dir / "bk_crash2.cfg")
                ),
                "bk2",
            )
        )
    sched.run_until_idle()
    solos = {
        "compaction": _solo(
            CompactionModel(SMALL_CONFIGS["producer_on"]), ()
        ),
        "bk": _solo(
            BookkeeperModel(BookkeeperConstants()),
            ("TypeOK", "LacIsConfirmed", "AckImpliesStoredOrCrashed",
             "ConfirmedEntryReadable"),
        ),
        "bk2": _solo(
            BookkeeperModel(BookkeeperConstants(max_bookie_crashes=2)),
            ("ConfirmedEntryReadable",),
        ),
    }
    assert sum(j.suspends for j, _k in jobs) >= 4
    for j, k in jobs:
        assert j.state == jobmod.DONE
        assert_result_matches_solo(j, solos[k])


@pytest.mark.slow
def test_serve_cli_sigterm_recover_subprocess(tmp_path, cfg_dir):
    """The full acceptance drill as real processes: `cli.py serve`,
    client submits over the socket, SIGTERM mid-job, then
    `serve --recover --drain` completes the queue with solo results."""
    import signal
    import subprocess
    import sys

    state = tmp_path / "state"
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(*extra):
        return subprocess.Popen(
            [
                sys.executable, "-m", "pulsar_tlaplus_tpu.cli",
                "serve", str(state), "--no-prewarm", "--slice", "0.2",
                "--maxstates", str(GEOM["max_states"]),
                "--checkpoint-every", "1", "-chunk", "64", *extra,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=ROOT, env=env,
        )

    daemon = spawn()
    try:
        assert "serving on" in daemon.stdout.readline()
        cl = ServiceClient(
            str(state / "serve.sock"), timeout=120.0
        )
        jid1 = cl.submit(
            "compaction", str(cfg_dir / "small_compaction.cfg"),
            invariants=[],
        )
        jid2 = cl.submit("bookkeeper", str(cfg_dir / "bk_crash2.cfg"))
        deadline = time.monotonic() + 180.0
        while cl.status(jid1)["state"] == jobmod.QUEUED:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        daemon.send_signal(signal.SIGTERM)
        assert daemon.wait(timeout=180.0) == 0  # graceful exit
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()

    recov = spawn("--recover", "--drain")
    try:
        assert recov.wait(timeout=600.0) == 0  # drained + stopped
    finally:
        if recov.poll() is None:
            recov.kill()
            recov.wait()

    # results from the job dirs (the daemon is gone)
    snap = json.load(open(state / "queue.json"))
    by_id = {d["job_id"]: d for d in snap["jobs"]}
    assert by_id[jid1]["state"] == by_id[jid2]["state"] == jobmod.DONE
    res1 = json.load(
        open(state / "jobs" / jid1 / "result.json")
    )
    res2 = json.load(
        open(state / "jobs" / jid2 / "result.json")
    )
    assert res1["status"] == "ok"
    assert res1["distinct_states"] == 1654
    assert res2["status"] == "violation"
    assert res2["violation"] == "ConfirmedEntryReadable"
    assert len(res2["trace"]) == 9
