"""Tiered state store (round 16): codec/budget/sieve units, the
tiered-vs-untiered state-for-state differentials on the pinned
compaction oracles, the 45,198-state acceptance run with the hot tier
pinned under 25% of the reachable set, crash/suspend resume through
the spill manifest, schema-v9 validation, and the spill ledger gate."""

import dataclasses
import importlib.util
import json
import os
import shutil
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import ledger, report
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.store import budget as store_budget
from pulsar_tlaplus_tpu.store import compress as codec
from pulsar_tlaplus_tpu.store import sieve as store_sieve
from pulsar_tlaplus_tpu.store.tiers import (
    TieredStore,
    cleanup_stale_spill,
)
from tests.helpers import (
    SMALL_CONFIGS,
    assert_valid_counterexample,
    tight_hbm_budget,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPILL_PINNED = os.path.join(
    ROOT, "tests", "data", "mini_bench_spill_producer_on.jsonl"
)


def _checker_mod():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(ROOT, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk(c, **kw):
    kw.setdefault("invariants", ())
    kw.setdefault("check_deadlock", False)
    kw.setdefault("sub_batch", 64)
    kw.setdefault("visited_cap", 1 << 9)
    kw.setdefault("frontier_cap", 1 << 9)
    return DeviceChecker(CompactionModel(c), **kw)


def _tight_budget(c, slack=4096, **kw):
    """A budget just above the engine's initial-tier minimum — tiers
    pinned at their smallest, so the run MUST spill (the shared
    helpers.tight_hbm_budget recipe at this file's shapes)."""
    return tight_hbm_budget(
        lambda b: _mk(c, hbm_budget=b, **kw), slack=slack
    )


def _merged_logs(ck, nv):
    """(parent, lane) over [0, nv) — cold segments + device window."""
    base = ck._last_rb["row_base"]
    cp, cl = ck.tstore.fetch_logs(0, base)
    par = np.concatenate(
        [cp, np.asarray(ck.last_bufs["parent"][: nv - base])]
    )
    lan = np.concatenate(
        [cl, np.asarray(ck.last_bufs["lane"][: nv - base])]
    )
    return par, lan


def _merged_rows(ck, nv):
    base = ck._last_rb["row_base"]
    W = ck.W
    cold = ck.tstore.fetch_rows(0, base, W)
    return np.concatenate(
        [cold, np.asarray(ck.last_bufs["rows"][: (nv - base) * W])]
    )


# ---------------------------------------------------- budget / codec


def test_parse_budget():
    assert store_budget.parse_budget("512M") == 512 << 20
    assert store_budget.parse_budget("7.5G") == int(7.5 * (1 << 30))
    assert store_budget.parse_budget("65536") == 65536
    assert store_budget.parse_budget(1 << 20) == 1 << 20
    for bad in ("", "12X", "-1", 0, "0M"):
        with pytest.raises(ValueError):
            store_budget.parse_budget(bad)


def test_resolve_budget_env(monkeypatch):
    monkeypatch.delenv(store_budget.ENV_VAR, raising=False)
    assert store_budget.resolve_budget(None) is None
    monkeypatch.setenv(store_budget.ENV_VAR, "2M")
    assert store_budget.resolve_budget(None) == 2 << 20
    assert store_budget.resolve_budget("1M") == 1 << 20  # explicit wins


@pytest.mark.parametrize("compress", [True, False])
def test_key_run_codec_roundtrip(compress):
    rng = np.random.default_rng(7)
    hi = np.sort(rng.integers(0, 1 << 60, 5000).astype(np.uint64))
    lo = rng.integers(0, 1 << 32, 5000).astype(np.uint32)
    blob, raw, comp = codec.encode_key_run(hi, lo, compress=compress)
    assert raw == hi.nbytes + lo.nbytes
    if compress:
        assert comp < raw  # sorted deltas must actually compress
    hi2, lo2 = codec.decode_key_run(blob)
    assert (hi2 == hi).all() and (lo2 == lo).all()
    # empty run round-trips too
    b2, _, _ = codec.encode_key_run(
        np.zeros(0, np.uint64), np.zeros(0, np.uint32)
    )
    h, l = codec.decode_key_run(b2)
    assert len(h) == 0 and len(l) == 0


def test_plane_codec_roundtrip_and_magic():
    arr = np.arange(1000, dtype=np.int32) - 500
    blob, raw, comp = codec.encode_plane(arr)
    assert (codec.decode_plane(blob) == arr).all()
    with pytest.raises(ValueError, match="magic"):
        codec.decode_plane(b"XXXX" + blob[4:])
    with pytest.raises(ValueError, match="magic"):
        codec.decode_key_run(blob)  # wrong blob kind


def test_pack_keys_order_matches_column_sort():
    rng = np.random.default_rng(3)
    cols = tuple(
        rng.integers(0, 1 << 32, 300).astype(np.uint32)
        for _ in range(2)
    )
    hi, lo = codec.pack_keys(cols)
    order = np.lexsort((lo, hi))
    # unsigned lexicographic column order == (hi, lo) order
    order2 = np.lexsort((cols[1], cols[0]))
    assert (order == order2).all()
    back = codec.unpack_keys(hi, lo, 2)
    assert all((a == b).all() for a, b in zip(back, cols))


# ------------------------------------------------------- TieredStore


def test_store_evict_lookup_and_miss_accounting():
    ts = TieredStore(2)
    rng = np.random.default_rng(11)
    keys = np.unique(rng.integers(0, 1 << 48, 4000).astype(np.uint64))
    c0 = (keys >> np.uint64(32)).astype(np.uint32)
    c1 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    assert ts.evict_keys((c0, c1)) == len(keys)
    assert ts.has_cold_keys and ts.cold_keys == len(keys)
    # members hit, fresh keys miss
    q0 = np.concatenate([c0[:100], c0[:100] ^ np.uint32(0x5A5A5A5A)])
    q1 = np.concatenate([c1[:100], c1[:100]])
    mask = ts.lookup_keys((q0, q1))
    assert mask[:100].all()
    assert not mask[100:].any() or (
        # astronomically unlikely collision with the xor'd keys
        mask[100:].sum() == 0
    )
    assert ts.stats.misses_resolved == 200
    ts.flush()
    assert ts.stats.bytes_comp > 0
    ts.close()


def test_store_rows_logs_gather_and_gap_detection():
    ts = TieredStore(2)
    W = 3
    ts.spill_rows(0, 10, np.arange(30, dtype=np.uint32))
    ts.spill_rows(10, 25, np.arange(30, 75, dtype=np.uint32))
    got = ts.fetch_rows(5, 20, W)
    assert (got == np.arange(15, 60, dtype=np.uint32)).all()
    assert ts.rows_spilled_hi == 25
    ts.spill_logs(0, 4, np.arange(4), np.arange(4) * 2)
    par, lan = ts.fetch_logs(1, 3)
    assert (par == [1, 2]).all() and (lan == [2, 4]).all()
    with pytest.raises(ValueError, match="gap"):
        ts.fetch_rows(20, 40, W)
    with pytest.raises(ValueError, match="gap"):
        ts.fetch_logs(2, 9)
    ts.close()


def test_store_manifest_restore_and_digest_tamper(tmp_path):
    sdir = str(tmp_path / "spill")
    ts = TieredStore(2, spill_dir=sdir, durable=True)
    c0 = np.sort(np.arange(100, dtype=np.uint32) * 7)
    c1 = np.arange(100, dtype=np.uint32)
    ts.evict_keys((c0, c1))
    ts.spill_rows(0, 8, np.arange(16, dtype=np.uint32))
    ts.spill_logs(0, 8, np.arange(8), np.arange(8))
    man = ts.manifest()
    ts.close()
    # restore in a fresh store: identical lookups and gathers
    ts2 = TieredStore(2, spill_dir=sdir, durable=True)
    ts2.restore(man)
    assert ts2.cold_keys == 100
    assert ts2.lookup_keys((c0[:5], c1[:5])).all()
    assert (ts2.fetch_rows(0, 8, 2) == np.arange(16)).all()
    # cumulative stats continue (the monotone telemetry contract)
    assert ts2.stats.keys_evicted == 100
    ts2.close()
    # a tampered spill file must fail the digest check loudly
    victim = os.path.join(sdir, man["key_runs"][0]["file"])
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    ts3 = TieredStore(2, spill_dir=sdir, durable=True)
    with pytest.raises(ValueError, match="digest mismatch"):
        ts3.restore(man)
    ts3.close()


def test_store_wipe_and_stale_tmp_hygiene(tmp_path):
    sdir = str(tmp_path / "spill")
    os.makedirs(sdir)
    # a crashed writer's temp and a dead run's spill files
    open(os.path.join(sdir, f"keys_1.ptsk.tmp.{os.getpid()}.1"), "w").close()
    open(os.path.join(sdir, "keys_9.ptsk"), "w").close()
    assert cleanup_stale_spill(sdir) == 1
    assert os.path.exists(os.path.join(sdir, "keys_9.ptsk"))
    ts = TieredStore(2, spill_dir=sdir, durable=True)
    ts.wipe()  # a FRESH run owns the dir: dead files must not leak
    assert os.listdir(sdir) == []
    ts.close()


# -------------------------------------------------- sieve device ops


def test_sieve_tag_evict_unflag_roundtrip():
    from pulsar_tlaplus_tpu.ops import fpset as fps
    from pulsar_tlaplus_tpu.ops.dedup import SENTINEL

    cap = 64
    tc = fps.empty_cols(cap, 2)
    keys = (
        jnp.asarray(np.arange(10, dtype=np.uint32) + 1),
        jnp.asarray(np.arange(10, dtype=np.uint32) * 3 + 1),
    )
    is_new, tc, nf, _ = fps.lookup_or_insert(
        tc, keys, jnp.ones((10,), bool)
    )
    assert int(nf) == 0 and bool(np.asarray(is_new).all())
    gen = jnp.zeros((cap + 1,), jnp.int32)
    gen = store_sieve.tag_generation(tc, gen, 1)
    assert int(np.asarray(gen).sum()) == 10  # 10 slots tagged epoch 1
    # second insert wave tags epoch 2
    keys2 = (
        jnp.asarray(np.arange(5, dtype=np.uint32) + 100),
        jnp.asarray(np.arange(5, dtype=np.uint32) + 200),
    )
    _, tc, nf2, _ = fps.lookup_or_insert(
        tc, keys2, jnp.ones((5,), bool)
    )
    assert int(nf2) == 0
    gen = store_sieve.tag_generation(tc, gen, 2)
    holed, gen2, ev, n_ev = store_sieve.extract_cold(tc, gen, 1)
    assert int(n_ev) == 10
    ev_np = [np.asarray(c[:10]) for c in ev]
    # sorted + exactly the epoch-1 keys
    hi, lo = codec.pack_keys(ev_np)
    assert (np.diff(hi.astype(np.int64)) >= 0).all()
    want_hi, _ = codec.pack_keys([np.asarray(k) for k in keys])
    assert set(hi.tolist()) == set(want_hi.tolist())
    # cleared slots: only the 5 epoch-2 keys remain occupied
    occ = ~np.asarray(fps.all_sentinel(holed))[:-1]
    assert occ.sum() == 5
    # unflag merges verdicts back
    flag = jnp.ones((16,), jnp.uint32)
    out = store_sieve.unflag_lanes(
        flag, jnp.asarray([3, 7, 0, 0], jnp.int32), jnp.int32(2)
    )
    out = np.asarray(out)
    assert out[3] == 0 and out[7] == 0 and out.sum() == 14
    # sieve_new packs exactly the flagged lanes with original ids
    ak = tuple(
        jnp.asarray(np.arange(16, dtype=np.uint32) + 10 * (i + 1))
        for i in range(2)
    )
    flags = np.zeros((16,), np.uint32)
    flags[[2, 5, 11]] = 1
    out = store_sieve.sieve_new(ak, jnp.asarray(flags))
    n = int(out[-1])
    assert n == 3
    lanes = np.asarray(out[-2][:n])
    assert (lanes == [2, 5, 11]).all()
    assert (np.asarray(out[0][:n]) == np.asarray(ak[0])[[2, 5, 11]]).all()


# --------------------------- tiered-vs-untiered exactness (the hinge)


@pytest.mark.parametrize(
    "name",
    [
        "producer_on",
        # the second config exercises the same machinery at deeper
        # duplicate rates; slow-marked for the tier-1 time budget
        # (producer_on + the subscription spill-parity differential
        # keep two specs' worth of coverage in tier-1)
        pytest.param("two_crashes", marks=pytest.mark.slow),
    ],
)
def test_tiered_vs_untiered_state_for_state(name):
    """Same states in the same order under a budget that forces key
    eviction, row/log spill, and cold-miss resolution: level sizes,
    packed rows, and parent/lane logs bit-identical (rows/logs via
    the merged cold+device view)."""
    c = SMALL_CONFIGS[name]
    ck_u = _mk(c)
    r_u = ck_u.run()
    ck_t = _mk(c, hbm_budget=_tight_budget(c))
    r_t = ck_t.run()
    assert r_t.distinct_states == r_u.distinct_states
    assert r_t.level_sizes == r_u.level_sizes
    st = ck_t.last_stats
    assert st["spill_evictions"] >= 1, "budget never forced an eviction"
    assert st["spill_rows_evicted"] > 0
    assert st["spill_misses_resolved"] > 0
    nv = r_u.distinct_states
    pu = np.asarray(ck_u.last_bufs["parent"][:nv])
    lu = np.asarray(ck_u.last_bufs["lane"][:nv])
    pt, lt = _merged_logs(ck_t, nv)
    assert (pu == pt).all() and (lu == lt).all()
    ru = np.asarray(ck_u.last_bufs["rows"][: nv * ck_u.W])
    assert (_merged_rows(ck_t, nv) == ru).all()


# the untiered device engine's deterministic verdicts at these exact
# shapes (sub_batch 512, visited_cap 2^11) — re-derivable with
# _mk(pe.SHIPPED_CFG, invariants=(inv,), ...); pinned so the tiered
# oracle test pays 2 runs instead of 4 (the untiered side of this
# differential is already exercised by tests/test_fuse.py)
BUG_ORACLE_PINS = {
    "CompactedLedgerLeak": (23329, 12),
    "DuplicateNullKeyMessage": (3645, 4),
}


@pytest.mark.parametrize(
    "invariant", sorted(BUG_ORACLE_PINS),
)
def test_tiered_bug_oracles_identical(invariant):
    """Both published counterexamples through the tiered store: the
    violation gid, diameter, and state count equal the pinned
    untiered-engine verdicts, and the replayed trace (through the
    merged cold+device logs) validates step-by-step on the Python
    oracle semantics."""
    gid, depth = BUG_ORACLE_PINS[invariant]
    kw = dict(
        invariants=(invariant,), check_deadlock=True,
        sub_batch=512, visited_cap=1 << 11, frontier_cap=1 << 11,
    )
    ck_t = _mk(
        pe.SHIPPED_CFG, hbm_budget=_tight_budget(pe.SHIPPED_CFG, **kw),
        **kw,
    )
    r_t = ck_t.run()
    assert r_t.violation == invariant
    assert r_t.violation_gid == gid
    assert r_t.diameter == depth
    # (distinct_states at a violation stop is dispatch-pipeline-
    # dependent — the tiered group-ahead clamp stops sooner after the
    # find; gid/diameter/trace are the order-exactness pins)
    assert len(r_t.trace) == depth
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r_t.trace, r_t.trace_actions, invariant
    )


def test_tiered_shipped_45k_hot_under_quarter(tmp_path):
    """THE acceptance run: the 45,198-state compaction oracle with the
    hot tier pinned under 25% of the reachable set completes
    untruncated with the pinned count/diameter, a validator-clean v9
    stream, and monotone-cumulative spill records."""
    stream = str(tmp_path / "spill45k.jsonl")
    kw = dict(sub_batch=512, visited_cap=1 << 12, frontier_cap=1 << 12)
    ck = _mk(
        pe.SHIPPED_CFG,
        hbm_budget=_tight_budget(pe.SHIPPED_CFG, slack=65536, **kw),
        telemetry=stream, **kw,
    )
    r = ck.run()
    assert (r.distinct_states, r.diameter) == (45198, 20)
    assert not r.truncated and r.violation is None
    st = ck.last_stats
    assert st["spill_hot_keys"] / r.distinct_states < 0.25
    assert st["spill_keys_evicted"] > 0
    assert st["spill_bytes_comp"] < st["spill_bytes_raw"]
    assert st["spill_bytes_per_state"] > 0
    mod = _checker_mod()
    assert mod.validate_stream(stream) == []
    evs = [json.loads(x) for x in open(stream)]
    spills = [e for e in evs if e["event"] == "spill"]
    assert spills, "tiered run emitted no spill records"
    hdr = next(e for e in evs if e["event"] == "run_header")
    assert hdr["hbm_budget"] == ck.hbm_budget


def test_spill_monotone_validator_negative(tmp_path):
    """A spill record whose cumulative bytes go BACKWARDS fails the
    v9 cross-check."""
    mod = _checker_mod()
    path = str(tmp_path / "bad.jsonl")
    base = dict(
        v=9, run_id="r1", tier="ram", keys_evicted=10,
        rows_evicted=0, transfer_s=0.1, misses_resolved=5,
        event="spill",
    )
    with open(path, "w") as f:
        f.write(json.dumps(dict(
            base, t=0.1, seq=0, bytes_raw=100, bytes_comp=50,
        )) + "\n")
        f.write(json.dumps(dict(
            base, t=0.2, seq=1, bytes_raw=90, bytes_comp=60,
        )) + "\n")
    errs = mod.validate_stream(path)
    assert any("bytes_raw went backwards" in e for e in errs)


# ------------------------------------------- survive + resume drills


def test_tiered_suspend_resume_through_manifest(tmp_path):
    """The daemon's suspend path: a cooperative mid-run suspend writes
    a frame embedding the spill manifest; a fresh checker resumes
    through it to the identical result (the scheduler's exact
    mechanism — suspend_hook + run(resume=True))."""
    c = SMALL_CONFIGS["producer_on"]
    ck_ref = _mk(c)
    r_ref = ck_ref.run()
    frame = str(tmp_path / "job.npz")
    budget = _tight_budget(c)
    polls = {"n": 0}

    def hook():
        polls["n"] += 1
        return "suspended" if polls["n"] >= 4 else None

    ck1 = _mk(
        c, hbm_budget=budget, checkpoint_path=frame,
        checkpoint_every=2, suspend_hook=hook,
    )
    r1 = ck1.run()
    assert r1.truncated and r1.stop_reason == "suspended"
    assert r1.distinct_states < r_ref.distinct_states
    assert os.path.exists(frame)
    # the suspended frame references durable spill files
    ck2 = _mk(
        c, hbm_budget=budget, checkpoint_path=frame,
        checkpoint_every=2,
    )
    r2 = ck2.run(resume=True)
    assert r2.distinct_states == r_ref.distinct_states
    assert r2.level_sizes == r_ref.level_sizes
    assert not r2.truncated
    nv = r_ref.distinct_states
    pu = np.asarray(ck_ref.last_bufs["parent"][:nv])
    pt, _lt = _merged_logs(ck2, nv)
    assert (pu == pt).all()


@pytest.mark.slow
def test_tiered_kill_drill_resumes_to_pinned_result(tmp_path):
    """kill@level mid-way through the tiered 45,198 run (hard exit
    137, only frames + spill files survive), then resume through the
    spill manifest to the exact pinned result — the crash half of the
    acceptance criteria, as a real subprocess.  Slow-marked (the r10/
    r14 precedent for subprocess differentials): the in-process
    suspend/resume test above drills the same manifest-restore path
    in tier-1."""
    frame = str(tmp_path / "drill.npz")
    stream = str(tmp_path / "drill.jsonl")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PTT_FAULT="kill@level:12"
    )
    args = [
        sys.executable, "-m", "tests._survivable_run",
        "--engine", "device", "--checkpoint", frame,
        "--telemetry", stream, "--every", "3",
        "--sub-batch", "512", "--visited-cap", "4096",
        "--hbm-budget", "min+65536",
    ]
    p1 = subprocess.run(
        args, env=env, cwd=ROOT, capture_output=True, text=True,
        timeout=560,
    )
    assert p1.returncode == 137, (p1.returncode, p1.stderr[-800:])
    assert os.path.exists(frame)
    spill_dir = f"{frame}.spill"
    assert os.listdir(spill_dir), "no durable spill files at the kill"
    env2 = dict(os.environ, JAX_PLATFORMS="cpu")
    p2 = subprocess.run(
        args + ["--resume"], env=env2, cwd=ROOT,
        capture_output=True, text=True, timeout=560,
    )
    assert p2.returncode == 0, p2.stderr[-800:]
    out = json.loads(p2.stdout.strip().splitlines()[-1])
    assert out["distinct_states"] == 45198
    assert out["diameter"] == 20
    assert not out["truncated"]
    # the crashed + resumed streams both validate at v9
    mod = _checker_mod()
    assert mod.validate_stream(stream) == []


# the untiered liveness verdict at these exact knobs (re-derivable
# by dropping hbm_budget below): the published consumer_on lasso
LASSO_PREFIX = [0, 1, 6, 30, 86, 162, 270, 394, 522, 678, 834, 995, 1187]


def test_tiered_liveness_lasso_verdict_from_cold_rows():
    """The consumer_on lasso oracle through a tiered inner explorer:
    the sweep streams the aged rows back from the cold tiers and
    reaches the SAME verdict (lasso included) as the pinned untiered
    run — retiring the sweep's rows_window='all' HBM requirement."""
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    cc = dataclasses.replace(
        SMALL_CONFIGS["producer_on"], model_consumer=True
    )
    budget = _tight_budget(
        cc, sub_batch=256, visited_cap=1 << 9, frontier_cap=1 << 9,
    )
    lt = LivenessChecker(
        CompactionModel(cc), hbm_budget=budget, goal="Termination",
        fairness="wf_next", frontier_chunk=256, visited_cap=1 << 9,
        sweep_chunk=1 << 10,
    )
    r_t = lt.run()
    assert not r_t.holds  # the published lasso oracle
    assert "no var-changing successor" in r_t.reason
    assert r_t.distinct_states == 1654
    assert r_t.lasso_prefix == LASSO_PREFIX
    assert r_t.lasso_cycle == [1187]
    inner = lt._checker
    assert inner.last_stats.get("spill_rows_evicted", 0) > 0, (
        "the inner explorer never spilled — the sweep read nothing "
        "from the cold tier"
    )


# ------------------------------------------------ ledger / tuner ties


def test_ledger_gate_spill_keys_pinned_baseline(tmp_path):
    """The spill tier-1 gate: a fresh tiered producer_on run gates
    clean against the committed spill baseline on the deterministic
    keys + spill_bytes_per_state; an injected spill-bytes regression
    fails."""
    from pulsar_tlaplus_tpu import cli

    path = str(tmp_path / "spill_ledger.jsonl")
    shutil.copy(SPILL_PINNED, path)
    assert ledger.validate_ledger(path) == []
    stream = str(tmp_path / "run.jsonl")
    c = SMALL_CONFIGS["producer_on"]
    _mk(c, hbm_budget=_tight_budget(c), telemetry=stream).run()
    assert cli.main(["ledger", "--ledger", path, "add", stream]) == 0
    keys = [
        "dispatches_per_level", "work_units_per_state",
        "spill_bytes_per_state",
    ]
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.1",
         "--keys"] + keys
    )
    assert rc == 0
    cur = ledger.load(path)[-1]
    bad = dict(cur, values=dict(cur["values"]))
    bad["values"]["spill_bytes_per_state"] = (
        cur["values"]["spill_bytes_per_state"] * 2
    )
    bad["digest"] = ledger._digest(bad["values"])
    ledger.append(path, [bad])
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.1",
         "--keys"] + keys
    )
    assert rc == 1
    v = ledger.gate(cur, bad, threshold=0.1, keys=tuple(keys))
    assert {x["key"] for x in v} == {"spill_bytes_per_state"}


def test_tune_space_and_predict_price_spill_knobs():
    from pulsar_tlaplus_tpu.tune import predict as tp
    from pulsar_tlaplus_tpu.tune import space as ts

    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    plain = ts.candidates(m)
    spill = ts.candidates(m, spill=True)
    assert len(spill) > len(plain)
    assert any("miss_batch" in c for c in spill)
    ref = {
        "backend": "cpu", "work": {"expand_rows": 1000},
        "level_sizes": [1, 10, 100], "sub_batch": 64,
        "fuse_group": 8, "flush_factor": 1, "group": 4, "A": 7,
        "dense_rounds": 4, "stages": ((4, 16), (16, 64)),
        "avg_probe_rounds": 1.5, "distinct_states": 111,
        "spill_bytes_raw": 10_000_000, "spill_bytes_comp": 3_000_000,
        "spill_misses_resolved": 50_000, "spill_compress": True,
        "miss_batch": 1 << 15,
    }
    cal = {"units": {}, "rtt_s": 0.001, "link_bytes_per_s": 1e6}
    p_comp = tp.predict_candidate({}, ref, cal)
    p_raw = tp.predict_candidate({"spill_compress": False}, ref, cal)
    # uncompressed candidates cross more bytes -> cost more
    assert p_raw["spill_s"] > p_comp["spill_s"] > 0
    # narrower miss batches pay more resolution syncs
    p_narrow = tp.predict_candidate({"miss_batch": 1 << 10}, ref, cal)
    assert p_narrow["spill_s"] > p_comp["spill_s"]


def test_profile_spill_knobs_validate_and_resolve(
    tmp_path, monkeypatch
):
    from pulsar_tlaplus_tpu.tune import profiles as tprof

    monkeypatch.setenv(tprof.TUNE_DIR_ENV, str(tmp_path))
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    sig = tprof.profile_key(
        model=m, invariants=(), engine="device_bfs", backend="cpu",
        tiered=True,  # spill knobs live under the tiered regime key
    )
    prof = tprof.build(
        sig=sig, engine="device_bfs", backend="cpu",
        knobs={
            "miss_batch": 1 << 14, "spill_compress": False,
            "hbm_headroom": 0.05,
        },
    )
    path = tprof.save(prof)
    assert tprof.validate_file(path) == []
    ck = _mk(
        SMALL_CONFIGS["producer_on"], hbm_budget="4M",
        profile=path,
    )
    assert ck.miss_batch == 1 << 14
    assert ck.spill_compress is False
    assert ck.hbm_headroom == 0.05
    # a hand-broken range fails validation (the resolver then
    # warns-and-ignores instead of crashing a ctor)
    prof["knobs"]["hbm_headroom"] = 2.0
    assert tprof.validate(prof) != []
