"""Differential tests for the spec->kernel compiler (frontend/codegen.py):
the compiled path must reproduce the interpreter's (and oracle's) state
counts, diameters, verdicts, and traces — on the real reference spec
(/root/reference/compaction.tla) WITHOUT the hand-written model, and on
the original specs in specs/."""

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.frontend import interp as I
from pulsar_tlaplus_tpu.frontend.codegen import CompiledSpec
from pulsar_tlaplus_tpu.frontend.loader import compaction_constants
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS

REFERENCE_TLA = "/root/reference/compaction.tla"


@pytest.fixture(scope="module")
def module():
    return parse_file(REFERENCE_TLA)


def _spec(module, c):
    return I.Spec(module, compaction_constants(c))


def _check(spec, invariants=(), **kw):
    cs = CompiledSpec(spec, invariants=invariants)
    kw.setdefault("sub_batch", 256)
    kw.setdefault("visited_cap", 1 << 12)
    kw.setdefault("frontier_cap", 1 << 12)
    return DeviceChecker(cs, **kw).run(), cs


@pytest.mark.parametrize(
    "name", ["producer_on", "two_crashes", "no_retain"]
)
def test_compiled_matches_oracle_small(module, name):
    c = SMALL_CONFIGS[name]
    want = pe.check(c, invariants=())
    got, _cs = _check(_spec(module, c))
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_compiled_shipped_cfg_published_count(module):
    """45,198 distinct states (compaction.tla:23) on the compiled path,
    straight from the reference .tla text — no hand-written model."""
    got, cs = _check(
        _spec(module, pe.SHIPPED_CFG),
        sub_batch=1024, visited_cap=1 << 16, frontier_cap=1 << 14,
    )
    assert got.distinct_states == 45198
    assert got.diameter == 20
    assert got.violation is None and not got.deadlock


def test_compiled_leak_counterexample(module):
    got, cs = _check(
        _spec(module, pe.SHIPPED_CFG),
        invariants=("CompactedLedgerLeak",),
        sub_batch=1024, visited_cap=1 << 16, frontier_cap=1 << 14,
    )
    assert got.violation == "CompactedLedgerLeak"
    assert got.diameter == 12
    assert len(got.trace) == 12
    # rendered trace: every step labeled with a real action
    assert all(isinstance(a, str) and a for a in got.trace_actions)


def test_compiled_duplicate_null_key_counterexample(module):
    got, _cs = _check(
        _spec(module, pe.SHIPPED_CFG),
        invariants=("DuplicateNullKeyMessage",),
        sub_batch=1024, visited_cap=1 << 16, frontier_cap=1 << 14,
    )
    assert got.violation == "DuplicateNullKeyMessage"
    assert got.diameter == 4
    assert len(got.trace) == 4


def test_compiled_lane_order_matches_interpreter(module):
    """Per-state successor sets must match the interpreter exactly
    (in-set equality; lanes are a superset ordering of enabled succs)."""
    c = SMALL_CONFIGS["producer_on"]
    spec = _spec(module, c)
    I.install_defs(spec)
    cs = CompiledSpec(spec)
    import jax
    import numpy as np

    step = jax.jit(cs.successors)
    # walk a few BFS levels with the interpreter, compare per state
    frontier = spec.initial_states()
    seen = set(frontier)
    for _lvl in range(4):
        nxt = []
        for s in frontier[:40]:
            want = {t for _a, t in spec.successors(s)}
            enc = {
                v: jax.tree_util.tree_map(
                    jax.numpy.asarray,
                    __import__(
                        "pulsar_tlaplus_tpu.frontend.codegen_ir",
                        fromlist=["encode_value"],
                    ).encode_value(cs.var_descs[v], val),
                )
                for v, val in zip(spec.vars, s)
            }
            enc["__err__"] = jax.numpy.bool_(False)
            succ, valid = step(enc)
            got = set()
            for k in range(cs.A):
                if not bool(np.asarray(valid)[k]):
                    continue
                one = jax.tree_util.tree_map(lambda x: x[k], succ)
                dec = cs.decode_state(one)
                assert not bool(np.asarray(one["__err__"])), dec
                got.add(tuple(dec[v] for v in spec.vars))
            assert got == want, f"successor mismatch at {s}"
            for t in want:
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt


def test_invariant_eval_poison_reports_eval_error():
    """An invariant whose evaluation errors on a reachable state (here:
    out-of-domain sequence index) must be reported as an evaluation
    error (__EvalError__), matching TLC's behavior, NOT as a violation
    of the invariant itself (ADVICE r2: codegen poison routing)."""
    from pulsar_tlaplus_tpu.frontend.parser import parse_module

    mod = parse_module(
        """---- MODULE poisoninv ----
EXTENDS Naturals, Sequences
VARIABLES x
Init == x = 0
Next == x < 2 /\\ x' = x + 1
BadInv == <<5, 6>>[x] > 0
====
"""
    )
    spec = I.Spec(mod, {})
    got, _cs = _check(
        spec, invariants=("BadInv",),
        sub_batch=8, visited_cap=1 << 10, frontier_cap=1 << 10,
    )
    # x = 0 is initial and indexes out of 1..2 -> eval error, not a
    # "BadInv is violated" report
    assert got.violation == "__EvalError__"


@pytest.mark.parametrize(
    "name", ["subscription", "bookkeeper", "georeplication"]
)
def test_compiled_original_specs(name):
    """Every original spec in specs/ compiles and matches its
    interpreter counts — structurally different protocols (cursor acks,
    BK write quorum, geo-replication) exercising nested functions,
    Cardinality, dynamic EXCEPT keys, and var-vs-var guard narrowing."""
    from pulsar_tlaplus_tpu.frontend.loader import bind_cfg
    from pulsar_tlaplus_tpu.utils.cfg import parse_cfg

    mod = parse_file(f"/root/repo/specs/{name}.tla")
    cfg = parse_cfg(open(f"/root/repo/specs/{name}.cfg").read())
    consts = bind_cfg(mod, cfg)
    spec = I.Spec(mod, consts)
    from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker

    want = InterpChecker(spec, invariants=()).run()
    got, _cs = _check(spec)
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
