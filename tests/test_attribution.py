"""Fused-era cost attribution tests (round 14, ISSUE 10).

The acceptance bar:

- **work-counter parity**: the fused megakernel's in-kernel work units
  equal the ``-fuse stage`` host dispatch-chain counts EXACTLY —
  state-for-state on the small differential configs and on both
  published bug oracles (the same harness shape as tests/test_fuse.py);
- **zero extra syncs**: the counters ride the packed stats vector —
  the r13 pinned dispatch/fetch economy is unchanged (fetch-count-
  identical, as r8 asserted for the heartbeat);
- **attribution from one fused run**: ``--attribution`` prices a
  single default-mode fused run's counters with a calibration derived
  from a real ``-fuse stage`` + ``PTT_STAGE_TIMING`` run, agreeing
  with that run's RTT-corrected measured stage seconds within a stated
  tolerance (exact parity of the work counts makes the agreement
  deterministic at the calibration shape);
- **v7 schema**: validator positive/negative streams for the new
  ``fuse`` work fields and the ``attribution`` record;
- **the run ledger**: round-trips every committed BENCH_r0*.json,
  renders a correct delta table between two artifacts, and ``ledger
  gate`` catches an injected dispatches/level / work-units/state
  regression against the pinned mini-bench baseline (tier-1 gate).
"""

import dataclasses
import importlib.util
import json
import os
import shutil
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import attribution, ledger, report
from pulsar_tlaplus_tpu.obs import telemetry as obs
from pulsar_tlaplus_tpu.ops import fpset
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PINNED = os.path.join(
    ROOT, "tests", "data", "mini_bench_producer_on.jsonl"
)

WORK_KEYS = (
    "work_expand_rows", "work_probe_lanes", "work_compact_elems",
    "work_append_rows", "work_groups", "work_init_lanes",
)


def _checker_mod():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(ROOT, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk(c, fuse="level", sub_batch=256, **kw):
    kw.setdefault("visited_cap", 1 << 12)
    kw.setdefault("frontier_cap", 1 << 12)
    return DeviceChecker(
        CompactionModel(c), invariants=kw.pop("invariants", ()),
        sub_batch=sub_batch, fuse=fuse, **kw,
    )


def _work(ck):
    return {
        k: v for k, v in ck.last_stats.items() if k.startswith("work_")
    }


# ---- the in-kernel counter primitives -------------------------------


def test_wkm_carry_arithmetic():
    """The hi/lo uint32 carry survives past 2^32 accumulated lanes —
    the r12 fpm pattern, pinned on the work vector."""
    wkm = jnp.zeros((fpset.WKM_N,), jnp.int32)
    big = (1 << 31) - 7  # near the int32 edge, added 3x crosses 2^32
    for _ in range(3):
        wkm = fpset.wkm_update(
            wkm, jnp.int32(5), jnp.int32(big), jnp.int32(big),
            jnp.int32(2), jnp.int32(1),
        )
    logical = fpset.wkm_logical(np.asarray(wkm))
    assert logical[0] == 15
    assert logical[1] == 3 * big  # > 2^32: needs the carry word
    assert logical[2] == 3 * big
    assert logical[3] == 6
    assert logical[4] == 3
    assert 3 * big > (1 << 32)


def test_wkm_logical_short_vectors_zero_pad():
    assert list(fpset.wkm_logical(np.zeros((3,), np.int32))) == [0] * 5


# ---- fused-vs-stage work-counter parity -----------------------------


@pytest.mark.parametrize("name", ["producer_on", "two_crashes"])
def test_work_counter_parity_small_configs(name):
    """Fused in-kernel totals == stage host dispatch-chain totals,
    key for key — the differential contract the whole attribution
    model rests on."""
    c = SMALL_CONFIGS[name]
    ck_f = _mk(c)
    r_f = ck_f.run()
    ck_s = _mk(c, fuse="stage")
    r_s = ck_s.run()
    assert r_f.distinct_states == r_s.distinct_states
    wf, ws = _work(ck_f), _work(ck_s)
    assert wf == ws and wf
    # structural identities: lanes/elems are flush-count x ACAP, and
    # every distinct state is appended exactly once; expand rows sum
    # the level frontiers
    assert wf["work_probe_lanes"] == ck_f.last_stats[
        "fpset_flushes"
    ] * ck_f.ACAP
    assert wf["work_compact_elems"] == wf["work_probe_lanes"]
    assert wf["work_append_rows"] == r_f.distinct_states
    assert wf["work_expand_rows"] == sum(r_f.level_sizes)
    assert wf["work_groups"] == ck_f.last_stats["fpset_flushes"]


def test_work_counter_parity_under_growth_and_flush_factor():
    """Mid-level capacity exits (the megakernel re-enters via w_off)
    and multi-window flush groups with masked partial tails must not
    skew any counter."""
    c = SMALL_CONFIGS["producer_on"]
    a_f = _mk(c, sub_batch=64, visited_cap=1 << 6, frontier_cap=1 << 6,
              group=2)
    a_f.run()
    a_s = _mk(c, fuse="stage", sub_batch=64, visited_cap=1 << 6,
              frontier_cap=1 << 6, group=2)
    a_s.run()
    assert _work(a_f) == _work(a_s)
    b_f = _mk(c, sub_batch=128, visited_cap=1 << 10,
              frontier_cap=1 << 10, flush_factor=4)
    b_f.run()
    b_s = _mk(c, fuse="stage", sub_batch=128, visited_cap=1 << 10,
              frontier_cap=1 << 10, flush_factor=4)
    b_s.run()
    assert _work(b_f) == _work(b_s)


@pytest.mark.parametrize(
    "invariant", ["CompactedLedgerLeak", "DuplicateNullKeyMessage"]
)
def test_work_counter_parity_bug_oracles(invariant):
    """Both published counterexamples (the tests/test_fuse.py
    differential harness): identical work totals through the
    violation-stopped fused and stage paths."""
    ck_f = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), invariants=(invariant,),
        sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15,
    )
    r_f = ck_f.run()
    ck_s = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), invariants=(invariant,),
        sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15,
        fuse="stage",
    )
    r_s = ck_s.run()
    assert r_f.violation == r_s.violation == invariant
    assert _work(ck_f) == _work(ck_s)
    assert _work(ck_f)


def test_work_counters_add_zero_fetches(tmp_path):
    """The r13 pinned dispatch economy is UNCHANGED with the work
    counters on board (they ride the same packed stats vector): the
    producer_on gate numbers — 2 megakernel dispatches + 3 stats
    fetches — hold, and every fuse record carries the v7 per-dispatch
    work deltas summing to the run totals."""
    stream = str(tmp_path / "wk.jsonl")
    ck = _mk(SMALL_CONFIGS["producer_on"], telemetry=stream)
    r = ck.run()
    assert r.distinct_states == 1654
    assert ck._fetch_n == 3  # fetch-count-identical to the r13 gate
    assert ck.last_stats["stage_fused_n"] == 2
    evs = [json.loads(x) for x in open(stream)]
    fuse_evs = [e for e in evs if e["event"] == "fuse"]
    assert fuse_evs
    for key in ("work_expand_rows", "work_probe_lanes",
                "work_compact_elems", "work_append_rows"):
        assert all(isinstance(e[key], int) for e in fuse_evs)
    # per-dispatch deltas sum to the run totals (minus the host-side
    # init chain, which appends level 1 before any fused dispatch)
    assert sum(e["work_probe_lanes"] for e in fuse_evs) + ck.ACAP == (
        ck.last_stats["work_probe_lanes"]
    )
    assert sum(
        e["work_append_rows"] for e in fuse_evs
    ) + r.level_sizes[0] == ck.last_stats["work_append_rows"]
    # the attribution record precedes the result with the same totals
    attr = [e for e in evs if e["event"] == "attribution"]
    assert len(attr) == 1
    assert attr[0]["stages"]["probe_lanes"] == ck.last_stats[
        "work_probe_lanes"
    ]


# ---- calibration + the attribution report ---------------------------


def _stage_timed_run(c, tmp_path, monkeypatch, **kw):
    """A -fuse stage run under PTT_STAGE_TIMING=1 (the calibration
    reference).  The flag is read at ctor time, so patch first."""
    monkeypatch.setenv("PTT_STAGE_TIMING", "1")
    stream = str(tmp_path / "stage_timed.jsonl")
    ck = _mk(c, fuse="stage", telemetry=stream, **kw)
    ck.run()
    monkeypatch.delenv("PTT_STAGE_TIMING")
    events, errs = report.load_events(stream)
    assert not errs
    return ck, events


def test_attribution_single_fused_run_matches_stage_timed(
    tmp_path, monkeypatch
):
    """THE acceptance composition: calibrate from a real ``-fuse
    stage`` + ``PTT_STAGE_TIMING`` run (RTT-corrected), attribute a
    single default-mode FUSED run — the estimates must reproduce the
    measured per-stage seconds within 2% (the work counts are exactly
    equal, so the only slack is float rounding in the emitted
    stream)."""
    c = SMALL_CONFIGS["producer_on"]
    _ck, stage_events = _stage_timed_run(c, tmp_path, monkeypatch)
    cal = attribution.calibrate_from_events(stage_events, label="test")
    assert set(cal["measured_stages"]) >= {
        "expand", "flush", "compact", "append",
    }
    fused_stream = str(tmp_path / "fused.jsonl")
    ck_f = _mk(c, telemetry=fused_stream)
    ck_f.run()
    fused_events, _ = report.load_events(fused_stream)
    rows = {
        r["stage"]: r for r in attribution.attribute(fused_events, cal)
    }
    measured = report.stage_split(stage_events)
    for stage in ("expand", "flush", "compact", "append"):
        est = rows[stage]["est_s"]
        dev = measured[stage]["device_s"]
        assert est is not None and dev is not None
        assert est == pytest.approx(dev, rel=0.02), stage
        # the fused stream itself carries NO measured timings — the
        # whole point: no stage rerun was needed for the estimate
        assert rows[stage]["measured_s"] is None
    table = attribution.render_attribution([("fused", fused_events)], cal)
    assert "| flush |" in table and "est s" in table


def test_attribution_cli_front_end(tmp_path):
    """scripts/telemetry_report.py --attribution renders the table
    from a fused stream (with the default, footnoted-uncalibrated
    units when no calibration file is given)."""
    stream = str(tmp_path / "cli.jsonl")
    _mk(SMALL_CONFIGS["producer_on"], telemetry=stream).run()
    cal_path = str(tmp_path / "cal.json")
    attribution.save_calibration(
        cal_path, attribution.default_calibration("cpu")
    )
    p = subprocess.run(
        [
            sys.executable, "scripts/telemetry_report.py", stream,
            "--attribution", "--calibration", cal_path,
        ],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert p.returncode == 0, p.stderr[-500:]
    assert "| flush |" in p.stdout
    assert "est s" in p.stdout


def test_calibration_round_trip(tmp_path):
    path = str(tmp_path / "c.json")
    cal = attribution.default_calibration("cpu")
    attribution.save_calibration(path, cal)
    assert attribution.load_calibration(path)["units"] == cal["units"]
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump({"nope": 1}, f)
    with pytest.raises(ValueError, match="units"):
        attribution.load_calibration(bad)


# ---- v7 schema: validator positive/negative -------------------------


def test_v7_stream_validates_and_negatives(tmp_path):
    ckr = _checker_mod()
    stream = tmp_path / "v7.jsonl"
    _mk(SMALL_CONFIGS["producer_on"], telemetry=str(stream)).run()
    assert ckr.validate_stream(str(stream)) == []
    evs = [json.loads(x) for x in open(stream)]
    assert any(e["event"] == "attribution" for e in evs)
    # negative: a v7 fuse record missing a work field fails validation
    bad = []
    done = False
    for e in evs:
        if not done and e["event"] == "fuse":
            e = {k: v for k, v in e.items() if k != "work_probe_lanes"}
            done = True
        bad.append(e)
    p = tmp_path / "v7_bad.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in bad))
    errs = ckr.validate_stream(str(p))
    assert errs and any("work_probe_lanes" in e for e in errs)
    # a v6 fuse record WITHOUT work fields stays valid (FIELD_SINCE)
    old = []
    for e in evs:
        if e["event"] == "fuse":
            e = {
                k: v for k, v in e.items()
                if not k.startswith("work_")
            }
            e["v"] = 6
        old.append(e)
    p2 = tmp_path / "v6_ok.jsonl"
    p2.write_text("".join(json.dumps(e) + "\n" for e in old))
    assert ckr.validate_stream(str(p2)) == []
    # negative: an attribution record without stages fails
    noat = [
        dict(e, stages=None) if e["event"] == "attribution" else e
        for e in evs
    ]
    for e in noat:
        if e["event"] == "attribution":
            del e["stages"]
    p3 = tmp_path / "v7_noattr.jsonl"
    p3.write_text("".join(json.dumps(e) + "\n" for e in noat))
    errs3 = ckr.validate_stream(str(p3))
    assert errs3 and any("stages" in e for e in errs3)


def test_bench_schema_v7_keys():
    ckr = _checker_mod()
    base = {k: 1 for k in ckr.BENCH_KEYS_V7}
    base.update(bench_schema=7, value=1.0)
    assert ckr.validate_bench_artifact(dict(base), "good") == []
    bad = dict(base)
    del bad["work_probe_lanes"], bad["work_groups"]
    errs = ckr.validate_bench_artifact(bad, "bad")
    assert any("work_probe_lanes" in e for e in errs)
    assert any("work_groups" in e for e in errs)
    # a schema-6 artifact is NOT held to the work keys
    v6 = {k: 1 for k in ckr.BENCH_KEYS_V6}
    v6.update(bench_schema=6, value=1.0)
    assert ckr.validate_bench_artifact(v6, "v6") == []


# ---- liveness sweep attribution (satellite 1) -----------------------


def test_sweep_work_counters_and_attribution(tmp_path):
    """The fused+grouped sweep counts its merge-sort lanes,
    gid-propagation pass-lanes, and edge-compaction elements; the
    stream validates at v7 and the attribution layer renders a sweep
    section."""
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    ckr = _checker_mod()
    stream = str(tmp_path / "sweep.jsonl")
    c = SMALL_CONFIGS["producer_on"]
    lck = LivenessChecker(
        CompactionModel(c), goal="Termination", fairness="wf_next",
        frontier_chunk=256, visited_cap=1 << 12, telemetry=stream,
    )
    lres = lck.run()
    assert lres.distinct_states == 1654
    assert ckr.validate_stream(stream) == []
    events, _ = report.load_events(stream)
    sweeps = [e for e in events if e.get("event") == "sweep"]
    assert sweeps
    last = sweeps[-1]
    # cumulative totals match the trace-time constants: chunks x the
    # per-chunk sort/prop/compact widths
    n_chunks = last["chunk"]
    NQ = lck.SF * lck.model.A
    cap = lck._table_cap(lres.distinct_states)
    assert last["sort_lanes"] == n_chunks * 2 * (cap + NQ)
    assert last["compact_elems"] == n_chunks * NQ
    assert last["prop_lanes"] % (cap + NQ) == 0
    # monotone cumulative across records
    assert all(
        a["sort_lanes"] <= b["sort_lanes"]
        for a, b in zip(sweeps, sweeps[1:])
    )
    # the liveness result carries the totals + an attribution record
    res = [e for e in events if e.get("event") == "result"][-1]
    assert res["work_sweep_sort_lanes"] == last["sort_lanes"]
    attr = [e for e in events if e.get("event") == "attribution"]
    assert any("sweep_sort_lanes" in a["stages"] for a in attr)
    rows = attribution.sweep_attribute(events)
    stages = [r["stage"] for r in rows]
    assert "sweep_sort" in stages and "sweep_compact" in stages
    table = attribution.render_attribution([("lv", events)])
    assert "sweep_sort" in table


# ---- heartbeat smoothing (satellite 2) ------------------------------


def test_heartbeat_ewma_and_partial_marker():
    """The heartbeat's displayed rate is an EWMA across beats (the
    fuse-batch sawtooth damper) and a line whose newest snapshot was
    an intra-level anchor carries the ~ marker."""
    lines = []
    snap = {"distinct_states": 0, "level": 3}
    hb = obs.Heartbeat(5.0, snap, log=lines.append)
    import time as _time

    t0 = _time.monotonic() - 1.0
    snap["distinct_states"] = 1000
    prev = hb._beat(t0, (t0, 0))
    assert hb.ewma_sps is not None
    first = hb.ewma_sps
    # a huge burst (a ramp batch landing 8 levels at once): the EWMA
    # moves toward the spike but stays well below the raw sample
    snap["distinct_states"] = 101000
    snap["partial"] = True
    _time.sleep(0.01)
    hb._beat(t0, prev)
    raw_spike = (101000 - 1000) / max(
        _time.monotonic() - prev[0], 1e-9
    )
    assert first < hb.ewma_sps < raw_spike
    assert hb.ewma_sps < 0.5 * raw_spike  # genuinely smoothed
    assert "~" in lines[1].split(")")[0]  # the partial marker
    assert "~" not in lines[0].split(")")[0]


def test_engine_snap_carries_partial_flag(tmp_path):
    """The engine's heartbeat snapshot tags intra-level anchors so the
    marker reflects the newest record kind."""
    ck = _mk(SMALL_CONFIGS["producer_on"])
    ck.run()
    # the final record of a clean run is a level boundary
    assert ck._snap.get("partial") is False


# ---- the run ledger (tentpole part 3) -------------------------------


def test_ledger_roundtrip_every_committed_bench_artifact(tmp_path):
    """All five committed BENCH artifacts (pre-schema r1 through
    schema-2 r5, driver-wrapper shape) ingest, dedup, validate, and
    render."""
    path = str(tmp_path / "ledger.jsonl")
    sources = sorted(
        p for p in os.listdir(ROOT)
        if p.startswith("BENCH_r0") and p.endswith(".json")
    )
    assert len(sources) >= 5
    recs = [
        ledger.record_from_file(os.path.join(ROOT, p)) for p in sources
    ]
    assert ledger.append(path, recs) == len(sources)
    assert ledger.append(path, recs) == 0  # idempotent by digest
    assert ledger.validate_ledger(path) == []
    loaded = ledger.load(path)
    assert [r["source"] for r in loaded] == sources
    assert all(r["values"].get("value") for r in loaded)
    # rounds parsed from the driver wrapper
    assert [r["round"] for r in loaded] == [1, 2, 3, 4, 5]
    table = ledger.render_list(loaded)
    assert "BENCH_r05.json" in table


def test_ledger_compare_two_committed_artifacts():
    """The acceptance delta table: r04 -> r05 shows the headline rate
    moving by the published amounts."""
    a = ledger.record_from_file(os.path.join(ROOT, "BENCH_r04.json"))
    b = ledger.record_from_file(os.path.join(ROOT, "BENCH_r05.json"))
    rows = {r["key"]: r for r in ledger.compare(a, b)}
    assert rows["value"]["a"] == pytest.approx(2021923.9)
    assert rows["value"]["b"] == pytest.approx(3184662.1)
    assert rows["value"]["pct"] == pytest.approx(57.5, abs=0.1)
    assert rows["distinct_states"]["delta"] == 171410570 - 61685485
    out = ledger.render_compare(a, b)
    assert "+57.5%" in out
    # same config key: no incomparability warning
    assert "WARNING" not in out


def test_ledger_stream_record_and_key_grouping(tmp_path):
    """Telemetry streams ingest through the same bench_keys layer;
    runs of the same config/engine/modes share a config key, and a
    mode flip (fuse=stage) changes it."""
    s1 = str(tmp_path / "a.jsonl")
    s2 = str(tmp_path / "b.jsonl")
    s3 = str(tmp_path / "c.jsonl")
    _mk(SMALL_CONFIGS["producer_on"], telemetry=s1).run()
    _mk(SMALL_CONFIGS["producer_on"], telemetry=s2).run()
    _mk(SMALL_CONFIGS["producer_on"], fuse="stage", telemetry=s3).run()
    r1 = ledger.record_from_file(s1)
    r2 = ledger.record_from_file(s2)
    r3 = ledger.record_from_file(s3)
    assert r1["key"] == r2["key"]
    assert r1["key"] != r3["key"]
    assert "fuse=level" in r1["key"] and "fuse=stage" in r3["key"]
    assert r1["values"]["work_units_per_state"] > 0


def test_ledger_gate_tier1_pinned_baseline(tmp_path):
    """THE tier-1 gate: a fresh producer_on fused run gates clean
    against the committed mini-bench baseline on the deterministic
    economy keys; an injected dispatches/level or work-units/state
    regression fails with exit 1."""
    from pulsar_tlaplus_tpu import cli

    path = str(tmp_path / "gate_ledger.jsonl")
    shutil.copy(PINNED, path)
    assert ledger.validate_ledger(path) == []
    stream = str(tmp_path / "run.jsonl")
    _mk(SMALL_CONFIGS["producer_on"], telemetry=stream).run()
    rc = cli.main(["ledger", "--ledger", path, "add", stream])
    assert rc == 0
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate", "--threshold", "0.1",
            "--keys", "dispatches_per_level", "work_units_per_state",
        ]
    )
    assert rc == 0  # the current build does not regress the economy
    # inject a regression: a future PR that doubles dispatches/level
    # or work per state must fail the suite here
    cur = ledger.load(path)[-1]
    bad = dict(cur, values=dict(cur["values"]))
    bad["values"]["dispatches_per_level"] = (
        cur["values"]["dispatches_per_level"] * 2
    )
    bad["values"]["work_units_per_state"] = (
        cur["values"]["work_units_per_state"] * 1.5
    )
    bad["digest"] = ledger._digest(bad["values"])
    ledger.append(path, [bad])
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate", "--threshold", "0.1",
            "--keys", "dispatches_per_level", "work_units_per_state",
        ]
    )
    assert rc == 1
    violations = ledger.gate(
        cur, bad, threshold=0.1,
        keys=("dispatches_per_level", "work_units_per_state"),
    )
    assert {v["key"] for v in violations} == {
        "dispatches_per_level", "work_units_per_state",
    }


def test_ledger_validator_catches_tampering(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = ledger.record_from_file(os.path.join(ROOT, "BENCH_r05.json"))
    ledger.append(path, [rec])
    # hand-edit a value without refreshing the digest
    lines = open(path).read().splitlines()
    d = json.loads(lines[0])
    d["values"]["value"] = 999.0
    with open(path, "w") as f:
        f.write(json.dumps(d) + "\n")
    errs = ledger.validate_ledger(path)
    assert errs and any("digest" in e for e in errs)


def test_ledger_cli_validator_front_end(tmp_path):
    """check_telemetry_schema.py --ledger validates ledger files."""
    ckr = _checker_mod()
    path = str(tmp_path / "v.jsonl")
    ledger.append(
        path,
        [ledger.record_from_file(os.path.join(ROOT, "BENCH_r05.json"))],
    )
    assert ckr.main([path, "--ledger"]) == 0
    with open(path, "a") as f:
        f.write('{"not": "a record"}\n')
    assert ckr.main([path, "--ledger"]) == 1


def test_liveness_stream_attributes_engine_and_sweep_stages(tmp_path):
    """A liveness stream carries TWO attribution records (the inner
    explorer's and the sweep's) — work_units merges them, so the
    engine per-stage rows never vanish behind the sweep-only record
    (review finding: last-record-wins dropped the whole explorer
    table)."""
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    stream = str(tmp_path / "lv2.jsonl")
    LivenessChecker(
        CompactionModel(SMALL_CONFIGS["producer_on"]),
        goal="Termination", fairness="wf_next", frontier_chunk=256,
        visited_cap=1 << 12, telemetry=stream,
    ).run()
    events, _ = report.load_events(stream)
    w = attribution.work_units(events)
    assert "probe_lanes" in w and "sweep_sort_lanes" in w
    rows = attribution.attribute(events)
    assert {r["stage"] for r in rows} >= {"expand", "flush", "append"}


def test_gate_rejects_unknown_keys(tmp_path):
    """A typo'd --keys must error (exit 2), never pass vacuously."""
    from pulsar_tlaplus_tpu import cli

    a = ledger.record_from_file(os.path.join(ROOT, "BENCH_r04.json"))
    b = ledger.record_from_file(os.path.join(ROOT, "BENCH_r05.json"))
    with pytest.raises(KeyError, match="dispaches_per_level"):
        ledger.gate(a, b, keys=("dispaches_per_level",))
    path = str(tmp_path / "l.jsonl")
    ledger.append(path, [a, b])
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate",
            "--keys", "dispaches_per_level",
        ]
    )
    assert rc == 2


def test_ledger_rejects_non_telemetry_jsonl(tmp_path):
    """The append-only ledger must refuse to ingest a .jsonl that is
    not a telemetry stream (e.g. the ledger file itself) — a junk
    record could never be deleted again."""
    from pulsar_tlaplus_tpu import cli

    path = str(tmp_path / "self.jsonl")
    ledger.append(
        path,
        [ledger.record_from_file(os.path.join(ROOT, "BENCH_r05.json"))],
    )
    ledger.append(
        path,
        [ledger.record_from_file(os.path.join(ROOT, "BENCH_r04.json"))],
    )
    with pytest.raises(ValueError, match="not a telemetry stream"):
        ledger.record_from_file(path)
    assert cli.main(["ledger", "--ledger", path, "add", path]) == 2
    assert len(ledger.load(path)) == 2  # nothing was appended


def test_gate_default_baseline_precedes_current(tmp_path):
    """Gating an OLDER record must pick an even earlier baseline —
    never a newer run (which would invert the comparison)."""
    from pulsar_tlaplus_tpu import cli

    base = ledger.record_from_file(PINNED)

    def forged(dpl, tag):
        r = dict(base, values=dict(base["values"]), source=tag)
        r["values"]["dispatches_per_level"] = dpl
        r["digest"] = ledger._digest(r["values"])
        return r

    old, mid, new = (
        forged(0.31, "old"), forged(0.32, "mid"), forged(0.10, "new")
    )
    path = str(tmp_path / "ord.jsonl")
    ledger.append(path, [old, mid, new])
    # gate `mid`: its baseline must be `old` (0.31 -> 0.32 = +3%,
    # passes), NOT `new` (0.10 -> 0.32 = +220%, would fail)
    rc = cli.main(
        [
            "ledger", "--ledger", path, "gate",
            "--current", mid["digest"],
            "--keys", "dispatches_per_level",
        ]
    )
    assert rc == 0


# ---- the 253k acceptance oracle -------------------------------------


FULL_253K = dataclasses.replace(
    pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
)


def test_253k_single_fused_run_attribution(tmp_path):
    """ISSUE 10 acceptance: a SINGLE default-mode fused run on the
    253k CPU-mesh oracle yields the --attribution per-stage table —
    no ``-fuse stage`` rerun, zero extra device fetches (the work
    counters ride the one packed stats vector), and the counters
    reconcile against the run's own flush/level accounting."""
    stream = str(tmp_path / "full.jsonl")
    ck = DeviceChecker(
        CompactionModel(FULL_253K), invariants=(), sub_batch=4096,
        visited_cap=1 << 18, frontier_cap=1 << 17, flush_factor=2,
        telemetry=stream,
    )
    r = ck.run()
    assert r.distinct_states == 253361 and r.diameter == 23
    # zero-extra-fetch: every fetch is one the r13 economy already
    # paid (init chain + one per megakernel dispatch + growth exits)
    assert ck._fetch_n == ck.last_stats["stats_fetches"]
    w = _work(ck)
    assert w["work_probe_lanes"] == (
        ck.last_stats["fpset_flushes"] * ck.ACAP
    )
    assert w["work_append_rows"] == r.distinct_states
    assert w["work_expand_rows"] == sum(r.level_sizes)
    events, _ = report.load_events(stream)
    table = attribution.render_attribution([("253k", events)])
    assert "| flush |" in table and "253361" in table


@pytest.mark.slow
def test_253k_fused_vs_stage_work_parity():
    """The full differential at the 253k shape (two runs — slow-marked
    like the r10 253k compact differential; the real host runs it).
    The small-config + bug-oracle parity tests cover the same
    contract in-tier."""
    ck_f = DeviceChecker(
        CompactionModel(FULL_253K), invariants=(), sub_batch=4096,
        visited_cap=1 << 18, frontier_cap=1 << 17, flush_factor=2,
    )
    r_f = ck_f.run()
    ck_s = DeviceChecker(
        CompactionModel(FULL_253K), invariants=(), sub_batch=4096,
        visited_cap=1 << 18, frontier_cap=1 << 17, flush_factor=2,
        fuse="stage",
    )
    r_s = ck_s.run()
    assert r_f.distinct_states == r_s.distinct_states == 253361
    assert _work(ck_f) == _work(ck_s)
