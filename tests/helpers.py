"""Shared helpers for differential tests: oracle BFS sampling and
counterexample-trace validation."""

import functools
import random
import subprocess

import jax
import pytest

from pulsar_tlaplus_tpu.frontend.loader import reference_spec_path
from pulsar_tlaplus_tpu.ref import pyeval as pe

# The reference compaction module: the vendored specs/compaction.tla
# wins; /root/reference/ (the original retrieval mount) is the fallback
# on hosts that still carry it.
REFERENCE_TLA = reference_spec_path("compaction")

# Both sharded engines build on jax.shard_map (added after jax 0.4.37,
# the container's version).  Known-environment failures are noise, not
# signal: tier-1 SKIPS these tests where shard_map is absent — the real
# host (and any jax >= 0.5) still runs them.
needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="sharded engines need jax.shard_map (newer jax; container "
    "jax 0.4.37 lacks it)",
)


@functools.lru_cache(maxsize=1)
def _pallas_lowers_natively() -> bool:
    try:
        from pulsar_tlaplus_tpu.ops import tiles

        return tiles.pallas_lowers_natively()
    except Exception:  # noqa: BLE001 — any failure mode means "skip"
        return False


# The r23 Pallas tile kernels compile natively only on a TPU backend;
# everywhere else ops/tiles.py runs them under interpret=True, which
# the always-on parity tests already exercise.  Same regime as
# needs_shard_map: tests pinning NATIVE lowering behavior (mosaic
# compilation, on-chip timing) SKIP on the CPU-mesh container and run
# on the real host.
needs_pallas_tpu = pytest.mark.skipif(
    not _pallas_lowers_natively(),
    reason="native Pallas lowering needs a TPU backend (interpret-"
    "mode parity tests still run here)",
)


@functools.lru_cache(maxsize=1)
def _native_baseline_runnable() -> bool:
    """True when the COMMITTED native baseline binary actually RUNS
    here.  The binary was built on the real host; a container with an
    older glibc loads it and dies before main — probe with a tiny
    config instead of pattern-matching on toolchain presence.  Probes
    the tracked binary path directly, never ``build_baseline()``: a
    rebuild would overwrite the tracked binary AND mask the very
    environment difference the skip exists to report."""
    try:
        import os

        from pulsar_tlaplus_tpu import native

        binary = os.path.join(
            os.path.dirname(native.__file__), "compaction_bfs"
        )
        if not os.path.exists(binary):
            return False
        p = subprocess.run(
            [binary, "1", "1", "1", "1", "0", "0", "1", "5", "1", "10"],
            capture_output=True, text=True, timeout=60,
        )
        return p.returncode in (0, 1) and bool(p.stdout.strip())
    except Exception:  # noqa: BLE001 — any failure mode means "skip"
        return False


# The native TLC-class baseline (BASELINE.md) needs a binary the
# current libc can actually load.  Same regime as needs_shard_map: a
# clean container run reports SKIPs, not failures; the real host (and
# any glibc >= the build host's) still runs the tests.
needs_native_binary = pytest.mark.skipif(
    not _native_baseline_runnable(),
    reason="native baseline binary is not runnable in this "
    "environment (glibc/toolchain mismatch; runnable on the real "
    "host)",
)


def assert_valid_counterexample(c, trace, trace_actions, invariant):
    """A counterexample must start at an initial state, follow real
    transitions (named actions must map to the oracle's successors), satisfy
    the invariant at every non-final state, and violate it at the end."""
    assert trace and trace[0] in set(pe.initial_states(c))
    inv = pe.INVARIANTS[invariant]
    for s, act, t in zip(trace, trace_actions, trace[1:]):
        act_name = act if isinstance(act, str) else pe.ACTION_NAMES[act]
        succ = {}
        for a, st in pe.successors(c, s):
            succ.setdefault(pe.ACTION_NAMES[a], []).append(st)
        assert t in succ.get(act_name, []), (act_name, s)
        assert inv(c, s), "only the final state may violate"
    assert not inv(c, trace[-1])


def oracle_sample(c, n_states=150, levels=8, seed=0):
    """A deterministic sample of reachable states, spread across BFS depth."""
    seen = {}
    frontier = []
    for s in pe.initial_states(c):
        if s not in seen:
            seen[s] = None
            frontier.append(s)
    for _ in range(levels):
        new = []
        for s in frontier:
            for _a, t in pe.successors(c, s):
                if t not in seen:
                    seen[t] = None
                    new.append(t)
        if not new:
            break
        frontier = new
    rng = random.Random(seed)
    pool = list(seen)
    return rng.sample(pool, min(n_states, len(pool)))


def tight_hbm_budget(checker_ctor, slack=4096):
    """A budget just above a checker shape's initial-tier minimum —
    tiers pinned at their smallest, so a tiered run MUST spill.
    ``checker_ctor(hbm_budget)`` builds a throwaway probe checker with
    the workload's exact shape knobs; the 0.9 divisor mirrors the
    engine's default ``hbm_headroom=0.1``.  One definition so every
    spill drill/test stays in lockstep with the engine's byte
    arithmetic (tests/test_store.py, tests/test_subscription.py,
    tests/_survivable_run.py)."""
    probe = checker_ctor("1G")
    return (
        int(
            probe._device_bytes_est(probe.TCAP, probe.LCAP, probe.PCAP)
            / (1.0 - probe.hbm_headroom)
        )
        + slack
    )


# Small configurations exercising distinct semantic corners (cheap enough
# for exhaustive engine-vs-oracle runs on the CPU backend).
SMALL_CONFIGS = {
    "shipped": pe.SHIPPED_CFG,
    "producer_on": pe.Constants(
        message_sent_limit=2,
        compaction_times_limit=2,
        num_keys=1,
        num_values=1,
        max_crash_times=1,
        model_producer=True,
    ),
    "no_retain": pe.Constants(
        message_sent_limit=3,
        compaction_times_limit=2,
        num_keys=2,
        num_values=1,
        retain_null_key=False,
        max_crash_times=1,
    ),
    "two_crashes": pe.Constants(
        message_sent_limit=2,
        compaction_times_limit=3,
        num_keys=1,
        num_values=2,
        max_crash_times=2,
    ),
    "wide_mask": pe.Constants(
        # message positions spill into a second 32-bit mask word only when
        # M > 32; keep a cheap variant that still crosses field boundaries.
        message_sent_limit=4,
        compaction_times_limit=2,
        num_keys=3,
        num_values=1,
        max_crash_times=1,
    ),
}
