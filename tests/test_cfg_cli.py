"""Config front-end and CLI tests (SURVEY.md §1-L4, §5 config system)."""

import subprocess
import sys
import warnings

import pytest

from pulsar_tlaplus_tpu.ref.pyeval import SHIPPED_CFG
from pulsar_tlaplus_tpu.utils import cfg as cfgmod

# Semantically identical to the reference compaction.cfg (string KeySpace,
# model-value block, commented-out bug invariants), written independently.
SHIPPED_LIKE = """
CONSTANTS
    MessageSentLimit = 3,
    CompactionTimesLimit = 3,
    ModelConsumer = FALSE,
    ConsumeTimesLimit = 2,
    KeySpace = {"key1", "key2"},
    ValueSpace = {1, 2},
    RetainNullKey = TRUE,
    MaxCrashTimes = 1,
    ModelProducer = FALSE

CONSTANTS
    Nil = Nil,
    Compactor_In_PhaseOne = Compactor_In_PhaseOne

SPECIFICATION Spec

INVARIANTS
    TypeSafe,
    \\* CompactedLedgerLeak,
    CompactionHorizonCorrectness
"""


def test_parse_shipped_like_cfg():
    cfg = cfgmod.parse_cfg(SHIPPED_LIKE)
    assert cfg.specification == "Spec"
    assert cfg.invariants == ["TypeSafe", "CompactionHorizonCorrectness"]
    assert "Nil" in cfg.model_values
    assert cfg.constants["MessageSentLimit"] == 3
    assert cfg.constants["KeySpace"] == frozenset({"key1", "key2"})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        constants = cfgmod.to_constants(cfg)
        # the string-key ASSUME discrepancy must be diagnosed, not silent
        assert any("SUBSET Nat" in str(x.message) for x in w)
    assert constants == SHIPPED_CFG


def test_integer_keyspace_strict():
    cfg = cfgmod.parse_cfg(SHIPPED_LIKE.replace('{"key1", "key2"}', "{1, 2}"))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        constants = cfgmod.to_constants(cfg)
        assert not w  # dense 1..n integer space needs no diagnostics
    assert constants == SHIPPED_CFG


def test_zero_in_keyspace_rejected():
    cfg = cfgmod.parse_cfg(SHIPPED_LIKE.replace('{"key1", "key2"}', "{0, 1}"))
    with pytest.raises(ValueError, match="reserved"):
        cfgmod.to_constants(cfg)


def test_missing_constant_rejected():
    cfg = cfgmod.parse_cfg(SHIPPED_LIKE.replace("MaxCrashTimes = 1,", ""))
    with pytest.raises(ValueError, match="MaxCrashTimes"):
        cfgmod.to_constants(cfg)


def test_cli_end_to_end(tmp_path):
    """CLI on a small producer-on model: clean run, TLC-style summary."""
    spec = tmp_path / "compaction.tla"
    spec.write_text("---- MODULE compaction ----\n====\n")  # registry stub
    cfg = tmp_path / "compaction.cfg"
    cfg.write_text(
        SHIPPED_LIKE.replace("MessageSentLimit = 3", "MessageSentLimit = 2")
        .replace('{"key1", "key2"}', "{1}")
        .replace("ValueSpace = {1, 2}", "ValueSpace = {1}")
        .replace("CompactionTimesLimit = 3", "CompactionTimesLimit = 2")
        .replace("ModelProducer = FALSE", "ModelProducer = TRUE")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "pulsar_tlaplus_tpu.cli", "check", str(spec), "-cpu", "-chunk", "256"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr
    assert "distinct states found" in proc.stdout
