"""Test harness config: virtual 8-device CPU mesh + persistent compile cache.

Multi-chip behavior is tested without TPUs by forcing 8 host-platform
devices (SURVEY.md §4e); the real-chip path is exercised by bench.py.
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# jax_platforms programmatically, so the env var alone is not enough.
jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
