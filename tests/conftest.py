"""Test harness config: virtual 8-device CPU mesh + persistent compile cache.

Multi-chip behavior is tested without TPUs by forcing 8 host-platform
devices (SURVEY.md §4e); the real-chip path is exercised by bench.py.
Must run before jax is imported anywhere.
"""

import os
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Tuned-profile hermeticity (r15): CLI/daemon paths resolve profiles
# from PTT_TUNE_DIR (default ~/.ptt_profiles) — a stray profile on the
# developer's machine must never reshape pinned test geometry, and
# adaptation must never default on mid-suite.  Set unconditionally
# (not setdefault): subprocess-driven CLI tests inherit this env.
os.environ["PTT_TUNE_DIR"] = tempfile.mkdtemp(prefix="ptt_test_profiles_")
os.environ.pop("PTT_TUNE_ADAPT", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# jax_platforms programmatically, so the env var alone is not enough.
jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# ---- quick tier (VERDICT r4 #9) -------------------------------------
# `pytest -m quick`: the fast green signal — oracle pins + one engine
# per family, ~50s total on the 1-core image (full suite: ~770s).
# Central nodeid list rather than per-file decorators so the tier's
# composition is reviewable in one place.
_QUICK = (
    "test_pyeval_oracle.py",  # every oracle pin
    "test_packing.py",        # layout round-trip properties
    "test_device_bfs.py::test_device_engine_shipped_cfg_published_count",
    "test_device_bfs.py::test_device_engine_leak_counterexample",
    "test_sharded_device.py::test_sharded_device_counts_identical_across_meshes[8]",
    "test_codegen.py::test_compiled_shipped_cfg_published_count",
    "test_actions.py::test_successors_match_oracle[shipped]",
    "test_engine.py::test_engine_shipped_cfg_published_count",
    "test_frontend.py::TestOracles::test_shipped_cfg_state_count",
    "test_native_baseline.py::test_native_baseline_shipped_cfg_published_count",
)


def pytest_collection_modifyitems(items):
    for item in items:
        rel = item.nodeid.split("tests/")[-1]
        if any(
            rel == q or rel.startswith(q + "::") or rel.startswith(q)
            and q.endswith(".py")
            for q in _QUICK
        ):
            item.add_marker(pytest.mark.quick)
