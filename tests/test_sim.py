"""Swarm simulation subsystem tests (round 18, ISSUE 14): the
streaming engine's determinism/resume contract, both published
bug-discovery oracles with interpreter-replayed traces, the
kill->resume drill, daemon time-slicing with solo parity, the
differential fuzz fast drill, the sim ledger gate, and the v11
telemetry/bench_schema-9 validator gates."""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import ledger, metrics, report
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.sim.engine import StreamingSimulator
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIM_PINNED = os.path.join(
    ROOT, "tests", "data", "mini_bench_sim_producer_on.jsonl"
)

# the deterministic small shape every stream-identity test shares
# (producer_on: 1,654 reachable states — walkers revisit heavily,
# which is exactly what the duplicate estimator should report)
SMALL_KW = dict(
    n_walkers=128, depth=16, segment_len=4, seed=3,
    max_steps=128 * 16 * 3, profile=None,
)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _sim_events(path):
    """The deterministic view of a stream's `sim` records (cumulative
    counters only — no clocks)."""
    evs, errs = report.load_events(path)
    assert not errs
    return [
        {
            k: e[k]
            for k in (
                "steps", "states", "walks", "violations",
                "stutter_steps", "enabled_lanes", "dup_attempts",
                "dup_hits", "epoch",
            )
        }
        for e in evs
        if e.get("event") == "sim"
    ]


@pytest.fixture(scope="module")
def small_model():
    return CompactionModel(SMALL_CONFIGS["producer_on"])


@pytest.fixture(scope="module")
def shipped_model():
    return CompactionModel(pe.SHIPPED_CFG)


# ------------------------------------------------------------- units


def test_segment_len_clamps_to_depth_divisor(small_model):
    s = StreamingSimulator(
        small_model, depth=48, segment_len=20, profile=None
    )
    assert s.L == 16 and 48 % s.L == 0  # largest divisor <= 20
    s2 = StreamingSimulator(
        small_model, depth=48, segment_len=500, profile=None
    )
    assert s2.L == 48  # clamped to depth


def test_unknown_invariant_raises(small_model):
    with pytest.raises(ValueError, match="unknown invariant"):
        StreamingSimulator(
            small_model, invariants=("NoSuchInv",), profile=None
        )


def test_default_budget_is_one_round(small_model):
    s = StreamingSimulator(small_model, n_walkers=8, depth=4,
                           profile=None)
    assert s.max_rounds == 1


def test_one_round_contract_spans_multiple_segments(small_model):
    """The legacy one-round budget must cover the FULL depth even when
    a round spans several segments (steps are swarm-total: one round =
    B * depth, not depth — the r18 review regression)."""
    r = StreamingSimulator(
        small_model, n_walkers=16, depth=64, profile=None
    ).run()
    assert r.steps == 16 * 64
    assert r.states_visited == 16 * 65
    assert r.walks == 16
    assert r.stop_reason == "round_budget"


def test_resume_restores_frame_budgets(small_model, tmp_path):
    """A resume constructed WITHOUT explicit budgets adopts the
    frame's persisted ones — `simulate -recover` must finish the
    original step budget, never the one-round default (which would
    end a recovered long run immediately, reported clean)."""
    ck = str(tmp_path / "f.npz")
    budget = 128 * 16 * 3
    polls = [0]

    def hook():
        polls[0] += 1
        return None if polls[0] <= 3 else "suspended"

    r1 = StreamingSimulator(
        small_model, n_walkers=128, depth=16, segment_len=4, seed=3,
        max_steps=budget, checkpoint_path=ck, suspend_hook=hook,
        profile=None,
    ).run()
    assert r1.stop_reason == "suspended" and r1.steps < budget
    # note: NO budget args — the frame must supply them
    r2 = StreamingSimulator(
        small_model, n_walkers=128, depth=16, segment_len=4, seed=3,
        checkpoint_path=ck, profile=None,
    ).run(resume=True)
    assert r2.steps == budget
    assert r2.stop_reason == "step_budget"


def test_heartbeat_reports_walks_rate():
    from pulsar_tlaplus_tpu.obs.telemetry import Heartbeat

    lines = []
    snap = {"distinct_states": 100, "generated": 90, "walks": 0}
    hb = Heartbeat(60.0, snap, log=lines.append)
    import time as _time

    t0 = _time.monotonic() - 1.0
    prev = hb._beat(t0, (t0, 0))
    snap.update(distinct_states=300, generated=280, walks=128)
    hb._beat(t0, prev)
    assert hb.ewma_wps is not None and hb.ewma_wps > 0
    assert any("walks/s" in ln for ln in lines)


# --------------------------------------------- determinism + resume


def test_deterministic_stream_and_counters(small_model, tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    r1 = StreamingSimulator(small_model, telemetry=a, **SMALL_KW).run()
    r2 = StreamingSimulator(small_model, telemetry=b, **SMALL_KW).run()
    assert _sim_events(a) == _sim_events(b)
    assert (r1.steps, r1.states_visited, r1.walks, r1.dup_ratio_est) \
        == (r2.steps, r2.states_visited, r2.walks, r2.dup_ratio_est)
    assert r1.steps == SMALL_KW["max_steps"]
    assert r1.walks == 128 * 3  # three completed rounds
    assert r1.stop_reason == "step_budget" and not r1.truncated
    # a producer_on swarm revisits the 1,654-state space constantly —
    # the advisory estimator must see substantial duplication
    assert r1.dup_ratio_est is not None and r1.dup_ratio_est > 0.2
    # a different seed is a different (deterministic) stream
    kw = dict(SMALL_KW, seed=4)
    r3 = StreamingSimulator(small_model, **kw).run()
    assert (r3.steps, r3.states_visited) == (r1.steps, r1.states_visited)
    assert r3.dup_ratio_est != r1.dup_ratio_est


def test_suspend_resume_continues_identical_stream(
    small_model, tmp_path
):
    solo = str(tmp_path / "solo.jsonl")
    r_solo = StreamingSimulator(
        small_model, telemetry=solo, **SMALL_KW
    ).run()
    ck = str(tmp_path / "f.npz")
    sliced = str(tmp_path / "sliced.jsonl")
    polls = [0]

    def hook():
        polls[0] += 1
        return None if polls[0] <= 4 else "suspended"

    r1 = StreamingSimulator(
        small_model, telemetry=sliced, checkpoint_path=ck,
        suspend_hook=hook, **SMALL_KW,
    ).run()
    assert r1.stop_reason == "suspended" and r1.truncated
    assert r1.steps < r_solo.steps
    r2 = StreamingSimulator(
        small_model, telemetry=sliced, checkpoint_path=ck, **SMALL_KW
    ).run(resume=True)
    assert (r2.steps, r2.states_visited, r2.walks, r2.dup_ratio_est) \
        == (
            r_solo.steps, r_solo.states_visited, r_solo.walks,
            r_solo.dup_ratio_est,
        )
    # the sliced stream (suspend + resume) carries the IDENTICAL sim
    # records as the uninterrupted run — the r18 resumability contract
    assert _sim_events(sliced) == _sim_events(solo)
    # resume linking: the resumed header names the prior run's frame
    evs, _ = report.load_events(sliced)
    headers = [e for e in evs if e.get("event") == "run_header"]
    assert headers[-1]["resume"] is True
    assert headers[-1]["resume_of"] == headers[0]["run_id"]
    assert headers[-1]["mode"] == "simulate"


def test_keys_digest_refuses_foreign_frame(small_model, tmp_path):
    ck = str(tmp_path / "f.npz")
    eng = StreamingSimulator(
        small_model, checkpoint_path=ck, checkpoint_every=1, **SMALL_KW
    )
    eng.run()
    # a frame from a different seed's stream must refuse to anchor
    kw = dict(SMALL_KW, seed=99)
    other = StreamingSimulator(
        small_model, checkpoint_path=ck, **kw
    )
    with pytest.raises(ValueError, match="different simulation"):
        other.run(resume=True)


def test_kill_resume_drill_identical_post_resume_stream(
    small_model, tmp_path
):
    """THE acceptance drill: a hard kill mid-stream (PTT_FAULT
    kill@segment:N), then resume — the post-resume stream continues
    the identical walk stream (sim records equal to an uninterrupted
    solo run's, final counters equal)."""
    solo = str(tmp_path / "solo.jsonl")
    r_solo = StreamingSimulator(
        small_model, telemetry=solo, **SMALL_KW
    ).run()
    ck = str(tmp_path / "f.npz")
    stream = str(tmp_path / "killed.jsonl")
    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.sim.engine import StreamingSimulator
c = pe.Constants(message_sent_limit=2, compaction_times_limit=2,
                 num_keys=1, num_values=1, max_crash_times=1,
                 model_producer=True)
StreamingSimulator(CompactionModel(c), n_walkers=128, depth=16,
                   segment_len=4, seed=3, max_steps=128*16*3,
                   profile=None, telemetry={stream!r},
                   checkpoint_path={ck!r}, checkpoint_every=1).run()
"""
    env = dict(os.environ, PTT_FAULT="kill@segment:4",
               JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=300, cwd=ROOT,
    )
    assert p.returncode == 137, (p.returncode, p.stderr[-500:])
    assert os.path.exists(ck)
    killed_events = _sim_events(stream)
    assert killed_events  # progress reached the stream pre-kill
    r2 = StreamingSimulator(
        small_model, telemetry=stream, checkpoint_path=ck, **SMALL_KW
    ).run(resume=True)
    assert (r2.steps, r2.states_visited, r2.walks, r2.dup_ratio_est) \
        == (
            r_solo.steps, r_solo.states_visited, r_solo.walks,
            r_solo.dup_ratio_est,
        )
    assert _sim_events(stream) == _sim_events(solo)
    # both streams are v11-validator-clean
    checker = _load_script("check_telemetry_schema")
    assert checker.validate_stream(stream) == []
    assert checker.validate_stream(solo) == []


# ------------------------------------- published bug oracles, pinned


def test_sim_finds_leak_bug_pinned(shipped_model, tmp_path):
    """The retention-leak bug config (CompactedLedgerLeak, published
    diameter 12) found within a pinned (seed, n_walkers, depth)
    budget; the trace replays state-for-state through the interpreter;
    a deterministic re-run yields the identical discovery."""
    kw = dict(
        n_walkers=256, depth=32, segment_len=16, seed=1, profile=None,
        invariants=("TypeSafe", "CompactedLedgerLeak"),
    )
    st = str(tmp_path / "leak.jsonl")
    r = StreamingSimulator(shipped_model, telemetry=st, **kw).run()
    assert r.violation == "CompactedLedgerLeak"
    assert r.stop_reason == "violation" and not r.truncated
    assert len(r.trace) == 12  # the published shortest-diameter shape
    assert r.verified is True
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )
    r2 = StreamingSimulator(shipped_model, **kw).run()
    assert (r2.violation_walker, r2.violation_step, r2.steps) == (
        r.violation_walker, r.violation_step, r.steps
    )
    assert r2.trace == r.trace and r2.trace_actions == r.trace_actions
    checker = _load_script("check_telemetry_schema")
    assert checker.validate_stream(st) == []


def test_sim_finds_dup_null_key_bug_pinned(shipped_model):
    """The dup-null-key bug config (DuplicateNullKeyMessage, published
    diameter 4) found within a pinned budget, interpreter-replayed."""
    kw = dict(
        n_walkers=256, depth=16, segment_len=8, seed=0, profile=None,
        invariants=("DuplicateNullKeyMessage",),
    )
    r = StreamingSimulator(shipped_model, **kw).run()
    assert r.violation == "DuplicateNullKeyMessage"
    assert len(r.trace) == 4  # the published shortest-diameter shape
    assert r.verified is True
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions,
        "DuplicateNullKeyMessage",
    )
    r2 = StreamingSimulator(shipped_model, **kw).run()
    assert r2.trace == r.trace and r2.trace_actions == r.trace_actions


# ----------------------------------------------- daemon time-slicing

SMALL_COMPACTION_CFG = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 2
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = TRUE
    MaxCrashTimes = 1
    ModelProducer = TRUE
SPECIFICATION Spec
INVARIANTS
"""


def test_daemon_two_job_slice_with_sim_solo_parity(
    small_model, tmp_path
):
    """A simulation job and a BFS job time-slice one device; the sim
    job suspends/resumes at SEGMENT boundaries and finishes with the
    counters of an uninterrupted solo run (`submit --mode simulate`
    acceptance)."""
    from pulsar_tlaplus_tpu.obs.telemetry import Telemetry
    from pulsar_tlaplus_tpu.service.scheduler import (
        CheckerPool,
        Scheduler,
        ServiceConfig,
    )

    cfg = str(tmp_path / "small.cfg")
    with open(cfg, "w") as f:
        f.write(SMALL_COMPACTION_CFG)
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"), slice_s=0.2, sub_batch=64,
        visited_cap=1 << 10, frontier_cap=1 << 8, max_states=1 << 20,
        prewarm_tiers=False, checkpoint_every=1,
    )
    pool = CheckerPool(config)
    tel = Telemetry(str(tmp_path / "service.jsonl"))
    sched = Scheduler(config, pool=pool, telemetry=tel)
    sim_kw = {
        "n_walkers": 128, "depth": 16, "segment_len": 4, "seed": 3,
        "max_steps": 128 * 16 * 6,
    }
    j1 = sched.submit("compaction", cfg, mode="simulate", sim=sim_kw)
    j2 = sched.submit("compaction", cfg, invariants=[])
    sched.run_until_idle()
    tel.close()
    assert j1.state == "done" and j2.state == "done"
    assert j1.suspends >= 1 and j2.suspends >= 1  # genuine slicing
    r_solo = StreamingSimulator(
        small_model, profile=None,
        **{
            "n_walkers": 128, "depth": 16, "segment_len": 4,
            "seed": 3, "max_steps": 128 * 16 * 6,
        },
    ).run()
    assert j1.result["mode"] == "simulate"
    assert j1.result["status"] == "ok"
    assert j1.result["steps"] == r_solo.steps
    assert j1.result["states_visited"] == r_solo.states_visited
    assert j1.result["walks"] == r_solo.walks
    assert j1.result["dup_ratio_est"] == r_solo.dup_ratio_est
    assert j2.result["distinct_states"] == 1654  # the pinned BFS job
    # per-job stream: v11-clean, and its tail exports ptt_sim_*
    checker = _load_script("check_telemetry_schema")
    job_stream = os.path.join(config.jobs_dir, j1.job_id, "events.jsonl")
    assert checker.validate_stream(job_stream) == []
    assert checker.validate_stream(str(tmp_path / "service.jsonl")) == []
    evs, _ = report.load_events(job_stream)
    text = metrics.render_stream_metrics(evs)
    fams, _types = metrics.parse_exposition(text)
    assert fams["ptt_sim_steps_total"][0][1] == r_solo.steps
    assert fams["ptt_sim_walks_total"][0][1] == r_solo.walks
    # every engine run header carries the slice's tenant + mode
    headers = [e for e in evs if e.get("event") == "run_header"]
    assert headers and all(
        h["mode"] == "simulate" and h["tenant"] == "local"
        for h in headers
    )


# ------------------------------------------------- fuzz fast drill


def test_fuzz_fast_drill_pinned_seed():
    """The differential fuzz harness's tier-1 drill: one pinned-seed
    binding per registered spec, device engine vs interpreter — any
    mismatch (counts, diameter, verdict, trace replay) fails."""
    fuzz = _load_script("fuzz")
    records, failures = fuzz.run(seed=0, per_spec=1, log=lambda m: None)
    assert len(records) == 4
    assert failures == [], failures
    # the drill genuinely exercises both verdict classes
    verdicts = {r["device"]["violation"] for r in records}
    assert None in verdicts and len(verdicts) > 1


# --------------------------------------------------- ledger + bench


def test_sim_ledger_gate_pinned_baseline(small_model, tmp_path):
    """The sim tier-1 gate: a fresh deterministic sim run gates clean
    against the committed baseline on steps_per_state; an injected
    walk-stream change fails."""
    from pulsar_tlaplus_tpu import cli

    path = str(tmp_path / "sim_ledger.jsonl")
    shutil.copy(SIM_PINNED, path)
    assert ledger.validate_ledger(path) == []
    # the committed CPU-mesh sim bench artifact (BASELINE.md round 18)
    # ingests cleanly beside the pinned baseline
    rec = ledger.record_from_file(
        os.path.join(ROOT, "BENCH_sim_r18.json")
    )
    assert rec["values"]["walks_per_sec"] > 0
    assert rec["values"]["mode"] == "simulate"
    assert ledger.append(path, [rec]) == 1
    stream = str(tmp_path / "run.jsonl")
    StreamingSimulator(
        small_model, telemetry=stream, **SMALL_KW
    ).run()
    assert cli.main(["ledger", "--ledger", path, "add", stream]) == 0
    keys = list(ledger.SIM_GATE_KEYS)
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.02",
         "--keys"] + keys
    )
    assert rc == 0
    cur = ledger.load(path)[-1]
    assert cur["values"]["steps_per_state"] == pytest.approx(
        ledger.load(SIM_PINNED)[0]["values"]["steps_per_state"]
    )
    bad = dict(cur, values=dict(cur["values"]))
    bad["values"]["steps_per_state"] = (
        cur["values"]["steps_per_state"] * 1.5
    )
    bad["digest"] = ledger._digest(bad["values"])
    ledger.append(path, [bad])
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.02",
         "--keys"] + keys
    )
    assert rc == 1


def test_bench_sim_and_matrix_artifacts_validate(tmp_path, capsys):
    """bench --mode simulate and one --matrix point both emit
    bench_schema-9 artifacts the validator accepts and the ledger
    ingests."""
    # load bench.py from the repo root
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(ROOT, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    checker = _load_script("check_telemetry_schema")
    # simulate mode at a tiny deterministic shape
    args = bench.parse_args(
        [
            "--mode", "simulate", "--walkers", "64", "--depth", "8",
            "--sim-steps", str(64 * 8 * 2),
            "--telemetry-path", str(tmp_path),
        ]
    )
    bench.run_sim_bench(args)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(line)
    assert checker.validate_bench_artifact(d, path="sim-bench") == []
    assert d["mode"] == "simulate"
    assert d["walks_per_sec"] > 0 and d["steps_per_state"] > 0
    rec = ledger.record_from_bench(d, source="sim_bench.json")
    assert rec["values"]["walks_per_sec"] == d["walks_per_sec"]
    # one matrix point, ledger-ingested
    margs = bench.parse_args(
        [
            "--matrix", "--matrix-spec", "subscription",
            "--matrix-limit", "1",
            "--matrix-out", str(tmp_path / "mx"),
            "--matrix-ledger", str(tmp_path / "mx" / "L.jsonl"),
        ]
    )
    bench.run_matrix(margs)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(out)
    assert summary["matrix"], summary
    art = summary["matrix"][0]["artifact"]
    assert checker.validate_bench_artifact(art) == []
    recs = ledger.load(str(tmp_path / "mx" / "L.jsonl"))
    assert len(recs) == 1 and recs[0]["values"]["matrix_spec"] == (
        "subscription"
    )


# --------------------------------------------------- validator gates


def test_validator_rejects_backwards_sim_counters(tmp_path):
    from pulsar_tlaplus_tpu.obs import telemetry as obs

    checker = _load_script("check_telemetry_schema")
    path = str(tmp_path / "torn.jsonl")
    base = {
        "v": obs.SCHEMA_VERSION, "run_id": "r1",
        "event": "sim", "walkers": 8, "violations": 0,
    }
    with open(path, "w") as f:
        f.write(json.dumps(
            {**base, "t": 0.1, "seq": 0, "steps": 100, "states": 108}
        ) + "\n")
        f.write(json.dumps(
            {**base, "t": 0.2, "seq": 1, "steps": 60, "states": 200}
        ) + "\n")
    errs = checker.validate_stream(path)
    assert any("sim.steps went backwards" in e for e in errs)


def test_validator_requires_mode_at_v11(tmp_path):
    from pulsar_tlaplus_tpu.obs import telemetry as obs

    checker = _load_script("check_telemetry_schema")
    path = str(tmp_path / "nomode.jsonl")
    rec = {
        "v": obs.SCHEMA_VERSION, "run_id": "r1", "t": 0.1, "seq": 0,
        "event": "run_header", "engine": "sim", "visited_impl": None,
        "config_sig": "x", "profile_sig": None, "hbm_budget": None,
        "tenant": None,
    }
    with open(path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    errs = checker.validate_stream(path)
    # (v12 additionally requires `warm`, so match the field, not the
    # exact missing-list rendering)
    assert any("missing" in e and "'mode'" in e for e in errs)
    # a v10 record without mode stays clean (FIELD_SINCE gate)
    rec10 = dict(rec, v=10)
    with open(path, "w") as f:
        f.write(json.dumps(rec10) + "\n")
    assert checker.validate_stream(path) == []


def test_bench_schema9_requires_sim_keys():
    checker = _load_script("check_telemetry_schema")
    d = {k: None for k in checker.BENCH_KEYS_V9}
    d.update(bench_schema=9, value=1.0)
    assert checker.validate_bench_artifact(d, path="ok") == []
    del d["walks_per_sec"]
    errs = checker.validate_bench_artifact(d, path="bad")
    assert any("walks_per_sec" in e for e in errs)
    # schema 8 artifacts do NOT need the sim keys (committed history)
    d8 = {k: None for k in checker.BENCH_KEYS_V8}
    d8.update(bench_schema=8, value=1.0)
    assert checker.validate_bench_artifact(d8, path="v8") == []


# ----------------------------------------------------- tuned profile


def test_sim_profile_resolution_and_explicit_wins(
    small_model, tmp_path, monkeypatch
):
    from pulsar_tlaplus_tpu.tune import profiles as tune_profiles

    monkeypatch.setenv("PTT_TUNE_DIR", str(tmp_path))
    sig = tune_profiles.profile_key(
        model=small_model, invariants=("TypeSafe",), engine="sim",
    )
    prof = tune_profiles.build(
        sig=sig, engine="sim",
        backend=tune_profiles.default_backend(),
        knobs={"n_walkers": 512, "segment_len": 8}, spec="compaction",
    )
    tune_profiles.save(prof)
    s = StreamingSimulator(
        small_model, invariants=("TypeSafe",), depth=16
    )
    assert s.profile_sig == sig and s.B == 512 and s.L == 8
    # explicit knobs win over the profile
    s2 = StreamingSimulator(
        small_model, invariants=("TypeSafe",), depth=16, n_walkers=64
    )
    assert s2.B == 64
    # a wrong-engine profile warns-and-ignores
    bad = dict(prof, engine="device_bfs")
    path = tune_profiles.path_for(sig)
    with open(path, "w") as f:
        json.dump(bad, f)
    s3 = StreamingSimulator(
        small_model, invariants=("TypeSafe",), depth=16
    )
    assert s3.profile_sig is None and s3.B == 1024


# ------------------------------------------------------- CLI surface


def test_cli_simulate_subcommand(tmp_path, capsys):
    from pulsar_tlaplus_tpu import cli

    cfg = str(tmp_path / "small.cfg")
    with open(cfg, "w") as f:
        f.write(SMALL_COMPACTION_CFG)
    st = str(tmp_path / "s.jsonl")
    rc = cli.main(
        [
            "simulate", "compaction", "-config", cfg, "-walkers", "64",
            "-depth", "8", "-seed", "5", "-cpu", "-telemetry", st,
            "-no-profile",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "64 walkers of depth 8" in out
    assert "walks/sec" in out
    checker = _load_script("check_telemetry_schema")
    assert checker.validate_stream(st) == []


def test_cli_check_simulate_routes_streaming_engine(
    tmp_path, capsys
):
    from pulsar_tlaplus_tpu import cli

    cfg = str(tmp_path / "small.cfg")
    with open(cfg, "w") as f:
        f.write(SMALL_COMPACTION_CFG)
    tla = os.path.join(ROOT, "specs", "compaction.tla")
    st = str(tmp_path / "s.jsonl")
    rc = cli.main(
        [
            "check", tla, "-config", cfg, "-simulate", "64",
            "-depth", "8", "-sim-seed", "5", "-cpu",
            "-telemetry", st, "-no-profile",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "64 walkers of depth 8" in out
    evs, _ = report.load_events(st)
    hd = report.header(evs)
    assert hd["engine"] == "sim" and hd["mode"] == "simulate"
