"""End-to-end engine differential tests (SURVEY.md §4a/§4b): distinct-state
counts, diameters, invariant verdicts, and counterexample traces must match
the Python oracle exactly — including the published 45,198-state oracle."""

import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS


@pytest.mark.parametrize("dedup", ["hash", "sort"])
@pytest.mark.parametrize("name", sorted(set(SMALL_CONFIGS) - {"shipped"}))
def test_engine_matches_oracle_small(name, dedup):
    c = SMALL_CONFIGS[name]
    want = pe.check(c, invariants=())
    got = Checker(
        CompactionModel(c), invariants=(), frontier_chunk=1024,
        visited_cap=1 << 14, dedup=dedup,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_engine_hash_growth_matches_oracle():
    """Start the hash table tiny so the run forces several rehash-growth
    cycles; counts must still be exact."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = Checker(
        CompactionModel(c), invariants=(), frontier_chunk=128,
        visited_cap=1 << 8, dedup="hash",
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_engine_shipped_cfg_published_count():
    m = CompactionModel(pe.SHIPPED_CFG)
    r = Checker(m, visited_cap=1 << 16).run()
    assert r.distinct_states == 45198  # compaction.tla:23
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock


def test_engine_leak_counterexample():
    from tests.helpers import assert_valid_counterexample

    m = CompactionModel(pe.SHIPPED_CFG)
    r = Checker(
        m, invariants=("CompactedLedgerLeak",), visited_cap=1 << 16
    ).run()
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12  # same depth as the oracle's shortest trace
    assert len(r.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_engine_duplicate_null_key_counterexample():
    from tests.helpers import assert_valid_counterexample

    m = CompactionModel(pe.SHIPPED_CFG)
    r = Checker(
        m, invariants=("DuplicateNullKeyMessage",), visited_cap=1 << 16
    ).run()
    assert r.violation == "DuplicateNullKeyMessage"
    assert r.diameter == 4
    assert len(r.trace) == 4
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "DuplicateNullKeyMessage"
    )
