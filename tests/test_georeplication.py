"""Differential tests for the georeplication spec
(specs/georeplication.tla): compiled TPU model vs the generic interpreter
on the same .tla source, plus the safety+liveness+simulation trio this
spec headlines."""

import os

import jax
import jax.numpy as jnp
import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker
from pulsar_tlaplus_tpu.frontend.interp import Spec, install_defs
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.models.georeplication import (
    GeoConstants,
    GeoreplicationModel,
)
from tests.helpers import needs_shard_map

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "specs",
    "georeplication.tla",
)

CONFIGS = {
    "shipped": GeoConstants(),  # 3 clusters, 1 msg each, 1 crash
    "two_clusters": GeoConstants(
        num_clusters=2, publish_limit=2, max_replicator_crashes=1
    ),
    "no_crash": GeoConstants(max_replicator_crashes=0),
}

SAFE = ("TypeOK", "CursorWithinWatermark", "NoPhantomMessages")


@pytest.fixture(scope="module")
def module():
    return parse_file(SPEC_PATH)


def spec_for(module, c: GeoConstants) -> Spec:
    return Spec(
        module,
        {
            "NumClusters": c.num_clusters,
            "PublishLimit": c.publish_limit,
            "MaxReplicatorCrashes": c.max_replicator_crashes,
        },
    )


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_counts_and_verdicts_match_interpreter(module, name):
    c = CONFIGS[name]
    spec = spec_for(module, c)
    ri = InterpChecker(spec, invariants=SAFE).run()
    m = GeoreplicationModel(c)
    rm = Checker(m, invariants=SAFE, frontier_chunk=512).run()
    assert ri.violation is None and rm.violation is None
    assert not ri.deadlock and not rm.deadlock
    assert rm.distinct_states == ri.distinct_states
    assert rm.diameter == ri.diameter
    assert rm.level_sizes == ri.level_sizes


def test_exact_state_set_matches_interpreter(module):
    c = CONFIGS["two_clusters"]
    spec = spec_for(module, c)
    install_defs(spec)
    expected = set(spec.initial_states())
    frontier = list(expected)
    while frontier:
        new = []
        for s in frontier:
            for _lab, t in spec.successors(s):
                if t not in expected:
                    expected.add(t)
                    new.append(t)
        frontier = new
    m = GeoreplicationModel(c)
    ck = Checker(m, frontier_chunk=512, keep_log=True)
    ck.run()
    packed = ck.last_run_state.log.packed_matrix()
    unpack = jax.jit(m.layout.unpack)
    got = {m.to_interp_state(unpack(jnp.asarray(row))) for row in packed}
    assert got == expected


def test_golden_bug_duplicate_delivery(module):
    """NoDuplicateDelivery is violated at MaxReplicatorCrashes >= 1 with
    the shortest failover-redelivery trace, identical on both paths, and
    HOLDS at zero crashes (exactly-once without failover)."""
    m_ok = GeoreplicationModel(CONFIGS["no_crash"])
    r_ok = Checker(m_ok, invariants=("NoDuplicateDelivery",)).run()
    assert r_ok.violation is None

    c = CONFIGS["shipped"]
    spec = spec_for(module, c)
    install_defs(spec)
    ri = InterpChecker(spec, invariants=("NoDuplicateDelivery",)).run()
    m = GeoreplicationModel(c)
    rm = Checker(m, invariants=("NoDuplicateDelivery",)).run()
    assert ri.violation == rm.violation == "NoDuplicateDelivery"
    assert len(ri.trace) == len(rm.trace) == 5
    assert rm.trace_actions == [
        "Publish", "Replicate", "ReplicatorCrash", "Replicate",
    ]
    # replay the compiled trace on interpreter semantics
    rendered = lambda t: m.to_pystate(m.from_interp_state(t))
    cur = spec.initial_states()[0]
    assert rendered(cur) == rm.trace[0]
    for act, want in zip(rm.trace_actions, rm.trace[1:]):
        nxt = [
            t for lab, t in spec.successors(cur)
            if lab == act and rendered(t) == want
        ]
        assert nxt, (act, want)
        cur = nxt[0]


@needs_shard_map
def test_sharded_counts_match():
    from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker

    c = CONFIGS["shipped"]
    m = GeoreplicationModel(c)
    base = Checker(m, frontier_chunk=512).run()
    for nd in (2, 8):
        r = ShardedChecker(
            m, n_devices=nd, frontier_chunk=128, visited_cap=1 << 12
        ).run()
        assert r.distinct_states == base.distinct_states, nd
        assert r.diameter == base.diameter


def test_liveness_termination():
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    m = GeoreplicationModel(CONFIGS["two_clusters"])
    r = LivenessChecker(m, goal="Termination", fairness="wf_next").run()
    assert r.holds, r.reason
    r2 = LivenessChecker(m, goal="Termination", fairness="none").run()
    assert not r2.holds


# ---- pinned oracle counts (r15, scenario diversity) -----------------
# Georeplication becomes the THIRD exact-parity pinned workload beside
# compaction (45,198 / 253,361) and bookkeeper (297 / 2,257): the
# shipped binding (specs/georeplication.cfg — 3 clusters, 1 msg, 1
# crash) pins 6,400 states / diameter 18 on the interpreter AND the
# device engine, making it a tuning target and a daemon registry
# workload with a ground truth.  Derived from the interpreter BFS on
# specs/georeplication.tla; the smaller two_clusters binding (460 /
# 14) re-derives inline as the cheap cross-check.

SHIPPED_STATES, SHIPPED_DIAMETER = 6400, 18   # specs/georeplication.cfg
TWO_CLUSTERS_STATES, TWO_CLUSTERS_DIAMETER = 460, 14


def test_shipped_cfg_pinned_oracle_count(module):
    """Interpreter, host engine, and device engine all reproduce the
    pinned shipped-binding count — the exact-parity contract the
    other two registry workloads already carry."""
    c = CONFIGS["shipped"]
    ri = InterpChecker(spec_for(module, c)).run()
    assert (ri.distinct_states, ri.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    rh = Checker(GeoreplicationModel(c), frontier_chunk=512).run()
    assert (rh.distinct_states, rh.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    rd = DeviceChecker(
        GeoreplicationModel(c), sub_batch=512, visited_cap=1 << 13,
        frontier_cap=1 << 11,
    ).run()
    assert (rd.distinct_states, rd.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    assert rd.violation is None and not rd.deadlock


def test_two_clusters_pinned_oracle_count(module):
    """The cheap binding's pinned count (re-derived on the
    interpreter + pinned on the device engine)."""
    c = CONFIGS["two_clusters"]
    ri = InterpChecker(spec_for(module, c)).run()
    assert (ri.distinct_states, ri.diameter) == (
        TWO_CLUSTERS_STATES, TWO_CLUSTERS_DIAMETER,
    )
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    rd = DeviceChecker(
        GeoreplicationModel(c), sub_batch=256, visited_cap=1 << 11,
        frontier_cap=1 << 9,
    ).run()
    assert (rd.distinct_states, rd.diameter) == (
        TWO_CLUSTERS_STATES, TWO_CLUSTERS_DIAMETER,
    )


def test_simulation_finds_duplicate():
    from pulsar_tlaplus_tpu.engine.simulate import Simulator

    m = GeoreplicationModel(CONFIGS["shipped"])
    sres = Simulator(
        m,
        invariants=("NoDuplicateDelivery",),
        n_walkers=1024,
        depth=24,
        seed=2,
    ).run()
    assert sres.violation == "NoDuplicateDelivery"
    final = sres.trace[-1]
    assert "{1" in final["duplicated"] or "{2" in final["duplicated"]
    for st in sres.trace[:-1]:
        assert "{1" not in st["duplicated"] and "{2" not in st["duplicated"]
