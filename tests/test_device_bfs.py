"""Differential tests for the device-resident engine (engine/device_bfs.py):
must match the Python oracle exactly on counts, diameters, verdicts, and
produce replayable counterexample traces — same bar as the round-1 engine
(SURVEY.md §4a/§4b), plus growth/truncation behaviors specific to the
bound-tracking driver."""

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample


@pytest.mark.parametrize("name", sorted(set(SMALL_CONFIGS) - {"shipped"}))
def test_device_engine_matches_oracle_small(name):
    c = SMALL_CONFIGS[name]
    want = pe.check(c, invariants=())
    got = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=256,
        visited_cap=1 << 12, frontier_cap=1 << 12,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_device_engine_growth_matches_oracle():
    """Start every capacity tiny so the run forces visited + frontier
    growth (and the mid-level sync path); counts must still be exact."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=64,
        visited_cap=1 << 6, frontier_cap=1 << 6, group=2,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_device_engine_shipped_cfg_published_count():
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15
    ).run()
    assert r.distinct_states == 45198  # compaction.tla:23
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock


def test_device_engine_leak_counterexample():
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, invariants=("CompactedLedgerLeak",), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    ).run()
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12  # oracle's shortest-trace depth
    assert len(r.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_device_engine_duplicate_null_key_counterexample():
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, invariants=("DuplicateNullKeyMessage",), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    ).run()
    assert r.violation == "DuplicateNullKeyMessage"
    assert r.diameter == 4
    assert len(r.trace) == 4
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "DuplicateNullKeyMessage"
    )


def test_device_engine_host_seeded_matches_oracle():
    """A host-enumerated BFS prefix (warm start) must not change counts,
    diameter, or verdicts; the handoff level structure must line up."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    m = CompactionModel(c)
    seed = m.host_seed(max_level_states=40, max_total=120)
    assert len(seed[3]) > 1  # actually seeds multiple levels
    got = DeviceChecker(
        m, invariants=(), sub_batch=64, visited_cap=1 << 10,
        frontier_cap=1 << 10,
    ).run(seed=seed)
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_device_engine_host_seeded_violation_trace():
    """A violation discovered after the seeded prefix must replay a
    valid counterexample THROUGH the prefix (seed parents/lanes exact)."""
    m = CompactionModel(pe.SHIPPED_CFG)
    seed = m.host_seed(max_level_states=3000, max_total=5000)
    assert len(seed[3]) > 2
    r = DeviceChecker(
        m, invariants=("CompactedLedgerLeak",), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    ).run(seed=seed)
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12
    assert len(r.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_device_engine_host_seeded_violation_inside_seed():
    """An invariant violated by a state inside the seed prefix is still
    reported (the seed pipeline fuses the same invariant checks), and
    the diameter is the violation's level even when the seed runs much
    deeper than the violating state."""
    m = CompactionModel(pe.SHIPPED_CFG)
    seed = m.host_seed(max_level_states=12000, max_total=20000)
    assert len(seed[3]) > 4  # seed strictly deeper than the depth-4 bug
    r = DeviceChecker(
        m, invariants=("DuplicateNullKeyMessage",), sub_batch=2048,
        visited_cap=1 << 16, frontier_cap=1 << 15,
    ).run(seed=seed)
    assert r.violation == "DuplicateNullKeyMessage"
    assert r.diameter == 4  # depth-4 bug: inside the seeded prefix
    assert len(r.trace) == 4


def test_device_engine_append_chunking_matches_oracle():
    """Force the chunked append scan (C > 1) with an append_chunk that
    does NOT divide ACAP, so the scan's padded tail window is exercised
    — a clamped payload slice here would silently corrupt the row store
    (round-3 review regression)."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    m = CompactionModel(c)
    assert (64 * m.A) % 96  # ACAP not a multiple -> pad path taken
    got = DeviceChecker(
        m, invariants=(), sub_batch=64, visited_cap=1 << 10,
        frontier_cap=1 << 10, append_chunk=96, flush_factor=1,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_device_engine_flush_factor_matches_oracle():
    """Accumulating several expand windows per flush (the round-3
    amortization) must not change counts, diameter, or verdicts."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=128,
        visited_cap=1 << 10, frontier_cap=1 << 10, flush_factor=4,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_device_engine_full_cfg_published_count():
    """The second published oracle (compaction.tla:23): producer
    modeled, RetainNullKey=FALSE — 253,361 distinct states, diameter 23
    — pinned on the TPU device engine itself (VERDICT r2 #7; round 2
    pinned it only on the Python oracle)."""
    import dataclasses

    c = dataclasses.replace(
        pe.SHIPPED_CFG, model_producer=True, retain_null_key=False
    )
    r = DeviceChecker(
        CompactionModel(c), invariants=(), sub_batch=4096,
        visited_cap=1 << 18, frontier_cap=1 << 17, flush_factor=2,
    ).run()
    assert r.distinct_states == 253361
    assert r.diameter == 23
    assert r.violation is None and not r.deadlock


def test_device_engine_max_states_truncation():
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    r = DeviceChecker(
        m, invariants=(), sub_batch=64, visited_cap=1 << 10,
        frontier_cap=1 << 10, max_states=40,
    ).run()
    assert r.truncated
    assert r.distinct_states <= 40 + 64 * m.A


# ---- frontier-window row store (round 5, VERDICT r4 #2) --------------


def test_frontier_window_matches_oracle():
    """rows_window="frontier" with a window far smaller than the state
    space: every level boundary slides the frontier to offset 0 and
    drops older rows; counts/diameter must still be exact."""
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, sub_batch=256, visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 13,
    ).run()
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock and not r.truncated


def test_frontier_window_violation_trace():
    """Counterexample traces never needed rows: a violation found many
    shifts deep must still replay exactly from the parent/lane logs."""
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, invariants=("CompactedLedgerLeak",), sub_batch=256,
        visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 13,
    ).run()
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12
    assert len(r.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_frontier_window_host_seeded_matches_oracle():
    """Seed prefix + frontier window: the first boundary shift drops the
    seed levels' rows; counts must be unchanged."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    m = CompactionModel(c)
    seed = m.host_seed(max_level_states=40, max_total=120)
    got = DeviceChecker(
        m, invariants=(), sub_batch=64, visited_cap=1 << 10,
        rows_window="frontier", row_cap_states=1 << 11,
    ).run(seed=seed)
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_frontier_window_exhaustion_stops_honestly():
    """A window too small for a mid-BFS level: the run keeps deduping/
    counting to the level boundary, then stops with stop_reason
    "row_window" instead of corrupting rows or crashing."""
    m = CompactionModel(pe.SHIPPED_CFG)
    r = DeviceChecker(
        m, sub_batch=64, visited_cap=1 << 16,
        rows_window="frontier", row_cap_states=1 << 10,
    ).run()
    assert r.truncated
    assert r.stop_reason == "row_window"
    assert 0 < r.distinct_states < 45198
