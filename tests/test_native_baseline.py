"""The native (C++) TLC-class baseline checker must agree with the
published state-space oracles — it exists to make the BASELINE.md
comparison honest (BASELINE.md round-3; /root/reference/compaction.tla:23),
so its semantics are pinned against the same counts as every engine."""

import pytest

from pulsar_tlaplus_tpu import native
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, needs_native_binary

# every test here shells out to the committed baseline binary; where
# the environment cannot run it (container glibc older than the build
# host's) the whole module SKIPS — same regime as needs_shard_map
pytestmark = needs_native_binary


def _run(c, budget_s=300.0):
    return native.run_baseline(
        c.message_sent_limit, c.num_keys, c.num_values,
        c.compaction_times_limit, c.max_crash_times, c.model_producer,
        c.retain_null_key, budget_s, table_log2=22,
    )


def test_native_baseline_shipped_cfg_published_count():
    r = _run(pe.SHIPPED_CFG)
    assert not r["truncated"] and not r["violated"]
    assert r["distinct_states"] == 45198  # compaction.tla:23
    assert r["levels"] == 20


def test_native_baseline_full_cfg_published_count():
    """Producer modeled, RetainNullKey=FALSE: the 253,361-state /
    diameter-23 oracle (compaction.tla:23)."""
    r = native.run_baseline(
        3, 2, 2, 3, 1, True, False, 300.0, table_log2=22
    )
    assert not r["truncated"] and not r["violated"]
    assert r["distinct_states"] == 253361
    assert r["levels"] == 23


@pytest.mark.parametrize("name", ["producer_on", "two_crashes", "no_retain"])
def test_native_baseline_matches_oracle_small(name):
    c = SMALL_CONFIGS[name]
    want = pe.check(c, invariants=())
    r = _run(c)
    assert not r["truncated"] and not r["violated"]
    assert r["distinct_states"] == want.distinct_states
    assert r["levels"] == want.diameter
