"""Generic interpreter-backed checker tests (engine/interp_check.py):
the any-spec fallback path must agree exactly with the pyeval oracle and
with the compiled TPU path, and the CLI must route unknown modules (or
``-interp``) through it."""

import subprocess
import sys

import pytest

from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker, format_value
from pulsar_tlaplus_tpu.frontend.interp import FDict, MV, Spec
from pulsar_tlaplus_tpu.frontend.loader import compaction_constants
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.ref import pyeval
from tests.helpers import SMALL_CONFIGS

from tests.helpers import REFERENCE_TLA  # specs/ first, /root/reference fallback

# compaction_times_limit=3 makes CompactedLedgerLeak violable (needs three
# live ledger slots; same config as test_frontend's bug repro).
LEAK_CFG = pyeval.Constants(
    message_sent_limit=2,
    compaction_times_limit=3,
    num_keys=1,
    num_values=1,
    max_crash_times=1,
    model_producer=True,
)


@pytest.fixture(scope="module")
def module():
    return parse_file(REFERENCE_TLA)


@pytest.mark.parametrize("name", ["producer_on", "two_crashes"])
def test_counts_match_oracle(module, name):
    c = SMALL_CONFIGS[name]
    spec = Spec(module, compaction_constants(c))
    r = InterpChecker(spec, invariants=("TypeSafe",)).run()
    o = pyeval.check(c, invariants=("TypeSafe",))
    assert r.violation is None and not r.deadlock
    assert r.distinct_states == o.distinct_states
    assert r.diameter == o.diameter


def test_violation_trace_matches_oracle_depth(module):
    spec = Spec(module, compaction_constants(LEAK_CFG))
    r = InterpChecker(spec, invariants=("CompactedLedgerLeak",)).run()
    o = pyeval.check(LEAK_CFG, invariants=("CompactedLedgerLeak",))
    assert r.violation == "CompactedLedgerLeak" == o.violation
    assert len(r.trace) == len(o.trace)  # same shortest-counterexample depth
    assert r.trace_actions[-1] == "CompactorPhaseTwoWrite"
    # rendered states carry all 9 variables
    assert set(r.trace[0]) == {
        "messages", "compactedLedgers", "cursor", "compactorState",
        "phaseOneResult", "compactionHorizon", "compactedTopicContext",
        "crashTimes", "consumeTimes",
    }


def test_unknown_invariant_rejected(module):
    spec = Spec(module, compaction_constants(SMALL_CONFIGS["producer_on"]))
    with pytest.raises(ValueError, match="NoSuchInvariant"):
        InterpChecker(spec, invariants=("NoSuchInvariant",))


def test_format_value_tla_syntax():
    assert format_value(True) == "TRUE"
    assert format_value((1, 2)) == "<<1, 2>>"
    assert format_value(MV("Nil")) == "Nil"
    assert format_value(frozenset({2, 1})) == "{1, 2}"
    assert format_value(FDict({"a": 1})) == "[a |-> 1]"
    assert format_value(FDict({2: True})) == "(2 :> TRUE)"


CFG_SMALL = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 0
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = FALSE
    MaxCrashTimes = 1
    ModelProducer = TRUE
CONSTANTS
    Nil = Nil
    Compactor_In_PhaseOne = Compactor_In_PhaseOne
    Compactor_In_PhaseTwoWrite = Compactor_In_PhaseTwoWrite
    Compactor_In_PhaseTwoUpdateContext = Compactor_In_PhaseTwoUpdateContext
    Compactor_In_PhaseTwoUpdateHorizon = Compactor_In_PhaseTwoUpdateHorizon
    Compactor_In_PhaseTwoPersistCusror = Compactor_In_PhaseTwoPersistCusror
    Compactor_In_PhaseTwoDeleteLedger = Compactor_In_PhaseTwoDeleteLedger
SPECIFICATION Spec
INVARIANTS
    TypeSafe
    CompactionHorizonCorrectness
"""


def test_cli_interp_path(tmp_path):
    cfg = tmp_path / "small.cfg"
    cfg.write_text(CFG_SMALL)
    out = subprocess.run(
        [
            sys.executable, "-m", "pulsar_tlaplus_tpu.cli", "check",
            REFERENCE_TLA, "-config", str(cfg), "-interp",
        ],
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    assert "via the generic interpreter" in out.stdout
    assert "1566 distinct states found" in out.stdout
    assert "diameter) 16" in out.stdout
