"""Fleet-tier tests (ISSUE 16, ``pulsar_tlaplus_tpu/fleet/``).

The acceptance bar (docs/fleet.md):

- a 2-backend fleet behind one dispatcher routes submits by live
  signal (sticky where warm locality pays), every result
  state-for-state equal to a solo run of the same spec + .cfg;
- a truncated job's warm artifact replicates to the NON-owning
  backend via the sieve handshake, and a widened submit landing there
  warm-continues from the replicated artifact;
- the failover drill (scripts/chaos.py ``--fleet``): the owning
  backend killed mid-job, its queued job resubmitted elsewhere
  through ``submit_id`` dedup, the running job marked ``lost``, and
  the widened resubmit solo-exact on the survivor;
- a warm submit THROUGH the dispatcher pays zero jit compiles — the
  routing hop must not cost a recompile;
- the warm store survives hammering concurrent writers (the
  fleet-era multi-writer mix: saves, peer-push installs, LRU cap).

The slow-marked load test runs a 3-backend mixed-spec batch and emits
a current-rev bench artifact the validator and ledger accept.
"""

import json
import os
import threading
import time

import pytest

from pulsar_tlaplus_tpu.fleet.dispatcher import (
    FleetConfig,
    FleetDispatcher,
)
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.client import ServiceClient, ServiceError
from pulsar_tlaplus_tpu.service.scheduler import CheckerPool
from pulsar_tlaplus_tpu.service.server import ServiceDaemon
from pulsar_tlaplus_tpu.warm import store as warmstore

# the service-layer harness is the contract here too: same geometry,
# same cfg bindings, same solo baselines, same parity assertion
from tests.test_service import (  # noqa: F401  (fixtures by name)
    BK_CFG,
    GEOM,
    _config,
    _load_script,
    assert_result_matches_solo,
    cfg_dir,
    checker_mod,
    pool,
    solo_bk_crash2,
    solo_compaction,
)


class _Result:
    """Adapter: assert_result_matches_solo wants a job-shaped object
    with ``.result``/``.state``/``.error`` — wire replies are dicts."""

    def __init__(self, reply):
        self.result = reply.get("result")
        self.state = reply.get("state")
        self.error = reply.get("error")


@pytest.fixture(scope="module")
def fleet(tmp_path_factory, pool):
    """One 2-backend fleet for the module: backend0 holds the shared
    module pool (the warmed one), backend1 compiles its own — exactly
    the heterogeneous-warmth shape routing must handle."""
    root = tmp_path_factory.mktemp("fleet")
    configs = [
        _config(root / "b0", slice_s=0.3),
        _config(root / "b1", slice_s=0.3),
    ]
    daemons = [
        ServiceDaemon(configs[0], pool=pool),
        ServiceDaemon(configs[1]),
    ]
    for d in daemons:
        d.start()
    fc = FleetConfig(
        state_dir=str(root / "disp"),
        backends=tuple(c.socket_path for c in configs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    cl = ServiceClient(fc.socket_path, timeout=240.0)
    state = dict(
        daemons=daemons, configs=configs, disp=disp, client=cl,
        addrs=[c.socket_path for c in configs],
    )
    try:
        yield state
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()


# ---- 2-backend routing smoke (tier-1 acceptance) --------------------


def test_fleet_routing_smoke_solo_parity(
    fleet, cfg_dir, solo_compaction, solo_bk_crash2
):
    """Two specs through ONE dispatcher endpoint: every reply carries
    the chosen backend, the routing table scopes listings, and both
    results are state-for-state solo-exact — the hop through the
    dispatcher must be invisible to the verdict."""
    cl = fleet["client"]
    pong = cl.ping()
    assert pong["fleet"] is True
    assert set(pong["backends"]) == set(fleet["addrs"])
    assert all(s == "up" for s in pong["backends"].values())

    r1 = cl.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], full=True,
    )
    r2 = cl.submit(
        "bookkeeper", str(cfg_dir / "bk_crash2.cfg"), full=True,
    )
    assert r1["backend"] in fleet["addrs"]
    assert r2["backend"] in fleet["addrs"]

    w1 = cl.wait(r1["job_id"], timeout=600.0)
    w2 = cl.wait(r2["job_id"], timeout=600.0)
    assert w1["state"] == jobmod.DONE
    assert w2["state"] == jobmod.DONE
    assert_result_matches_solo(_Result(w1), solo_compaction)
    assert_result_matches_solo(_Result(w2), solo_bk_crash2)
    # result replies are proxied — they name the owning backend too
    assert w1["backend"] == r1["backend"]
    assert w2["backend"] == r2["backend"]

    # the dispatcher's listing comes from its OWN routing table
    jobs = {j["job_id"]: j for j in cl.status()}
    assert {r1["job_id"], r2["job_id"]} <= set(jobs)
    assert jobs[r1["job_id"]]["backend"] == r1["backend"]
    assert jobs[r1["job_id"]]["state"] == jobmod.DONE

    # routing decisions surfaced as ptt_fleet_* metrics
    snap = fleet["disp"].metrics_snapshot()
    reasons = {why for (_a, why) in snap["routes"]}
    assert reasons <= {"sticky", "least_loaded", "only_backend"}
    assert sum(snap["routes"].values()) >= 2
    text = cl.metrics()
    assert "ptt_fleet_backends" in text
    assert "ptt_fleet_routes_total" in text

    # errors proxy typed: a bad spec fails eagerly through the hop
    with pytest.raises(ServiceError, match="not in the compiled"):
        cl.submit("no_such_spec", str(cfg_dir / "bk_crash2.cfg"))
    with pytest.raises(ServiceError, match="not routed through"):
        cl.status("nope")


# ---- warm replication: the hit lands on the NON-owning backend ------


def test_fleet_replicates_warm_artifact_to_peer(
    fleet, cfg_dir, solo_compaction
):
    """A truncated probe's artifact must cross the fleet via the sieve
    so a widened submit landing on the OTHER backend warm-continues
    from the replicated frame — warm locality without ownership."""
    cl = fleet["client"]
    probe = cl.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], max_states=600,
        submit_id="fleet-repl-probe", full=True,
    )
    owner = probe["backend"]
    peer_i = 1 - fleet["addrs"].index(owner)
    peer_daemon = fleet["daemons"][peer_i]
    done = cl.wait(probe["job_id"], timeout=600.0)
    assert done["result"]["status"] == "truncated"

    # the health thread notices the terminal job and replicates; the
    # peer's OWN store must end up holding the truncated artifact
    deadline = time.monotonic() + 120.0
    man = None
    while man is None:
        for _adir, m in peer_daemon.sched.warm_store.manifests():
            if m.get("spec") == "compaction" and m.get("truncated"):
                man = m
        if man is None:
            assert time.monotonic() < deadline, (
                "replication never reached the peer store"
            )
            time.sleep(0.1)
    snap = fleet["disp"].metrics_snapshot()
    assert sum(snap["repl_blobs"].values()) >= 1
    assert sum(snap["repl_bytes"].values()) >= 1

    # widened submit sent DIRECTLY to the peer (bypassing routing
    # stickiness): it never owned the probe, so a warm start here is
    # proof the replicated artifact is genuinely usable
    peer_cl = ServiceClient(
        fleet["configs"][peer_i].socket_path, timeout=240.0
    )
    wide = peer_cl.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], full=True,
    )
    w = peer_cl.wait(wide["job_id"], timeout=600.0)
    assert w["state"] == jobmod.DONE
    assert w["result"]["warm"] in ("continue", "reseed")
    assert_result_matches_solo(_Result(w), solo_compaction)


# ---- failover: the chaos drill is the pinned acceptance criterion ---


def test_fleet_failover_drill_solo_exact(
    tmp_path, pool, solo_compaction
):
    """The ISSUE-16 acceptance drill, in-process: kill the owning
    backend mid-job; the queued job is resubmitted by the dispatcher
    through ``submit_id`` dedup, the running job is marked ``lost``,
    and the widened resubmit warm-starts from the REPLICATED artifact
    on the survivor — state-for-state solo-exact."""
    chaos = _load_script("chaos")
    out = chaos.run_fleet_chaos(
        str(tmp_path / "drill"),
        geom=GEOM,
        solo=solo_compaction,
        pool=pool,
        log=lambda m: None,
    )
    assert out["resubmitted"] == 1
    assert out["replicated_wire_bytes"] > 0
    assert out["warm_mode"] in ("continue", "reseed")


# ---- ledger gate: committed mini fleet-bench baseline (r21) ---------

FLEET_PINNED = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "data", "mini_bench_fleet.jsonl",
)

# the baseline's identity strings: the ledger groups records by a hash
# of the metric (config_key), so the committed baseline and the fresh
# run must agree byte-for-byte or the gate finds no baseline at all
FLEET_GATE_METRIC = (
    "fleet replication economy: truncated small-compaction artifact "
    "shipped to the non-owning peer (2 backends)"
)
FLEET_GATE_ENGINE = "fleet r21 (2 serve backends, sieve replication)"


def build_fleet_gate_artifact(root, pool, cfg_path):
    """The mini fleet bench the tier-1 gate pins: a 2-backend fleet
    ships the truncated small-compaction probe's artifact to the peer
    and reports the zlib wire bytes — codec-deterministic for the
    fixed workload (``ledger.FLEET_GATE_KEYS``, lower is better).
    Doubles as the generator for ``tests/data/mini_bench_fleet.jsonl``
    (write ``ledger.record_from_bench(artifact, source=...)`` as one
    JSON line)."""
    import importlib.util

    configs = [
        _config(os.path.join(str(root), "b0"), slice_s=0.3),
        _config(os.path.join(str(root), "b1"), slice_s=0.3),
    ]
    daemons = [
        ServiceDaemon(configs[0], pool=pool),
        ServiceDaemon(configs[1]),
    ]
    for d in daemons:
        d.start()
    disp = FleetDispatcher(FleetConfig(
        state_dir=os.path.join(str(root), "disp"),
        backends=tuple(c.socket_path for c in configs),
        health_interval_s=0.2,
    ))
    disp.start()
    try:
        cl = ServiceClient(disp.config.socket_path, timeout=240.0)
        probe = cl.submit(
            "compaction", cfg_path, invariants=[], max_states=600,
            submit_id="fleet-gate-probe", full=True,
        )
        done = cl.wait(probe["job_id"], timeout=600.0)
        assert done["result"]["status"] == "truncated"
        # both backends idle at submit time -> the tie breaks to b0
        # (the warmed pool); the peer only installs, never compiles
        wire = 0
        deadline = time.monotonic() + 120.0
        while not wire:
            snap = disp.metrics_snapshot()
            wire = int(sum(snap["repl_bytes"].values()))
            if not wire:
                assert time.monotonic() < deadline, (
                    "replication never shipped"
                )
                time.sleep(0.1)
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__
            ))), "bench.py",
        )
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    d = bench.artifact_skeleton()
    d.update(
        metric=FLEET_GATE_METRIC,
        value=wire,
        unit="bytes",
        mode="fleet",
        engine=FLEET_GATE_ENGINE,
        stop_reason="done",
        truncated=False,
        fleet_backends=2,
        fleet_replicated_wire_bytes=wire,
    )
    return d


def test_fleet_ledger_gate_pinned_baseline(
    tmp_path, pool, cfg_dir, checker_mod
):
    """The fleet tier-1 gate (r21 satellite): a fresh replication run
    gates clean against the committed mini fleet-bench baseline on
    ``fleet_replicated_wire_bytes``; an injected codec regression
    (half again the bytes for the same warm coverage) fails."""
    import shutil

    from pulsar_tlaplus_tpu import cli
    from pulsar_tlaplus_tpu.obs import ledger as ledgermod

    path = str(tmp_path / "fleet_ledger.jsonl")
    shutil.copy(FLEET_PINNED, path)
    assert ledgermod.validate_ledger(path) == []

    art = build_fleet_gate_artifact(
        tmp_path / "gate", pool,
        str(cfg_dir / "small_compaction.cfg"),
    )
    assert art["bench_schema"] == 12  # current rev (r23 bump)
    errs = checker_mod.validate_bench_artifact(art, "fleet-gate")
    assert errs == []
    apath = str(tmp_path / "fleet_gate.json")
    with open(apath, "w") as f:
        f.write(json.dumps(art))
    assert cli.main(["ledger", "--ledger", path, "add", apath]) == 0
    keys = list(ledgermod.FLEET_GATE_KEYS)
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.05",
         "--keys"] + keys
    )
    assert rc == 0
    # the two records genuinely grouped (same config key), so the
    # pass above was a real comparison, not a missing-baseline skip
    recs = ledgermod.load(path)
    assert recs[-1]["key"] == recs[0]["key"]
    bad = dict(recs[-1], values=dict(recs[-1]["values"]))
    bad["values"]["fleet_replicated_wire_bytes"] = int(
        recs[-1]["values"]["fleet_replicated_wire_bytes"] * 1.5
    )
    bad["digest"] = ledgermod._digest(bad["values"])
    ledgermod.append(path, [bad])
    rc = cli.main(
        ["ledger", "--ledger", path, "gate", "--threshold", "0.05",
         "--keys"] + keys
    )
    assert rc == 1


# ---- zero-compile warm submit THROUGH the dispatcher ----------------


def test_fleet_warm_submit_pays_zero_jit_compiles(tmp_path):
    """The resident-fleet payoff: after prewarm, a submit routed
    through the dispatcher adds ZERO jitted programs — the same
    ``set(ck._jits)`` harness as the service-layer proof, with the
    routing hop in the loop."""
    config = _config(
        tmp_path / "b0",
        visited_cap=1 << 8, frontier_cap=1 << 7, max_states=1 << 12,
    )
    own_pool = CheckerPool(config)
    key, _compile_s = own_pool.warm("bookkeeper", BK_CFG)
    ck = own_pool._checkers[key]
    assert ck._jits  # genuinely warmed
    keys_before = set(ck._jits)

    daemon = ServiceDaemon(config, pool=own_pool)
    daemon.start()
    disp = FleetDispatcher(FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=(config.socket_path,),
        health_interval_s=0.2,
    ))
    disp.start()
    try:
        cl = ServiceClient(disp.config.socket_path, timeout=240.0)
        r = cl.submit("bookkeeper", BK_CFG, full=True)
        assert r["backend"] == config.socket_path
        w = cl.wait(r["job_id"], timeout=600.0)
        assert w["state"] == jobmod.DONE
        assert w["result"]["status"] == "ok"
        assert w["result"]["distinct_states"] == 297  # pinned oracle
        assert set(ck._jits) == keys_before  # ZERO post-warm compiles
    finally:
        disp.shutdown()
        daemon.shutdown()


# ---- warm store: hammering concurrent writers (satellite 6) ---------


def _mini_artifact(tmp_path, i):
    """A tiny self-consistent (frame, manifest) pair for store ops."""
    frame = str(tmp_path / f"frame{i}.npz")
    with open(frame, "wb") as f:
        f.write(os.urandom(256) + bytes([i % 256]) * 64)
    manifest = {
        "spec": "compaction",
        "config_sig": f"sig-{i}",
        "module_digest": "d" * 16,
        "bindings": {},
        "invariants": [],
        "distinct_states": 10 + i,
        "levels": 3,
        "truncated": True,
    }
    return frame, manifest


def test_warm_store_survives_hammering_writers(tmp_path):
    """The fleet made the warm dir genuinely multi-writer: post-run
    harvest saves, peer-push installs, and the LRU cap all run at
    once.  N threads hammer saves + installs across overlapping sigs
    under a tight byte cap; afterwards every surviving artifact must
    verify digest-clean, no stage/tmp litter may remain, and the cap
    must hold — a torn survivor here is the bug the ``_locked()``
    store mutex exists to prevent."""
    store = warmstore.WarmStore(
        str(tmp_path / "warm"), max_bytes=2048
    )
    n_threads, n_iters, n_sigs = 6, 8, 4
    frames = [_mini_artifact(tmp_path, i) for i in range(n_sigs)]
    # a donor store provides published manifests for the install path
    donor = warmstore.WarmStore(str(tmp_path / "donor"))
    pushes = []
    for frame, man in frames:
        adir = donor.save(frame, dict(man))
        assert adir is not None
        full_man = donor.load_manifest(adir)
        blobs = {
            rel: open(os.path.join(adir, rel), "rb").read()
            for rel in full_man["files"]
        }
        pushes.append((full_man, blobs))
    errors = []

    def hammer(tid):
        try:
            for it in range(n_iters):
                i = (tid + it) % n_sigs
                if (tid + it) % 2:
                    frame, man = frames[i]
                    store.save(frame, dict(man))
                else:
                    full_man, blobs = pushes[i]
                    adir, why = store.install(dict(full_man), blobs)
                    assert adir is not None, why
        except Exception as e:  # surfaced after join
            errors.append((tid, repr(e)))

    threads = [
        threading.Thread(target=hammer, args=(t,), daemon=True)
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)
        assert not t.is_alive()
    assert errors == []
    # every survivor verifies byte-for-byte
    survivors = store.manifests()
    assert survivors  # the cap never empties the store entirely
    for adir, _man in survivors:
        ok, reason = store.verify(adir)
        assert ok, reason
    # no writer litter: stage dirs and tmp files are all cleaned up
    litter = [
        n for n in os.listdir(store.root)
        if n.startswith(".stage.") or ".tmp." in n
    ]
    assert litter == []
    # the byte cap held through the concurrent mix (actual on-disk
    # bytes, the same accounting the evictor uses)
    total = sum(store.entry_bytes(adir) for adir, _man in survivors)
    assert total <= store.max_bytes
    # and a sweep finds nothing to quarantine
    assert store.sweep() == []


# ---- slow: 3-backend mixed-spec load test + bench artifact ----------


@pytest.mark.slow
def test_fleet_three_backend_load(
    tmp_path, pool, cfg_dir, solo_compaction, solo_bk_crash2,
    checker_mod,
):
    """Load shape: 3 backends, a mixed batch of compaction +
    bookkeeper jobs through one dispatcher, every result solo-exact;
    the measured queue throughput / route latency / replication bytes
    are emitted as a current-rev bench artifact the validator accepts
    and the ledger ingests."""
    configs = [
        _config(tmp_path / f"b{i}", slice_s=0.3) for i in range(3)
    ]
    daemons = [ServiceDaemon(configs[0], pool=pool)] + [
        ServiceDaemon(c) for c in configs[1:]
    ]
    for d in daemons:
        d.start()
    fc = FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=tuple(c.socket_path for c in configs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
        sticky_s=0.0,  # load shape: spread by signal, no stickiness
    )
    disp = FleetDispatcher(fc)
    disp.start()
    t0 = time.monotonic()
    try:
        cl = ServiceClient(fc.socket_path, timeout=240.0)
        subs = []
        for i in range(3):
            subs.append(("compaction", cl.submit(
                "compaction", str(cfg_dir / "small_compaction.cfg"),
                invariants=[], full=True,
            )))
            subs.append(("bookkeeper", cl.submit(
                "bookkeeper", str(cfg_dir / "bk_crash2.cfg"),
                full=True,
            )))
        used = set()
        for spec, r in subs:
            used.add(r["backend"])
            w = cl.wait(r["job_id"], timeout=600.0)
            assert w["state"] == jobmod.DONE
            assert_result_matches_solo(
                _Result(w),
                solo_compaction if spec == "compaction"
                else solo_bk_crash2,
            )
        assert len(used) >= 2  # the load genuinely spread
        elapsed = time.monotonic() - t0
        snap = disp.metrics_snapshot()
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()

    # BENCH-shaped artifact at the current rev
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__
            ))), "bench.py",
        )
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    routes = sum(snap["routes"].values())
    d = bench.artifact_skeleton()
    d.update(
        metric="fleet_jobs_per_sec",
        value=len(subs) / max(elapsed, 1e-9),
        unit="jobs/s",
        mode="fleet",
        fleet_backends=len(configs),
        fleet_jobs_per_sec=len(subs) / max(elapsed, 1e-9),
        fleet_route_ms=(
            1e3 * float(snap["route_s"]) / max(routes, 1)
        ),
        fleet_replicated_wire_bytes=sum(
            snap["repl_bytes"].values()
        ),
        # survivability latencies (r21): this healthy-path drill sees
        # no drain/rejoin — null is the validator-legal value
        fleet_failover_ms=(
            1e3 * float(snap["failover_s"]) / snap["failover_n"]
            if snap.get("failover_n") else None
        ),
        fleet_reconcile_ms=(
            1e3 * float(snap["reconcile_s"]) / snap["reconcile_n"]
            if snap.get("reconcile_n") else None
        ),
    )
    assert d["bench_schema"] == 12  # current rev (r23 bump)
    errs = checker_mod.validate_bench_artifact(d, "fleet")
    assert errs == []

    # and the ledger ingests it at the new rev
    from pulsar_tlaplus_tpu.obs import ledger as ledgermod

    path = str(tmp_path / "ledger.jsonl")
    art = str(tmp_path / "fleet_bench.json")
    with open(art, "w") as f:
        f.write(json.dumps(d))
    rec = ledgermod.record_from_file(art)
    assert rec["bench_schema"] == 12
    assert ledgermod.append(path, [rec]) == 1
    assert ledgermod.validate_ledger(path) == []
