"""Differential tests for the device-resident sharded engine
(engine/sharded_device.py): counts, diameters, and verdicts must be
identical to the Python oracle for EVERY shard count (SURVEY.md §4e —
multi-node determinism on a virtual CPU mesh), and counterexamples must
replay through the model exactly like the single-chip engine's."""

import pytest

from pulsar_tlaplus_tpu.engine.sharded_device import ShardedDeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import SMALL_CONFIGS, assert_valid_counterexample


@pytest.mark.parametrize("n", [1, 2, 8])
def test_sharded_device_counts_identical_across_meshes(n):
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=n, invariants=(), sub_batch=128,
        visited_cap=1 << 10,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_device_shipped_cfg_published_count():
    """45,198 distinct states / diameter 20 (compaction.tla:23) on an
    8-shard mesh — the init fanout (729 states) is routed too."""
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=8, sub_batch=512,
        visited_cap=1 << 13,
    ).run()
    assert got.distinct_states == 45198
    assert got.diameter == 20
    assert got.violation is None and not got.deadlock


def test_sharded_device_leak_counterexample_replays():
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=4,
        invariants=("CompactedLedgerLeak",), sub_batch=512,
        visited_cap=1 << 13,
    ).run()
    assert got.violation == "CompactedLedgerLeak"
    assert got.diameter == 12
    assert len(got.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, got.trace, got.trace_actions, "CompactedLedgerLeak"
    )


def test_sharded_device_growth_matches_oracle():
    """Tiny initial capacities force visited + store growth mid-run on
    every shard; counts must stay exact."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 6, group=2,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_device_flush_factor_matches_oracle():
    c = SMALL_CONFIGS["two_crashes"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=2, invariants=(), sub_batch=128,
        visited_cap=1 << 10, flush_factor=3,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_device_truncation():
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    r = ShardedDeviceChecker(
        m, n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 10, max_states=64,
    ).run()
    assert r.truncated
