"""Differential tests for the device-resident sharded engine
(engine/sharded_device.py): counts, diameters, and verdicts must be
identical to the Python oracle for EVERY shard count (SURVEY.md §4e —
multi-node determinism on a virtual CPU mesh), and counterexamples must
replay through the model exactly like the single-chip engine's."""

import pytest

from pulsar_tlaplus_tpu.engine.sharded_device import ShardedDeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import needs_shard_map, SMALL_CONFIGS, assert_valid_counterexample

pytestmark = needs_shard_map


@pytest.mark.parametrize("n", [1, 2, 8])
def test_sharded_device_counts_identical_across_meshes(n):
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=n, invariants=(), sub_batch=128,
        visited_cap=1 << 10,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_device_shipped_cfg_published_count():
    """45,198 distinct states / diameter 20 (compaction.tla:23) on an
    8-shard mesh — the init fanout (729 states) is routed too."""
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=8, sub_batch=512,
        visited_cap=1 << 13,
    ).run()
    assert got.distinct_states == 45198
    assert got.diameter == 20
    assert got.violation is None and not got.deadlock


def test_sharded_device_leak_counterexample_replays():
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=4,
        invariants=("CompactedLedgerLeak",), sub_batch=512,
        visited_cap=1 << 13,
    ).run()
    assert got.violation == "CompactedLedgerLeak"
    assert got.diameter == 12
    assert len(got.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, got.trace, got.trace_actions, "CompactedLedgerLeak"
    )


def test_sharded_device_growth_matches_oracle():
    """Tiny initial capacities force visited + store growth mid-run on
    every shard; counts must stay exact."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 6, group=2,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_device_flush_factor_matches_oracle():
    c = SMALL_CONFIGS["two_crashes"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=2, invariants=(), sub_batch=128,
        visited_cap=1 << 10, flush_factor=3,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_device_truncation():
    m = CompactionModel(SMALL_CONFIGS["producer_on"])
    r = ShardedDeviceChecker(
        m, n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 10, max_states=64,
    ).run()
    assert r.truncated


def test_sharded_device_checkpoint_resume_exact_count(tmp_path):
    """Truncate-and-resume on an 8-shard mesh must reach the published
    45,198 / diameter-20 oracle exactly (VERDICT r3 #6): run with a
    tiny max_states to force truncation, then resume (repeatedly, to
    cross several checkpoints) until complete."""
    ckpt = str(tmp_path / "sd.npz")

    def make(max_states):
        ck = ShardedDeviceChecker(
            CompactionModel(pe.SHIPPED_CFG), n_devices=8, sub_batch=512,
            visited_cap=1 << 13, max_states=max_states,
            checkpoint_path=ckpt, checkpoint_every=2,
        )
        return ck

    r = make(2_000).run()
    assert r.truncated
    r = make(20_000).run(resume=True)
    assert r.truncated
    r = make(1 << 26).run(resume=True)
    assert not r.truncated
    assert r.distinct_states == 45198
    assert r.diameter == 20
    assert r.violation is None and not r.deadlock


def test_sharded_device_resume_rejects_other_config(tmp_path):
    ckpt = str(tmp_path / "sd.npz")
    ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=4, sub_batch=512,
        visited_cap=1 << 13, max_states=2_000, checkpoint_path=ckpt,
    ).run()
    other = ShardedDeviceChecker(
        CompactionModel(SMALL_CONFIGS["producer_on"]), n_devices=4,
        sub_batch=128, visited_cap=1 << 10, checkpoint_path=ckpt,
    )
    with pytest.raises(ValueError, match="different configuration"):
        other.run(resume=True)


def test_sharded_device_trace_spans_resume(tmp_path):
    """A counterexample found after a resume must replay across the
    checkpoint boundary (parent chain lives in the restored logs)."""
    ckpt = str(tmp_path / "sd.npz")
    r = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=4,
        invariants=("CompactedLedgerLeak",), sub_batch=512,
        visited_cap=1 << 13, max_states=9_000,
        checkpoint_path=ckpt, checkpoint_every=1,
    ).run()
    assert r.truncated and r.violation is None
    r = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=4,
        invariants=("CompactedLedgerLeak",), sub_batch=512,
        visited_cap=1 << 13, checkpoint_path=ckpt,
    ).run(resume=True)
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )


def test_sharded_device_route_overflow_autorecovers():
    """A deliberately starved route capacity (route_slack << 1) must
    auto-recover (double slack, re-jit, retry the level) and still
    reach the oracle count exactly (VERDICT r3 #8)."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ck = ShardedDeviceChecker(
        CompactionModel(c), n_devices=4, invariants=(), sub_batch=128,
        visited_cap=1 << 10, route_slack=0.03,
    )
    got = ck.run()
    assert ck.route_slack > 0.03  # recovery actually fired
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


@pytest.mark.parametrize("slices,per", [(2, 4), (4, 2)])
def test_sharded_device_2d_mesh_counts_identical(slices, per):
    """Hierarchical dcn x ici routing (owner-slice over dcn, then
    owner-chip over ici) inside the jitted round step must reproduce
    the oracle exactly on a 2-D virtual mesh (VERDICT r3 #7)."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        CompactionModel(c), n_devices=slices * per, n_slices=slices,
        invariants=(), sub_batch=128, visited_cap=1 << 10,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_device_2d_shipped_cfg_published_count():
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=8, n_slices=2,
        sub_batch=512, visited_cap=1 << 13,
    ).run()
    assert got.distinct_states == 45198
    assert got.diameter == 20


def test_sharded_device_2d_counterexample_replays():
    got = ShardedDeviceChecker(
        CompactionModel(pe.SHIPPED_CFG), n_devices=8, n_slices=4,
        invariants=("DuplicateNullKeyMessage",), sub_batch=512,
        visited_cap=1 << 13,
    ).run()
    assert got.violation == "DuplicateNullKeyMessage"
    assert_valid_counterexample(
        pe.SHIPPED_CFG, got.trace, got.trace_actions,
        "DuplicateNullKeyMessage",
    )


def test_sharded_device_2d_route_overflow_autorecovers():
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    ck = ShardedDeviceChecker(
        CompactionModel(c), n_devices=8, n_slices=2, invariants=(),
        sub_batch=128, visited_cap=1 << 10, route_slack=0.03,
    )
    got = ck.run()
    assert ck.route_slack > 0.03
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_sharded_device_host_seeded_matches_oracle():
    """Round 5 (VERDICT r4 #4): a host-enumerated BFS prefix loads onto
    the mesh (rows round-robin by BFS index, keys routed to owners)
    without changing counts, diameter, or verdicts."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    m = CompactionModel(c)
    seed = m.host_seed(max_level_states=40, max_total=120)
    assert len(seed[3]) > 1
    got = ShardedDeviceChecker(
        m, n_devices=4, invariants=(), sub_batch=64,
        visited_cap=1 << 10,
    ).run(seed=seed)
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


def test_sharded_device_host_seeded_violation_trace():
    """A violation found beyond the seeded prefix must replay a valid
    counterexample through remapped cross-shard parent chains."""
    m = CompactionModel(pe.SHIPPED_CFG)
    seed = m.host_seed(max_level_states=300, max_total=900)
    r = ShardedDeviceChecker(
        m, n_devices=4, invariants=("CompactedLedgerLeak",),
        sub_batch=256, visited_cap=1 << 12,
    ).run(seed=seed)
    assert r.violation == "CompactedLedgerLeak"
    assert r.diameter == 12
    assert len(r.trace) == 12
    assert_valid_counterexample(
        pe.SHIPPED_CFG, r.trace, r.trace_actions, "CompactedLedgerLeak"
    )
