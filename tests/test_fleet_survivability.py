"""Fleet survivability: crash-safe dispatcher, partition-tolerant
routing, and lost-job reconciliation (ISSUE 17, r21).

The dispatcher half of the r17 durability story: ``serve`` already
survives kill -9 (queue.json + checkpoint frames); these tests pin
that the DISPATCHER tier now does too —

- kill -9 mid-fleet + ``--recover`` resolves every acked submit
  exactly-once (the routing decision was persisted BEFORE the client
  ack), and a retried ``submit_id`` dedups to the same job across the
  crash;
- a torn ``fleet_jobs.json`` is quarantined (never trusted, never
  fatal) and the table is rebuilt from the backends' own job tables;
- a watch relayed through the dispatcher survives a backend failover
  mid-stream: the failed-over relay restarts at offset 0 and the
  client's (run_id, seq) join yields every event exactly once;
- replication negative paths: a pulled blob whose digest does not
  match the manifest is quarantined and re-pulled once (never pushed
  corrupt), and a torn push can never install (stage + digest verify
  + manifest-last atomic swap);
- registry health: readmission wants ``readmit_after`` CONSECUTIVE
  clean polls (a flap cycle costs exactly one failover), and a poll
  timeout degrades routing weight as immediately as a refused
  connect;
- a lost job whose backend rejoins delivers the backend's REAL
  result (``lost`` -> ``done`` with the ``reconciled`` marker —
  never a silent re-run);
- an all-backends-down window degrades to a bounded queue-and-hold
  with typed ``capacity`` sheds past the buffer.

The seeded end-to-end drill (``scripts/chaos.py --fleet``) runs
pinned here (tier-1) and randomized in the slow soak.
"""

import hashlib
import json
import os
import signal
import threading
import time

import pytest

from pulsar_tlaplus_tpu.fleet import replicate
from pulsar_tlaplus_tpu.fleet.dispatcher import (
    FleetConfig,
    FleetDispatcher,
)
from pulsar_tlaplus_tpu.fleet.registry import BackendRegistry
from pulsar_tlaplus_tpu.service.client import (
    AdmissionRejected,
    BackendUnavailable,
    ServiceClient,
)
from pulsar_tlaplus_tpu.service.server import ServiceDaemon
from pulsar_tlaplus_tpu.utils import faults
from pulsar_tlaplus_tpu.warm import store as warmstore

from tests.test_service import (  # noqa: F401  (fixtures by name)
    _config,
    _load_script,
    assert_result_matches_solo,
    cfg_dir,
    pool,
    solo_compaction,
)


@pytest.fixture(scope="module")
def chaos_mod():
    return _load_script("chaos")


class _Result:
    def __init__(self, reply):
        self.result = reply.get("result")
        self.state = reply.get("state")
        self.error = reply.get("error")


def _two_daemons(root, pool, slice_s=0.3):
    configs = [
        _config(root / "b0", slice_s=slice_s),
        _config(root / "b1", slice_s=slice_s),
    ]
    daemons = [
        ServiceDaemon(configs[0], pool=pool),
        ServiceDaemon(configs[1]),
    ]
    for d in daemons:
        d.start()
    return configs, daemons


def _wait(pred, timeout=30.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ---- kill -9 + --recover: every acked submit exactly-once -----------


def test_kill9_recover_resolves_acked_submits_exactly_once(
    tmp_path, pool, cfg_dir, solo_compaction, chaos_mod
):
    """The crash drill the tentpole exists for: routing decisions are
    persisted BEFORE the client ack, so kill -9 between ack and
    completion loses nothing — the restarted ``--recover`` dispatcher
    answers for every acked job, dedups retried submit_ids to the
    same job, and every job lands the solo-exact result."""
    cfg_path = str(cfg_dir / "small_compaction.cfg")
    configs, daemons = _two_daemons(tmp_path, pool)
    disp_dir = str(tmp_path / "disp")
    addrs = [c.socket_path for c in configs]
    proc = None
    try:
        proc = chaos_mod._spawn_dispatcher(disp_dir, addrs)
        sock = os.path.join(disp_dir, "dispatch.sock")
        cl = ServiceClient(sock, timeout=240.0, retries=6)
        acked = []
        for k in range(2):
            sid = f"kill9-{k}"
            acked.append((sid, cl.submit(
                "compaction", cfg_path, invariants=[],
                submit_id=sid, warm=False,
            )))

        proc.send_signal(signal.SIGKILL)
        proc.wait(30.0)
        proc = chaos_mod._spawn_dispatcher(
            disp_dir, addrs, recover=True
        )

        table = {j["job_id"]: j for j in cl.status()}
        assert len(table) == len(acked), table
        for sid, jid in acked:
            assert jid in table, (sid, jid, table)
            # exactly-once: the retried submit_id routes back to its
            # persisted owner and dedups to the SAME job
            assert cl.submit(
                "compaction", cfg_path, invariants=[],
                submit_id=sid, warm=False,
            ) == jid
        for _sid, jid in acked:
            r = cl.wait(jid, timeout=240.0)
            assert r.get("state") == "done", r
            assert_result_matches_solo(_Result(r), solo_compaction)
    finally:
        if proc is not None:
            proc.terminate()
            proc.wait(30.0)
        for d in daemons:
            d.shutdown()


def test_recover_quarantines_torn_jobs_file_and_rebuilds(
    tmp_path, pool, cfg_dir, chaos_mod
):
    """A torn fleet_jobs.json (half-written at the crash) is moved
    aside as ``fleet_jobs.json.corrupt.*`` — never trusted, never
    fatal — and ``--recover`` rebuilds the table from the backends'
    own (authoritative) job listings instead."""
    cfg_path = str(cfg_dir / "small_compaction.cfg")
    configs, daemons = _two_daemons(tmp_path, pool)
    disp_dir = str(tmp_path / "disp")
    addrs = [c.socket_path for c in configs]
    jobs_path = os.path.join(disp_dir, "fleet_jobs.json")
    proc = None
    try:
        proc = chaos_mod._spawn_dispatcher(disp_dir, addrs)
        sock = os.path.join(disp_dir, "dispatch.sock")
        cl = ServiceClient(sock, timeout=240.0, retries=6)
        jid = cl.submit(
            "compaction", cfg_path, invariants=[],
            submit_id="torn-table-probe", warm=False,
        )
        assert cl.wait(jid, timeout=240.0).get("state") == "done"
        proc.terminate()
        proc.wait(30.0)
        proc = None

        with open(jobs_path, "w") as f:
            f.write('{"fleet_jobs_v": 2, "jobs": {"half')  # torn
        proc = chaos_mod._spawn_dispatcher(
            disp_dir, addrs, recover=True
        )
        quarantined = [
            n for n in os.listdir(disp_dir)
            if n.startswith("fleet_jobs.json.corrupt.")
        ]
        assert quarantined, os.listdir(disp_dir)
        # the torn file was never parsed into the table; the job came
        # back through the backends' own listings (submit_id intact:
        # the dedup key survives the quarantine)
        table = {j["job_id"]: j for j in cl.status()}
        assert table.get(jid, {}).get("state") == "done", table
        assert cl.submit(
            "compaction", cfg_path, invariants=[],
            submit_id="torn-table-probe", warm=False,
        ) == jid
    finally:
        if proc is not None:
            proc.terminate()
            proc.wait(30.0)
        for d in daemons:
            d.shutdown()


# ---- watch relay survives a backend failover mid-stream -------------


def test_watch_relay_survives_backend_failover(
    tmp_path, pool, cfg_dir, solo_compaction
):
    """Satellite 3: a client watching through the dispatcher while
    the owning backend dies sees the failed-over job's stream from
    its head — the dispatcher restarts the relay at offset 0 (the
    old reconnect offset indexed the DEAD backend's event log) and
    the client's (run_id, seq) join drops replayed duplicates: no
    event yielded twice, none skipped, and the final result is
    solo-exact."""
    cfg_path = str(cfg_dir / "small_compaction.cfg")
    configs, daemons = _two_daemons(tmp_path, pool, slice_s=2.0)
    addrs = [c.socket_path for c in configs]
    fc = FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=tuple(addrs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    cl = ServiceClient(fc.socket_path, timeout=240.0, retries=8)
    try:
        # pin one backend busy so the watched job is QUEUED there
        # (queued jobs fail over; running jobs are lost — the watch
        # must survive the failover kind)
        js = cl.submit(
            "compaction", cfg_path, mode="simulate",
            sim=dict(
                n_walkers=64, depth=32, segment_len=8,
                max_steps=1 << 22, seed=7,
            ),
            warm=False, submit_id="watch-sim",
        )
        _wait(
            lambda: cl.status(js).get("state") == "running",
            timeout=120.0, what="sim start",
        )
        jw_sub = cl.submit(
            "compaction", cfg_path, invariants=[], warm=False,
            submit_id="watch-probe", full=True,
        )
        jw, owner = jw_sub["job_id"], jw_sub["backend"]
        assert cl.status(jw).get("state") == "queued"

        events, failures = [], []

        def watch_body():
            wcl = ServiceClient(
                fc.socket_path, timeout=240.0, retries=8
            )
            try:
                for msg in wcl.watch(jw, timeout_s=240.0):
                    events.append(msg)
            except Exception as e:  # noqa: BLE001 — asserted below
                failures.append(e)

        t = threading.Thread(target=watch_body)
        t.start()
        time.sleep(0.4)  # let the relay attach to the doomed owner
        daemons[addrs.index(owner)].shutdown()
        _wait(
            lambda: disp.metrics_snapshot()["failovers"].get(owner),
            timeout=60.0, what="owner drain",
        )
        t.join(240.0)
        assert not t.is_alive(), "watch never terminated"
        assert not failures, failures

        assert events and "done" in events[-1], events[-1:]
        recs = [m["event"] for m in events if "event" in m]
        keys = [(r.get("run_id"), r.get("seq")) for r in recs]
        assert len(keys) == len(set(keys)), "duplicate events yielded"
        by_run = {}
        for rid, seq in keys:
            by_run.setdefault(rid, []).append(seq)
        for rid, seqs in by_run.items():
            assert seqs == list(
                range(seqs[0], seqs[0] + len(seqs))
            ), f"gap in relayed stream for run {rid}: {seqs}"

        r = cl.wait(jw, timeout=240.0)
        assert r.get("state") == "done", r
        assert_result_matches_solo(_Result(r), solo_compaction)
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()


# ---- replication negative paths (satellite 4) -----------------------


def _tiny_manifest(good: bytes):
    # every REQUIRED_FIELDS key: the store's read path (verify/sweep)
    # refuses manifests that are not fully formed
    return {
        "warm_v": warmstore.WARM_VERSION,
        "spec": "compaction",
        "config_sig": "surv-test-sig",
        "module_digest": "0" * 16,
        "bindings": {},
        "invariants": [],
        "distinct_states": 1,
        "levels": 1,
        "truncated": False,
        "files": {
            warmstore.FRAME: {
                "sha256": hashlib.sha256(good).hexdigest(),
                "bytes": len(good),
            },
        },
    }


def test_replicate_pull_digest_mismatch_quarantines_and_repulls(
    monkeypatch,
):
    """A blob corrupted in flight is caught against the MANIFEST
    digest before it ever rides to the peer: quarantined (dropped)
    and re-pulled once.  A clean second pull ships; a second corrupt
    pull fails the artifact typed ``pull_corrupt`` with nothing
    pushed."""
    good = b"the frame bytes the manifest promised"
    bad = b"torn partition garbage xxxxxxxxxxxxxxx"
    man = _tiny_manifest(good)
    calls = {"pull": 0, "push": 0}

    def scripted(pulls):
        def fake_request(addr, op, timeout=0, **kw):
            if op == "warm_offer":
                return {"ok": True, "need": [warmstore.FRAME],
                        "have": [], "identical": False}
            if op == "warm_pull":
                data = pulls[min(calls["pull"], len(pulls) - 1)]
                calls["pull"] += 1
                b64, raw, wire = replicate.encode_blob(data)
                return {"ok": True, "rel": warmstore.FRAME,
                        "data": b64, "raw_bytes": raw,
                        "wire_bytes": wire}
            if op == "warm_push":
                calls["push"] += 1
                blob = kw["blobs"][warmstore.FRAME]
                got = replicate.decode_blob(
                    blob["data"], blob["raw_bytes"]
                )
                assert got == good, "a corrupt blob was pushed"
                return {"ok": True, "reason": "ok"}
            raise AssertionError(f"unexpected op {op}")
        return fake_request

    monkeypatch.setattr(
        replicate.protocol, "request", scripted([bad, good])
    )
    out = replicate.replicate_artifact("src", "dst", man)
    assert out["status"] == "ok", out
    assert calls == {"pull": 2, "push": 1}

    calls.update(pull=0, push=0)
    monkeypatch.setattr(
        replicate.protocol, "request", scripted([bad, bad])
    )
    out = replicate.replicate_artifact("src", "dst", man)
    assert out["status"].startswith("pull_corrupt"), out
    assert calls["pull"] == 2 and calls["push"] == 0, calls


def test_torn_push_never_installs_manifest_last(tmp_path):
    """A push whose bytes do not match its manifest digests is
    refused BEFORE publication: the store stages, verifies, and only
    then swaps atomically (manifest written last), so a torn push
    leaves no manifest and cannot replace a good artifact."""
    good = b"verified artifact frame bytes 1234"
    torn = good[: len(good) // 2]  # a push cut mid-blob

    def wire(data: bytes) -> dict:
        b64, raw, _w = replicate.encode_blob(data)
        return {warmstore.FRAME: {"data": b64, "raw_bytes": raw}}

    man = _tiny_manifest(good)
    ws = warmstore.WarmStore(str(tmp_path / "store"))

    adir, reason = replicate.install_push(ws, man, wire(torn))
    assert adir is None and reason.startswith("digest_mismatch"), (
        adir, reason,
    )
    assert ws.manifests() == []  # nothing published, even partially
    assert not os.path.exists(
        os.path.join(ws.dir_for(man["config_sig"]), warmstore.MANIFEST)
    )

    # a good install, then a torn REPLACEMENT: the original survives
    adir, reason = replicate.install_push(ws, man, wire(good))
    assert reason == "ok" and adir is not None
    adir2, reason2 = replicate.install_push(ws, man, wire(torn))
    assert adir2 is None and reason2.startswith("digest_mismatch")
    ok, why = ws.verify(adir)
    assert ok, why
    assert ws.sweep() == []


# ---- registry health: hysteresis, flap, slow polls ------------------


class _StubBackend:
    """The smallest thing that answers ``ping`` + ``metrics`` — a
    registry poll target with no engine behind it."""

    def __init__(self, sock_path: str):
        import socket as socketmod

        self.addr = sock_path
        self._srv = socketmod.socket(
            socketmod.AF_UNIX, socketmod.SOCK_STREAM
        )
        self._srv.bind(sock_path)
        self._srv.listen(8)
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except OSError:
                continue
            try:
                r = conn.makefile("r", encoding="utf-8")
                w = conn.makefile("w", encoding="utf-8")
                req = json.loads(r.readline())
                if req.get("op") == "ping":
                    reply = {"ok": True, "pid": os.getpid(),
                             "warmed": []}
                else:
                    reply = {"ok": True, "metrics": ""}
                w.write(json.dumps(reply) + "\n")
                w.flush()
            except (OSError, ValueError):
                pass
            finally:
                conn.close()

    def close(self):
        self._stop.set()
        self._t.join(5.0)
        self._srv.close()


def test_registry_readmission_needs_consecutive_clean_polls(
    tmp_path,
):
    """Hysteresis (tentpole part 2): a flap cycle — die, one clean
    poll, die again, return — drains the backend exactly ONCE and
    readmits it only after ``readmit_after`` CONSECUTIVE clean polls.
    One lucky poll mid-flap is not health."""
    stub = _StubBackend(str(tmp_path / "stub.sock"))
    try:
        reg = BackendRegistry(
            [stub.addr], fail_after=2, readmit_after=2, timeout=2.0,
        )
        down_events, up_events = [], []

        def poll():
            nd, nu = reg.poll_once()
            down_events.extend(nd)
            up_events.extend(nu)

        poll()
        b = reg.backends[stub.addr]
        assert b.state == "up"

        # the flap shape the PTT_FAULT kind arms: drain, one clean
        # poll, drain again, one clean poll
        b.fault_script.extend(
            ["fail", "fail", "ok", "fail", "fail", "ok"]
        )
        for _ in range(6):
            poll()
        assert len(down_events) == 1, "flap drained more than once"
        assert up_events == [], (
            "one clean poll mid-flap readmitted the backend"
        )
        assert b.state == "down"
        # the flap's trailing ok was clean poll 1 of the streak; one
        # more consecutive clean completes readmission
        poll()
        assert b.state == "up"
        assert len(up_events) == 1 and len(down_events) == 1

        # and directly: after a plain drain, ONE clean poll is not
        # health — readmission waits for the streak
        b.fault_script.extend(["fail", "fail"])
        poll()
        poll()
        assert b.state == "down" and len(down_events) == 2
        poll()  # streak 1: still down
        assert b.state == "down"
        poll()  # streak 2: readmitted
        assert b.state == "up" and len(up_events) == 2
    finally:
        stub.close()


def test_registry_partition_fault_kind_arms_via_poll_counter(
    tmp_path, monkeypatch,
):
    """``partition@backend:N`` (realized in the health loop) arms
    ``fail_after`` injected failures on the N-th polled backend —
    enough to drain it while the daemon stays alive."""
    stub = _StubBackend(str(tmp_path / "stub.sock"))
    monkeypatch.setenv("PTT_FAULT", "partition@backend:2")
    faults.reset()
    try:
        reg = BackendRegistry(
            [stub.addr], fail_after=2, readmit_after=2, timeout=2.0,
        )
        nd, _ = reg.poll_once()  # poll 1: clean
        assert not nd
        reg.poll_once()  # poll 2: partition arms + first fail
        nd, _ = reg.poll_once()  # poll 3: second fail -> drained
        assert [b.addr for b in nd] == [stub.addr]
        reg.poll_once()  # clean again (script exhausted): streak 1
        _, nu = reg.poll_once()  # streak 2 -> rejoins
        assert [b.addr for b in nu] == [stub.addr]
    finally:
        monkeypatch.delenv("PTT_FAULT")
        faults.reset()
        stub.close()


def test_registry_slow_poll_degrades_score_immediately(
    tmp_path, monkeypatch,
):
    """Satellite 2: a poll TIMEOUT costs routing weight the moment it
    happens, exactly like a refused connect — a hung backend must not
    coast on its last-known-good score while new work piles on.
    Pinned via ``slow@conn``: the stalled backend scores behind the
    clean one and loses the next routing decision, while remaining
    ``up`` (one timeout is not a drain)."""
    stubs = [
        _StubBackend(str(tmp_path / "a.sock")),
        _StubBackend(str(tmp_path / "b.sock")),
    ]
    # the 3rd outbound poll connection = backend index 0 on pass 2
    monkeypatch.setenv("PTT_FAULT", "slow@conn:3")
    faults.reset()
    try:
        reg = BackendRegistry(
            [s.addr for s in stubs],
            fail_after=3, readmit_after=2, timeout=0.3,
        )
        reg.poll_once()  # pass 1: both clean
        t0 = time.monotonic()
        reg.poll_once()  # pass 2: stubs[0] stalls past the timeout
        assert time.monotonic() - t0 >= 0.3
        slow, clean = (
            reg.backends[stubs[0].addr], reg.backends[stubs[1].addr],
        )
        assert slow.failures == 1 and slow.state == "up"
        assert slow.score() > clean.score() + 999.0
        chosen, why = reg.choose("fresh-tenant")
        assert chosen.addr == clean.addr, (why, chosen.addr)
    finally:
        monkeypatch.delenv("PTT_FAULT")
        faults.reset()
        for s in stubs:
            s.close()


# ---- lost-job reconciliation: lost -> done, real result -------------


def test_lost_job_reconciles_to_done_with_backends_real_result(
    tmp_path, pool, cfg_dir, solo_compaction,
):
    """Tentpole part 3, the deterministic shape: a job mid-run when
    its backend partitions away is typed ``lost``; the backend — alive
    the whole time — finishes it; on rejoin the dispatcher re-polls
    and the job goes ``lost`` -> ``done`` carrying the backend's real
    result and the ``reconciled`` marker.  Exactly-once: the backend
    ran it once, nothing was resubmitted.

    Determinism: the probe must still be non-terminal when the drain
    fires, however fast the compile cache makes it — so a
    higher-priority sim hog (submitted straight to the backend,
    invisible to the dispatcher) preempts it at a level boundary and
    STARVES it in ``suspended`` (the scheduler's pick is strict
    priority) until the partition is in place; the hog is cancelled
    while the backend is partitioned, letting the probe finish behind
    the partition."""
    cfg_path = str(cfg_dir / "small_compaction.cfg")
    configs, daemons = _two_daemons(tmp_path, pool)
    addrs = [c.socket_path for c in configs]
    fc = FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=tuple(addrs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
        readmit_after=2,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    cl = ServiceClient(fc.socket_path, timeout=240.0, retries=6)
    try:
        sub = cl.submit(
            "compaction", cfg_path, invariants=[], warm=False,
            submit_id="lost-done-probe", full=True,
        )
        jid, owner = sub["job_id"], sub["backend"]
        bcl = ServiceClient(owner, timeout=60.0, retries=4)
        _wait(
            lambda: cl.status(jid).get("state") == "running",
            timeout=120.0, what="probe start",
        )
        hog = bcl.submit(
            "compaction", cfg_path, mode="simulate",
            sim=dict(
                n_walkers=64, depth=32, segment_len=8,
                max_steps=1 << 22, seed=11,
            ),
            warm=False, priority=5, submit_id="lost-hog",
        )
        _wait(
            lambda: bcl.status(jid).get("state") == "suspended",
            timeout=120.0, what="probe preempted by the hog",
        )
        # partition the owner (health-loop fault realization drained
        # of its PTT_FAULT costume: extend the same script directly)
        # and HOLD it down until the backend finishes the job
        breg = disp.registry.backends[owner]
        breg.fault_script.extend(["fail"] * 4)
        _wait(
            lambda: {
                j["job_id"]: j for j in cl.status()
            }[jid].get("state") == "lost",
            timeout=30.0, what="drain -> lost",
        )
        bcl.cancel(hog)  # the starved probe takes the device back
        _wait(
            lambda: (
                breg.fault_script.extend(["fail"] * 2) or
                bcl.status(jid).get("state") == "done"
            ),
            timeout=120.0, interval=0.2,
            what="backend-side completion while partitioned",
        )
        breg.fault_script.clear()  # partition heals
        _wait(
            lambda: {
                j["job_id"]: j for j in cl.status()
            }[jid].get("state") == "done",
            timeout=30.0, what="rejoin + reconcile",
        )
        listing = {j["job_id"]: j for j in cl.status()}
        assert listing[jid].get("reconciled") is True, listing[jid]
        r = cl.wait(jid, timeout=30.0)
        assert r.get("state") == "done", r
        assert_result_matches_solo(_Result(r), solo_compaction)
        # exactly-once: the backend ran the probe exactly once —
        # nothing was resubmitted behind the partition's back (the
        # only other table entry is the cancelled hog)
        probes = [
            j for j in bcl.status()
            if j.get("submit_id") == "lost-done-probe"
        ]
        assert len(probes) == 1, bcl.status()
        snap = disp.metrics_snapshot()
        assert snap["reconciled"].get(owner, 0) >= 1, snap
        assert snap["partitions"].get(owner, 0) >= 1, snap
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()


# ---- all-backends-down: bounded queue-and-hold ----------------------


def test_all_backends_down_holds_then_sheds_typed(tmp_path, pool):
    """Tentpole part 2, the floor: with every backend drained the
    dispatcher degrades to a bounded queue-and-hold — a submit waits
    up to ``hold_s`` for a backend (and proceeds if one appears),
    the (hold_max+1)-th concurrent submit sheds with the typed
    ``capacity`` code, and an expired hold answers the typed
    ``backend_unavailable``.  Never a crash, never an unbounded
    pile-up."""
    b0_config = _config(tmp_path / "b0", slice_s=0.3)
    fc = FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=(
            b0_config.socket_path,  # not started yet
            str(tmp_path / "never.sock"),
        ),
        health_interval_s=0.1,
        fail_after=1,
        backend_timeout_s=2.0,
        readmit_after=1,
        hold_max=1,
        hold_s=2.0,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    daemon = None
    try:
        _wait(
            lambda: set(disp.registry.snapshot().values()) == {"down"},
            timeout=10.0, what="all backends down",
        )
        outcomes = {}

        def held_submit(tag):
            hcl = ServiceClient(fc.socket_path, timeout=30.0, retries=0)
            t0 = time.monotonic()
            try:
                outcomes[tag] = hcl.submit(
                    "compaction", "/nonexistent.cfg", invariants=[],
                )
            except Exception as e:  # noqa: BLE001 — asserted below
                outcomes[tag] = e
            outcomes[tag + "_s"] = time.monotonic() - t0

        t = threading.Thread(target=held_submit, args=("hold",))
        t.start()
        time.sleep(0.3)  # the hold slot is taken; the next must shed
        cl = ServiceClient(fc.socket_path, timeout=30.0, retries=0)
        with pytest.raises(AdmissionRejected) as shed:
            cl.submit("compaction", "/nonexistent.cfg", invariants=[])
        assert shed.value.code == "capacity"
        t.join(30.0)
        assert isinstance(outcomes["hold"], BackendUnavailable), (
            outcomes
        )
        assert outcomes["hold_s"] >= 1.6  # it genuinely held
        assert disp.metrics_snapshot()["held_sheds"] == 1

        # a backend appearing MID-HOLD releases the held submit into
        # a normal route (the bounded buffer absorbs a fleet-wide
        # blip invisibly; the bogus cfg path is rejected by the
        # BACKEND, proving the submit reached one)
        t2 = threading.Thread(target=held_submit, args=("release",))
        t2.start()
        time.sleep(0.2)
        daemon = ServiceDaemon(b0_config, pool=pool)
        daemon.start()
        t2.join(30.0)
        assert not isinstance(
            outcomes["release"], BackendUnavailable
        ), outcomes["release"]
        assert not isinstance(
            outcomes["release"], AdmissionRejected
        ), outcomes["release"]
    finally:
        disp.shutdown()
        if daemon is not None:
            daemon.shutdown()


# ---- the seeded end-to-end drill: pinned (tier-1) + soak (slow) -----


def test_fleet_chaos_v2_pinned_schedule(
    tmp_path, pool, solo_compaction, chaos_mod
):
    """The whole survivability story under one seeded schedule
    (``scripts/chaos.py --fleet``): dispatcher kill -9 + --recover
    exactly-once, a partition window reconciled, a flap held to one
    failover by hysteresis, torn replication leaving only verified
    artifacts, every stream v15-validator-clean, and (r22) every
    acked submit's trace_id stitching into a complete chain inside
    one validator-clean Perfetto export."""
    report = chaos_mod.run_fleet_chaos_v2(
        str(tmp_path / "drill"),
        seed=0,
        pool=pool,
        solo=solo_compaction,
        clients=2,
        jobs_per_client=1,
        log=lambda m: None,
    )
    assert report["recovered"] == 2
    assert report["reconciled_jobs"] >= 1
    assert report["partitions"] >= 1
    assert report["replicated_wire_bytes"] > 0
    assert report["streams_validated"] == 3
    assert report["trace_chains"] >= 1
    assert os.path.exists(
        os.path.join(str(tmp_path / "drill"), "fleet_trace.json")
    )


@pytest.mark.slow
def test_fleet_chaos_v2_random_soak(tmp_path, pool, solo_compaction):
    """Randomized soak: a fresh seed per run (printed for replay via
    ``scripts/chaos.py --fleet --seed N``)."""
    chaos_mod = _load_script("chaos")
    seed = int.from_bytes(os.urandom(2), "big")
    print(f"fleet chaos v2 soak seed: {seed}")
    report = chaos_mod.run_fleet_chaos_v2(
        str(tmp_path / "soak"),
        seed=seed,
        pool=pool,
        solo=solo_compaction,
        clients=3,
        jobs_per_client=2,
    )
    assert report["recovered"] == 6
    assert report["reconciled_jobs"] >= 1
