"""Subprocess driver for crash-resume differential tests.

Runs one checker (device or sharded, CPU backend) on the shipped
compaction config with a checkpoint path, printing a one-line JSON
result on success.  Fault injection rides the PTT_FAULT env var set by
the calling test — ``kill@level:k`` hard-exits 137 mid-run, leaving
only the checkpoint frames behind, which is the whole point.

Not collected by pytest (no ``test_`` prefix); invoked as
``python -m tests._survivable_run`` from the repo root.
"""

import argparse
import json
import os
import sys


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", choices=["device", "sharded", "liveness"],
                    default="device")
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--invariant", default=None)
    ap.add_argument("--every", type=int, default=2)
    ap.add_argument("--max-states", type=int, default=200_000_000)
    ap.add_argument("--telemetry", default=None)
    ap.add_argument("--progress", type=float, default=None)
    ap.add_argument("--goal", default="Termination")
    ap.add_argument("--fairness", default="wf_next")
    ap.add_argument("--sweep-chunk", type=int, default=1 << 12)
    ap.add_argument("--frontier-chunk", type=int, default=2048)
    ap.add_argument(
        "--hbm-budget", dest="hbm_budget", default=None,
        help="tiered-store byte budget (device engine; 'min+N' = the "
        "engine's initial-tier minimum plus N bytes, resolved here so "
        "drills stay shape-independent)",
    )
    ap.add_argument("--sub-batch", type=int, default=2048)
    ap.add_argument("--visited-cap", type=int, default=1 << 16)
    ap.add_argument(
        "--config", default="shipped",
        choices=["shipped", "producer_on", "consumer_on"],
        help="shipped = the published 45k oracle; producer_on / "
        "consumer_on = the small liveness oracles (no-lasso / lasso)",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    # share the suite's persistent compile cache (tests/conftest.py):
    # drill subprocesses otherwise pay the full cold compile of the
    # engine programs on every single drill
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from pulsar_tlaplus_tpu.models.compaction import CompactionModel
    from pulsar_tlaplus_tpu.ref import pyeval as pe

    if args.config == "shipped":
        c = pe.SHIPPED_CFG
    else:
        import dataclasses

        from tests.helpers import SMALL_CONFIGS

        c = SMALL_CONFIGS["producer_on"]
        if args.config == "consumer_on":
            c = dataclasses.replace(c, model_consumer=True)
    m = CompactionModel(c)
    inv = (args.invariant,) if args.invariant else ()
    if args.engine == "liveness":
        from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

        lck = LivenessChecker(
            m, goal=args.goal, fairness=args.fairness,
            frontier_chunk=args.frontier_chunk,
            sweep_chunk=args.sweep_chunk,
            visited_cap=1 << 13,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.every,
            telemetry=args.telemetry,
            heartbeat_s=args.progress,
        )
        lr = lck.run(resume=args.resume)
        print(
            json.dumps(
                {
                    "holds": lr.holds,
                    "reason": lr.reason,
                    "distinct_states": lr.distinct_states,
                    "truncated": lr.truncated,
                    "stop_reason": lr.stop_reason,
                    "lasso_prefix": lr.lasso_prefix,
                    "lasso_cycle": lr.lasso_cycle,
                }
            )
        )
        return 0
    if args.engine == "device":
        from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

        hbm_budget = args.hbm_budget
        if hbm_budget and hbm_budget.startswith("min+"):
            # resolve "minimum viable + N" against a throwaway probe so
            # the drill pins a TIGHT budget without hard-coding bytes
            # (the shared helpers.tight_hbm_budget recipe)
            from tests.helpers import tight_hbm_budget

            hbm_budget = tight_hbm_budget(
                lambda b: DeviceChecker(
                    m, invariants=inv, sub_batch=args.sub_batch,
                    visited_cap=args.visited_cap,
                    frontier_cap=args.visited_cap // 2,
                    max_states=args.max_states, hbm_budget=b,
                ),
                slack=int(hbm_budget[4:]),
            )
        ck = DeviceChecker(
            m, invariants=inv, sub_batch=args.sub_batch,
            visited_cap=args.visited_cap,
            frontier_cap=args.visited_cap // 2,
            max_states=args.max_states,
            hbm_budget=hbm_budget,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.every,
            telemetry=args.telemetry,
            heartbeat_s=args.progress,
        )
    else:
        from pulsar_tlaplus_tpu.engine.sharded_device import (
            ShardedDeviceChecker,
        )

        ck = ShardedDeviceChecker(
            m, n_devices=4, invariants=inv, sub_batch=512,
            visited_cap=1 << 13, max_states=args.max_states,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.every,
            telemetry=args.telemetry,
            heartbeat_s=args.progress,
        )
    r = ck.run(resume=args.resume)
    print(
        json.dumps(
            {
                "distinct_states": r.distinct_states,
                "diameter": r.diameter,
                "level_sizes": r.level_sizes,
                "truncated": r.truncated,
                "stop_reason": r.stop_reason,
                "violation": r.violation,
                "violation_gid": r.violation_gid,
                "trace": (
                    [repr(s) for s in r.trace]
                    if r.trace is not None
                    else None
                ),
                "trace_actions": (
                    list(r.trace_actions)
                    if r.trace_actions is not None
                    else None
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
