"""Unified-telemetry tests (ISSUE r8): the structured JSONL event
stream, zero-sync device counters, the TLC-style progress heartbeat,
resume linking across kill->resume runs, frame-write stall accounting,
and the schema validator that gates BENCH artifacts."""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import FPM_N, DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import report, telemetry
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.utils import ckpt

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KW = dict(sub_batch=2048, visited_cap=1 << 16, frontier_cap=1 << 15)


def _shipped():
    return CompactionModel(pe.SHIPPED_CFG)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def checker_mod():
    return _load_script("check_telemetry_schema")


@pytest.fixture(scope="module")
def std_run(tmp_path_factory):
    """One telemetry-instrumented device run on the shipped config
    (with checkpointing), shared by the schema/report/counter tests."""
    tmp = tmp_path_factory.mktemp("tel")
    stream = str(tmp / "run.jsonl")
    frame = str(tmp / "run.npz")
    ck = DeviceChecker(
        _shipped(), telemetry=stream, checkpoint_path=frame,
        checkpoint_every=5, **KW,
    )
    r = ck.run()
    events = [json.loads(x) for x in open(stream)]
    return stream, frame, ck, r, events


# ---- stream schema ---------------------------------------------------


def test_stream_validates_and_has_lifecycle(std_run, checker_mod):
    """Every line parses and carries the base envelope; the stream has
    the run lifecycle: header, levels, per-flush records, checkpoint
    frames, and a result whose stats carry the zero-sync counters."""
    stream, _frame, ck, r, events = std_run
    assert r.distinct_states == 45198
    assert checker_mod.validate_stream(stream) == []
    kinds = {e["event"] for e in events}
    assert {"run_header", "level", "flush", "ckpt_frame", "result"} \
        <= kinds
    for e in events:
        assert e["v"] == telemetry.SCHEMA_VERSION
        assert isinstance(e["t"], (int, float))
        assert e["run_id"]
    # seq is strictly increasing within the stream
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    hdr = events[0]
    assert hdr["event"] == "run_header"
    assert hdr["engine"] == "device_bfs"
    assert hdr["visited_impl"] == "fpset"
    res = events[-1]
    assert res["event"] == "result"
    assert res["distinct_states"] == 45198
    assert res["diameter"] == 20


def test_zero_sync_counters_ride_the_stats_fetch(std_run):
    """The device counters vector carries flushes/rounds/failures/
    valid_lanes/max_rounds (FPM_N) and their aggregates agree between
    the stream's flush deltas and the final result stats — with no
    telemetry-specific fetches (one flush record per stats fetch at
    most)."""
    _stream, _frame, ck, r, events = std_run
    # r12: valid_lanes split into hi/lo uint32 words (int32-wrap fix)
    assert FPM_N == 6
    stats = [e for e in events if e["event"] == "result"][-1]["stats"]
    flushes = [e for e in events if e["event"] == "flush"]
    assert stats["fpset_flushes"] == sum(e["flushes"] for e in flushes)
    assert stats["fpset_probe_rounds"] == sum(
        e["probe_rounds"] for e in flushes
    )
    assert stats["fpset_valid_lanes"] == sum(
        e["valid_lanes"] for e in flushes
    )
    # every distinct state was a valid candidate lane once
    assert stats["fpset_valid_lanes"] >= r.distinct_states
    assert stats["fpset_max_probe_rounds"] >= 1
    assert 0.0 <= stats["fpset_duplicate_ratio"] < 1.0
    # dispatch counters ride for free (no PTT_STAGE_TIMING barrier).
    # Since r13 the level megakernel runs its flushes in-device: the
    # device flush count = stage-chain flush dispatches (the init
    # path) + the flushes the `fuse` records account per dispatch
    fuse_flushes = sum(
        e.get("flushes", 0)
        for e in events
        if e["event"] == "fuse"
    )
    assert (
        stats["stage_flush_n"] + fuse_flushes == stats["fpset_flushes"]
    )
    assert "stage_flush_s" not in stats  # timing stays legacy-only
    # flush records only ever ride an existing fetch
    assert len(flushes) <= stats["stats_fetches"]


def test_ckpt_frame_stall_accounting(std_run):
    """Frame writes record their write-stall seconds per frame and the
    run total lands in last_stats (the BENCH_r07 ckpt_write_s ask)."""
    _stream, frame, ck, r, events = std_run
    frames = [e for e in events if e["event"] == "ckpt_frame"]
    assert frames and os.path.exists(frame)
    for i, e in enumerate(frames):
        assert e["frame_seq"] == i + 1
        assert e["bytes"] > 0
        assert e["write_s"] >= 0.0
        assert e["stall_s"] >= e["write_s"]
    assert ck.last_stats["ckpt_frames"] == len(frames)
    assert ck.last_stats["ckpt_write_s"] >= sum(
        e["write_s"] for e in frames
    ) * 0.5  # rounding slack


def test_frame_meta_roundtrip(tmp_path):
    p = str(tmp_path / "f.npz")
    import numpy as np

    nbytes, write_s, retries = ckpt.save_frame(
        p, "sig", {"a": np.arange(3)},
        meta={"run_id": "abc", "frame_seq": 7},
    )
    assert nbytes > 0 and write_s >= 0.0 and retries == 0
    d = ckpt.load_frame(p, "sig")
    assert ckpt.frame_meta(d) == {"run_id": "abc", "frame_seq": 7}
    # frames without meta read back as {}
    nbytes, _, _ = ckpt.save_frame(p, "sig", {"a": np.arange(3)})
    assert ckpt.frame_meta(ckpt.load_frame(p, "sig")) == {}


# ---- kill -> resume stream linking -----------------------------------


def _run_sub(args, fault=None, expect_kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PTT_FAULT", None)
    if fault:
        env["PTT_FAULT"] = fault
    proc = subprocess.run(
        [sys.executable, "-m", "tests._survivable_run", *args],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=ROOT,
    )
    if expect_kill:
        assert proc.returncode == 137, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        return None
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_kill_resume_stream_links_prior_frame(tmp_path, checker_mod):
    """A killed run's stream ends with a fault breadcrumb and complete
    frames; the resumed run's header links the prior run's last frame
    event (run_id + frame_seq) — the chain the ISSUE demands."""
    frame = str(tmp_path / "kill.npz")
    s1 = str(tmp_path / "s1.jsonl")
    s2 = str(tmp_path / "s2.jsonl")
    _run_sub(
        ["--checkpoint", frame, "--every", "2", "--telemetry", s1],
        fault="kill@level:8", expect_kill=True,
    )
    assert os.path.exists(frame)
    out = _run_sub(
        ["--checkpoint", frame, "--telemetry", s2, "--resume"]
    )
    assert out["distinct_states"] == 45198
    # both streams validate line-for-line, even the killed one
    assert checker_mod.validate_stream(s1) == []
    assert checker_mod.validate_stream(s2) == []
    e1 = [json.loads(x) for x in open(s1)]
    e2 = [json.loads(x) for x in open(s2)]
    # the kill left a breadcrumb BEFORE dying
    faults_seen = [e for e in e1 if e["event"] == "fault"]
    assert faults_seen and faults_seen[0]["kind"] == "kill"
    assert e1[-1] is not None  # last line is complete (validated above)
    frames1 = [e for e in e1 if e["event"] == "ckpt_frame"]
    assert frames1
    hdr2 = [e for e in e2 if e["event"] == "run_header"][0]
    assert hdr2["resume"] is True
    assert hdr2["resume_of"] == e1[0]["run_id"]
    assert hdr2["resume_frame_seq"] == frames1[-1]["frame_seq"]
    # and the resumed run is a different run_id (streams chain, not mix)
    assert hdr2["run_id"] != e1[0]["run_id"]


# ---- heartbeat -------------------------------------------------------


def test_heartbeat_cadence_and_zero_extra_syncs(tmp_path):
    """The heartbeat emits at its cadence on a small oracle run and
    adds ZERO device syncs: the stats-fetch count is identical with
    the heartbeat on and off."""
    m = _shipped()
    base = DeviceChecker(m, **KW)
    r0 = base.run()
    stream = str(tmp_path / "hb.jsonl")
    hb = DeviceChecker(
        m, telemetry=stream, heartbeat_s=0.05, **KW
    )
    r1 = hb.run()
    assert r1.distinct_states == r0.distinct_states == 45198
    assert hb._fetch_n == base._fetch_n  # the zero-sync contract
    beats = [
        json.loads(x)
        for x in open(stream)
        if json.loads(x)["event"] == "progress"
    ]
    # a ~5+s run at 50 ms cadence: plenty of beats, each well-formed
    assert len(beats) >= 3
    for b in beats:
        assert b["distinct_states"] >= 0
        assert "states_per_sec" in b
    # beats carry snapshot data (level/occupancy) once levels exist
    assert any("level" in b and "occupancy" in b for b in beats)


def test_heartbeat_sigterm_clean_exit(tmp_path, checker_mod):
    """A preemption (SIGTERM mid-run) with the heartbeat on exits
    resumably with a COMPLETE stream: no torn lines, a final result
    record with stop_reason=preempted, and the heartbeat thread never
    outlives the run."""
    frame = str(tmp_path / "pre.npz")
    stream = str(tmp_path / "pre.jsonl")
    out = _run_sub(
        [
            "--checkpoint", frame, "--every", "2",
            "--telemetry", stream, "--progress", "0.05",
        ],
        fault="sigterm@level:4",
    )
    assert out["truncated"] is True
    assert out["stop_reason"] == "preempted"
    assert checker_mod.validate_stream(stream) == []
    events = [json.loads(x) for x in open(stream)]
    assert events[-1]["event"] == "result"
    assert events[-1]["stop_reason"] == "preempted"
    assert any(e["event"] == "fault" for e in events)
    assert any(e["event"] == "progress" for e in events)


# ---- report layer ----------------------------------------------------


def test_report_reproduces_bench_keys(std_run):
    """scripts/telemetry_report.py --bench-keys reproduces every
    fpset_*/ckpt_* BENCH key from the stream alone — no hand-editing."""
    stream, _frame, ck, r, events = std_run
    keys = report.bench_keys(events)
    for k in (
        "fpset_flushes", "fpset_probe_rounds", "fpset_avg_probe_rounds",
        "fpset_failures", "fpset_occupancy", "fpset_valid_lanes",
        "fpset_max_probe_rounds", "ckpt_frames", "ckpt_bytes",
        "ckpt_write_s",
    ):
        assert k in keys, k
        assert keys[k] == ck.last_stats[k], k
    assert keys["distinct_states"] == r.distinct_states
    assert keys["stop_reason"] is None
    # the CLI front-end agrees with the library
    rep = _load_script("telemetry_report")
    rc = rep.main([stream, "--bench-keys"])
    assert rc == 0


def test_report_rtt_correction():
    """Legacy stage timings are corrected by n x rtt (satellite 2: the
    ~130 ms/drain RTT was documented but never subtracted)."""
    events = [
        {
            "v": 1, "event": "run_header", "t": 0.0, "seq": 0,
            "run_id": "x", "engine": "device_bfs",
            "visited_impl": "fpset", "config_sig": "s",
        },
        {
            "v": 1, "event": "result", "t": 9.0, "seq": 1,
            "run_id": "x", "distinct_states": 10, "diameter": 2,
            "wall_s": 9.0, "truncated": False,
            "stats": {
                "rtt_s": 0.13,
                "stage_flush_s": 5.0, "stage_flush_n": 10,
                "stage_expand_s": 1.0, "stage_expand_n": 20,
            },
        },
    ]
    split = report.stage_split(events)
    assert split["flush"]["device_s"] == pytest.approx(5.0 - 1.3)
    # over-subtraction floors at zero instead of going negative
    assert split["expand"]["device_s"] == 0.0
    table = report.render_stage_table([("run", events)])
    assert "flush" in table and "RTT-corrected" in table


def test_stage_table_differential_shape():
    """Two streams render the BASELINE round-6 comparison table with a
    ratio column."""
    def mk(flush_s):
        return [
            {
                "v": 1, "event": "result", "t": 1.0, "seq": 0,
                "run_id": "x", "distinct_states": 1, "diameter": 1,
                "wall_s": 44.3, "truncated": False,
                "stats": {
                    "stage_flush_s": flush_s, "stage_flush_n": 45,
                    "rtt_s": 0.0,
                },
            }
        ]

    table = report.render_stage_table(
        [("sort-merge", mk(38.8)), ("fpset", mk(7.5))]
    )
    assert "| Stage | sort-merge | fpset | ratio |" in table
    assert "5.2x" in table


# ---- schema validator (the tier-1 gate) ------------------------------


def test_validator_rejects_bad_streams(tmp_path, checker_mod):
    p = str(tmp_path / "bad.jsonl")
    with open(p, "w") as f:
        f.write('{"v": 1, "event": "level", "t": 1.0}\n')  # no run_id
        f.write("not json\n")
        f.write(
            '{"v": 99, "event": "x", "t": 0.5, "seq": 2, "run_id": "r"}\n'
        )
    errs = checker_mod.validate_stream(p)
    assert len(errs) == 3
    assert any("missing base fields" in e for e in errs)
    assert any("unparseable" in e for e in errs)
    assert any("newer than supported" in e for e in errs)
    # monotonic-t violation within one run_id
    p2 = str(tmp_path / "order.jsonl")
    with open(p2, "w") as f:
        f.write(
            '{"v": 1, "event": "a", "t": 2.0, "seq": 0, "run_id": "r"}\n'
        )
        f.write(
            '{"v": 1, "event": "a", "t": 1.0, "seq": 1, "run_id": "r"}\n'
        )
    assert any(
        "went backwards" in e for e in checker_mod.validate_stream(p2)
    )


def test_validator_accepts_repo_bench_artifacts(checker_mod):
    """Every BENCH_*.json the repo ships validates under its declared
    bench_schema — the artifact-regression gate the ISSUE asks for."""
    import glob

    arts = sorted(glob.glob(os.path.join(ROOT, "BENCH_*.json")))
    assert arts
    for p in arts:
        assert checker_mod.validate_bench_artifact(p) == [], p


def test_validator_bench_schema3_requirements(checker_mod):
    good = {
        "bench_schema": 3, "metric": "m", "value": 1.0, "unit": "u",
        "vs_baseline": 1.0, "vs_baseline_definition": "d",
        "distinct_states": 1, "levels": 1, "compile_warmup_s": 0.0,
        "stop_reason": None, "truncated": False, "hbm_recovered": 0,
        "ckpt_frames": 0, "ckpt_bytes": 0, "ckpt_write_s": 0.0,
        "fpset_flushes": 1, "fpset_probe_rounds": 1,
        "fpset_avg_probe_rounds": 1.0, "fpset_failures": 0,
        "fpset_occupancy": 0.1, "fpset_valid_lanes": 1,
        "fpset_max_probe_rounds": 1, "visited_impl": "fpset",
        "max_states": 1, "stats_fetches": 1,
    }
    assert checker_mod.validate_bench_artifact(dict(good), "g") == []
    bad = dict(good)
    del bad["ckpt_write_s"]
    errs = checker_mod.validate_bench_artifact(bad, "b")
    assert errs and "ckpt_write_s" in errs[0]
    # schema 2 artifacts are NOT held to the r8 key set
    v2 = {
        k: good[k]
        for k in (
            "metric", "value", "unit", "vs_baseline",
            "vs_baseline_definition", "distinct_states", "levels",
            "compile_warmup_s",
        )
    }
    v2["bench_schema"] = 2
    assert checker_mod.validate_bench_artifact(v2, "v2") == []


# ---- telemetry primitives --------------------------------------------


def test_null_telemetry_and_as_telemetry(tmp_path):
    assert telemetry.as_telemetry(None) is telemetry.NULL
    telemetry.NULL.emit("anything", x=1)  # no-op, no error
    p = str(tmp_path / "t.jsonl")
    t = telemetry.as_telemetry(p, run_id="rid1")
    assert telemetry.as_telemetry(t) is t
    t.emit("custom_event", foo="bar")
    t.close()
    t.emit("after_close")  # swallowed, never raises
    recs = [json.loads(x) for x in open(p)]
    assert len(recs) == 1
    assert recs[0]["run_id"] == "rid1"
    assert recs[0]["foo"] == "bar"
    # ownership: engines close streams they opened, never caller-passed
    assert telemetry.owns_stream(p) and telemetry.owns_stream(None)
    assert not telemetry.owns_stream(t)
    assert not telemetry.owns_stream(telemetry.NULL)


def test_caller_owned_stream_survives_engine_run(tmp_path):
    """A caller-passed Telemetry instance collects MULTIPLE runs into
    one stream: the engine must not close it (code-review finding)."""
    p = str(tmp_path / "shared.jsonl")
    t = telemetry.Telemetry(p, run_id="shared1")
    m = _shipped()
    DeviceChecker(m, telemetry=t, max_states=2_000, **KW).run()
    DeviceChecker(m, telemetry=t, max_states=2_000, **KW).run()
    t.close()
    recs = [json.loads(x) for x in open(p)]
    assert sum(1 for r in recs if r["event"] == "result") == 2
    # monotonic t holds across both runs (single stream clock)
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)


def test_heartbeat_thread_stops_cleanly():
    snap = {"distinct_states": 0}
    hb = telemetry.Heartbeat(0.02, snap, log=lambda m: None)
    with hb:
        snap["distinct_states"] = 10
        time.sleep(0.15)
    assert hb.beats >= 2
    assert hb._thread is None  # joined


def test_fpset_wrapper_emits(tmp_path):
    import jax.numpy as jnp

    from pulsar_tlaplus_tpu.ops.fpset import FPSet

    p = str(tmp_path / "fp.jsonl")
    s = FPSet(2, cap=1 << 12, telemetry=p)
    k = (
        jnp.arange(100, dtype=jnp.uint32),
        jnp.arange(100, dtype=jnp.uint32) * 7,
    )
    s.insert(k)
    s.close()
    recs = [json.loads(x) for x in open(p)]
    assert recs and recs[0]["event"] == "fpset_insert"
    assert recs[0]["n"] == 100


def test_fault_observer_breadcrumb(monkeypatch):
    from pulsar_tlaplus_tpu.utils import faults

    seen = []
    monkeypatch.setenv("PTT_FAULT", "oom@level:3")
    faults.reset()
    faults.set_observer(lambda k, s, c: seen.append((k, s, c)))
    try:
        assert faults.poll("level", 3) == ("oom",)
    finally:
        faults.set_observer(None)
        faults.reset()
    assert seen == [("oom", "level", 3)]


# ---- schema v14 + bench_schema 11 (fleet survivability, r21) ---------


def test_validator_v14_survivability_events(tmp_path, checker_mod):
    """The r21 events — ``reconcile`` (a lost job answered for by its
    rejoined backend), ``partition`` (a drained backend rejoined
    still holding its jobs), ``recover`` (a ``--recover`` table
    rebuild) — validate with their required fields and fail without
    them; v13-and-older records are NOT held to them (FIELD_SINCE)."""
    good = str(tmp_path / "v14.jsonl")
    with open(good, "w") as f:
        for seq, (event, fields) in enumerate([
            ("recover", {"jobs": 3}),
            ("partition", {"backend": "b0.sock"}),
            ("reconcile", {"backend": "b0.sock", "job_id": "j1",
                           "state": "done"}),
        ]):
            f.write(json.dumps({
                "v": 14, "event": event, "t": float(seq),
                "seq": seq, "run_id": "surv", **fields,
            }) + "\n")
    assert checker_mod.validate_stream(good) == []

    bad = str(tmp_path / "v14-bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps({  # reconcile without the real state
            "v": 14, "event": "reconcile", "t": 0.0, "seq": 0,
            "run_id": "surv", "backend": "b0.sock",
        }) + "\n")
    errs = checker_mod.validate_stream(bad)
    assert any("reconcile missing" in e for e in errs), errs

    # committed v13 streams stay clean: the fields are since-14
    old = str(tmp_path / "v13.jsonl")
    with open(old, "w") as f:
        f.write(json.dumps({
            "v": 13, "event": "reconcile", "t": 0.0, "seq": 0,
            "run_id": "surv",
        }) + "\n")
    assert checker_mod.validate_stream(old) == []


def test_validator_v14_multi_incarnation_stream(tmp_path, checker_mod):
    """A dispatcher restarted after kill -9 APPENDS to its stream:
    distinct run_ids interleave legally (per-run monotonicity only),
    but one run's writer repeating a seq is still a torn stream."""
    p = str(tmp_path / "incarnations.jsonl")
    with open(p, "w") as f:
        for rid in ("life1", "life2", "life3"):
            for seq in range(2):
                f.write(json.dumps({
                    "v": 14, "event": "route", "t": float(seq),
                    "seq": seq, "run_id": rid, "backend": "b0",
                    "tenant": "local",
                }) + "\n")
    assert checker_mod.validate_stream(p) == []
    with open(p, "a") as f:
        f.write(json.dumps({  # life2 repeats seq 1: torn
            "v": 14, "event": "route", "t": 9.0, "seq": 1,
            "run_id": "life2", "backend": "b0", "tenant": "local",
        }) + "\n")
    errs = checker_mod.validate_stream(p)
    assert any("seq not increasing" in e for e in errs), errs


def test_bench_schema11_requires_fleet_survivability_keys(checker_mod):
    d = {k: None for k in checker_mod.BENCH_KEYS_V11}
    d.update(bench_schema=11, value=1.0)
    assert checker_mod.validate_bench_artifact(d) == []
    for k in ("fleet_failover_ms", "fleet_reconcile_ms"):
        broken = dict(d)
        del broken[k]
        errs = checker_mod.validate_bench_artifact(broken)
        assert any(k in e for e in errs), (k, errs)
    # schema-10 artifacts (committed r20 history) do NOT need them
    d10 = {k: None for k in checker_mod.BENCH_KEYS_V10}
    d10.update(bench_schema=10, value=1.0)
    assert checker_mod.validate_bench_artifact(d10) == []
