"""Fleet observability plane (ISSUE 18, r22): distributed tracing,
aggregated metrics with latency histograms, and the flight deck.

The acceptance bars (docs/observability.md, "Fleet plane"):

- one ``trace_id`` per accepted submit, minted at the dispatcher,
  stamped on every hop: the ``route`` record (with the split
  ``route_ms``/``ack_ms`` decision-vs-ack latencies), the backend's
  ``job_*`` echoes, the engine slice ``run_header``s, and the closing
  ``complete`` record with the wall-clock end-to-end latency — and a
  retried ``submit_id`` dedups to the SAME trace;
- a failed-over job is ONE chain: the ``failover`` record carries the
  affected ``trace_ids`` and the id spans both backends' streams;
- ``metrics --aggregate`` re-emits every live backend's families
  under a ``backend`` label beside fleet rollups and well-formed
  fixed-bucket histograms; a backend down mid-scrape degrades to
  ``ptt_fleet_scrape_errors`` instead of failing the scrape;
- the ``ptt_fleet_*`` families — histograms included — render
  IDENTICALLY from the live dispatcher and a replay of its stream
  (the r12 live-vs-stream contract extended to the fleet tier,
  closing the held_sheds/persist_failures replay gaps);
- the stitched Perfetto export (dispatcher stream + backend streams)
  validates clean and carries flow arrows binding each job's spans
  across process tracks;
- ``top --dispatch`` renders the whole fleet from one poll.

The schema-level pieces (v15 trace_id gating, the ``--metrics``
histogram-consistency validator, the ``--jobs`` fleet columns) are
unit-tested here against synthetic streams; the live assertions ride
a real 2-backend mini fleet.
"""

import json
import os
import time

import pytest

from pulsar_tlaplus_tpu.fleet.dispatcher import (
    FleetConfig,
    FleetDispatcher,
)
from pulsar_tlaplus_tpu.obs import metrics as metrics_mod
from pulsar_tlaplus_tpu.obs import report as report_mod
from pulsar_tlaplus_tpu.obs import top as top_mod
from pulsar_tlaplus_tpu.obs import trace as trace_mod
from pulsar_tlaplus_tpu.obs.telemetry import SCHEMA_VERSION
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.client import ServiceClient
from pulsar_tlaplus_tpu.service.server import ServiceDaemon

from tests.test_service import (  # noqa: F401  (fixtures by name)
    _config,
    _load_script,
    assert_result_matches_solo,
    cfg_dir,
    checker_mod,
    pool,
    solo_compaction,
)


def _events(path):
    evs, _errs = report_mod.load_events(path)
    return evs

def _wait(pred, timeout=60.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def obs_fleet(tmp_path_factory, pool):
    """One 2-backend fleet for the module (the test_fleet shape:
    backend0 holds the warmed shared pool, backend1 compiles its
    own); health ticks fast so the job sweep emits ``complete``
    records promptly."""
    root = tmp_path_factory.mktemp("obsfleet")
    configs = [
        _config(root / "b0", slice_s=0.3),
        _config(root / "b1", slice_s=0.3),
    ]
    daemons = [
        ServiceDaemon(configs[0], pool=pool),
        ServiceDaemon(configs[1]),
    ]
    for d in daemons:
        d.start()
    fc = FleetConfig(
        state_dir=str(root / "disp"),
        backends=tuple(c.socket_path for c in configs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    cl = ServiceClient(fc.socket_path, timeout=240.0)
    state = dict(
        daemons=daemons, configs=configs, disp=disp, client=cl,
        addrs=[c.socket_path for c in configs], fc=fc,
        dispatch_stream=os.path.join(fc.state_dir, "dispatch.jsonl"),
    )
    try:
        yield state
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()


# ---- histogram math (the metrics layer, no fleet needed) ------------


def test_histogram_buckets_cumulative_and_quantiles():
    """Fixed-bucket math: samples land in the right ``le`` bucket,
    ``cumulative()`` ends at +Inf == count, and the interpolated
    quantiles bracket the observations."""
    h = metrics_mod.Histogram()
    assert h.bounds == metrics_mod.LATENCY_BUCKETS_S
    # 3ms -> the (0.0025, 0.005] bucket; 40ms -> (0.025, 0.05];
    # 500s -> the +Inf overflow bucket
    for v in (0.003, 0.003, 0.040, 500.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(0.003 + 0.003 + 0.040 + 500.0)
    cum = h.cumulative()
    assert cum[-1] == ("+Inf", 4)
    by_le = dict(cum)
    assert by_le["0.0025"] == 0
    assert by_le["0.005"] == 2
    assert by_le["0.05"] == 3
    assert by_le["120"] == 3  # the 500s sample only in +Inf

    pairs = [(float(le), n) for le, n in cum[:-1]]
    pairs.append((float("inf"), 4))
    p50 = metrics_mod.histogram_quantile(0.5, pairs)
    assert 0.0025 <= p50 <= 0.005
    # a quantile landing in the +Inf bucket floors at the largest
    # finite edge instead of fabricating an unbounded value
    p99 = metrics_mod.histogram_quantile(0.99, pairs)
    assert p99 == pytest.approx(120.0)
    assert metrics_mod.histogram_quantile(0.5, []) is None


def test_fleet_hists_from_events_bins_ms_fields():
    """Stream replay derives the six ``ptt_fleet_*_seconds``
    histograms from the v15 ``*_ms`` fields — non-numeric latencies
    (an adopted job's null ``e2e_ms``) are skipped, never crash."""
    events = [
        {"event": "route", "route_ms": 3.0, "ack_ms": 12.0},
        {"event": "complete", "e2e_ms": 800.0},
        {"event": "complete", "e2e_ms": None},  # adopted job
        {"event": "relay", "leg_ms": 40.0},
        {"event": "failover", "wall_ms": 90.0},
        {"event": "partition", "wall_ms": 150.0},
    ]
    hists = metrics_mod.fleet_hists_from_events(events)
    assert set(hists) == {
        name for name, _h, _e, _f in metrics_mod.FLEET_HIST_SPECS
    }
    assert hists["ptt_fleet_route_seconds"].count == 1
    assert hists["ptt_fleet_submit_ack_seconds"].count == 1
    assert hists["ptt_fleet_job_e2e_seconds"].count == 1  # null skipped
    assert hists["ptt_fleet_watch_leg_seconds"].count == 1
    assert hists["ptt_fleet_failover_seconds"].count == 1
    assert hists["ptt_fleet_reconcile_seconds"].count == 1
    # ms -> s binning: 800ms lands in the (0.5, 1.0] bucket
    by_le = dict(hists["ptt_fleet_job_e2e_seconds"].cumulative())
    assert by_le["0.5"] == 0 and by_le["1"] == 1


# ---- exposition validator (satellite: positive + negative) ----------


def _hist_exposition() -> str:
    h = metrics_mod.Histogram()
    for v in (0.003, 0.040, 0.041):
        h.observe(v)
    fam = metrics_mod.Family(
        "ptt_fleet_route_seconds", "histogram", "route decision"
    ).add_hist(h)
    return metrics_mod.render_exposition([fam])


def test_validate_exposition_clean_on_rendered_histogram():
    text = _hist_exposition()
    assert metrics_mod.validate_exposition(text) == []


def test_validate_exposition_flags_tampered_histograms():
    """Each consistency rule trips on the matching corruption: a
    dropped +Inf bucket, a ``_count`` that disagrees with it,
    non-cumulative buckets, and a ``_sum`` outside what the buckets
    admit."""
    text = _hist_exposition()

    no_inf = "\n".join(
        ln for ln in text.splitlines() if 'le="+Inf"' not in ln
    )
    assert any(
        "no +Inf bucket" in e
        for e in metrics_mod.validate_exposition(no_inf)
    )

    bad_count = text.replace("ptt_fleet_route_seconds_count 3",
                             "ptt_fleet_route_seconds_count 5")
    assert any(
        "_count" in e
        for e in metrics_mod.validate_exposition(bad_count)
    )

    # shrink one mid-series cumulative bucket below its predecessor
    shrunk = text.replace(
        'ptt_fleet_route_seconds_bucket{le="0.05"} 3',
        'ptt_fleet_route_seconds_bucket{le="0.05"} 0',
    )
    assert shrunk != text
    assert any(
        "cumulative" in e
        for e in metrics_mod.validate_exposition(shrunk)
    )

    # all three observations sit inside finite buckets, so a huge
    # _sum breaks the bucket ceiling; a negative one the floor
    big = text.replace("ptt_fleet_route_seconds_sum 0.084",
                       "ptt_fleet_route_seconds_sum 999")
    assert big != text
    assert any(
        "ceiling" in e for e in metrics_mod.validate_exposition(big)
    )


def test_check_schema_metrics_flag(tmp_path, checker_mod):
    """``check_telemetry_schema.py --metrics`` exits 0 on a clean
    exposition file and 1 on a tampered one."""
    good = tmp_path / "good.prom"
    good.write_text(_hist_exposition())
    assert checker_mod.main(["--metrics", str(good)]) == 0
    bad = tmp_path / "bad.prom"
    bad.write_text(
        _hist_exposition().replace(
            "ptt_fleet_route_seconds_count 3",
            "ptt_fleet_route_seconds_count 7",
        )
    )
    assert checker_mod.main(["--metrics", str(bad)]) == 1


# ---- v15 stream gating: trace_id required, null legal ---------------


def _line(seq, **rec):
    base = {
        "v": SCHEMA_VERSION, "event": "?", "t": float(seq) / 10.0,
        "seq": seq, "run_id": "r-obs",
    }
    base.update(rec)
    return json.dumps(base)


def test_v15_requires_trace_id_on_job_and_fleet_events(
    tmp_path, checker_mod
):
    """The FIELD_SINCE gate: a v15 ``job_submit`` (or ``route``)
    without the trace envelope fails; present-with-null passes; a
    committed v14 record without it stays clean."""
    ok = tmp_path / "ok.jsonl"
    ok.write_text("\n".join([
        _line(0, event="job_submit", job_id="j1", spec="compaction",
              trace_id=None),
        _line(1, event="route", backend="b0", tenant="local",
              trace_id="t" * 32, route_ms=1.0, ack_ms=2.0),
        _line(2, event="complete", job_id="j1", backend="b0",
              e2e_ms=5.0, trace_id="t" * 32),
        _line(3, event="persist_fail", n=1),
    ]) + "\n")
    assert checker_mod.validate_stream(str(ok)) == []

    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join([
        _line(0, event="job_submit", job_id="j1", spec="compaction"),
        _line(1, event="route", backend="b0", tenant="local"),
    ]) + "\n")
    errs = checker_mod.validate_stream(str(bad))
    assert any("job_submit missing" in e and "trace_id" in e
               for e in errs)
    assert any("route missing" in e for e in errs)

    old = tmp_path / "old.jsonl"
    old.write_text(
        _line(0, v=14, event="job_submit", job_id="j1",
              spec="compaction") + "\n"
    )
    assert checker_mod.validate_stream(str(old)) == []


# ---- --jobs fleet columns (trace_id join, synthetic streams) --------


def test_job_table_fleet_columns_join_by_trace_id():
    """``render_job_table`` with a dispatcher stream beside it: the
    owning backend comes from the chain (the COMPLETING backend after
    a failover, not the first route), hops = 1 + failovers, and the
    dispatcher-measured e2e seconds land beside the on-device wall."""
    tid = "a" * 32
    backend_events = [
        {"event": "job_submit", "job_id": "j1", "spec": "compaction",
         "trace_id": tid},
        {"event": "job_start", "job_id": "j1", "spec": "compaction",
         "slice": 1, "trace_id": tid},
        {"event": "job_result", "job_id": "j1", "status": "ok",
         "wall_s": 1.5, "trace_id": tid},
    ]
    fleet_events = [
        {"event": "route", "backend": "sock-A", "trace_id": tid},
        {"event": "failover", "backend": "sock-A",
         "trace_ids": [tid]},
        {"event": "complete", "job_id": "j1", "backend": "sock-B",
         "e2e_ms": 2500.0, "trace_id": tid},
    ]
    idx = report_mod.fleet_job_index(fleet_events)
    assert idx[tid] == {
        "backend": "sock-B", "hops": 2, "e2e_ms": 2500.0,
    }
    table = report_mod.render_job_table(
        backend_events, fleet_events=fleet_events
    )
    assert "backend | hops | e2e s |" in table.splitlines()[0]
    assert "sock-B | 2 | 2.50 |" in table
    # without the dispatcher stream the table keeps its old shape
    plain = report_mod.render_job_table(backend_events)
    assert "backend" not in plain.splitlines()[0]


# ---- live mini fleet: trace_id end to end ---------------------------


def test_trace_id_submit_to_engine_and_complete(
    obs_fleet, cfg_dir, solo_compaction
):
    """One submit through the dispatcher: the reply's ``trace_id``
    reappears on the route record (with ack >= route decision
    latency), every backend ``job_*`` echo, the engine slice
    ``run_header``, and the sweep's ``complete`` record with a
    positive wall-clock e2e — and a ``submit_id`` retry dedups to
    the SAME trace."""
    cl = obs_fleet["client"]
    r = cl.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], warm=False, submit_id="obs-trace-1",
        full=True,
    )
    tid, jid = r["trace_id"], r["job_id"]
    assert isinstance(tid, str) and len(tid) == 32
    w = cl.wait(jid, timeout=600.0)
    assert w["state"] == jobmod.DONE
    assert_result_matches_solo(
        type("R", (), {
            "result": w.get("result"), "state": w.get("state"),
            "error": w.get("error"),
        })(),
        solo_compaction,
    )

    again = cl.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg"),
        invariants=[], warm=False, submit_id="obs-trace-1",
        full=True,
    )
    assert again["job_id"] == jid
    assert again["trace_id"] == tid

    routes = [
        e for e in _events(obs_fleet["dispatch_stream"])
        if e.get("event") == "route" and e.get("trace_id") == tid
    ]
    assert routes
    for e in routes:
        assert e["ack_ms"] >= e["route_ms"] >= 0.0

    def completes():
        return [
            e for e in _events(obs_fleet["dispatch_stream"])
            if e.get("event") == "complete"
            and e.get("trace_id") == tid
        ]

    _wait(completes, timeout=60.0, what="complete record (job sweep)")
    comp = completes()[0]
    assert comp["backend"] == r["backend"]
    assert comp["job_id"] == jid
    assert comp["e2e_ms"] > 0.0

    owner_cfg = obs_fleet["configs"][
        obs_fleet["addrs"].index(r["backend"])
    ]
    job_events = [
        e for e in _events(owner_cfg.telemetry_path)
        if str(e.get("event", "")).startswith("job_")
        and e.get("job_id") == jid
    ]
    kinds = {e["event"] for e in job_events}
    assert {"job_submit", "job_result"} <= kinds
    assert all(e.get("trace_id") == tid for e in job_events)

    # the engine slice's run_header carries the id too — the last
    # stitch between the fleet chain and the on-device timeline
    engine_stream = os.path.join(
        owner_cfg.state_dir, "jobs", jid, "events.jsonl"
    )
    headers = [
        e for e in _events(engine_stream)
        if e.get("event") == "run_header"
    ]
    assert headers and all(h.get("trace_id") == tid for h in headers)

    # the live histograms saw the decision/ack/e2e samples
    snap = obs_fleet["disp"].metrics_snapshot()
    for fam in (
        "ptt_fleet_route_seconds", "ptt_fleet_submit_ack_seconds",
        "ptt_fleet_job_e2e_seconds",
    ):
        assert snap["hists"][fam].count >= 1, fam


# ---- live mini fleet: aggregate scrape + replay parity --------------


def test_aggregate_scrape_labels_rollups_and_wellformed_hists(
    obs_fleet,
):
    """``metrics --aggregate``: backend families re-emitted under a
    ``backend`` label, fleet job rollups summed across backends, the
    dispatcher's histogram families well-formed under the
    ``--metrics`` consistency validator, and no scrape errors while
    everyone is up."""
    text = obs_fleet["client"].metrics(aggregate=True)
    assert metrics_mod.validate_exposition(text, "aggregate") == []
    fams, types = metrics_mod.parse_exposition(text)
    # backend job tables ride in under their own label (a backend
    # with an empty table exports no ptt_jobs — absent beats zero —
    # so assert the label set is non-empty and well-formed, not full)
    job_labels = {
        lb.get("backend") for lb, _v in fams.get("ptt_jobs", [])
    }
    assert job_labels and job_labels <= set(obs_fleet["addrs"])
    # rollups summed across the scrape
    assert types.get("ptt_fleet_jobs") == "gauge"
    assert sum(v for _lb, v in fams["ptt_fleet_jobs"]) >= 1.0
    assert "ptt_fleet_queue_depth" in fams
    # the dispatcher's own histograms, unlabelled
    assert types.get("ptt_fleet_route_seconds") == "histogram"
    own_buckets = [
        (lb, v)
        for lb, v in fams["ptt_fleet_route_seconds_bucket"]
        if not lb.get("backend")
    ]
    assert own_buckets[-1][0]["le"] == "+Inf"
    assert "ptt_fleet_scrape_errors" not in fams  # everyone answered


def test_fleet_families_live_vs_stream_replay_parity(obs_fleet):
    """The r12 contract at the fleet tier (satellite): every
    ``ptt_fleet_*`` family the live dispatcher exports derives
    family-for-family — and for the counters + histograms below,
    value-for-value — from a replay of its own stream.  This pins
    the previously stream-invisible signals (holds, held sheds,
    persist failures) and the histogram re-binning."""
    live_fams, live_types = metrics_mod.parse_exposition(
        obs_fleet["client"].metrics()
    )
    stream_text = metrics_mod.render_stream_metrics(
        _events(obs_fleet["dispatch_stream"])
    )
    st_fams, st_types = metrics_mod.parse_exposition(stream_text)

    live_fleet = {n for n in live_types if n.startswith("ptt_fleet_")}
    st_fleet = {n for n in st_types if n.startswith("ptt_fleet_")}
    assert live_fleet == st_fleet
    assert "ptt_fleet_job_e2e_seconds" in live_fleet

    # counters agree exactly (the stream is the ledger of record)
    for fam in ("ptt_fleet_routes_total",):
        live_total = sum(v for _lb, v in live_fams.get(fam, []))
        st_total = sum(v for _lb, v in st_fams.get(fam, []))
        assert live_total == st_total, fam

    # histograms re-bin identically: same bucket lines, same counts
    for fam, kind in sorted(live_types.items()):
        if kind != "histogram":
            continue
        for suffix in ("_bucket", "_count", "_sum"):
            live_s = sorted(
                (tuple(sorted(lb.items())), v)
                for lb, v in live_fams.get(fam + suffix, [])
            )
            st_s = sorted(
                (tuple(sorted(lb.items())), v)
                for lb, v in st_fams.get(fam + suffix, [])
            )
            assert live_s == st_s, f"{fam}{suffix} diverged"


# ---- live mini fleet: stitched trace + flight deck ------------------


def test_stitched_trace_validator_clean_with_flow_arrows(
    obs_fleet, tmp_path
):
    """The dispatcher stream + both backend streams export as ONE
    Chrome trace: fleet spans on the dispatcher track, flow arrows
    (``s``/``t``/``f`` phases keyed by trace_id) binding the chain
    across tracks, and the whole file validator-clean."""
    streams = [
        ("dispatch", _events(obs_fleet["dispatch_stream"])),
        ("backend0", _events(obs_fleet["configs"][0].telemetry_path)),
        ("backend1", _events(obs_fleet["configs"][1].telemetry_path)),
    ]
    out = str(tmp_path / "fleet_trace.json")
    tr = trace_mod.write_trace(streams, out)
    assert trace_mod.validate_trace(out) == []
    phases = {}
    for e in tr["traceEvents"]:
        phases.setdefault(e.get("ph"), []).append(e)
    # route opens a flow, complete closes it
    assert phases.get("s"), "no flow-start events (route spans)"
    assert phases.get("f"), "no flow-end events (complete records)"
    for e in phases["s"] + phases.get("t", []) + phases["f"]:
        assert e.get("id"), "flow event without a trace_id binding"
    fleet_spans = [
        e for e in tr["traceEvents"] if e.get("cat") == "ptt.fleet"
    ]
    assert any(
        str(e.get("name", "")).startswith("route ")
        for e in fleet_spans
    )

    chains = trace_mod.trace_chains(streams)
    routed = [
        e["trace_id"] for e in streams[0][1]
        if e.get("event") == "route"
        and isinstance(e.get("trace_id"), str)
    ]
    for tid in routed:
        ch = chains[tid]
        assert ch["routes"] >= 1
        assert ch["job_events"] >= 1
        assert any(s.startswith("backend") for s in ch["streams"])


def test_top_dispatch_flight_deck_frame(obs_fleet):
    """One poll fills the fleet model (backend table, rollups,
    quantiles); the renderer is pure and the second poll grows rate
    sparklines — the ``top --dispatch --once`` path end to end."""
    cl = ServiceClient(obs_fleet["fc"].socket_path, timeout=240.0)
    model = top_mod.FleetTopModel(obs_fleet["fc"].socket_path)
    frame = top_mod.poll_dispatch_frame(cl, model)
    assert model.backends
    for addr in obs_fleet["addrs"]:
        assert addr in model.backends
        assert model.backends[addr].get("state") == "up"
    assert any(
        fam == "ptt_fleet_job_e2e_seconds"
        for fam, _p50, _p99, _n in model.quantiles
    )
    assert "BACKEND" in frame and "STATE" in frame
    assert "job e2e" in frame or "P50" in frame
    frame2 = top_mod.poll_dispatch_frame(cl, model)
    assert "BACKEND" in frame2


# ---- failover: one trace chain across two backends ------------------


def test_failover_chain_spans_both_backend_streams(
    tmp_path, pool, cfg_dir, solo_compaction
):
    """The acceptance bar's failed-over job: a queued job's owner
    dies, the dispatcher resubmits it to the survivor, and the SAME
    ``trace_id`` chains the dispatcher route, the ``failover``
    record's ``trace_ids``, and ``job_*`` echoes on BOTH backend
    streams; the degraded aggregate scrape reports the dead backend
    in ``ptt_fleet_scrape_errors`` instead of failing."""
    cfg_path = str(cfg_dir / "small_compaction.cfg")
    configs = [
        _config(tmp_path / "b0", slice_s=2.0),
        _config(tmp_path / "b1", slice_s=2.0),
    ]
    daemons = [
        ServiceDaemon(configs[0], pool=pool),
        ServiceDaemon(configs[1]),
    ]
    for d in daemons:
        d.start()
    addrs = [c.socket_path for c in configs]
    fc = FleetConfig(
        state_dir=str(tmp_path / "disp"),
        backends=tuple(addrs),
        health_interval_s=0.2,
        fail_after=2,
        backend_timeout_s=5.0,
    )
    disp = FleetDispatcher(fc)
    disp.start()
    cl = ServiceClient(fc.socket_path, timeout=240.0, retries=8)
    try:
        # pin one backend busy so the probe job QUEUES there (queued
        # jobs fail over; running jobs are typed lost)
        js = cl.submit(
            "compaction", cfg_path, mode="simulate",
            sim=dict(
                n_walkers=64, depth=32, segment_len=8,
                max_steps=1 << 22, seed=7,
            ),
            warm=False, submit_id="obs-fo-sim",
        )
        _wait(
            lambda: cl.status(js).get("state") == "running",
            timeout=120.0, what="sim start",
        )
        sub = cl.submit(
            "compaction", cfg_path, invariants=[], warm=False,
            submit_id="obs-fo-probe", full=True,
        )
        jid, owner, tid = sub["job_id"], sub["backend"], sub["trace_id"]
        assert cl.status(jid).get("state") == "queued"
        daemons[addrs.index(owner)].shutdown()
        _wait(
            lambda: disp.metrics_snapshot()["failovers"].get(owner),
            timeout=60.0, what="owner drain",
        )
        r = cl.wait(jid, timeout=600.0)
        assert r.get("state") == jobmod.DONE
        assert_result_matches_solo(
            type("R", (), {
                "result": r.get("result"), "state": r.get("state"),
                "error": r.get("error"),
            })(),
            solo_compaction,
        )

        # degraded aggregate scrape: the dead owner is reported, the
        # survivor still rides in labelled
        text = cl.metrics(aggregate=True)
        fams, _types = metrics_mod.parse_exposition(text)
        err_backends = {
            lb.get("backend")
            for lb, _v in fams.get("ptt_fleet_scrape_errors", [])
        }
        assert owner in err_backends
    finally:
        disp.shutdown()
        for d in daemons:
            d.shutdown()

    disp_events = _events(os.path.join(fc.state_dir, "dispatch.jsonl"))
    fo = [
        e for e in disp_events
        if e.get("event") == "failover" and e.get("backend") == owner
    ]
    assert fo and any(tid in (e.get("trace_ids") or []) for e in fo)
    assert all(
        isinstance(e.get("wall_ms"), (int, float)) for e in fo
    )

    streams = [("dispatch", disp_events)] + [
        (f"backend{i}", _events(c.telemetry_path))
        for i, c in enumerate(configs)
    ]
    chains = trace_mod.trace_chains(streams)
    ch = chains[tid]
    assert ch["failovers"] >= 1
    both = {f"backend{i}" for i in range(2)}
    assert both <= set(ch["streams"]), (
        f"chain {tid} did not span both backends: {ch}"
    )
    # and the stitched export of the whole incident validates clean
    out = str(tmp_path / "failover_trace.json")
    trace_mod.write_trace(streams, out)
    assert trace_mod.validate_trace(out) == []
