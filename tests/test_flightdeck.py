"""Flight-deck tests (r12): Perfetto trace export, the daemon
``metrics`` verb + file-scrape parity, the schema-v5 context-switch
fields, and the ``top`` dashboard's one-frame render.

The acceptance bar (ISSUE 8):

- ``cli.py trace`` on the 2-job service fixture stream produces a
  Perfetto-loadable JSON whose job-slice spans and context-switch gap
  spans sum (within 5%) to the daemon wall clock;
- a ``metrics`` scrape of a live daemon returns parseable Prometheus
  text with >= 10 metric families and adds ZERO device stats fetches
  (the same fetch-count harness as the heartbeat tests);
- stream-tail scraping exports identically-named engine families;
- trace export round-trips: valid JSON, every complete span has a
  non-negative duration, level spans nest monotonically per run.
"""

import importlib.util
import json
import os

import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models.compaction import CompactionModel
from pulsar_tlaplus_tpu.obs import metrics as metrics_mod
from pulsar_tlaplus_tpu.obs import report
from pulsar_tlaplus_tpu.obs import top as top_mod
from pulsar_tlaplus_tpu.obs import trace as trace_mod
from pulsar_tlaplus_tpu.obs.telemetry import Telemetry
from pulsar_tlaplus_tpu.ref import pyeval as pe
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.client import ServiceClient
from pulsar_tlaplus_tpu.service.scheduler import (
    CheckerPool,
    Scheduler,
    ServiceConfig,
)
from pulsar_tlaplus_tpu.service.server import ServiceDaemon

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BK_CFG = os.path.join(ROOT, "specs", "bookkeeper.cfg")

GEOM = dict(
    sub_batch=64,
    visited_cap=1 << 10,
    frontier_cap=1 << 8,
    max_states=1 << 20,
    checkpoint_every=1,
)

SMALL_COMPACTION_CFG = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 2
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = TRUE
    MaxCrashTimes = 1
    ModelProducer = TRUE
SPECIFICATION Spec
INVARIANTS
"""


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    return CheckerPool(
        ServiceConfig(
            state_dir=str(tmp_path_factory.mktemp("fd-pool")), **GEOM
        )
    )


@pytest.fixture(scope="module")
def solo_stream(tmp_path_factory):
    """One telemetry-instrumented solo run on the shipped 45,198-state
    oracle (checkpointing on) — the single-run trace/metrics fixture."""
    tmp = tmp_path_factory.mktemp("fd-solo")
    stream = str(tmp / "run.jsonl")
    ck = DeviceChecker(
        CompactionModel(pe.SHIPPED_CFG),
        telemetry=stream,
        checkpoint_path=str(tmp / "run.npz"),
        checkpoint_every=5,
        sub_batch=2048,
        visited_cap=1 << 16,
        frontier_cap=1 << 15,
    )
    r = ck.run()
    assert r.distinct_states == 45198
    events, errors = report.load_events(stream)
    assert not errors
    return stream, ck, r, events


@pytest.fixture(scope="module")
def service_run(tmp_path_factory, pool):
    """The 2-job time-sliced service fixture: both jobs queued before
    the loop starts (every slice expiry sees a waiter), a daemon-style
    telemetry stream collecting the v5 job lifecycle."""
    state = tmp_path_factory.mktemp("fd-two-job")
    (state / "small_compaction.cfg").write_text(SMALL_COMPACTION_CFG)
    config = ServiceConfig(
        state_dir=str(state / "state"), slice_s=0.3, **GEOM
    )
    svc_stream = str(state / "service.jsonl")
    tel = Telemetry(svc_stream)
    sched = Scheduler(config, pool=pool, telemetry=tel)
    j1 = sched.submit(
        "compaction", str(state / "small_compaction.cfg"),
        invariants=[],
    )
    j2 = sched.submit("bookkeeper", BK_CFG)
    sched.run_until_idle()
    tel.close()
    assert j1.state == j2.state == jobmod.DONE
    assert j1.suspends >= 1 and j2.suspends >= 1  # genuinely sliced
    events, errors = report.load_events(svc_stream)
    assert not errors
    return config, j1, j2, svc_stream, events


# ---- schema v5: the measured context switch -------------------------


def test_v5_suspend_resume_fields_and_validator(service_run):
    """Every job_resume carries the measured restore_s and every
    job_suspend its slice_wall_s + suspend-frame costs; the stream is
    v5-validator-clean."""
    _config, j1, j2, svc_stream, events = service_run
    checker = _load_script("check_telemetry_schema")
    assert checker.validate_stream(svc_stream) == []
    resumes = [e for e in events if e["event"] == "job_resume"]
    suspends = [e for e in events if e["event"] == "job_suspend"]
    assert len(suspends) == j1.suspends + j2.suspends
    assert len(resumes) == len(suspends)  # every suspend was resumed
    for e in resumes:
        assert e["v"] >= 5
        assert isinstance(e["restore_s"], float) and e["restore_s"] >= 0
    for e in suspends:
        assert isinstance(e["slice_wall_s"], float)
        assert e["slice_wall_s"] >= 0
        # the suspend frame's write/stall cost rides along
        assert e.get("frame_stall_s", 0.0) >= e.get(
            "frame_write_s", 0.0
        )
    # a v5 job_resume without restore_s must FAIL validation
    bad = dict(resumes[0])
    del bad["restore_s"]
    import tempfile

    with tempfile.NamedTemporaryFile(
        "w", suffix=".jsonl", delete=False
    ) as f:
        f.write(json.dumps(bad) + "\n")
    errs = checker.validate_stream(f.name)
    os.unlink(f.name)
    assert any("restore_s" in e for e in errs)


def test_jobs_report_overhead_columns(service_run):
    """telemetry_report --jobs carries the per-slice suspend-overhead
    columns: frame write+stall per suspend, restore per resume."""
    _config, j1, j2, _svc, events = service_run
    rows = {r["job_id"]: r for r in report.job_table(events)}
    for j in (j1, j2):
        r = rows[j.job_id]
        assert r["suspends"] == j.suspends
        assert r["resumes"] == j.suspends  # each suspend resumed once
        assert r["restore_s"] > 0
        assert r["slice_wall_s"] > 0
        assert r["frame_stall_s"] >= r["frame_write_s"] >= 0
    table = report.render_job_table(events)
    assert "susp s (write+stall)" in table and "restore s" in table
    # averages render as numbers, not the pre-v5 em-dash
    row1 = next(
        ln for ln in table.splitlines() if j1.job_id in ln
    )
    assert "—" not in row1.split("|")[6] + row1.split("|")[7]


# ---- trace export ---------------------------------------------------


def test_trace_roundtrip_solo_run(solo_stream, tmp_path):
    """Single-run export: valid JSON file, structurally valid events,
    one span per BFS level with monotonically increasing, non-
    overlapping extents, ckpt stalls as spans."""
    _stream, _ck, r, events = solo_stream
    out = str(tmp_path / "trace.json")
    tr = trace_mod.write_trace([("run", events)], out)
    with open(out) as f:
        again = json.load(f)  # valid JSON round-trip
    assert again["traceEvents"]
    assert trace_mod.validate_trace(out) == []
    levels = [
        e
        for e in tr["traceEvents"]
        if e.get("ph") == "X"
        and str(e.get("name", "")).startswith("level ")
    ]
    # one span per level record (r13: the fused engine emits exactly
    # one boundary record per level past the init level — no
    # intra-level fetch records on this no-growth shape)
    n_level_records = sum(1 for e in events if e["event"] == "level")
    assert len(levels) == n_level_records == r.diameter - 1
    ends = 0.0
    for e in sorted(levels, key=lambda e: e["ts"]):
        assert e["dur"] >= 0
        assert e["ts"] >= ends - 1e-6  # spans nest monotonically
        ends = e["ts"] + e["dur"]
    stalls = [
        e
        for e in tr["traceEvents"]
        if str(e.get("name", "")).startswith("ckpt frame")
    ]
    assert stalls and all(e["dur"] >= 0 for e in stalls)
    # counters ride beside the spans
    assert any(e.get("ph") == "C" for e in tr["traceEvents"])


def test_trace_job_slices_and_gaps_sum_to_daemon_wall(
    service_run, tmp_path
):
    """THE acceptance criterion: exporting the 2-job fixture stream
    yields job-slice spans and context-switch gap spans whose total
    duration equals (within 5%) the daemon wall clock between the
    first slice start and the last slice end."""
    _config, j1, j2, svc_stream, events = service_run
    from pulsar_tlaplus_tpu import cli

    out = str(tmp_path / "service_trace.json")
    assert cli.main(["trace", svc_stream, "-o", out]) == 0
    assert trace_mod.validate_trace(out) == []
    with open(out) as f:
        tr = json.load(f)
    slices = [
        e for e in tr["traceEvents"] if e.get("cat") == "job-slice"
    ]
    gaps = [
        e
        for e in tr["traceEvents"]
        if e.get("cat") == "context-switch"
    ]
    # both jobs' slices are on the device track, suspends made gaps
    assert len(slices) == (j1.suspends + 1) + (j2.suspends + 1)
    assert len(gaps) == len(slices) - 1
    total_us = sum(e["dur"] for e in slices) + sum(
        e["dur"] for e in gaps
    )
    t0 = min(e["ts"] for e in slices)
    t1 = max(e["ts"] + e["dur"] for e in slices)
    wall_us = t1 - t0
    assert wall_us > 0
    assert total_us == pytest.approx(wall_us, rel=0.05)
    # gaps into RESUMED slices carry the v5 restore cost (a gap into a
    # fresh job's first slice has no frame to restore)
    with_restore = [
        g for g in gaps if "restore_s" in (g.get("args") or {})
    ]
    assert len(with_restore) == j1.suspends + j2.suspends


def test_trace_unified_daemon_plus_job_streams(service_run, tmp_path):
    """Daemon + per-job streams export onto ONE aligned timeline: the
    engine level spans of a job land inside [first, last] extent of
    that job's device slices (wall_unix anchor alignment)."""
    _config, j1, _j2, svc_stream, events = service_run
    job_events, errs = report.load_events(j1.events_path)
    assert not errs
    tr = trace_mod.build_trace(
        [("service", events), ("job1", job_events)]
    )
    assert trace_mod.validate_trace(tr) == []
    slices = [
        e
        for e in tr["traceEvents"]
        if e.get("cat") == "job-slice"
        and j1.job_id[:6] in str(e.get("name", ""))
    ]
    levels = [
        e
        for e in tr["traceEvents"]
        if e.get("pid") == 2
        and e.get("ph") == "X"
        and str(e.get("name", "")).startswith("level ")
    ]
    assert slices and levels
    lo = min(e["ts"] for e in slices)
    hi = max(e["ts"] + e["dur"] for e in slices)
    span_us = hi - lo
    # alignment tolerance: one slice length of clock skew, not hours
    for e in levels:
        assert lo - 0.5 * span_us <= e["ts"] <= hi + 0.5 * span_us


def test_trace_daemon_restart_run_ids_align_not_splice():
    """A restart-appended service.jsonl (two daemon run_ids, each with
    its own t axis) must pair slices WITHIN a run_id and order them by
    their wall anchors — never splice two clocks into one span or
    render inverted context-switch gaps."""
    def rec(rid, seq, t, event, **kw):
        return {
            "v": 5, "event": event, "t": t, "run_id": rid, "seq": seq,
            **kw,
        }

    events = [
        # daemon lifetime 1: job A runs t=1..5, daemon dies mid-slice
        # of job B (open slice at stream end of this run_id)
        rec("d1", 0, 0.5, "job_submit", job_id="A", spec="s",
            wall_unix=1000.5),
        rec("d1", 1, 1.0, "job_start", job_id="A", spec="s", slice=1),
        rec("d1", 2, 5.0, "job_suspend", job_id="A", slice=1,
            slice_wall_s=4.0),
        rec("d1", 3, 6.0, "job_start", job_id="B", spec="s", slice=1),
        # daemon lifetime 2 (restart): fresh clock, later wall anchor
        rec("d2", 0, 0.2, "job_submit", job_id="C", spec="s",
            wall_unix=2000.2),
        rec("d2", 1, 1.0, "job_resume", job_id="A", spec="s", slice=2,
            restore_s=0.1),
        rec("d2", 2, 3.0, "job_result", job_id="A", status="ok",
            wall_s=6.0),
        rec("d2", 3, 4.0, "job_start", job_id="C", spec="s", slice=1),
        rec("d2", 4, 5.0, "job_result", job_id="C", status="ok",
            wall_s=1.0),
    ]
    tr = trace_mod.build_trace([("svc", events)])
    slices = [
        e for e in tr["traceEvents"] if e.get("cat") == "job-slice"
    ]
    # d1's open job-B slice is dropped (no honest end); A#1, A#2, C#1
    assert len(slices) == 3
    by_ts = sorted(slices, key=lambda e: e["ts"])
    names = [e["name"] for e in by_ts]
    # wall order: A slice 1 (d1 @1001) < A slice 2 (d2 @2001) < C
    assert "A" in names[0] and "slice 1" in names[0]
    assert "A" in names[1] and "slice 2" in names[1]
    assert "C" in names[2]
    # no overlap, no inverted gap spans
    gaps = [
        e
        for e in tr["traceEvents"]
        if e.get("cat") == "context-switch"
    ]
    assert all(g["dur"] >= 0 for g in gaps)
    ends = 0.0
    for e in by_ts:
        assert e["ts"] >= ends
        ends = e["ts"] + e["dur"]
    # the d2 restart really landed ~1000s after d1 on the shared axis
    assert by_ts[1]["ts"] - by_ts[0]["ts"] >= 900 * 1e6


def test_stream_metrics_and_top_use_newest_progress():
    """Heartbeat-only streams (no level records) must report the
    NEWEST snapshot — a dashboard showing the first heartbeat beside
    the latest rate reads as a frozen run."""
    def prog(seq, n, rate):
        return {
            "v": 5, "event": "progress", "t": float(seq),
            "run_id": "r", "seq": seq, "distinct_states": n,
            "states_per_sec": rate, "level": seq + 1,
        }

    events = [prog(0, 1_000, 10.0), prog(1, 9_000_000, 500_000.0)]
    fams, _types = metrics_mod.parse_exposition(
        metrics_mod.render_stream_metrics(events)
    )
    assert fams["ptt_distinct_states"][0][1] == 9_000_000
    assert fams["ptt_states_per_sec"][0][1] == 500_000.0
    model = top_mod.TopModel("x")
    model.ingest_events(events)
    assert "9.0M" in model.status_line


def test_job_table_total_wall_includes_final_slice(service_run):
    """The --jobs wall column uses job_result's cumulative wall_s —
    the suspended-slices sum alone misses every job's final slice."""
    _config, j1, _j2, _svc, events = service_run
    row = {
        r["job_id"]: r for r in report.job_table(events)
    }[j1.job_id]
    assert row["wall_s"] == pytest.approx(j1.wall_s, abs=0.01)
    # and it is strictly more than the suspended slices could account
    assert row["wall_s"] > row["slice_wall_s"] - 0.01
    table = report.render_job_table(events)
    line = next(ln for ln in table.splitlines() if j1.job_id in ln)
    assert f"{row['wall_s']:.2f}" in line


def test_trace_validator_rejects_garbage(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as f:
        json.dump({"nope": []}, f)
    assert trace_mod.validate_trace(p)
    with open(p, "w") as f:
        json.dump(
            {
                "traceEvents": [
                    {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0,
                     "name": "x", "dur": -5},
                    {"ph": "Z", "pid": 1, "tid": 1, "ts": 0.0,
                     "name": "y"},
                ]
            },
            f,
        )
    errs = trace_mod.validate_trace(p)
    assert any("dur" in e for e in errs)
    assert any("unknown phase" in e for e in errs)
    # the script front-end drives the same validation
    checker = _load_script("check_telemetry_schema")
    assert checker.main([p, "--trace"]) == 1


# ---- metrics exposition ---------------------------------------------


def test_daemon_metrics_scrape_zero_fetches(tmp_path, pool):
    """Live scrape: >= 10 parseable families, zero device stats
    fetches added (the heartbeat harness's fetch-count assertion),
    job-table families consistent with the daemon's state."""
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"), slice_s=0.2, **GEOM
    )
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        cl = ServiceClient(config.socket_path, timeout=120.0)
        jid = cl.submit("bookkeeper", BK_CFG)
        r = cl.wait(jid, timeout=240.0)
        assert r["state"] == jobmod.DONE
        fetches_before = {
            k: ck._fetch_n for k, ck in pool._checkers.items()
        }
        text = cl.metrics()
        assert fetches_before == {
            k: ck._fetch_n for k, ck in pool._checkers.items()
        }  # the zero-sync contract, now for scrapes
        fams, types = metrics_mod.parse_exposition(text)
        assert len(fams) >= 10
        assert fams["ptt_daemon_up"][0][1] == 1
        assert types["ptt_fpset_flushes_total"] == "counter"
        assert types["ptt_distinct_states"] == "gauge"
        done = [
            v
            for labels, v in fams["ptt_jobs"]
            if labels.get("state") == jobmod.DONE
        ]
        assert done == [1.0]
        assert fams["ptt_distinct_states"][0][1] == 297  # bk oracle
        assert fams["ptt_queue_depth"][0][1] == 0
        # scraping twice is stable and still fetch-free
        text2 = cl.metrics()
        assert metrics_mod.parse_exposition(text2)[0].keys() == (
            fams.keys()
        )
    finally:
        daemon.shutdown()


def test_stream_scrape_parity_with_live_families(solo_stream):
    """File-scrape mode exports identically-named engine families, and
    the values agree with the run's own last_stats."""
    _stream, ck, r, events = solo_stream
    fams, _types = metrics_mod.parse_exposition(
        metrics_mod.render_stream_metrics(events)
    )
    # the engine family set live daemon scrapes emit (metrics.py
    # _engine_families is the shared source)
    live_names = {
        f.name
        for f in metrics_mod._engine_families(
            ck.last_stats, {"distinct_states": r.distinct_states}
        )
        if f.samples
    }
    assert live_names <= set(fams)
    assert fams["ptt_distinct_states"][0][1] == r.distinct_states
    assert (
        fams["ptt_fpset_flushes_total"][0][1]
        == ck.last_stats["fpset_flushes"]
    )
    assert (
        fams["ptt_fpset_valid_lanes_total"][0][1]
        == ck.last_stats["fpset_valid_lanes"]
    )
    assert (
        fams["ptt_ckpt_frames_total"][0][1]
        == ck.last_stats["ckpt_frames"]
    )
    assert fams["ptt_bfs_level"][0][1] == r.diameter


def test_exposition_parser_roundtrip():
    fams = [
        metrics_mod.Family("ptt_x_total", "counter", "help text")
        .add(3)
        .add(4.5, {"state": "done", "q": 'a"b'}),
        metrics_mod.Family("ptt_empty", "gauge", "skipped"),
    ]
    text = metrics_mod.render_exposition(fams)
    assert "ptt_empty" not in text  # sample-less families are absent
    parsed, types = metrics_mod.parse_exposition(text)
    assert types["ptt_x_total"] == "counter"
    assert parsed["ptt_x_total"][0] == ({}, 3.0)
    assert parsed["ptt_x_total"][1] == (
        {"state": "done", "q": 'a"b'}, 4.5
    )


def test_service_stream_scrape_exports_job_families(service_run):
    """The daemon's own stream file scrapes into the job families the
    live verb also serves (identically named)."""
    _config, j1, j2, _svc, events = service_run
    fams, _types = metrics_mod.parse_exposition(
        metrics_mod.render_stream_metrics(events)
    )
    assert fams["ptt_job_slices_total"][0][1] == j1.slices + j2.slices
    assert (
        fams["ptt_job_suspends_total"][0][1]
        == j1.suspends + j2.suspends
    )
    done = [
        v
        for labels, v in fams["ptt_jobs"]
        if labels.get("state") == jobmod.DONE
    ]
    assert done == [2.0]


# ---- top ------------------------------------------------------------


def test_top_one_frame_render_from_stream(service_run, capsys):
    """`top --stream --once` renders one complete frame from a stream
    tail: header, job table rows, sparkline, status line — no daemon,
    no ANSI clear codes in --once mode.  Passing the per-job streams
    alongside joins their level-record sparklines onto the job rows
    via the r12 engine_run_id fields."""
    _config, j1, j2, svc_stream, _events = service_run
    from pulsar_tlaplus_tpu import cli

    assert cli.main(["top", "--stream", svc_stream, "--once"]) == 0
    out = capsys.readouterr().out
    assert "tpu-tlc top" in out
    assert j1.job_id[:12] in out and j2.job_id[:12] in out
    assert "ok" in out  # both jobs' terminal status rendered
    assert top_mod.CLEAR not in out  # --once never clears the screen
    assert cli.main([
        "top", "--stream", svc_stream,
        "--stream", j1.events_path, "--stream", j2.events_path,
        "--once",
    ]) == 0
    out2 = capsys.readouterr().out
    j1_row = next(
        ln
        for ln in out2.splitlines()
        if ln.startswith(j1.job_id[:12])  # the table row, not the
        #                                   header's stream paths
    )
    # the job row carries a real sparkline joined from the job
    # stream's level records
    assert any(c in j1_row for c in top_mod.SPARK_CHARS)
    assert "/s" in j1_row
    # a lone engine stream (no job events) still shows per-run rates
    model = top_mod.TopModel("job")
    frame = top_mod.tail_stream_frame(j1.events_path, model)
    assert "RUN" in frame
    assert any(c in frame for c in top_mod.SPARK_CHARS)


def test_top_frame_model_and_sparkline(solo_stream):
    _stream, _ck, r, events = solo_stream
    model = top_mod.TopModel("run.jsonl")
    model.ingest_events(events)
    # level records fed the run's sparkline history
    assert any(len(h) > 3 for h in model.rates.values())
    assert str(r.diameter) in model.status_line  # final level
    frame = top_mod.render_frame(model, now=0.0)
    assert "tpu-tlc top" in frame.splitlines()[0]
    assert model.status_line in frame
    # sparkline scales to its own max and clamps to the char set
    s = top_mod.sparkline([0, 1, 2, 4, 8])
    assert len(s) == 5 and s[-1] == top_mod.SPARK_CHARS[-1]
    assert top_mod.sparkline([]) == ""
    assert top_mod.sparkline([0, 0]) == top_mod.SPARK_CHARS[0] * 2
    assert top_mod.fmt_si(1_234_567) == "1.2M"


def test_top_daemon_poll_frame(tmp_path, pool):
    """One daemon poll paints pid/uptime, the job row, and a status
    line fed by the metrics scrape."""
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"), slice_s=0.2, **GEOM
    )
    daemon = ServiceDaemon(config, pool=pool)
    daemon.start()
    try:
        cl = ServiceClient(config.socket_path, timeout=120.0)
        jid = cl.submit("bookkeeper", BK_CFG)
        cl.wait(jid, timeout=240.0)
        model = top_mod.TopModel(config.socket_path)
        frame = top_mod.poll_daemon_frame(cl, model)
        assert f"pid {os.getpid()}" in frame
        assert jid[:12] in frame
        assert "297" in frame or "done" in frame
    finally:
        daemon.shutdown()
