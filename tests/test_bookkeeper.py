"""Differential tests for the bookkeeper spec (specs/bookkeeper.tla):
compiled TPU model vs the generic interpreter on the same .tla source."""

import os

import jax
import jax.numpy as jnp
import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker
from pulsar_tlaplus_tpu.frontend.interp import Spec, install_defs
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.models.bookkeeper import (
    BookkeeperConstants,
    BookkeeperModel,
)
from tests.helpers import needs_shard_map

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "specs",
    "bookkeeper.tla",
)

CONFIGS = {
    "shipped": BookkeeperConstants(),  # E=3 Qw=2 Qa=2 L=2 crashes=1
    "crash2": BookkeeperConstants(max_bookie_crashes=2),
    "wide_quorum": BookkeeperConstants(
        num_bookies=4, write_quorum=3, ack_quorum=2, entry_limit=2,
        max_bookie_crashes=1,
    ),
    "qa1": BookkeeperConstants(
        num_bookies=2, write_quorum=2, ack_quorum=1, entry_limit=2,
        max_bookie_crashes=1,
    ),
}

SAFE = ("TypeOK", "LacIsConfirmed", "AckImpliesStoredOrCrashed")


@pytest.fixture(scope="module")
def module():
    return parse_file(SPEC_PATH)


def spec_for(module, c: BookkeeperConstants) -> Spec:
    return Spec(
        module,
        {
            "NumBookies": c.num_bookies,
            "WriteQuorum": c.write_quorum,
            "AckQuorum": c.ack_quorum,
            "EntryLimit": c.entry_limit,
            "MaxBookieCrashes": c.max_bookie_crashes,
        },
    )


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_counts_and_verdicts_match_interpreter(module, name):
    c = CONFIGS[name]
    spec = spec_for(module, c)
    ri = InterpChecker(spec, invariants=SAFE).run()
    m = BookkeeperModel(c)
    rm = Checker(m, invariants=SAFE, frontier_chunk=256).run()
    assert ri.violation is None and rm.violation is None
    assert not ri.deadlock and not rm.deadlock
    assert rm.distinct_states == ri.distinct_states
    assert rm.diameter == ri.diameter
    assert rm.level_sizes == ri.level_sizes


def test_exact_state_set_matches_interpreter(module):
    c = CONFIGS["shipped"]
    spec = spec_for(module, c)
    install_defs(spec)
    expected = set(spec.initial_states())
    frontier = list(expected)
    while frontier:
        new = []
        for s in frontier:
            for _lab, t in spec.successors(s):
                if t not in expected:
                    expected.add(t)
                    new.append(t)
        frontier = new
    m = BookkeeperModel(c)
    ck = Checker(m, frontier_chunk=256, keep_log=True)
    ck.run()
    packed = ck.last_run_state.log.packed_matrix()
    unpack = jax.jit(m.layout.unpack)
    got = {m.to_interp_state(unpack(jnp.asarray(row))) for row in packed}
    assert got == expected


def test_durability_contract_boundary(module):
    """MaxBookieCrashes < AckQuorum: ConfirmedEntryReadable HOLDS (the
    BookKeeper durability contract); at >= AckQuorum it is VIOLATED, with
    the same shortest ack-then-crash counterexample on both paths."""
    m_ok = BookkeeperModel(CONFIGS["shipped"])
    r_ok = Checker(m_ok, invariants=("ConfirmedEntryReadable",)).run()
    assert r_ok.violation is None

    c = CONFIGS["crash2"]
    spec = spec_for(module, c)
    install_defs(spec)
    ri = InterpChecker(spec, invariants=("ConfirmedEntryReadable",)).run()
    m = BookkeeperModel(c)
    rm = Checker(m, invariants=("ConfirmedEntryReadable",)).run()
    assert ri.violation == rm.violation == "ConfirmedEntryReadable"
    assert len(ri.trace) == len(rm.trace) == 9
    assert rm.trace_actions == [
        "AddEntry", "WriteLand", "WriteLand", "AckArrive", "AckArrive",
        "AdvanceLAC", "BookieCrash", "BookieCrash",
    ]
    # replay the compiled trace on interpreter semantics via rendering
    rendered = lambda t: m.to_pystate(m.from_interp_state(t))
    cur = spec.initial_states()[0]
    assert rendered(cur) == rm.trace[0]
    for act, want in zip(rm.trace_actions, rm.trace[1:]):
        nxt = [
            t for lab, t in spec.successors(cur)
            if lab == act and rendered(t) == want
        ]
        assert nxt, (act, want)
        cur = nxt[0]


@needs_shard_map
def test_sharded_counts_match():
    from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker

    c = CONFIGS["shipped"]
    m = BookkeeperModel(c)
    base = Checker(m, frontier_chunk=256).run()
    for nd in (2, 8):
        r = ShardedChecker(
            m, n_devices=nd, frontier_chunk=64, visited_cap=1 << 10
        ).run()
        assert r.distinct_states == base.distinct_states, nd
        assert r.diameter == base.diameter


def test_liveness_termination():
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    m = BookkeeperModel(CONFIGS["shipped"])
    r = LivenessChecker(m, goal="Termination", fairness="wf_next").run()
    assert r.holds, r.reason
    r2 = LivenessChecker(m, goal="Termination", fairness="none").run()
    assert not r2.holds


def test_simulation_finds_durability_violation():
    from pulsar_tlaplus_tpu.engine.simulate import Simulator

    m = BookkeeperModel(CONFIGS["crash2"])
    sres = Simulator(
        m,
        invariants=("ConfirmedEntryReadable",),
        n_walkers=1024,
        depth=32,
        seed=1,
    ).run()
    assert sres.violation == "ConfirmedEntryReadable"
    # final state: some confirmed entry with no surviving replica
    final = sres.trace[-1]
    assert final["lac"] >= 1
