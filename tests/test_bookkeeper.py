"""Differential tests for the bookkeeper spec (specs/bookkeeper.tla):
compiled TPU model vs the generic interpreter on the same .tla source."""

import os

import jax
import jax.numpy as jnp
import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker
from pulsar_tlaplus_tpu.frontend.interp import Spec, install_defs
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.models.bookkeeper import (
    BookkeeperConstants,
    BookkeeperModel,
)
from tests.helpers import needs_shard_map

SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "specs",
    "bookkeeper.tla",
)

CONFIGS = {
    "shipped": BookkeeperConstants(),  # E=3 Qw=2 Qa=2 L=2 crashes=1
    "crash2": BookkeeperConstants(max_bookie_crashes=2),
    "wide_quorum": BookkeeperConstants(
        num_bookies=4, write_quorum=3, ack_quorum=2, entry_limit=2,
        max_bookie_crashes=1,
    ),
    "qa1": BookkeeperConstants(
        num_bookies=2, write_quorum=2, ack_quorum=1, entry_limit=2,
        max_bookie_crashes=1,
    ),
}

SAFE = ("TypeOK", "LacIsConfirmed", "AckImpliesStoredOrCrashed")


@pytest.fixture(scope="module")
def module():
    return parse_file(SPEC_PATH)


def spec_for(module, c: BookkeeperConstants) -> Spec:
    return Spec(
        module,
        {
            "NumBookies": c.num_bookies,
            "WriteQuorum": c.write_quorum,
            "AckQuorum": c.ack_quorum,
            "EntryLimit": c.entry_limit,
            "MaxBookieCrashes": c.max_bookie_crashes,
        },
    )


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_counts_and_verdicts_match_interpreter(module, name):
    c = CONFIGS[name]
    spec = spec_for(module, c)
    ri = InterpChecker(spec, invariants=SAFE).run()
    m = BookkeeperModel(c)
    rm = Checker(m, invariants=SAFE, frontier_chunk=256).run()
    assert ri.violation is None and rm.violation is None
    assert not ri.deadlock and not rm.deadlock
    assert rm.distinct_states == ri.distinct_states
    assert rm.diameter == ri.diameter
    assert rm.level_sizes == ri.level_sizes


def test_exact_state_set_matches_interpreter(module):
    c = CONFIGS["shipped"]
    spec = spec_for(module, c)
    install_defs(spec)
    expected = set(spec.initial_states())
    frontier = list(expected)
    while frontier:
        new = []
        for s in frontier:
            for _lab, t in spec.successors(s):
                if t not in expected:
                    expected.add(t)
                    new.append(t)
        frontier = new
    m = BookkeeperModel(c)
    ck = Checker(m, frontier_chunk=256, keep_log=True)
    ck.run()
    packed = ck.last_run_state.log.packed_matrix()
    unpack = jax.jit(m.layout.unpack)
    got = {m.to_interp_state(unpack(jnp.asarray(row))) for row in packed}
    assert got == expected


def test_durability_contract_boundary(module):
    """MaxBookieCrashes < AckQuorum: ConfirmedEntryReadable HOLDS (the
    BookKeeper durability contract); at >= AckQuorum it is VIOLATED, with
    the same shortest ack-then-crash counterexample on both paths."""
    m_ok = BookkeeperModel(CONFIGS["shipped"])
    r_ok = Checker(m_ok, invariants=("ConfirmedEntryReadable",)).run()
    assert r_ok.violation is None

    c = CONFIGS["crash2"]
    spec = spec_for(module, c)
    install_defs(spec)
    ri = InterpChecker(spec, invariants=("ConfirmedEntryReadable",)).run()
    m = BookkeeperModel(c)
    rm = Checker(m, invariants=("ConfirmedEntryReadable",)).run()
    assert ri.violation == rm.violation == "ConfirmedEntryReadable"
    assert len(ri.trace) == len(rm.trace) == 9
    assert rm.trace_actions == [
        "AddEntry", "WriteLand", "WriteLand", "AckArrive", "AckArrive",
        "AdvanceLAC", "BookieCrash", "BookieCrash",
    ]
    # replay the compiled trace on interpreter semantics via rendering
    rendered = lambda t: m.to_pystate(m.from_interp_state(t))
    cur = spec.initial_states()[0]
    assert rendered(cur) == rm.trace[0]
    for act, want in zip(rm.trace_actions, rm.trace[1:]):
        nxt = [
            t for lab, t in spec.successors(cur)
            if lab == act and rendered(t) == want
        ]
        assert nxt, (act, want)
        cur = nxt[0]


@needs_shard_map
def test_sharded_counts_match():
    from pulsar_tlaplus_tpu.engine.sharded import ShardedChecker

    c = CONFIGS["shipped"]
    m = BookkeeperModel(c)
    base = Checker(m, frontier_chunk=256).run()
    for nd in (2, 8):
        r = ShardedChecker(
            m, n_devices=nd, frontier_chunk=64, visited_cap=1 << 10
        ).run()
        assert r.distinct_states == base.distinct_states, nd
        assert r.diameter == base.diameter


def test_liveness_termination():
    from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker

    m = BookkeeperModel(CONFIGS["shipped"])
    r = LivenessChecker(m, goal="Termination", fairness="wf_next").run()
    assert r.holds, r.reason
    r2 = LivenessChecker(m, goal="Termination", fairness="none").run()
    assert not r2.holds


def test_simulation_finds_durability_violation():
    """Random walks find the ack-then-crash durability violation.

    The jax PRNG stream is version/platform-dependent, so any SINGLE
    pinned seed is an environment lottery (this test shipped red for
    rounds 11-14 because seed=1 happens to miss on the container's
    jax 0.4.37 while hitting on the host's).  Scan a small
    deterministic seed list instead: each attempt exercises the full
    rollout+replay path, ~60% of seeds hit at these walk parameters,
    and the union is robust on every environment."""
    from pulsar_tlaplus_tpu.engine.simulate import Simulator

    m = BookkeeperModel(CONFIGS["crash2"])
    sres = None
    for seed in range(8):
        s = Simulator(
            m,
            invariants=("ConfirmedEntryReadable",),
            n_walkers=1024,
            depth=32,
            seed=seed,
        ).run()
        if s.violation is not None:
            sres = s
            break
    assert sres is not None, (
        "no seed in range(8) found the durability violation "
        "(1024 walkers x depth 32 — a genuine simulation regression)"
    )
    assert sres.violation == "ConfirmedEntryReadable"
    # final state: some confirmed entry with no surviving replica
    final = sres.trace[-1]
    assert final["lac"] >= 1


# ---- pinned oracle counts (r11, checking-as-a-service) --------------
# The daemon's multi-spec registry needs a second exact-parity workload
# beside compaction's published 45,198/253,361 figures: pin the Python
# oracle's reachable-state counts for bookkeeper and hold every engine
# the registry dispatches to them.  Derived once from the interpreter
# BFS on specs/bookkeeper.tla (the "shipped" count is re-derived inline
# below; the meatier EntryLimit=3 run takes ~2 s and is asserted
# against the literal only).

ORACLE_CFG = BookkeeperConstants(entry_limit=3)
SHIPPED_STATES, SHIPPED_DIAMETER = 297, 14    # specs/bookkeeper.cfg
ORACLE_STATES, ORACLE_DIAMETER = 2257, 20     # EntryLimit = 3


def test_shipped_cfg_pinned_oracle_count(module):
    """The daemon's default bookkeeper binding (specs/bookkeeper.cfg):
    interpreter, host engine, and the service registry's device engine
    all reproduce the pinned count."""
    c = CONFIGS["shipped"]
    ri = InterpChecker(spec_for(module, c)).run()
    assert (ri.distinct_states, ri.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    rh = Checker(BookkeeperModel(c), frontier_chunk=256).run()
    assert (rh.distinct_states, rh.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    rd = DeviceChecker(
        BookkeeperModel(c), sub_batch=256, visited_cap=1 << 12,
        frontier_cap=1 << 10,
    ).run()
    assert (rd.distinct_states, rd.diameter) == (
        SHIPPED_STATES, SHIPPED_DIAMETER,
    )
    assert rd.violation is None and not rd.deadlock


def test_entry_limit3_pinned_oracle_count(module):
    """EntryLimit=3 is the meatier pinned workload (2,257 states,
    diameter 20 — the bookkeeper analog of compaction's 253k oracle
    regime, scaled to the CPU-mesh test budget)."""
    ri = InterpChecker(spec_for(module, ORACLE_CFG)).run()
    assert (ri.distinct_states, ri.diameter) == (
        ORACLE_STATES, ORACLE_DIAMETER,
    )
    from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker

    rd = DeviceChecker(
        BookkeeperModel(ORACLE_CFG), sub_batch=256,
        visited_cap=1 << 13, frontier_cap=1 << 11,
    ).run()
    assert (rd.distinct_states, rd.diameter) == (
        ORACLE_STATES, ORACLE_DIAMETER,
    )
