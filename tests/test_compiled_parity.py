"""Compiled-spec feature parity (VERDICT r2 #4/#5): every engine that
the hand-compiled registry models run on must accept a ``CompiledSpec``
built from raw .tla text and produce identical counts/verdicts —
sharded checking, simulation, checkpoint/resume, and compiled temporal
properties (the ``<>(predicate)`` fragment)."""

import pytest

from pulsar_tlaplus_tpu.engine.bfs import Checker
from pulsar_tlaplus_tpu.engine.liveness import LivenessChecker
from pulsar_tlaplus_tpu.engine.sharded_device import ShardedDeviceChecker
from pulsar_tlaplus_tpu.engine.simulate import Simulator
from pulsar_tlaplus_tpu.frontend import interp as I
from pulsar_tlaplus_tpu.frontend.codegen import CompiledSpec
from pulsar_tlaplus_tpu.frontend.loader import compaction_constants
from pulsar_tlaplus_tpu.frontend.parser import parse_file
from pulsar_tlaplus_tpu.ref import pyeval as pe
from tests.helpers import needs_shard_map, SMALL_CONFIGS

from tests.helpers import REFERENCE_TLA  # specs/ first, /root/reference fallback


@pytest.fixture(scope="module")
def module():
    return parse_file(REFERENCE_TLA)


def _compiled(module, c, invariants=()):
    spec = I.Spec(module, compaction_constants(c))
    return CompiledSpec(spec, invariants=invariants)


@needs_shard_map
def test_compiled_sharded_matches_oracle(module):
    """-compile -sharded: the device-resident sharded engine accepts a
    CompiledSpec and matches the oracle exactly on an 8-shard mesh."""
    c = SMALL_CONFIGS["producer_on"]
    want = pe.check(c, invariants=())
    got = ShardedDeviceChecker(
        _compiled(module, c), n_devices=8, invariants=(), sub_batch=128,
        visited_cap=1 << 10,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter
    assert got.violation is None and not got.deadlock


@pytest.mark.parametrize(
    "name", ["subscription", "bookkeeper", "georeplication"]
)
@needs_shard_map
def test_compiled_sharded_original_specs(name):
    from pulsar_tlaplus_tpu.engine.interp_check import InterpChecker
    from pulsar_tlaplus_tpu.frontend.loader import bind_cfg
    from pulsar_tlaplus_tpu.utils.cfg import parse_cfg

    mod = parse_file(f"/root/repo/specs/{name}.tla")
    cfg = parse_cfg(open(f"/root/repo/specs/{name}.cfg").read())
    spec = I.Spec(mod, bind_cfg(mod, cfg))
    want = InterpChecker(spec, invariants=()).run()
    got = ShardedDeviceChecker(
        CompiledSpec(spec), n_devices=4, invariants=(), sub_batch=128,
        visited_cap=1 << 10,
    ).run()
    assert got.distinct_states == want.distinct_states
    assert got.diameter == want.diameter


def test_compiled_checkpoint_resume_exact_count(module, tmp_path):
    """Checkpoint/resume on the compiled path: a truncated run resumes
    to the exact published 45,198-state count."""
    cs = _compiled(module, pe.SHIPPED_CFG)
    path = str(tmp_path / "ck.npz")
    r1 = Checker(
        cs, visited_cap=1 << 16, checkpoint_path=path,
        checkpoint_every=3, max_states=10_000,
    ).run()
    assert r1.truncated and r1.distinct_states < 45198
    r2 = Checker(
        cs, visited_cap=1 << 16, checkpoint_path=path
    ).run(resume=True)
    assert r2.distinct_states == 45198
    assert r2.diameter == 20


def test_compiled_simulation_finds_duplicate_bug(module):
    """Simulation mode on the compiled path: random walkers find the
    depth-4 DuplicateNullKeyMessage violation from the raw .tla."""
    cs = _compiled(
        module, pe.SHIPPED_CFG, invariants=("DuplicateNullKeyMessage",)
    )
    res = Simulator(cs, n_walkers=512, depth=8, seed=3).run()
    assert res.violation == "DuplicateNullKeyMessage"
    assert res.trace is not None


def test_compiled_termination_goal_matches_oracle(module):
    """<>Termination compiled from the raw .tla: verdicts match the
    oracle's liveness semantics under both fairness modes."""
    c = SMALL_CONFIGS["producer_on"]
    cs = _compiled(module, c)
    assert "Termination" in cs.liveness_goals
    for fairness in ("none", "wf_next"):
        want_holds, _why = pe.check_eventually(c, fairness=fairness)
        got = LivenessChecker(
            cs, goal="Termination", fairness=fairness,
        ).run()
        assert got.holds == want_holds, fairness
