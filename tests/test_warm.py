"""Incremental checking tests (r19, ``pulsar_tlaplus_tpu/warm/``).

The acceptance bar (ISSUE 15 / docs/incremental.md):

- a TRUNCATED job resubmitted at a widened budget **continues** from
  its warm artifact instead of restarting — distinct states, level
  sizes, verdict, violation gid, and full trace pinned equal to an
  uninterrupted cold run (both the clean compaction shape and the
  bookkeeper crash2 violation shape);
- a constant-widening **reseed** on subscription (MaxCrashTimes 2->3)
  is pinned warm-vs-cold state-for-state — exact reachable STATE-SET
  equality, not just counts;
- the **fallback matrix**: every non-reusable change (module edit,
  invariant change, non-widening binding change, narrowing, a bitlen
  layout step, digest tamper, version skew, torn artifact) plans/
  demotes COLD with its typed reason — never a wrong verdict;
- the robustness drills: ``kill@warmwrite`` mid-harvest (subprocess),
  ``torn@warmwrite``, and ``corrupt@warm`` all leave the daemon
  serving correct results with quarantined artifacts;
- satellites: sim-job admission pricing, ledger warm tagging + gate
  baseline scoping, the ``--warm`` validator flag, and the fuzz
  ``--widen`` fast drill.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from pulsar_tlaplus_tpu.engine.device_bfs import DeviceChecker
from pulsar_tlaplus_tpu.models import registry
from pulsar_tlaplus_tpu.models.subscription import (
    SubscriptionConstants,
    SubscriptionModel,
)
from pulsar_tlaplus_tpu.obs import ledger
from pulsar_tlaplus_tpu.obs import metrics as metrics_mod
from pulsar_tlaplus_tpu.obs import report
from pulsar_tlaplus_tpu.service import admission as admmod
from pulsar_tlaplus_tpu.service import jobs as jobmod
from pulsar_tlaplus_tpu.service.scheduler import (
    CheckerPool,
    Scheduler,
    ServiceConfig,
)
from pulsar_tlaplus_tpu.utils import cfg as cfgmod
from pulsar_tlaplus_tpu.utils import faults
from pulsar_tlaplus_tpu.warm import plan as warm_plan
from pulsar_tlaplus_tpu.warm import store as warm_store

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GEOM = dict(
    sub_batch=64,
    visited_cap=1 << 10,
    frontier_cap=1 << 8,
    max_states=1 << 20,
    checkpoint_every=1,
)

SMALL_COMPACTION_CFG = """
CONSTANTS
    MessageSentLimit = 2
    CompactionTimesLimit = 2
    ModelConsumer = FALSE
    ConsumeTimesLimit = 2
    KeySpace = {1}
    ValueSpace = {1}
    RetainNullKey = TRUE
    MaxCrashTimes = 1
    ModelProducer = TRUE
SPECIFICATION Spec
INVARIANTS
"""

BK_CRASH2_CFG = """
CONSTANTS
    NumBookies = 3
    WriteQuorum = 2
    AckQuorum = 2
    EntryLimit = 2
    MaxBookieCrashes = 2
SPECIFICATION Spec
INVARIANTS
    ConfirmedEntryReadable
"""

SUB_CFG = """
CONSTANTS
    MessageLimit = 2
    MaxCrashTimes = 2
SPECIFICATION Spec
INVARIANTS
"""

# the declared-monotone widening: MaxCrashTimes 2 -> 3 keeps
# bitlen(2) == bitlen(3) == 2, so the packed layout is bit-identical
SUB_CFG_WIDE = SUB_CFG.replace("MaxCrashTimes = 2", "MaxCrashTimes = 3")
# a NARROWING of the same axis (the planner must refuse)
SUB_CFG_NARROW = SUB_CFG.replace(
    "MaxCrashTimes = 2", "MaxCrashTimes = 1"
)
# a non-axis binding change (MessageLimit sizes the layout)
SUB_CFG_OTHER = SUB_CFG.replace("MessageLimit = 2", "MessageLimit = 3")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def checker_mod():
    return _load_script("check_telemetry_schema")


@pytest.fixture(scope="module")
def cfg_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("warm_cfgs")
    (d / "small_compaction.cfg").write_text(SMALL_COMPACTION_CFG)
    (d / "bk_crash2.cfg").write_text(BK_CRASH2_CFG)
    (d / "sub.cfg").write_text(SUB_CFG)
    (d / "sub_wide.cfg").write_text(SUB_CFG_WIDE)
    (d / "sub_narrow.cfg").write_text(SUB_CFG_NARROW)
    (d / "sub_other.cfg").write_text(SUB_CFG_OTHER)
    return d


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    config = ServiceConfig(
        state_dir=str(tmp_path_factory.mktemp("warm_pool")), **GEOM
    )
    return CheckerPool(config)


def _sched(state_dir, pool, **kw):
    base = dict(GEOM)
    base.update(kw)
    config = ServiceConfig(state_dir=str(state_dir), **base)
    return Scheduler(config, pool=pool), config


def _solo(pool, spec, cfg_path, max_states=None):
    tlc = cfgmod.load(str(cfg_path))
    invs = pool.resolve_invariants(spec, tlc, None)
    _k, ck = pool.get(spec, tlc, invs, max_states)
    return ck.run()


def _validate_streams(checker_mod, paths):
    errors = []
    for p in paths:
        if os.path.exists(p):
            errors += checker_mod.validate_stream(p)
    return errors


# ---- the continue fast path -----------------------------------------


def test_truncated_resubmit_continues_clean_shape(
    tmp_path, pool, cfg_dir, checker_mod
):
    """THE acceptance pin: a truncated producer_on-shape job
    resubmitted at a widened state budget CONTINUES from its warm
    artifact — distinct states, level sizes, diameter, and verdict
    pinned equal to an uninterrupted cold run."""
    sched, config = _sched(tmp_path / "state", pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    j1 = sched.submit("compaction", cfg, max_states=600)
    assert (j1.warm_mode, j1.warm_reason) == (
        "cold", warm_plan.REASON_NO_ARTIFACT
    )
    sched.run_until_idle()
    assert j1.result["status"] == "truncated"
    assert j1.result["distinct_states"] == 600
    # the truncation frame became a digest-verified warm artifact
    entries = [
        d for d in os.listdir(config.warm_dir)
        if d != "quarantine" and not d.startswith(".")
    ]
    assert len(entries) == 1
    ok, why = sched.warm_store.verify(
        os.path.join(config.warm_dir, entries[0])
    )
    assert ok, why

    j2 = sched.submit("compaction", cfg, max_states=GEOM["max_states"])
    assert (j2.warm_mode, j2.warm_reason) == ("continue", "sig_match")
    sched.run_until_idle()
    solo = _solo(pool, "compaction", cfg, GEOM["max_states"])
    assert j2.result["status"] == "ok"
    assert j2.result["warm"] == "continue"
    assert j2.result["distinct_states"] == solo.distinct_states == 1654
    assert j2.result["diameter"] == solo.diameter == 16
    assert j2.result["level_sizes"] == [
        int(x) for x in solo.level_sizes
    ]
    assert j2.result["violation"] is None
    # warm attribution on the continued slice's engine run header
    # (filter to j2's OWN run ids: the pooled checker's stale
    # telemetry path also routes the solo baseline's header here)
    headers = []
    with open(j2.events_path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("event") == "run_header" and (
                rec.get("run_id") in j2.run_ids
            ):
                headers.append(rec)
    assert headers and all(h["warm"] == "continue" for h in headers)
    assert headers[0]["resume"] is True  # continued, not restarted
    # streams v12-validator-clean (daemon + both jobs)
    assert _validate_streams(
        checker_mod,
        [config.telemetry_path, j1.events_path, j2.events_path],
    ) == []
    assert sched.warm_counts[("continue", "sig_match")] == 1

    # the spec-CI cache hit: resubmitting the identical COMPLETED job
    # continues from the final frame — the frontier is empty, so the
    # identical verdict returns without re-expanding a single state
    j3 = sched.submit("compaction", cfg, max_states=GEOM["max_states"])
    assert j3.warm_mode == "continue"
    sched.run_until_idle()
    for k in ("status", "distinct_states", "diameter", "level_sizes"):
        assert j3.result[k] == j2.result[k]

    # ptt_warm_* from the live scheduler counters
    text = metrics_mod.render_exposition(
        metrics_mod.scheduler_metrics(sched)
    )
    assert 'ptt_warm_cold_total{reason="no_artifact"} 1' in text
    assert 'ptt_warm_hit_total{reason="sig_match"} 2' in text
    assert "ptt_warm_cache_bytes" in text

    # ---- the VIOLATION half of the pin, same resident daemon:
    # bookkeeper crash2 truncated BEFORE its ConfirmedEntryReadable
    # counterexample is reachable, then resubmitted at the full
    # budget — violation, violation_gid, and the full 9-state trace
    # pinned equal to the cold run
    bk = str(cfg_dir / "bk_crash2.cfg")
    b1 = sched.submit("bookkeeper", bk, max_states=150)
    sched.run_until_idle()
    assert b1.result["status"] == "truncated"
    assert b1.result["violation"] is None

    b2 = sched.submit("bookkeeper", bk)
    assert b2.warm_mode == "continue"
    sched.run_until_idle()
    solo_bk = _solo(pool, "bookkeeper", bk)
    assert solo_bk.violation == "ConfirmedEntryReadable"
    assert b2.result["status"] == "violation"
    assert b2.result["violation"] == solo_bk.violation
    assert b2.result["violation_gid"] == solo_bk.violation_gid == 305
    assert b2.result["trace"] == [repr(s) for s in solo_bk.trace]
    assert b2.result["trace_actions"] == list(solo_bk.trace_actions)
    # a violation run is NEVER harvested: the bookkeeper artifact is
    # still b1's truncation frame, not a verdict-bearing one
    bk_mans = [
        m for _d, m in sched.warm_store.manifests()
        if m["spec"] == "bookkeeper"
    ]
    assert len(bk_mans) == 1
    assert bk_mans[0]["truncated"] is True
    assert bk_mans[0]["distinct_states"] == b1.result[
        "distinct_states"
    ]


# ---- the reseed path ------------------------------------------------


def _rows_set(ck, n):
    W = int(ck.model.layout.W)
    rows = np.asarray(ck.last_bufs["rows"])[: n * W].reshape(n, W)
    return rows[np.lexsort(rows.T[::-1])]


def test_reseed_widening_pinned_state_for_state(
    tmp_path, pool, cfg_dir, base_artifact
):
    """The reseed acceptance pin (subscription MaxCrashTimes 2->3,
    bitlen-stable): the daemon plans reseed across the widening, and
    a standalone reseed through the same planner/seed machinery pins
    exact reachable STATE-SET equality against a cold run."""
    # standalone set-equality half (reuses the module base artifact)
    _store, adir, _ck, invs, r_old = base_artifact
    man = _store.load_manifest(adir)
    c_new = SubscriptionConstants(message_limit=2, max_crash_times=3)
    m_new = SubscriptionModel(c_new)
    seed, info = warm_plan.build_reseed_seed(
        adir, man, m_new, {"MaxCrashTimes": (2, 3)}
    )
    assert info["replay_rows"] >= 1
    assert info["reused_rows"] >= 1
    assert info["reused_rows"] + info["replay_rows"] == (
        r_old.distinct_states
    )
    ck_warm = DeviceChecker(m_new, invariants=invs, **GEOM_ENGINE)
    ck_warm.extra_trace_depth = len(r_old.level_sizes)
    r_warm = ck_warm.run(seed=seed)
    ck_cold = DeviceChecker(m_new, invariants=invs, **GEOM_ENGINE)
    r_cold = ck_cold.run()
    assert r_warm.violation is None and r_cold.violation is None
    assert r_warm.distinct_states == r_cold.distinct_states
    assert np.array_equal(
        _rows_set(ck_warm, r_warm.distinct_states),
        _rows_set(ck_cold, r_cold.distinct_states),
    )

    # daemon half: the scheduler plans + installs the same reseed
    sched, _config = _sched(tmp_path / "state", pool)
    j1 = sched.submit("subscription", str(cfg_dir / "sub.cfg"))
    sched.run_until_idle()
    assert j1.result["status"] == "ok"

    j2 = sched.submit("subscription", str(cfg_dir / "sub_wide.cfg"))
    assert j2.warm_mode == "reseed"
    assert j2.warm_reason == "widened:MaxCrashTimes"
    assert j2.warm_widened == {"MaxCrashTimes": [2, 3]}
    sched.run_until_idle()
    assert j2.result["status"] == "ok"
    assert j2.result["warm"] == "reseed"
    # the reachable COUNT is engine-shape-independent: the daemon's
    # reseed agrees with the standalone cold run above
    assert j2.result["distinct_states"] == r_cold.distinct_states
    assert sched.warm_counts[
        ("reseed", "widened:MaxCrashTimes")
    ] == 1


GEOM_ENGINE = dict(
    sub_batch=64, visited_cap=1 << 10, frontier_cap=1 << 8,
    max_states=1 << 18,
)


# ---- the fallback matrix --------------------------------------------


@pytest.fixture(scope="module")
def base_artifact(tmp_path_factory):
    """ONE real subscription artifact shared by the matrix/validator
    tests — each consumer copies the store dir and forges what it
    needs (one engine run instead of fifteen)."""
    root = tmp_path_factory.mktemp("warm_base")
    c = SubscriptionConstants(message_limit=2, max_crash_times=2)
    m = SubscriptionModel(c)
    invs = tuple(m.default_invariants)
    frame = str(root / "frame.npz")
    ck = DeviceChecker(
        m, invariants=invs, checkpoint_path=frame, **GEOM_ENGINE
    )
    ck.final_frame = True
    r = ck.run()
    store = warm_store.WarmStore(str(root / "store"))
    man = warm_plan.manifest_for(
        "subscription", {"MessageLimit": 2, "MaxCrashTimes": 2},
        invs, ck,
        {
            "distinct_states": r.distinct_states,
            "levels": len(r.level_sizes),
            "truncated": False, "stop_reason": None,
        },
    )
    adir = store.save(frame, man)
    assert adir and store.verify(adir)[0]
    return store, adir, ck, invs, r


def _copy_store(base_artifact, dst):
    """A private mutable copy of the base artifact's store."""
    store, adir, ck, invs, r = base_artifact
    shutil.copytree(store.root, str(dst))
    new_store = warm_store.WarmStore(str(dst))
    new_adir = os.path.join(str(dst), os.path.basename(adir))
    return new_store, new_adir, ck, invs


def _replan(store, ck, invs, constants, **over):
    kw = dict(
        spec="subscription",
        constants=constants,
        invariants=invs,
        config_sig=ck._config_sig(),
        module_digest=registry.module_digest("subscription"),
        lsig=warm_plan.layout_sig(ck.model),
        n_initial=int(ck.model.n_initial),
        max_states=1 << 18,
        check_deadlock=True,
    )
    kw.update(over)
    return warm_plan.plan(store, **kw)


def _rewrite_manifest(store, adir, **mutations):
    """Forge manifest fields, keeping the file digests valid (the
    planner reads manifests; only verify() checks content digests)."""
    man = store.load_manifest(adir)
    man.update(mutations)
    with open(os.path.join(adir, warm_store.MANIFEST), "w") as f:
        json.dump(man, f)


def test_fallback_matrix_table_driven(tmp_path, base_artifact):
    """Satellite: (change kind) x (expected mode/reason), enumerated.
    Every non-reusable change must plan COLD with its typed reason —
    the planner never guesses.  ``incoming_sig`` stands in for the
    changed model's engine config signature (any binding or module
    change changes the real one)."""
    base = {"MessageLimit": 2, "MaxCrashTimes": 2}
    wide = {"MessageLimit": 2, "MaxCrashTimes": 3}
    other = "incoming-changed-config-sig"
    cases = [
        # (name, manifest mutations, incoming constants,
        #  incoming config_sig override, want mode, want reason)
        ("identical", {}, base, None, "continue", "sig_match"),
        (
            "widening", {}, wide, other,
            "reseed", "widened:MaxCrashTimes",
        ),
        (
            "module_edit", {"module_digest": "deadbeef"}, wide, other,
            "cold", warm_plan.REASON_MODULE_EDIT,
        ),
        (
            # a re-guarded action keeps the config signature (it
            # identifies the model by name + bindings, not source):
            # the SOURCE digest alone must block the continue path
            "module_edit_same_sig", {"module_digest": "deadbeef"},
            base, None, "cold", warm_plan.REASON_MODULE_EDIT,
        ),
        (
            "invariant_change", {"invariants": ["SomethingElse"]},
            wide, other, "cold", warm_plan.REASON_INVARIANT_CHANGE,
        ),
        (
            "non_axis_binding", {},
            {"MessageLimit": 3, "MaxCrashTimes": 2}, other,
            "cold", warm_plan.REASON_BINDING_CHANGE,
        ),
        (
            "narrowing", {},
            {"MessageLimit": 2, "MaxCrashTimes": 1}, other,
            "cold", warm_plan.REASON_NARROWED,
        ),
        (
            "layout_step", {"layout_sig": "other-layout"}, wide,
            other, "cold", warm_plan.REASON_LAYOUT_CHANGE,
        ),
        (
            "init_change", {"n_initial": 99}, wide, other,
            "cold", warm_plan.REASON_INIT_CHANGE,
        ),
        (
            "rows_windowed", {"rows_all": False}, wide, other,
            "cold", warm_plan.REASON_ROWS,
        ),
        (
            "budget_narrowed_reseed",
            {"distinct_states": (1 << 18) + 1}, wide, other,
            "cold", warm_plan.REASON_BUDGET,
        ),
        (
            "deadlock_config", {"check_deadlock": False}, wide,
            other, "cold", warm_plan.REASON_ENGINE_CONFIG,
        ),
        (
            "engine_config_same_bindings", {}, base, other,
            "cold", warm_plan.REASON_ENGINE_CONFIG,
        ),
    ]
    for name, mut, constants, sig_over, want_mode, want_reason in cases:
        store, adir, ck, invs = _copy_store(
            base_artifact, tmp_path / name
        )
        if mut:
            _rewrite_manifest(store, adir, **mut)
        over = {"config_sig": sig_over} if sig_over else {}
        p = _replan(store, ck, invs, constants, **over)
        assert (p.mode, p.reason) == (want_mode, want_reason), (
            f"{name}: got {p.mode}/{p.reason}, want "
            f"{want_mode}/{want_reason}"
        )

    # budget narrowed below the artifact's states: CONTINUE refused
    store, adir, ck, invs = _copy_store(base_artifact, tmp_path / "bud")
    man = store.load_manifest(adir)
    p = _replan(
        store, ck, invs, base,
        max_states=int(man["distinct_states"]) - 1,
    )
    assert (p.mode, p.reason) == ("cold", warm_plan.REASON_BUDGET)

    # version skew: a newer warm_v is refused as torn/unreadable
    store, adir, ck, invs = _copy_store(base_artifact, tmp_path / "ver")
    _rewrite_manifest(store, adir, warm_v=warm_store.WARM_VERSION + 1)
    p = _replan(store, ck, invs, base)
    assert p.mode == "cold"
    assert p.reason in (
        warm_plan.REASON_TORN, warm_plan.REASON_NO_ARTIFACT
    )

    # torn manifest (half-written file) -> unreadable -> cold, and
    # the startup sweep quarantines it
    store, adir, ck, invs = _copy_store(
        base_artifact, tmp_path / "torn"
    )
    mpath = os.path.join(adir, warm_store.MANIFEST)
    blob = open(mpath).read()
    with open(mpath, "w") as f:
        f.write(blob[: len(blob) // 2])
    p = _replan(store, ck, invs, base)
    assert p.mode == "cold"
    assert store.sweep()  # quarantined
    assert not os.path.isdir(adir)
    assert os.listdir(store.quarantine_dir)

    # digest tamper: verify() fails (the install-time gate)
    store, adir, ck, invs = _copy_store(
        base_artifact, tmp_path / "tamper"
    )
    fpath = os.path.join(adir, warm_store.FRAME)
    raw = bytearray(open(fpath, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(fpath, "wb") as f:
        f.write(bytes(raw))
    ok, why = store.verify(adir)
    assert not ok and why.startswith(warm_plan.REASON_DIGEST)


# ---- robustness drills ----------------------------------------------


def test_corrupt_warm_demotes_to_cold_with_parity(
    tmp_path, pool, cfg_dir
):
    """``corrupt@warm:N``: the install-time digest verification
    computes a corrupted digest — the job demotes to a full cold
    recheck (typed reason, quarantined artifact) and the verdict
    still equals the solo run."""
    sched, config = _sched(tmp_path / "state", pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    j1 = sched.submit("compaction", cfg, max_states=600)
    sched.run_until_idle()
    assert j1.result["status"] == "truncated"
    prev = os.environ.get("PTT_FAULT")
    os.environ["PTT_FAULT"] = (
        f"corrupt@warm:{sched.warm_store._verify_n + 1}"
    )
    faults.reset()
    try:
        j2 = sched.submit("compaction", cfg)
        assert j2.warm_mode == "continue"  # the plan trusts the store
        sched.run_until_idle()
    finally:
        if prev is None:
            os.environ.pop("PTT_FAULT", None)
        else:
            os.environ["PTT_FAULT"] = prev
        faults.reset()
    assert j2.warm_mode == "cold"
    assert j2.warm_reason == warm_plan.REASON_DIGEST
    assert j2.result["warm"] == "cold"
    assert j2.result["warm_reason"] == warm_plan.REASON_DIGEST
    solo = _solo(pool, "compaction", cfg, GEOM["max_states"])
    assert j2.result["distinct_states"] == solo.distinct_states
    assert j2.result["level_sizes"] == [
        int(x) for x in solo.level_sizes
    ]
    assert os.listdir(sched.warm_store.quarantine_dir)
    assert sched.warm_counts[("cold", warm_plan.REASON_DIGEST)] == 1


def test_torn_warmwrite_artifact_quarantined(tmp_path, pool, cfg_dir):
    """``torn@warmwrite:N``: the harvest publishes half a manifest —
    the artifact is unreadable, the next submit plans cold, and the
    startup sweep quarantines the torn dir."""
    sched, config = _sched(tmp_path / "state", pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    prev = os.environ.get("PTT_FAULT")
    os.environ["PTT_FAULT"] = "torn@warmwrite:1"
    faults.reset()
    try:
        j1 = sched.submit("compaction", cfg, max_states=600)
        sched.run_until_idle()
    finally:
        if prev is None:
            os.environ.pop("PTT_FAULT", None)
        else:
            os.environ["PTT_FAULT"] = prev
        faults.reset()
    assert j1.result["status"] == "truncated"  # job unaffected
    j2 = sched.submit("compaction", cfg)
    assert j2.warm_mode == "cold"
    assert j2.warm_reason in (
        warm_plan.REASON_NO_ARTIFACT, warm_plan.REASON_TORN
    )
    # a freshly constructed store (daemon restart) quarantines it
    store2 = warm_store.WarmStore(config.warm_dir)
    assert store2.sweep()
    assert os.listdir(store2.quarantine_dir)


def test_kill_mid_warm_write_subprocess_drill(tmp_path, cfg_dir):
    """THE mid-harvest crash drill: ``kill@warmwrite:1`` hard-kills
    the daemon process between the artifact's frame copy and its
    manifest publish.  The restarted scheduler's startup sweep
    quarantines the manifest-less dir, the resubmit plans an honest
    cold recheck, and the verdict is still exact."""
    state = tmp_path / "state"
    driver = f"""
import os, sys
sys.path.insert(0, {ROOT!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PTT_FAULT"] = "kill@warmwrite:1"
from pulsar_tlaplus_tpu.service.scheduler import Scheduler, ServiceConfig
config = ServiceConfig(state_dir={str(state)!r}, **{GEOM!r})
sched = Scheduler(config)
sched.submit("compaction", {str(cfg_dir / "small_compaction.cfg")!r},
             max_states=600)
sched.run_until_idle()
print("UNREACHED")  # the kill fires inside the harvest
"""
    proc = subprocess.run(
        [sys.executable, "-c", driver],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 137, (proc.stdout, proc.stderr)
    assert "UNREACHED" not in proc.stdout
    # the artifact dir exists but has no manifest (frame copied, kill
    # before publish): a fresh scheduler quarantines it at startup
    config = ServiceConfig(state_dir=str(state), **GEOM)
    leftovers = [
        d for d in os.listdir(config.warm_dir)
        if d != "quarantine" and not d.startswith(".")
    ]
    assert leftovers  # the torn dir is there...
    sched = Scheduler(config)
    sched.recover()
    assert [
        d for d in os.listdir(config.warm_dir)
        if d != "quarantine" and not d.startswith(".")
    ] == []  # ...and swept into quarantine
    assert os.listdir(sched.warm_store.quarantine_dir)
    j = sched.submit(
        "compaction", str(cfg_dir / "small_compaction.cfg")
    )
    assert (j.warm_mode, j.warm_reason) == (
        "cold", warm_plan.REASON_NO_ARTIFACT
    )


def test_no_warm_opt_out(tmp_path, pool, cfg_dir):
    """--no-warm: neither reuse nor harvest."""
    sched, config = _sched(tmp_path / "state", pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    j1 = sched.submit("compaction", cfg, max_states=600, warm=False)
    assert (j1.warm_mode, j1.warm_reason) == (
        "cold", warm_plan.REASON_OPT_OUT
    )
    sched.run_until_idle()
    assert [
        d for d in os.listdir(config.warm_dir)
        if d != "quarantine" and not d.startswith(".")
    ] == []  # no artifact harvested
    j2 = sched.submit("compaction", cfg, warm=False)
    assert j2.warm_reason == warm_plan.REASON_OPT_OUT


def test_warm_store_lru_byte_cap(tmp_path, base_artifact):
    """--warm-max-bytes: oldest-touched artifacts evict past the cap
    (the aot_cache discipline)."""
    store, adir, _ck, _invs = _copy_store(base_artifact, tmp_path / "s")
    nbytes = store.entry_bytes(adir)
    # a second entry under a forged sig key, with the first made OLD
    dst = os.path.join(store.root, "ffffffffffffffff")
    shutil.copytree(adir, dst)
    os.utime(os.path.join(adir, warm_store.MANIFEST), (1, 1))
    store.max_bytes = nbytes + 10  # room for ONE artifact
    assert store.enforce_cap() == 1
    assert not os.path.isdir(adir)  # oldest-touched evicted
    assert os.path.isdir(dst)
    store.max_bytes = 0  # 0 = the layer is off, cap never enforced
    assert store.enforce_cap() == 0


# ---- satellites -----------------------------------------------------


def test_sim_admission_priced_from_walk_budget(tmp_path, pool, cfg_dir):
    """Satellite (r18 NOTE): a sim job prices at its ACTUAL step/walk
    budget, not the BFS default max_states."""
    assert admmod.state_price(None, "check", None, 500) == 500
    assert admmod.state_price(1000, "check", None, 500) == 1000
    assert admmod.state_price(
        None, "simulate", {"n_walkers": 16, "depth": 64}, 10**9
    ) == 16 * 65
    assert admmod.state_price(
        None, "simulate", {"max_steps": 4096}, 10**9
    ) == 4096
    # end to end through the scheduler door: the quota admits the
    # small sim job where a default-priced BFS job is rejected
    sched, _config = _sched(
        tmp_path / "state", pool, tenant_max_states=10_000
    )
    cfg = str(cfg_dir / "small_compaction.cfg")
    js = sched.submit(
        "compaction", cfg, tenant="alpha", mode="simulate",
        sim={"n_walkers": 16, "depth": 64},
    )
    assert js.state == jobmod.QUEUED  # admitted: priced 1,040
    with pytest.raises(admmod.AdmissionError) as ei:
        # a check job at the 1M default blows the 10k quota
        sched.submit("compaction", cfg, tenant="alpha")
    assert ei.value.reason == "tenant_states"
    # the live sim job's aggregate price is its walk budget too: a
    # second small sim job still fits under the quota
    sched.submit(
        "compaction", cfg, tenant="alpha", mode="simulate",
        sim={"n_walkers": 16, "depth": 64},
    )


def test_rejected_submit_never_builds_a_checker(
    tmp_path, cfg_dir
):
    """Admission gates BEFORE warm planning: an over-quota submit is
    shed at the door without constructing (and permanently pooling) a
    DeviceChecker — the submit-spam cost admission control exists to
    prevent."""
    config = ServiceConfig(
        state_dir=str(tmp_path / "state"), tenant_max_queued=1, **GEOM
    )
    own_pool = CheckerPool(config)
    sched = Scheduler(config, pool=own_pool)
    cfg = str(cfg_dir / "small_compaction.cfg")
    sched.submit("compaction", cfg, tenant="alpha")
    n_before = len(own_pool._checkers)
    with pytest.raises(admmod.AdmissionError):
        # a DISTINCT pool key (max_states differs): were planning to
        # run before admission, this would build + pool a checker
        sched.submit(
            "compaction", cfg, tenant="alpha", max_states=12345
        )
    assert len(own_pool._checkers) == n_before
    assert not any(k[3] == 12345 for k in own_pool._checkers)


def test_ledger_warm_tagging_and_gate_baseline(tmp_path):
    """Satellite: warm mode tags ledger records from the v12 run
    header; the default gate baseline never crosses warm contexts;
    re-ingesting the same stream under a new path dedupes."""

    def stream(warm, path, states):
        events = [
            {
                "v": 12, "event": "run_header", "t": 0.0, "seq": 0,
                "run_id": "r1", "engine": "device_bfs",
                "visited_impl": "fpset", "config_sig": "SIG",
                "profile_sig": None, "hbm_budget": None,
                "tenant": None, "mode": "check", "warm": warm,
                "fuse": "level", "compact_impl": "logshift",
            },
            {
                "v": 12, "event": "result", "t": 1.0, "seq": 1,
                "run_id": "r1", "distinct_states": states,
                "diameter": 3, "wall_s": 1.0, "truncated": False,
                "stats": {},
            },
        ]
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return events

    cold_events = stream(None, tmp_path / "cold.jsonl", 1000)
    warm_events = stream("continue", tmp_path / "warm.jsonl", 400)
    rc = ledger.record_from_stream(cold_events, source="cold.jsonl")
    rw = ledger.record_from_stream(warm_events, source="warm.jsonl")
    assert "warm" not in rc["values"]
    assert rw["values"]["warm"] == "continue"
    assert ledger.warm_of(rc) == "cold"
    assert ledger.warm_of(rw) == "continue"
    assert not ledger.baseline_matches_warm(rw, rc)
    assert not ledger.baseline_matches_warm(rc, rw)
    assert ledger.baseline_matches_warm(rc, rc)
    # same config key either way (comparability grouping unchanged)
    assert rc["key"] == rw["key"]

    # dedupe: the SAME stream content under a NEW file path is one
    # ledger record (digest is over values, not the path)
    lpath = str(tmp_path / "LEDGER.jsonl")
    assert ledger.append(lpath, [rc]) == 1
    shutil.copyfile(tmp_path / "cold.jsonl", tmp_path / "cold2.jsonl")
    rc2 = ledger.record_from_file(str(tmp_path / "cold2.jsonl"))
    assert rc2["digest"] == rc["digest"]
    assert ledger.append(lpath, [rc2]) == 0  # deduped
    assert ledger.append(lpath, [rw]) == 1

    # the default-baseline scan (the cli `ledger gate` rule): gating
    # the cold record must refuse the warm-continue partial
    rc_new = dict(rc)
    rc_new["values"] = dict(rc["values"], distinct_states=1001)
    rc_new["digest"] = "f" * 16
    with open(lpath, "a") as f:
        f.write(json.dumps(rc_new) + "\n")
    recs = ledger.load(lpath)
    cur = recs[-1]
    base = next(
        (
            r for r in reversed(recs[:-1])
            if r.get("key") == cur.get("key")
            and ledger.baseline_matches_warm(r, cur)
        ),
        None,
    )
    assert base is not None and base["digest"] == rc["digest"]


def test_validator_warm_flag_and_v12(
    tmp_path, checker_mod, base_artifact
):
    """Satellite: ``check_telemetry_schema --warm`` validates artifact
    digests; the v12 stream schema gates run_header.warm and the warm
    event."""
    store, adir, _ck, _invs = _copy_store(base_artifact, tmp_path / "v")
    assert checker_mod.main(["--warm", adir]) == 0
    # tamper -> violations
    fpath = os.path.join(adir, warm_store.FRAME)
    raw = bytearray(open(fpath, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(fpath, "wb") as f:
        f.write(bytes(raw))
    assert checker_mod.main(["--warm", adir]) == 1

    # v12 stream rules: a v12 run_header without `warm` fails, a v11
    # one stays clean (FIELD_SINCE); a warm event needs mode+reason
    def write_stream(path, recs):
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return str(path)

    head = {
        "event": "run_header", "t": 0.0, "seq": 0, "run_id": "x",
        "engine": "device_bfs", "visited_impl": "fpset",
        "config_sig": "S", "profile_sig": None, "hbm_budget": None,
        "tenant": None, "mode": "check",
    }
    bad = write_stream(tmp_path / "bad.jsonl", [dict(head, v=12)])
    assert any(
        "warm" in e for e in checker_mod.validate_stream(bad)
    )
    ok11 = write_stream(tmp_path / "ok11.jsonl", [dict(head, v=11)])
    assert checker_mod.validate_stream(ok11) == []
    ok12 = write_stream(
        tmp_path / "ok12.jsonl", [dict(head, v=12, warm=None)]
    )
    assert checker_mod.validate_stream(ok12) == []
    badw = write_stream(
        tmp_path / "badw.jsonl",
        [
            dict(head, v=12, warm=None),
            {
                "v": 12, "event": "warm", "t": 0.1, "seq": 1,
                "run_id": "x", "mode": "cold",
            },
        ],
    )
    assert any(
        "reason" in e for e in checker_mod.validate_stream(badw)
    )


def test_warm_metrics_stream_scrape_parity(tmp_path, pool, cfg_dir):
    """ptt_warm_{hit,reseed,cold}_total{reason} derive from the
    daemon stream tail with the SAME names and counting points as the
    live scheduler (cold counts at plan, continue/reseed at install —
    a demotion counts once, as cold)."""
    from pulsar_tlaplus_tpu.obs import telemetry as obs

    config = ServiceConfig(state_dir=str(tmp_path / "state"), **GEOM)
    tel = obs.Telemetry(config.telemetry_path)
    sched = Scheduler(config, pool=pool, telemetry=tel)
    # the exact event shapes the scheduler emits, without re-running
    # engines: one cold plan, one continue plan + install (counts at
    # install), one demoted install, one harvest (not counted)
    tel.emit("warm", phase="plan", mode="cold", reason="no_artifact")
    tel.emit("warm", phase="plan", mode="continue", reason="sig_match")
    tel.emit(
        "warm", phase="install", mode="continue", reason="sig_match"
    )
    tel.emit(
        "warm", phase="install", mode="cold", reason="digest_mismatch"
    )
    tel.emit("warm", phase="harvest", mode="cold", reason="harvested")
    events, _errs = report.load_events(config.telemetry_path)
    stext = metrics_mod.render_exposition(
        metrics_mod.stream_metrics(events)
    )
    assert 'ptt_warm_cold_total{reason="no_artifact"} 1' in stext
    assert 'ptt_warm_hit_total{reason="sig_match"} 1' in stext
    assert (
        'ptt_warm_cold_total{reason="digest_mismatch"} 1' in stext
    )
    assert "harvested" not in stext  # harvest is not an outcome
    # and the live renderer names the same families from the counters
    sched.warm_counts[("cold", "no_artifact")] = 1
    ltext = metrics_mod.render_exposition(
        metrics_mod.scheduler_metrics(sched)
    )
    assert 'ptt_warm_cold_total{reason="no_artifact"} 1' in ltext


@pytest.mark.slow
def test_fuzz_soak_slow_lane():
    """The scheduled long-randomized soak (ROADMAP r18 follow-up +
    ISSUE 15 satellite): 20 bindings/spec through the plain
    device-vs-interpreter differential AND 20 widenings/spec through
    the warm-reseed differential."""
    fuzz = _load_script("fuzz")
    _records, failures = fuzz.run(20, 20, log=lambda m: None)
    assert failures == []
    _records, failures = fuzz.run_widen(20, 20, log=lambda m: None)
    assert failures == []


def test_fuzz_widen_fast_drill(tmp_path):
    """Satellite: the pinned-seed --widen drill on the spec whose
    axis is layout-stable under every widening (bookkeeper's popcount
    axis) — a genuine reseed differential runs warm-vs-cold in
    tier-1; the all-spec randomized sweep is the slow soak lane."""
    fuzz = _load_script("fuzz")
    # the suite-common geometry: every jit shape is already in the
    # persistent compile cache, so the drill pays no fresh compiles
    fuzz.DEVICE_KW = dict(
        sub_batch=64, visited_cap=1 << 10, frontier_cap=1 << 8,
        max_states=1 << 18,
    )
    records, failures = fuzz.run_widen(
        seed=5, per_spec=1, specs=("bookkeeper",), log=lambda m: None
    )
    assert failures == []
    assert len(records) == 1
    assert (records[0].get("plan") or {}).get("mode") == "reseed"
    assert records[0]["reseed"]["replay_rows"] >= 1
    assert records[0]["reseed"]["reused_rows"] >= 1
