--------------------------- MODULE georeplication ---------------------------
(***************************************************************************)
(* Model of Apache Pulsar geo-replication across a full mesh of clusters   *)
(* (the canonical deployment is 3).  Each cluster owns a local copy of the *)
(* topic; producers publish locally, and a per-(source, destination)       *)
(* replicator ships the source's LOCALLY-ORIGINATED messages to the other  *)
(* clusters in order (origin marking prevents replication loops, so a      *)
(* message hops exactly once: origin -> every other cluster).              *)
(*                                                                         *)
(* Each replicator is a Pulsar consumer on the source topic with its own   *)
(* cursor.  The in-memory read position (`repCursor`) runs ahead of the    *)
(* durably persisted position (`repAcked`) — acking is lazy, exactly like  *)
(* the compaction cursor (reference compaction.tla:147-151).  When a       *)
(* replicator crashes and fails over, it resumes from the durable          *)
(* position and RE-SHIPS everything in (repAcked, repCursor] — Pulsar      *)
(* geo-replication is at-least-once, and the `duplicated` history makes    *)
(* the resulting duplicate deliveries observable (violated invariant      *)
(* NoDuplicateDelivery, the known anomaly when broker deduplication is     *)
(* not enabled on the remote topic).                                       *)
(*                                                                         *)
(* Message identity is (origin cluster, per-origin seqno); per-pair        *)
(* delivery is in seqno order, so the set of messages dst holds from src   *)
(* is always the prefix 1..recvHwm[dst][src] — recvHwm is the monotone     *)
(* high watermark (it never rewinds; only the cursor does).                *)
(***************************************************************************)
EXTENDS Naturals, FiniteSets

CONSTANTS
    NumClusters,          \* mesh size (headline config: 3)
    PublishLimit,         \* messages published per cluster
    MaxReplicatorCrashes  \* bound on replicator failovers (mesh-wide)

ASSUME
    /\ NumClusters \in Nat /\ NumClusters >= 2
    /\ PublishLimit \in Nat /\ PublishLimit >= 1
    /\ MaxReplicatorCrashes \in Nat

VARIABLES
    published,   \* [c -> count of messages published at (originating in) c]
    recvHwm,     \* [dst -> [src -> high watermark of src-origin msgs at dst]]
    repCursor,   \* [src -> [dst -> in-memory replicator read position]]
    repAcked,    \* [src -> [dst -> durably persisted replicator position]]
    duplicated,  \* [dst -> [src -> set of seqnos delivered twice at dst]]
    crashTimes

vars == <<published, recvHwm, repCursor, repAcked, duplicated, crashTimes>>

Clusters == 1..NumClusters

ZeroMatrix == [a \in Clusters |-> [b \in Clusters |-> 0]]

Init ==
    /\ published = [c \in Clusters |-> 0]
    /\ recvHwm = ZeroMatrix
    /\ repCursor = ZeroMatrix
    /\ repAcked = ZeroMatrix
    /\ duplicated = [a \in Clusters |-> [b \in Clusters |-> {}]]
    /\ crashTimes = 0

(* A producer publishes the next message at cluster c (delivered locally
   by the broker; replication to the mesh is asynchronous). *)
Publish ==
    /\ \E c \in Clusters :
        /\ published[c] < PublishLimit
        /\ published' = [published EXCEPT ![c] = published[c] + 1]
    /\ UNCHANGED <<recvHwm, repCursor, repAcked, duplicated, crashTimes>>

(* The (src -> dst) replicator ships the next local-origin message.  After
   a failover rewound the cursor below the high watermark, the shipped
   message is a DUPLICATE at dst. *)
Replicate ==
    /\ \E src \in Clusters :
        \E dst \in Clusters :
            /\ src # dst
            /\ repCursor[src][dst] < published[src]
            /\ repCursor' = [repCursor EXCEPT
                   ![src] = [repCursor[src] EXCEPT
                       ![dst] = repCursor[src][dst] + 1]]
            /\ recvHwm' = [recvHwm EXCEPT
                   ![dst] = [recvHwm[dst] EXCEPT
                       ![src] = IF repCursor[src][dst] + 1 > recvHwm[dst][src]
                                THEN repCursor[src][dst] + 1
                                ELSE recvHwm[dst][src]]]
            /\ duplicated' = [duplicated EXCEPT
                   ![dst] = [duplicated[dst] EXCEPT
                       ![src] = IF repCursor[src][dst] + 1 <= recvHwm[dst][src]
                                THEN duplicated[dst][src]
                                         \cup {repCursor[src][dst] + 1}
                                ELSE duplicated[dst][src]]]
    /\ UNCHANGED <<published, repAcked, crashTimes>>

(* The replicator durably acks its read position (lazy, like the
   compaction cursor persist at compaction.tla:147-151). *)
PersistCursor ==
    /\ \E src \in Clusters :
        \E dst \in Clusters :
            /\ src # dst
            /\ repAcked[src][dst] < repCursor[src][dst]
            /\ repAcked' = [repAcked EXCEPT
                   ![src] = [repAcked[src] EXCEPT
                       ![dst] = repCursor[src][dst]]]
    /\ UNCHANGED <<published, recvHwm, repCursor, duplicated, crashTimes>>

(* Replicator failover: the new instance resumes from the durable cursor,
   forgetting the unacked read-ahead.  Only rewinding crashes are modeled
   (a crash with repCursor = repAcked changes nothing observable). *)
ReplicatorCrash ==
    /\ crashTimes < MaxReplicatorCrashes
    /\ \E src \in Clusters :
        \E dst \in Clusters :
            /\ src # dst
            /\ repAcked[src][dst] < repCursor[src][dst]
            /\ repCursor' = [repCursor EXCEPT
                   ![src] = [repCursor[src] EXCEPT
                       ![dst] = repAcked[src][dst]]]
    /\ crashTimes' = crashTimes + 1
    /\ UNCHANGED <<published, recvHwm, repAcked, duplicated>>

(* Fully replicated and quiesced. *)
Done ==
    /\ \A c \in Clusters : published[c] = PublishLimit
    /\ \A src \in Clusters : \A dst \in Clusters :
        src # dst =>
            /\ repCursor[src][dst] = PublishLimit
            /\ repAcked[src][dst] = PublishLimit

Terminating ==
    /\ Done
    /\ UNCHANGED vars

Next ==
    \/ Publish
    \/ Replicate
    \/ PersistCursor
    \/ ReplicatorCrash
    \/ Terminating

Spec == Init /\ [][Next]_vars

-----------------------------------------------------------------------------
(* Invariants *)

TypeOK ==
    /\ \A c \in Clusters :
        /\ published[c] \in 0..PublishLimit
        /\ recvHwm[c][c] = 0
        /\ repCursor[c][c] = 0
        /\ repAcked[c][c] = 0
        /\ duplicated[c][c] = {}
    /\ \A src \in Clusters : \A dst \in Clusters :
        src # dst =>
            /\ repCursor[src][dst] \in 0..published[src]
            /\ repAcked[src][dst] \in 0..repCursor[src][dst]
            /\ recvHwm[dst][src] \in 0..published[src]
            /\ duplicated[dst][src] \subseteq 1..recvHwm[dst][src]
    /\ crashTimes \in 0..MaxReplicatorCrashes

(* Per-pair delivery is in order and the watermark is monotone: what dst
   holds from src is exactly the prefix up to the watermark, and the
   replicator never reads past what it already delivered. *)
CursorWithinWatermark ==
    \A src \in Clusters : \A dst \in Clusters :
        src # dst => repCursor[src][dst] <= recvHwm[dst][src]

(* A message never reaches a remote cluster before it exists at its
   origin — origin marking means exactly one hop. *)
NoPhantomMessages ==
    \A src \in Clusters : \A dst \in Clusters :
        src # dst => recvHwm[dst][src] <= published[src]

(* Geo-replication is at-least-once: a replicator failover between read
   and cursor persist re-ships the gap.  VIOLATED whenever
   MaxReplicatorCrashes >= 1 — enable to get the duplicate-delivery
   counterexample (the known anomaly when broker deduplication is not
   enabled on the remote cluster). *)
NoDuplicateDelivery ==
    \A dst \in Clusters : \A src \in Clusters :
        duplicated[dst][src] = {}

-----------------------------------------------------------------------------
(* With weak fairness every message reaches every cluster and the mesh
   quiesces (crashes are bounded). *)
Termination ==
    <>Done

=============================================================================
