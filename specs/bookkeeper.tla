----------------------------- MODULE bookkeeper -----------------------------
(***************************************************************************)
(* Model of Apache BookKeeper's ledger write path: a single writer adds    *)
(* entries to a ledger striped over an ensemble of bookies with a write    *)
(* quorum and an ack quorum, advancing the LastAddConfirmed (LAC) position *)
(* as ack quorums complete, while bookies may crash and lose their data.   *)
(*                                                                         *)
(* The modeled roles:                                                      *)
(*   - writer: sends entry e to its deterministic round-robin write set    *)
(*             of WriteQuorum bookies; confirms e (advances LAC) once      *)
(*             AckQuorum of them have acked; acks are monotone writer      *)
(*             knowledge — a bookie crashing later does NOT revoke them;   *)
(*   - bookie: persists a write, then its ack travels to the writer;       *)
(*             a crash is permanent and loses ALL data on that bookie      *)
(*             (node-replacement failure model, no autorecovery);          *)
(*   - environment: at most MaxBookieCrashes crashes.                      *)
(*                                                                         *)
(* The headline property is BookKeeper's durability contract: a confirmed  *)
(* entry survives as long as FEWER than AckQuorum bookies fail.  With      *)
(* MaxBookieCrashes >= AckQuorum the invariant ConfirmedEntryReadable is   *)
(* violated — the writer confirmed an entry to its client on AckQuorum     *)
(* acks, then every bookie holding it crashed (the counterexample shows    *)
(* exactly the ack-then-crash interleaving).                               *)
(*                                                                         *)
(* Companion spec to compaction.tla from thetumbled/pulsar-tlaplus         *)
(* (crash-bounding and Terminating-self-loop conventions per               *)
(* compaction.tla:169-182, 205-214).                                       *)
(***************************************************************************)
EXTENDS Naturals, FiniteSets

CONSTANTS
    NumBookies,        \* ensemble size E
    WriteQuorum,       \* Qw: bookies each entry is written to
    AckQuorum,         \* Qa: acks required to confirm an entry
    EntryLimit,        \* how many entries the writer adds
    MaxBookieCrashes   \* bound on bookie failures

ASSUME
    /\ NumBookies \in Nat /\ NumBookies >= 1
    /\ WriteQuorum \in 1..NumBookies
    /\ AckQuorum \in 1..WriteQuorum
    /\ EntryLimit \in Nat /\ EntryLimit >= 1
    /\ MaxBookieCrashes \in 0..NumBookies

VARIABLES
    added,    \* entries sent so far (ids 1..added)
    stored,   \* [bookie -> set of entry ids persisted on it]
    ackedBy,  \* [entry -> set of bookies whose ack reached the writer]
    lac,      \* LastAddConfirmed: entries 1..lac are confirmed to clients
    crashed   \* set of permanently failed bookies

vars == <<added, stored, ackedBy, lac, crashed>>

Bookies == 1..NumBookies
Entries == 1..EntryLimit

(* Round-robin striping: entry e goes to WriteQuorum bookies starting at
   bookie ((e-1) % E) + 1 (BookKeeper's RoundRobinDistributionSchedule). *)
WriteSet(e) == {((e - 1 + i) % NumBookies) + 1 : i \in 0..(WriteQuorum - 1)}

Init ==
    /\ added = 0
    /\ stored = [b \in Bookies |-> {}]
    /\ ackedBy = [e \in Entries |-> {}]
    /\ lac = 0
    /\ crashed = {}

(* Writer sends the next entry (to its write set; landing is async). *)
AddEntry ==
    /\ added < EntryLimit
    /\ added' = added + 1
    /\ UNCHANGED <<stored, ackedBy, lac, crashed>>

(* A pending write lands on a live write-set bookie. *)
WriteLand ==
    /\ \E b \in Bookies :
        \E e \in Entries :
            /\ e <= added
            /\ b \in WriteSet(e)
            /\ b \notin crashed
            /\ e \notin stored[b]
            /\ stored' = [stored EXCEPT ![b] = stored[b] \cup {e}]
    /\ UNCHANGED <<added, ackedBy, lac, crashed>>

(* A bookie's ack reaches the writer.  Writer knowledge is monotone: the
   ack stays even if the bookie crashes afterwards — this is the race the
   durability bound lives on. *)
AckArrive ==
    /\ \E b \in Bookies :
        \E e \in Entries :
            /\ e \in stored[b]
            /\ b \notin ackedBy[e]
            /\ ackedBy' = [ackedBy EXCEPT ![e] = ackedBy[e] \cup {b}]
    /\ UNCHANGED <<added, stored, lac, crashed>>

(* LAC advances in order once the next entry has an ack quorum. *)
AdvanceLAC ==
    /\ lac < added
    /\ Cardinality(ackedBy[lac + 1]) >= AckQuorum
    /\ lac' = lac + 1
    /\ UNCHANGED <<added, stored, ackedBy, crashed>>

(* Permanent bookie failure with data loss (node replacement). *)
BookieCrash ==
    /\ Cardinality(crashed) < MaxBookieCrashes
    /\ \E b \in Bookies :
        /\ b \notin crashed
        /\ crashed' = crashed \cup {b}
        /\ stored' = [stored EXCEPT ![b] = {}]
    /\ UNCHANGED <<added, ackedBy, lac>>

(* End states: all entries confirmed, or the next entry can never reach an
   ack quorum (too many of its write-set bookies died before acking) and
   the ledger is wedged.  Self-loop so TLC reports no deadlock. *)
Wedged ==
    /\ lac < added
    /\ Cardinality(ackedBy[lac + 1]
           \cup {b \in WriteSet(lac + 1) : b \notin crashed}) < AckQuorum

Done ==
    /\ added = EntryLimit
    /\ \/ lac = EntryLimit
       \/ Wedged

Terminating ==
    /\ Done
    /\ UNCHANGED vars

Next ==
    \/ AddEntry
    \/ WriteLand
    \/ AckArrive
    \/ AdvanceLAC
    \/ BookieCrash
    \/ Terminating

Spec == Init /\ [][Next]_vars

-----------------------------------------------------------------------------
(* Invariants *)

TypeOK ==
    /\ added \in 0..EntryLimit
    /\ lac \in 0..added
    /\ crashed \subseteq Bookies
    /\ Cardinality(crashed) <= MaxBookieCrashes
    /\ \A b \in Bookies :
        /\ stored[b] \subseteq Entries
        /\ \A e \in stored[b] : e <= added /\ b \in WriteSet(e)
    /\ \A e \in Entries :
        /\ ackedBy[e] \subseteq WriteSet(e)
        /\ \A b \in ackedBy[e] : e <= added
    /\ \A b \in crashed : stored[b] = {}

(* Confirmation is honest: every confirmed entry reached an ack quorum. *)
LacIsConfirmed ==
    \A e \in 1..lac : Cardinality(ackedBy[e]) >= AckQuorum

(* Acks only come from bookies that stored the entry — unless the bookie
   has since crashed (ack knowledge is monotone, storage is not). *)
AckImpliesStoredOrCrashed ==
    \A e \in Entries : \A b \in ackedBy[e] :
        e \in stored[b] \/ b \in crashed

(* BookKeeper's durability contract: a confirmed entry is still readable
   somewhere.  HOLDS whenever MaxBookieCrashes < AckQuorum; VIOLATED as
   soon as MaxBookieCrashes >= AckQuorum (every replica of a confirmed
   entry can crash after acking) — enable it in such a cfg to get the
   ack-then-crash counterexample trace. *)
ConfirmedEntryReadable ==
    \A e \in 1..lac : \E b \in Bookies : e \in stored[b]

-----------------------------------------------------------------------------
(* With weak fairness the ledger run always finishes: either everything
   confirms or the ledger wedges on a crash-starved entry. *)
Termination ==
    <>Done

=============================================================================
