---------------------------- MODULE compaction ----------------------------
(***************************************************************************)
(* Pulsar topic compaction (thetumbled/pulsar-tlaplus), vendored for this  *)
(* repo: the spec->kernel compiler, generic interpreter, and the pyeval    *)
(* oracle (ref/pyeval.py) are all differentially tested against this       *)
(* module.  A producer appends keyed messages; a two-phase compactor       *)
(* builds a compacted ledger (phase one scans for the latest position per  *)
(* key, phase two writes/publishes it); the broker may crash between any   *)
(* two compactor steps, rolling the compaction horizon back to the last    *)
(* persisted cursor.  Two known, unfixed Pulsar bugs are expressible as    *)
(* invariant violations: CompactedLedgerLeak (more than two compacted      *)
(* ledgers alive) and DuplicateNullKeyMessage (a retained null-key entry   *)
(* readable both from the compacted ledger and the topic tail).           *)
(*                                                                         *)
(* Ground truth at the shipped configuration (compaction.cfg):             *)
(* 45,198 distinct reachable states, search depth (diameter) 20.           *)
(***************************************************************************)
EXTENDS Naturals, Sequences, FiniteSets

CONSTANTS
    MessageSentLimit,      \* messages the producer may send
    CompactionTimesLimit,  \* compacted-ledger slots (compaction runs)
    ModelConsumer,         \* include the consumer role
    ConsumeTimesLimit,     \* consumer reads to termination
    KeySpace,              \* message keys (NullKey added below)
    ValueSpace,            \* message values (NullValue added below)
    RetainNullKey,         \* compaction keeps null-key messages
    MaxCrashTimes,         \* bound on broker crashes
    ModelProducer          \* TRUE: producer acts; FALSE: drawn at Init

ASSUME
    /\ MessageSentLimit \in Nat
    /\ CompactionTimesLimit \in Nat
    /\ ModelConsumer \in BOOLEAN
    /\ ConsumeTimesLimit \in Nat
    /\ KeySpace \in SUBSET Nat
    /\ ValueSpace \in SUBSET Nat
    /\ RetainNullKey \in BOOLEAN
    /\ MaxCrashTimes \in Nat
    /\ ModelProducer \in BOOLEAN

CONSTANTS
    Nil,
    Compactor_In_PhaseOne,
    Compactor_In_PhaseTwoWrite,
    Compactor_In_PhaseTwoUpdateContext,
    Compactor_In_PhaseTwoUpdateHorizon,
    Compactor_In_PhaseTwoPersistCusror,  \* [sic] the reference's spelling
    Compactor_In_PhaseTwoDeleteLedger

NullKey == 0
NullValue == 0
KeySet == KeySpace \cup {NullKey}
ValueSet == ValueSpace \cup {NullValue}

CompactorStates == {
    Compactor_In_PhaseOne,
    Compactor_In_PhaseTwoWrite,
    Compactor_In_PhaseTwoUpdateContext,
    Compactor_In_PhaseTwoUpdateHorizon,
    Compactor_In_PhaseTwoPersistCusror,
    Compactor_In_PhaseTwoDeleteLedger
}

VARIABLES
    messages,              \* sequence of [id, key, value] records
    compactedLedgers,      \* [1..CompactionTimesLimit -> Nil | seq of records]
    cursor,                \* Nil | [compactionHorizon, compactedTopicContext]
    compactorState,        \* one of CompactorStates
    phaseOneResult,        \* Nil | [readPosition, latestForKey]
    compactionHorizon,     \* messages 1..horizon are served compacted
    compactedTopicContext, \* id of the ledger serving the compacted view
    crashTimes,            \* broker crashes so far
    consumeTimes           \* consumer reads so far

vars == <<messages, compactedLedgers, cursor, compactorState, phaseOneResult,
          compactionHorizon, compactedTopicContext, crashTimes, consumeTimes>>

MessageSpace == [id: 1..MessageSentLimit, key: KeySet, value: ValueSet]

Max(S) == CHOOSE x \in S : \A y \in S : y <= x

(* The producer appends the next message (id = its position). *)
Producer ==
    /\ Len(messages) < MessageSentLimit
    /\ \E key \in KeySet :
        \E value \in ValueSet :
            messages' = Append(messages,
                [id |-> Len(messages) + 1, key |-> key, value |-> value])
    /\ UNCHANGED <<compactedLedgers, cursor, compactorState, phaseOneResult,
                   compactionHorizon, compactedTopicContext, crashTimes,
                   consumeTimes>>

(* Phase one: scan the whole topic, recording the read position and the
   latest position of every non-null key. *)
CompactorPhaseOne ==
    /\ compactorState = Compactor_In_PhaseOne
    /\ phaseOneResult = Nil
    /\ Len(messages) > 0
    /\ LET n == Len(messages)
           keys == {messages[i].key : i \in 1..n} \ {NullKey}
       IN phaseOneResult' = [
              readPosition |-> n,
              latestForKey |-> [k \in keys |->
                  Max({i \in 1..n : messages[i].key = k})]]
    /\ compactorState' = Compactor_In_PhaseTwoWrite
    /\ UNCHANGED <<messages, compactedLedgers, cursor, compactionHorizon,
                   compactedTopicContext, crashTimes, consumeTimes>>

(* The id of the newest live compacted ledger; 0 when none exists. *)
MaxCompactedLedgerId ==
    IF \A i \in 1..CompactionTimesLimit : compactedLedgers[i] = Nil
    THEN 0
    ELSE CHOOSE i \in 1..CompactionTimesLimit :
            /\ compactedLedgers[i] # Nil
            /\ \A j \in 1..CompactionTimesLimit :
                   j > i => compactedLedgers[j] = Nil

(* The compacted view of the scanned prefix: null-key messages survive
   iff RetainNullKey; keyed messages survive only at their key's latest
   scanned position.  (Message ids equal their positions, by Init and
   Producer.) *)
CompactedMessages ==
    LET rp == phaseOneResult.readPosition
        lm == phaseOneResult.latestForKey
    IN SelectSeq(messages,
           LAMBDA m :
               /\ m.id <= rp
               /\ IF m.key = NullKey
                  THEN RetainNullKey
                  ELSE m.id = lm[m.key])

(* Phase two, step 1: write the compacted ledger into the next slot. *)
CompactorPhaseTwoWrite ==
    /\ phaseOneResult # Nil
    /\ compactorState = Compactor_In_PhaseTwoWrite
    /\ LET newLedgerId == MaxCompactedLedgerId + 1
       IN /\ newLedgerId >= 1
          /\ newLedgerId <= CompactionTimesLimit
          /\ compactedLedgers' =
                 [compactedLedgers EXCEPT ![newLedgerId] = CompactedMessages]
    /\ compactorState' = Compactor_In_PhaseTwoUpdateContext
    /\ UNCHANGED <<messages, cursor, phaseOneResult, compactionHorizon,
                   compactedTopicContext, crashTimes, consumeTimes>>

(* Phase two, step 2: point the topic context at the new ledger. *)
CompactorPhaseTwoUpdateContext ==
    /\ compactorState = Compactor_In_PhaseTwoUpdateContext
    /\ compactedTopicContext' = MaxCompactedLedgerId
    /\ compactorState' = Compactor_In_PhaseTwoUpdateHorizon
    /\ UNCHANGED <<messages, compactedLedgers, cursor, phaseOneResult,
                   compactionHorizon, crashTimes, consumeTimes>>

(* Phase two, step 3: advance the compaction horizon to the scan edge. *)
CompactorPhaseTwoUpdateHorizon ==
    /\ compactorState = Compactor_In_PhaseTwoUpdateHorizon
    /\ compactionHorizon' = phaseOneResult.readPosition
    /\ compactorState' = Compactor_In_PhaseTwoPersistCusror
    /\ UNCHANGED <<messages, compactedLedgers, cursor, phaseOneResult,
                   compactedTopicContext, crashTimes, consumeTimes>>

(* Phase two, step 4: persist horizon + context durably in the cursor. *)
CompactorPhaseTwoPersistCusror ==
    /\ compactorState = Compactor_In_PhaseTwoPersistCusror
    /\ cursor' = [compactionHorizon |-> compactionHorizon,
                  compactedTopicContext |-> compactedTopicContext]
    /\ compactorState' = Compactor_In_PhaseTwoDeleteLedger
    /\ UNCHANGED <<messages, compactedLedgers, phaseOneResult,
                   compactionHorizon, compactedTopicContext, crashTimes,
                   consumeTimes>>

(* Phase two, step 5: delete the superseded ledger (the one before the
   newest), then return to phase one. *)
CompactorPhaseTwoDeleteLedger ==
    /\ compactorState = Compactor_In_PhaseTwoDeleteLedger
    /\ LET maxLedgerId == MaxCompactedLedgerId
           oldLedgerId == IF maxLedgerId = 1 THEN Nil ELSE maxLedgerId - 1
       IN compactedLedgers' =
              IF /\ oldLedgerId # Nil
                 /\ compactedLedgers[oldLedgerId] # Nil
              THEN [compactedLedgers EXCEPT ![oldLedgerId] = Nil]
              ELSE compactedLedgers
    /\ compactorState' = Compactor_In_PhaseOne
    /\ phaseOneResult' = Nil
    /\ UNCHANGED <<messages, cursor, compactionHorizon,
                   compactedTopicContext, crashTimes, consumeTimes>>

(* A broker crash aborts any in-flight compaction and rolls the served
   horizon/context back to the last persisted cursor. *)
BrokerCrash ==
    /\ crashTimes < MaxCrashTimes
    /\ crashTimes' = crashTimes + 1
    /\ compactorState' = Compactor_In_PhaseOne
    /\ phaseOneResult' = Nil
    /\ IF cursor = Nil
       THEN /\ compactionHorizon' = 0
            /\ compactedTopicContext' = 0
       ELSE /\ compactionHorizon' = cursor.compactionHorizon
            /\ compactedTopicContext' = cursor.compactedTopicContext
    /\ UNCHANGED <<messages, compactedLedgers, cursor, consumeTimes>>

(* The consumer is modeled as a read-only observer (a stutter step). *)
Consumer ==
    UNCHANGED vars

(* Init: either an empty topic the producer fills (ModelProducer), or a
   draw over every id-consistent full-length message sequence. *)
Init ==
    /\ \/ /\ ModelProducer
          /\ messages = <<>>
       \/ /\ ~ModelProducer
          /\ messages \in {ms \in [1..MessageSentLimit -> MessageSpace] :
                               \A i \in 1..MessageSentLimit : ms[i].id = i}
    /\ compactedLedgers = [i \in 1..CompactionTimesLimit |-> Nil]
    /\ cursor = Nil
    /\ compactorState = Compactor_In_PhaseOne
    /\ phaseOneResult = Nil
    /\ compactionHorizon = 0
    /\ compactedTopicContext = 0
    /\ crashTimes = 0
    /\ consumeTimes = 0

(* The run is complete: every message sent, every compaction slot used,
   the compactor parked before its (impossible) next write, and — when
   modeled — the consumer done. *)
TerminationCondition ==
    /\ Len(messages) = MessageSentLimit
    /\ compactorState = Compactor_In_PhaseTwoWrite
    /\ MaxCompactedLedgerId = CompactionTimesLimit
    /\ ModelConsumer => consumeTimes = ConsumeTimesLimit

(* Self-loop at complete states so TLC reports no deadlock. *)
Terminating ==
    /\ TerminationCondition
    /\ UNCHANGED vars

Next ==
    \/ /\ ModelProducer
       /\ Producer
    \/ CompactorPhaseOne
    \/ CompactorPhaseTwoWrite
    \/ CompactorPhaseTwoUpdateContext
    \/ CompactorPhaseTwoUpdateHorizon
    \/ CompactorPhaseTwoPersistCusror
    \/ CompactorPhaseTwoDeleteLedger
    \/ BrokerCrash
    \/ /\ ModelConsumer
       /\ Consumer
    \/ Terminating

Spec == Init /\ [][Next]_vars

----------------------------------------------------------------------------
(* Invariants *)

MessageOK(m) ==
    /\ m.id \in 1..MessageSentLimit
    /\ m.key \in KeySet
    /\ m.value \in ValueSet

TypeSafe ==
    /\ \A i \in 1..Len(messages) : MessageOK(messages[i])
    /\ \A l \in 1..CompactionTimesLimit :
        \/ compactedLedgers[l] = Nil
        \/ \A i \in 1..Len(compactedLedgers[l]) :
               MessageOK(compactedLedgers[l][i])
    /\ \/ phaseOneResult = Nil
       \/ /\ phaseOneResult.readPosition \in 1..Len(messages)
          /\ \A k \in DOMAIN phaseOneResult.latestForKey :
                 phaseOneResult.latestForKey[k] \in 1..Len(messages)
    /\ compactorState \in CompactorStates
    /\ compactionHorizon \in 0..MessageSentLimit
    /\ compactedTopicContext \in 0..CompactionTimesLimit
    /\ crashTimes \in 0..MaxCrashTimes
    /\ \/ cursor = Nil
       \/ /\ cursor.compactionHorizon \in 1..MessageSentLimit
          /\ cursor.compactedTopicContext \in 1..CompactionTimesLimit

(* Pulsar bug #1: crashes between DeleteLedger steps leak ledgers — more
   than two may be alive at once. *)
CompactedLedgerLeak ==
    Cardinality({l \in 1..CompactionTimesLimit :
                     compactedLedgers[l] # Nil}) <= 2

(* Every message below the horizon is represented in the serving ledger
   by an entry for its key at least as new as itself. *)
CompactionHorizonCorrectness ==
    LET ledger == compactedLedgers[compactedTopicContext]
    IN \/ compactionHorizon = 0
       \/ \A i \in 1..compactionHorizon :
              LET m == messages[i]
              IN IF m.key = NullKey /\ ~RetainNullKey
                 THEN TRUE
                 ELSE \E j \in 1..Len(ledger) :
                          /\ ledger[j].key = m.key
                          /\ ledger[j].id >= m.id

(* Pulsar bug #2: a retained null-key message can be served twice — once
   from the compacted ledger and once from the topic tail above the
   horizon. *)
DuplicateNullKeyMessage ==
    \/ ~RetainNullKey
    \/ compactedTopicContext = 0
    \/ LET ledger == compactedLedgers[compactedTopicContext]
       IN \/ ledger = Nil
          \/ \A i \in 1..Len(ledger) :
                 ledger[i].key = NullKey =>
                     \A j \in (compactionHorizon + 1)..Len(messages) :
                         messages[j] # ledger[i]

----------------------------------------------------------------------------
(* Temporal properties *)

Termination ==
    <>(/\ Len(messages) = MessageSentLimit
       /\ compactorState = Compactor_In_PhaseTwoWrite
       /\ MaxCompactedLedgerId = CompactionTimesLimit
       /\ ModelConsumer => consumeTimes = ConsumeTimesLimit)

============================================================================
