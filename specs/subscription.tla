---------------------------- MODULE subscription ----------------------------
(***************************************************************************)
(* Model of an Apache Pulsar subscription's cursor protocol: message       *)
(* dispatch, individual acknowledgment, mark-delete advancement, and       *)
(* redelivery of unacknowledged messages after a consumer crash.           *)
(*                                                                         *)
(* The modeled roles:                                                      *)
(*   - producer: publishes messages 1..MessageLimit in order;              *)
(*   - broker:   dispatches unacked messages past the cursor, receives     *)
(*               individual acks, advances the durable mark-delete         *)
(*               position over the contiguous acked prefix (Pulsar's       *)
(*               ManagedCursor semantics: individuallyDeletedMessages      *)
(*               beyond markDeletePosition, merged as holes fill);         *)
(*   - consumer: processes in-flight messages and acks them; may crash,    *)
(*               losing its in-flight messages AND its not-yet-sent acks   *)
(*               (both are redelivered -> at-least-once delivery).         *)
(*                                                                         *)
(* The per-message lifecycle:                                              *)
(*     unread -> delivered (in flight) -> pending (processed, ack in       *)
(*     flight) -> acked (broker-side) -> covered by markDelete.            *)
(* ConsumerCrash returns `delivered` and `pending` messages to unread;     *)
(* the application-level fact that a message was processed is monotone     *)
(* (everProcessed), and a second processing records the id in              *)
(* `duplicated` — making the at-least-once duplicate observable.           *)
(*                                                                         *)
(* Companion spec to compaction.tla from thetumbled/pulsar-tlaplus        *)
(* (reference layout: compaction.tla:56-75 variable grouping,             *)
(* compaction.tla:169-182 crash/recovery style, compaction.tla:205-214    *)
(* Terminating self-loop convention).                                      *)
(***************************************************************************)
EXTENDS Naturals, FiniteSets

CONSTANTS
    MessageLimit,       \* how many messages the producer publishes
    MaxCrashTimes       \* bound on consumer crash/reconnect cycles

ASSUME
    /\ MessageLimit \in Nat
    /\ MessageLimit >= 1
    /\ MaxCrashTimes \in Nat

VARIABLES
    produced,       \* count of published messages (ids 1..produced)
    delivered,      \* ids in flight to the consumer, not yet processed
    pending,        \* ids processed by the consumer, ack not yet on broker
    acked,          \* ids individually acked beyond markDelete (ack holes)
    markDelete,     \* durable cursor: every id <= markDelete is consumed
    everProcessed,  \* history: ids the application processed at least once
    duplicated,     \* history: ids the application processed MORE than once
    crashTimes

vars == <<produced, delivered, pending, acked, markDelete,
          everProcessed, duplicated, crashTimes>>

Ids == 1..MessageLimit

Init ==
    /\ produced = 0
    /\ delivered = {}
    /\ pending = {}
    /\ acked = {}
    /\ markDelete = 0
    /\ everProcessed = {}
    /\ duplicated = {}
    /\ crashTimes = 0

(* Producer publishes the next message. *)
Publish ==
    /\ produced < MessageLimit
    /\ produced' = produced + 1
    /\ UNCHANGED <<delivered, pending, acked, markDelete,
                   everProcessed, duplicated, crashTimes>>

(* Broker dispatches an unconsumed, un-dispatched message to the consumer.
   A message that was processed but whose ack was lost in a crash is no
   longer in `pending`, so it is dispatched AGAIN here — redelivery. *)
Deliver ==
    /\ \E m \in Ids :
        /\ m <= produced
        /\ m > markDelete
        /\ m \notin delivered
        /\ m \notin pending
        /\ m \notin acked
        /\ delivered' = delivered \cup {m}
    /\ UNCHANGED <<produced, pending, acked, markDelete,
                   everProcessed, duplicated, crashTimes>>

(* Consumer processes an in-flight message (the application side effect
   happens HERE); the ack is now outstanding.  Processing an id that was
   already processed in a previous delivery is recorded in `duplicated`. *)
Process ==
    /\ \E m \in delivered :
        /\ delivered' = delivered \ {m}
        /\ pending' = pending \cup {m}
        /\ everProcessed' = everProcessed \cup {m}
        /\ duplicated' = IF m \in everProcessed
                         THEN duplicated \cup {m}
                         ELSE duplicated
    /\ UNCHANGED <<produced, acked, markDelete, crashTimes>>

(* Broker receives an individual ack (an "ack hole" until the prefix below
   it is also acked). *)
SendAck ==
    /\ \E m \in pending :
        /\ pending' = pending \ {m}
        /\ acked' = acked \cup {m}
    /\ UNCHANGED <<produced, delivered, markDelete,
                   everProcessed, duplicated, crashTimes>>

(* Cursor management: the mark-delete position swallows the next
   contiguous acked id (Pulsar merges individuallyDeletedMessages into
   markDeletePosition as holes fill). *)
AdvanceMarkDelete ==
    /\ (markDelete + 1) \in acked
    /\ markDelete' = markDelete + 1
    /\ acked' = acked \ {markDelete + 1}
    /\ UNCHANGED <<produced, delivered, pending,
                   everProcessed, duplicated, crashTimes>>

(* Consumer crashes and reconnects: in-flight messages and in-flight acks
   are lost; the broker will redeliver everything not individually acked
   and not covered by markDelete.  Broker-side cursor state survives. *)
ConsumerCrash ==
    /\ crashTimes < MaxCrashTimes
    /\ crashTimes' = crashTimes + 1
    /\ delivered' = {}
    /\ pending' = {}
    /\ UNCHANGED <<produced, acked, markDelete,
                   everProcessed, duplicated>>

(* Self-loop at the drained end state so TLC reports no deadlock. *)
Drained ==
    /\ produced = MessageLimit
    /\ markDelete = MessageLimit

Terminating ==
    /\ Drained
    /\ UNCHANGED vars

Next ==
    \/ Publish
    \/ Deliver
    \/ Process
    \/ SendAck
    \/ AdvanceMarkDelete
    \/ ConsumerCrash
    \/ Terminating

Spec == Init /\ [][Next]_vars

-----------------------------------------------------------------------------
(* Invariants *)

TypeOK ==
    /\ produced \in 0..MessageLimit
    /\ markDelete \in 0..MessageLimit
    /\ markDelete <= produced
    /\ delivered \subseteq Ids
    /\ pending \subseteq Ids
    /\ acked \subseteq Ids
    /\ everProcessed \subseteq Ids
    /\ duplicated \subseteq everProcessed
    /\ crashTimes \in 0..MaxCrashTimes
    /\ delivered \cap pending = {}
    /\ delivered \cap acked = {}
    /\ pending \cap acked = {}
    /\ \A m \in delivered \cup pending \cup acked :
        /\ m > markDelete
        /\ m <= produced

(* The core cursor-safety property: the mark-delete position never covers
   a message the application did not process — advancing the cursor is the
   broker's promise the message was consumed. *)
NoLostMessage ==
    \A m \in 1..markDelete : m \in everProcessed

(* Acks are only ever generated by processing. *)
AckedWasProcessed ==
    (acked \cup pending) \subseteq everProcessed

(* Pulsar subscriptions are at-least-once: a crash between processing and
   ack receipt forces redelivery, so this invariant is VIOLATED whenever
   MaxCrashTimes >= 1 — enable it to obtain the duplicate-consumption
   counterexample trace (the analog of compaction.tla's commented-out
   known-bug invariants, compaction.tla:252,279). *)
ExactlyOnceProcessing ==
    duplicated = {}

-----------------------------------------------------------------------------
(* With weak fairness on Next the subscription drains: crashes are bounded,
   so eventually every message is processed, acked, and covered by the
   cursor.  Without fairness the spec may stutter forever (TLC semantics
   for the raw Spec). *)
Termination ==
    <>Drained

=============================================================================
